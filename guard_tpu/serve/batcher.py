"""Cross-request batch coalescing for the serving plane.

`serve` answers one request per device dispatch even when sixteen
clients are asking about the SAME rule registry — the plan is warm
(ops/plan.py), so the marginal cost of a request is the dispatch, and
a dispatch over 4 docs wastes almost the whole padded batch slot. The
batcher closes that gap: in-flight validate requests are admitted to a
bounded queue, grouped by rule-content digest (the plan-cache key —
same digest = same lowered program), and each group evaluates as ONE
packed (docs x rules) device batch via `ops.backend.tpu_validate_multi`.
Per-request doc-segment offsets demux the shared status/rim arrays back
to each caller, byte-identically to a sequential run (statuses are
invariant under batch composition and intern-id labels — the plan
layer's relocation contract underwrites the parity).

Latency policy: the dispatcher thread waits at most
`GUARD_TPU_COALESCE_WAIT_MS` (default 5) after the first arrival for
peers to join, and never packs more than
`GUARD_TPU_COALESCE_MAX_BATCH` (default 16) requests into one batch.
The admission queue holds at most `GUARD_TPU_SERVE_QUEUE_MAX`
(default 64) requests; a full queue blocks admission (backpressure,
never silent drops) — unless the caller passes a bounded `queue_wait`,
in which case admission past the deadline raises
`frontdoor.QueueFull` so the front door can shed the request to solo
dispatch or answer a structured 429 (the accept loop never wedges
behind a saturated queue). `GUARD_TPU_COALESCE=0` disables coalescing
entirely — every request runs the sequential path.

Failure isolation (the PR 5 plane, scoped to batches): the
`serve_batch` injection point fires per group before dispatch; an
injected or real shared-phase failure re-fires every member SOLO
through the ordinary sequential path (`isolation_refires` counts
them), a per-request report-phase failure is captured into that
request's slot only, and a request whose PREPARE step fails (e.g. a
poisoned document payload) drops out of the group and runs solo so its
error output reproduces byte-identically — its peers still coalesce.
A timed-out waiter abandons its slot (`request_timeouts`); the batch
result is discarded for that request, never for its peers.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Optional

from ..utils import telemetry
from ..utils.faults import maybe_fail
from ..utils.io import Reader
from ..utils.telemetry import SERVE_COUNTERS


def coalesce_enabled() -> bool:
    """GUARD_TPU_COALESCE=0 is the escape hatch; default on."""
    return os.environ.get("GUARD_TPU_COALESCE", "1") != "0"


def coalesce_wait_s() -> float:
    """Batch-formation window after the first arrival, in seconds
    (GUARD_TPU_COALESCE_WAIT_MS, default 5ms) — the latency-SLO knob:
    longer windows fill batches, shorter ones bound p50."""
    raw = os.environ.get("GUARD_TPU_COALESCE_WAIT_MS", "").strip()
    try:
        return (float(raw) if raw else 5.0) / 1000.0
    except ValueError:
        return 0.005


def coalesce_max_batch() -> int:
    raw = os.environ.get("GUARD_TPU_COALESCE_MAX_BATCH", "").strip()
    try:
        n = int(raw) if raw else 16
    except ValueError:
        n = 16
    return max(1, n)


def serve_queue_max() -> int:
    raw = os.environ.get("GUARD_TPU_SERVE_QUEUE_MAX", "").strip()
    try:
        n = int(raw) if raw else 64
    except ValueError:
        n = 64
    return max(1, n)


class BatchTimeout(Exception):
    """A submitter's wait expired before its batch answered; the
    serve layer maps this to the session's RequestTimeout contract."""


class _Item:
    """One admitted request: the serve-built Validate command, its raw
    payload text, the digest it groups under, and the per-request
    buffered writer the demuxed report pass emits into."""

    __slots__ = (
        "cmd", "payload", "digest", "writer",
        "done", "code", "error", "enqueued_at", "arrived_alone",
    )

    def __init__(self, cmd, payload, digest, writer):
        self.cmd = cmd
        self.payload = payload
        self.digest = digest
        self.writer = writer
        self.done = threading.Event()
        self.code: Optional[int] = None
        self.error: Optional[BaseException] = None
        self.enqueued_at = time.monotonic()
        self.arrived_alone = False


class CoalescingBatcher:
    """Bounded admission queue + dispatcher thread. `submit()` blocks
    the calling request thread until its item is answered (or its
    timeout expires); the dispatcher drains arrivals in max-wait/
    max-batch windows and evaluates each digest group as one batch."""

    def __init__(self, wait_s: Optional[float] = None,
                 max_batch: Optional[int] = None,
                 queue_limit: Optional[int] = None):
        self._wait = coalesce_wait_s() if wait_s is None else wait_s
        self._max_batch = (
            coalesce_max_batch() if max_batch is None else max_batch
        )
        self._limit = serve_queue_max() if queue_limit is None else queue_limit
        self._q: "deque[_Item]" = deque()
        self._cv = threading.Condition()
        self._closed = False
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="guard-tpu-coalescer"
        )
        self._thread.start()

    # -- admission ----------------------------------------------------
    def submit(self, cmd, payload: str, digest: str, writer,
               timeout: float = 0.0,
               queue_wait: Optional[float] = None) -> int:
        """Admit one request and block until it is answered. Raises
        BatchTimeout when `timeout` (seconds, 0 = unbounded) expires
        first — the batch keeps running, the result is discarded — and
        re-raises whatever per-request exception the run captured.

        `queue_wait` bounds the ADMISSION wait on a full queue:
        None keeps the legacy infinite backpressure; a number of
        seconds raises `frontdoor.QueueFull` past the deadline so the
        front door can shed or 429 instead of wedging the caller."""
        item = _Item(cmd, payload, digest, writer)
        with self._cv:
            if queue_wait is None:
                while len(self._q) >= self._limit and not self._closed:
                    # bounded admission: backpressure, not drops
                    self._cv.wait(0.05)
            else:
                deadline = time.monotonic() + max(0.0, queue_wait)
                while len(self._q) >= self._limit and not self._closed:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        from .frontdoor import QueueFull

                        raise QueueFull(
                            f"admission queue full ({self._limit}) "
                            f"past {queue_wait * 1000:g}ms wait",
                            retry_after_ms=max(
                                1, int(queue_wait * 1000) or 100
                            ),
                        )
                    self._cv.wait(min(remaining, 0.05))
            if self._closed:
                raise RuntimeError("serve batcher is closed")
            item.arrived_alone = not self._q
            self._q.append(item)
            telemetry.REGISTRY.set_gauge("serve_queue_depth", len(self._q))
            self._cv.notify_all()
        if not item.done.wait(timeout if timeout and timeout > 0 else None):
            SERVE_COUNTERS["request_timeouts"] += 1
            raise BatchTimeout(f"request timed out after {timeout:g}s")
        if item.error is not None:
            raise item.error
        return item.code

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Graceful shutdown (the durability plane's serve leg): stop
        admitting — `submit` raises once closed — let the dispatcher
        finish every already-admitted batch, and join it bounded by
        `timeout` seconds. Returns True when the queue fully drained
        inside the bound (False = in-flight work abandoned to the
        daemonic dispatcher, same as any process exit)."""
        self.close()
        self._thread.join(timeout)
        return not self._thread.is_alive()

    # -- dispatcher ---------------------------------------------------
    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._closed:
                    self._cv.wait()
                if not self._q and self._closed:
                    return
                # batch formation: after the first arrival, wait up to
                # the coalesce window for peers (or until max-batch) —
                # UNLESS the sole queued request found the queue empty
                # on admission: with no peer in flight the window buys
                # only latency, so dispatch immediately (c=1 parity
                # with coalesce-off). Concurrent arrivals — a request
                # admitted while others were queued — still pay the
                # window so their peers can join the batch.
                if len(self._q) == 1 and self._q[0].arrived_alone:
                    SERVE_COUNTERS["coalesce_window_adaptive"] += 1
                else:
                    deadline = time.monotonic() + self._wait
                    while len(self._q) < self._max_batch:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            break
                        self._cv.wait(remaining)
                batch = [
                    self._q.popleft()
                    for _ in range(min(len(self._q), self._max_batch))
                ]
                telemetry.REGISTRY.set_gauge("serve_queue_depth", len(self._q))
                self._cv.notify_all()
            wait_hist = telemetry.REGISTRY.histogram(
                "serve_queue_wait_seconds", persistent=True
            )
            now = time.monotonic()
            for it in batch:
                wait_hist.observe(now - it.enqueued_at)
            groups: "dict[str, list]" = {}
            for it in batch:
                groups.setdefault(it.digest, []).append(it)
            for digest, items in groups.items():
                try:
                    self._run_group(digest, items)
                except Exception as e:  # noqa: BLE001 — keep serving
                    for it in items:
                        if not it.done.is_set():
                            it.error = e
                            it.done.set()

    # -- evaluation ---------------------------------------------------
    def _run_solo(self, item: _Item) -> None:
        """The sequential path, verbatim: exactly what a lone stdio
        request runs, so output/exit code reproduce byte-for-byte."""
        try:
            item.code = item.cmd.execute(
                item.writer, Reader.from_string(item.payload)
            )
        except Exception as e:  # noqa: BLE001 — per-request isolation
            item.error = e
        finally:
            item.done.set()

    def _run_group(self, digest: str, items: list) -> None:
        telemetry.REGISTRY.set_gauge("serve_batch_fill", len(items))
        try:
            # the failure plane's serving leg: a batch-scoped fault
            # (injected via GUARD_TPU_FAULT=serve_batch:... or a real
            # shared-phase error below) quarantines the BATCH, not the
            # session — every member re-fires solo
            maybe_fail("serve_batch", key=digest)
        except Exception:
            SERVE_COUNTERS["isolation_refires"] += len(items)
            for it in items:
                self._run_solo(it)
            return
        if len(items) == 1:
            SERVE_COUNTERS["singleton_batches"] += 1
            self._run_solo(items[0])
            return

        from ..commands.validate import payload_inputs

        reqs = []
        members = []
        for it in items:
            try:
                # the sequential payload branch, minus the per-request
                # work coalescing amortizes: prepared rules are already
                # parsed (eligibility requires it), so payload_inputs
                # only decodes documents — any failure here (e.g. a
                # poisoned document) drops this request to the solo
                # path where its error output reproduces exactly
                rule_files, data_files, _errs = payload_inputs(
                    it.payload, it.writer, it.cmd.prepared_rules
                )
                reqs.append((it.cmd, rule_files, data_files, it.writer))
                members.append(it)
            except Exception:
                SERVE_COUNTERS["solo_fallbacks"] += 1
                self._run_solo(it)
        if not members:
            return
        if len(members) == 1:
            SERVE_COUNTERS["singleton_batches"] += 1
            self._run_solo(members[0])
            return

        from ..ops.backend import tpu_validate_multi

        try:
            outcomes = tpu_validate_multi(reqs)
        except Exception:
            # shared phase (encode/lower/dispatch) failed: nobody has
            # written output yet, so every member re-fires solo
            SERVE_COUNTERS["isolation_refires"] += len(members)
            for it in members:
                self._run_solo(it)
            return
        SERVE_COUNTERS["coalesced_batches"] += 1
        SERVE_COUNTERS["coalesced_requests"] += len(members)
        for it, out in zip(members, outcomes):
            if isinstance(out, BaseException):
                it.error = out
            else:
                it.code = out
            it.done.set()
