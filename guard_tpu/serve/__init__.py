"""The concurrent serving plane: multi-client connection handling
(`server.py` — multiplexed stdio ids, threaded TCP/HTTP listener) and
cross-request batch coalescing (`batcher.py` — one packed device
dispatch per rule digest instead of one per request)."""

from .batcher import BatchTimeout, CoalescingBatcher, coalesce_enabled
from .server import ServeServer

__all__ = [
    "BatchTimeout",
    "CoalescingBatcher",
    "ServeServer",
    "coalesce_enabled",
]
