"""Traffic discipline for the serving plane: the front door.

The coalescing tier (serve/batcher.py) made requests cheap; nothing
made them SAFE. One hot tenant can fill the bounded admission queue
for everyone, and a stalled coalesce window has no escape to solo
dispatch. This module is the layer in front of the batcher that turns
warm plans into traffic actually served:

* **Per-tenant admission quotas** (`AdmissionController`): a token
  bucket per tenant (`GUARD_TPU_TENANT_RATE` requests/sec, burst
  `GUARD_TPU_TENANT_BURST`) plus a per-tenant in-flight ceiling
  (`GUARD_TPU_TENANT_MAX_INFLIGHT`). Over-quota requests get a
  structured 429-class rejection (`QuotaExceeded`, mapped to HTTP 429
  by serve/server.py and to a `code: 5` + `error_class` JSONL envelope
  by commands/serve.py) — never a hang, never a silent drop. The
  tenant id comes from the request envelope (`"tenant"`), the HTTP
  header (`X-Guard-Tenant`), or the connection default
  (`GUARD_TPU_TENANT_DEFAULT`).

* **A latency-SLO circuit breaker** (`CircuitBreaker`): tracks
  per-digest formation+dispatch latency (the whole time a request
  spends inside `CoalescingBatcher.submit`) against
  `GUARD_TPU_SERVE_SLO_MS`. When the sliding-window p99 breaches the
  SLO — batch fill is stalling — or the admission queue saturates, the
  breaker OPENS and subsequent same-digest requests shed to immediate
  solo dispatch (`GUARD_TPU_SERVE_SHED=0` disables shedding: the
  queue-full path then answers a structured 429 instead). After
  `GUARD_TPU_BREAKER_COOLDOWN_MS` one HALF-OPEN probe rides the
  batcher; meeting the SLO re-CLOSES the breaker, missing it re-opens.
  States are observable as `breaker_state.<digest>` gauges (0 closed /
  1 open / 2 half-open) and every transition increments an
  `admission` EventedCounter — an instant trace event the flight
  recorder's ring captures.

Both state machines take an injectable `clock` (seconds, monotonic) so
the breaker/quota tests run on a deterministic clock — no wall-time in
assertions, same discipline as utils/faults.py.

Fault points (the PR 5 plane, scoped to the front door): `admission`
fires inside the quota check, `shed` inside the breaker's solo-shed
path — chaos runs prove an injected front-door fault still answers
every request with a structured error.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, Optional

from ..core.errors import GuardError
from ..utils import telemetry
from ..utils.faults import maybe_fail
from ..utils.telemetry import ADMISSION_COUNTERS

#: breaker states (gauge values: the snapshot face of the machine)
CLOSED, OPEN, HALF_OPEN = 0, 1, 2

_STATE_NAMES = {CLOSED: "closed", OPEN: "open", HALF_OPEN: "half_open"}


# -- rejection envelope --------------------------------------------------

class AdmissionRejected(GuardError):
    """Base of the structured 429-class rejections: the request was
    refused by traffic discipline, not by evaluation. Carries a retry
    hint the response envelope and the HTTP face both surface."""

    def __init__(self, msg: str, retry_after_ms: int = 1000):
        super().__init__(msg)
        self.retry_after_ms = retry_after_ms


class QuotaExceeded(AdmissionRejected):
    """A tenant exceeded its token-bucket rate or in-flight ceiling."""


class QueueFull(AdmissionRejected):
    """The bounded admission queue stayed full past the bounded wait
    (and shedding was disabled or unavailable)."""


class BodyTooLarge(GuardError):
    """An HTTP body or JSONL line exceeded GUARD_TPU_SERVE_MAX_BODY;
    the transport answers a structured 413."""


# -- env knobs (same try/except idiom as the rest of the repo) -----------

def default_tenant() -> str:
    """Connection-default tenant id (GUARD_TPU_TENANT_DEFAULT) for
    requests that carry no envelope field or header."""
    return os.environ.get("GUARD_TPU_TENANT_DEFAULT", "").strip() or "default"


def tenant_rate() -> float:
    """Token-bucket refill rate in requests/sec per tenant
    (GUARD_TPU_TENANT_RATE); 0 or unset = unlimited."""
    raw = os.environ.get("GUARD_TPU_TENANT_RATE", "").strip()
    try:
        return max(0.0, float(raw)) if raw else 0.0
    except ValueError:
        return 0.0


def tenant_burst() -> float:
    """Token-bucket capacity per tenant (GUARD_TPU_TENANT_BURST);
    defaults to the rate (>= 1) so a quiet tenant can always send at
    least one request instantly."""
    raw = os.environ.get("GUARD_TPU_TENANT_BURST", "").strip()
    try:
        if raw:
            return max(1.0, float(raw))
    except ValueError:
        pass
    return max(1.0, tenant_rate())


def tenant_max_inflight() -> int:
    """Per-tenant in-flight request ceiling
    (GUARD_TPU_TENANT_MAX_INFLIGHT); 0 or unset = unlimited."""
    raw = os.environ.get("GUARD_TPU_TENANT_MAX_INFLIGHT", "").strip()
    try:
        return max(0, int(raw)) if raw else 0
    except ValueError:
        return 0


def serve_slo_s() -> float:
    """Formation+dispatch latency SLO in seconds
    (GUARD_TPU_SERVE_SLO_MS); 0 or unset disables the breaker — the
    bit-parity default: with no SLO configured the serving path is
    byte-identical to the pre-front-door tier."""
    raw = os.environ.get("GUARD_TPU_SERVE_SLO_MS", "").strip()
    try:
        return max(0.0, float(raw)) / 1000.0 if raw else 0.0
    except ValueError:
        return 0.0


def breaker_cooldown_s() -> float:
    """OPEN -> HALF_OPEN cooldown (GUARD_TPU_BREAKER_COOLDOWN_MS,
    default 1000ms): how long the breaker sheds before probing."""
    raw = os.environ.get("GUARD_TPU_BREAKER_COOLDOWN_MS", "").strip()
    try:
        return max(0.0, float(raw)) / 1000.0 if raw else 1.0
    except ValueError:
        return 1.0


def breaker_min_samples() -> int:
    """Samples required before a p99 breach can trip the breaker
    (GUARD_TPU_BREAKER_MIN_SAMPLES, default 8) — one slow compile
    must not open the breaker on a cold digest. Queue saturation
    trips immediately regardless."""
    raw = os.environ.get("GUARD_TPU_BREAKER_MIN_SAMPLES", "").strip()
    try:
        return max(1, int(raw)) if raw else 8
    except ValueError:
        return 8


def shed_enabled() -> bool:
    """GUARD_TPU_SERVE_SHED=0 disables overload shedding (queue-full
    then answers a structured 429 instead of solo dispatch)."""
    return os.environ.get("GUARD_TPU_SERVE_SHED", "1") != "0"


def queue_wait_s() -> float:
    """Bounded wait for admission-queue space
    (GUARD_TPU_SERVE_QUEUE_WAIT_MS, default 100ms). The front door
    never blocks unboundedly: past this wait the request is shed or
    rejected 429, so a saturated queue cannot wedge the accept loop."""
    raw = os.environ.get("GUARD_TPU_SERVE_QUEUE_WAIT_MS", "").strip()
    try:
        return max(0.0, float(raw)) / 1000.0 if raw else 0.1
    except ValueError:
        return 0.1


def max_body_bytes() -> int:
    """Request body / JSONL line size cap in bytes
    (GUARD_TPU_SERVE_MAX_BODY, default 10 MiB); 0 disables the cap."""
    raw = os.environ.get("GUARD_TPU_SERVE_MAX_BODY", "").strip()
    try:
        return max(0, int(raw)) if raw else 10 * 1024 * 1024
    except ValueError:
        return 10 * 1024 * 1024


# -- per-tenant admission quotas -----------------------------------------

class _TokenBucket:
    """Classic token bucket on an injected clock: `rate` tokens/sec
    refill up to `burst`; `take()` consumes one or reports empty."""

    __slots__ = ("rate", "burst", "tokens", "stamp")

    def __init__(self, rate: float, burst: float, now: float):
        self.rate = rate
        self.burst = burst
        self.tokens = burst
        self.stamp = now

    def take(self, now: float) -> bool:
        if now > self.stamp:
            self.tokens = min(
                self.burst, self.tokens + (now - self.stamp) * self.rate
            )
            self.stamp = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False


class AdmissionController:
    """Per-tenant token-bucket rate + in-flight ceiling over the
    serving plane's admission path. `admit(tenant)` either returns
    (counted in-flight until `release`) or raises QuotaExceeded — it
    NEVER blocks. Limits resolve from the env per controller (tests
    pass them explicitly); rate 0 / inflight 0 mean unlimited, which
    keeps the default serving path byte-identical."""

    def __init__(self, rate: Optional[float] = None,
                 burst: Optional[float] = None,
                 max_inflight: Optional[int] = None,
                 clock=time.monotonic):
        self.rate = tenant_rate() if rate is None else rate
        self.burst = tenant_burst() if burst is None else burst
        self.max_inflight = (
            tenant_max_inflight() if max_inflight is None else max_inflight
        )
        self._clock = clock
        self._lock = threading.Lock()
        self._buckets: Dict[str, _TokenBucket] = {}
        self._inflight: Dict[str, int] = {}

    def admit(self, tenant: str) -> None:
        # the failure plane's front-door leg: an injected admission
        # fault answers a structured error, never a hang
        maybe_fail("admission", key=tenant)
        with self._lock:
            now = self._clock()
            if self.max_inflight > 0:
                if self._inflight.get(tenant, 0) >= self.max_inflight:
                    ADMISSION_COUNTERS["rejected_inflight"] += 1
                    raise QuotaExceeded(
                        f"tenant {tenant!r} at max in-flight "
                        f"({self.max_inflight})",
                        retry_after_ms=100,
                    )
            if self.rate > 0:
                bucket = self._buckets.get(tenant)
                if bucket is None:
                    bucket = self._buckets[tenant] = _TokenBucket(
                        self.rate, self.burst, now
                    )
                    telemetry.REGISTRY.set_gauge(
                        "admission_tenants", len(self._buckets)
                    )
                if not bucket.take(now):
                    ADMISSION_COUNTERS["rejected_rate"] += 1
                    raise QuotaExceeded(
                        f"tenant {tenant!r} over rate "
                        f"({self.rate:g} req/s, burst {self.burst:g})",
                        retry_after_ms=int(1000.0 / self.rate) or 1,
                    )
            self._inflight[tenant] = self._inflight.get(tenant, 0) + 1
            ADMISSION_COUNTERS["admitted"] += 1
            telemetry.REGISTRY.set_gauge(
                "admission_inflight", sum(self._inflight.values())
            )

    def release(self, tenant: str) -> None:
        with self._lock:
            n = self._inflight.get(tenant, 1) - 1
            if n <= 0:
                self._inflight.pop(tenant, None)
            else:
                self._inflight[tenant] = n
            telemetry.REGISTRY.set_gauge(
                "admission_inflight", sum(self._inflight.values())
            )


# -- latency-SLO circuit breaker -----------------------------------------

class _DigestState:
    __slots__ = ("state", "samples", "opened_at", "probing")

    def __init__(self):
        self.state = CLOSED
        # sliding latency window: enough depth that one p99 outlier
        # needs real company to breach, small enough to recover fast
        self.samples: "deque[float]" = deque(maxlen=64)
        self.opened_at = 0.0
        self.probing = False


class CircuitBreaker:
    """Per-digest closed -> open -> half-open -> closed machine over
    formation+dispatch latency. `decide(digest)` returns the route for
    one request: "batch" (ride the coalescing batcher), "shed"
    (immediate solo dispatch), or "probe" (the half-open trial riding
    the batcher); `observe(digest, seconds)` feeds the outcome back.
    Disabled (SLO 0) it answers "batch" on one branch — bit-parity
    with the pre-breaker tier."""

    def __init__(self, slo_s: Optional[float] = None,
                 cooldown_s: Optional[float] = None,
                 min_samples: Optional[int] = None,
                 clock=time.monotonic):
        self.slo = serve_slo_s() if slo_s is None else slo_s
        self.cooldown = (
            breaker_cooldown_s() if cooldown_s is None else cooldown_s
        )
        self.min_samples = (
            breaker_min_samples() if min_samples is None else min_samples
        )
        self._clock = clock
        self._lock = threading.Lock()
        self._digests: Dict[str, _DigestState] = {}

    @property
    def enabled(self) -> bool:
        return self.slo > 0

    def state(self, digest: str) -> int:
        with self._lock:
            st = self._digests.get(digest)
            return CLOSED if st is None else st.state

    def _gauge(self, digest: str, st: _DigestState) -> None:
        telemetry.REGISTRY.set_gauge(f"breaker_state.{digest[:12]}",
                                     st.state)

    def _trip(self, digest: str, st: _DigestState, cause: str) -> None:
        st.state = OPEN
        st.opened_at = self._clock()
        st.probing = False
        ADMISSION_COUNTERS["breaker_trips"] += 1
        self._gauge(digest, st)
        telemetry.event(
            "admission.breaker_trip",
            {"digest": digest[:12], "cause": cause,
             "slo_ms": round(self.slo * 1000.0, 3)},
        )

    def decide(self, digest: str) -> str:
        if not self.enabled:
            return "batch"
        with self._lock:
            st = self._digests.get(digest)
            if st is None or st.state == CLOSED:
                return "batch"
            now = self._clock()
            if st.state == OPEN and now - st.opened_at >= self.cooldown:
                # cooldown elapsed: promote to HALF_OPEN and let ONE
                # probe ride the batcher; peers keep shedding until
                # the probe's verdict lands
                st.state = HALF_OPEN
                st.probing = True
                ADMISSION_COUNTERS["breaker_probes"] += 1
                self._gauge(digest, st)
                return "probe"
            if st.state == HALF_OPEN and not st.probing:
                st.probing = True
                ADMISSION_COUNTERS["breaker_probes"] += 1
                return "probe"
            return "shed"

    def observe(self, digest: str, seconds: float,
                probe: bool = False) -> None:
        """Feed one formation+dispatch latency back. A probe's verdict
        closes (within SLO) or re-opens the breaker; closed-state
        samples trip it when the sliding-window p99 breaches the
        SLO."""
        if not self.enabled:
            return
        with self._lock:
            st = self._digests.get(digest)
            if st is None:
                st = self._digests[digest] = _DigestState()
            st.samples.append(seconds)
            if probe:
                st.probing = False
                if seconds <= self.slo:
                    st.state = CLOSED
                    st.samples.clear()
                    ADMISSION_COUNTERS["breaker_closes"] += 1
                    self._gauge(digest, st)
                    telemetry.event(
                        "admission.breaker_close", {"digest": digest[:12]}
                    )
                else:
                    self._trip(digest, st, "probe_missed_slo")
                return
            if st.state != CLOSED:
                return
            n = len(st.samples)
            if n < self.min_samples:
                return
            p99 = sorted(st.samples)[min(n - 1, max(0, -(-99 * n // 100) - 1))]
            if p99 > self.slo:
                self._trip(digest, st, "p99_over_slo")

    def on_queue_full(self, digest: str) -> None:
        """Queue saturation is an immediate trip — no sample quorum:
        a full admission queue means formation is not keeping up."""
        if not self.enabled:
            return
        with self._lock:
            st = self._digests.get(digest)
            if st is None:
                st = self._digests[digest] = _DigestState()
            if st.state != OPEN:
                self._trip(digest, st, "queue_saturated")


class FrontDoor:
    """One serving session's traffic discipline: the admission
    controller and the circuit breaker, with limits resolved from the
    env at construction (one FrontDoor per Serve session, like the
    batcher)."""

    def __init__(self, clock=time.monotonic):
        self.admission = AdmissionController(clock=clock)
        self.breaker = CircuitBreaker(clock=clock)


def state_name(state: int) -> str:
    return _STATE_NAMES.get(state, str(state))
