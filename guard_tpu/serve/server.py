"""Threaded TCP/HTTP listener for the serving plane (`--listen`).

One process, many clients: each accepted connection gets a handler
thread that speaks either of two protocols, sniffed from the first
bytes —

* **JSONL** (the stdio protocol over a socket): newline-delimited JSON
  requests, one JSON response line per request, identical envelopes to
  `serve --stdio`. Requests tagged with an `"id"` are answered with
  the id echoed (responses may interleave across a connection's
  pipelined requests exactly as the multiplexed stdio session does).
* **HTTP/1.1** (curl-able face): `POST /validate` with a JSON request
  body returns the response envelope as `application/json`;
  `GET /metrics` returns the live telemetry snapshot;
  `POST /webhook` is the Kubernetes ValidatingWebhook face
  (AdmissionReview in, allowed/denied + per-rule messages out,
  evaluated against the session's `--rules` registry). Minimal by
  design — one request per connection, no keep-alive.

Input discipline (the front door's transport leg): bodies and JSONL
lines are capped at `GUARD_TPU_SERVE_MAX_BODY` bytes — an oversized
HTTP body answers a structured 413 WITHOUT reading the payload, an
oversized JSONL line answers a structured error envelope; per-tenant
quota rejections and a saturated admission queue map to HTTP 429
(with a Retry-After hint) or the same structured JSONL envelope —
the accept loop never blocks on traffic it will not serve. The
connection-default tenant comes from the `X-Guard-Tenant` header.

Every connection shares the session's `Serve` instance, so the
prepared-rules cache, the process-global plan memo and the coalescing
batcher amortize across clients — sixteen connections asking about one
registry fill one packed dispatch (serve/batcher.py).
"""

from __future__ import annotations

import json
import socket
import threading
from typing import Optional

from ..utils.io import Writer
from . import frontdoor


def _parse_hostport(listen: str) -> tuple:
    """`HOST:PORT` (port 0 = OS-assigned); bare `PORT` binds localhost."""
    if ":" in listen:
        host, _, port = listen.rpartition(":")
        return host or "127.0.0.1", int(port)
    return "127.0.0.1", int(listen)


class ServeServer:
    """Accept loop + per-connection handler threads over one shared
    `Serve` session. `start()` binds and returns (port available as
    `.port` — bind with :0 in tests); `serve_forever()` blocks until
    `stop()` or KeyboardInterrupt."""

    def __init__(self, serve, listen: str):
        self.serve = serve
        self.host, self.port = _parse_hostport(listen)
        self._sock: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._stopped = threading.Event()

    # -- lifecycle ----------------------------------------------------
    def start(self) -> "ServeServer":
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self.host, self.port))
        s.listen(64)
        self.port = s.getsockname()[1]
        self._sock = s
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="guard-tpu-accept"
        )
        self._accept_thread.start()
        return self

    def stop(self) -> None:
        self._stopped.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass

    def serve_forever(self) -> int:
        if self._sock is None:
            self.start()
        try:
            self._stopped.wait()
        except KeyboardInterrupt:
            pass
        finally:
            self.stop()
        return 0

    # -- connection handling ------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return  # socket closed by stop()
            t = threading.Thread(
                target=self._handle_conn, args=(conn,), daemon=True,
                name="guard-tpu-conn",
            )
            t.start()

    def _handle_conn(self, conn: socket.socket) -> None:
        try:
            f = conn.makefile("rwb")
            first = f.peek(8)[:8] if hasattr(f, "peek") else b""
            if first.split(b" ", 1)[0] in (b"POST", b"GET", b"PUT", b"HEAD"):
                self._handle_http(f)
            else:
                self._handle_jsonl(f)
        except (OSError, ValueError):
            pass  # client went away mid-request
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _handle_jsonl(self, f) -> None:
        """The stdio protocol over a socket, ids multiplexed exactly
        like the stdio session: untagged requests answer in order,
        tagged ones may coalesce with peers from other connections."""
        wlock = threading.Lock()
        pending = []
        cap = frontdoor.max_body_bytes()
        for raw in f:
            if cap and len(raw) > cap:
                # oversized line: structured 413-class envelope, no
                # parse attempt (the line is already drained off the
                # socket — a line protocol cannot refuse mid-line)
                frontdoor.ADMISSION_COUNTERS["rejected_body_size"] += 1
                with wlock:
                    f.write((json.dumps({
                        "code": 5, "output": "",
                        "error": f"request line exceeds "
                                 f"GUARD_TPU_SERVE_MAX_BODY ({cap}B)",
                        "error_class": "BodyTooLarge",
                    }) + "\n").encode())
                    f.flush()
                continue
            line = raw.decode("utf-8", "replace").strip()
            if not line:
                break
            rid = self.serve.request_id(line)

            def _answer(line=line, rid=rid):
                resp = self.serve.handle_line(line)
                if rid is not None:
                    resp["id"] = rid
                with wlock:
                    f.write((json.dumps(resp) + "\n").encode())
                    f.flush()

            if rid is None:
                _answer()
            else:
                t = threading.Thread(target=_answer, daemon=True)
                t.start()
                pending.append(t)
        for t in pending:
            t.join()

    def _handle_http(self, f) -> None:
        request_line = f.readline().decode("latin-1").strip()
        parts = request_line.split(" ")
        if len(parts) < 2:
            return
        method, path = parts[0], parts[1]
        clen = 0
        headers = {}
        while True:
            h = f.readline().decode("latin-1").strip()
            if not h:
                break
            k, _, v = h.partition(":")
            headers[k.strip().lower()] = v.strip()
        try:
            clen = int(headers.get("content-length", "0"))
        except ValueError:
            clen = 0
        # connection-default tenant: the header names it; the request
        # envelope's own "tenant" field still wins
        tenant = headers.get("x-guard-tenant") or None
        cap = frontdoor.max_body_bytes()
        if method == "POST" and cap and clen > cap:
            # 413 BEFORE reading the body — an oversized payload never
            # ties up the handler thread
            frontdoor.ADMISSION_COUNTERS["rejected_body_size"] += 1
            self._http_reply(f, 413, json.dumps({
                "code": 5, "output": "",
                "error": f"body of {clen}B exceeds "
                         f"GUARD_TPU_SERVE_MAX_BODY ({cap}B)",
                "error_class": "BodyTooLarge",
            }))
            return
        if method == "GET" and path == "/metrics":
            body = json.dumps(self.serve.handle_line('{"metrics": true}'))
            self._http_reply(f, 200, body)
            return
        if method == "POST" and path == "/webhook":
            payload = f.read(clen).decode("utf-8", "replace") if clen else ""
            status, doc = self.serve.handle_webhook(payload, tenant)
            extra = {}
            if status == 429:
                extra["Retry-After"] = str(
                    max(1, doc.get("retry_after_ms", 1000) // 1000)
                )
            self._http_reply(f, status, json.dumps(doc), extra)
            return
        if method == "POST":
            payload = f.read(clen).decode("utf-8", "replace") if clen else ""
            resp = self.serve.handle_line(
                payload.strip() or "{}", default_tenant=tenant
            )
            err_class = resp.get("error_class")
            if err_class in ("QuotaExceeded", "QueueFull"):
                # traffic discipline speaks HTTP: quota and saturation
                # are 429s with a Retry-After hint, not generic 422s
                self._http_reply(
                    f, 429, json.dumps(resp),
                    {"Retry-After": str(
                        max(1, resp.get("retry_after_ms", 1000) // 1000)
                    )},
                )
                return
            code = 200 if err_class is None else 422
            self._http_reply(f, code, json.dumps(resp))
            return
        self._http_reply(f, 404, json.dumps({"error": "not found"}))

    @staticmethod
    def _http_reply(f, status: int, body: str,
                    extra_headers: Optional[dict] = None) -> None:
        reason = {
            200: "OK", 404: "Not Found", 413: "Payload Too Large",
            422: "Unprocessable Entity", 429: "Too Many Requests",
        }
        data = body.encode()
        extras = "".join(
            f"{k}: {v}\r\n" for k, v in (extra_headers or {}).items()
        )
        head = (
            f"HTTP/1.1 {status} {reason.get(status, 'OK')}\r\n"
            f"Content-Type: application/json\r\n"
            f"Content-Length: {len(data)}\r\n"
            f"{extras}"
            f"Connection: close\r\n\r\n"
        )
        f.write(head.encode("latin-1") + data)
        f.flush()


def run_listener(serve, listen: str, writer: Writer) -> int:
    """CLI entry: bind, announce the bound address on stderr (port 0
    resolves here), then serve until interrupted. With a drain latch on
    the session (commands/serve.py installs one), a SIGTERM/SIGINT trip
    stops the accept loop; the caller finishes in-flight batches and
    maps the trip to the drain exit code."""
    server = ServeServer(serve, listen).start()
    writer.writeln_err(
        f"guard-tpu serve: listening on {server.host}:{server.port}"
    )
    latch = getattr(serve, "drain_latch", None)
    if latch is None:
        return server.serve_forever()
    try:
        while not server._stopped.is_set() and not latch.tripped():
            latch.wait(0.1)
    except KeyboardInterrupt:
        latch.trip("SIGINT")
    finally:
        server.stop()
    return 0
