"""Lowering: Guard AST -> flat predicate/path-query IR.

Compiles a parsed `RulesFile` into per-rule straight-line programs over
the columnar document encoding (guard_tpu/ops/encoder.py). This is the
TPU analogue of the reference's recursive evaluator
(`/root/reference/guard/src/rules/eval.rs` + `eval_context.rs`): queries
become step lists (key / all-values / all-indices / index / filter /
keys-match), clauses become leaf comparisons against pre-resolved
literals (string equality via intern ids, regex and substring matches
via host-precomputed bit tables), and block/when/CNF structure becomes
tri-state combinator nodes.

Lowering is *exact or refused*: any construct whose semantics the kernel
cannot reproduce bit-for-bit (function calls, query-to-query compares,
parameterized rules, map literals, variable captures) raises
`Unlowerable`, and the backend falls back to the CPU oracle for that
rule. Coverage is wide enough for the dominant registry rule shapes.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple, Union

import numpy as np

from ..core.exprs import (
    AccessQuery,
    Block,
    BlockGuardClause,
    CmpOperator,
    FunctionExpr,
    GuardAccessClause,
    GuardNamedRuleClause,
    ParameterizedNamedRuleClause,
    QAllIndices,
    QAllValues,
    QFilter,
    QIndex,
    QKey,
    QMapKeyFilter,
    QThis,
    Rule,
    RulesFile,
    TypeBlock,
    WhenBlockClause,
    part_is_variable,
    part_variable,
)
from ..core.scopes import CONVERTERS
from ..core.values import (
    BOOL,
    CHAR,
    FLOAT,
    INT,
    NULL,
    RANGE_CHAR,
    RANGE_FLOAT,
    RANGE_INT,
    REGEX,
    STRING,
    PV,
)
from .encoder import Interner

PASS, FAIL, SKIP = 0, 1, 2


class Unlowerable(Exception):
    """Raised when a rule uses semantics outside the kernel's coverage."""


# ---------------------------------------------------------------------------
# Step IR
# ---------------------------------------------------------------------------
@dataclass
class StepKey:
    key_ids: List[int]  # original key id + case-converted aliases
    drop_unres: bool = False  # `some`-marked variable splice


@dataclass
class StepAllValues:
    pass


@dataclass
class StepAllIndices:
    pass


@dataclass
class StepIndex:
    index: int  # already abs()'d (eval_context.rs:119-140)


@dataclass
class StepFilter:
    conjunctions: List[List["CClause"]]


@dataclass
class StepKeysMatch:
    rhs: "RhsSpec"
    op: CmpOperator
    op_not: bool


Step = Union[StepKey, StepAllValues, StepAllIndices, StepIndex, StepFilter, StepKeysMatch]


# ---------------------------------------------------------------------------
# RHS literal specs — everything pre-resolved against the intern table
# ---------------------------------------------------------------------------
@dataclass
class RhsSpec:
    kind: str  # 'str' | 'regex' | 'num' | 'bool' | 'null' | 'range' | 'list' | 'substr'
    str_id: int = -1
    bits: Optional[np.ndarray] = None  # (S,) bool for regex/substr
    num: float = 0.0
    num_kind: int = INT  # INT or FLOAT for numeric literals
    range_lo: float = 0.0
    range_hi: float = 0.0
    range_incl: int = 0
    range_kind: int = RANGE_INT
    items: Optional[List["RhsSpec"]] = None  # for 'list'


@dataclass
class CClause:
    """One guard access clause over a relative query."""

    steps: List[Step]
    op: CmpOperator
    op_not: bool
    negation: bool
    match_all: bool
    rhs: Optional[RhsSpec]
    empty_on_expr: bool  # eval.rs:193-196 special EMPTY handling
    lhs_starts_at_root: bool = False  # absolute query inside value scope? no: relative


@dataclass
class CBlockClause:
    query_steps: List[Step]
    match_all: bool
    not_empty: bool
    inner: List[List["CNode"]]  # conjunctions of CNodes


@dataclass
class CWhenBlock:
    conditions: List[List["CNode"]]
    inner: List[List["CNode"]]


@dataclass
class CNamedRef:
    rule_index: int  # index into the compiled-rules list
    negation: bool


CNode = Union[CClause, CBlockClause, CWhenBlock, CNamedRef]


@dataclass
class CRule:
    name: str
    conditions: Optional[List[List[CNode]]]
    conjunctions: List[List[CNode]]


@dataclass
class CompiledRules:
    rules: List[CRule]
    # rules that could not be lowered: (index in original file order, Rule)
    host_rules: List[Rule]
    interner: Interner
    # empty-string bit table for the EMPTY check on strings
    str_empty_bits: np.ndarray


# ---------------------------------------------------------------------------
# Lowering
# ---------------------------------------------------------------------------
class _RuleLowering:
    def __init__(self, rules_file: RulesFile, interner: Interner):
        self.rf = rules_file
        self.interner = interner
        self.var_queries = {}
        self.var_literals = {}
        for let in rules_file.assignments:
            if isinstance(let.value, AccessQuery):
                self.var_queries[let.var] = let.value
            elif isinstance(let.value, PV):
                self.var_literals[let.var] = let.value
            else:
                # function-call assignment: rules touching it go host-side
                self.var_queries[let.var] = None
        self.rule_index = {}

    # -- query lowering ------------------------------------------------
    def lower_query(self, parts: List, block_vars: dict) -> List[Step]:
        steps: List[Step] = []
        idx = 0
        if parts and part_is_variable(parts[0]):
            var = part_variable(parts[0])
            vq = self._lookup_var(var, block_vars)
            if vq is None:
                raise Unlowerable(f"variable {var} is not a plain query")
            inner = self.lower_query(vq.query, block_vars)
            if not vq.match_all:
                for s in inner:
                    if isinstance(s, StepKey):
                        s.drop_unres = True
            steps.extend(inner)
            idx = 1
            # skip the implicit [*] the parser inserted after the variable
            if idx < len(parts) and isinstance(parts[idx], QAllIndices):
                idx += 1
        for part in parts[idx:]:
            steps.append(self.lower_part(part, block_vars))
        return steps

    def _lookup_var(self, var: str, block_vars: dict):
        if var in block_vars:
            v = block_vars[var]
        elif var in self.var_queries:
            v = self.var_queries[var]
        elif var in self.var_literals:
            raise Unlowerable(f"literal variable {var} used as query head")
        else:
            raise Unlowerable(f"unknown variable {var}")
        if v is None or not isinstance(v, AccessQuery):
            return None
        return v

    def lower_part(self, part, block_vars) -> Step:
        if isinstance(part, QThis):
            raise Unlowerable("`this` inside query")
        if isinstance(part, QKey):
            if part_is_variable(part):
                raise Unlowerable("variable key interpolation")
            try:
                return StepIndex(abs(int(part.name)))
            except ValueError:
                pass
            kid = self.interner.lookup(part.name)
            ids = [kid] if kid >= 0 else []
            for conv in CONVERTERS:
                alias = self.interner.lookup(conv(part.name))
                if alias >= 0 and alias not in ids:
                    ids.append(alias)
            if not ids:
                ids = [-99]  # key absent from corpus: always unresolved
            return StepKey(key_ids=ids)
        if isinstance(part, QAllValues):
            if part.name is not None:
                raise Unlowerable("variable capture in projection")
            return StepAllValues()
        if isinstance(part, QAllIndices):
            if part.name is not None:
                raise Unlowerable("variable capture in projection")
            return StepAllIndices()
        if isinstance(part, QIndex):
            return StepIndex(abs(part.index))
        if isinstance(part, QFilter):
            if part.name is not None:
                raise Unlowerable("variable capture in filter")
            return StepFilter(
                conjunctions=[
                    [self.lower_guard_clause(c, block_vars) for c in disj]
                    for disj in part.conjunctions
                ]
            )
        if isinstance(part, QMapKeyFilter):
            if part.name is not None:
                raise Unlowerable("variable capture in keys filter")
            rhs = self.lower_rhs(part.clause.compare_with, block_vars)
            return StepKeysMatch(
                rhs=rhs, op=part.clause.comparator, op_not=part.clause.comparator_inverse
            )
        raise Unlowerable(f"query part {part!r}")

    # -- rhs lowering --------------------------------------------------
    def lower_rhs(self, cw, block_vars=None) -> RhsSpec:
        if isinstance(cw, AccessQuery):
            # `x IN %allowed` where %allowed is a literal assignment:
            # resolve at compile time (a Literal RHS in the reference,
            # eval_context.rs:1117-1119)
            parts = cw.query
            if parts and part_is_variable(parts[0]):
                var = part_variable(parts[0])
                lit = None
                if block_vars and var in block_vars and isinstance(block_vars[var], PV):
                    lit = block_vars[var]
                elif var in self.var_literals:
                    lit = self.var_literals[var]
                rest = parts[1:]
                if rest and isinstance(rest[0], QAllIndices):
                    rest = rest[1:]
                if lit is not None and not rest:
                    return self.lower_rhs(lit)
            raise Unlowerable("non-literal RHS (query or function call)")
        if not isinstance(cw, PV):
            raise Unlowerable("non-literal RHS (query or function call)")
        k = cw.kind
        if k == STRING:
            return RhsSpec(
                kind="str",
                str_id=self.interner.lookup(cw.val),
                bits=self.interner.substring_bits(-1, cw.val),
            )
        if k == REGEX:
            return RhsSpec(kind="regex", bits=self.interner.regex_match_bits(cw.val))
        if k == CHAR:
            return RhsSpec(kind="str", str_id=self.interner.lookup(cw.val))
        if k == INT:
            return RhsSpec(kind="num", num=float(cw.val), num_kind=INT)
        if k == FLOAT:
            return RhsSpec(kind="num", num=float(cw.val), num_kind=FLOAT)
        if k == BOOL:
            return RhsSpec(kind="bool", num=1.0 if cw.val else 0.0)
        if k == NULL:
            return RhsSpec(kind="null")
        if k in (RANGE_INT, RANGE_FLOAT, RANGE_CHAR):
            if k == RANGE_CHAR:
                raise Unlowerable("char range literal")
            r = cw.val
            return RhsSpec(
                kind="range",
                range_lo=float(r.lower),
                range_hi=float(r.upper),
                range_incl=r.inclusive,
                range_kind=k,
                num_kind=INT if k == RANGE_INT else FLOAT,
            )
        if k == 7:  # LIST
            items = [self.lower_rhs(e) for e in cw.val]
            for it in items:
                if it.kind not in ("str", "regex", "num", "bool", "null", "range"):
                    raise Unlowerable("nested list in RHS list literal")
            return RhsSpec(kind="list", items=items)
        raise Unlowerable(f"RHS literal kind {cw.type_info()}")

    # -- clause lowering ----------------------------------------------
    def lower_guard_clause_as_cclause(self, clause, block_vars) -> "CClause":
        if not isinstance(clause, GuardAccessClause):
            raise Unlowerable(f"filter clause {type(clause).__name__}")
        return self.lower_access_clause(clause, block_vars)

    def lower_access_clause(self, gac: GuardAccessClause, block_vars) -> CClause:
        ac = gac.access_clause
        parts = ac.query.query
        # the `empty`-on-expression special case (eval.rs:193-196)
        last = parts[-1]
        empty_on_expr = isinstance(last, (QFilter, QMapKeyFilter)) or (
            part_is_variable(last) and len(parts) == 1
        )
        steps = self.lower_query(parts, block_vars)
        rhs = None
        if not ac.comparator.is_unary():
            rhs = self.lower_rhs(ac.compare_with, block_vars)
        return CClause(
            steps=steps,
            op=ac.comparator,
            op_not=ac.comparator_inverse,
            negation=gac.negation,
            match_all=ac.query.match_all,
            rhs=rhs,
            empty_on_expr=empty_on_expr,
        )

    def lower_guard_clause(self, clause, block_vars) -> CNode:
        if isinstance(clause, GuardAccessClause):
            return self.lower_access_clause(clause, block_vars)
        if isinstance(clause, BlockGuardClause):
            inner_vars = self._merge_block_vars(block_vars, clause.block)
            return CBlockClause(
                query_steps=self.lower_query(clause.query.query, block_vars),
                match_all=clause.query.match_all,
                not_empty=clause.not_empty,
                inner=[
                    [self.lower_guard_clause(c, inner_vars) for c in disj]
                    for disj in clause.block.conjunctions
                ],
            )
        if isinstance(clause, WhenBlockClause):
            inner_vars = self._merge_block_vars(block_vars, clause.block)
            return CWhenBlock(
                conditions=[
                    [self.lower_guard_clause(c, block_vars) for c in disj]
                    for disj in clause.conditions
                ],
                inner=[
                    [self.lower_guard_clause(c, inner_vars) for c in disj]
                    for disj in clause.block.conjunctions
                ],
            )
        if isinstance(clause, GuardNamedRuleClause):
            target = self.rule_index.get(clause.dependent_rule)
            if target is None:
                raise Unlowerable(f"named rule {clause.dependent_rule} not lowerable")
            return CNamedRef(rule_index=target, negation=clause.negation)
        if isinstance(clause, ParameterizedNamedRuleClause):
            raise Unlowerable("parameterized rule call")
        if isinstance(clause, TypeBlock):
            inner_vars = self._merge_block_vars(block_vars, clause.block)
            if clause.conditions is not None:
                raise Unlowerable("type block with when conditions")
            return CBlockClause(
                query_steps=self.lower_query(clause.query, block_vars),
                match_all=True,
                not_empty=False,
                inner=[
                    [self.lower_guard_clause(c, inner_vars) for c in disj]
                    for disj in clause.block.conjunctions
                ],
            )
        raise Unlowerable(f"clause {type(clause).__name__}")

    def _merge_block_vars(self, outer: dict, block: Block) -> dict:
        merged = dict(outer)
        for let in block.assignments:
            if isinstance(let.value, (AccessQuery, PV)):
                merged[let.var] = let.value
            else:
                merged[let.var] = None  # function call: bail if used
        return merged

    def lower_rule(self, rule: Rule) -> CRule:
        block_vars = self._merge_block_vars({}, rule.block)
        conditions = None
        if rule.conditions is not None:
            conditions = [
                [self.lower_guard_clause(c, block_vars) for c in disj]
                for disj in rule.conditions
            ]
        conjunctions = [
            [self.lower_guard_clause(c, block_vars) for c in disj]
            for disj in rule.block.conjunctions
        ]
        return CRule(name=rule.rule_name, conditions=conditions, conjunctions=conjunctions)


def compile_rules_file(rules_file: RulesFile, interner: Interner) -> CompiledRules:
    """Lower every rule; rules that refuse lowering are returned in
    `host_rules` for CPU-oracle evaluation (the fail-rerun design)."""
    lowering = _RuleLowering(rules_file, interner)
    compiled: List[CRule] = []
    host: List[Rule] = []
    # duplicate rule names can't use CNamedRef's first-non-SKIP semantics
    names_seen = {}
    for r in rules_file.guard_rules:
        names_seen[r.rule_name] = names_seen.get(r.rule_name, 0) + 1
    for rule in rules_file.guard_rules:
        if names_seen[rule.rule_name] > 1:
            host.append(rule)
            continue
        try:
            cr = lowering.lower_rule(rule)
        except Unlowerable:
            host.append(rule)
            continue
        lowering.rule_index[rule.rule_name] = len(compiled)
        compiled.append(cr)
    str_empty_bits = np.array(
        [len(s) == 0 for s in interner.strings], dtype=bool
    )
    return CompiledRules(
        rules=compiled,
        host_rules=host,
        interner=interner,
        str_empty_bits=str_empty_bits,
    )
