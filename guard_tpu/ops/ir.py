"""Lowering: Guard AST -> flat predicate/path-query IR.

Compiles a parsed `RulesFile` into per-rule straight-line programs over
the columnar document encoding (guard_tpu/ops/encoder.py). This is the
TPU analogue of the reference's recursive evaluator
(`/root/reference/guard/src/rules/eval.rs` + `eval_context.rs`): queries
become step lists (key / all-values / all-indices / index / filter /
keys-match), clauses become leaf comparisons against pre-resolved
literals (string equality via intern ids, regex and substring matches
via host-precomputed bit tables), and block/when/CNF structure becomes
tri-state combinator nodes.

Lowering is *exact or refused*: any construct whose semantics the kernel
cannot reproduce bit-for-bit raises `Unlowerable`, and the backend falls
back to the CPU oracle for that rule. The *semantic categories* that
stay host-side are enumerated in `HOST_ONLY_CONSTRUCTS` below (kept
honest by `tests/test_ir_refusals.py`); beyond those, individual raise
sites in this file refuse structural edge shapes (chained filters,
numeric literals with no exact device encoding, count bounds beyond
i32, malformed parameterized calls, ...) — grep `Unlowerable(` for the
full set. Function calls, query-to-query compares, map/struct literals
and root-bound variable captures all lower as of rounds 2-3 (see
docs/KNOWN_ISSUES.md "TPU backend coverage").
Parameterized rule calls (eval.rs:1504-1618) lower by inline expansion:
argument queries are pre-lowered in the caller's scope, literals bind
like `let` literals, and the callee body becomes an anonymous gated
block. Coverage spans all 21 reference guard-examples rules and the
full vendored registry corpus.
"""

from __future__ import annotations

import copy

from dataclasses import dataclass, field
from typing import List, Optional, Tuple, Union

import numpy as np

from ..core.exprs import (
    AccessQuery,
    Block,
    BlockGuardClause,
    CmpOperator,
    FunctionExpr,
    GuardAccessClause,
    GuardNamedRuleClause,
    ParameterizedNamedRuleClause,
    QAllIndices,
    QAllValues,
    QFilter,
    QIndex,
    QKey,
    QMapKeyFilter,
    QThis,
    Rule,
    RulesFile,
    TypeBlock,
    WhenBlockClause,
    part_is_variable,
    part_variable,
)
from ..core.scopes import CONVERTERS
from ..core.values import (
    BOOL,
    CHAR,
    FLOAT,
    INT,
    NULL,
    RANGE_CHAR,
    RANGE_FLOAT,
    RANGE_INT,
    REGEX,
    STRING,
    PV,
    compiled_regex,
)
from .encoder import Interner, num_key

PASS, FAIL, SKIP = 0, 1, 2


class Unlowerable(Exception):
    """Raised when a rule uses semantics outside the kernel's coverage."""


#: The documented host-only *semantic categories* (the same list
#: docs/KNOWN_ISSUES.md publishes to users). Not an enumeration of
#: every `Unlowerable` raise site — structural edge shapes also refuse;
#: see the module docstring. `tests/test_ir_refusals.py` holds one
#: canonical example per key and asserts it actually falls back to the
#: host, and asserts the formerly-documented refusals still lower, so
#: the categories listed here track the implementation in both
#: directions for the shapes they name.
HOST_ONLY_CONSTRUCTS = {
    "now_builtin": (
        "now() is nondeterministic: precomputing at encode time could "
        "straddle a second boundary vs the oracle rerun"
    ),
    "parse_char_builtin": (
        "parse_char produces CHAR nodes, which documents otherwise "
        "never contain"
    ),
    "cross_scope_value_var_head": (
        "a variable bound in a non-root value scope used as a query "
        "HEAD (or interpolated) in another scope re-resolves per "
        "origin mid-walk — bare `%v` uses as clause RHS lower via "
        "per-use-site precompute ('pvar' slots) as of round 5, but a "
        "head use starts a fresh traversal from per-origin values, "
        "which the columnar walk cannot replay"
    ),
    "variable_capture": (
        "variable capture inside a query projection or filter binds "
        "per traversal step — refused only when the captured name is "
        "actually referenced as %name somewhere in the file "
        "(unreferenced markers are unobservable and lower as the "
        "unnamed form)"
    ),
}


class CrossScopeRootVar(Unlowerable):
    """A query head references a variable bound at the ROOT scope from
    inside a value scope. The query then resolves against the document
    root regardless of the current selection, so the owning clause can
    evaluate once from root and broadcast (CClause.eval_from_root)."""


# ---------------------------------------------------------------------------
# Step IR
# ---------------------------------------------------------------------------
@dataclass
class StepKey:
    # original key STRING + case-converted aliases (deduped by value).
    # The IR carries strings, not interned ids: ids are corpus-dependent
    # and live in the runtime `lits` array (CompiledRules.lit_values),
    # so the kernel trace is corpus-INDEPENDENT and executables reuse
    # across validate invocations / sweep chunks / serve requests.
    key_names: List[str]
    drop_unres: bool = False  # `some`-marked variable splice
    # slot into CompiledRules.kidc_tables: host-precomputed (D, N)
    # "this node has a child under one of the keys" column — the
    # resolved/miss check is static per node, so the kernel never pays
    # a count-children reduction for it
    kc_slot: int = -1
    # slots into the runtime lits array, parallel to key_names
    lit_slots: List[int] = field(default_factory=list)


@dataclass
class StepKeyInterpLit:
    """`.%var` where %var is a LITERAL string / list of strings
    (eval_context.rs:421-526 via scopes._retrieve_key:545-632): each
    string is a separate EXACT key lookup (no case-converter retry) —
    hits concatenate, each miss is its own UnResolved entry."""

    # one literal string per entry; None = a key that can never match
    # (out-of-bounds literal index) — binds to the never-matching id
    key_names: List[Optional[str]]
    # per-key has-child column slots (parallel to key_names): the
    # per-(map, key) miss check is static per node
    kc_slots: List[int] = field(default_factory=list)
    # runtime lits slots, parallel to key_names
    lit_slots: List[int] = field(default_factory=list)


@dataclass
class StepKeyInterpVar:
    """`.%var` where %var is a QUERY over the same document: the
    variable resolves from the root scope at evaluation time, list
    values flatten one level, and each resolved string is an exact key
    lookup per selected map (one UnResolved per missing (map, key)
    pair). Non-string key values raise on the oracle
    (scopes._retrieve_key:621-631) — the kernel flags the document
    unsure instead.

    `index`: `.%var[k]` picks the k-th entry of the variable's result
    list BEFORE key matching — and the reference then ALSO walks the
    `[k]` part into the resolved value (eval_context.rs:421-526 does
    not consume the index), so the lowering keeps the following
    StepIndex too. Out-of-bounds k UnResolves every candidate. Entry
    order with UnResolved entries present is not representable on
    device, so those documents flag unsure."""

    var_steps: List["Step"]
    index: Optional[int] = None


@dataclass
class StepAllValues:
    pass


@dataclass
class StepAllIndices:
    pass


@dataclass
class StepIndex:
    index: int  # already abs()'d (eval_context.rs:119-140)
    # host-precomputed "has a child at this list index" column slot
    kc_slot: int = -1


@dataclass
class StepFilter:
    """Filter semantics depend on the preceding query part
    (scopes._retrieve_filter, eval_context.rs:723-828): after a key (or
    at query start) maps expand to their values; after `.*` the map
    itself is the filter candidate (each value was re-scoped by
    accumulate_map, eval_context.rs:216-229); scalars are UnResolved.
    Lists always iterate. Filters after `[*]` refuse lowering: list
    elements are NOT re-scoped (accumulate, eval_context.rs:142-178),
    so map candidates there evaluate the filter against the *outer*
    scope — semantics the kernel does not model.

    After a VARIABLE head (`%var[ ... ]`, scopes.py:390-408 wraps each
    resolved value in its own ValueScope before the implicit-`[*]`-
    skipped walk reaches the filter) maps and scalars both filter
    THEMSELVES in their own scope and lists iterate — `scalar_self`
    marks that mode (no candidate is ever UnResolved there)."""

    conjunctions: List[List["CClause"]]
    # prev was a key / query start: map candidates expand to their values
    expand_maps: bool = False
    # prev was a spliced variable head: scalars self-filter too
    scalar_self: bool = False


@dataclass
class StepKeysMatch:
    rhs: "RhsSpec"
    op: CmpOperator
    op_not: bool


@dataclass
class StepKeyChain:
    """A maximal run of >= 2 StepKeys with pairwise-DISJOINT key-id
    sets, folded into ONE device permutation (vs one per step).

    Exactness rests on two facts. (1) Selections are ANTICHAINS (no
    selected node is an ancestor of another): every traversal step
    either replaces parents by children or keeps childless scalars, so
    by induction from {root} the property is preserved. (2) With
    pairwise-disjoint step keys, a node can prefix-match the chain at
    at most ONE position j >= 1 (its own key equals k_j for exactly
    one j). Together these give each node a unique static "anchor"
    ancestor (chain length up for full matches, j up for the node
    whose k_{j+1} child is missing), so the only dynamic information
    the whole run needs is `sel[anchor[m]]` — one permutation by a
    host-precomputed int32 column, serving both the new selection and
    the deep UnResolved charges. The basis-level miss (position 0:
    selected node lacking a k_1 child) anchors at the node itself and
    is charged inline from `sel` with the first step's has-child
    column — it would otherwise collide with deeper miss positions in
    the shared anchor column.

    Wildcard steps (`.*` / `[*]`) do NOT fold: they match every key,
    which breaks position uniqueness — and a folded trailing wildcard
    was tried and rejected because moved children carry unconstrained
    keys, so they can collide with position-1 miss anchors in the one
    shared anchor column.

    Columns per chain (CompiledRules.chain_tables -> device arrays):
      chF{i} (D, N) bool  — full prefix match ending here (depth k)
      chM{i} (D, N) bool  — deep miss at this node (position 1..k-1,
                            only for steps without drop_unres)
      chA{i} (D, N) int32 — the anchor ancestor (0 elsewhere)
    """

    steps: List[StepKey]
    chain_slot: int = -1


@dataclass
class StepFnVar:
    """Select the precomputed result roots of a function variable
    (ops/fnvars.py): orphan nodes tagged with the reserved negative
    key id. Shared slots are reachable only from the root basis
    (function lets bind at the root scope), so the selection carries
    origin label 1. `per_origin` slots ('pexpr' — inline calls whose
    query arguments resolve per candidate) select instead the result
    roots whose fn_origin column matches a currently-selected origin,
    relabelled with that origin's label — the per-origin query-RHS
    compare arms then join LHS and RHS per origin exactly. Function
    variables never hold UnResolved entries (scopes.resolve_function
    drops None results), so no UnResolved accounting applies."""

    key_id: int
    per_origin: bool = False


Step = Union[
    StepKey,
    StepKeyChain,
    StepKeyInterpLit,
    StepKeyInterpVar,
    StepAllValues,
    StepAllIndices,
    StepIndex,
    StepFilter,
    StepKeysMatch,
    StepFnVar,
]


# ---------------------------------------------------------------------------
# RHS literal specs — everything pre-resolved against the intern table
# ---------------------------------------------------------------------------
@dataclass
class RhsSpec:
    # 'str' | 'regex' | 'num' | 'bool' | 'null' | 'range' | 'list' |
    # 'substr' | 'never' (literal kinds no document scalar can ever be
    # comparable with, e.g. char ranges — docs never contain CHAR nodes)
    kind: str
    # the literal string itself ('str' kind); its interned id is bound
    # at batch time through the runtime lits array (str_slot)
    str_val: Optional[str] = None
    str_slot: int = -1
    bits: Optional[np.ndarray] = None  # (S,) bool for regex/substr
    # (S,) bool tables for lexicographic string ordering vs the literal
    # (path_value.rs:1048-1070 via compare_values; gt = ~le, ge = ~lt)
    lt_bits: Optional[np.ndarray] = None
    le_bits: Optional[np.ndarray] = None
    # the predicate each table row answers, as a corpus-independent
    # spec tuple (("substr", lit) / ("regex", pat) / ("lt", lit) /
    # ("le", lit)) — recorded so a table compiled against one interner
    # can be EXTENDED over strings interned later (ops/plan.py
    # relocation) by evaluating the same predicate over the new suffix
    bits_spec: Optional[tuple] = None
    lt_spec: Optional[tuple] = None
    le_spec: Optional[tuple] = None
    # slots into CompiledRules.bit_tables, assigned by _assign_bit_slots:
    # the (S,) per-string tables are materialized host-side into (D, N)
    # per-NODE bool columns per batch, so the kernel never gathers
    bits_slot: int = -1
    lt_slot: int = -1
    le_slot: int = -1
    # exact numeric literal as an order-preserving (hi, lo) int32 key
    # pair (encoder.num_key) — compares exactly against the document's
    # num_hi/num_lo columns; no float32 collisions
    num_key: Tuple[int, int] = (0, 0)
    num_kind: int = INT  # INT or FLOAT for numeric literals
    range_lo_key: Tuple[int, int] = (0, 0)
    range_hi_key: Tuple[int, int] = (0, 0)
    range_incl: int = 0
    range_kind: int = RANGE_INT
    items: Optional[List["RhsSpec"]] = None  # for 'list'
    # 'struct' literals (map / nested-list RHS): index into
    # CompiledRules.struct_literals; resolved per batch to a canonical
    # struct id (DocBatch.struct_ids classes = loose_eq)
    struct_slot: int = -1
    # the struct literal is itself a LIST: an In-rhs whose FIRST item
    # is a list switches to whole-list membership (operators.rs:317-327)
    struct_is_list: bool = False


@dataclass
class CCountClause:
    """A clause whose LHS is a `count()` function variable
    (`let n = count(q)` then `%n == 2`): the reference resolves the
    function once per scope into a single synthetic INT value
    (functions/collections.rs:6-23 counts the RESOLVED entries of the
    argument query; eval_context.rs:1286-1472 dispatch), so the clause
    reduces to one integer comparison. `steps` is the argument query
    lowered from the ROOT basis (file- and rule-level lets both bind at
    the root scope, eval_context.rs:926-997).

    `static_status`: unary ops over the count value depend only on the
    value's kind (always exactly one resolved INT), so their tri-state
    outcome is a compile-time constant and `steps` is not even run.

    `cmp` encodes the binary comparison against the count:
      ('int', v, op, op_not)        exact integer compare
      ('range', lo, hi, incl, op_not)  INT range membership (In)
      ('in', [ints], op_not)        list membership via loose_eq — only
                                    INT items can ever equal the count
      ('never',)                    NotComparable RHS kinds -> FAIL both
                                    with and without `not`
                                    (operators.rs:195-206)"""

    steps: List[Step]
    static_status: Optional[int] = None
    cmp: Optional[tuple] = None


@dataclass
class CClause:
    """One guard access clause over a relative query."""

    steps: List[Step]
    op: CmpOperator
    op_not: bool
    negation: bool
    match_all: bool
    rhs: Optional[RhsSpec]
    empty_on_expr: bool  # eval.rs:193-196 special EMPTY handling
    lhs_starts_at_root: bool = False  # absolute query inside value scope? no: relative
    # RHS that is itself a query (resolved per document in the same
    # scope as the LHS): set-comparison semantics, operators.rs:552-594
    # (Eq query_in) and :434-451 (In). Only for Eq/In.
    rhs_query_steps: Optional[List[Step]] = None
    # LHS head is a root-bound variable used inside a value scope: the
    # query result set is origin-independent, so the clause evaluates
    # once from the document root and the status broadcasts to every
    # origin (the oracle resolves the variable against its binding
    # scope, eval_context.rs:1117-1163)
    eval_from_root: bool = False
    # the RHS query's head is a root-bound variable (`x IN %allowed`
    # inside a filter): the RHS set resolves once from the root and is
    # shared by every origin; In-only (Eq needs per-origin reverse
    # membership)
    rhs_query_from_root: bool = False


@dataclass
class CBlockClause:
    query_steps: List[Step]
    match_all: bool
    not_empty: bool
    inner: List[List["CNode"]]  # conjunctions of CNodes


@dataclass
class CWhenBlock:
    # None = ungated grouping (inline-expanded parameterized rule body
    # without when conditions)
    conditions: Optional[List[List["CNode"]]]
    inner: List[List["CNode"]]


@dataclass
class CNamedRef:
    # compiled-rules indices of every rule with the referenced name, in
    # file order: the reference takes the FIRST non-SKIP status among
    # same-named rules (eval_context.rs:1087-1115)
    rule_indices: List[int]
    negation: bool


CNode = Union[CClause, CCountClause, CBlockClause, CWhenBlock, CNamedRef]


@dataclass
class CRule:
    name: str
    conditions: Optional[List[List[CNode]]]
    conjunctions: List[List[CNode]]


@dataclass
class CompiledRules:
    rules: List[CRule]
    # rules that could not be lowered: (index in original file order, Rule)
    host_rules: List[Rule]
    interner: Interner
    # empty-string bit table for the EMPTY check on strings
    str_empty_bits: np.ndarray
    # any rule compares against a query RHS or a struct literal:
    # kernels need the canonical struct-id column (DocBatch.struct_ids)
    needs_struct_ids: bool = False
    # any rule may emit per-(doc, rule) "unsure" bits routing those
    # docs to the oracle (query-RHS compares, key interpolation)
    needs_unsure: bool = False
    # (table, target) per slot; target "scalar" applies the (S,) table
    # through scalar_id, "key" through node_key_id
    bit_tables: List[Tuple[np.ndarray, str]] = field(default_factory=list)
    # parallel to bit_tables: the corpus-independent predicate each
    # table evaluates (("substr", lit) / ("regex", pat) / ("lt", lit) /
    # ("le", lit) / ("empty",)), so extend_bit_tables can grow a table
    # over strings interned AFTER compile without re-lowering
    bit_specs: List[tuple] = field(default_factory=list)
    str_empty_slot: int = -1
    # map / nested-list RHS literals, evaluated per batch into the
    # 'stri_m{i}'/'stri_c{i}'/'stri_l{i}' tri-state/loose columns
    # (encoder.struct_literal_tri)
    struct_literals: List[PV] = field(default_factory=list)
    # has-child column specs, one (D, N) bool device column each:
    # ("k", key_id, ...) = node has a child under one of the key ids;
    # ("i", index) = node has a child at the list index. Deduped across
    # steps (_assign_bit_slots); computed per batch in device_arrays.
    kidc_tables: List[tuple] = field(default_factory=list)
    # folded StepKeyChain specs (StepKeyChain docstring): per chain a
    # tuple of (key_names tuple, drop_unres) per step, resolved per
    # batch into the chF/chM/chA columns
    chain_tables: List[tuple] = field(default_factory=list)
    # non-empty when a lowered rule reads a precomputed function
    # variable (StepFnVar): the batch must be encoded with
    # encode_batch(fn_values=precompute_fn_values(rf, docs),
    # fn_var_order=this) BEFORE compile (function results intern new
    # strings the bit tables must cover)
    fn_vars: List[str] = field(default_factory=list)
    # ordering comparisons against query RHS need string-vs-string
    # order between arbitrary document strings: a per-node rank column
    # over the lexicographically sorted intern table
    needs_str_rank: bool = False
    # any lowered rule reads a PER-ORIGIN function variable (StepFnVar
    # per_origin): device_arrays must ship the batch's fn_origin column
    needs_fn_origin: bool = False
    # any rule uses pairwise constructions (query-RHS compares,
    # variable key interpolation). They no longer cap the bucket size:
    # gather mode evaluates them through O(N log N) sorted-set joins
    # (kernels._in_set_sorted and friends), and this flag now only
    # forces gather above 8,192 nodes (the one-hot arm still builds
    # (N, N) matrices, fine at small buckets only)
    needs_pairwise: bool = False
    # the literals-as-inputs table: one entry per unique rule-literal
    # string the kernel compares against (key lookups, string-equality
    # RHS). The kernel reads interned ids from a runtime (L,) int32
    # array (lit_values) instead of baking them into the trace — the
    # trace depends only on rule STRUCTURE, so executables reuse across
    # corpora, invocations, sweep chunks and serve requests. None
    # entries bind to the never-matching id.
    lit_names: List[Optional[str]] = field(default_factory=list)

    def lit_values(self, interner: Optional[Interner] = None) -> np.ndarray:
        """Bind lit_names against an interner: (L,) int32 of interned
        ids, -99 (never matches any node) for absent strings."""
        itn = interner if interner is not None else self.interner
        vals = []
        for name in self.lit_names:
            i = -1 if name is None else itn.lookup(name)
            vals.append(i if i >= 0 else -99)
        if not vals:
            vals = [-99]  # keep the runtime arg non-empty / stable
        return np.asarray(vals, dtype=np.int32)

    def device_arrays(self, batch) -> dict:
        """Everything the kernel reads, as a flat dict of (D, ...)
        arrays: the static per-node columns plus one precomputed bool
        column per bit-table slot (gathering `table[id]` here on the
        host — device gathers are ~150x slower than the kernels' fused
        one-hot forms at these shapes)."""
        out = {
            "node_kind": batch.node_kind,
            "node_parent": batch.node_parent,
            "scalar_id": batch.scalar_id,
            "num_hi": batch.num_hi,
            "num_lo": batch.num_lo,
            "child_count": batch.child_count,
            "node_key_id": batch.node_key_id,
            "node_index": batch.node_index,
            "node_parent_kind": batch.node_parent_kind,
        }
        if self.needs_fn_origin:
            out["fn_origin"] = (
                batch.fn_origin
                if batch.fn_origin is not None
                else np.full_like(batch.node_kind, -1)
            )
        if self.needs_struct_ids:
            out["struct_id"] = batch.struct_ids()
        if self.struct_literals:
            # exact compare_eq tri-state (match, comparable) + loose_eq
            # membership column per literal — host-evaluated once per
            # canonical class (encoder.struct_literal_tri), read by the
            # kernels' struct arm
            for i, (m, c, lo) in enumerate(
                batch.struct_literal_tri(self.struct_literals, self.interner)
            ):
                out[f"stri_m{i}"] = m
                out[f"stri_c{i}"] = c
                out[f"stri_l{i}"] = lo
        if self.needs_str_rank:
            strings = self.interner.strings
            rank = np.zeros(max(len(strings), 1), dtype=np.int32)
            for r, i in enumerate(sorted(range(len(strings)),
                                         key=strings.__getitem__)):
                rank[i] = r
            ids = batch.scalar_id
            safe = np.clip(ids, 0, len(rank) - 1)
            out["str_rank"] = np.where(
                (ids >= 0) & (ids < len(rank)), rank[safe], -1
            ).astype(np.int32)
        for i, (table, target) in enumerate(self.bit_tables):
            ids = batch.scalar_id if target == "scalar" else batch.node_key_id
            if len(table) == 0:
                col = np.zeros(ids.shape, dtype=bool)
            else:
                # ids beyond the table (strings interned after compile)
                # are conservatively False; the plan layer (ops/plan.py)
                # extends tables over newly interned strings before
                # dispatch, so this only affects padding
                safe = np.clip(ids, 0, len(table) - 1)
                col = table[safe] & (ids >= 0) & (ids < len(table))
            out[f"bits{i}"] = col
        if self.kidc_tables:
            for i, spec in enumerate(self.kidc_tables):
                out[f"kidc{i}"] = _has_child_col(batch, spec, self.interner)
        for i, spec in enumerate(self.chain_tables):
            f, m, a = _chain_columns(batch, spec, self.interner)
            out[f"chF{i}"] = f
            out[f"chM{i}"] = m
            out[f"chA{i}"] = a
        return out


def _resolve_key_names(names, interner: Interner) -> np.ndarray:
    """Key-name strings -> present interned ids (absent strings can
    never match a document key, so they simply drop out)."""
    ids = []
    for name in names:
        if name is None:
            continue
        i = interner.lookup(name)
        if i >= 0:
            ids.append(i)
    return np.asarray(ids if ids else [-99], dtype=np.int64)


def _has_child_col(batch, spec, interner: Interner) -> np.ndarray:
    """(D, N) bool: node has a child matching `spec` — ("k", *names)
    = under one of the key strings; ("i", index) = at the list index.
    Shared by the kidc_tables columns and the chain deep-miss columns
    so padding/edge_valid handling cannot drift between them."""
    d, n = batch.node_kind.shape
    flat = (
        np.arange(d, dtype=np.int64)[:, None] * n
        + np.maximum(batch.edge_parent, 0)
    )
    if spec[0] == "k":
        match = np.isin(batch.edge_key_id, _resolve_key_names(spec[1:], interner))
    else:  # ("i", index)
        match = batch.edge_index == spec[1]
    match &= batch.edge_valid
    return (
        np.bincount(flat[match], minlength=d * n)
        .reshape(d, n)
        .astype(bool)
    )


def _chain_columns(batch, spec, interner: Interner):
    """Host columns for one folded StepKeyChain (StepKeyChain
    docstring): walk the static parent structure once per level.

    spec = ((key_names, drop_unres), ...) per step, length k >= 2.
    Returns (full (D,N) bool, deep-miss (D,N) bool, anchor (D,N)
    int32): full marks nodes whose k-deep ancestor key path matches
    every step; deep-miss marks nodes prefix-matched through position
    j in [1, k-1] whose k_{j+1} child is missing (accounting steps
    only); anchor holds the j- (or k-) level ancestor for both."""
    d, n = batch.node_kind.shape
    parent = batch.node_parent
    valid = parent >= 0
    pclip = np.maximum(parent, 0)
    key_id = batch.node_key_id

    def has_child(names) -> np.ndarray:
        return _has_child_col(batch, ("k",) + tuple(names), interner)

    k = len(spec)
    full = np.zeros((d, n), dtype=bool)
    miss = np.zeros((d, n), dtype=bool)
    anchor = np.zeros((d, n), dtype=np.int32)
    # match_j[c]: c's key == k_j and its (j-1)-prefix matches; anc_j[c]
    # = the ancestor j levels up (the prospective basis node)
    match_prev = None
    anc_prev = None
    for j, (names, _du) in enumerate(spec):
        kh = np.isin(key_id, _resolve_key_names(names, interner))
        if j == 0:
            match_j = kh & valid
            anc_j = np.where(match_j, pclip, 0)
        else:
            pm = np.take_along_axis(match_prev, pclip, axis=1)
            match_j = kh & valid & pm
            anc_j = np.where(
                match_j, np.take_along_axis(anc_prev, pclip, axis=1), 0
            )
        pos = j + 1  # nodes matched through position `pos`
        if pos == k:
            full = match_j
            anchor = np.where(match_j, anc_j, anchor)
        else:
            nxt_names, nxt_du = spec[pos]
            if not nxt_du:
                mj = match_j & ~has_child(nxt_names)
                # pairwise-disjoint keys make positions unique: no
                # overwrite can occur here
                miss |= mj
                anchor = np.where(mj, anc_j, anchor)
        match_prev, anc_prev = match_j, anc_j
    return full, miss, anchor


# ---------------------------------------------------------------------------
# Lowering
# ---------------------------------------------------------------------------
def _prev_class(parts, i) -> str:
    """Classify the query part preceding parts[i] for filter semantics
    (scopes._retrieve_filter inspects query[query_index - 1])."""
    if i == 0:
        return "start"
    prev = parts[i - 1]
    if isinstance(prev, QAllValues):
        return "allvalues"
    if isinstance(prev, QAllIndices):
        return "allindices"
    if isinstance(prev, QKey):
        return "key"
    return "other"


@dataclass
class _PreloweredQuery:
    """A parameterized-rule argument query, lowered in the CALLER's
    scope at call time (eval.rs:1574-1599 resolves arguments against the
    caller's context before entering the callee)."""

    steps: List[Step]
    match_all: bool


def _referenced_variable_names(rf: RulesFile) -> set:
    """Every variable name mentioned as a `%x` query part anywhere in
    the file (queries, filter interiors, function arguments, let
    values, parameterized-rule bodies — all channels, because
    exprs.walk_expr_tree is structural, not enumerated)."""
    from ..core.exprs import walk_expr_tree

    out: set = set()

    def visit(o) -> bool:
        if isinstance(o, QKey):
            if part_is_variable(o):
                out.add(part_variable(o))
            return True
        return False

    walk_expr_tree(rf, visit)
    return out


class _RuleLowering:
    """Lowers one RulesFile.

    Variable scoping: a lowered query's steps run relative to the
    kernel's *current selection*, but the oracle resolves a variable
    against the scope where it was bound (RootScope/BlockScope,
    eval_context.rs:47-87). Splicing a variable's steps is therefore
    only exact when the use site evaluates at the same selection basis
    as the binding site. Each selection-changing construct (block
    bodies, filter conjunctions) gets a fresh scope token; bindings
    remember their token and a use under a different token refuses
    lowering (host fallback)."""

    def __init__(self, rules_file: RulesFile, interner: Interner):
        self.rf = rules_file
        self.interner = interner
        self.var_queries = {}
        self.var_literals = {}
        # count() assignments (`let n = count(q)`): the one function
        # the kernel lowers — value = number of RESOLVED entries of the
        # argument query (functions/collections.rs:6-23)
        self.var_counts = {}
        # file-level function lets by name (the binding OBJECT is the
        # var_slots key, so lookups go name -> fx -> slot)
        self.var_file_fns = {}
        for let in rules_file.assignments:
            if isinstance(let.value, AccessQuery):
                self.var_queries[let.var] = let.value
            elif isinstance(let.value, PV):
                self.var_literals[let.var] = let.value
            else:
                # function-call assignment: rules touching it go
                # host-side, except count-over-query (see var_counts)
                self.var_queries[let.var] = None
                fx = let.value
                if isinstance(fx, FunctionExpr):
                    self.var_file_fns[let.var] = fx
                if (
                    isinstance(fx, FunctionExpr)
                    and fx.name == "count"
                    and len(fx.parameters) == 1
                    and isinstance(fx.parameters[0], AccessQuery)
                ):
                    self.var_counts[let.var] = fx.parameters[0]
        # other function lets / literal query-heads / inline function
        # expressions: precomputed per document on the host and encoded
        # as orphan result subtrees (ops/fnvars.py). Slot numbering
        # MUST match the encoder's (both derive from fn_slots;
        # count/now/parse_char are excluded there, so count stays on
        # its native CCountClause path)
        from .fnvars import fn_slots

        self.fn_layout = fn_slots(rules_file)
        self.var_functions = self.fn_layout.var_slots
        # every variable NAME referenced anywhere in the file (`%x`
        # query parts — heads, interpolation, RHS queries, function
        # arguments, let values, parameterized-call args — found by a
        # generic structural walk, so no syntactic channel can be
        # missed). A variable CAPTURE whose name is never referenced is
        # unobservable (captures only surface through `%name`
        # resolution, scopes.add_variable_capture_key consumers), so
        # such markers lower as their unnamed equivalents.
        self.referenced_vars = _referenced_variable_names(rules_file)
        self._cur_rule_idx = -1  # set per rule by compile_rules_file
        self.rule_index = {}  # name -> [compiled indices], file order
        self.names_total = {}
        for r in rules_file.guard_rules:
            self.names_total[r.rule_name] = (
                self.names_total.get(r.rule_name, 0) + 1
            )
        self.param_rules = {
            p.rule.rule_name: p for p in rules_file.parameterized_rules
        }
        self._param_stack = set()
        self._scope = 0  # 0 = rule root (document root selection)
        self._scope_counter = 0
        self.needs_struct_ids = False
        self.needs_unsure = False
        self.needs_str_rank = False
        self.needs_fn_origin = False
        self.struct_literals: List[PV] = []

    def _push_scope(self):
        self._scope_counter += 1
        prev, self._scope = self._scope, self._scope_counter
        return prev

    # -- query lowering ------------------------------------------------
    def lower_query(self, parts: List, block_vars: dict) -> List[Step]:
        steps: List[Step] = []
        idx = 0
        if parts and part_is_variable(parts[0]):
            var = part_variable(parts[0])

            def fn_var_steps(slot: int) -> List[Step]:
                # precomputed function variable: select its encoded
                # result roots. Root-bound like every root-basis let —
                # inside a value scope the owning clause broadcasts.
                if self._scope != 0:
                    raise CrossScopeRootVar(var)
                from .fnvars import fn_key_id

                steps.append(StepFnVar(key_id=fn_key_id(slot)))
                j = 1
                if j < len(parts) and isinstance(parts[j], QAllIndices):
                    j += 1
                for i in range(j, len(parts)):
                    nxt = parts[i + 1] if i + 1 < len(parts) else None
                    prev = "varhead" if i == j else _prev_class(parts, i)
                    step = self.lower_part(parts[i], block_vars, prev, nxt)
                    if step is not None:
                        steps.append(step)
                return steps

            if var in block_vars:
                v, tok = block_vars[var]
                if isinstance(v, FunctionExpr):
                    # the binding OBJECT disambiguates same-named lets
                    # bound in several root-basis when blocks (the
                    # block_vars merge already resolved shadowing)
                    if tok == 0 and id(v) in self.var_functions:
                        return fn_var_steps(self.var_functions[id(v)])
                    raise Unlowerable(
                        f"function variable {var} outside precompute"
                    )
                if isinstance(v, PV) and tok == 0:
                    # rule-body literal let / literal call argument as
                    # query head: its value is a synthetic subtree
                    slot = self.fn_layout.lit_slots.get(
                        (self._cur_rule_idx, var)
                    )
                    if slot is None:
                        slot = self.fn_layout.pv_slots.get(id(v))
                    if slot is not None:
                        return fn_var_steps(slot)
            elif (
                var in self.var_file_fns
                and id(self.var_file_fns[var]) in self.var_functions
            ):
                return fn_var_steps(
                    self.var_functions[id(self.var_file_fns[var])]
                )
            elif var in self.var_queries:
                v, tok = self.var_queries[var], 0
            elif var in self.var_literals:
                if (-1, var) in self.fn_layout.lit_slots:
                    return fn_var_steps(self.fn_layout.lit_slots[(-1, var)])
                raise Unlowerable(f"literal variable {var} used as query head")
            else:
                raise Unlowerable(f"unknown variable {var}")
            if tok != self._scope:
                if tok == 0:
                    # root-bound variable inside a value scope: the
                    # owning clause may re-lower from the root basis
                    raise CrossScopeRootVar(var)
                raise Unlowerable(f"variable {var} crosses value scopes")
            if isinstance(v, _PreloweredQuery):
                match_all = v.match_all
                if match_all:
                    inner = list(v.steps)
                else:
                    # about to mark drop_unres: copy the mutated steps
                    inner = [
                        copy.copy(s) if isinstance(s, StepKey) else s
                        for s in v.steps
                    ]
            elif isinstance(v, AccessQuery):
                inner = self.lower_query(v.query, block_vars)
                match_all = v.match_all
            else:
                raise Unlowerable(f"variable {var} is not a plain query")
            if not match_all:
                for s in inner:
                    if isinstance(s, StepKey):
                        s.drop_unres = True
            steps.extend(inner)
            idx = 1
            # skip the implicit [*] the parser inserted after the variable
            # (the oracle skips it identically, scopes.py:399-400 /
            # eval_context.rs:348-385 — even an EXPLICIT `%var[*]` is
            # consumed there, so `%var[*][f]` == `%var[f]`)
            if idx < len(parts) and isinstance(parts[idx], QAllIndices):
                idx += 1
        spliced_at = idx if idx > 0 else None
        for i in range(idx, len(parts)):
            nxt = parts[i + 1] if i + 1 < len(parts) else None
            # the first part after a variable splice sees the var's
            # resolved values each wrapped in its own ValueScope, not
            # the [*] accumulate path — filters behave differently there
            prev = "varhead" if i == spliced_at else _prev_class(parts, i)
            step = self.lower_part(parts[i], block_vars, prev, nxt)
            if step is not None:
                steps.append(step)
        return steps

    def _lower_key_interpolation(self, part, block_vars, nxt) -> Step:
        """`.%var` mid-query (scopes._retrieve_key:545-632)."""
        # following-part restrictions: QIndex picks the k-th variable
        # ENTRY (and then still walks into the value, see
        # StepKeyInterpVar.index); anything except QKey/[*]/QIndex/end
        # raises on the oracle
        interp_index = None
        if isinstance(nxt, QIndex):
            interp_index = abs(nxt.index)
        elif nxt is not None and not isinstance(nxt, (QKey, QAllIndices)):
            raise Unlowerable("unsupported part after key interpolation")
        var = part_variable(part)

        def lit_step(lit: PV) -> StepKeyInterpLit:
            vals = lit.val if lit.kind == 7 else [lit]  # LIST
            names = []
            for v in vals:
                if v.kind != STRING:
                    # non-string keys raise NotComparable on the oracle
                    raise Unlowerable("non-string literal key interpolation")
                names.append(v.val)
            if interp_index is not None and interp_index > 0:
                # a literal var is ONE entry in the result list
                # (the whole list literal), so any index but 0 is out
                # of bounds: every candidate map UnResolves — the
                # never-matching key id reproduces exactly that
                return StepKeyInterpLit(key_names=[None])
            return StepKeyInterpLit(key_names=names)

        def query_interp(q: AccessQuery, q_vars) -> StepKeyInterpVar:
            # the variable resolves against its BINDING scope, which for
            # file- and rule-level lets is the document root
            # (scopes._resolve_variable_in:256 uses ctx.root()); the
            # kernel runs var_steps from the root selection regardless
            # of the use site's scope, so lower them at the root basis
            self.needs_unsure = True  # non-string key values flag unsure
            prev_scope, self._scope = self._scope, 0
            try:
                inner = self.lower_query(q.query, q_vars)
            finally:
                self._scope = prev_scope
            if not q.match_all:
                # `some`-marked assignments drop UnResolved entries
                # (eval_context.rs:1117-1163)
                inner = [
                    copy.copy(s) if isinstance(s, StepKey) else s
                    for s in inner
                ]
                for s in inner:
                    if isinstance(s, StepKey):
                        s.drop_unres = True
            return StepKeyInterpVar(var_steps=inner, index=interp_index)

        def fn_interp(slot: int) -> StepKeyInterpVar:
            # function-variable interpolation (`Resources.%upper`):
            # the interp machinery resolves var_steps from the root and
            # exact-matches each resolved string — selecting the
            # precomputed result roots composes directly
            from .fnvars import fn_key_id

            self.needs_unsure = True  # non-string results flag unsure
            return StepKeyInterpVar(
                var_steps=[StepFnVar(key_id=fn_key_id(slot))],
                index=interp_index,
            )

        # innermost scope first — block lets shadow file-level lets
        # (BlockScope.resolve_variable checks its own scope first)
        if var in (block_vars or {}):
            v, tok = block_vars[var]
            if isinstance(v, PV):
                if tok != self._scope:
                    raise Unlowerable(f"variable {var} crosses value scopes")
                return lit_step(v)
            if isinstance(v, AccessQuery) and tok == 0:
                # rule-body let: binds at the root basis like file lets
                return query_interp(v, block_vars)
            if isinstance(v, FunctionExpr) and tok == 0:
                if id(v) in self.var_functions:
                    return fn_interp(self.var_functions[id(v)])
            raise Unlowerable("block-scoped query variable interpolation")
        if var in self.var_literals:
            return lit_step(self.var_literals[var])
        if (
            var in self.var_file_fns
            and id(self.var_file_fns[var]) in self.var_functions
        ):
            return fn_interp(self.var_functions[id(self.var_file_fns[var])])
        q = self.var_queries.get(var)
        if q is None or not isinstance(q, AccessQuery):
            raise Unlowerable(f"variable {var} not interpolatable")
        return query_interp(q, {})

    def lower_part(self, part, block_vars, prev="start", nxt=None) -> Optional[Step]:
        if isinstance(part, QThis):
            # identity in the query walk (scopes.py query_retrieval,
            # eval_context.rs: This continues with the current value)
            return None
        if isinstance(part, QKey):
            if part_is_variable(part):
                return self._lower_key_interpolation(part, block_vars, nxt)
            try:
                return StepIndex(abs(int(part.name)))
            except ValueError:
                pass
            # the key string + its case-converted aliases, deduped by
            # VALUE — corpus-independent (ids bind at batch time via
            # the lits array; absent strings bind to the never-matching
            # id, reproducing the old absent-alias pruning exactly)
            names = [part.name]
            for conv in CONVERTERS:
                alias = conv(part.name)
                if alias not in names:
                    names.append(alias)
            return StepKey(key_names=names)
        if isinstance(part, QAllValues):
            if part.name is not None and part.name in self.referenced_vars:
                raise Unlowerable("variable capture in projection")
            # an unreferenced capture name is unobservable — the
            # marker lowers as the unnamed projection
            return StepAllValues()
        if isinstance(part, QAllIndices):
            if part.name is not None and part.name in self.referenced_vars:
                raise Unlowerable("variable capture in projection")
            return StepAllIndices()
        if isinstance(part, QIndex):
            return StepIndex(abs(part.index))
        if isinstance(part, QFilter):
            if part.name is not None and part.name in self.referenced_vars:
                raise Unlowerable("variable capture in filter")
            if prev == "other":
                # oracle raises InternalError for maps after such parts
                raise Unlowerable("filter after index/filter/this part")
            if prev == "allindices":
                # `[*]` does not re-scope list elements, so map
                # candidates evaluate the filter against the outer
                # scope (eval_context.rs:142-178 + :725-734) — host only
                raise Unlowerable("filter after [*] keeps the outer scope")
            # filter clauses evaluate each candidate as a value scope
            prev_scope = self._push_scope()
            try:
                conjunctions = [
                    [self.lower_guard_clause(c, block_vars) for c in disj]
                    for disj in part.conjunctions
                ]
            finally:
                self._scope = prev_scope
            return StepFilter(
                conjunctions=conjunctions,
                expand_maps=prev in ("start", "key"),
                scalar_self=prev == "varhead",
            )
        if isinstance(part, QMapKeyFilter):
            if part.name is not None and part.name in self.referenced_vars:
                raise Unlowerable("variable capture in keys filter")
            op = part.clause.comparator
            if op not in (CmpOperator.Eq, CmpOperator.In):
                # the grammar only produces ==/!=/in/not-in after
                # `keys` (parser.rs:810-835); anything else could only
                # arrive from a hand-built AST
                raise Unlowerable(f"keys filter with {op} comparator")
            rhs = self.lower_rhs(part.clause.compare_with, block_vars, op=op)
            ok_kinds = ("str", "regex")
            if rhs.kind == "list":
                if any(it.kind not in ok_kinds for it in rhs.items):
                    raise Unlowerable("keys filter list with non-string items")
                if op == CmpOperator.Eq:
                    # scalar key == list literal has len-1-unwrap /
                    # NotComparable semantics (operators.rs:512-528)
                    raise Unlowerable("keys == list literal")
            elif rhs.kind not in ok_kinds:
                raise Unlowerable(f"keys filter rhs kind {rhs.kind}")
            return StepKeysMatch(
                rhs=rhs, op=op, op_not=part.clause.comparator_inverse
            )
        raise Unlowerable(f"query part {part!r}")

    # -- rhs lowering --------------------------------------------------
    def lower_rhs(self, cw, block_vars=None, op=None) -> RhsSpec:
        if isinstance(cw, AccessQuery):
            # `x IN %allowed` where %allowed is a literal assignment:
            # resolve at compile time (a Literal RHS in the reference,
            # eval_context.rs:1117-1119)
            parts = cw.query
            if parts and part_is_variable(parts[0]):
                var = part_variable(parts[0])
                lit = None
                if block_vars and var in block_vars:
                    bound = block_vars[var][0]
                    if isinstance(bound, PV):
                        lit = bound
                elif var in self.var_literals:
                    lit = self.var_literals[var]
                rest = parts[1:]
                if rest and isinstance(rest[0], QAllIndices):
                    rest = rest[1:]
                if lit is not None and not rest:
                    return self.lower_rhs(lit, op=op)
            raise Unlowerable("non-literal RHS (query or function call)")
        if not isinstance(cw, PV):
            raise Unlowerable("non-literal RHS (query or function call)")
        k = cw.kind
        if k == STRING:
            lit = cw.val
            ordering = op in (
                CmpOperator.Gt,
                CmpOperator.Ge,
                CmpOperator.Lt,
                CmpOperator.Le,
            )
            return RhsSpec(
                kind="str",
                str_val=lit,
                bits=self.interner.substring_bits(-1, lit),
                bits_spec=("substr", lit),
                # ordering tables only when the clause actually orders
                lt_bits=np.array(
                    [s < lit for s in self.interner.strings], dtype=bool
                )
                if ordering
                else None,
                le_bits=np.array(
                    [s <= lit for s in self.interner.strings], dtype=bool
                )
                if ordering
                else None,
                lt_spec=("lt", lit) if ordering else None,
                le_spec=("le", lit) if ordering else None,
            )
        if k == REGEX:
            return RhsSpec(
                kind="regex",
                bits=self.interner.regex_match_bits(cw.val),
                bits_spec=("regex", cw.val),
            )
        if k == CHAR:
            # docs never contain CHAR nodes (loader emits STRING), and
            # STRING vs CHAR is NotComparable (path_value.rs:1048-1070)
            return RhsSpec(kind="never")
        if k == INT or k == FLOAT:
            key = num_key(k, cw.val)
            if key is None:
                # NaN / beyond-i64 literal: no exact device encoding
                raise Unlowerable("numeric literal without exact encoding")
            return RhsSpec(kind="num", num_key=key, num_kind=k)
        if k == BOOL:
            return RhsSpec(
                kind="bool", num_key=num_key(INT, 1 if cw.val else 0)
            )
        if k == NULL:
            return RhsSpec(kind="null")
        if k in (RANGE_INT, RANGE_FLOAT, RANGE_CHAR):
            if k == RANGE_CHAR:
                # only CHAR values fall inside a char range and docs
                # never contain CHAR nodes: never comparable -> FAIL
                return RhsSpec(kind="never")
            r = cw.val
            nk = INT if k == RANGE_INT else FLOAT
            lo_key = num_key(nk, r.lower)
            hi_key = num_key(nk, r.upper)
            if lo_key is None or hi_key is None:
                raise Unlowerable("range bound without exact encoding")
            return RhsSpec(
                kind="range",
                range_lo_key=lo_key,
                range_hi_key=hi_key,
                range_incl=r.inclusive,
                range_kind=k,
                num_kind=nk,
            )
        if k == 7:  # LIST
            items = []
            for e in cw.val:
                if e.kind in (7, 8):  # nested LIST / MAP element
                    items.append(self._struct_literal(e))
                else:
                    # pass the clause op through: ordering clauses
                    # compare each flattened item with the ordering op
                    # (CommonOperator), so string items need lt/le
                    # tables; Eq/In items compare by equality
                    items.append(
                        self.lower_rhs(
                            e,
                            op=op
                            if op
                            in (
                                CmpOperator.Gt,
                                CmpOperator.Ge,
                                CmpOperator.Lt,
                                CmpOperator.Le,
                            )
                            else None,
                        )
                    )
            for it in items:
                if it.kind not in (
                    "str", "regex", "num", "bool", "null", "range", "never",
                    "struct",
                ):
                    raise Unlowerable("unsupported RHS list literal item")
            return RhsSpec(kind="list", items=items)
        if k == 8:  # MAP literal
            return self._struct_literal(cw)
        raise Unlowerable(f"RHS literal kind {cw.type_info()}")

    def _struct_literal(self, pv: PV) -> RhsSpec:
        """Map / nested-list literal -> two device encodings, chosen by
        the kernel per use: canonical-struct-id equality (loose_eq, for
        IN membership) and the exact compare_eq tri-state columns
        (encoder.struct_literal_tri — covers regex matching inside maps
        (path_value.rs:1083-1105), range membership, and NotComparable
        propagation with the reference's per-entry short-circuit)."""
        is_list = pv.kind == 7
        for i, existing in enumerate(self.struct_literals):
            if existing is pv:
                return RhsSpec(kind="struct", struct_slot=i, struct_is_list=is_list)
        self.struct_literals.append(pv)
        return RhsSpec(
            kind="struct",
            struct_slot=len(self.struct_literals) - 1,
            struct_is_list=is_list,
        )

    def _lower_query_from_root(self, parts, block_vars) -> List[Step]:
        """Re-lower a query whose head is a root-bound variable from
        the root basis (CrossScopeRootVar recovery)."""
        prev_scope, self._scope = self._scope, 0
        try:
            return self.lower_query(parts, block_vars)
        finally:
            self._scope = prev_scope

    # -- clause lowering ----------------------------------------------
    def lower_guard_clause_as_cclause(self, clause, block_vars) -> "CClause":
        if not isinstance(clause, GuardAccessClause):
            raise Unlowerable(f"filter clause {type(clause).__name__}")
        return self.lower_access_clause(clause, block_vars)

    def _count_arg_query(self, parts, block_vars) -> Optional[AccessQuery]:
        """The argument query when `parts` is exactly a count-variable
        reference (`%n` / `%n[*]`), else None. Only root-basis bindings
        qualify (file lets always; rule-body lets bind at scope 0)."""
        if not parts or not part_is_variable(parts[0]):
            return None
        rest = parts[1:]
        if rest and isinstance(rest[0], QAllIndices):
            rest = rest[1:]
        if rest:
            # walking INTO the synthetic int (e.g. `%n.foo`) UnResolves
            # on the oracle — host fallback, it is never meaningful
            return None
        var = part_variable(parts[0])
        if block_vars and var in block_vars:
            v, tok = block_vars[var]
            if (
                isinstance(v, FunctionExpr)
                and v.name == "count"
                and len(v.parameters) == 1
                and isinstance(v.parameters[0], AccessQuery)
                and tok == 0
            ):
                return v.parameters[0]
            return None
        return self.var_counts.get(var)

    def _lower_count_clause(
        self, gac: GuardAccessClause, arg_query: AccessQuery, block_vars
    ) -> CCountClause:
        """`%n <op> rhs` where n is a count() let: one synthetic INT
        value, always resolved (fn_count never UnResolves), compared
        with the reference's exact comparison table
        (path_value.rs:1047-1191 compare_*, operators.rs EqOperation /
        InOperation / CommonOperator)."""
        ac = gac.access_clause
        prev_scope, self._scope = self._scope, 0
        try:
            steps = self.lower_query(arg_query.query, block_vars)
        finally:
            self._scope = prev_scope
        op, op_not = ac.comparator, ac.comparator_inverse

        if op.is_unary():
            # outcomes depend only on the value's kind (a single
            # resolved INT): compile-time constants (eval.rs:174-405)
            if op == CmpOperator.Empty:
                # `%n` alone is empty-on-expr (eval.rs:193-196): tests
                # zero RESOLVED values — count always yields one
                base = False
            elif op == CmpOperator.Exists:
                base = True
            elif op == CmpOperator.IsInt:
                base = True
            elif op in (
                CmpOperator.IsString,
                CmpOperator.IsList,
                CmpOperator.IsMap,
                CmpOperator.IsFloat,
                CmpOperator.IsBool,
                CmpOperator.IsNull,
            ):
                base = False
            else:
                raise Unlowerable(f"count variable with {op}")
            outcome = base
            if op_not:
                outcome = not outcome
            if gac.negation:
                outcome = not outcome
            return CCountClause(
                steps=steps, static_status=PASS if outcome else FAIL
            )

        cw = ac.compare_with
        # literal-variable RHS resolves at compile time like lower_rhs
        if isinstance(cw, AccessQuery):
            cparts = cw.query
            if cparts and part_is_variable(cparts[0]):
                cvar = part_variable(cparts[0])
                lit = None
                if block_vars and cvar in block_vars:
                    bound = block_vars[cvar][0]
                    if isinstance(bound, PV):
                        lit = bound
                elif cvar in self.var_literals:
                    lit = self.var_literals[cvar]
                crest = cparts[1:]
                if crest and isinstance(crest[0], QAllIndices):
                    crest = crest[1:]
                if lit is not None and not crest:
                    cw = lit
        if not isinstance(cw, PV):
            raise Unlowerable("count compare against non-literal RHS")

        i32 = lambda v: int(np.clip(int(v), -(2**31), 2**31 - 1))

        def int_range(r):
            lo, hi = int(r.lower), int(r.upper)
            if abs(lo) >= 2**31 or abs(hi) >= 2**31:
                raise Unlowerable("count range bound beyond i32")
            return lo, hi

        if op in (CmpOperator.Eq, CmpOperator.In) and cw.kind == RANGE_INT:
            # compare_eq(INT, RANGE_INT) is range membership — a
            # COMPARABLE pair, so `not` is a pure inversion
            # (path_value.rs compare_eq WithinRange arm)
            lo, hi = int_range(cw.val)
            cmp = ("range", lo, hi, cw.val.inclusive, op_not)
        elif op in (
            CmpOperator.Eq,
            CmpOperator.Gt,
            CmpOperator.Ge,
            CmpOperator.Lt,
            CmpOperator.Le,
        ):
            if cw.kind == INT:
                # counts are bounded by the node bucket (< 2^31), so a
                # clamped literal preserves every comparison outcome
                cmp = ("int", i32(cw.val), op, op_not)
            else:
                # INT vs any other kind (incl. ordering vs ranges):
                # NotComparable -> FAIL, surviving the `not` inversion
                # (operators.rs:195-206)
                cmp = ("never",)
        elif op == CmpOperator.In:
            if cw.kind == 7:  # LIST: membership via loose_eq
                only_plain = all(
                    e.kind in (INT, FLOAT, STRING, BOOL, NULL)
                    for e in cw.val
                )
                if not only_plain:
                    # range/regex/nested items have their own loose_eq
                    # arms — keep the host oracle authoritative there
                    raise Unlowerable("count IN list with non-scalar items")
                # only INT items can ever loose_eq the count
                ints = [
                    i32(e.val)
                    for e in cw.val
                    if e.kind == INT and abs(int(e.val)) < 2**31
                ]
                cmp = ("in", ints, op_not)
            elif cw.kind == INT:
                # scalar RHS goes through compare_eq: INT vs INT only
                cmp = ("int", i32(cw.val), CmpOperator.Eq, op_not)
            else:
                cmp = ("never",)
        else:
            raise Unlowerable(f"count variable with {op}")
        return CCountClause(steps=steps, cmp=cmp)

    def lower_access_clause(self, gac: GuardAccessClause, block_vars) -> CClause:
        ac = gac.access_clause
        parts = ac.query.query
        count_arg = self._count_arg_query(parts, block_vars)
        if count_arg is not None:
            return self._lower_count_clause(gac, count_arg, block_vars)
        # the `empty`-on-expression special case (eval.rs:193-196)
        last = parts[-1]
        empty_on_expr = isinstance(last, (QFilter, QMapKeyFilter)) or (
            part_is_variable(last) and len(parts) == 1
        )
        eval_from_root = False
        try:
            steps = self.lower_query(parts, block_vars)
        except CrossScopeRootVar:
            # re-lower from the root basis; the clause status is
            # origin-independent and broadcasts (kernels.eval_clause)
            steps = self._lower_query_from_root(parts, block_vars)
            eval_from_root = True
        if ac.comparator == CmpOperator.Empty and not empty_on_expr:
            # elementwise EMPTY raises on int/float/null values — the
            # kernel flags such documents unsure (oracle reruns them)
            self.needs_unsure = True
        rhs = None
        rhs_query_steps = None
        rhs_query_from_root = False
        if not ac.comparator.is_unary():
            try:
                rhs = self.lower_rhs(ac.compare_with, block_vars, op=ac.comparator)
                if rhs.kind == "struct" and ac.comparator not in (
                    CmpOperator.Eq, CmpOperator.In,
                ):
                    # ordering vs map literal: NotComparable -> FAIL
                    # both ways (compare_values raises on MAP kinds)
                    rhs = RhsSpec(kind="never")
            except Unlowerable:
                # non-literal RHS: a query (resolved per document in
                # the same scope as the LHS) or an inline function
                # call (resolved in the clause's scope,
                # eval_guard_access_clause -> resolve_function)
                if isinstance(ac.compare_with, FunctionExpr):
                    from .fnvars import fn_key_id

                    slot = self.fn_layout.expr_slots.get(
                        id(ac.compare_with)
                    )
                    if slot is None:
                        # origin-dependent inline call: per-origin
                        # precomputed results ('pexpr',
                        # fnvars._pexpr_scopes) joined per origin by
                        # the non-shared query-RHS arms
                        pslot = self.fn_layout.pexpr_slots.get(
                            id(ac.compare_with)
                        )
                        if pslot is None:
                            raise
                        if eval_from_root:
                            # LHS broadcasts from the root while the
                            # RHS differs per origin — labels cannot
                            # join (same refusal as the query analogue)
                            raise Unlowerable(
                                "root-based LHS with per-origin fn RHS"
                            )
                        self.needs_fn_origin = True
                        if ac.comparator in (
                            CmpOperator.Eq, CmpOperator.In,
                        ):
                            self.needs_struct_ids = True
                        else:
                            self.needs_str_rank = True
                        return CClause(
                            steps=steps,
                            op=ac.comparator,
                            op_not=ac.comparator_inverse,
                            negation=gac.negation,
                            match_all=ac.query.match_all,
                            rhs=None,
                            empty_on_expr=empty_on_expr,
                            rhs_query_steps=[
                                StepFnVar(
                                    key_id=fn_key_id(pslot),
                                    per_origin=True,
                                )
                            ],
                            eval_from_root=False,
                            rhs_query_from_root=False,
                        )
                    rhs_query_steps = [StepFnVar(key_id=fn_key_id(slot))]
                    rhs_root_basis = True
                    if not eval_from_root:
                        rhs_query_from_root = True
                    if ac.comparator in (CmpOperator.Eq, CmpOperator.In):
                        self.needs_struct_ids = True
                    else:
                        self.needs_str_rank = True
                    return CClause(
                        steps=steps,
                        op=ac.comparator,
                        op_not=ac.comparator_inverse,
                        negation=gac.negation,
                        match_all=ac.query.match_all,
                        rhs=None,
                        empty_on_expr=empty_on_expr,
                        rhs_query_steps=rhs_query_steps,
                        eval_from_root=eval_from_root,
                        rhs_query_from_root=rhs_query_from_root,
                    )
                if not isinstance(ac.compare_with, AccessQuery):
                    raise
                rhs_root_basis = False
                try:
                    rhs_query_steps = self.lower_query(
                        ac.compare_with.query, block_vars
                    )
                except CrossScopeRootVar:
                    rhs_query_steps = self._lower_query_from_root(
                        ac.compare_with.query, block_vars
                    )
                    rhs_root_basis = True
                    if not eval_from_root:
                        # per-origin LHS vs one shared root-resolved
                        # RHS set (kernels handle Eq — incl. the
                        # negated 4-way diff/reverse-diff complement —
                        # via per-origin reverse membership, In and
                        # orderings via the shared set)
                        rhs_query_from_root = True
                    # else: the whole clause evaluates once from the
                    # root selection — both sides resolve there with
                    # the same origin label, so the ordinary per-origin
                    # machinery is already exact
                except Unlowerable:
                    # a variable bound in a NON-root value scope used
                    # across scopes: its values precompute per
                    # use-site candidate (fnvars 'pvar' slots) and
                    # join per origin label, exactly like per-origin
                    # inline calls
                    pvslot = self.fn_layout.pvar_slots.get(
                        id(ac.compare_with)
                    )
                    if pvslot is None or eval_from_root:
                        raise
                    from .fnvars import fn_key_id

                    self.needs_fn_origin = True
                    rhs_query_steps = [
                        StepFnVar(
                            key_id=fn_key_id(pvslot), per_origin=True
                        )
                    ]
                if ac.comparator in (CmpOperator.Eq, CmpOperator.In):
                    self.needs_struct_ids = True
                else:
                    # ordering: cartesian pair comparison needs the
                    # string-rank column (operators.rs:146-176)
                    self.needs_str_rank = True
                if eval_from_root and not rhs_root_basis:
                    # the RHS resolves per origin inside the value
                    # scope while the LHS broadcasts from the root —
                    # genuinely origin-dependent, cannot lower
                    raise Unlowerable(
                        "root-based LHS with per-origin query RHS"
                    )
        return CClause(
            steps=steps,
            op=ac.comparator,
            op_not=ac.comparator_inverse,
            negation=gac.negation,
            match_all=ac.query.match_all,
            rhs=rhs,
            empty_on_expr=empty_on_expr,
            rhs_query_steps=rhs_query_steps,
            eval_from_root=eval_from_root,
            rhs_query_from_root=rhs_query_from_root,
        )

    def lower_guard_clause(self, clause, block_vars) -> CNode:
        if isinstance(clause, GuardAccessClause):
            return self.lower_access_clause(clause, block_vars)
        if isinstance(clause, BlockGuardClause):
            query_steps = self.lower_query(clause.query.query, block_vars)
            # block bodies evaluate each query leaf as a value scope
            prev_scope = self._push_scope()
            try:
                inner_vars = self._merge_block_vars(block_vars, clause.block)
                inner = [
                    [self.lower_guard_clause(c, inner_vars) for c in disj]
                    for disj in clause.block.conjunctions
                ]
            finally:
                self._scope = prev_scope
            return CBlockClause(
                query_steps=query_steps,
                match_all=clause.query.match_all,
                not_empty=clause.not_empty,
                inner=inner,
            )
        if isinstance(clause, WhenBlockClause):
            # when-blocks keep the enclosing selection (no value scope)
            inner_vars = self._merge_block_vars(block_vars, clause.block)
            return CWhenBlock(
                conditions=[
                    [self.lower_guard_clause(c, block_vars) for c in disj]
                    for disj in clause.conditions
                ],
                inner=[
                    [self.lower_guard_clause(c, inner_vars) for c in disj]
                    for disj in clause.block.conjunctions
                ],
            )
        if isinstance(clause, GuardNamedRuleClause):
            # every same-named rule must already be compiled (the
            # first-non-SKIP scan needs all of them, and kernel rule
            # statuses are produced in file order)
            targets = self.rule_index.get(clause.dependent_rule)
            if not targets or len(targets) != self.names_total.get(
                clause.dependent_rule, 0
            ):
                raise Unlowerable(f"named rule {clause.dependent_rule} not lowerable")
            return CNamedRef(rule_indices=list(targets), negation=clause.negation)
        if isinstance(clause, ParameterizedNamedRuleClause):
            return self.lower_parameterized_call(clause, block_vars)
        if isinstance(clause, TypeBlock):
            query_steps = self.lower_query(clause.query, block_vars)
            prev_scope = self._push_scope()
            try:
                inner_vars = self._merge_block_vars(block_vars, clause.block)
                inner = [
                    [self.lower_guard_clause(c, inner_vars) for c in disj]
                    for disj in clause.block.conjunctions
                ]
            finally:
                self._scope = prev_scope
            body = CBlockClause(
                query_steps=query_steps,
                match_all=True,
                not_empty=False,
                inner=inner,
            )
            if clause.conditions is None:
                return body
            # conditions gate at the enclosing scope; != PASS -> SKIP
            # (eval.rs:1649-1698, evaluator.eval_type_block_clause)
            return CWhenBlock(
                conditions=[
                    [self.lower_guard_clause(c, block_vars) for c in disj]
                    for disj in clause.conditions
                ],
                inner=[[body]],
            )
        raise Unlowerable(f"clause {type(clause).__name__}")

    def lower_parameterized_call(
        self, clause: ParameterizedNamedRuleClause, block_vars
    ) -> CNode:
        """Inline expansion of `rule_name(arg, ...)` (eval.rs:1504-1618):
        arguments resolve in the caller's scope, then the callee body
        evaluates with them overlaid (falling back to the caller's scope
        for free variables, _ResolvedParameterContext semantics)."""
        name = clause.named_rule.dependent_rule
        prule = self.param_rules.get(name)
        if prule is None:
            raise Unlowerable(f"unknown parameterized rule {name}")
        if name in self._param_stack:
            raise Unlowerable(f"recursive parameterized rule {name}")
        if len(prule.parameter_names) != len(clause.parameters):
            # arity mismatch raises on the oracle (exit-code error path)
            raise Unlowerable(f"arity mismatch calling {name}")
        if clause.named_rule.negation:
            raise Unlowerable(f"negated parameterized call {name}")
        callee_vars = dict(block_vars)
        for pname, arg in zip(prule.parameter_names, clause.parameters):
            if isinstance(arg, PV):
                callee_vars[pname] = (arg, self._scope)
            elif isinstance(arg, AccessQuery):
                callee_vars[pname] = (
                    _PreloweredQuery(
                        steps=self.lower_query(arg.query, block_vars),
                        match_all=arg.match_all,
                    ),
                    self._scope,
                )
            elif isinstance(arg, FunctionExpr):
                # function-call argument: resolved in the CALLER's
                # scope (eval.rs:1574-1599) — precomputed like an
                # inline RHS expression when a slot exists. Root-scope
                # call sites only: StepFnVar selections carry origin
                # label 1, which is only the caller's origin there.
                slot = self.fn_layout.expr_slots.get(id(arg))
                if slot is None or self._scope != 0:
                    raise Unlowerable("function-call argument in rule call")
                from .fnvars import fn_key_id

                callee_vars[pname] = (
                    _PreloweredQuery(
                        steps=[StepFnVar(key_id=fn_key_id(slot))],
                        match_all=True,
                    ),
                    self._scope,
                )
            else:
                raise Unlowerable("function-call argument in rule call")
        rule = prule.rule
        callee_vars = self._merge_block_vars(callee_vars, rule.block)
        self._param_stack.add(name)
        try:
            inner = [
                [self.lower_guard_clause(c, callee_vars) for c in disj]
                for disj in rule.block.conjunctions
            ]
            conds = None
            if rule.conditions is not None:
                conds = [
                    [self.lower_guard_clause(c, callee_vars) for c in disj]
                    for disj in rule.conditions
                ]
        finally:
            self._param_stack.discard(name)
        return CWhenBlock(conditions=conds, inner=inner)

    def _merge_block_vars(self, outer: dict, block: Block) -> dict:
        """Bindings carry the scope token they were made under."""
        merged = dict(outer)
        for let in block.assignments:
            if isinstance(let.value, (AccessQuery, PV, FunctionExpr)):
                merged[let.var] = (let.value, self._scope)
            else:
                merged[let.var] = (None, self._scope)  # unknown: bail if used
        return merged

    def lower_rule(self, rule: Rule) -> CRule:
        block_vars = self._merge_block_vars({}, rule.block)
        conditions = None
        if rule.conditions is not None:
            conditions = [
                [self.lower_guard_clause(c, block_vars) for c in disj]
                for disj in rule.conditions
            ]
        conjunctions = [
            [self.lower_guard_clause(c, block_vars) for c in disj]
            for disj in rule.block.conjunctions
        ]
        return CRule(name=rule.rule_name, conditions=conditions, conjunctions=conjunctions)


def compile_rules_file(rules_file: RulesFile, interner: Interner) -> CompiledRules:
    """Lower every rule; rules that refuse lowering are returned in
    `host_rules` for CPU-oracle evaluation (the fail-rerun design)."""
    lowering = _RuleLowering(rules_file, interner)
    compiled: List[CRule] = []
    host: List[Rule] = []
    needs_struct = False
    needs_unsure = False
    needs_rank = False
    needs_fn_origin = False
    for rule_idx, rule in enumerate(rules_file.guard_rules):
        lowering.needs_struct_ids = False
        lowering.needs_unsure = False
        lowering.needs_str_rank = False
        lowering.needs_fn_origin = False
        lowering._cur_rule_idx = rule_idx
        mark = len(lowering.struct_literals)
        try:
            cr = lowering.lower_rule(rule)
        except Unlowerable:
            del lowering.struct_literals[mark:]  # drop orphan slots
            host.append(rule)
            continue
        lowering.rule_index.setdefault(rule.rule_name, []).append(
            len(compiled)
        )
        compiled.append(cr)
        needs_struct = needs_struct or lowering.needs_struct_ids
        needs_unsure = needs_unsure or lowering.needs_unsure
        needs_rank = needs_rank or lowering.needs_str_rank
        needs_fn_origin = needs_fn_origin or lowering.needs_fn_origin
    str_empty_bits = np.array(
        [len(s) == 0 for s in interner.strings], dtype=bool
    )
    out = CompiledRules(
        rules=compiled,
        host_rules=host,
        interner=interner,
        str_empty_bits=str_empty_bits,
        needs_struct_ids=needs_struct,
        needs_unsure=needs_unsure or needs_struct,
        struct_literals=lowering.struct_literals,
        needs_str_rank=needs_rank,
        needs_fn_origin=needs_fn_origin,
    )
    _fold_key_chains(out)
    if _assign_bit_slots(out):
        from .fnvars import precomputable_fn_vars

        out.fn_vars = precomputable_fn_vars(rules_file)
    return out


def trace_signature(compiled: CompiledRules) -> str:
    """Canonical string of everything the kernel TRACE depends on — the
    rule program structure, slot assignments, operators and the
    corpus-independent baked scalars (numeric keys, indices, counts) —
    and nothing bound at runtime (interned ids, bit-table contents,
    document columns). Two CompiledRules with equal signatures trace to
    identical jaxprs at equal bucket shapes, so jitted evaluators key on
    (signature, mesh, shape) for cross-invocation executable reuse
    (parallel/mesh.py _shared_evaluator_fns)."""
    out: List[str] = []
    add = out.append

    def rhs(r: Optional[RhsSpec]) -> None:
        if r is None:
            add("~")
            return
        add(
            f"R({r.kind},{r.str_slot},{r.bits_slot},{r.lt_slot},"
            f"{r.le_slot},{r.num_key},{r.num_kind},{r.range_lo_key},"
            f"{r.range_hi_key},{r.range_incl},{r.range_kind},"
            f"{r.struct_slot},{int(r.struct_is_list)})"
        )
        if r.items is not None:
            add("[")
            for it in r.items:
                rhs(it)
            add("]")

    def steps(ss) -> None:
        add("{")
        for s in ss:
            if isinstance(s, StepKeyChain):
                add(f"C{s.chain_slot}")
                steps(s.steps)
            elif isinstance(s, StepKey):
                add(f"K{tuple(s.lit_slots)},{int(s.drop_unres)},{s.kc_slot};")
            elif isinstance(s, StepKeyInterpLit):
                add(f"L{tuple(s.lit_slots)},{tuple(s.kc_slots)};")
            elif isinstance(s, StepKeyInterpVar):
                add(f"V{s.index}")
                steps(s.var_steps)
            elif isinstance(s, StepFnVar):
                add(f"F{s.key_id},{int(s.per_origin)};")
            elif isinstance(s, StepAllValues):
                add("*;")
            elif isinstance(s, StepAllIndices):
                add("I;")
            elif isinstance(s, StepIndex):
                add(f"X{s.index},{s.kc_slot};")
            elif isinstance(s, StepFilter):
                add(f"f{int(s.expand_maps)}{int(s.scalar_self)}")
                conjs(s.conjunctions)
            elif isinstance(s, StepKeysMatch):
                add(f"M{s.op.value},{int(s.op_not)}")
                rhs(s.rhs)
        add("}")

    def node(n) -> None:
        if isinstance(n, CClause):
            add(
                f"c({n.op.value},{int(n.op_not)},{int(n.negation)},"
                f"{int(n.match_all)},{int(n.empty_on_expr)},"
                f"{int(n.eval_from_root)},{int(n.rhs_query_from_root)}"
            )
            steps(n.steps)
            rhs(n.rhs)
            if n.rhs_query_steps is not None:
                steps(n.rhs_query_steps)
        elif isinstance(n, CCountClause):
            add(f"n({n.static_status},{n.cmp}")
            steps(n.steps)
        elif isinstance(n, CBlockClause):
            add(f"b({int(n.match_all)},{int(n.not_empty)}")
            steps(n.query_steps)
            conjs(n.inner)
        elif isinstance(n, CWhenBlock):
            add("w(")
            if n.conditions is None:
                add("~")
            else:
                conjs(n.conditions)
            conjs(n.inner)
        elif isinstance(n, CNamedRef):
            add(f"r({tuple(n.rule_indices)},{int(n.negation)}")
        add(")")

    def conjs(cc) -> None:
        add("<")
        for disj in cc:
            add("|")
            for n in disj:
                node(n)
        add(">")

    for r in compiled.rules:
        add("RULE(")
        if r.conditions is None:
            add("~")
        else:
            conjs(r.conditions)
        conjs(r.conjunctions)
        add(")")
    add(
        f"|E{compiled.str_empty_slot}|S{int(compiled.needs_struct_ids)}"
        f"|U{int(compiled.needs_unsure)}|T{len(compiled.struct_literals)}"
        f"|B{len(compiled.bit_tables)}|H{len(compiled.kidc_tables)}"
        f"|N{len(compiled.chain_tables)}|L{len(compiled.lit_names)}"
        f"|K{int(compiled.needs_str_rank)}|P{int(compiled.needs_pairwise)}"
    )
    return "".join(out)


def _fold_key_chains(compiled: CompiledRules) -> None:
    """Peephole over every step list: fold maximal runs of >= 2
    StepKeys whose key-id sets are pairwise disjoint into StepKeyChain
    nodes (one device permutation per run instead of one per step —
    see StepKeyChain for the exactness argument)."""
    seen_chains: dict = {}

    def chain_slot(spec: tuple) -> int:
        if spec not in seen_chains:
            seen_chains[spec] = len(compiled.chain_tables)
            compiled.chain_tables.append(spec)
        return seen_chains[spec]

    def fold(steps: List[Step]) -> List[Step]:
        out: List[Step] = []
        run: List[StepKey] = []

        def flush():
            if len(run) >= 2:
                spec = tuple(
                    (tuple(s.key_names), s.drop_unres) for s in run
                )
                out.append(
                    StepKeyChain(steps=list(run), chain_slot=chain_slot(spec))
                )
            else:
                out.extend(run)
            run.clear()

        for s in steps:
            if isinstance(s, StepKey):
                # disjointness by key STRING (corpus-independent): a
                # shared string means a node could match two positions;
                # strings absent from a given corpus match nothing, so
                # string-disjointness implies id-disjointness
                names = set(s.key_names)
                overlapping = any(
                    names & set(prev.key_names) for prev in run
                )
                if overlapping:
                    flush()
                run.append(s)
            else:
                flush()
                if isinstance(s, StepFilter):
                    s.conjunctions = [
                        [fold_node(c) for c in disj]
                        for disj in s.conjunctions
                    ]
                elif isinstance(s, StepKeyInterpVar):
                    s.var_steps = fold(s.var_steps)
                out.append(s)
        flush()
        return out

    def fold_node(n):
        if isinstance(n, CClause):
            n.steps = fold(n.steps)
            if n.rhs_query_steps is not None:
                n.rhs_query_steps = fold(n.rhs_query_steps)
        elif isinstance(n, CCountClause):
            n.steps = fold(n.steps)
        elif isinstance(n, CBlockClause):
            n.query_steps = fold(n.query_steps)
            n.inner = [[fold_node(c) for c in disj] for disj in n.inner]
        elif isinstance(n, CWhenBlock):
            if n.conditions is not None:
                n.conditions = [
                    [fold_node(c) for c in disj] for disj in n.conditions
                ]
            n.inner = [[fold_node(c) for c in disj] for disj in n.inner]
        return n

    for r in compiled.rules:
        if r.conditions is not None:
            r.conditions = [
                [fold_node(n) for n in disj] for disj in r.conditions
            ]
        r.conjunctions = [
            [fold_node(n) for n in disj] for disj in r.conjunctions
        ]


def _assign_bit_slots(compiled: CompiledRules) -> None:
    """Walk the compiled tree and give a slot in `compiled.bit_tables`
    to every bit table the kernel will actually read (each slot becomes
    a host-materialized (D, N) column per batch, so unused ones cost
    real transfer/pad work). Tables inside StepKeysMatch apply to
    map-key ids ("key" target); everywhere else to scalar ids. Readers
    (kernels.py): regex bits under Eq/In; str substring bits only under
    In; lt/le ordering tables whenever present (they are only built for
    ordering clauses); the empty-string table only for elementwise
    Empty clauses."""
    seen = {}
    seen_kidc = {}
    seen_lits = {}
    uses_empty = [False]
    uses_fn = [False]
    uses_interp = [False]

    def kidc_slot(spec: tuple) -> int:
        if spec not in seen_kidc:
            seen_kidc[spec] = len(compiled.kidc_tables)
            compiled.kidc_tables.append(spec)
        return seen_kidc[spec]

    def slot(arr: np.ndarray, target: str, spec: tuple) -> int:
        k = (id(arr), target)
        if k not in seen:
            seen[k] = len(compiled.bit_tables)
            compiled.bit_tables.append((arr, target))
            compiled.bit_specs.append(spec)
        return seen[k]

    def lit_slot(name: Optional[str]) -> int:
        # one runtime lits entry per unique literal string (None = the
        # never-matching id); slot order is walk order — structural,
        # corpus-independent
        if name not in seen_lits:
            seen_lits[name] = len(compiled.lit_names)
            compiled.lit_names.append(name)
        return seen_lits[name]

    def do_rhs(rhs: Optional[RhsSpec], target: str, op) -> None:
        if rhs is None:
            return
        if rhs.kind == "str":
            rhs.str_slot = lit_slot(rhs.str_val)
        reads_bits = (
            rhs.kind == "regex" and op in (CmpOperator.Eq, CmpOperator.In)
        ) or (rhs.kind == "str" and op == CmpOperator.In)
        if reads_bits and rhs.bits is not None:
            rhs.bits_slot = slot(rhs.bits, target, rhs.bits_spec)
        if rhs.lt_bits is not None:
            rhs.lt_slot = slot(rhs.lt_bits, target, rhs.lt_spec)
        if rhs.le_bits is not None:
            rhs.le_slot = slot(rhs.le_bits, target, rhs.le_spec)
        if rhs.items:
            ordering = op in (
                CmpOperator.Gt, CmpOperator.Ge, CmpOperator.Lt, CmpOperator.Le,
            )
            for it in rhs.items:
                # Eq/In list items compare by Eq semantics (membership
                # / elementwise list-literal compare); ordering clauses
                # compare each flattened item with the ordering op
                do_rhs(it, target, op if ordering else CmpOperator.Eq)

    def do_steps(steps: List[Step]) -> None:
        for s in steps:
            if isinstance(s, StepKeysMatch):
                do_rhs(s.rhs, "key", s.op)
            elif isinstance(s, StepFilter):
                do_conjs(s.conjunctions)
            elif isinstance(s, StepKeyInterpVar):
                uses_interp[0] = True
                do_steps(s.var_steps)
            elif isinstance(s, StepFnVar):
                uses_fn[0] = True
            elif isinstance(s, StepKey):
                s.lit_slots = [lit_slot(n) for n in s.key_names]
                if not s.drop_unres:
                    s.kc_slot = kidc_slot(("k",) + tuple(s.key_names))
            elif isinstance(s, StepKeyChain):
                # only the FIRST step's has-child column is read (the
                # inline position-0 miss); deeper misses live in the
                # chain's static chM column
                first = s.steps[0]
                first.lit_slots = [lit_slot(n) for n in first.key_names]
                if not first.drop_unres:
                    first.kc_slot = kidc_slot(
                        ("k",) + tuple(first.key_names)
                    )
            elif isinstance(s, StepKeyInterpLit):
                s.lit_slots = [lit_slot(n) for n in s.key_names]
                s.kc_slots = [
                    kidc_slot(("k", n)) for n in s.key_names
                ]
            elif isinstance(s, StepIndex):
                s.kc_slot = kidc_slot(("i", s.index))

    def do_node(n) -> None:
        if isinstance(n, CClause):
            do_steps(n.steps)
            do_rhs(n.rhs, "scalar", n.op)
            if n.op == CmpOperator.Empty and not n.empty_on_expr:
                uses_empty[0] = True
            if n.rhs_query_steps is not None:
                do_steps(n.rhs_query_steps)
        elif isinstance(n, CCountClause):
            do_steps(n.steps)
        elif isinstance(n, CBlockClause):
            do_steps(n.query_steps)
            do_conjs(n.inner)
        elif isinstance(n, CWhenBlock):
            if n.conditions is not None:
                do_conjs(n.conditions)
            do_conjs(n.inner)

    def do_conjs(conjs) -> None:
        for disj in conjs:
            for n in disj:
                do_node(n)

    for r in compiled.rules:
        if r.conditions is not None:
            do_conjs(r.conditions)
        do_conjs(r.conjunctions)
    if uses_empty[0]:
        compiled.str_empty_slot = slot(
            compiled.str_empty_bits, "scalar", ("empty",)
        )
    compiled.needs_pairwise = (
        compiled.needs_struct_ids
        or compiled.needs_str_rank
        or uses_interp[0]
    )
    return uses_fn[0]


# ---------------------------------------------------------------------------
# Rule-file packing: many compiled files -> ONE executable
# ---------------------------------------------------------------------------
class PackIncompatible(Exception):
    """Raised when a CompiledRules cannot join a multi-file pack (it
    needs a per-file re-encoded batch, or was compiled against a
    different interner than the rest of the pack)."""


def pack_compatible(compiled: CompiledRules) -> Optional[str]:
    """None when `compiled` can join a pack, else the reason it
    cannot. Function-variable files are the one semantic exclusion:
    their batch is re-encoded per rule file (fn result subtrees +
    fn_origin columns), so they cannot share the pack's one batch."""
    if compiled.fn_vars:
        return "precomputed function variables need a per-file batch"
    if compiled.needs_fn_origin:
        return "per-origin function results need a per-file batch"
    if not compiled.rules:
        return "no device-lowered rules"
    return None


@dataclass
class PackedRules:
    """One CompiledRules concatenating several rule files' lowered IRs
    (pack_compiled), plus the per-file segment map: file i's rules
    occupy packed indices [offsets[i], offsets[i] + sizes[i]). The
    packed trace_signature doubles as the executable-cache key, so two
    invocations packing the same file structures in the same order
    reuse the jitted evaluator exactly like a single rule file does."""

    compiled: CompiledRules
    offsets: List[int]
    sizes: List[int]

    def segment(self, i: int) -> slice:
        return slice(self.offsets[i], self.offsets[i] + self.sizes[i])

    def rim_spec(self) -> "RimSpec":
        return build_rim_spec(
            [self.compiled.rules[self.segment(i)] for i in range(len(self.offsets))]
        )


def name_groups(rules: List[CRule]):
    """Per-rule name-group ids over one file's lowered rules: rules
    sharing a `rule_name` merge into one group, numbered in
    first-occurrence order — the same key order the per-doc
    `rule_statuses` dict build produces, so materialized dicts keep
    the declaration order the summary table prints. Returns
    ((R,) int32 group ids, group names)."""
    ids = np.zeros(len(rules), np.int32)
    names: List[str] = []
    seen: dict = {}
    for i, r in enumerate(rules):
        g = seen.get(r.name)
        if g is None:
            g = seen[r.name] = len(names)
            names.append(r.name)
        ids[i] = g
    return ids, names


@dataclass
class RimSpec:
    """Index tables for the post-kernel rim reductions
    (kernels.rim_reduce): one reduction over the (packed) rule axis
    yields every file's per-name-group merged statuses, per-doc
    overall status and any-fail / any-unsure bitmaps at once. Name
    groups are numbered GLOBALLY across the files (file k's groups
    occupy [group_offsets[k], group_offsets[k] + len(file_group_names
    [k]))), so a file's blocks slice back out of the pack-wide arrays
    by column range."""

    group_ids: np.ndarray  # (R,) int32: rule -> global name group
    file_ids: np.ndarray  # (R,) int32: rule -> file position
    last_ids: np.ndarray  # (G,) int32: group -> LAST rule index in it
    n_groups: int
    n_files: int
    group_offsets: List[int]
    file_group_names: List[List[str]]

    def file_slice(self, k: int) -> slice:
        return slice(
            self.group_offsets[k],
            self.group_offsets[k] + len(self.file_group_names[k]),
        )


def build_rim_spec(file_rules: List[List[CRule]]) -> RimSpec:
    """RimSpec over the concatenation of `file_rules` (one entry per
    rule file, in pack segment order; pass a single-element list for
    the per-file path)."""
    gids: List[np.ndarray] = []
    fids: List[np.ndarray] = []
    offsets: List[int] = []
    all_names: List[List[str]] = []
    base = 0
    for k, rules in enumerate(file_rules):
        ids, names = name_groups(rules)
        gids.append(ids + base)
        fids.append(np.full(len(rules), k, np.int32))
        offsets.append(base)
        all_names.append(names)
        base += len(names)
    group_ids = np.concatenate(gids) if gids else np.zeros(0, np.int32)
    last_ids = np.zeros(base, np.int32)
    last_ids[group_ids] = np.arange(len(group_ids), dtype=np.int32)
    return RimSpec(
        group_ids=group_ids,
        file_ids=(
            np.concatenate(fids) if fids else np.zeros(0, np.int32)
        ),
        last_ids=last_ids,
        n_groups=base,
        n_files=len(file_rules),
        group_offsets=offsets,
        file_group_names=all_names,
    )


def pack_compiled(parts: List[CompiledRules]) -> PackedRules:
    """Concatenate the lowered IRs of `parts` into ONE CompiledRules
    whose single vmap'd kernel evaluates a doc batch against every
    packed rule at once (the fused multi-rule-file dispatch: one
    compiled executable and one device dispatch per bucket for the
    whole pack, instead of one per rule file).

    Relocation is copy-on-write — the inputs stay valid for the
    per-file path. Every slot namespace is remapped into the pack:
    runtime-lits slots (deduped by literal string), bit-table slots
    (each file's empty-string table collapses onto ONE shared slot —
    the kernel reads `d.empty_slot` globally), has-child and folded-
    chain specs (deduped by value: registry files share many
    `Resources`-shaped columns), struct-literal slots (offset), and
    CNamedRef rule indices (offset by the file's rule base, preserving
    the compile-order invariant that referents precede referers).
    Host rules stay per-file with the caller. `needs_*` flags OR."""
    if not parts:
        raise PackIncompatible("empty pack")
    interner = parts[0].interner
    for p in parts:
        reason = pack_compatible(p)
        if reason is not None:
            raise PackIncompatible(reason)
        if p.interner is not interner:
            raise PackIncompatible("pack members must share one interner")
    out = CompiledRules(
        rules=[],
        host_rules=[],
        interner=interner,
        str_empty_bits=np.array(
            [len(s) == 0 for s in interner.strings], dtype=bool
        ),
        needs_struct_ids=any(p.needs_struct_ids for p in parts),
        needs_unsure=any(p.needs_unsure for p in parts),
        needs_str_rank=any(p.needs_str_rank for p in parts),
        needs_pairwise=any(p.needs_pairwise for p in parts),
    )
    seen_lits: dict = {}
    seen_kidc: dict = {}
    seen_chain: dict = {}
    offsets: List[int] = []
    sizes: List[int] = []

    def ensure_empty_slot() -> int:
        if out.str_empty_slot < 0:
            out.str_empty_slot = len(out.bit_tables)
            out.bit_tables.append((out.str_empty_bits, "scalar"))
            out.bit_specs.append(("empty",))
        return out.str_empty_slot

    for part in parts:
        # -- per-part slot remaps (dedupe where specs are by-value) --
        lits = {}
        for old, name in enumerate(part.lit_names):
            if name not in seen_lits:
                seen_lits[name] = len(out.lit_names)
                out.lit_names.append(name)
            lits[old] = seen_lits[name]
        bits = {}
        for old, (table, target) in enumerate(part.bit_tables):
            if old == part.str_empty_slot:
                bits[old] = ensure_empty_slot()
            else:
                bits[old] = len(out.bit_tables)
                out.bit_tables.append((table, target))
                out.bit_specs.append(part.bit_specs[old])
        kidcs = {}
        for old, spec in enumerate(part.kidc_tables):
            if spec not in seen_kidc:
                seen_kidc[spec] = len(out.kidc_tables)
                out.kidc_tables.append(spec)
            kidcs[old] = seen_kidc[spec]
        chains = {}
        for old, spec in enumerate(part.chain_tables):
            if spec not in seen_chain:
                seen_chain[spec] = len(out.chain_tables)
                out.chain_tables.append(spec)
            chains[old] = seen_chain[spec]
        struct_base = len(out.struct_literals)
        out.struct_literals.extend(part.struct_literals)
        rule_base = len(out.rules)

        def r_rhs(r: Optional[RhsSpec]) -> Optional[RhsSpec]:
            if r is None:
                return None
            c = copy.copy(r)
            if c.str_slot >= 0:
                c.str_slot = lits[c.str_slot]
            if c.bits_slot >= 0:
                c.bits_slot = bits[c.bits_slot]
            if c.lt_slot >= 0:
                c.lt_slot = bits[c.lt_slot]
            if c.le_slot >= 0:
                c.le_slot = bits[c.le_slot]
            if c.struct_slot >= 0:
                c.struct_slot = struct_base + c.struct_slot
            if c.items is not None:
                c.items = [r_rhs(it) for it in c.items]
            return c

        def r_step(s: Step) -> Step:
            if isinstance(s, StepFnVar):
                # unreachable behind pack_compatible; kept as the
                # exactness backstop should a new fn channel appear
                raise PackIncompatible(
                    "precomputed function variables are per-file"
                )
            if isinstance(s, StepKey):
                c = copy.copy(s)
                c.lit_slots = [lits[x] for x in s.lit_slots]
                if c.kc_slot >= 0:
                    c.kc_slot = kidcs[c.kc_slot]
                return c
            if isinstance(s, StepKeyChain):
                c = copy.copy(s)
                c.steps = [r_step(x) for x in s.steps]
                c.chain_slot = chains[s.chain_slot]
                return c
            if isinstance(s, StepKeyInterpLit):
                c = copy.copy(s)
                c.lit_slots = [lits[x] for x in s.lit_slots]
                c.kc_slots = [kidcs[x] for x in s.kc_slots]
                return c
            if isinstance(s, StepKeyInterpVar):
                c = copy.copy(s)
                c.var_steps = [r_step(x) for x in s.var_steps]
                return c
            if isinstance(s, StepIndex):
                c = copy.copy(s)
                if c.kc_slot >= 0:
                    c.kc_slot = kidcs[c.kc_slot]
                return c
            if isinstance(s, StepFilter):
                c = copy.copy(s)
                c.conjunctions = [
                    [r_node(n) for n in disj] for disj in s.conjunctions
                ]
                return c
            if isinstance(s, StepKeysMatch):
                c = copy.copy(s)
                c.rhs = r_rhs(s.rhs)
                return c
            return s  # StepAllValues / StepAllIndices carry no slots

        def r_node(n: CNode) -> CNode:
            if isinstance(n, CClause):
                c = copy.copy(n)
                c.steps = [r_step(x) for x in n.steps]
                c.rhs = r_rhs(n.rhs)
                if n.rhs_query_steps is not None:
                    c.rhs_query_steps = [
                        r_step(x) for x in n.rhs_query_steps
                    ]
                return c
            if isinstance(n, CCountClause):
                c = copy.copy(n)
                c.steps = [r_step(x) for x in n.steps]
                return c
            if isinstance(n, CBlockClause):
                c = copy.copy(n)
                c.query_steps = [r_step(x) for x in n.query_steps]
                c.inner = [[r_node(x) for x in disj] for disj in n.inner]
                return c
            if isinstance(n, CWhenBlock):
                c = copy.copy(n)
                if n.conditions is not None:
                    c.conditions = [
                        [r_node(x) for x in disj] for disj in n.conditions
                    ]
                c.inner = [[r_node(x) for x in disj] for disj in n.inner]
                return c
            if isinstance(n, CNamedRef):
                return CNamedRef(
                    rule_indices=[i + rule_base for i in n.rule_indices],
                    negation=n.negation,
                )
            return n

        offsets.append(rule_base)
        sizes.append(len(part.rules))
        for r in part.rules:
            nr = copy.copy(r)
            if r.conditions is not None:
                nr.conditions = [
                    [r_node(n) for n in disj] for disj in r.conditions
                ]
            nr.conjunctions = [
                [r_node(n) for n in disj] for disj in r.conjunctions
            ]
            out.rules.append(nr)
    # struct-id compares ride the unsure channel (compile_rules_file
    # applies the same implication)
    out.needs_unsure = out.needs_unsure or out.needs_struct_ids
    return PackedRules(compiled=out, offsets=offsets, sizes=sizes)


# ---------------------------------------------------------------------------
# Bit-table extension: grow compiled tables over a grown interner
# ---------------------------------------------------------------------------
def _eval_bit_spec(spec: tuple, strings: List[str]) -> np.ndarray:
    """Evaluate one bit_specs predicate over a string slice — the exact
    semantics the table was originally built with (Interner.
    substring_bits / regex_match_bits, the inline lt/le comprehensions
    in lower_rhs, and the empty-string table in compile_rules_file)."""
    kind = spec[0]
    if kind == "substr":
        lit = spec[1]
        return np.array([s in lit for s in strings], dtype=bool)
    if kind == "regex":
        rx = compiled_regex(spec[1])
        return np.array(
            [rx.search(s) is not None for s in strings], dtype=bool
        )
    if kind == "lt":
        lit = spec[1]
        return np.array([s < lit for s in strings], dtype=bool)
    if kind == "le":
        lit = spec[1]
        return np.array([s <= lit for s in strings], dtype=bool)
    if kind == "empty":
        return np.array([len(s) == 0 for s in strings], dtype=bool)
    raise ValueError(f"unknown bit spec {spec!r}")


def extend_bit_tables(
    parts: List[CompiledRules], interner: Interner
) -> int:
    """Grow every (S,) bit table in `parts` to cover `interner`'s
    current string count by evaluating each table's recorded bit_specs
    predicate over just the newly interned suffix. This is what lets a
    canonically lowered plan (ops/plan.py) survive interner growth
    without re-lowering: device_arrays gathers tables host-side per
    batch, so table LENGTH never reaches the kernel trace and extension
    causes zero recompiles.

    pack_compiled appends tables BY REFERENCE, so one underlying array
    can appear in several CompiledRules (a per-file part and the packs
    containing it); an id()-keyed memo extends each array once and
    rebinds every (table, target) entry to the same grown array.
    Returns the number of distinct arrays extended."""
    n = len(interner.strings)
    memo: dict = {}
    grown = 0
    for comp in parts:
        for i, (table, target) in enumerate(comp.bit_tables):
            if len(table) >= n:
                continue
            new = memo.get(id(table))
            if new is None:
                ext = _eval_bit_spec(
                    comp.bit_specs[i], interner.strings[len(table):]
                )
                new = np.concatenate([table, ext]) if len(table) else ext
                memo[id(table)] = new
                grown += 1
            comp.bit_tables[i] = (new, target)
        # keep the standalone empty-string table consistent (it aliases
        # bit_tables[str_empty_slot] when slotted; unused otherwise)
        tbl = comp.str_empty_bits
        if len(tbl) < n:
            new = memo.get(id(tbl))
            if new is None:
                ext = _eval_bit_spec(("empty",), interner.strings[len(tbl):])
                new = np.concatenate([tbl, ext]) if len(tbl) else ext
                memo[id(tbl)] = new
                grown += 1
            comp.str_empty_bits = new
    return grown
