"""Host-precomputed function variables (SURVEY.md §7 hard-part 7).

The reference's built-in functions (`guard/src/rules/functions/` —
strings.rs, converters.rs, date_time.rs, collections.rs) are stateful,
string-producing transforms that cannot run on device. Instead of
sending every rule that touches one to the CPU oracle, the device path
PRECOMPUTES each file-level function `let` per document on the host
(via the same oracle resolution the CPU engine uses,
eval_context.rs:1286-1472 dispatch) and encodes the resulting values as
EXTRA ORPHAN SUBTREES in the columnar batch:

  * result nodes are appended after the document's own nodes with
    `node_parent = -1`, so no traversal step can ever reach them —
    they are invisible to `.*`, `[*]`, keys filters and `empty`;
  * each result ROOT is tagged with a reserved negative key id
    (`fn_key_id(slot)` — a namespace that can never collide with
    interned map keys, which are >= 0), and a dedicated `StepFnVar`
    selects exactly those roots;
  * everything downstream — comparisons, regex bit columns, struct
    ids, key walks INTO `json_parse` trees — is ordinary kernel
    machinery, because the results ARE nodes.

Function variables never contain UnResolved entries (resolve_function
drops None results, scopes.py:343-356), so `StepFnVar` charges no
UnResolved accounting. Functions that RAISE on a document (e.g.
`parse_int('abc')`, converters.rs error paths) mark that document
host-only; the oracle rerun then reproduces the reference's error
behavior exactly.

Excluded from precompute (rules touching them fall back to the CPU
oracle):
  * `count`   — lowered natively as an integer compare (ir.CCountClause);
  * `now`     — nondeterministic: precomputing at encode time and
                re-resolving in the oracle rerun could straddle a
                second boundary and diverge;
  * `parse_char` — produces CHAR nodes, which documents otherwise
                never contain; kernel comparability tables assume so.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Set, Tuple

from ..core.errors import GuardError
from ..core.exprs import (
    AccessQuery,
    FunctionExpr,
    RulesFile,
    part_is_variable,
    part_variable,
)
from ..core.qresult import RESOLVED
from ..core.values import CHAR, PV, REGEX

_EXCLUDED = {"count", "now", "parse_char"}

# reserved node_key_id namespace: interned ids are >= 0, list elements
# -1, root/padding -2 — function slots live at -1000 - slot
_FN_KEY_BASE = -1000


def fn_key_id(slot: int) -> int:
    return _FN_KEY_BASE - slot


def _filter_candidates(prev_part, cur: PV):
    """Mirror of scopes._retrieve_filter's candidate derivation
    (eval_context.rs:723-828 / scopes.py:702-770): which values a
    filter's clause CNF evaluates against, given the value reached by
    the query prefix and the part class preceding the filter. Returns
    None where the oracle raises InternalError. The `[*]`-preceded
    outer-scope case never reaches consumption (ir refuses `filter
    after [*]` wholesale, so such rules stay on the host)."""
    from ..core.exprs import QAllIndices, QAllValues, QKey
    from ..core.values import LIST, MAP

    if prev_part is not None and part_is_variable(prev_part):
        # after a variable head, maps AND scalars filter themselves in
        # their own value scope; lists iterate (scopes.py:390-408,
        # ir.StepFilter scalar_self)
        if cur.kind == LIST:
            return list(cur.val)
        return [cur]
    if cur.kind == MAP:
        if isinstance(prev_part, (QAllValues, QAllIndices)):
            return [cur]
        if isinstance(prev_part, QKey) or prev_part is None:
            return list(cur.val.values.values())
        return None
    if cur.kind == LIST:
        return list(cur.val)
    if isinstance(prev_part, QAllIndices):
        return [cur]
    return []


def _pvar_bindable(value, excluded: Set[str]) -> bool:
    """A cross-scope binding precomputes only when nothing in it
    touches the excluded builtins (now/parse_char — nondeterminism /
    CHAR nodes) or a transitively-excluded variable."""
    if isinstance(value, FunctionExpr):
        vars_: Set[str] = set()
        names: Set[str] = set()
        _expr_refs(value, vars_, names)
        return not (names & _EXCLUDED) and not (vars_ & excluded)
    if isinstance(value, AccessQuery):
        return not (_query_vars(value) & excluded)
    return True


def _vs_depth(vs_path: tuple) -> int:
    """Value-scope DEPTH of a path: block / type-block / filter
    entries each open a new scope; when-blocks keep the enclosing
    selection (ir.lower_guard_clause keeps the scope token), so they
    are transparent. Cross-scope = binding depth strictly shallower
    than use depth."""
    return sum(1 for e in vs_path if e[0] != "when")


def _query_vars(q: AccessQuery) -> Set[str]:
    out: Set[str] = set()
    for part in q.query:
        if part_is_variable(part):
            out.add(part_variable(part))
    return out


def _expr_refs(fx: FunctionExpr, acc_vars: Set[str], acc_names: Set[str]) -> None:
    acc_names.add(fx.name)
    for p in fx.parameters:
        if isinstance(p, FunctionExpr):
            _expr_refs(p, acc_vars, acc_names)
        elif isinstance(p, AccessQuery):
            acc_vars.update(_query_vars(p))


def _when_chains(rule):
    """Yield (chain, block) for the rule body and every when-block
    nested through ROOT-BASIS paths only: when-blocks keep the
    enclosing selection (eval.rs:1428-1502), so a when-block reached
    without crossing a value scope still evaluates — and binds its
    `let`s — at the document root. `chain` is the list of enclosing
    Blocks from the rule body down (exclusive of `block`)."""
    from ..core.exprs import WhenBlockClause

    def walk(block, chain):
        yield chain, block
        for disj in block.conjunctions:
            for c in disj:
                if isinstance(c, WhenBlockClause):
                    yield from walk(c.block, chain + [block])

    yield from walk(rule.block, [])


def _fn_lets(rf: RulesFile) -> List[Tuple[int, str, FunctionExpr, list]]:
    """Every function `let` with a root binding basis: file-level
    (rule_idx -1), rule-BODY lets, and lets in when-blocks reached
    without crossing a value scope (rule blocks and their when-blocks
    evaluate with the document root as scope basis,
    eval_context.rs:980-997). Lets inside type blocks / nested
    query blocks are not enumerated (value scopes). The last element
    is the enclosing-Block chain for scope reconstruction (empty for
    file/rule-body lets)."""
    out: List[Tuple[int, str, FunctionExpr, list]] = []
    for let in rf.assignments:
        if isinstance(let.value, FunctionExpr):
            out.append((-1, let.var, let.value, []))
    for ri, rule in enumerate(rf.guard_rules):
        for chain, block in _when_chains(rule):
            for let in block.assignments:
                if isinstance(let.value, FunctionExpr):
                    out.append((ri, let.var, let.value, chain + [block]))
    return out


def _excluded_fn_vars(rf: RulesFile) -> Set[str]:
    """Variable NAMES excluded from precompute because their value
    (transitively, name-level fixpoint over possibly-forward
    references) touches an excluded builtin. Enumerates EVERY let in
    the file — root-basis AND value-scope lets, found by a generic
    structural walk — because a value-scope binding can indirect a
    precomputable slot to parse_char/now just as well as a root one
    (`let a = parse_char(Code)  let t = %a  Props[ K == %t ]`);
    name-level across scopes is a conservative over-approximation
    (same-named safe lets merely fall back to the host)."""
    from ..core.exprs import LetExpr, walk_expr_tree

    lets: List[LetExpr] = []

    def visit(o) -> bool:
        if isinstance(o, LetExpr):
            lets.append(o)
            return True
        return False

    walk_expr_tree(rf, visit)
    info = []
    for let in lets:
        vars_: Set[str] = set()
        names: Set[str] = set()
        if isinstance(let.value, FunctionExpr):
            _expr_refs(let.value, vars_, names)
        elif isinstance(let.value, AccessQuery):
            vars_ = _query_vars(let.value)
        info.append((let.var, vars_, names))
    excluded = {var for var, _, names in info if names & _EXCLUDED}
    changed = True
    while changed:
        changed = False
        for var, vars_, _ in info:
            if var not in excluded and vars_ & excluded:
                excluded.add(var)
                changed = True
    return excluded


def _encodable_literal(pv: PV) -> bool:
    """Only value kinds the document encoder models exactly may become
    synthetic nodes (no REGEX/RANGE/CHAR literals)."""
    k = pv.kind
    if k in (REGEX, CHAR) or k in (9, 10, 11):  # RANGE_*
        return False
    if k == 7:  # LIST
        return all(_encodable_literal(e) for e in pv.val)
    if k == 8:  # MAP
        return all(_encodable_literal(v) for v in pv.val.values.values())
    return True


def _walk_clauses(conjunctions, fn):
    from ..core.exprs import (
        BlockGuardClause,
        GuardAccessClause,
        ParameterizedNamedRuleClause,
        TypeBlock,
        WhenBlockClause,
    )

    for disj in conjunctions or []:
        for c in disj:
            fn(c)
            if isinstance(c, BlockGuardClause):
                _walk_clauses(c.block.conjunctions, fn)
            elif isinstance(c, WhenBlockClause):
                _walk_clauses(c.conditions, fn)
                _walk_clauses(c.block.conjunctions, fn)
            elif isinstance(c, TypeBlock):
                _walk_clauses(c.conditions, fn)
                _walk_clauses(c.block.conjunctions, fn)


def _walk_queries(conjunctions, fn):
    """Call fn(query_parts) for every AccessQuery under the clauses
    (including filters nested inside queries)."""
    from ..core.exprs import (
        BlockGuardClause,
        GuardAccessClause,
        ParameterizedNamedRuleClause,
        QFilter,
        TypeBlock,
    )

    def do_parts(parts):
        fn(parts)
        for part in parts:
            if isinstance(part, QFilter):
                _walk_queries(part.conjunctions, fn)

    def visit(c):
        if isinstance(c, GuardAccessClause):
            do_parts(c.access_clause.query.query)
            if isinstance(c.access_clause.compare_with, AccessQuery):
                do_parts(c.access_clause.compare_with.query)
        elif isinstance(c, ParameterizedNamedRuleClause):
            for p in c.parameters:
                if isinstance(p, AccessQuery):
                    do_parts(p.query)
        elif isinstance(c, BlockGuardClause):
            do_parts(c.query.query)
        elif isinstance(c, TypeBlock):
            do_parts(c.query)  # a plain parts list, not an AccessQuery

    _walk_clauses(conjunctions, visit)


def _head_var_names(rf: RulesFile) -> Set[str]:
    """Variable names used as a query HEAD anywhere in the file."""
    heads: Set[str] = set()

    def on_query(parts):
        if parts and part_is_variable(parts[0]):
            heads.add(part_variable(parts[0]))

    for rule in rf.guard_rules:
        _walk_queries(rule.conditions, on_query)
        _walk_queries(rule.block.conjunctions, on_query)
    for prule in rf.parameterized_rules:
        _walk_queries(prule.rule.conditions, on_query)
        _walk_queries(prule.rule.block.conjunctions, on_query)
    for let in rf.assignments:
        if isinstance(let.value, AccessQuery):
            on_query(let.value.query)
    return heads


@dataclass
class _Slot:
    key: tuple  # opaque encode-order key
    kind: str  # 'fn' | 'lit' | 'expr' | 'pexpr'
    rule_idx: int  # -1 = file scope
    var: str = ""  # fn/lit
    pv: object = None  # lit
    fx: object = None  # expr/pexpr (FunctionExpr)
    # enclosing-Block chain (rule body + nested when-blocks) the
    # precompute folds into a scope stack; empty = file/rule scope
    chain: tuple = ()
    # 'pexpr' only: the value-scope path from the root-basis chain down
    # to the clause — ('block', BlockGuardClause) / ('type', TypeBlock)
    # / ('when', WhenBlockClause) entries the precompute replays to
    # enumerate candidate origins exactly like the oracle
    # (evaluator.eval_guard_block_clause / eval_type_block_clause)
    vs_path: tuple = ()


@dataclass
class FnSlots:
    """Everything the encoder / lowering / precompute agree on."""

    slots: List[_Slot]
    # function lets, keyed by the BINDING's FunctionExpr identity: the
    # let's value object uniquely names the binding, so the same
    # (rule, name) bound in several when blocks disambiguates for free
    # (the lowering resolves the name through its scoped block_vars and
    # looks the winning object up here)
    var_slots: Dict[int, int]  # id(FunctionExpr) -> slot
    lit_slots: Dict[Tuple[int, str], int]  # literal lets used as heads
    expr_slots: Dict[int, int]  # id(FunctionExpr) -> slot (inline uses)
    pv_slots: Dict[int, int]  # id(PV) -> slot (literal call arguments)
    # id(FunctionExpr) -> slot for origin-DEPENDENT inline calls in
    # value scopes: precomputed once per (document, candidate origin),
    # selected per origin label by the kernels (ir.StepFnVar
    # per_origin)
    pexpr_slots: Dict[int, int] = None
    # id(AccessQuery) -> slot for CROSS-SCOPE value-scope variable
    # uses as clause RHS (`Resources.* { let t = Type  Properties[
    # Kind == %t ] exists }`): the variable re-resolves per enclosing
    # origin, so its values precompute once per USE-SITE candidate
    # (resolved through the replayed scope chain, which lands on the
    # binding origin's scope) and join per origin label exactly like
    # pexpr results
    pvar_slots: Dict[int, int] = None

    @property
    def keys(self) -> List[tuple]:
        return [s.key for s in self.slots]


def fn_slots(rf: RulesFile) -> FnSlots:
    """Enumerate every precomputable slot, in deterministic order:

      * function `let`s (file-level and rule-body) — resolved per doc;
      * literal `let`s whose NAME is used as a query head anywhere
        (their value becomes a synthetic subtree so `%lit.x` /
        `%lit == query` walks work) — constant across docs;
      * inline FunctionExpr uses in TOP-LEVEL rule clauses: clause RHS
        (`"a,b" == join(%c, ',')`) and parameterized-call arguments
        (eval.rs:1574-1599 resolves them in the caller's scope) —
        keyed by expression identity, resolved per doc in the owning
        rule's scope.
    """
    excluded = _excluded_fn_vars(rf)
    slots: List[_Slot] = []
    var_slots: Dict[int, int] = {}
    lit_slots: Dict[Tuple[int, str], int] = {}
    expr_slots: Dict[int, int] = {}
    pv_slots: Dict[int, int] = {}
    pexpr_slots: Dict[int, int] = {}
    pvar_slots: Dict[int, int] = {}

    def add(slot: _Slot) -> int:
        slots.append(slot)
        return len(slots) - 1

    # function lets, incl. when-block lets at root basis. A (rule,
    # name) bound in MORE THAN ONE when block gets one slot per
    # binding (the occurrence index keeps encode keys unique); the
    # precompute resolves each through its own block chain, and the
    # lowering disambiguates by the binding's FunctionExpr identity
    fn_lets = [t for t in _fn_lets(rf) if t[1] not in excluded]
    for occ, (ri, var, fx, chain) in enumerate(fn_lets):
        var_slots[id(fx)] = add(
            _Slot(
                key=("fn", ri, var, occ), kind="fn", rule_idx=ri,
                var=var, chain=tuple(chain),
            )
        )

    heads = _head_var_names(rf)
    for let in rf.assignments:
        if (
            isinstance(let.value, PV)
            and let.var in heads
            and _encodable_literal(let.value)
        ):
            lit_slots[(-1, let.var)] = add(
                _Slot(
                    key=("lit", -1, let.var), kind="lit", rule_idx=-1,
                    var=let.var, pv=let.value,
                )
            )
    for ri, rule in enumerate(rf.guard_rules):
        lit_lets = [
            (chain, let)
            for chain, block in _when_chains(rule)
            for let in block.assignments
            if isinstance(let.value, PV)
        ]
        lit_counts: Dict[str, int] = {}
        for _chain, let in lit_lets:
            lit_counts[let.var] = lit_counts.get(let.var, 0) + 1
        for _chain, let in lit_lets:
            if (
                let.var in heads
                and lit_counts[let.var] == 1
                and _encodable_literal(let.value)
            ):
                lit_slots[(ri, let.var)] = add(
                    _Slot(
                        key=("lit", ri, let.var), kind="lit", rule_idx=ri,
                        var=let.var, pv=let.value,
                    )
                )

    def usable_expr(fx: FunctionExpr) -> bool:
        vars_, names = set(), set()
        _expr_refs(fx, vars_, names)
        # count is excluded from LET precompute only because lets have
        # the cheaper native CCountClause path; inline there is none,
        # and its single-int result encodes exactly
        return not (names & (_EXCLUDED - {"count"})) and not (
            vars_ & excluded
        )

    file_let_names = {let.var for let in rf.assignments}

    def _root_safe(fx: FunctionExpr, bound: Set[str], vs_bound: Set[str]) -> bool:
        """Inside a VALUE scope an inline call only precomputes when its
        result is origin-independent: every query parameter must be
        headed by a variable whose binding lives on the root-basis
        chain (file / rule / enclosing when-block lets), with no name
        shadowed by a value-scope binding."""
        vars_, _names = set(), set()
        _expr_refs(fx, vars_, _names)
        if vars_ & vs_bound or not vars_ <= bound:
            return False

        def check(f: FunctionExpr) -> bool:
            for p in f.parameters:
                if isinstance(p, AccessQuery):
                    if not (p.query and part_is_variable(p.query[0])):
                        return False
                elif isinstance(p, FunctionExpr) and not check(p):
                    return False
            return True

        return check(fx)

    from ..core.exprs import (
        BlockGuardClause,
        GuardAccessClause,
        ParameterizedNamedRuleClause,
        QFilter,
        TypeBlock,
        WhenBlockClause,
    )

    for ri, rule in enumerate(rf.guard_rules):

        def bound_names(chain) -> Set[str]:
            names = set(file_let_names)
            for b in chain:
                names.update(let.var for let in b.assignments)
            return names

        def on_expr(fx, chain, in_vs, vs_binds, vs_path=(),
                    lhs_root=False, ri=ri):
            if (
                id(fx) in expr_slots
                or id(fx) in pexpr_slots
                or not usable_expr(fx)
            ):
                return
            if in_vs and not _root_safe(
                fx, bound_names(chain), set(vs_binds)
            ):
                # origin-DEPENDENT inline call: the result genuinely
                # differs per candidate, so it precomputes per origin
                # (kind 'pexpr') — the encoder tags each result subtree
                # with its origin node and the kernels select per
                # origin label (ir.StepFnVar per_origin). The scope
                # path replays block / type-block / when-block entries
                # AND query-filter entries (filter candidates derive
                # from the recorded query prefix exactly like
                # scopes._retrieve_filter). A clause whose LHS
                # evaluates from the ROOT basis (head variable bound on
                # the root chain -> ir raises CrossScopeRootVar and
                # then refuses the per-origin RHS) gets no slot:
                # the lowering could never consume it, so precomputing
                # and encoding its results would be pure waste.
                if lhs_root:
                    return
                pexpr_slots[id(fx)] = add(
                    _Slot(
                        key=("pexpr", ri, len(pexpr_slots)), kind="pexpr",
                        rule_idx=ri, fx=fx, chain=tuple(chain),
                        vs_path=tuple(vs_path),
                    )
                )
                return
            expr_slots[id(fx)] = add(
                _Slot(
                    key=("expr", ri, len(expr_slots)), kind="expr",
                    rule_idx=ri, fx=fx, chain=tuple(chain),
                )
            )

        def walk_parts(parts, chain, vs_binds, vs_path=(), ri=ri):
            for pi, part in enumerate(parts):
                if isinstance(part, QFilter):
                    # record the query prefix: the precompute derives
                    # this filter's candidate set from it
                    vp = vs_path + (("filter", part, tuple(parts[:pi])),)
                    for disj in part.conjunctions:
                        for cc in disj:
                            walk_clause(cc, chain, True, vs_binds, vp)

        def walk_clause(c, chain, in_vs, vs_binds, vs_path=(), ri=ri):
            if isinstance(c, GuardAccessClause):
                cw = c.access_clause.compare_with
                parts = c.access_clause.query.query
                lhs_root = bool(
                    in_vs
                    and parts
                    and part_is_variable(parts[0])
                    and part_variable(parts[0]) not in vs_binds
                    and part_variable(parts[0]) in bound_names(chain)
                )
                if isinstance(cw, FunctionExpr):
                    # mirror of ir's CrossScopeRootVar: a head variable
                    # bound on the root chain (and not shadowed in the
                    # value scope) re-roots the LHS at the document
                    # root, which the per-origin RHS then refuses
                    on_expr(cw, chain, in_vs, vs_binds, vs_path, lhs_root)
                elif (
                    isinstance(cw, AccessQuery)
                    and in_vs
                    and not lhs_root
                    and len(cw.query) == 1
                    and part_is_variable(cw.query[0])
                    and id(cw) not in pvar_slots
                ):
                    # cross-scope value-scope variable as clause RHS:
                    # bound in an ENCLOSING value scope (strictly
                    # shallower than this clause — same-depth uses
                    # lower natively), so it re-resolves per origin.
                    # Precomputed per use-site candidate ('pvar').
                    # LITERAL bindings are origin-independent and
                    # already lower through ir.lower_rhs — no slot.
                    var = part_variable(cw.query[0])
                    bind = vs_binds.get(var)
                    if (
                        bind is not None
                        and bind[0] < _vs_depth(vs_path)
                        and not isinstance(bind[1], PV)
                        and _pvar_bindable(bind[1], excluded)
                    ):
                        pvar_slots[id(cw)] = add(
                            _Slot(
                                key=("pvar", ri, len(pvar_slots)),
                                kind="pvar", rule_idx=ri, var=var,
                                chain=tuple(chain),
                                vs_path=tuple(vs_path),
                            )
                        )
                walk_parts(parts, chain, vs_binds, vs_path)
                if isinstance(cw, AccessQuery):
                    walk_parts(cw.query, chain, vs_binds, vs_path)
            elif isinstance(c, ParameterizedNamedRuleClause):
                for p in c.parameters:
                    if isinstance(p, FunctionExpr):
                        # rule-call args lower at root scope only
                        # (ir.lower_parameterized_call)
                        if not in_vs:
                            on_expr(p, chain, in_vs, vs_binds)
                    elif isinstance(p, PV):
                        # literal call argument: the callee may use the
                        # parameter as a query head
                        if (
                            not in_vs
                            and id(p) not in pv_slots
                            and _encodable_literal(p)
                        ):
                            pv_slots[id(p)] = add(
                                _Slot(
                                    key=("plit", ri, len(pv_slots)),
                                    kind="lit", rule_idx=ri, pv=p,
                                )
                            )
                    elif isinstance(p, AccessQuery):
                        walk_parts(p.query, chain, vs_binds, vs_path)
            elif isinstance(c, WhenBlockClause):
                for disj in c.conditions or []:
                    for cc in disj:
                        walk_clause(cc, chain, in_vs, vs_binds, vs_path)
                if in_vs:
                    vp = vs_path + (("when", c),)
                    # when-blocks keep the enclosing selection, so
                    # their lets bind at the ENCLOSING depth
                    vb = dict(vs_binds)
                    for let in c.block.assignments:
                        vb[let.var] = (_vs_depth(vs_path), let.value)
                    for disj in c.block.conjunctions:
                        for cc in disj:
                            walk_clause(cc, chain, True, vb, vp)
                else:
                    ch = chain + (c.block,)
                    for disj in c.block.conjunctions:
                        for cc in disj:
                            walk_clause(cc, ch, False, vs_binds)
            elif isinstance(c, (BlockGuardClause, TypeBlock)):
                if isinstance(c, BlockGuardClause):
                    walk_parts(c.query.query, chain, vs_binds, vs_path)
                    vp = vs_path + (("block", c),)
                else:
                    walk_parts(c.query, chain, vs_binds, vs_path)
                    for disj in c.conditions or []:
                        for cc in disj:
                            walk_clause(cc, chain, in_vs, vs_binds, vs_path)
                    vp = vs_path + (("type", c),)
                vb = dict(vs_binds)
                for let in c.block.assignments:
                    vb[let.var] = (_vs_depth(vp), let.value)
                for disj in c.block.conjunctions:
                    for cc in disj:
                        walk_clause(cc, chain, True, vb, vp)

        base_chain = (rule.block,)
        for disj in rule.conditions or []:
            for c in disj:
                walk_clause(c, base_chain, False, {})
        for disj in rule.block.conjunctions:
            for c in disj:
                walk_clause(c, base_chain, False, {})

    return FnSlots(
        slots=slots, var_slots=var_slots, lit_slots=lit_slots,
        expr_slots=expr_slots, pv_slots=pv_slots,
        pexpr_slots=pexpr_slots, pvar_slots=pvar_slots,
    )


def precomputable_fn_vars(rf: RulesFile) -> List[tuple]:
    """Slot keys in encode order (empty = nothing to precompute)."""
    return fn_slots(rf).keys


def precompute_fn_values(
    rf: RulesFile, docs: List[PV]
) -> Tuple[List[tuple], List[Dict[tuple, List[PV]]], Set[int]]:
    """(slot keys in encode order, per-doc {slot key: [result PVs]},
    error doc indices).

    Resolution goes through the same RootScope/BlockScope machinery
    the CPU engine uses, so chained lets (`let b = to_upper(%a)`),
    references to file- and rule-level query lets, and literal/query
    arguments behave identically. A document on which any function
    raises lands in the error set — the caller routes it to the CPU
    oracle, which reproduces the error through the normal path. (The
    precompute is eager, so a document whose erroring rule would have
    been when-gated to SKIP still lands in the error set — it then
    merely evaluates on the oracle, with identical statuses.)"""
    layout = fn_slots(rf)
    keys = layout.keys
    values: List[Dict[tuple, List[PV]]] = []
    errors: Set[int] = set()
    if not layout.slots:
        return keys, [{} for _ in docs], errors
    from ..core.scopes import (  # lazy
        BlockScope,
        RootScope,
        ValueScope,
        resolve_function,
    )

    def _pexpr_scopes(slot, base_scope, cache):
        """[(origin PV, resolver)] replaying the slot's value-scope
        path with the SAME scope shapes the oracle builds: each
        block/type-block level resolves its query in the current scope
        and wraps every RESOLVED value in ValueScope + BlockScope
        (evaluator.eval_guard_block_clause:1126 /
        eval_type_block_clause:1424 -> eval_general_block_clause:1071);
        when-blocks keep the origin and add their lets. Origins are
        reached by strictly-descending traversal, so each innermost
        origin has exactly one scope chain. Query-FILTER entries
        derive their candidate sets from the recorded query prefix,
        mirroring scopes._retrieve_filter branch for branch. `cache`
        memoizes the pairs per (base scope, vs_path) within one
        document: k calls in the same scope replay its queries and
        when-gates once, not k times."""
        ckey = (id(base_scope),) + tuple(id(e[1]) for e in slot.vs_path)
        hit = cache.get(ckey)
        if hit is not None:
            return hit
        from ..core.evaluator import (  # lazy (cycle via scopes)
            eval_conjunction_clauses,
            eval_when_clause,
        )
        from ..core.qresult import Status

        def when_passes(conditions, sc) -> bool:
            """eval.rs:1428-1502 gate: only PASSing conditions enter
            the block — origins behind a false/skipped guard are NOT
            precomputed, so a guard protecting a call from bad input
            (`when Limit == /^[0-9]+$/ { ... parse_int(Limit) ... }`)
            keeps its documents on the device path instead of flagging
            spurious fn errors. A RAISE during condition evaluation
            propagates: the caller flags the doc and the oracle
            reproduces the error."""
            if not conditions:
                return True
            return (
                eval_conjunction_clauses(
                    conditions, sc, eval_when_clause,
                    context=(
                        "cfn_guard::rules::exprs::WhenGuardClause"
                        "#disjunction"
                    ),
                )
                == Status.PASS
            )

        pairs = [(None, base_scope)]
        for entry in slot.vs_path:
            kind, node = entry[0], entry[1]
            if kind == "when":
                pairs = [
                    (o, BlockScope(node.block, sc.root(), sc))
                    for o, sc in pairs
                    if when_passes(node.conditions, sc)
                ]
                continue
            if kind == "filter":
                # candidates per scopes._retrieve_filter: resolve the
                # recorded query prefix in the current scope, then
                # expand per the part class preceding the filter
                prefix = list(entry[2])
                prev_part = prefix[-1] if prefix else None
                new = []
                for _o, sc in pairs:
                    if prefix:
                        curs = [
                            qr.value
                            for qr in sc.query(prefix)
                            if qr.tag == RESOLVED
                        ]
                    else:
                        curs = [sc.root()]
                    for cur in curs:
                        cands = _filter_candidates(prev_part, cur)
                        if cands is None:
                            # the oracle raises InternalError for
                            # filters after such parts — route the
                            # doc there
                            from ..core.errors import InternalError

                            raise InternalError(
                                "filter after unexpected query part"
                            )
                        for cand in cands:
                            new.append((cand, ValueScope(cand, sc)))
                pairs = new
                continue
            q = node.query.query if kind == "block" else node.query
            new = []
            for _o, sc in pairs:
                if kind == "type" and not when_passes(
                    getattr(node, "conditions", None), sc
                ):
                    # type-block conditions gate at the OUTER scope
                    # (eval_type_block_clause) — a non-PASS gate means
                    # no origins at all
                    continue
                for qr in sc.query(q):
                    if qr.tag != RESOLVED:
                        continue
                    vs = ValueScope(qr.value, sc)
                    new.append(
                        (qr.value, BlockScope(node.block, vs.root(), vs))
                    )
            pairs = new
        cache[ckey] = pairs
        return pairs

    for i, doc in enumerate(docs):
        per: Dict[tuple, List[PV]] = {}
        root = RootScope(rf, doc)
        chain_scopes: Dict[tuple, BlockScope] = {}
        pexpr_cache: Dict[tuple, list] = {}

        def scope_for(chain):
            """Fold the slot's enclosing-Block chain (rule body +
            nested when-blocks, all root-basis) into a scope stack so
            chained lets and shadowing resolve like the oracle's."""
            if not chain:
                return root
            key = tuple(id(b) for b in chain)
            s = chain_scopes.get(key)
            if s is None:
                s = BlockScope(chain[-1], doc, scope_for(chain[:-1]))
                chain_scopes[key] = s
            return s

        try:
            for slot in layout.slots:
                if slot.kind == "lit":
                    per[slot.key] = [slot.pv]
                elif slot.kind == "fn":
                    per[slot.key] = [
                        q.value
                        for q in scope_for(slot.chain).resolve_variable(
                            slot.var
                        )
                        if q.tag == RESOLVED
                    ]
                elif slot.kind == "pexpr":
                    # origin-dependent inline call: one result list per
                    # candidate origin, keyed by the origin node's path
                    # (unique per node; the encoder maps it back to the
                    # node index for the fn_origin column)
                    per_origin: Dict[str, List[PV]] = {}
                    for origin, sc in _pexpr_scopes(
                        slot, scope_for(slot.chain), pexpr_cache
                    ):
                        opath = origin.path.s
                        if opath in per_origin:
                            continue
                        per_origin[opath] = [
                            q.value
                            for q in resolve_function(
                                slot.fx.name, slot.fx.parameters, sc
                            )
                            if q.tag == RESOLVED
                        ]
                    per[slot.key] = per_origin
                elif slot.kind == "pvar":
                    # cross-scope value-scope variable as clause RHS:
                    # resolve the variable through each use-site
                    # candidate's replayed scope chain (which lands on
                    # the binding origin's scope, with shadowing and
                    # single-shot caching exactly like the oracle's).
                    # UnResolved entries would need per-origin
                    # UnResolved accounting the kernels don't model —
                    # such documents route to the oracle instead.
                    per_origin = {}
                    for origin, sc in _pexpr_scopes(
                        slot, scope_for(slot.chain), pexpr_cache
                    ):
                        opath = origin.path.s
                        if opath in per_origin:
                            continue
                        rs = sc.resolve_variable(slot.var)
                        if any(q.tag != RESOLVED for q in rs):
                            raise GuardError(
                                "cross-scope variable resolves "
                                "UnResolved entries; host evaluation"
                            )
                        per_origin[opath] = [q.value for q in rs]
                    per[slot.key] = per_origin
                else:  # inline expression
                    per[slot.key] = [
                        q.value
                        for q in resolve_function(
                            slot.fx.name,
                            slot.fx.parameters,
                            scope_for(slot.chain),
                        )
                        if q.tag == RESOLVED
                    ]
        except GuardError:
            errors.add(i)
            per = {}
        values.append(per)
    return keys, values, errors
