"""`validate --backend=tpu`: batch evaluation with CPU fail-rerun.

The integration point between the command layer and the JAX engine
(BASELINE.json north star: "gated behind the ffi boundary and surfaced
as `validate --backend=tpu`"):

  1. encode all data files into one columnar batch (shared interner);
  2. lower each rule file; rules outside kernel coverage stay on the
     CPU oracle (host_rules);
  3. evaluate the (docs x rules) batch on the mesh — statuses only;
  4. re-run only documents that need rich reports (failures, verbose or
     structured output) through the CPU oracle — the "fail-rerun" design
     (SURVEY.md §7 hard-part 6) that keeps kernels lean while reports
     stay bit-identical to the reference path.
"""

from __future__ import annotations

import json
import logging
import os
from collections import OrderedDict
from typing import List

log = logging.getLogger("guard_tpu.backend")

from ..core.errors import GuardError
from ..core.evaluator import eval_rules_file
from ..core.qresult import Status
from ..core.scopes import RootScope
from ..utils.faults import (
    FAULT_COUNTERS,
    bounded_call,
    fault_stats,
    maybe_fail,
    quarantine_record,
    reset_fault_counters,
)
from ..utils.io import Writer
from ..utils.telemetry import REGISTRY as _TELEMETRY
from ..utils.telemetry import span as _span
from ..utils.telemetry import span_begin as _span_begin
from ..utils.telemetry import span_end as _span_end
from .encoder import encode_batch
from .ir import FAIL, PASS, SKIP, compile_rules_file
from ..commands.report import rule_statuses_from_root, simplified_report_from_root

_STATUS = {PASS: Status.PASS, FAIL: Status.FAIL, SKIP: Status.SKIP}
_STATUS_VALUES = {s.value for s in Status}

# rule-packing ceiling: packs close when their rule count would exceed
# this (one pack executable traces every packed rule program, so the
# cap bounds trace/compile time for pathologically huge registries;
# the 250-file corpus' ~257 rules fit in ONE pack at the default)
PACK_MAX_RULES = int(os.environ.get("GUARD_TPU_PACK_MAX_RULES", "512"))


def vector_rim_enabled() -> bool:
    """The vectorized results plane (device-side rim reductions, numpy
    mask arithmetic in pass A, bulk report materialization in pass B).
    `GUARD_TPU_VECTOR_RIM=0` is the bit-parity escape hatch back to the
    scalar per-(doc, rule) walk; read at call time so one process can
    compare both (tests/test_vector_rim.py does)."""
    return os.environ.get("GUARD_TPU_VECTOR_RIM", "1") != "0"


# Rim observability, next to PR 1's dispatch counters
# (parallel.mesh.DISPATCH_COUNTERS): `docs_materialized` counts (doc,
# rule-file) pairs whose per-rule status dict was actually built —
# failures, unsure-flagged, host-fallback, rich output — and
# `docs_settled` those answered entirely in-array (report/console/JUnit
# served from the shared per-unique-status-row cache). The scalar rim
# materializes EVERY doc, so the all-PASS CI rim-smoke pins
# docs_materialized == 0 only on the vectorized path.
RIM_COUNTERS = _TELEMETRY.counter_group(
    "rim", {"docs_materialized": 0, "docs_settled": 0}
)


def rim_stats() -> dict:
    return _TELEMETRY.group_stats("rim")


def reset_rim_stats() -> None:
    _TELEMETRY.reset_group("rim")


# pack_compiled output cache: the slot relocation is a pure function of
# the member CompiledRules objects, so repeated evaluation of the same
# registry (serve sessions, sweep chunks re-using lowered files, bench
# reps) skips the IR rewrite. Keyed by member identity; values keep the
# members alive so ids cannot be recycled under the cache.
_PACK_CACHE: "OrderedDict[tuple, tuple]" = OrderedDict()
_PACK_CACHE_MAX = 8


def _pack_cached(parts: list):
    """pack_compiled(parts) with an LRU over member identities.
    Returns (PackedRules, RimSpec)."""
    from .ir import pack_compiled

    key = tuple(id(c) for c in parts)
    hit = _PACK_CACHE.get(key)
    if hit is not None:
        _PACK_CACHE.move_to_end(key)
        return hit[1], hit[2]
    with _span("pack_compile", {"files": len(parts)}):
        packed = pack_compiled(parts)
        spec = packed.rim_spec()
    _PACK_CACHE[key] = (list(parts), packed, spec)
    while len(_PACK_CACHE) > _PACK_CACHE_MAX:
        _PACK_CACHE.popitem(last=False)
    return packed, spec


def dispatch_stats() -> dict:
    """Snapshot of the run's device-dispatch observability counters
    (parallel.mesh.DISPATCH_COUNTERS): `dispatches` = jitted evaluator
    calls issued, `executables_compiled` = distinct (evaluator, bucket
    shape) pairs those calls compiled. bench.py emits these and the CPU
    bench-smoke pins a ceiling on the packed path's dispatch count."""
    from ..parallel import mesh  # noqa: F401  registers the group

    return _TELEMETRY.group_stats("dispatch")


def reset_dispatch_stats() -> None:
    from ..parallel import mesh  # noqa: F401  registers the group

    _TELEMETRY.reset_group("dispatch")


def pipeline_stats() -> dict:
    """Snapshot of the ingest-pipeline counters
    (parallel.mesh.PIPELINE_COUNTERS): worker-prefetched chunks,
    encode/dispatch overlap events, the queued-chunk high-water mark
    and the stage timing accumulators bench.py's ingest decomposition
    rows divide into per-run numbers."""
    from ..parallel import mesh  # noqa: F401  registers the group

    return _TELEMETRY.group_stats("pipeline")


def reset_pipeline_stats() -> None:
    from ..parallel import mesh  # noqa: F401  registers the group

    _TELEMETRY.reset_group("pipeline")


def efficiency_stats() -> dict:
    """Snapshot of the hardware-efficiency counters
    (parallel.mesh.EFFICIENCY_COUNTERS): padded-batch occupancy (real
    vs padding doc/node slots), host<->device transfer bytes, and pack
    rule-slot usage vs the PACK_MAX_RULES ceiling. `guard-tpu report
    --efficiency` renders these from ledger records; tests reconcile
    them against hand-computed batch shapes."""
    from ..parallel import mesh  # noqa: F401  registers the group

    return _TELEMETRY.group_stats("efficiency")


def reset_efficiency_stats() -> None:
    from ..parallel import mesh  # noqa: F401  registers the group

    _TELEMETRY.reset_group("efficiency")


def reset_fault_stats() -> None:
    """Reset the failure-plane counters (utils.faults.FAULT_COUNTERS);
    `fault_stats` is re-exported above them for symmetry with the
    dispatch/pipeline/rim accessors."""
    reset_fault_counters()


def admission_stats() -> dict:
    """Snapshot of the serving front door's traffic-discipline
    counters (utils.telemetry.ADMISSION_COUNTERS): per-tenant quota
    admissions/rejections, SLO circuit-breaker trips/probes/closes,
    overload sheds and streaming follow-mode micro-batches. The group
    registers with utils.telemetry itself, so the accessor — unlike
    dispatch/pipeline — needs no mesh import and stays jax-free."""
    return _TELEMETRY.group_stats("admission")


def reset_admission_stats() -> None:
    _TELEMETRY.reset_group("admission")


def reset_all_stats() -> None:
    """Reset EVERY observability plane atomically: dispatch, pipeline,
    rim and fault counter groups plus the telemetry gauges, stage
    histograms and span roll-ups — one switch instead of four reset
    calls each entry point had to remember. Used by serve between
    requests and by every bench measure_* entry point. Persistent
    histograms (serve request latency) and the trace buffer survive:
    the former accumulate across requests by design, the latter is an
    artifact log, not a stat. Deliberately does NOT import
    parallel.mesh (and with it jax): a group that was never registered
    was never incremented, so there is nothing to reset — which keeps
    this safe to call from jax-free serve sessions."""
    _TELEMETRY.reset()


def plan_packs(items, max_rules: int = None):
    """Greedy pack planner over [(file_idx, CompiledRules)] pairs
    already screened with ir.pack_compatible: packs fill in file order
    and close when the next file would push the pack past `max_rules`.
    File order is preserved so packed statuses slice back per file in
    the caller's iteration order."""
    max_rules = PACK_MAX_RULES if max_rules is None else max_rules
    packs, cur, cur_rules = [], [], 0
    for fi, c in items:
        n = len(c.rules)
        if cur and cur_rules + n > max_rules:
            packs.append(cur)
            cur, cur_rules = [], 0
        cur.append((fi, c))
        cur_rules += n
    if cur:
        packs.append(cur)
    return packs


class PackPending:
    """In-flight state between `dispatch_packs` and `collect_packs` —
    the decoupling the three-stage sweep pipeline needs: chunk k's
    packs stay dispatched (device executing) while the host emits
    chunk k-1's reports and the ingest workers encode chunk k+1.

    `rim_blocks` (mesh2d.RIM_PROFILES) records which rim blocks this
    dispatch shipped: None means the full legacy protocol (statuses +
    all six blocks); a tuple means the mesh rim-only collect — the
    padded status matrix never crossed, so collect_packs returns
    statuses/unsure as None and only the shipped blocks in each
    file's rim."""

    __slots__ = ("pending", "host_docs", "with_rim", "rim_blocks")

    def __init__(self, pending, host_docs, with_rim, rim_blocks=None):
        self.pending = pending
        self.host_docs = host_docs
        self.with_rim = with_rim
        self.rim_blocks = rim_blocks


def dispatch_packs(items, batch, with_rim=None, prepacked=None,
                   profile=None) -> PackPending:
    """Dispatch half of the fused multi-rule-file pipeline: pack the
    compatible compiled files (plan_packs) and dispatch EVERY (pack,
    doc shard, bucket group) WITHOUT collecting — JAX dispatch is
    async, so the returned PackPending represents genuinely in-flight
    device work.

    `prepacked` (the plan layer, ops/plan.py): an already-computed
    [(pack, PackedRules, RimSpec)] list — the pack plan is part of the
    canonical artifact, so warm chunks skip plan_packs/_pack_cached
    entirely.

    `profile` ("validate" | "sweep", mesh2d.RIM_PROFILES) activates
    the 2-D mesh rim-only collect when the mesh is on and the rim
    rides the dispatch: the consumer's named rim blocks are the ONLY
    payload that leaves the mesh per collect."""
    if with_rim is None:
        with_rim = vector_rim_enabled()
    if (not prepacked) if prepacked is not None else (len(items) < 2):
        return PackPending([], set(), with_rim)
    with _span("dispatch", {"files": len(items)}):
        return _dispatch_packs_inner(items, batch, with_rim, prepacked,
                                     profile)


def _dispatch_packs_inner(items, batch, with_rim, prepacked=None,
                          profile=None) -> PackPending:
    from .encoder import NODE_BUCKETS_EXTENDED, split_batch_by_size
    from .ir import PackIncompatible
    from ..parallel import mesh2d
    from ..parallel.mesh import EFFICIENCY_COUNTERS, ShardedBatchEvaluator

    # the 2-D (docs x packs) mesh is the default whenever >1 device is
    # visible (GUARD_TPU_MESH=off is the single-device escape hatch):
    # contiguous doc shards dispatch independently, and with a rim
    # consumer profile only that profile's rim blocks leave the mesh
    shape = mesh2d.resolve_mesh_shape()
    rim_blocks = (
        mesh2d.RIM_PROFILES.get(profile)
        if (with_rim and shape is not None) else None
    )
    if shape is not None:
        bounds = mesh2d.doc_shard_bounds(batch.n_docs, shape[0])
    else:
        bounds = [(0, batch.n_docs)]
    pending = []
    if prepacked is not None:
        planned = prepacked
    else:
        planned = []
        for pack in plan_packs(items):
            if len(pack) < 2:
                continue  # a singleton pack gains nothing over per-file
            try:
                packed, spec = _pack_cached([c for _, c in pack])
            except PackIncompatible as e:
                log.info("pack of %d files fell back to per-file: %s",
                         len(pack), e)
                continue
            planned.append((pack, packed, spec))
    if not planned:
        return PackPending([], set(), with_rim, rim_blocks)
    columns = (
        mesh2d.assign_columns(
            [len(p.compiled.rules) for _pk, p, _sp in planned], shape[1]
        )
        if shape is not None else None
    )
    # every pack's evaluator is built BEFORE the shard loop so that
    # loop is pure dispatch: shards OUTER, packs INNER, consuming the
    # bounded shard prefetcher — shard s+1's host prep (take_docs +
    # bucket columnarization, on the prefetch thread) overlaps shard
    # s's in-flight device programs
    for pi, (pack, packed, spec) in enumerate(planned):
        # pack-slot occupancy: rule slots this pack fills against the
        # PACK_MAX_RULES ceiling packs close at (one executable traces
        # every packed rule, so unused slots are pure headroom, not
        # padding — but the fill fraction says how fused dispatch is)
        EFFICIENCY_COUNTERS["pack_rule_slots_used"] += len(
            packed.compiled.rules
        )
        EFFICIENCY_COUNTERS["pack_rule_slots_capacity"] += PACK_MAX_RULES
        if shape is not None:
            ev = mesh2d.MeshSweepEvaluator(
                packed.compiled,
                rim_spec=spec if with_rim else None,
                shape=shape, column=columns[pi],
                rim_blocks=rim_blocks,
                ship_statuses=rim_blocks is None,
            )
        else:
            ev = ShardedBatchEvaluator(
                packed.compiled, rim_spec=spec if with_rim else None
            )
        pending.append((pack, packed, spec, ev, []))
    host_docs = set()
    if len(bounds) > 1:
        from ..parallel.ingest import ShardPrefetcher

        shard_iter = iter(ShardPrefetcher(
            batch, bounds, NODE_BUCKETS_EXTENDED
        ))
    else:
        def _inline_shards():
            for s, (lo, hi) in enumerate(bounds):
                sub_batch = mesh2d.take_docs(batch, lo, hi)
                groups, oversize = split_batch_by_size(
                    sub_batch, NODE_BUCKETS_EXTENDED
                )
                yield s, lo, groups, oversize

        shard_iter = _inline_shards()
    # a failed bucket dispatch keeps its sub-batch (handle None) so
    # collect_packs can walk the degradation ladder: per-file dispatch
    # for just that (doc shard, bucket), then the host oracle — scoped
    # to THAT shard's docs, other shards stand
    for s, lo, groups, oversize in shard_iter:
        host_docs.update(int(i) + lo for i in oversize)
        for pack, packed, spec, ev, handles in pending:
            for sub, idx in groups:
                gidx = idx + lo
                try:
                    maybe_fail("dispatch")
                    handle = (
                        ev.dispatch(sub, shard=s) if shape is not None
                        else ev.dispatch(sub)
                    )
                    handles.append((gidx, sub, handle))
                except Exception as e:
                    log.warning(
                        "packed dispatch failed for a %d-doc bucket "
                        "of shard %d (%s); will retry per-file at "
                        "collect", len(idx), s, e,
                    )
                    FAULT_COUNTERS["dispatch_fallbacks"] += 1
                    handles.append((gidx, sub, None))
    used = EFFICIENCY_COUNTERS["pack_rule_slots_used"]
    cap = EFFICIENCY_COUNTERS["pack_rule_slots_capacity"]
    if cap:
        _TELEMETRY.set_gauge(
            "efficiency.pack_slot_utilization", used / cap
        )
    return PackPending(pending, host_docs, with_rim, rim_blocks)


def collect_packs(pp: PackPending, batch) -> dict:
    """Collect half: block on the PackPending handles and slice results
    back per file. Returns {file_idx: (statuses (D, R_f) int8, unsure
    (D, R_f) bool, host_docs set, rim)} through the pack's segment map;
    files left out of the result fall back to the per-file path
    unchanged.

    `rim` is the file's slice of the device-reduced results plane —
    (name_statuses (D, G_f), name_unsure (D, G_f), doc_status (D,),
    any_fail (D,), any_unsure (D,), name_last (D, G_f), group names) —
    or None when the vectorized rim is disabled (GUARD_TPU_VECTOR_RIM
    =0): the reductions ride the same dispatch, so per-(pack, bucket)
    only the blocks pass A actually consumes cross the device
    boundary alongside the status matrix."""
    if not pp.pending:
        return {}
    with _span("collect", {"packs": len(pp.pending)}):
        return _collect_packs_inner(pp, batch)


def _collect_packs_inner(pp: PackPending, batch) -> dict:
    import numpy as np

    from ..parallel.mesh import ShardedBatchEvaluator

    results: dict = {}
    with_rim = pp.with_rim
    host_docs = pp.host_docs
    # mesh rim-only protocol: pp.rim_blocks names the blocks that
    # actually shipped — the (D, R) scratch below only receives data
    # on degradation rungs (full per-file recovery), so per-file
    # statuses/unsure return as None and consumers read the rim
    rim_only = pp.rim_blocks is not None
    for pack, packed, spec, ev, handles in pp.pending:
        n_rules = len(packed.compiled.rules)
        statuses = np.full((batch.n_docs, n_rules), SKIP, np.int8)
        unsure = np.zeros((batch.n_docs, n_rules), bool)
        host_extra: dict = {}
        recovered = []  # bucket idx arrays that lost their rim blocks
        rim = None
        if with_rim:
            rim = (
                np.full((batch.n_docs, spec.n_groups), SKIP, np.int8),
                np.zeros((batch.n_docs, spec.n_groups), bool),
                np.full((batch.n_docs, spec.n_files), SKIP, np.int8),
                np.zeros((batch.n_docs, spec.n_files), bool),
                np.zeros((batch.n_docs, spec.n_files), bool),
                np.full((batch.n_docs, spec.n_groups), SKIP, np.int8),
            )
        for idx, sub, handle in handles:
            if handle is not None:
                try:
                    maybe_fail("collect")
                    collected = bounded_call(ev.collect, handle)
                except Exception as e:
                    log.warning(
                        "packed collect failed for a %d-doc bucket "
                        "(%s); retrying per-file", len(idx), e,
                    )
                    FAULT_COUNTERS["dispatch_fallbacks"] += 1
                    handle = None
                else:
                    if collected[0] is not None:
                        statuses[idx] = collected[0]
                    if collected[1] is not None:
                        unsure[idx] = collected[1]
                    if with_rim:
                        for b, block in enumerate(collected[2]):
                            # None = a block the rim profile did not
                            # ship; its scratch rows stay SKIP-filled
                            # and are never exposed below
                            if block is not None:
                                rim[b][idx] = block
                    continue
            # degradation rung 2: per-file dispatch for just this
            # bucket; a file that still fails lands on the host oracle
            # (rung 3) for these docs only
            for k, (fi, c) in enumerate(pack):
                seg = packed.segment(k)
                try:
                    ev2 = ShardedBatchEvaluator(c)
                    st, un = bounded_call(
                        lambda: ev2.collect(ev2.dispatch(sub))
                    )[:2]
                except Exception as e:
                    log.warning(
                        "per-file retry failed for file %d (%s); "
                        "%d docs fall back to the host oracle",
                        fi, e, len(idx),
                    )
                    FAULT_COUNTERS["oracle_fallbacks"] += 1
                    host_extra.setdefault(fi, set()).update(
                        int(i) for i in idx
                    )
                    continue
                cols = np.arange(seg.start, seg.stop)
                statuses[np.ix_(idx, cols)] = st
                if un is not None:
                    unsure[np.ix_(idx, cols)] = un
            if with_rim:
                recovered.append(idx)
        if with_rim and recovered:
            # recompute the lost rim blocks host-side from the
            # recovered status rows (same reduction the device ran)
            from .kernels import rim_reduce

            for idx in recovered:
                blocks = rim_reduce(
                    statuses[idx], unsure[idx],
                    spec.group_ids, spec.file_ids, spec.last_ids,
                    spec.n_groups, spec.n_files,
                )
                for b, block in enumerate(blocks):
                    rim[b][idx] = np.asarray(block)
        for k, (fi, _c) in enumerate(pack):
            seg = packed.segment(k)
            rim_f = None
            if with_rim:
                gsl = spec.file_slice(k)
                blocks_f = (
                    rim[0][:, gsl], rim[1][:, gsl], rim[2][:, k],
                    rim[3][:, k], rim[4][:, k], rim[5][:, gsl],
                )
                if rim_only:
                    # expose ONLY the shipped blocks: degradation rungs
                    # recover every block for their rows, but the other
                    # rows of an unshipped block are SKIP scratch
                    blocks_f = tuple(
                        b if i in pp.rim_blocks else None
                        for i, b in enumerate(blocks_f)
                    )
                rim_f = blocks_f + (spec.file_group_names[k],)
            results[fi] = (
                None if rim_only else statuses[:, seg],
                None if rim_only else unsure[:, seg],
                set(host_docs) | host_extra.get(fi, set()), rim_f,
            )
    return results


def _evaluate_packs(items, batch, after_dispatch=None, with_rim=None,
                    prepacked=None, profile=None) -> dict:
    """dispatch_packs + collect_packs fused: every (pack, bucket group)
    dispatches before anything collects, so host columnarization of the
    next bucket/pack overlaps device execution of the previous one.
    `after_dispatch` (the legacy double-buffering hook: commands/
    sweep.py's serial path encodes doc chunk k+1 in it while the device
    executes chunk k) runs once everything is in flight, before the
    first collect."""
    pp = dispatch_packs(items, batch, with_rim, prepacked=prepacked,
                        profile=profile)
    if after_dispatch is not None:
        after_dispatch()
    return collect_packs(pp, batch)

# spawn-pool state: each worker parses the rule files once (initializer)
# and never imports jax — oracle reruns are pure-Python CPU work
_WORKER_RULES: dict = {}

# reruns below this count stay inline (spawn + import cost dominates)
_POOL_MIN_JOBS = 48


# single copy of the raw-JSON sniff: both backends must agree on raw
# eligibility (the import is jax-free — this module defers every jax
# import into tpu_validate)
from ..commands.validate import _looks_json  # noqa: E402


def _oracle_pool_init(rule_texts) -> None:

    os.environ["JAX_PLATFORMS"] = "cpu"
    global _WORKER_RULES
    from ..core.parser import parse_rules_file

    _WORKER_RULES = {}
    for key, name, text in rule_texts:
        _WORKER_RULES[key] = parse_rules_file(text, name)


def _oracle_job(args):
    """One oracle rerun in a worker process: returns
    (doc_key, status_value, report, {rule: status_value}, error)."""
    rules_key, doc_key, doc_name, doc_content = args
    rf = _WORKER_RULES[rules_key]
    try:
        from ..core.loader import load_document

        doc = load_document(doc_content, doc_name)
        scope = RootScope(rf, doc)
        status = eval_rules_file(rf, scope, doc_name)
    except GuardError as e:
        return (doc_key, None, None, None, str(e))
    root = scope.reset_recorder().extract()
    report = simplified_report_from_root(root, doc_name)
    statuses = {
        n: s.value for n, s in rule_statuses_from_root(root).items()
    }
    return (doc_key, status.value, report, statuses, None)


def _run_oracle_jobs(rules_key, rule_file, jobs, workers: int) -> dict:
    """Fan the oracle reruns over a spawn pool (fork would inherit the
    initialized JAX runtime; spawn workers import only the pure-Python
    core). Returns {doc_key: job result}. The fail-rerun design makes
    fail-heavy workloads oracle-bound — this turns that bound from one
    core into all of them."""
    import multiprocessing as mp

    ctx = mp.get_context("spawn")
    results = {}
    with ctx.Pool(
        processes=workers,
        initializer=_oracle_pool_init,
        initargs=([(rules_key, rule_file.name, rule_file.content)],),
    ) as pool:
        for res in pool.imap_unordered(_oracle_job, jobs, chunksize=8):
            results[res[0]] = res
    return results


def _honor_platform_env() -> None:
    """`JAX_PLATFORMS=cpu` in the environment is NOT reliably honored
    by plugin discovery (a wedged TPU tunnel can hang device init even
    then); only `jax.config.update` before the first device query is.
    Mirror the env var programmatically so CLI subprocesses with
    JAX_PLATFORMS=cpu never touch the TPU plugin."""

    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
    _setup_compile_cache()


_cache_configured = False


def _setup_compile_cache() -> None:
    """Opt-in persistent XLA compilation cache
    (`GUARD_TPU_JAX_CACHE=<dir>`): with the literals-as-inputs kernels
    the trace for a (rule-file structure, bucket shape) is
    corpus-independent, so its compiled executable is stable across
    PROCESSES too — a warm CLI start skips XLA compilation entirely
    (tracing still runs; in-process reuse via
    parallel/mesh._shared_evaluator_fns skips both)."""
    global _cache_configured
    if _cache_configured:
        return

    path = os.environ.get("GUARD_TPU_JAX_CACHE", "").strip()
    if path and path != "0":
        import jax

        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # guard workloads compile many small executables; cache all
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    _cache_configured = True


def rim_masks(any_fail, any_unsure, host_mask, has_host_rules: bool,
              rich_mode: bool, statuses_only: bool,
              show_rich: bool = False):
    """Pass A as whole-corpus boolean arrays — the scalar per-doc
    conditionals of the fail-rerun design expressed once as numpy mask
    arithmetic over the rim blocks (kernels.rim_reduce):

      need_oracle    — docs whose answer needs an oracle visit: host
          rules present, kernel-flagged unsure, oversized/host docs,
          rich output, or (unless --statuses-only) a device FAIL;
      needs_statuses — the subset where statuses themselves are missing
          (host rules / unsure / host docs): the native statuses
          prefilter applies only there (or under --statuses-only);
      materialize    — docs whose per-rule dict must be BUILT at all:
          the oracle set plus device-FAIL docs (their report lists
          failing names even in --statuses-only) plus everything when
          the summary shows pass/skip rows (`show_rich`). Docs outside
          this mask settle in-array: report/console/JUnit come from the
          per-unique-status-row cache, no per-doc dict exists.
    """
    import numpy as np

    base = bool(has_host_rules) or bool(rich_mode)
    need_oracle = any_unsure | host_mask
    if base:
        need_oracle = need_oracle | np.True_
    if not statuses_only:
        need_oracle = need_oracle | any_fail
    needs_statuses = any_unsure | host_mask
    if has_host_rules:
        needs_statuses = needs_statuses | np.True_
    materialize = need_oracle | any_fail
    if show_rich:
        materialize = materialize | np.True_
    return need_oracle, needs_statuses, materialize


def _materialize_row(name_row, unsure_row, names):
    """One doc's (rule_statuses dict, unsure_rules set) from its rim
    row — same first-occurrence key order as the scalar per-rule walk
    (the summary table prints declaration order)."""
    rule_statuses = {}
    unsure_rules = set()
    for g, name in enumerate(names):
        rule_statuses[name] = _STATUS[int(name_row[g])]
        if unsure_row is not None and bool(unsure_row[g]):
            unsure_rules.add(name)
    return rule_statuses, unsure_rules


def _settled_template(name_row, names):
    """Everything shared by every doc with this status row — the
    status-list report fields (the same construction the scalar pass B
    performs per doc) plus the rule_statuses dict the console summary
    reads. The bulk-materialization path builds this once per UNIQUE
    row (an all-PASS corpus has exactly one) and per-doc reports are
    thin dicts around the shared lists."""
    rule_statuses, _ = _materialize_row(name_row, None, names)
    vals = list(rule_statuses.values())
    if Status.FAIL in vals:
        status = Status.FAIL
    elif Status.PASS in vals:
        status = Status.PASS
    else:
        status = Status.SKIP
    fields = {
        "status": status.value,
        "not_compliant": [
            {
                "Rule": {
                    "name": n,
                    "metadata": {},
                    "messages": {
                        "custom_message": None,
                        "error_message": None,
                    },
                    "checks": [],
                }
            }
            for n, s in sorted(rule_statuses.items())
            if s == Status.FAIL
        ],
        "not_applicable": sorted(
            n for n, s in rule_statuses.items() if s == Status.SKIP
        ),
        "compliant": sorted(
            n for n, s in rule_statuses.items() if s == Status.PASS
        ),
    }
    return fields, rule_statuses, status


def _docs_for(data_files, quarantined):
    """Python document trees, built LAZILY (DataFile.path_value): on
    all-JSON corpora the native encoder, device kernels and native
    oracle run entirely from raw content, and the eager per-doc tree
    build (~40% of all-lowered sweep time, measured round 3) is paid
    only by the docs something actually walks. Quarantined docs stand
    in as `null` so batch geometry stays aligned."""
    if quarantined:
        from ..core.values import PV
        from ..core.values import Path as VPath

        return [
            PV.null(VPath.root()) if di in quarantined else df.path_value
            for di, df in enumerate(data_files)
        ]
    return [df.path_value for df in data_files]


def _encode_docs(validate, data_files, writer: Writer):
    """Encode front half of the tpu path: quarantine-aware encode,
    parallel-ingest or inline (native/Python) encode. Returns (batch,
    interner, quarantined, max_df) — `quarantined` maps doc index to
    its failure record (empty outside --max-doc-failures mode)."""
    # failure plane: with --max-doc-failures set, a doc that fails to
    # parse/encode is QUARANTINED — structured error record, `null`
    # stand-in in the batch, excluded from every report pass — instead
    # of aborting the whole run. `quarantined` maps doc index -> record.
    quarantined: dict = {}
    max_df = getattr(validate, "max_doc_failures", None)
    q_mode = max_df is not None and not validate.input_params

    batch = interner = None
    if q_mode:
        from .encoder import encode_chunk_texts

        (batch, interner, q_order, q_msgs, _q_err, q_records,
         q_pvs) = encode_chunk_texts(
            [df.name for df in data_files],
            [df.content for df in data_files],
        )
        quarantined = dict(zip(q_order, q_records))
        for m in q_msgs:
            writer.writeln_err(m)
        if q_pvs is not None:
            for df, pv in zip(data_files, q_pvs):
                if pv is not None and getattr(df, "_pv", None) is None:
                    df._pv = pv
    # parallel ingest plane (parallel/ingest.py): with workers >= 2 the
    # document list splits into contiguous shards, each encoded in an
    # ingest worker process with a private interner, merged through an
    # id remap — statuses and reports are invariant under intern-id
    # labels, so output stays byte-identical to the serial encode.
    # Payload/stdin sessions and --input-parameters merges keep the
    # inline path (merged trees exist only in this process).
    from ..parallel.ingest import resolve_ingest_workers

    ingest_workers = resolve_ingest_workers(
        getattr(validate, "ingest_workers", None)
    )
    if (
        batch is None
        and ingest_workers >= 2
        and len(data_files) >= 2
        and not validate.payload
        and not validate.input_params
    ):
        from ..parallel.ingest import parallel_encode_documents

        enc = parallel_encode_documents(
            [df.name for df in data_files],
            [df.content for df in data_files],
            ingest_workers,
        )
        if enc is not None:
            batch, interner = enc
    if batch is None:
        # inline (non-worker) encode: one span covers whichever encoder
        # wins; the parallel path above records per-worker spans instead
        with _span("encode", {"docs": len(data_files)}):
            if all(_looks_json(df.content) for df in data_files):
                # JSON corpus: native C++ data loader (native/encoder.cpp)
                from .native_encoder import (
                    encode_json_batch_native,
                    native_available,
                )

                if native_available():
                    try:
                        batch, interner, err = encode_json_batch_native(
                            [df.content for df in data_files]
                        )
                        if err is not None:
                            batch = interner = None
                    except RuntimeError:
                        pass
            if batch is None:
                batch, interner = encode_batch(
                    _docs_for(data_files, quarantined)
                )
    return batch, interner, quarantined, max_df


def _lower_rules(validate, rule_files, batch, interner, data_files,
                 quarantined):
    """Lowering front half: every rule file compiles UP-FRONT (the
    pack planner needs the whole registry before the first dispatch),
    via the plan layer when enabled. Files with precomputable function
    lets (ops/fnvars.py) re-encode the batch with per-doc function
    results BEFORE compile (result strings must intern under the bit
    tables) — those keep a per-file batch and are excluded from packing
    by ir.pack_compatible. Returns (prep, plan, interner) with
    prep = [(rule_file, rbatch, compiled)]."""
    from .fnvars import precompute_fn_values, precomputable_fn_vars
    from .plan import get_plan, plan_cache_enabled, relocate_batch

    prep = []
    plan = None
    if plan_cache_enabled(getattr(validate, "plan_cache", True)):
        # plan layer (ops/plan.py): reuse the canonically lowered +
        # packed program (in-process memo or disk artifact) and move
        # the batch into its id namespace — warm calls skip
        # compile_rules_file and pack_compiled entirely
        verify = getattr(validate, "verify_plans", True)
        plan = get_plan(rule_files, verify=verify)
        relocate_batch(plan, batch, interner, verify=verify)
        interner = plan.interner
        for fi, rule_file in enumerate(rule_files):
            rbatch = batch
            compiled = plan.compiled[fi]
            if compiled is None:
                # fn-var slow path, per batch as before — but against
                # the plan interner, so ids stay in one namespace
                with _span("lower_compile", {"files": 1, "mode": "fnvar"}):
                    docs = _docs_for(data_files, quarantined)
                    fn_vars, fn_vals, fn_err = precompute_fn_values(
                        rule_file.rules, docs
                    )
                    rbatch, _ = encode_batch(
                        docs, interner, fn_values=fn_vals,
                        fn_var_order=fn_vars,
                    )
                    if fn_err:
                        rbatch.num_exotic[sorted(fn_err)] = True
                    compiled = compile_rules_file(rule_file.rules, interner)
            prep.append((rule_file, rbatch, compiled))
    else:
        with _span("lower_compile", {"files": len(rule_files)}):
            for rule_file in rule_files:
                rbatch = batch
                if precomputable_fn_vars(rule_file.rules):
                    docs = _docs_for(data_files, quarantined)
                    fn_vars, fn_vals, fn_err = precompute_fn_values(
                        rule_file.rules, docs
                    )
                    rbatch, _ = encode_batch(
                        docs, interner, fn_values=fn_vals, fn_var_order=fn_vars
                    )
                    if fn_err:
                        # a function raised on these docs: route them to the
                        # oracle, which reproduces the error path
                        rbatch.num_exotic[sorted(fn_err)] = True
                compiled = compile_rules_file(rule_file.rules, interner)
                n_dev, n_host = len(compiled.rules), len(compiled.host_rules)
                log.info(
                    "%s: %d/%d rules lowered to device kernels (%d host-fallback)",
                    rule_file.name, n_dev, n_dev + n_host, n_host,
                )
                prep.append((rule_file, rbatch, compiled))
    return prep, plan, interner


def _eval_packed(validate, prep, batch, plan):
    """Fused multi-rule-file dispatch: compatible files (shared batch,
    no per-file fn re-encode) evaluate as packed executables, one
    device dispatch per (pack, bucket) instead of one per file.
    Returns (packed_results, rim_on)."""
    from .ir import pack_compatible

    pack_enabled = (
        getattr(validate, "pack_rules", True)
        and os.environ.get("GUARD_TPU_PACK", "1") != "0"
    )
    rim_on = vector_rim_enabled() and getattr(validate, "vector_rim", True)
    packed_results: dict = {}
    if pack_enabled:
        packed_results = _evaluate_packs(
            [
                (fi, c)
                for fi, (_rf, rb, c) in enumerate(prep)
                if rb is batch and pack_compatible(c) is None
            ],
            batch,
            with_rim=rim_on,
            prepacked=plan.prepacked_items() if plan is not None else None,
            # report-path rim profile: on the 2-D mesh only the blocks
            # _report_files' pass A reads (0-4 + names) leave the mesh
            profile="validate",
        )
    return packed_results, rim_on


class _ReportAcc:
    """Per-request report accumulators threaded through _report_files —
    request-scoped so the coalesced serve path (tpu_validate_multi) can
    run one report pass per caller over a shared device evaluation."""

    __slots__ = ("errors", "had_fail", "all_reports", "junit_suites")

    def __init__(self, data_files, quarantined):
        self.errors = 0
        self.had_fail = False
        self.all_reports: List[dict] = []
        self.junit_suites = {
            df.name: []
            for di, df in enumerate(data_files)
            if di not in quarantined
        }


# -- incremental validation plane (cache/results.py) ------------------
def _result_cache_setup(validate, rule_files, data_files):
    """Partition one validate request against the result cache: per-doc
    content-addressed lookups BEFORE encode. Returns None when the
    layer is off (--no-result-cache / GUARD_TPU_RESULT_CACHE=0, or
    non-file inputs), else the ctx dict threaded through _report_files:
    `cached` maps original doc index -> per-rule-file replay fragments,
    `delta_idx` the docs that must encode+dispatch, `keys` the
    store-back addresses, `capture`/`skip_store` filled during pass B,
    and `fault_snap` the failure-plane level at partition time (a run
    that degraded anywhere is never written back)."""
    from ..cache import results as rcache

    if not rcache.result_cache_enabled(
        getattr(validate, "result_cache", True)
    ):
        return None
    if validate.payload or validate.input_params:
        # merged / stdin documents are not content-addressable files
        return None
    from .plan import plan_digest

    cfg = rcache.config_hash(
        mode="validate",
        output_format=validate.output_format,
        show_summary=list(validate.show_summary),
        structured=bool(validate.structured),
        verbose=bool(validate.verbose),
        print_json=bool(validate.print_json),
        statuses_only=bool(getattr(validate, "statuses_only", False)),
    )
    pdig = plan_digest(rule_files)
    n_files = len(rule_files)
    cached: dict = {}
    keys: dict = {}
    delta_idx: list = []
    for odi, df in enumerate(data_files):
        key = rcache.result_key(pdig, rcache.doc_digest(df.content), cfg)
        keys[odi] = key
        # name guard: validate reports EMBED the doc name (the key
        # deliberately does not), so a same-content doc under a new
        # name is a plain miss and recomputes under its own name
        payload = rcache.load_entry(key, name=df.name)
        frags = payload.get("files") if payload else None
        if (
            isinstance(frags, list)
            and len(frags) == n_files
            and all(
                isinstance(f, dict)
                and isinstance(f.get("report"), dict)
                and isinstance(f.get("rs"), dict)
                and f.get("ds") in _STATUS_VALUES
                for f in frags
            )
        ):
            cached[odi] = frags
        else:
            delta_idx.append(odi)
    rcache.set_delta_gauge(len(delta_idx), len(data_files))
    return {
        "full_files": list(data_files),
        "cached": cached,
        "delta_idx": delta_idx,
        "keys": keys,
        "capture": {},
        "skip_store": set(),
        "fault_snap": int(sum(FAULT_COUNTERS.values())),
    }


def _replay_cached_doc(validate, writer, acc, data_file, rule_file,
                       frag) -> None:
    """Emit one (doc, rule file) result from a cached fragment through
    the SAME lazy report path a fresh evaluation takes — console chain,
    report list, junit accumulation — so every output mode reconstructs
    byte-identically. Settled docs (non-structured runs) also replay
    through here: their extra report/junit accumulation is harmless
    because non-structured runs never emit those accumulators."""
    from ..commands.reporters.aware import console_chain
    from ..commands.reporters.junit import (
        JunitTestCase,
        failure_info_from_report,
    )

    report = frag["report"]
    if report.get("name") != data_file.name:
        # portable entry replayed under a different doc name: rebuild
        # with the live name, preserving key order exactly (structured
        # output serializes reports in insertion order)
        report = {
            k: (data_file.name if k == "name" else v)
            for k, v in report.items()
        }
    rule_statuses = {n: Status(v) for n, v in frag["rs"].items()}
    doc_status = Status(frag["ds"])
    if doc_status == Status.FAIL:
        acc.had_fail = True
    acc.all_reports.append(report)
    fname, fmsgs = failure_info_from_report(report)
    acc.junit_suites[data_file.name].append(
        JunitTestCase(
            name=rule_file.name,
            status=doc_status,
            failure_name=fname if doc_status == Status.FAIL else None,
            failure_messages=fmsgs if doc_status == Status.FAIL else None,
        )
    )
    if not validate.structured:
        console_chain(
            writer, data_file.name, data_file.content, data_file,
            rule_file.name, doc_status, rule_statuses, report,
            validate.show_summary, validate.output_format,
        )


def _result_cache_store(rule_files, cache_ctx) -> None:
    """Write back the delta docs' captured fragments. Never stored:
    docs the run's degradation paths touched (quarantine, host-oracle
    fallback, oracle errors — the `skip_store` set), and the whole run
    when ANY fault/recovery counter moved since partition time.
    Deterministic oracle passes (kernel-unsure reruns, rich-report
    fail reruns) DO cache."""
    from ..cache import results as rcache

    if int(sum(FAULT_COUNTERS.values())) != cache_ctx["fault_snap"]:
        return
    n_files = len(rule_files)
    for odi in cache_ctx["delta_idx"]:
        if odi in cache_ctx["skip_store"]:
            continue
        frags = cache_ctx["capture"].get(odi)
        if frags is None or len(frags) != n_files:
            continue
        df = cache_ctx["full_files"][odi]
        # portability probe: when the doc name appears nowhere in the
        # fragments except each report's top-level name field, a
        # same-content doc under ANY name can replay this entry with
        # its own name substituted (duplicate templates are common in
        # real corpora); an embedded name anywhere else locks the
        # entry to this exact name (conservative substring check)
        scrubbed = [
            {
                **f,
                "report": {
                    k: v for k, v in f["report"].items() if k != "name"
                },
            }
            for f in frags
        ]
        portable = df.name not in json.dumps(scrubbed)
        rcache.store_entry(
            cache_ctx["keys"][odi],
            {"name": df.name, "files": frags, "portable": portable},
        )


def _emit_delta_stats(validate, writer, cache_ctx) -> None:
    """--delta-stats: one stderr line with the partition outcome
    (stdout stays byte-identical to the cache-off run)."""
    if cache_ctx is None or not getattr(validate, "delta_stats", False):
        return
    hits = len(cache_ctx["cached"])
    delta = len(cache_ctx["delta_idx"])
    writer.writeln_err(
        f"result-cache: {hits}/{hits + delta} docs cached, "
        f"{delta} dispatched"
    )


def _report_files(validate, file_iter, data_files, quarantined, writer,
                  acc: _ReportAcc, rim_on: bool, cache_ctx=None) -> None:
    """Report half of the tpu path: pass A (which docs need the
    oracle), the pooled/native/inline oracle reruns, and pass B (report
    emission) — one iteration per rule file. `file_iter` yields
    (fi, rule_file, compiled, statuses, unsure, host_docs, rim); the
    sequential path yields lazily (dispatch of file k+1 overlaps the
    report pass of file k exactly as before the eval/report split), the
    coalesced serve path yields per-request doc-segment slices of a
    shared evaluation.

    With a `cache_ctx` (the incremental plane), `data_files` is the
    DELTA subset — pass A and the oracle fan-out stay delta-sized —
    while pass B walks the FULL original doc order, replaying cache
    hits between the fresh docs and capturing fresh fragments for the
    store-back."""
    from ..commands.reporters.aware import console_chain
    from ..commands.reporters.junit import JunitTestCase

    for fi, rule_file, compiled, statuses, unsure, host_docs, rim in file_iter:
        # native statuses oracle (native/oracle.cpp): the compiled-
        # engine prefilter. When the full record tree isn't required it
        # answers host-rule/unsure/oversized-doc statuses at native
        # speed, pre-filters which failing docs actually need the rich
        # rerun, and serves structured (non-verbose) reports directly
        # (eval_report is byte-equal to the Python oracle's
        # simplified_report_from_root — the corpus differential pins
        # it); only verbose/print-json need the Python record tree.
        rich_tree = validate.verbose or validate.print_json
        rich_mode = validate.structured or rich_tree
        native = None
        if not rich_tree:
            from .native_oracle import (
                NativeEvalError,
                NativeOracle,
                NativeUnsupported,
                overall_status,
            )

            try:
                native = NativeOracle(rule_file.rules)
            except NativeUnsupported:
                native = None
        guard_rule_names = [r.rule_name for r in rule_file.rules.guard_rules]

        def _merge_native(raw_statuses):
            """Same-name merge as the report layer (non-SKIP beats
            SKIP, FAIL dominates)."""
            merged = {}
            for name, s in zip(guard_rule_names, raw_statuses):
                st = _STATUS[s]
                prev = merged.get(name)
                if prev is None or (prev == Status.SKIP and st != Status.SKIP):
                    merged[name] = st
                elif st == Status.FAIL:
                    merged[name] = Status.FAIL
            return merged
        statuses_only = getattr(validate, "statuses_only", False)

        def _native_prefilter(data_file):
            """The native statuses prefilter for one doc: (merged
            statuses, overall) or None on decline. Shared by the scalar
            walk and the vectorized pass A."""
            raw = None
            raw_ok = not validate.input_params and _looks_json(
                data_file.content
            )
            if raw_ok:
                try:
                    raw = native.eval_raw_json(data_file.content)
                except (NativeUnsupported, NativeEvalError):
                    # e.g. flow-style YAML that sniffs as JSON, or a
                    # decline — the loaded-PV wire is authoritative
                    raw = None
            if raw is None:
                try:
                    raw = native.eval_doc(data_file.path_value)
                except (NativeUnsupported, NativeEvalError):
                    raw = None
            if raw is None:
                return None
            return (_merge_native(raw), _STATUS[overall_status(raw)])

        doc_infos: dict = {}
        oracle_dis = []
        native_declines = 0
        settled = None  # vectorized rim: (name_st, names, materialize mask)
        _sp_rim = _span_begin(
            "rim_reduce",
            {"docs": len(data_files), "file": fi,
             "mode": "vector" if rim_on else "scalar"},
        )
        if rim_on:
            # pass A, vectorized: whole-corpus mask arithmetic over the
            # rim blocks; per-doc dicts build ONLY for docs the masks
            # select (failures, unsure, host-fallback, rich output)
            import numpy as np

            D = len(data_files)
            if statuses is not None and rim is None:
                # per-file / fn-var path: same reductions, host-side
                from .ir import build_rim_spec
                from .kernels import rim_reduce

                spec = build_rim_spec([compiled.rules])
                blocks = rim_reduce(
                    statuses, unsure, spec.group_ids, spec.file_ids,
                    spec.last_ids, spec.n_groups, spec.n_files,
                )
                rim = (
                    blocks[0], blocks[1], blocks[2][:, 0],
                    blocks[3][:, 0], blocks[4][:, 0], blocks[5],
                    spec.file_group_names[0],
                )
            if rim is not None:
                name_st, name_un, _doc_st, any_fail, any_un = rim[:5]
                names = rim[6]
            else:
                name_st = np.zeros((D, 0), np.int8)
                name_un = None
                any_fail = np.zeros(D, bool)
                any_un = np.zeros(D, bool)
                names = []
            host_mask = np.zeros(D, bool)
            for hd in host_docs:
                if hd < D:
                    host_mask[hd] = True
            show_rich = bool(
                {"pass", "skip", "all"} & set(validate.show_summary)
            )
            need_oracle_v, needs_statuses_v, materialize_v = rim_masks(
                any_fail, any_un, host_mask, bool(compiled.host_rules),
                rich_mode, statuses_only, show_rich,
            )
            if quarantined:
                qmask = np.zeros(D, bool)
                qmask[list(quarantined)] = True
                need_oracle_v &= ~qmask
                materialize_v &= ~qmask
            prefilter_v = need_oracle_v & (
                needs_statuses_v | bool(statuses_only)
            )
            for di in np.nonzero(materialize_v)[0]:
                di = int(di)
                data_file = data_files[di]
                # device coverage for this doc: either the full status
                # matrix crossed (legacy) or the rim-only mesh collect
                # shipped the reduced blocks the row builds from
                if (statuses is not None or rim is not None) \
                        and not host_mask[di]:
                    rule_statuses, unsure_rules = _materialize_row(
                        name_st[di], None if name_un is None else name_un[di],
                        names,
                    )
                    doc_status = _STATUS[int(_doc_st[di])]
                else:
                    rule_statuses, unsure_rules = {}, set()
                    doc_status = Status.SKIP
                RIM_COUNTERS["docs_materialized"] += 1
                need_oracle = bool(need_oracle_v[di])
                native_statuses = None
                if need_oracle and native is not None and prefilter_v[di]:
                    native_statuses = _native_prefilter(data_file)
                    if native_statuses is not None:
                        if statuses_only or native_statuses[1] != Status.FAIL:
                            # statuses suffice: no rich rerun
                            need_oracle = False
                    else:
                        native_declines += 1
                doc_infos[di] = (
                    rule_statuses, unsure_rules, doc_status, native_statuses
                )
                if need_oracle:
                    oracle_dis.append(di)
            n_settled = int(D - materialize_v.sum())
            RIM_COUNTERS["docs_settled"] += n_settled
            settled = (name_st, names)
        else:
            # pass A, scalar (GUARD_TPU_VECTOR_RIM=0 escape hatch):
            # device statuses + which docs need the oracle, one
            # (doc, rule) pair at a time
            for di, data_file in enumerate(data_files):
                if di in quarantined:
                    continue
                rule_statuses = {}
                unsure_rules = set()
                doc_status = Status.SKIP
                if statuses is not None and di not in host_docs:
                    for ri, crule in enumerate(compiled.rules):
                        st = _STATUS[int(statuses[di, ri])]
                        # same-name merge as the report layer
                        # (report.rule_statuses_from_root): non-SKIP
                        # beats SKIP, FAIL dominates
                        prev = rule_statuses.get(crule.name)
                        if prev is None or (
                            prev == Status.SKIP and st != Status.SKIP
                        ):
                            rule_statuses[crule.name] = st
                        elif st == Status.FAIL:
                            rule_statuses[crule.name] = Status.FAIL
                        doc_status = doc_status.and_(st)
                        if unsure is not None and bool(unsure[di, ri]):
                            unsure_rules.add(crule.name)
                RIM_COUNTERS["docs_materialized"] += 1

                # host fallback for unlowerable rules + rich reporting:
                # rerun the oracle when anything failed (unless
                # --statuses-only), output needs detail, or the kernel
                # flagged a shape it can't decide
                need_oracle = (
                    bool(compiled.host_rules)
                    or bool(unsure_rules)
                    or di in host_docs
                    or rich_mode
                    or (
                        not statuses_only
                        and any(
                            s == Status.FAIL for s in rule_statuses.values()
                        )
                    )
                )
                # native statuses can settle the doc only when statuses
                # are what's missing (host rules / unsure / oversized
                # docs, or statuses-only mode); a device-decided FAIL
                # needing a rich report goes straight to the pass-B
                # report path instead of paying a redundant statuses
                # evaluation
                needs_statuses = (
                    bool(compiled.host_rules)
                    or bool(unsure_rules)
                    or di in host_docs
                )
                native_statuses = None
                if need_oracle and native is not None and (
                    needs_statuses or statuses_only
                ):
                    native_statuses = _native_prefilter(data_file)
                    if native_statuses is not None:
                        if statuses_only or native_statuses[1] != Status.FAIL:
                            # statuses suffice: no Python rerun
                            need_oracle = False
                    else:
                        native_declines += 1
                doc_infos[di] = (
                    rule_statuses, unsure_rules, doc_status, native_statuses
                )
                if need_oracle:
                    oracle_dis.append(di)
        _span_end(_sp_rim)

        # the oracle reruns are independent pure-Python work: fan them
        # over a process pool when there are enough to amortize spawn
        # (fail-heavy corpora would otherwise be bound by ONE core).
        # Workers rebuild documents from raw content, so merged
        # --input-params docs keep the inline path.
        pooled_results = {}
        if (
            (native is None or native_declines >= _POOL_MIN_JOBS)
            and len(oracle_dis) >= _POOL_MIN_JOBS
            and not validate.input_params
        ):

            workers = min(len(oracle_dis), os.cpu_count() or 1, 16)
            if workers > 1:
                jobs = [
                    (0, di, data_files[di].name, data_files[di].content)
                    for di in oracle_dis
                ]
                try:
                    with _span(
                        "oracle", {"jobs": len(jobs), "workers": workers}
                    ):
                        pooled_results = _run_oracle_jobs(
                            0, rule_file, jobs, workers
                        )
                except Exception as e:  # pool bootstrap can fail when
                    # an embedder's unguarded __main__ re-executes
                    # under spawn — the inline path is always safe
                    log.warning(
                        "oracle rerun pool unavailable (%s); "
                        "falling back to inline reruns", e,
                    )
                    pooled_results = {}

        # pass B: emit per-doc output in order, using pooled results
        # where available and the inline oracle otherwise. Docs the
        # vectorized pass A left un-materialized take the bulk path:
        # report fields and summary dict come from the shared
        # per-unique-status-row cache (one build per distinct row), and
        # JUnit/structured accumulation is skipped entirely — settled
        # docs only exist in non-structured runs.
        oracle_set = set(oracle_dis)
        row_cache: dict = {}
        full_files = data_files
        delta_pos = None
        if cache_ctx is not None:
            full_files = cache_ctx["full_files"]
            delta_pos = {
                odi: k for k, odi in enumerate(cache_ctx["delta_idx"])
            }
        _sp_report = _span_begin(
            "report", {"docs": len(full_files), "file": fi}
        )
        for odi, data_file in enumerate(full_files):
            if cache_ctx is None:
                di = odi
            else:
                frags = cache_ctx["cached"].get(odi)
                if frags is not None:
                    _replay_cached_doc(
                        validate, writer, acc, data_file, rule_file,
                        frags[fi],
                    )
                    continue
                di = delta_pos[odi]
            if di in quarantined:
                if cache_ctx is not None:
                    cache_ctx["skip_store"].add(odi)
                continue
            if settled is not None and di not in doc_infos:
                name_st, names = settled
                key = name_st[di].tobytes()
                cached = row_cache.get(key)
                if cached is None:
                    cached = row_cache[key] = _settled_template(
                        name_st[di], names
                    )
                fields, rule_statuses, doc_status = cached
                if doc_status == Status.FAIL:
                    acc.had_fail = True
                if not validate.structured:
                    report = {
                        "name": data_file.name,
                        "metadata": {},
                        **fields,
                    }
                    console_chain(
                        writer, data_file.name, data_file.content,
                        data_file, rule_file.name,
                        doc_status, rule_statuses, report,
                        validate.show_summary, validate.output_format,
                    )
                if cache_ctx is not None:
                    cache_ctx["capture"].setdefault(odi, []).append({
                        "report": {
                            "name": data_file.name,
                            "metadata": {},
                            **fields,
                        },
                        "rs": {
                            n: s.value for n, s in rule_statuses.items()
                        },
                        "ds": doc_status.value,
                    })
                continue
            (rule_statuses, unsure_rules, doc_status, native_statuses) = doc_infos[di]
            need_oracle = di in oracle_set
            if native_statuses is not None and not need_oracle:
                merged, n_doc_status = native_statuses
                # device/native parity net (kernel-flagged unsure rules
                # excepted — the oracle's answer is authoritative there)
                for rn, st in rule_statuses.items():
                    nst = merged.get(rn)
                    if nst is not None and nst != st and rn not in unsure_rules:
                        raise GuardError(
                            f"TPU/native status divergence for rule {rn} on "
                            f"{data_file.name}: tpu={st.value} native={nst.value}"
                        )
                rule_statuses = merged
                doc_status = n_doc_status
            report = {
                "name": data_file.name,
                "metadata": {},
                "status": doc_status.value,
                "not_compliant": [
                    {
                        "Rule": {
                            "name": n,
                            "metadata": {},
                            "messages": {
                                "custom_message": None,
                                "error_message": None,
                            },
                            "checks": [],
                        }
                    }
                    for n, s in sorted(rule_statuses.items())
                    if s == Status.FAIL
                ],
                "not_applicable": sorted(
                    n for n, s in rule_statuses.items() if s == Status.SKIP
                ),
                "compliant": sorted(
                    n for n, s in rule_statuses.items() if s == Status.PASS
                ),
            }
            if (
                need_oracle
                and native is not None
                and not rich_tree
                and di not in pooled_results
            ):
                # rich reports from the native engine, byte-identical to
                # simplified_report_from_root over the Python evaluator's
                # tree (tests/test_native_oracle.py corpus differential).
                # Structured non-verbose output rides this path too:
                # write_structured consumes the same report dicts.
                native_result = None
                raw_ok = not validate.input_params and _looks_json(
                    data_file.content
                )
                if raw_ok:
                    try:
                        native_result = native.eval_report_raw(
                            data_file.content, data_file.name
                        )
                    except (NativeUnsupported, NativeEvalError):
                        # possibly flow-style YAML sniffing as JSON —
                        # retry from the loaded tree before giving up
                        native_result = None
                if native_result is None:
                    try:
                        native_result = native.eval_report(
                            data_file.path_value, data_file.name
                        )
                    except (NativeUnsupported, NativeEvalError):
                        # declined or errored: the Python path below
                        # reproduces a genuine evaluation error
                        native_result = None
                if native_result is not None:
                    report, oracle_rule_statuses, oracle_status = native_result
                    for rn, st in rule_statuses.items():
                        ost = oracle_rule_statuses.get(rn)
                        if ost is not None and ost != st and rn not in unsure_rules:
                            raise GuardError(
                                f"TPU/native status divergence for rule {rn} on "
                                f"{data_file.name}: tpu={st.value} "
                                f"native={ost.value}"
                            )
                    rule_statuses = oracle_rule_statuses
                    doc_status = oracle_status
                    need_oracle = False
            if need_oracle:
                if di in pooled_results:
                    (_key, st_val, p_report, p_statuses, err) = pooled_results[di]
                    if err is not None:
                        writer.writeln_err(err)
                        acc.errors += 1
                        if cache_ctx is not None:
                            cache_ctx["skip_store"].add(odi)
                        continue
                    oracle_status = Status(st_val)
                    report = p_report
                    oracle_rule_statuses = {
                        n: Status(v) for n, v in p_statuses.items()
                    }
                else:
                    try:
                        maybe_fail("oracle", key=data_file.name)
                        scope = RootScope(rule_file.rules, data_file.path_value)
                        oracle_status = eval_rules_file(
                            rule_file.rules, scope, data_file.name
                        )
                    except GuardError as e:
                        writer.writeln_err(str(e))
                        acc.errors += 1
                        if cache_ctx is not None:
                            cache_ctx["skip_store"].add(odi)
                        continue
                    root_record = scope.reset_recorder().extract()
                    report = simplified_report_from_root(
                        root_record, data_file.name
                    )
                    oracle_rule_statuses = rule_statuses_from_root(root_record)
                # parity assertion: kernel statuses must agree with the
                # oracle (except results the kernel flagged unsure —
                # those use the oracle's answer by design)
                for rn, st in rule_statuses.items():
                    ost = oracle_rule_statuses.get(rn)
                    if ost is not None and ost != st and rn not in unsure_rules:
                        raise GuardError(
                            f"TPU/CPU status divergence for rule {rn} on "
                            f"{data_file.name}: tpu={st.value} cpu={ost.value}"
                        )
                rule_statuses = oracle_rule_statuses
                doc_status = oracle_status

            if doc_status == Status.FAIL:
                acc.had_fail = True
            acc.all_reports.append(report)
            from ..commands.reporters.junit import failure_info_from_report

            fname, fmsgs = failure_info_from_report(report)
            acc.junit_suites[data_file.name].append(
                JunitTestCase(
                    name=rule_file.name,
                    status=doc_status,
                    failure_name=fname if doc_status == Status.FAIL else None,
                    failure_messages=fmsgs if doc_status == Status.FAIL else None,
                )
            )

            if not validate.structured:
                console_chain(
                    writer, data_file.name, data_file.content,
                    data_file, rule_file.name,
                    doc_status, rule_statuses, report, validate.show_summary,
                    validate.output_format,
                )
            if cache_ctx is not None:
                # degradation-path docs never cache: host-oracle
                # fallbacks (oversized docs). Kernel-unsure reruns and
                # deliberate rich-report reruns DO cache — both are
                # deterministic oracle passes (the precision ladder /
                # the fail-rerun design), not degradations
                if di in host_docs:
                    cache_ctx["skip_store"].add(odi)
                cache_ctx["capture"].setdefault(odi, []).append({
                    "report": report,
                    "rs": {n: s.value for n, s in rule_statuses.items()},
                    "ds": doc_status.value,
                })
        _span_end(_sp_report)

        if native is not None:
            native.close()


def _finish_report(validate, acc: _ReportAcc, writer: Writer, quarantined,
                   max_df) -> int:
    """Structured-output emission + exit-code resolution over one
    request's accumulators."""
    from ..commands.validate import (
        ERROR_STATUS_CODE,
        FAILURE_STATUS_CODE,
        SUCCESS_STATUS_CODE,
    )
    from ..commands.reporters.junit import write_junit
    from ..commands.reporters.sarif import write_sarif
    from ..commands.reporters.structured import write_structured

    if validate.structured:
        if validate.output_format in ("json", "yaml"):
            write_structured(writer, acc.all_reports, validate.output_format)
        elif validate.output_format == "sarif":
            write_sarif(writer, acc.all_reports)
        elif validate.output_format == "junit":
            write_junit(writer, acc.junit_suites)

    if acc.errors > 0:
        return ERROR_STATUS_CODE
    if quarantined:
        FAULT_COUNTERS["quarantined_docs"] += len(quarantined)
        # negative limit = unlimited quarantine (degrade, never error)
        if max_df is not None and 0 <= max_df < len(quarantined):
            return ERROR_STATUS_CODE
    if acc.had_fail:
        return FAILURE_STATUS_CODE
    return SUCCESS_STATUS_CODE


def tpu_validate(validate, rule_files, data_files, writer: Writer) -> int:
    """Drop-in body for Validate.execute's evaluation loop."""
    _honor_platform_env()
    from ..commands.validate import SUCCESS_STATUS_CODE
    from ..parallel.mesh import ShardedBatchEvaluator

    if not data_files or not rule_files:
        return SUCCESS_STATUS_CODE

    # incremental plane: partition against the result cache BEFORE
    # encode — only the delta pays columnarization and dispatch
    cache_ctx = _result_cache_setup(validate, rule_files, data_files)
    delta_files = data_files
    if cache_ctx is not None:
        delta_files = [data_files[i] for i in cache_ctx["delta_idx"]]
        if not delta_files:
            # 100% warm: replay every doc, never touching encode/jax
            acc = _ReportAcc(data_files, {})
            for fi, rule_file in enumerate(rule_files):
                with _span("report", {"docs": len(data_files), "file": fi}):
                    for odi, df in enumerate(data_files):
                        _replay_cached_doc(
                            validate, writer, acc, df, rule_file,
                            cache_ctx["cached"][odi][fi],
                        )
            _emit_delta_stats(validate, writer, cache_ctx)
            return _finish_report(
                validate, acc, writer, {},
                getattr(validate, "max_doc_failures", None),
            )

    batch, interner, quarantined, max_df = _encode_docs(
        validate, delta_files, writer
    )
    prep, plan, interner = _lower_rules(
        validate, rule_files, batch, interner, delta_files, quarantined
    )
    packed_results, rim_on = _eval_packed(validate, prep, batch, plan)

    def _eval_iter():
        # lazy per-file dispatch: fused packs resolved above, the
        # per-file fallback dispatches inside iteration — ordering
        # (dispatch k, report k, dispatch k+1, ...) and the host_docs
        # carry-over across files are exactly the pre-split loop
        host_docs = set()
        for fi, (rule_file, rbatch, compiled) in enumerate(prep):
            statuses = unsure = rim = None
            if fi in packed_results:
                # the packed segment slice is bit-identical to the
                # per-file path (tests/test_rule_packing.py parity)
                statuses, unsure, host_docs, rim = packed_results[fi]
            elif compiled.rules:
                evaluator = ShardedBatchEvaluator(compiled)
                with _span("dispatch", {"mode": "per_file", "file": fi}):
                    statuses, unsure, host_docs = (
                        evaluator.evaluate_bucketed(rbatch)
                    )
            yield fi, rule_file, compiled, statuses, unsure, host_docs, rim

    if cache_ctx is None:
        acc = _ReportAcc(data_files, quarantined)
    else:
        # accumulators span the FULL corpus; quarantine indices are
        # delta-local, so translate for the junit-suite exclusion
        q_full = {
            cache_ctx["delta_idx"][di]: rec
            for di, rec in quarantined.items()
        }
        acc = _ReportAcc(data_files, q_full)
    _report_files(
        validate, _eval_iter(), delta_files, quarantined, writer, acc,
        rim_on, cache_ctx=cache_ctx,
    )
    if cache_ctx is not None:
        _result_cache_store(rule_files, cache_ctx)
        _emit_delta_stats(validate, writer, cache_ctx)
    return _finish_report(validate, acc, writer, quarantined, max_df)


def _segment_iter(file_results, start, end):
    """Slice a shared multi-request evaluation down to one request's
    doc segment. Status/unsure matrices are (docs x rules) and rim
    blocks doc-major, so everything slices on axis 0; host_docs shift
    to segment-local indices."""
    for fi, (rule_file, compiled, statuses, unsure, host_docs,
             rim) in enumerate(file_results):
        seg_st = None if statuses is None else statuses[start:end]
        seg_un = None if unsure is None else unsure[start:end]
        seg_hosts = {hd - start for hd in host_docs if start <= hd < end}
        seg_rim = None
        if rim is not None:
            seg_rim = tuple(
                None if b is None else b[start:end] for b in rim[:6]
            ) + (rim[6],)
        yield fi, rule_file, compiled, seg_st, seg_un, seg_hosts, seg_rim


def tpu_validate_multi(requests) -> list:
    """Coalesced serve path: evaluate SEVERAL validate requests that
    share one rule digest as ONE packed (docs x rules) device batch,
    then run each request's report pass over its own doc-segment slice.

    `requests` is a list of (validate, rule_files, data_files, writer)
    tuples whose rule files coalesce to the same plan digest and whose
    evaluation-relevant Validate fields are identical (the serve
    batcher guarantees both; see serve/batcher.py). Statuses are
    invariant under batch composition and intern-id labels (the plan
    layer's relocation contract, ops/plan.py), so each demuxed segment
    is byte-identical to running that request sequentially.

    Returns one entry per request: an int exit code, or the exception
    the request's REPORT phase raised (captured so one poisoned
    request cannot fail its batch peers). Shared-phase failures
    (encode/lower/dispatch) propagate to the caller, which re-fires
    each request solo.
    """
    import time

    _honor_platform_env()
    from ..commands.validate import ERROR_STATUS_CODE, SUCCESS_STATUS_CODE
    from ..parallel.mesh import ShardedBatchEvaluator

    t_dispatch = time.perf_counter()
    base_validate, rule_files, _bd, base_writer = requests[0]

    all_data = []
    segments = []
    for _v, _rf, data_files, _w in requests:
        start = len(all_data)
        all_data.extend(data_files)
        segments.append((start, len(all_data)))

    outcomes: list = [None] * len(requests)
    if not all_data or not rule_files:
        # mirror the sequential early return: no report pass runs, so
        # no structured doc is emitted for an empty corpus
        return [SUCCESS_STATUS_CODE] * len(requests)

    # shared phases (encode -> lower -> dispatch) run once under the
    # first request's settings; the batcher only coalesces requests
    # without --max-doc-failures, so quarantine mode stays off here
    batch, interner, quarantined, _mdf = _encode_docs(
        base_validate, all_data, base_writer
    )
    prep, plan, interner = _lower_rules(
        base_validate, rule_files, batch, interner, all_data, quarantined
    )
    packed_results, rim_on = _eval_packed(base_validate, prep, batch, plan)

    file_results = []
    host_docs = set()
    for fi, (rule_file, rbatch, compiled) in enumerate(prep):
        statuses = unsure = rim = None
        if fi in packed_results:
            statuses, unsure, host_docs, rim = packed_results[fi]
        elif compiled.rules:
            evaluator = ShardedBatchEvaluator(compiled)
            with _span(
                "dispatch",
                {"mode": "per_file", "file": fi, "requests": len(requests)},
            ):
                statuses, unsure, host_docs = (
                    evaluator.evaluate_bucketed(rbatch)
                )
        file_results.append(
            (rule_file, compiled, statuses, unsure, host_docs, rim)
        )
    # shared-phase (encode -> lower -> dispatch) latency per coalesced
    # batch: persistent so a registry reset never erases the serving
    # story; the front door's circuit breaker watches the same span
    # end-to-end (queue wait + formation + this) per digest
    _TELEMETRY.histogram(
        "serve_dispatch_seconds", persistent=True
    ).observe(time.perf_counter() - t_dispatch)

    for ri, (validate, _rf, data_files, writer) in enumerate(requests):
        start, end = segments[ri]
        if not data_files:
            outcomes[ri] = SUCCESS_STATUS_CODE
            continue
        try:
            acc = _ReportAcc(data_files, {})
            _report_files(
                validate,
                _segment_iter(file_results, start, end),
                data_files, {}, writer, acc, rim_on,
            )
            outcomes[ri] = _finish_report(validate, acc, writer, {}, None)
        except GuardError as exc:
            # parity with Validate.execute's tpu wrapper: GuardError
            # becomes a stderr line + error exit for THIS request only
            writer.writeln_err(str(exc))
            outcomes[ri] = ERROR_STATUS_CODE
        except Exception as exc:  # noqa: BLE001 — peer isolation
            outcomes[ri] = exc
    return outcomes
