"""Shared loader for the native shared libraries (native/*.so).

Both ctypes binding modules (native_encoder.py, native_oracle.py)
resolve the same `GUARD_TPU_NATIVE_DIR` root, cache one CDLL per
library, and drive the same build-script contract; this is the single
copy of that plumbing.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from pathlib import Path
from typing import Dict, Optional

NATIVE_DIR = Path(
    os.environ.get(
        "GUARD_TPU_NATIVE_DIR",
        Path(__file__).resolve().parent.parent.parent / "native",
    )
)

_libs: Dict[str, ctypes.CDLL] = {}


def so_path(so_name: str) -> Path:
    return NATIVE_DIR / so_name


def load_lib(so_name: str) -> Optional[ctypes.CDLL]:
    """CDLL for `so_name`, cached; None when not built."""
    if so_name in _libs:
        return _libs[so_name]
    path = so_path(so_name)
    if not path.exists():
        return None
    lib = ctypes.CDLL(str(path))
    _libs[so_name] = lib
    return lib


def build(so_name: str, build_script: str, force: bool = False) -> bool:
    """Compile `so_name` via its build script; True when present."""
    path = so_path(so_name)
    if path.exists() and not force:
        return True
    try:
        subprocess.run(
            ["sh", str(NATIVE_DIR / build_script)],
            check=True,
            capture_output=True,
        )
    except (subprocess.CalledProcessError, OSError):
        return False
    return path.exists()
