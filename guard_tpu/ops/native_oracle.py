"""ctypes bindings for the native C++ statuses oracle (native/oracle.cpp).

`NativeOracle` compiles a parsed `RulesFile` once (Python serializes the
AST, C++ deserializes) and then evaluates per-document rule statuses at
compiled-engine speed — the economics of the reference's Rust evaluator
(`/root/reference/guard/src/rules/eval.rs:1915`) that the pure-Python
oracle cannot match. Two outcomes per document:

  * a status list (0 PASS / 1 FAIL / 2 SKIP per guard rule, file order)
    guaranteed to match the Python oracle bit-for-bit (differential
    suite: tests/test_native_oracle.py), or
  * `NativeUnsupported` / `NativeEvalError` — the engine declined
    (construct outside its certain-parity subset) or hit the same
    evaluation error Python would raise; callers fall back to the
    Python oracle either way.

Falls back transparently when the shared library hasn't been built
(`native/build_oracle.sh`).
"""

from __future__ import annotations

import ctypes
import threading
from typing import List, Optional

from ..core.ast_serde import (
    Unserializable,
    doc_to_compact,
    doc_to_json,
    records_from_wire,
    rules_file_to_json,
)
from ..core.exprs import RulesFile
from ..core.values import PV
from ._native_lib import build, load_lib

#: stand-in lock for close() on partially-constructed instances
_NULL_LOCK = threading.Lock()

_SO_NAME = "libguard_oracle.so"
_BUILD_SCRIPT = "build_oracle.sh"

_configured = None


class NativeUnsupported(Exception):
    """The native engine declined (fall back to the Python oracle)."""


class NativeEvalError(Exception):
    """The native engine hit the evaluation error Python would raise."""


def _load() -> Optional[ctypes.CDLL]:
    global _configured
    if _configured is not None:
        return _configured
    lib = load_lib(_SO_NAME)
    if lib is None:
        return None
    lib.guard_oracle_compile.restype = ctypes.c_void_p
    lib.guard_oracle_compile.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_char_p),
    ]
    for fn_name in ("guard_oracle_eval", "guard_oracle_eval_raw"):
        fn = getattr(lib, fn_name)
        fn.restype = ctypes.c_int32
        fn.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_char_p),
        ]
    for fn_name in (
        "guard_oracle_eval_records",
        "guard_oracle_eval_report",
        "guard_oracle_eval_report_raw",
    ):
        fn = getattr(lib, fn_name)
        fn.restype = ctypes.c_void_p  # char* we free
        fn.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_char_p),
        ]
    lib.guard_oracle_free.argtypes = [ctypes.c_void_p]
    lib.guard_oracle_free.restype = None
    lib.guard_oracle_free_str.argtypes = [ctypes.c_void_p]
    lib.guard_oracle_free_str.restype = None
    _configured = lib
    return lib


def build_native(force: bool = False) -> bool:
    """Compile the shared library via native/build_oracle.sh."""
    return build(_SO_NAME, _BUILD_SCRIPT, force)


def native_available() -> bool:
    return _load() is not None


def _consume_err(lib, err: ctypes.c_char_p) -> str:
    msg = err.value.decode("utf-8", "replace") if err.value else "unknown"
    lib.guard_oracle_free_str(err)
    return msg


class NativeOracle:
    """One compiled rule file; evaluates per-doc statuses natively.

    Thread-safe via a per-thread handle pool: the engine's regex cache
    and pcre2 match data are per-handle and unsynchronized, so sharing
    ONE handle across threads was a documented footgun — instead each
    thread lazily compiles its own handle from the serialized AST (the
    constructor compiles the calling thread's eagerly, preserving the
    compile-failure-raises contract). A pipelined consumer stage can
    therefore hammer one NativeOracle from several threads
    (tests/test_native_oracle.py pins it)."""

    def __init__(self, rules_file: RulesFile):
        import threading

        lib = _load()
        if lib is None:
            raise NativeUnsupported(
                "native oracle not built; run native/build_oracle.sh"
            )
        self._lib = lib
        self.n_rules = len(rules_file.guard_rules)
        try:
            self._ast_json = rules_file_to_json(rules_file).encode("utf-8")
        except (Unserializable, RecursionError) as e:
            raise NativeUnsupported(str(e))
        self._pool_lock = threading.Lock()
        self._handles: dict = {}  # thread ident -> engine handle
        self._closed = False
        self._handle_for_thread()  # compile now: constructor must raise

    def _compile_handle(self):
        err = ctypes.c_char_p()
        handle = self._lib.guard_oracle_compile(
            self._ast_json, ctypes.byref(err)
        )
        if not handle:
            raise NativeUnsupported(_consume_err(self._lib, err))
        return handle

    def _handle_for_thread(self):
        """The calling thread's private engine handle (compiled on
        first use). Raises NativeUnsupported after close()."""
        import threading

        if self._closed:
            raise NativeUnsupported("oracle handle closed")
        tid = threading.get_ident()
        handle = self._handles.get(tid)
        if handle is None:
            handle = self._compile_handle()
            with self._pool_lock:
                if self._closed:  # closed during our compile: lost race
                    self._lib.guard_oracle_free(handle)
                    raise NativeUnsupported("oracle handle closed")
                self._handles[tid] = handle
        return handle

    def close(self) -> None:
        with getattr(self, "_pool_lock", None) or _NULL_LOCK:
            self._closed = True
            for handle in getattr(self, "_handles", {}).values():
                self._lib.guard_oracle_free(handle)
            self._handles = {}

    def __del__(self):  # pragma: no cover - interpreter teardown order
        try:
            self.close()
        except Exception:
            pass

    def eval_doc(self, doc: PV) -> List[int]:
        """Per-rule statuses for one loaded document (0/1/2 =
        PASS/FAIL/SKIP in guard-rule file order)."""
        try:
            wire = doc_to_compact(doc).encode("utf-8")
        except (Unserializable, RecursionError) as e:
            raise NativeUnsupported(str(e))
        return self.eval_wire(wire)

    def eval_records(self, doc: PV, data_file_name: str):
        """Full evaluation record tree (EventRecord) for one document —
        the rich-report path. The returned tree is byte-equivalent to
        the Python evaluator's (differential suite pins the serde
        encoding), so simplified_report_from_root / rule_statuses_from_root
        consume it unchanged."""
        handle = self._handle_for_thread()
        try:
            wire = doc_to_json(doc).encode("utf-8")
        except (Unserializable, RecursionError) as e:
            raise NativeUnsupported(str(e))
        err = ctypes.c_char_p()
        ptr = self._lib.guard_oracle_eval_records(
            handle, wire, data_file_name.encode("utf-8"), ctypes.byref(err)
        )
        if not ptr:
            msg = _consume_err(self._lib, err)
            if msg.startswith("unsupported:"):
                raise NativeUnsupported(msg)
            raise NativeEvalError(
                msg[len("error: "):] if msg.startswith("error: ") else msg
            )
        try:
            text = ctypes.string_at(ptr).decode("utf-8")
        finally:
            self._lib.guard_oracle_free_str(ptr)
        return records_from_wire(text)

    def eval_report(self, doc: PV, data_file_name: str):
        """(report_dict, {rule: Status}, overall Status) for one
        document — the simplified report built natively from failing
        records only (the fail-rerun fast path). Byte-equal to
        simplified_report_from_root over the Python evaluator's tree
        (differential suite)."""
        self._handle_for_thread()
        try:
            wire = doc_to_compact(doc, locs=True).encode("utf-8")
        except (Unserializable, RecursionError) as e:
            raise NativeUnsupported(str(e))
        return self._report_call(
            self._lib.guard_oracle_eval_report, wire, data_file_name
        )

    def eval_report_raw(self, content: str, data_file_name: str):
        """eval_report straight from raw JSON text — no Python-side
        load or serialization; source marks match the loader's."""
        self._handle_for_thread()
        return self._report_call(
            self._lib.guard_oracle_eval_report_raw,
            content.encode("utf-8"),
            data_file_name,
        )

    def _report_call(self, entry, wire: bytes, data_file_name: str):
        import json as _json

        from ..core.qresult import Status

        err = ctypes.c_char_p()
        ptr = entry(
            self._handle_for_thread(), wire, data_file_name.encode("utf-8"),
            ctypes.byref(err),
        )
        if not ptr:
            msg = _consume_err(self._lib, err)
            if msg.startswith("unsupported:"):
                raise NativeUnsupported(msg)
            raise NativeEvalError(
                msg[len("error: "):] if msg.startswith("error: ") else msg
            )
        try:
            text = ctypes.string_at(ptr).decode("utf-8")
        finally:
            self._lib.guard_oracle_free_str(ptr)
        env = _json.loads(text)
        st_map = {0: Status.PASS, 1: Status.FAIL, 2: Status.SKIP}
        statuses = {k: st_map[v] for k, v in env["statuses"].items()}
        return env["report"], statuses, st_map[env["overall"]]

    def eval_raw_json(self, content: str) -> List[int]:
        """Per-rule statuses straight from raw JSON document text — no
        Python-side load or serialization (the sweep / fail-rerun JSON
        fast path; typing matches the location-aware loader's)."""
        return self.eval_wire(content.encode("utf-8"), raw=True)

    def eval_wire(self, wire: bytes, raw: bool = False) -> List[int]:
        handle = self._handle_for_thread()
        err = ctypes.c_char_p()
        buf = (ctypes.c_int32 * max(self.n_rules, 1))()
        entry = self._lib.guard_oracle_eval_raw if raw else self._lib.guard_oracle_eval
        n = entry(handle, wire, buf, len(buf), ctypes.byref(err))
        if n < 0:
            msg = _consume_err(self._lib, err)
            if msg.startswith("unsupported:"):
                raise NativeUnsupported(msg)
            raise NativeEvalError(msg[len("error: "):] if msg.startswith("error: ") else msg)
        return [int(buf[i]) for i in range(n)]


def overall_status(statuses: List[int]) -> int:
    """eval_rules_file aggregation (evaluator.py:1533-1564): FAIL if any
    rule failed, else PASS if any passed, else SKIP."""
    if any(s == 1 for s in statuses):
        return 1
    if any(s == 0 for s in statuses):
        return 0
    return 2
