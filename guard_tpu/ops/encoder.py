"""Columnar document encoder: PV trees -> padded int32 arrays.

The TPU evaluation path never touches Python objects: a batch of parsed
documents is flattened into fixed-shape arrays (SURVEY.md §7, north-star
"documents -> padded columnar arrays"):

  * node columns: kind, parent, scalar-id, numeric value;
  * edge columns (parent -> child): parent, child, key-id (interned map
    key), list index;
  * one shared string-intern table across the batch, so string equality
    becomes integer equality and each regex in the rule set is matched
    ONCE per unique string on the host — the kernel just gathers bits.

Documents are padded to the batch maxima (buckets are handled a level
up), so the whole batch is a single `vmap`-able pytree of arrays.

Replaces the pointer-chasing recursive walk of the reference's
`PathAwareValue` traversal (`/root/reference/guard/src/rules/
eval_context.rs:337-924`) with data-parallel scatter/gather over these
arrays (see guard_tpu/ops/kernels.py).
"""

from __future__ import annotations

import math
import struct
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..core.values import (
    BOOL,
    CHAR,
    FLOAT,
    INT,
    LIST,
    MAP,
    NULL,
    RANGE_FLOAT,
    RANGE_INT,
    REGEX,
    STRING,
    PV,
    compiled_regex,
)
from ..utils.telemetry import span as _span


_BIAS32 = 1 << 31
_BIAS64 = 1 << 63


def num_key(kind: int, v) -> Optional[Tuple[int, int]]:
    """Order-preserving exact (hi, lo) int32 pair for a numeric value.

    The device compares numbers EXACTLY — the reference compares native
    i64/f64 (`/root/reference/guard/src/rules/path_value.rs:1071-1191`)
    and float32 columns silently collide above 2^24:

      * INT / BOOL: the i64 value biased to u64, split into two int32
        lanes (hi signed-biased, lo biased) — lexicographic (hi, lo)
        compare == exact i64 compare, for ALL i64 values;
      * FLOAT: the f64 bit pattern mapped through the standard monotone
        key (negative values bit-flipped, positives sign-set), -0.0
        collapsed to 0.0 — lexicographic compare == exact IEEE total
        order restricted to non-NaN values.

    Returns None when no exact encoding exists (NaN, ints outside i64):
    the encoder flags the whole document `num_exotic` and the backend
    routes it to the CPU oracle, never deciding it on device.
    """
    if kind == FLOAT:
        fv = float(v)
        if math.isnan(fv):
            return None
        if fv == 0.0:
            fv = 0.0  # collapse -0.0 so -0.0 == 0.0 holds
        b = struct.unpack("<Q", struct.pack("<d", fv))[0]
        u = (b ^ 0xFFFFFFFFFFFFFFFF) if (b >> 63) else (b | _BIAS64)
    else:
        iv = int(v)
        if iv < -_BIAS64 or iv >= _BIAS64:
            return None
        u = iv + _BIAS64
    return int((u >> 32) - _BIAS32), int((u & 0xFFFFFFFF) - _BIAS32)


class Interner:
    """Shared string table. Key ids and scalar-string ids share one
    namespace so `keys ==` filters work on the same table."""

    def __init__(self):
        self._ids: Dict[str, int] = {}
        self._strings: List[str] = []

    def intern(self, s: str) -> int:
        i = self._ids.get(s)
        if i is None:
            i = len(self._strings)
            self._ids[s] = i
            self._strings.append(s)
        return i

    def lookup(self, s: str) -> int:
        """-1 when the string is absent from the corpus (a literal that
        can never match by equality)."""
        return self._ids.get(s, -1)

    @property
    def strings(self) -> List[str]:
        return self._strings

    def __len__(self) -> int:
        return len(self._strings)

    @classmethod
    def from_strings(cls, strings: List[str]) -> "Interner":
        """Rebuild an interner from its string table (the picklable
        wire form ingest workers ship across the process boundary)."""
        it = cls()
        it._strings = list(strings)
        it._ids = {s: i for i, s in enumerate(it._strings)}
        return it

    def regex_match_bits(self, pattern: str) -> np.ndarray:
        """(S,) bool: does `pattern` match each interned string —
        host-precomputed so the TPU kernel only gathers."""
        rx = compiled_regex(pattern)
        return np.array(
            [rx.search(s) is not None for s in self._strings], dtype=bool
        )

    def substring_bits(self, needle_id_unused: int, needle: str) -> np.ndarray:
        """(S,) bool: is each interned string a substring of `needle`?
        The IN operator's string-containment case is `lhs.val in
        rhs.val` with lhs the document value (operators.rs:218-230)."""
        return np.array([s in needle for s in self._strings], dtype=bool)


@dataclass
class EncodedDoc:
    """Flat columnar form of one document."""

    node_kind: np.ndarray  # (n,) int32, PV kind; -1 padding
    node_parent: np.ndarray  # (n,) int32, -1 for root
    scalar_id: np.ndarray  # (n,) int32 intern id for STRING/REGEX/CHAR else -1
    num_hi: np.ndarray  # (n,) int32 exact numeric key, high lane (num_key)
    num_lo: np.ndarray  # (n,) int32 exact numeric key, low lane
    child_count: np.ndarray  # (n,) int32 (len of list / size of map)
    edge_parent: np.ndarray  # (e,) int32
    edge_child: np.ndarray  # (e,) int32
    edge_key_id: np.ndarray  # (e,) int32 interned key, -1 for list elems
    edge_index: np.ndarray  # (e,) int32 list index, -1 for map entries
    n_nodes: int
    n_edges: int
    # document contains a number with no exact device encoding (NaN or
    # an int outside i64): must be evaluated by the CPU oracle
    num_exotic: bool = False
    # (slot, root node index, origin node index) of each precomputed
    # function-result ROOT (ops/fnvars.py): orphan subtrees appended
    # after the document, tagged post-batch with the reserved
    # fn_key_id(slot). origin = -1 for shared (root-basis) slots;
    # per-origin slots ('pexpr') carry the candidate node the result
    # belongs to (the fn_origin column the kernels select by)
    fn_roots: list = field(default_factory=list)
    # a per-origin result's origin path did not map back to a node —
    # cannot happen for origins enumerated from this same tree, but if
    # it ever does the document must route to the CPU oracle rather
    # than silently losing its RHS
    fn_origin_miss: bool = False


def encode_document(
    doc: PV, interner: Interner, fn_results=None
) -> EncodedDoc:
    kinds: List[int] = []
    parents: List[int] = []
    scalar_ids: List[int] = []
    num_his: List[int] = []
    num_los: List[int] = []
    child_counts: List[int] = []
    e_parent: List[int] = []
    e_child: List[int] = []
    e_key: List[int] = []
    e_index: List[int] = []
    exotic = [False]
    # origin-path -> node index, only built when a per-origin function
    # result needs mapping back to its candidate node
    # record paths during the MAIN doc visit only (result subtrees
    # carry fabricated paths that must not shadow document nodes).
    # Paths are unescaped slash-joined strings, so a map KEY containing
    # '/' can collide with a genuinely nested path — colliding docs
    # set the miss flag and route to the oracle instead of silently
    # mapping an origin to the wrong node (review finding, round 5)
    # fn_results entries are always (slot, pv, origin_path-or-None)
    want_paths = [any(fr[2] is not None for fr in fn_results or [])]
    path_idx: dict = {}
    path_dup = [False]

    def push_num(kind: int, v) -> None:
        key = num_key(kind, v)
        if key is None:
            exotic[0] = True
            key = (0, 0)
        num_his.append(key[0])
        num_los.append(key[1])

    def visit(pv: PV, parent: int) -> int:
        idx = len(kinds)
        if want_paths[0]:
            if pv.path.s in path_idx:
                path_dup[0] = True
            path_idx[pv.path.s] = idx
        kinds.append(pv.kind)
        parents.append(parent)
        k = pv.kind
        if k in (STRING, REGEX, CHAR):
            scalar_ids.append(interner.intern(pv.val))
            num_his.append(0)
            num_los.append(0)
            child_counts.append(0)
        elif k == INT or k == FLOAT:
            scalar_ids.append(-1)
            push_num(k, pv.val)
            child_counts.append(0)
        elif k == BOOL:
            scalar_ids.append(-1)
            push_num(INT, 1 if pv.val else 0)
            child_counts.append(0)
        elif k == NULL:
            scalar_ids.append(-1)
            num_his.append(0)
            num_los.append(0)
            child_counts.append(0)
        elif k == LIST:
            scalar_ids.append(-1)
            num_his.append(0)
            num_los.append(0)
            child_counts.append(len(pv.val))
            for i, item in enumerate(pv.val):
                ci = visit(item, idx)
                e_parent.append(idx)
                e_child.append(ci)
                e_key.append(-1)
                e_index.append(i)
        elif k == MAP:
            mv = pv.val
            scalar_ids.append(-1)
            num_his.append(0)
            num_los.append(0)
            child_counts.append(len(mv.values))
            for key_node in mv.keys:
                child = mv.values.get(key_node.val)
                if child is None:
                    continue
                ci = visit(child, idx)
                e_parent.append(idx)
                e_child.append(ci)
                e_key.append(interner.intern(key_node.val))
                e_index.append(-1)
        else:  # ranges never appear in documents
            scalar_ids.append(-1)
            num_his.append(0)
            num_los.append(0)
            child_counts.append(0)
        return idx

    visit(doc, -1)
    # precomputed function results: orphan subtrees (parent -1 -> no
    # traversal step ever reaches them; internal edges are real so
    # walks INTO the results work normally)
    want_paths[0] = False
    fn_roots = []
    origin_miss = path_dup[0]
    for slot, pv, opath in fn_results or []:
        if opath is None:
            origin = -1
        elif origin_miss:
            continue  # ambiguous path space: doc goes to the oracle
        else:
            origin = path_idx.get(opath, -2)
            if origin == -2:
                origin_miss = True
                continue
        fn_roots.append((slot, visit(pv, -1), origin))
    return EncodedDoc(
        fn_roots=fn_roots,
        fn_origin_miss=origin_miss,
        node_kind=np.array(kinds, dtype=np.int32),
        node_parent=np.array(parents, dtype=np.int32),
        scalar_id=np.array(scalar_ids, dtype=np.int32),
        num_hi=np.array(num_his, dtype=np.int32),
        num_lo=np.array(num_los, dtype=np.int32),
        child_count=np.array(child_counts, dtype=np.int32),
        edge_parent=np.array(e_parent, dtype=np.int32),
        edge_child=np.array(e_child, dtype=np.int32),
        edge_key_id=np.array(e_key, dtype=np.int32),
        edge_index=np.array(e_index, dtype=np.int32),
        n_nodes=len(kinds),
        n_edges=len(e_parent),
        num_exotic=exotic[0],
    )


@dataclass
class DocBatch:
    """Batch of encoded documents padded to common (N, E) shapes.

    All arrays have a leading doc axis — the axis that gets DP-sharded
    across the TPU mesh (guard_tpu/parallel/mesh.py).

    On construction three derived *per-node* columns are computed from
    the edge arrays. They fold each node's unique parent edge into the
    node itself, which is what lets the kernels run entirely on
    elementwise ops + one-hot parent compares — device-side gathers are
    catastrophically slow on TPU (measured ~150x a fused masked
    reduction at these shapes), so every array the kernel indexes by a
    *data-dependent* index is instead precomputed host-side:

      * ``node_key_id``     (D, N): intern id of the map key under
        which this node sits (-1 for list elements, -2 for the root
        and padding);
      * ``node_index``      (D, N): list index of this node in its
        parent (-1 for map entries, -2 for root/padding);
      * ``node_parent_kind`` (D, N): node kind of the parent (-1 for
        root/padding).
    """

    node_kind: np.ndarray  # (D, N) int32; -1 padding
    node_parent: np.ndarray  # (D, N)
    scalar_id: np.ndarray  # (D, N)
    num_hi: np.ndarray  # (D, N) int32 exact numeric key, high lane (num_key)
    num_lo: np.ndarray  # (D, N) int32 exact numeric key, low lane
    child_count: np.ndarray  # (D, N)
    edge_parent: np.ndarray  # (D, E); padding edges point at node N-? no: -1
    edge_child: np.ndarray  # (D, E)
    edge_key_id: np.ndarray  # (D, E)
    edge_index: np.ndarray  # (D, E)
    edge_valid: np.ndarray  # (D, E) bool
    n_docs: int
    n_nodes: int
    n_edges: int
    node_key_id: np.ndarray = None  # (D, N) derived, see class docstring
    node_index: np.ndarray = None  # (D, N) derived
    node_parent_kind: np.ndarray = None  # (D, N) derived
    # (D,) bool: doc has a number with no exact device encoding (NaN or
    # beyond-i64 int); such docs route to the CPU oracle like oversize
    # ones (split_batch_by_size) so the device never decides them
    num_exotic: np.ndarray = None
    # (D, N) int32, only when the batch carries per-origin function
    # results (ops/fnvars.py 'pexpr' slots): the candidate node index a
    # result root belongs to, -1 everywhere else. None when no
    # per-origin slot exists — the column ships to the device only for
    # rule files that read it (ir.CompiledRules.needs_fn_origin)
    fn_origin: np.ndarray = None

    def __post_init__(self):
        if self.num_exotic is None:
            self.num_exotic = np.zeros(self.node_kind.shape[0], dtype=bool)
        if self.node_key_id is not None:
            return
        d, n = self.node_kind.shape
        # scatter each edge's attributes onto its child node; invalid
        # padding edges all have child 0 (the root), which is fixed up
        # after the scatter — the root has no parent edge
        self.node_key_id = np.full((d, n), -2, dtype=np.int32)
        np.put_along_axis(self.node_key_id, self.edge_child, self.edge_key_id, axis=1)
        self.node_key_id[:, 0] = -2
        self.node_index = np.full((d, n), -2, dtype=np.int32)
        np.put_along_axis(self.node_index, self.edge_child, self.edge_index, axis=1)
        self.node_index[:, 0] = -2
        pk = np.take_along_axis(self.node_kind, np.maximum(self.edge_parent, 0), axis=1)
        self.node_parent_kind = np.full((d, n), -1, dtype=np.int32)
        np.put_along_axis(self.node_parent_kind, self.edge_child, pk, axis=1)
        self.node_parent_kind[:, 0] = -1

    def arrays(self, include_struct: bool = False) -> dict:
        out = {
            "node_kind": self.node_kind,
            "node_parent": self.node_parent,
            "scalar_id": self.scalar_id,
            "num_hi": self.num_hi,
            "num_lo": self.num_lo,
            "child_count": self.child_count,
            "edge_parent": self.edge_parent,
            "edge_child": self.edge_child,
            "edge_key_id": self.edge_key_id,
            "edge_index": self.edge_index,
            "edge_valid": self.edge_valid,
            "node_key_id": self.node_key_id,
            "node_index": self.node_index,
            "node_parent_kind": self.node_parent_kind,
        }
        if self.fn_origin is not None:
            out["fn_origin"] = self.fn_origin
        if include_struct:
            out["struct_id"] = self.struct_ids()
        return out

    def struct_ids(self) -> np.ndarray:
        """(D, N) int32 canonical-form ids: two nodes get the same id
        iff they are `loose_eq` (values.loose_eq — strict scalar kinds,
        ordered lists, unordered maps). Used by query-RHS comparisons
        so set membership is an id-equality test on device. Computed
        lazily (only rules with query RHS pay for it) and cached."""
        self._canonicalize()
        return self._struct_ids

    def _canonicalize(self) -> None:
        """Builds BOTH canonical id spaces in one bottom-up pass:
        `_struct_ids` (loose_eq classes, see struct_ids) and
        `_ord_ids` — ORDER-PRESERVING classes where two nodes share an
        id iff `compare_eq(node, lit)` behaves identically for every
        possible literal (map entries keep document insertion order
        because compare_eq short-circuits per entry,
        values.compare_eq:386-399; finer than loose_eq, which collapses
        map order). The ord space feeds the struct-literal tri-state
        tables (struct_literal_tri)."""
        if getattr(self, "_struct_ids", None) is not None:
            return
        d_n = self.node_kind.shape
        out = np.full(d_n, -1, dtype=np.int32)
        oout = np.full(d_n, -1, dtype=np.int32)
        table: dict = {}
        otable: dict = {}
        for di in range(d_n[0]):
            kinds = self.node_kind[di]
            sids = self.scalar_id[di]
            nhi = self.num_hi[di]
            nlo = self.num_lo[di]
            # group children per parent from the edge arrays
            children: dict = {}
            ev = self.edge_valid[di]
            ep = self.edge_parent[di]
            ec = self.edge_child[di]
            ek = self.edge_key_id[di]
            ei = self.edge_index[di]
            for e in range(self.edge_parent.shape[1]):
                if not ev[e]:
                    continue
                children.setdefault(int(ep[e]), []).append(
                    (int(ei[e]), int(ek[e]), int(ec[e]))
                )
            # children always have higher indices than their parent
            # (encoder visit order), so a reverse scan is bottom-up
            for i in range(d_n[1] - 1, -1, -1):
                k = int(kinds[i])
                if k < 0:
                    continue
                if k == LIST:
                    elems = sorted(children.get(i, []))
                    key = ("l",) + tuple(int(out[di, c]) for _, _, c in elems)
                    okey = ("L",) + tuple(int(oout[di, c]) for _, _, c in elems)
                elif k == MAP:
                    entries = children.get(i, [])
                    key = ("m", frozenset(
                        (kid, int(out[di, c])) for _, kid, c in entries
                    ))
                    # encoder visit order == document insertion order
                    # (child node index ascends in insertion order)
                    okey = ("M",) + tuple(
                        (kid, int(oout[di, c]))
                        for _, kid, c in sorted(entries, key=lambda t: t[2])
                    )
                elif k in (STRING, REGEX, CHAR):
                    key = ("s", int(sids[i]))
                    okey = key
                elif k in (INT, FLOAT, BOOL):
                    # the exact key pair: no float32 collisions
                    key = (k, int(nhi[i]), int(nlo[i]))
                    okey = key
                else:  # NULL
                    key = ("n",)
                    okey = key
                sid = table.get(key)
                if sid is None:
                    sid = len(table)
                    table[key] = sid
                out[di, i] = sid
                oid = otable.get(okey)
                if oid is None:
                    oid = len(otable)
                    otable[okey] = oid
                oout[di, i] = oid
        self._struct_ids = out
        self._struct_table = table
        self._ord_ids = oout
        self._ord_table = otable

    def struct_literal_tri(self, literals, interner) -> list:
        """Per struct literal: ((D, N) match, (D, N) comparable,
        (D, N) loose_match) bool columns.

        match/comparable carry exact `compare_eq(doc_node, literal)`
        tri-state semantics (path_value.rs:1071-1146 incl. regex
        matching inside maps, range membership, and NotComparable
        propagation with the reference's per-entry short-circuit
        order); loose_match is `loose_eq(doc_node, literal)`
        (path_value.rs:245-291 — never raises, maps compare values
        order-insensitively via MapValue PartialEq, regex members
        match). Evaluated ONCE per order-preserving canonical class
        (ord_ids) on the host, then broadcast to nodes — the kernel
        reads plain bool columns."""
        self._canonicalize()
        otable = self._ord_table
        strings = interner.strings
        # reconstruct each canonical entry's scalar value lazily from
        # the exact (hi, lo) key (num_key is bijective off NaN)
        T, F, R = 1, 0, 2  # tri-states: True / False / Raise

        def unkey(kind: int, hi: int, lo: int):
            u = ((hi + _BIAS32) << 32) | ((lo + _BIAS32) & 0xFFFFFFFF)
            if kind == FLOAT:
                b = (u ^ _BIAS64) if (u >> 63) else (u ^ 0xFFFFFFFFFFFFFFFF)
                return struct.unpack("<d", struct.pack("<Q", b))[0]
            return u - _BIAS64

        rev = {oid: okey for okey, oid in otable.items()}
        out = []
        for lit in literals:
            memo: Dict[tuple, int] = {}

            def tri(okey, pv) -> int:
                mk = (okey, id(pv))
                got = memo.get(mk)
                if got is not None:
                    return got
                memo[mk] = r = _tri(okey, pv)
                return r

            def _tri(okey, pv) -> int:
                tag = okey[0]
                k = pv.kind
                if tag == "s":  # document STRING node
                    s = strings[okey[1]]
                    if k == STRING:
                        return T if s == pv.val else F
                    if k == REGEX:
                        return T if compiled_regex(pv.val).search(s) else F
                    return R
                if tag == "n":
                    return T if k == NULL else R
                if tag == INT:
                    v = unkey(INT, okey[1], okey[2])
                    if k == INT:
                        return T if v == pv.val else F
                    if k == RANGE_INT:
                        return T if pv.val.contains(v) else F
                    return R
                if tag == FLOAT:
                    v = unkey(FLOAT, okey[1], okey[2])
                    if k == FLOAT:
                        return T if v == pv.val else F
                    if k == RANGE_FLOAT:
                        return T if pv.val.contains(v) else F
                    return R
                if tag == BOOL:
                    v = bool(unkey(INT, okey[1], okey[2]))
                    return (T if v == pv.val else F) if k == BOOL else R
                if tag == "L":
                    if k != LIST:
                        return R
                    elems = okey[1:]
                    if len(elems) != len(pv.val):
                        return F
                    # okey elements here are ord ids: resolve back to
                    # keys via the reverse table built below
                    for oid, e in zip(elems, pv.val):
                        r = tri(rev[oid], e)
                        if r != T:
                            return r
                    return T
                if tag == "M":
                    if k != MAP:
                        return R
                    entries = okey[1:]
                    if len(entries) != len(pv.val.values):
                        return F
                    for kid, oid in entries:
                        v2 = pv.val.values.get(strings[kid])
                        if v2 is None:
                            return F
                        r = tri(rev[oid], v2)
                        if r != T:
                            return r
                    return T
                raise AssertionError(f"canonical tag {tag}")

            lmemo: Dict[tuple, bool] = {}

            def loose(okey, pv) -> bool:
                mk = (okey, id(pv))
                got = lmemo.get(mk)
                if got is not None:
                    return got
                lmemo[mk] = r = _loose(okey, pv)
                return r

            def _loose(okey, pv) -> bool:
                tag = okey[0]
                k = pv.kind
                if tag == "M":
                    # MapValue PartialEq: same size, every doc entry
                    # loose_eq the literal's same-key value
                    if k != MAP:
                        return False
                    entries = okey[1:]
                    if len(entries) != len(pv.val.values):
                        return False
                    for kid, oid in entries:
                        v2 = pv.val.values.get(strings[kid])
                        if v2 is None or not loose(rev[oid], v2):
                            return False
                    return True
                if tag == "L":
                    if k != LIST:
                        return False
                    elems = okey[1:]
                    if len(elems) != len(pv.val):
                        return False
                    return all(
                        loose(rev[oid], e) for oid, e in zip(elems, pv.val)
                    )
                if tag == "s" and k == REGEX:
                    # loose_eq guards regex compile errors itself
                    try:
                        return bool(
                            compiled_regex(pv.val).search(strings[okey[1]])
                        )
                    except Exception:
                        return False
                return tri(okey, pv) == T

            tri_of = np.zeros(max(len(otable), 1), dtype=np.int8)
            loose_of = np.zeros(max(len(otable), 1), dtype=bool)
            for okey, oid in otable.items():
                tri_of[oid] = tri(okey, lit)
                loose_of[oid] = loose(okey, lit)
            ids = self._ord_ids
            safe = np.clip(ids, 0, len(tri_of) - 1)
            vals = np.where(ids >= 0, tri_of[safe], R)
            lvals = np.where(ids >= 0, loose_of[safe], False)
            out.append((vals == T, vals != R, lvals))
        return out



def _round_up(n: int, multiple: int = 8) -> int:
    return max(multiple, ((n + multiple - 1) // multiple) * multiple)


# node-capacity buckets for the kernel path. Small buckets use the
# fused one-hot traversal (O(N^2) lanes per doc per step — fastest
# below kernels.GATHER_MIN_NODES where the compare fuses into the
# consuming reduction); buckets at and above that threshold trace the
# O(N) gather/segment-sum formulation instead, so the per-doc cost
# stays proportional to document size. EVERY rule file uses the
# extended buckets (documents up to 64k nodes stay on device): as of
# round 5 the pairwise constructions (query-RHS compares, variable key
# interpolation — CompiledRules.needs_pairwise) evaluate through
# O(N log N) sorted-set joins in gather mode, which needs_pairwise
# forces above 8,192 nodes, so no (N, N) matrix exists at the big
# buckets. Only documents beyond the last bucket route to the CPU
# oracle (ops/backend.py)
NODE_BUCKETS = (64, 128, 256, 512, 1024, 2048, 4096, 8192)
NODE_BUCKETS_EXTENDED = NODE_BUCKETS + (16384, 32768, 65536)


def split_batch_by_size(
    batch: DocBatch, buckets: Tuple[int, ...] = NODE_BUCKETS
) -> Tuple[List[Tuple[DocBatch, np.ndarray]], np.ndarray]:
    """Split a batch into per-size-bucket sub-batches so small documents
    are not padded (and evaluated) at the largest document's shape.

    Returns (groups, oversize_doc_indices): each group is (sub_batch,
    doc_indices) with node/edge axes sliced down to the bucket shape —
    exact because padding is always a suffix. Documents larger than the
    biggest bucket — and documents whose numbers have no exact device
    encoding (num_exotic) — are returned in `oversize_doc_indices` for
    CPU-oracle evaluation."""
    n_real = (batch.node_kind >= 0).sum(axis=1)
    e_real = batch.edge_valid.sum(axis=1)
    host_mask = (n_real > buckets[-1]) | batch.num_exotic
    oversize = np.where(host_mask)[0]
    groups: List[Tuple[DocBatch, np.ndarray]] = []
    lo = 0
    for b in buckets:
        idx = np.where((n_real > lo) & (n_real <= b) & ~host_mask)[0]
        lo = b
        if len(idx) == 0:
            continue
        m_nodes = min(b, batch.n_nodes)
        m_edges = min(
            max(_round_up(int(e_real[idx].max())), 8), batch.n_edges
        )
        sub = DocBatch(
            node_kind=batch.node_kind[idx, :m_nodes],
            node_parent=batch.node_parent[idx, :m_nodes],
            scalar_id=batch.scalar_id[idx, :m_nodes],
            num_hi=batch.num_hi[idx, :m_nodes],
            num_lo=batch.num_lo[idx, :m_nodes],
            child_count=batch.child_count[idx, :m_nodes],
            edge_parent=batch.edge_parent[idx, :m_edges],
            edge_child=batch.edge_child[idx, :m_edges],
            edge_key_id=batch.edge_key_id[idx, :m_edges],
            edge_index=batch.edge_index[idx, :m_edges],
            edge_valid=batch.edge_valid[idx, :m_edges],
            n_docs=len(idx),
            n_nodes=m_nodes,
            n_edges=m_edges,
            node_key_id=batch.node_key_id[idx, :m_nodes],
            node_index=batch.node_index[idx, :m_nodes],
            node_parent_kind=batch.node_parent_kind[idx, :m_nodes],
            num_exotic=batch.num_exotic[idx],
            fn_origin=(
                batch.fn_origin[idx, :m_nodes]
                if batch.fn_origin is not None
                else None
            ),
        )
        groups.append((sub, idx))
    return groups, oversize


def take_doc_subset(batch: DocBatch, idx) -> DocBatch:
    """Arbitrary doc-index subset of an encoded batch (the incremental
    plane's delta extraction: a worker-encoded full chunk minus its
    result-cache hits). Node/edge widths are kept — statuses are
    invariant under batch composition (the plan layer's relocation
    contract), so the narrower batch evaluates identically and
    split_batch_by_size re-buckets it as usual. Derived per-node
    columns pass through so __post_init__ skips the edge re-scatter."""
    idx = np.asarray(idx, dtype=np.int64)
    if len(idx) == batch.n_docs:
        return batch
    return DocBatch(
        node_kind=batch.node_kind[idx],
        node_parent=batch.node_parent[idx],
        scalar_id=batch.scalar_id[idx],
        num_hi=batch.num_hi[idx],
        num_lo=batch.num_lo[idx],
        child_count=batch.child_count[idx],
        edge_parent=batch.edge_parent[idx],
        edge_child=batch.edge_child[idx],
        edge_key_id=batch.edge_key_id[idx],
        edge_index=batch.edge_index[idx],
        edge_valid=batch.edge_valid[idx],
        n_docs=len(idx),
        n_nodes=batch.n_nodes,
        n_edges=batch.n_edges,
        node_key_id=batch.node_key_id[idx],
        node_index=batch.node_index[idx],
        node_parent_kind=batch.node_parent_kind[idx],
        num_exotic=batch.num_exotic[idx],
        fn_origin=(
            batch.fn_origin[idx] if batch.fn_origin is not None else None
        ),
    )


def encode_batch(docs: List[PV], interner: Optional[Interner] = None,
                 pad_nodes: Optional[int] = None, pad_edges: Optional[int] = None,
                 fn_values=None, fn_var_order=None,
                 ) -> Tuple[DocBatch, Interner]:
    """Encode + pad a list of documents into one batch.

    Pads node/edge axes to bucket sizes (multiples of 8) so XLA sees a
    small number of distinct shapes across batches.

    `fn_values` (per-doc {var: [PV]}, ops/fnvars.precompute_fn_values)
    with `fn_var_order` (the slot order) appends each function result
    as an orphan subtree and tags its root with the reserved
    fn_key_id(slot) in the derived node_key_id column.
    """
    interner = interner if interner is not None else Interner()
    any_per_origin = False
    if fn_values is not None and fn_var_order:
        encoded = []
        for i, d in enumerate(docs):
            per = fn_values[i]
            flat = []
            for slot, var in enumerate(fn_var_order):
                vals = per.get(var, [])
                if isinstance(vals, dict):
                    # per-origin slot ('pexpr'): {origin path: [PV]}
                    any_per_origin = True
                    for opath, pvs in vals.items():
                        for pv in pvs:
                            flat.append((slot, pv, opath))
                else:
                    for pv in vals:
                        flat.append((slot, pv, None))
            encoded.append(encode_document(d, interner, fn_results=flat))
    else:
        encoded = [encode_document(d, interner) for d in docs]
    n = pad_nodes or _round_up(max((e.n_nodes for e in encoded), default=1))
    e_max = pad_edges or _round_up(max((e.n_edges for e in encoded), default=1))
    d = len(encoded)

    def pad_node(attr, fill):
        out = np.full((d, n), fill, dtype=getattr(encoded[0], attr).dtype if encoded else np.int32)
        for i, enc in enumerate(encoded):
            arr = getattr(enc, attr)
            out[i, : len(arr)] = arr
        return out

    def pad_edge(attr, fill):
        out = np.full((d, e_max), fill, dtype=np.int32)
        for i, enc in enumerate(encoded):
            arr = getattr(enc, attr)
            out[i, : len(arr)] = arr
        return out

    edge_valid = np.zeros((d, e_max), dtype=bool)
    for i, enc in enumerate(encoded):
        edge_valid[i, : enc.n_edges] = True

    batch = DocBatch(
        node_kind=pad_node("node_kind", -1),
        node_parent=pad_node("node_parent", -1),
        scalar_id=pad_node("scalar_id", -1),
        num_hi=pad_node("num_hi", 0),
        num_lo=pad_node("num_lo", 0),
        child_count=pad_node("child_count", 0),
        # padding edges self-loop on node 0 but are masked by edge_valid
        edge_parent=pad_edge("edge_parent", 0),
        edge_child=pad_edge("edge_child", 0),
        edge_key_id=pad_edge("edge_key_id", -2),
        edge_index=pad_edge("edge_index", -2),
        edge_valid=edge_valid,
        n_docs=d,
        n_nodes=n,
        n_edges=e_max,
        num_exotic=np.array(
            [enc.num_exotic for enc in encoded], dtype=bool
        ),
    )
    # tag function-result roots AFTER the derived-column pass: the ids
    # live in a reserved negative namespace (ops/fnvars.fn_key_id)
    # that no interned key or sentinel uses, and carrying them in the
    # derived column (not the edge arrays) keeps the results out of
    # struct-id child grouping and parent-kind derivation
    from .fnvars import fn_key_id

    if any_per_origin:
        batch.fn_origin = np.full((d, n), -1, dtype=np.int32)
    for i, enc in enumerate(encoded):
        for slot, idx, origin in enc.fn_roots:
            batch.node_key_id[i, idx] = fn_key_id(slot)
            if origin >= 0:
                batch.fn_origin[i, idx] = origin
        if enc.fn_origin_miss:
            batch.num_exotic[i] = True
    return batch, interner


# -- ingest-plane transport (parallel/ingest.py) ----------------------
# The worker pool ships encoded chunks across the process boundary as
# plain dicts of numpy arrays: cheap to pickle, and the derived columns
# travel along so the receiving process never re-runs the __post_init__
# derivation.

_PAYLOAD_ARRAYS = (
    "node_kind", "node_parent", "scalar_id", "num_hi", "num_lo",
    "child_count", "edge_parent", "edge_child", "edge_key_id",
    "edge_index", "edge_valid", "node_key_id", "node_index",
    "node_parent_kind", "num_exotic", "fn_origin",
)


def batch_payload(batch: DocBatch) -> dict:
    """Picklable wire form of a DocBatch (derived columns included)."""
    out = {k: getattr(batch, k) for k in _PAYLOAD_ARRAYS}
    out["n_docs"] = batch.n_docs
    out["n_nodes"] = batch.n_nodes
    out["n_edges"] = batch.n_edges
    return out


def batch_from_payload(payload: dict) -> DocBatch:
    return DocBatch(**payload)


def remap_interned_ids(batch: DocBatch, remap: np.ndarray) -> None:
    """Relabel a shard batch's intern ids in place through `remap`
    (shard-local id -> merged id). Only non-negative entries are ids;
    the sentinel namespaces (-1/-2, and the reserved fn ids — never
    present at encode time) pass through untouched."""
    if len(remap) == 0:
        return
    for attr in ("scalar_id", "edge_key_id", "node_key_id"):
        col = getattr(batch, attr)
        if col.size:
            safe = np.clip(col, 0, len(remap) - 1)
            col[...] = np.where(col >= 0, remap[safe], col)


_CONCAT_FILL = {
    "node_kind": -1, "node_parent": -1, "scalar_id": -1, "num_hi": 0,
    "num_lo": 0, "child_count": 0, "edge_parent": 0, "edge_child": 0,
    "edge_key_id": -2, "edge_index": -2, "edge_valid": False,
    "node_key_id": -2, "node_index": -2, "node_parent_kind": -1,
}


def concat_batches(parts: List[DocBatch]) -> DocBatch:
    """Concatenate shard batches along the doc axis, padding node/edge
    axes to the widest shard with the same suffix fills encode_batch
    uses — so the result is shape- and content-equivalent to encoding
    the union serially (modulo intern-id labels, which the caller has
    already remapped into one namespace)."""
    assert parts, "concat_batches needs at least one shard"
    assert all(p.fn_origin is None for p in parts), (
        "fn results are encoded after the shard merge, never inside it"
    )
    n_nodes = max(p.n_nodes for p in parts)
    n_edges = max(p.n_edges for p in parts)

    def padcat(attr: str, width: int) -> np.ndarray:
        fill = _CONCAT_FILL[attr]
        cols = []
        for p in parts:
            col = getattr(p, attr)
            if col.shape[1] < width:
                pad = np.full(
                    (col.shape[0], width - col.shape[1]), fill,
                    dtype=col.dtype,
                )
                col = np.concatenate([col, pad], axis=1)
            cols.append(col)
        return np.concatenate(cols, axis=0)

    node_attrs = (
        "node_kind", "node_parent", "scalar_id", "num_hi", "num_lo",
        "child_count", "node_key_id", "node_index", "node_parent_kind",
    )
    edge_attrs = (
        "edge_parent", "edge_child", "edge_key_id", "edge_index",
        "edge_valid",
    )
    fields = {a: padcat(a, n_nodes) for a in node_attrs}
    fields.update({a: padcat(a, n_edges) for a in edge_attrs})
    return DocBatch(
        n_docs=sum(p.n_docs for p in parts),
        n_nodes=n_nodes,
        n_edges=n_edges,
        num_exotic=np.concatenate([p.num_exotic for p in parts]),
        **fields,
    )


def encode_chunk_texts(names: List[str], contents: List[str]):
    """Worker-safe chunk encode entrypoint — the sweep's chunk-encode
    semantics as a pure function over raw texts, shared by the serial
    path, the ingest workers and the serve session so the three can
    never drift: the native C++ JSON encoder when the whole chunk
    sniffs as JSON (an invalid doc is marked, substituted with a `null`
    stand-in and the rest retried), the Python loader otherwise (a
    parse failure marks the doc and encodes a null stand-in).

    Returns (batch, interner, pv_failed_indices, messages, errors,
    quarantined, pvs): `quarantined` holds one structured error record
    per failed index (same order as pv_failed_indices) for the failure
    plane's manifest/report outputs; `pvs` is the per-doc Python
    document list when the Python path ran (callers in the same
    process can cache them for oracle fallbacks) and None on the
    native path.
    """
    with _span("encode", {"docs": len(names)}):
        return _encode_chunk_texts_inner(names, contents)


def _encode_chunk_texts_inner(names: List[str], contents: List[str]):
    from ..utils.faults import fault_active, maybe_fail, quarantine_record
    from .native_encoder import encode_json_batch_resilient

    pv_failed: set = set()
    messages: List[str] = []
    recs: dict = {}
    errors = 0
    batch = interner = pvs = None
    if fault_active("parse") or fault_active("encode"):
        contents = list(contents)
        for i, name in enumerate(names):
            for stage in ("parse", "encode"):
                if i in pv_failed:
                    continue
                try:
                    maybe_fail(stage, key=name)
                except Exception as e:
                    pv_failed.add(i)
                    messages.append(f"skipping {name}: {e}")
                    recs[i] = quarantine_record(name, stage, e)
                    errors += 1
                    contents[i] = "null"  # neutral stand-in downstream
    if all(c.lstrip()[:1] in ("{", "[") for c in contents):
        batch, interner, failed, msgs = encode_json_batch_resilient(
            contents, names
        )
        pv_failed |= failed
        messages += msgs
        errors += len(failed)
        for i in failed:
            recs[i] = {
                "file": names[i], "stage": "parse",
                "error": "ParseError", "message": "invalid JSON",
            }
    if batch is None:
        from ..core.errors import GuardError
        from ..core.loader import load_document
        from ..core.values import PV, Path as VPath

        pvs = []
        for i, content in enumerate(contents):
            if i in pv_failed:
                pvs.append(None)  # already marked by the native retry
                continue
            try:
                pvs.append(load_document(content, names[i]))
            except GuardError as e:
                pv_failed.add(i)
                messages.append(f"skipping {names[i]}: {e}")
                recs[i] = quarantine_record(names[i], "parse", e)
                errors += 1
                pvs.append(None)
        batch, interner = encode_batch(
            [pv if pv is not None else PV.null(VPath.root()) for pv in pvs]
        )
    order = sorted(pv_failed)
    return (batch, interner, order, messages, errors,
            [recs[i] for i in order], pvs)
