"""ctypes bindings for the native C++ columnar encoder.

`encode_json_batch_native` parses a list of JSON document strings in C++
(native/encoder.cpp) and returns the same `DocBatch` + `Interner` pair
as the Python encoder (guard_tpu/ops/encoder.py), ~an order of magnitude
faster — the org-sweep data-loader path. Falls back transparently when
the shared library hasn't been built (`native/build.sh`).
"""

from __future__ import annotations

import ctypes
from typing import List, Optional, Tuple

import numpy as np

from .encoder import DocBatch, Interner
from ._native_lib import build, load_lib

_SO_NAME = "libguard_encoder.so"
_BUILD_SCRIPT = "build.sh"


class _EncodedBatchStruct(ctypes.Structure):
    _fields_ = [
        ("n_docs", ctypes.c_int32),
        ("n_nodes", ctypes.c_int32),
        ("n_edges", ctypes.c_int32),
        ("n_strings", ctypes.c_int32),
        ("node_kind", ctypes.POINTER(ctypes.c_int32)),
        ("node_parent", ctypes.POINTER(ctypes.c_int32)),
        ("scalar_id", ctypes.POINTER(ctypes.c_int32)),
        ("num_hi", ctypes.POINTER(ctypes.c_int32)),
        ("num_lo", ctypes.POINTER(ctypes.c_int32)),
        ("child_count", ctypes.POINTER(ctypes.c_int32)),
        ("edge_parent", ctypes.POINTER(ctypes.c_int32)),
        ("edge_child", ctypes.POINTER(ctypes.c_int32)),
        ("edge_key_id", ctypes.POINTER(ctypes.c_int32)),
        ("edge_index", ctypes.POINTER(ctypes.c_int32)),
        ("edge_valid", ctypes.POINTER(ctypes.c_uint8)),
        ("doc_exotic", ctypes.POINTER(ctypes.c_uint8)),
        ("string_blob", ctypes.POINTER(ctypes.c_char)),
        ("string_blob_len", ctypes.c_int64),
        ("error_doc", ctypes.c_int32),
    ]


_configured = None


def _load() -> Optional[ctypes.CDLL]:
    global _configured
    if _configured is not None:
        return _configured
    lib = load_lib(_SO_NAME)
    if lib is None:
        return None
    lib.guard_encode_json_batch.restype = ctypes.POINTER(_EncodedBatchStruct)
    lib.guard_encode_json_batch.argtypes = [
        ctypes.POINTER(ctypes.c_char_p),
        ctypes.c_int32,
    ]
    lib.guard_batch_free.argtypes = [ctypes.POINTER(_EncodedBatchStruct)]
    lib.guard_batch_free.restype = None
    _configured = lib
    return lib


def build_native(force: bool = False) -> bool:
    """Compile the shared library via native/build.sh."""
    return build(_SO_NAME, _BUILD_SCRIPT, force)


def native_available() -> bool:
    return _load() is not None


def encode_json_batch_resilient(contents: List[str], names: List[str]):
    """`encode_json_batch_native` with per-document error isolation:
    an invalid document must not push the whole chunk off the native
    encoder, so it is reported, replaced by a `null` stand-in and the
    remainder retried (the sweep chunk contract; callers exclude the
    marked docs from tallies). Returns (batch, interner,
    failed_indices, messages) — (None, None, failed, msgs) when the
    shared library is unavailable or errors, in which case the caller
    falls back to the Python loader with the marks kept."""
    failed: set = set()
    msgs: List[str] = []
    if not native_available():
        return None, None, failed, msgs
    work = list(contents)
    for _ in range(len(work) + 1):
        try:
            batch, interner, err = encode_json_batch_native(work)
        except RuntimeError:
            return None, None, failed, msgs
        if err is None:
            return batch, interner, failed, msgs
        if err not in failed:
            failed.add(err)
            msgs.append(f"skipping {names[err]}: invalid JSON")
        work[err] = "null"
    return None, None, failed, msgs


def encode_json_batch_native(
    docs: List[str],
) -> Tuple[DocBatch, Interner, Optional[int]]:
    """Encode JSON strings natively. Returns (batch, interner,
    error_doc_index-or-None). Raises RuntimeError if the library is
    unavailable."""
    lib = _load()
    if lib is None:
        raise RuntimeError(
            "native encoder not built; run native/build.sh or use the "
            "python encoder"
        )
    n = len(docs)
    arr = (ctypes.c_char_p * n)(*[d.encode("utf-8") for d in docs])
    ptr = lib.guard_encode_json_batch(arr, n)
    try:
        b = ptr.contents
        nn = b.n_docs * b.n_nodes
        ne = b.n_docs * b.n_edges

        def np_copy(cptr, count, dtype):
            return np.ctypeslib.as_array(cptr, shape=(count,)).astype(dtype, copy=True)

        shape_n = (b.n_docs, b.n_nodes)
        shape_e = (b.n_docs, b.n_edges)
        batch = DocBatch(
            node_kind=np_copy(b.node_kind, nn, np.int32).reshape(shape_n),
            node_parent=np_copy(b.node_parent, nn, np.int32).reshape(shape_n),
            scalar_id=np_copy(b.scalar_id, nn, np.int32).reshape(shape_n),
            num_hi=np_copy(b.num_hi, nn, np.int32).reshape(shape_n),
            num_lo=np_copy(b.num_lo, nn, np.int32).reshape(shape_n),
            child_count=np_copy(b.child_count, nn, np.int32).reshape(shape_n),
            edge_parent=np_copy(b.edge_parent, ne, np.int32).reshape(shape_e),
            edge_child=np_copy(b.edge_child, ne, np.int32).reshape(shape_e),
            edge_key_id=np_copy(b.edge_key_id, ne, np.int32).reshape(shape_e),
            edge_index=np_copy(b.edge_index, ne, np.int32).reshape(shape_e),
            edge_valid=np_copy(b.edge_valid, ne, np.uint8)
            .reshape(shape_e)
            .astype(bool),
            n_docs=b.n_docs,
            n_nodes=b.n_nodes,
            n_edges=b.n_edges,
            num_exotic=np_copy(b.doc_exotic, b.n_docs, np.uint8).astype(bool)
            if b.n_docs
            else np.zeros(0, dtype=bool),
        )
        blob = ctypes.string_at(b.string_blob, b.string_blob_len)
        strings = blob.decode("utf-8").split("\x00")[:-1] if b.string_blob_len else []
        interner = Interner()
        for s in strings:
            interner.intern(s)
        error_doc = b.error_doc if b.error_doc >= 0 else None
        return batch, interner, error_doc
    finally:
        lib.guard_batch_free(ptr)
