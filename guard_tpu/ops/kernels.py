"""JAX evaluation kernels: origin-labeled query walks over columnar docs.

The TPU-native replacement for the reference's recursive tree-walk
(`/root/reference/guard/src/rules/eval_context.rs:337-924`) and clause
evaluation (`eval.rs:174-1225`):

  * a query's current selection is an (N,) int32 vector of *origin
    labels* (0 = unselected; label o = node selected on behalf of origin
    node o-1);
  * each traversal step moves labels from parents to children through a
    one-hot compare against the static `node_parent` column — because
    the document is a tree every node has exactly one parent, so the
    "scatter" is exact, and because the compare fuses into the reduce
    the whole step is a streamed masked reduction (measured ~150x
    faster than any gather-based formulation on v5e — TPU gathers
    serialize);
  * per-origin aggregation (the `some`/`match_all`, block and filter
    semantics) is a fused one-hot segment-sum keyed by origin label;
  * UnResolved propagation is an (N+1,) per-origin counter carried
    through every step, reproducing the reference's tri-state outcomes;
  * string equality is intern-id equality; regex / substring / string-
    ordering / empty-string checks read host-precomputed per-node bool
    columns (ir.CompiledRules.device_arrays) — the kernel performs no
    data-dependent indexing at all.

Everything is fixed-shape and traced once per (rule-file, node bucket):
`vmap` batches documents, and the doc axis is DP-sharded across the TPU
mesh by guard_tpu/parallel/mesh.py.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# node-bucket size at and above which the traversal primitives switch
# from fused one-hot masked reductions (O(N^2) lanes, fastest for small
# docs where the compare fuses into the consuming reduction) to XLA
# gather / segment-sum (O(N) work, the only formulation whose cost
# scales linearly with document size). Default from the round-5
# on-chip bake-off (tools/tune_gather.py on v5e, 2026-07-31): one-hot
# won every bucket through 8,192 (941 vs 696 docs/s there); gather
# first won at 16,384 (349 vs 224 docs/s). Overridable for bake-off
# probes.
GATHER_MIN_NODES = int(os.environ.get("GUARD_TPU_GATHER_MIN_NODES", "16384"))

# on CPU backends real gathers are cheap and the one-hot's N^2 lanes
# are not (tools/tune_gather.py measured gather 6-33x faster even at
# the 64-node bucket), so CPU runs use gather at EVERY bucket — the
# threshold above only governs accelerator backends
GATHER_ALWAYS_ON_CPU = (
    os.environ.get("GUARD_TPU_GATHER_ON_CPU", "1") != "0"
)

# ... with a small-bucket floor: below this node count CPU runs keep
# the one-hot formulation after all. The round-5 CPU tuning was
# measured at the 64-node bucket and above; at the trimmed 16-node
# bucket the registry corpus actually lands in, the gather arm's
# per-op lax.sort overhead dominates and the packed 257-rule program
# runs 4.6x SLOWER than one-hot (0.67s vs 0.14s per 2048-doc run,
# measured on this host for PR 2). 32 keeps the tuned behavior for
# every bucket the round-5 bake-off covered.
GATHER_CPU_MIN_NODES = int(
    os.environ.get("GUARD_TPU_GATHER_CPU_MIN_NODES", "32")
)


def _use_gather(n: int, platform: Optional[str] = None) -> bool:
    """Trace-time formulation choice for an n-node bucket. `platform`
    is the backend the evaluator will actually run on (mesh evaluators
    pass their mesh's device platform — the process default can differ
    under explicit placement); falls back to jax.default_backend()."""
    if n >= GATHER_MIN_NODES:
        return True
    if not GATHER_ALWAYS_ON_CPU or n < GATHER_CPU_MIN_NODES:
        return False
    if platform is None:
        platform = jax.default_backend()
    return platform == "cpu"

from ..core.values import BOOL, FLOAT, INT, LIST, MAP, NULL, STRING
from ..core.values import LOWER_INCLUSIVE, UPPER_INCLUSIVE
from .encoder import DocBatch
from .ir import (
    FAIL,
    PASS,
    SKIP,
    CBlockClause,
    CClause,
    CCountClause,
    CNamedRef,
    CompiledRules,
    CRule,
    CWhenBlock,
    RhsSpec,
    Step,
    StepAllIndices,
    StepAllValues,
    StepFilter,
    StepFnVar,
    StepIndex,
    StepKey,
    StepKeyChain,
    StepKeyInterpLit,
    StepKeyInterpVar,
    StepKeysMatch,
)
from ..core.exprs import CmpOperator


class _DocArrays:
    """Unbatched (per-document) views used inside the vmap'd kernel.

    `gather_mode` selects the traversal-primitive formulation:
    False = fused one-hot masked reductions (O(N^2) lanes per
    primitive, fastest below ~2k nodes where the compare fuses into
    the consuming reduction and XLA streams it on the VPU); True =
    XLA gather/scatter (O(N) work per primitive — `jnp.take` on the
    static parent column and sorted segment-sums — the only
    formulation whose cost stays proportional to document size, used
    for the big buckets where the one-hot's quadratic lane count
    collapses MFU, and for EVERY bucket on CPU backends). Chosen per
    node bucket and platform by _use_gather."""

    def __init__(self, arrays: Dict[str, jnp.ndarray], gather_mode: bool = False):
        self.gather_mode = gather_mode
        self.node_kind = arrays["node_kind"]
        self.node_parent = arrays["node_parent"]
        self.scalar_id = arrays["scalar_id"]
        self.num_hi = arrays["num_hi"]
        self.num_lo = arrays["num_lo"]
        self.child_count = arrays["child_count"]
        self.node_key_id = arrays["node_key_id"]
        self.node_index = arrays["node_index"]
        self.node_parent_kind = arrays["node_parent_kind"]
        self.struct_id = arrays.get("struct_id")  # only for query-RHS rules
        self.fn_origin = arrays.get("fn_origin")  # only per-origin fn rules
        # per-struct-literal (N,) bool columns (encoder.struct_literal_tri):
        # exact compare_eq match/comparable + loose_eq membership
        self.stri_m = {
            int(k[6:]): v for k, v in arrays.items() if k.startswith("stri_m")
        }
        self.stri_c = {
            int(k[6:]): v for k, v in arrays.items() if k.startswith("stri_c")
        }
        self.stri_l = {
            int(k[6:]): v for k, v in arrays.items() if k.startswith("stri_l")
        }
        self.str_rank = arrays.get("str_rank")  # only for ordering-RHS rules
        # host-precomputed per-node bool columns, one per bit-table slot
        self.bits = {
            int(k[4:]): v for k, v in arrays.items() if k.startswith("bits")
        }
        # host-precomputed has-child columns (ir.CompiledRules
        # .kidc_tables): the StepKey/StepIndex resolved checks are
        # static per node, so no count-children reduction is paid
        self.kidc = {
            int(k[4:]): v for k, v in arrays.items() if k.startswith("kidc")
        }
        # folded key-chain columns (ir.StepKeyChain): full-match flag,
        # deep-miss flag, anchor-ancestor index per chain slot
        self.chF = {
            int(k[3:]): v for k, v in arrays.items() if k.startswith("chF")
        }
        self.chM = {
            int(k[3:]): v for k, v in arrays.items() if k.startswith("chM")
        }
        self.chA = {
            int(k[3:]): v for k, v in arrays.items() if k.startswith("chA")
        }
        self.empty_slot = -1  # set by build_doc_evaluator
        # the literals-as-inputs table: (L,) int32 of interned ids for
        # every rule-literal string (CompiledRules.lit_values), passed
        # as a RUNTIME argument (vmap in_axes=None) so the trace carries
        # only static slot indices — corpus-independent, reusable
        self.lits: Optional[jnp.ndarray] = None
        self.n = self.node_kind.shape[0]
        # trace-time accumulator of per-clause "unsure" bits (shapes the
        # kernel cannot decide exactly, routed to the oracle by the
        # backend); eval_rule scoops up the bits its body appended
        self.unsure_acc: List[jnp.ndarray] = []


# ---------------------------------------------------------------------------
# traversal/aggregation primitives — all fused one-hot masked reductions
# (broadcast-compare-select-reduce chains XLA streams on the VPU with no
# materialized intermediates; every alternative with a device gather or
# scatter measured orders of magnitude slower on v5e)
# ---------------------------------------------------------------------------


def _sel_root(d: _DocArrays) -> jnp.ndarray:
    """(N,) selection of the document root (node 0, origin label 1)."""
    return (jnp.arange(d.n, dtype=jnp.int32) == 0).astype(jnp.int32)


def _parent_onehot(d: _DocArrays) -> jnp.ndarray:
    """(N, N) bool: [c, p] = node p is the parent of node c. Cheap to
    recompute per use — XLA CSEs the compare and fuses it into each
    consuming reduction."""
    return d.node_parent[:, None] == jnp.arange(d.n, dtype=jnp.int32)[None, :]


def _parent_select(d: _DocArrays, vec: jnp.ndarray) -> jnp.ndarray:
    """(N,) int32 per-node values -> (N,) value of each node's parent
    (0 where there is no parent: root and padding)."""
    if d.gather_mode:
        got = jnp.take(vec, jnp.maximum(d.node_parent, 0))
        return jnp.where(d.node_parent >= 0, got, 0)
    oh = _parent_onehot(d)
    return jnp.sum(jnp.where(oh, vec[None, :], 0), axis=1)


def _count_children(d: _DocArrays, pred: jnp.ndarray) -> jnp.ndarray:
    """(N,) bool per-node predicate -> (N,) int32 count of each node's
    children satisfying it."""
    if d.gather_mode:
        # scatter-add onto parents; the root's own lane (parent -1 ->
        # clamped 0) never carries pred (pred at the root reflects the
        # root node, whose parent clamp targets itself) — mask it out
        val = (pred & (d.node_parent >= 0)).astype(jnp.int32)
        return jax.ops.segment_sum(
            val, jnp.maximum(d.node_parent, 0), num_segments=d.n
        )
    oh = _parent_onehot(d)
    return jnp.sum(oh & pred[:, None], axis=0, dtype=jnp.int32)


def _segment_count(d: _DocArrays, sel, pred) -> jnp.ndarray:
    """(N+1,) counts of pred-true selected nodes per origin label."""
    active = pred & (sel > 0)
    if d.gather_mode:
        return jax.ops.segment_sum(
            active.astype(jnp.int32),
            jnp.where(active, sel, 0),
            num_segments=d.n + 1,
        )
    labels = jnp.where(active, sel, 0)
    mask = labels[None, :] == jnp.arange(d.n + 1, dtype=jnp.int32)[:, None]
    return jnp.sum(mask & active[None, :], axis=1, dtype=jnp.int32)


def _agg(d: _DocArrays, sel, pred, scalar: bool):
    """Count pred-true selected nodes: per origin label (N+1,) in node
    mode, or one scalar when the selection provably has a single origin
    (rule-root evaluation) — the scalar form replaces the (N+1, N)
    one-hot histogram with an O(N) masked sum."""
    if scalar:
        return jnp.sum(pred & (sel > 0), dtype=jnp.int32)
    return _segment_count(d, sel, pred)


class _UnresAcc:
    """Deferred UnResolved accounting for one query walk.

    A node can leave the selection at most once along a walk (selection
    only moves down the tree) and its origin label is constant while
    selected — so instead of one (N+1, N) histogram per STEP, each step
    records miss labels/counts and the walk pays for a single weighted
    histogram (or one masked sum in scalar mode) at the end. Counts
    matter: key interpolation charges one UnResolved per missing
    (map, key) pair, so a single node can carry several miss events."""

    __slots__ = ("miss_labels", "miss_count", "touched")

    def __init__(self, d: _DocArrays):
        self.miss_labels = jnp.zeros(d.n, jnp.int32)
        self.miss_count = jnp.zeros(d.n, jnp.int32)
        self.touched = False

    def add(self, sel, miss) -> None:
        # every call site's `miss` implies sel > 0
        self.miss_labels = jnp.where(miss, sel, self.miss_labels)
        self.miss_count = self.miss_count + miss.astype(jnp.int32)
        self.touched = True

    def add_count(self, sel, counts) -> None:
        """Charge `counts` (int32 per node, 0 where none) miss events."""
        self.miss_labels = jnp.where(counts > 0, sel, self.miss_labels)
        self.miss_count = self.miss_count + counts
        self.touched = True

    def finalize(self, d: _DocArrays, scalar: bool):
        if not self.touched:
            # no step recorded a miss event (e.g. an RHS walk that is
            # a single StepFnVar, which charges no UnResolved): the
            # counts are structurally zero. Returning the constant
            # directly matters beyond speed — the all-constant
            # segment_sum this would otherwise emit (zero weights
            # scattered at constant zero indices) CRASHES the TPU AOT
            # compiler (scatter_emitter.cc CHECK operand_indices.size()
            # == 1 (2 vs. 1), reproduced round 5 on v5e)
            return (
                jnp.int32(0) if scalar
                else jnp.zeros(d.n + 1, jnp.int32)
            )
        if scalar:
            return jnp.sum(self.miss_count, dtype=jnp.int32)
        weight = jnp.where(self.miss_labels > 0, self.miss_count, 0)
        if d.gather_mode:
            return jax.ops.segment_sum(
                weight, jnp.maximum(self.miss_labels, 0),
                num_segments=d.n + 1,
            )
        mask = self.miss_labels[None, :] == jnp.arange(
            d.n + 1, dtype=jnp.int32
        )[:, None]
        return jnp.sum(jnp.where(mask, weight[None, :], 0), axis=1, dtype=jnp.int32)


def run_steps(d: _DocArrays, steps: List[Step], sel, rule_statuses=None,
              scalar: bool = False, sel_is_root: Optional[bool] = None):
    """Walk a query: returns (leaf selection, unresolved counts) —
    counts are (N+1,) per origin, or a scalar in single-origin mode.

    `sel_is_root`: the incoming selection is exactly `_sel_root` (label
    1 on node 0) — the FIRST step's parent-select is then the static
    elementwise `node_parent == 0` instead of a permutation.

    CONTRACT: `scalar=True` means single-origin ROOT-BASIS evaluation
    (it always has — the scalar aggregations in _agg/_UnresAcc assume
    one origin, which only the rule-root selection provides), so it
    defaults sel_is_root. A future scalar-mode caller evaluating from
    a NON-root single-origin selection must pass sel_is_root=False
    explicitly or the first step miscompiles."""
    if sel_is_root is None:
        sel_is_root = scalar
    acc = _UnresAcc(d)
    for step in steps:
        sel = run_step(d, step, sel, acc, rule_statuses,
                       sel_is_root=sel_is_root)
        sel_is_root = False
    return sel, acc.finalize(d, scalar)


def _key_hit(d: _DocArrays, lit_slots: List[int]) -> jnp.ndarray:
    """(N,) bool: node key id equals any of the slots' runtime literal
    ids (absent strings bind to -99 and never match)."""
    kh = jnp.zeros(d.n, bool)
    for sl in lit_slots:
        kh = kh | (d.node_key_id == d.lits[sl])
    return kh


def _select_at(d: _DocArrays, vec: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """(N,) vec, (N,) static per-node indices -> vec[idx] — the one
    permutation a folded key chain pays (one-hot compare-reduce below
    GATHER_MIN_NODES, XLA gather above)."""
    if d.gather_mode:
        return jnp.take(vec, idx)
    oh = idx[:, None] == jnp.arange(d.n, dtype=jnp.int32)[None, :]
    return jnp.sum(jnp.where(oh, vec[None, :], 0), axis=1)


def run_step(d: _DocArrays, step: Step, sel, acc: _UnresAcc, rule_statuses=None,
             sel_is_root: bool = False):
    if isinstance(step, StepKeyChain):
        # k >= 2 key steps in ONE permutation (ir.StepKeyChain): the
        # anchor column points each full-match / deep-miss node at its
        # would-be basis ancestor; sel[anchor] both relabels the new
        # selection and supplies the charge labels for deep misses.
        # From the root basis the permutation degenerates to the
        # static `anchor == 0` (the columns gate every read of P)
        first = step.steps[0]
        if sel_is_root:
            P = (d.chA[step.chain_slot] == 0).astype(jnp.int32)
        else:
            P = _select_at(d, sel, d.chA[step.chain_slot])
        new_sel = jnp.where(d.chF[step.chain_slot], P, 0)
        if not first.drop_unres:
            # position-0 miss: the basis node itself lacks a k_1 child
            resolved = (
                d.kidc[first.kc_slot]
                if first.kc_slot >= 0
                else _count_children(d, _key_hit(d, first.lit_slots)) > 0
            )
            acc.add(sel, (sel > 0) & ~resolved)
        # deep misses (positions 1..k-1, drop_unres steps pre-excluded
        # in the static column)
        acc.add(P, d.chM[step.chain_slot] & (P > 0))
        return new_sel

    if isinstance(step, StepFnVar):
        # precomputed function-result roots (ops/fnvars.py): orphan
        # nodes tagged with the reserved key id; function variables
        # never carry UnResolved entries.
        hit = d.node_key_id == step.key_id
        if step.per_origin:
            # per-origin results ('pexpr'): each result root carries
            # the candidate node it belongs to in the fn_origin
            # column. The incoming selection labels each candidate
            # with its own origin label (eval_block_clause /
            # StepFilter: idx + 1), so sel[fn_origin] both gates the
            # result (0 when its origin is not currently selected)
            # and relabels it with the origin's label — the
            # per-origin query-RHS join then matches LHS and RHS of
            # the same candidate exactly.
            lab = _select_at(d, sel, jnp.maximum(d.fn_origin, 0))
            return jnp.where(hit & (d.fn_origin >= 0), lab, 0)
        return jnp.where(hit, jnp.int32(1), jnp.int32(0))

    if sel_is_root:
        # sel is exactly `_sel_root` (label 1 on node 0): each node's
        # parent label is the static root-child indicator
        psel = (d.node_parent == 0).astype(jnp.int32)
    else:
        psel = _parent_select(d, sel)  # label of each node's parent
    if isinstance(step, StepKey):
        kh = _key_hit(d, step.lit_slots)
        new_sel = jnp.where(kh, psel, 0)
        if not step.drop_unres:
            # resolved = "has a child under one of the key ids" — a
            # static per-node fact, host-precomputed (step.kc_slot)
            resolved = (
                d.kidc[step.kc_slot]
                if step.kc_slot >= 0
                else _count_children(d, kh) > 0
            )
            acc.add(sel, (sel > 0) & ~resolved)
        return new_sel

    if isinstance(step, StepKeyInterpLit):
        # `.%var` with literal strings: each string is an EXACT key
        # lookup (no converter retry); one UnResolved per missing
        # (map, key) pair; non-map candidates UnResolve first
        # (scopes._retrieve_key:533-632). The per-key has-child checks
        # are static per node (kidc columns)
        is_map_sel = (sel > 0) & (d.node_kind == MAP)
        acc.add(sel, (sel > 0) & (d.node_kind != MAP))
        kh_any = jnp.zeros(d.n, bool)
        for i, sl in enumerate(step.lit_slots):
            hit = d.node_key_id == d.lits[sl]
            kh_any = kh_any | hit
            has = (
                d.kidc[step.kc_slots[i]]
                if i < len(step.kc_slots)
                else _count_children(d, hit) > 0
            )
            acc.add(sel, is_map_sel & ~has)
        # a key id implies a map parent, so psel needs no extra guard
        return jnp.where(kh_any, psel, 0)

    if isinstance(step, StepKeyInterpVar):
        # `.%var` with a query variable: resolve it from the ROOT
        # scope, flatten one level of lists, then exact-match each
        # string against the selected maps' keys
        sel_root = _sel_root(d)
        var_sel, var_unres = run_steps(
            d, step.var_steps, sel_root, rule_statuses, scalar=True
        )
        if step.index is not None:
            # `.%var[k]`: pick the k-th entry of the result list
            # (eval_context.rs:421-526). Resolved entries appear in
            # node (= walk) order; with UnResolved entries present the
            # entry order is ambiguous on device — flag unsure.
            d.unsure_acc.append(var_unres > 0)
            rank = jnp.cumsum((var_sel > 0).astype(jnp.int32))
            kth = (var_sel > 0) & (rank == step.index + 1)
            oob = jnp.int32(step.index) >= (
                jnp.sum(var_sel > 0, dtype=jnp.int32) + var_unres
            )
            # out of bounds: one UnResolved per MAP candidate (the
            # non-map check precedes interpolation and charges its
            # own); in bounds, only the k-th entry participates (kth
            # is empty when oob)
            acc.add(sel, (sel > 0) & (d.node_kind == MAP) & oob)
            var_sel = jnp.where(kth, var_sel, 0)
            var_unres = jnp.int32(0)
        direct = var_sel > 0
        is_list = d.node_kind == LIST
        pvar = _parent_select(d, var_sel)
        elem = (pvar > 0) & (d.node_parent_kind == LIST)
        flat = (direct & ~is_list) | elem
        is_str = d.node_kind == STRING
        good = flat & is_str
        # non-string key values raise NotComparable on the oracle
        # (scopes._retrieve_key:621-631): flag the document unsure
        d.unsure_acc.append(jnp.any(flat & ~is_str))
        is_map_sel = (sel > 0) & (d.node_kind == MAP)
        acc.add(sel, (sel > 0) & (d.node_kind != MAP))
        if d.gather_mode:
            # O(N log N): key-hit via a sorted set join; per-map
            # matched-entry counts via distinct (parent, key) child
            # pairs weighted by the var multiset's per-string
            # multiplicity (kernels.py sorted primitives)
            zeros = jnp.zeros(d.n, jnp.int32)
            vs = jnp.where(good, d.scalar_id, -1)
            kh = _in_set_sorted(
                d.n, zeros, d.node_key_id, d.node_key_id >= 0,
                zeros, vs, good,
            )
            pk_mask = (d.node_key_id >= 0) & (d.node_parent >= 0)
            f_pk = _distinct_first_sorted(
                d.node_parent, d.node_key_id, pk_mask
            )
            mult = _set_count_sorted(
                d.n, zeros, d.node_key_id, f_pk, zeros, vs, good
            )
            matched = jax.ops.segment_sum(
                jnp.where(f_pk, mult, 0),
                jnp.maximum(d.node_parent, 0),
                num_segments=d.n,
            )
            miss_counts = jnp.sum(good, dtype=jnp.int32) - matched
        else:
            # match[c, v]: child c sits under a key equal to var string v
            vids = jnp.where(good, d.scalar_id, -7)
            match = (d.node_key_id[:, None] == vids[None, :]) & good[None, :]
            kh = jnp.any(match, axis=1)
            # found[s, v]: map s has a child under key v — one boolean
            # matmul on the MXU instead of an (N, N, N) reduction
            oh = _parent_onehot(d)  # [c, p]
            found = (
                jnp.matmul(
                    oh.astype(jnp.float32).T,
                    match.astype(jnp.float32),
                    preferred_element_type=jnp.float32,
                )
                > 0.0
            )  # (p, v)
            miss_counts = jnp.sum(
                (~found) & good[None, :], axis=1, dtype=jnp.int32
            )
        acc.add_count(sel, jnp.where(is_map_sel, miss_counts, 0))
        # every UnResolved entry in the variable's own resolution is
        # re-reported per selected candidate
        acc.add_count(sel, jnp.where(sel > 0, var_unres, 0))
        return jnp.where(kh, psel, 0)

    if isinstance(step, StepAllValues):
        # `.*`: all children of maps AND lists; scalars pass through;
        # empty containers are unresolved (eval_context.rs:667-721)
        is_container = (d.node_kind == MAP) | (d.node_kind == LIST)
        keep = jnp.where((sel > 0) & ~is_container, sel, 0)
        new_sel = jnp.maximum(psel, keep)
        empty_c = (sel > 0) & is_container & (d.child_count == 0)
        acc.add(sel, empty_c)
        return new_sel

    if isinstance(step, StepAllIndices):
        # `[*]`: elements of lists; maps and scalars pass through
        # (eval_context.rs:609-665)
        child_sel = jnp.where(d.node_parent_kind == LIST, psel, 0)
        keep = jnp.where((sel > 0) & (d.node_kind != LIST), sel, 0)
        new_sel = jnp.maximum(child_sel, keep)
        empty_l = (sel > 0) & (d.node_kind == LIST) & (d.child_count == 0)
        acc.add(sel, empty_l)
        return new_sel

    if isinstance(step, StepIndex):
        at_idx = d.node_index == step.index
        new_sel = jnp.where(at_idx, psel, 0)
        resolved = (
            d.kidc[step.kc_slot]
            if step.kc_slot >= 0
            else _count_children(d, at_idx & (psel > 0)) > 0
        )
        miss = (sel > 0) & ((d.node_kind != LIST) | ~resolved)
        acc.add(sel, miss)
        return new_sel

    if isinstance(step, StepFilter):
        # list candidates always iterate their elements
        # (eval_context.rs:755-791); map/scalar handling depends on the
        # preceding part (ir.StepFilter docstring)
        is_map = d.node_kind == MAP
        is_list = d.node_kind == LIST
        is_scalar = (sel > 0) & ~is_map & ~is_list
        expand_parent = d.node_parent_kind == LIST
        if step.expand_maps:
            expand_parent = expand_parent | (d.node_parent_kind == MAP)
        elems = jnp.where(expand_parent, psel, 0)
        if not step.scalar_self:
            # scalar candidates are UnResolved either way
            acc.add(sel, is_scalar)
        if step.scalar_self:
            # after a variable head: maps AND scalars filter themselves
            # in their own value scope (scopes.py:390-408 + 708-714 +
            # 749-757); lists still iterate
            keep = jnp.where((sel > 0) & ~is_list, sel, 0)
        elif step.expand_maps:
            # maps expanded to values
            keep = jnp.zeros_like(sel)
        else:
            # after `.*`: maps filter themselves (accumulate_map
            # re-scoped each value)
            keep = jnp.where((sel > 0) & is_map, sel, 0)
        cand = jnp.maximum(elems, keep)  # candidates labeled with OUTER origin
        idx = jnp.arange(d.n, dtype=jnp.int32)
        cand_self = jnp.where(cand > 0, idx + 1, 0)  # each candidate = own origin
        status = eval_conjunctions(d, step.conjunctions, cand_self, rule_statuses)
        st_per_node = status[1:]
        selected = (cand > 0) & (st_per_node == PASS)
        new_sel = jnp.where(selected, cand, 0)
        return new_sel

    if isinstance(step, StepKeysMatch):
        # `[ keys == ... ]` (eval_context.rs:830-922): select map values
        # whose KEY matches; per-node key ids come from the encoder.
        # Non-map candidates are UnResolved (scopes._retrieve_map_key_filter)
        match = _rhs_match_on_keys(d, step.rhs, step.op)
        if step.op_not:
            match = ~match
        new_sel = jnp.where(match & (d.node_key_id >= 0), psel, 0)
        not_map = (sel > 0) & (d.node_kind != MAP)
        acc.add(sel, not_map)
        return new_sel

    raise TypeError(f"unknown step {step!r}")


def _rhs_match_on_keys(d: _DocArrays, rhs: RhsSpec, op: CmpOperator) -> jnp.ndarray:
    """(N,) bool: does this node's map key match the RHS. Lowering
    restricts keys-filter RHS to Eq/In over str/regex/list (the only
    comparators the grammar produces after `keys`, parser.rs:810-835);
    bit columns here are registered with the "key" target."""
    if rhs.kind == "str":
        if op == CmpOperator.In:
            # `keys in 'lit'`: substring containment (operators.rs:218-230)
            return d.bits[rhs.bits_slot] & (d.node_key_id >= 0)
        return d.node_key_id == d.lits[rhs.str_slot]
    if rhs.kind == "regex":
        return d.bits[rhs.bits_slot] & (d.node_key_id >= 0)
    if rhs.kind == "list":
        out = jnp.zeros(d.n, dtype=bool)
        for item in rhs.items:
            out = out | _rhs_match_on_keys(d, item, CmpOperator.Eq)
        return out
    raise TypeError(f"keys filter rhs {rhs.kind}")


# ---------------------------------------------------------------------------
# leaf comparisons
# ---------------------------------------------------------------------------
def _num_eq(d: _DocArrays, key) -> jnp.ndarray:
    """Exact numeric equality against a literal's (hi, lo) key pair."""
    return (d.num_hi == jnp.int32(key[0])) & (d.num_lo == jnp.int32(key[1]))


def _num_lt(d: _DocArrays, key) -> jnp.ndarray:
    """Exact numeric < via lexicographic (hi, lo) compare — both lanes
    are biased int32, so signed compare == the underlying i64/f64
    order (encoder.num_key)."""
    hi, lo = jnp.int32(key[0]), jnp.int32(key[1])
    return (d.num_hi < hi) | ((d.num_hi == hi) & (d.num_lo < lo))


def _num_gt(d: _DocArrays, key) -> jnp.ndarray:
    hi, lo = jnp.int32(key[0]), jnp.int32(key[1])
    return (d.num_hi > hi) | ((d.num_hi == hi) & (d.num_lo > lo))


def _compare_scalar_full(d: _DocArrays, rhs: RhsSpec, op: CmpOperator,
                         loose: bool = False):
    """(match (N,), comparable (N,)) of `node <op> literal` per node.
    Non-comparable pairs FAIL regardless of `not` inversion
    (operators.rs:195-206 keeps NotComparable through the inversion pass,
    operators.rs:774-777). `loose` switches struct literals to loose_eq
    membership semantics (never NotComparable — IN containment)."""
    kind = d.node_kind

    if rhs.kind == "never":
        # literal kinds no document scalar is comparable with (char
        # ranges, char literals): NotComparable -> FAIL everywhere
        never = jnp.zeros(d.n, bool)
        return never, never

    if rhs.kind == "struct":
        # map / nested-list literal: host-precomputed per-node columns
        # with exact compare_eq tri-state (or loose_eq membership)
        # semantics, encoder.struct_literal_tri
        if loose:
            m = d.stri_l[rhs.struct_slot]
            return m, m
        return d.stri_m[rhs.struct_slot], d.stri_c[rhs.struct_slot]

    if op == CmpOperator.Eq or op == CmpOperator.In:
        if rhs.kind == "str":
            comparable = kind == STRING
            return comparable & (d.scalar_id == d.lits[rhs.str_slot]), comparable
        if rhs.kind == "regex":
            comparable = kind == STRING
            return comparable & d.bits[rhs.bits_slot], comparable
        if rhs.kind == "num":
            k = INT if rhs.num_kind == INT else FLOAT
            comparable = kind == k
            return comparable & _num_eq(d, rhs.num_key), comparable
        if rhs.kind == "bool":
            comparable = kind == BOOL
            return comparable & _num_eq(d, rhs.num_key), comparable
        if rhs.kind == "null":
            comparable = kind == NULL
            return comparable, comparable
        if rhs.kind == "range":
            k = INT if rhs.range_kind == 9 else FLOAT
            comparable = kind == k
            lo_ok = (
                ~_num_lt(d, rhs.range_lo_key)
                if rhs.range_incl & LOWER_INCLUSIVE
                else _num_gt(d, rhs.range_lo_key)
            )
            hi_ok = (
                ~_num_gt(d, rhs.range_hi_key)
                if rhs.range_incl & UPPER_INCLUSIVE
                else _num_lt(d, rhs.range_hi_key)
            )
            return comparable & lo_ok & hi_ok, comparable
        raise TypeError(f"eq rhs {rhs.kind}")

    # ordering ops: same-kind scalars only (path_value.rs:1048-1070)
    if rhs.kind == "str":
        # lexicographic string ordering via precomputed tables
        comparable = (kind == STRING) & (d.scalar_id >= 0)
        lt = d.bits[rhs.lt_slot]
        le = d.bits[rhs.le_slot]
        if op == CmpOperator.Gt:
            out = ~le
        elif op == CmpOperator.Ge:
            out = ~lt
        elif op == CmpOperator.Lt:
            out = lt
        else:
            out = le
        return comparable & out, comparable
    if rhs.kind == "null":
        # NULL is ordered and all nulls compare equal (compare_values)
        comparable = kind == NULL
        out = op in (CmpOperator.Ge, CmpOperator.Le)
        return comparable & out, comparable
    if rhs.kind != "num":
        # bool/regex/range/list RHS: NotComparable -> FAIL everywhere
        never = jnp.zeros(d.n, bool)
        return never, never
    k = INT if rhs.num_kind == INT else FLOAT
    comparable = kind == k
    if op == CmpOperator.Gt:
        out = _num_gt(d, rhs.num_key)
    elif op == CmpOperator.Ge:
        out = ~_num_lt(d, rhs.num_key)
    elif op == CmpOperator.Lt:
        out = _num_lt(d, rhs.num_key)
    else:
        out = ~_num_gt(d, rhs.num_key)
    return comparable & out, comparable


def _compare_scalar(d: _DocArrays, rhs: RhsSpec, op: CmpOperator,
                    loose: bool = False):
    return _compare_scalar_full(d, rhs, op, loose=loose)[0]


def _eval_binary_outcomes(d: _DocArrays, c: CClause, sel_leaf):
    """Per-leaf boolean outcome for binary ops, mirroring EqOperation /
    InOperation / CommonOperator (operators.rs:146-598). Returns
    (outcome (N,), active (N,)) where active marks evaluated leaves
    (lists may be expanded to elements)."""
    rhs = c.rhs
    op = c.op
    is_list_leaf = (sel_leaf > 0) & (d.node_kind == LIST)
    is_scalar_leaf = (sel_leaf > 0) & (d.node_kind != LIST) & (d.node_kind != MAP)
    is_map_leaf = (sel_leaf > 0) & (d.node_kind == MAP)
    # a list leaf's element count (only read at list leaves)
    n_child = d.child_count

    if op in (CmpOperator.Gt, CmpOperator.Ge, CmpOperator.Lt, CmpOperator.Le):
        # CommonOperator flattens BOTH sides one level and compares
        # every (lhs value, rhs value) pair (operators.rs:132-176 +
        # evaluator._common_operation): list leaves expand to their
        # elements, a literal-list RHS expands to its items (an empty
        # literal list means zero pairs — vacuously PASS under
        # match_all, FAIL under some). NotComparable pairs FAIL.
        items = rhs.items if rhs.kind == "list" else [rhs]
        node_all = jnp.ones(d.n, bool)
        node_any = jnp.zeros(d.n, bool)
        for item in items:
            if item.kind == "struct":
                # compare_values(x, list/map) raises: NotComparable
                s = jnp.zeros(d.n, bool)
            else:
                m_i, c_i = _compare_scalar_full(d, item, op)
                s = c_i & m_i
            node_all = node_all & s
            node_any = node_any | s
        cnt_all = _count_children(d, node_all)
        cnt_any = _count_children(d, node_any)
        outcome_all = jnp.where(is_list_leaf, cnt_all == n_child, node_all)
        outcome_any = jnp.where(is_list_leaf, cnt_any > 0, node_any)
        return (outcome_all, outcome_any), (sel_leaf > 0)

    if op == CmpOperator.Eq:
        if rhs.kind == "list":
            # list literal RHS (compare_eq list arm + operators.rs
            # :512-528 len-1 unwrap): list leaf -> ordered elementwise
            # compare_eq with SHORT-CIRCUIT NotComparable semantics —
            # item j only evaluates if items 0..j-1 all matched, and a
            # NotComparable pair there makes the whole compare raise
            # (-> FAIL surviving `not`); a False pair just yields
            # False (comparable, invertible). Scalar leaf vs len-1
            # list compares against the element; any other leaf shape
            # is NotComparable.
            items = rhs.items
            n_items = len(items)
            len_ok = d.child_count == n_items
            prefix = len_ok  # all prior items returned True
            raised = jnp.zeros(d.n, bool)
            for j, item in enumerate(items):
                m_j, c_j = _compare_scalar_full(d, item, CmpOperator.Eq)
                at_j = d.node_index == j
                has_m = _count_children(d, m_j & at_j) > 0
                has_c = _count_children(d, c_j & at_j) > 0
                raised = raised | (prefix & ~has_c)
                prefix = prefix & has_c & has_m
            eq_true = prefix
            comparable_list = ~raised
            if c.op_not:
                outcome = jnp.where(
                    is_list_leaf, comparable_list & ~eq_true, False
                )
                if n_items == 1:
                    m1, c1 = _compare_scalar_full(d, items[0], CmpOperator.Eq)
                    outcome = jnp.where(is_scalar_leaf, c1 & ~m1, outcome)
            else:
                outcome = jnp.where(is_list_leaf, eq_true, False)
                if n_items == 1:
                    m1 = _compare_scalar(d, items[0], CmpOperator.Eq)
                    outcome = jnp.where(is_scalar_leaf, m1, outcome)
            return outcome, (sel_leaf > 0)
        # scalar literal RHS: list leaves expand to elements
        match, comparable = _compare_scalar_full(d, rhs, CmpOperator.Eq)
        if c.op_not:
            # `not` only flips comparable pairs; NotComparable stays FAIL
            match = comparable & ~match
        n_child_ok = _count_children(d, match)
        # all expanded elements must pass for match_all; `some` needs
        # any-element, hence the (outcome_all, outcome_any) pair.
        outcome = jnp.where(is_list_leaf, n_child_ok == n_child, match)
        outcome_any = jnp.where(is_list_leaf, n_child_ok > 0, match)
        if rhs.kind != "struct":
            # map leaves vs scalar literals are NotComparable -> FAIL;
            # vs a struct (map) literal they compare directly
            # (compare_eq map-vs-map does not raise)
            outcome = jnp.where(is_map_leaf, False, outcome)
            outcome_any = jnp.where(is_map_leaf, False, outcome_any)
        return (outcome, outcome_any), (sel_leaf > 0)

    if op == CmpOperator.In:
        if rhs.kind == "str":
            # string containment lhs in rhs (operators.rs:218-230),
            # one entry per flattened element; non-strings are
            # NotComparable -> FAIL either way
            comparable = d.node_kind == STRING
            m = comparable & d.bits[rhs.bits_slot]
            if c.op_not:
                m = comparable & ~m
            ok_child = _count_children(d, m)
            outcome_all = jnp.where(is_list_leaf, ok_child == n_child, m)
            outcome_any = jnp.where(is_list_leaf, ok_child > 0, m)
            return (outcome_all, outcome_any), (sel_leaf > 0)
        if rhs.kind == "list":
            # membership via loose_eq (never NotComparable): pure
            # inversion under `not` (operators.rs value_in/list_in)
            m = jnp.zeros(d.n, bool)
            for item in rhs.items:
                m = m | _compare_scalar(d, item, CmpOperator.Eq, loose=True)
            if rhs.items and rhs.items[0].kind == "struct" and rhs.items[0].struct_is_list:
                # rhs's first item is a LIST: whole-value membership
                # for every leaf kind (operators.rs:317-327 list-of-
                # lists branch; scalars/maps use the value_in branch)
                outcome = ~m if c.op_not else m
                return outcome, (sel_leaf > 0)
            # scalar: in == any match; list leaf: ALL elements in rhs
            # (contained_in, operators.rs:256-321); not_in: NO element
            # in rhs AND the list is non-empty — an empty lhs list is
            # a vacuous `in` success, and the inversion of a list_in
            # success is an unconditional FAIL (operator_compare's
            # negation arm emits ("fail", list(l.val)) for successes)
            in_child = _count_children(d, m)
            if c.op_not:
                outcome = jnp.where(
                    is_list_leaf, (in_child == 0) & (n_child > 0), ~m
                )
            else:
                outcome = jnp.where(is_list_leaf, in_child == n_child, m)
            return outcome, (sel_leaf > 0)
        # scalar RHS: _contained_in -> _match_value(compare_eq), where
        # NotComparable stays FAIL through the `not` inversion
        # (evaluator.operator_compare keeps not_comparable tuples), and
        # a LIST lhs vs non-list RHS is NotComparable -> FAIL
        m, comparable = _compare_scalar_full(d, rhs, CmpOperator.Eq)
        if c.op_not:
            m = comparable & ~m
        outcome = jnp.where(is_list_leaf, False, m)
        return outcome, (sel_leaf > 0)

    raise TypeError(f"binary op {op}")


# ---------------------------------------------------------------------------
# clause / block / conjunction evaluation — all per-origin (N+1,) int8
# ---------------------------------------------------------------------------
# ---------------------------------------------------------------------------
# sorted (O(N log N)) set primitives — the gather-mode replacement for
# the (N, N) pairwise matrices in query-RHS compares and key
# interpolation. Each builds on ONE lexicographic lax.sort plus O(N)
# scans/segment-sums, so big node buckets (encoder.NODE_BUCKETS_EXTENDED)
# stay feasible for every rule file.
# ---------------------------------------------------------------------------


def _runs(org_s: jnp.ndarray, key_s: jnp.ndarray) -> jnp.ndarray:
    """Run ids over a SORTED (org, key) sequence (equal pairs share a
    run)."""
    start = jnp.concatenate(
        [
            jnp.ones((1,), bool),
            (org_s[1:] != org_s[:-1]) | (key_s[1:] != key_s[:-1]),
        ]
    )
    return jnp.cumsum(start.astype(jnp.int32)) - 1


def _set_count_sorted(
    n_out: int,
    q_org: jnp.ndarray,
    q_key: jnp.ndarray,
    q_mask: jnp.ndarray,
    s_org: jnp.ndarray,
    s_key: jnp.ndarray,
    s_mask: jnp.ndarray,
) -> jnp.ndarray:
    """(n_out,) int32: for masked query entry i (at index q_idx[i] =
    its position), the number of masked SET entries with the same
    (org, key). Masked-out query entries read 0. One lexicographic
    sort + O(N) scans."""
    nq = q_org.shape[0]
    org = jnp.concatenate(
        [jnp.where(s_mask, s_org, -1), jnp.where(q_mask, q_org, -2)]
    ).astype(jnp.int32)
    key = jnp.concatenate([s_key, q_key]).astype(jnp.int32)
    side = jnp.concatenate(
        [jnp.zeros(s_org.shape[0], jnp.int32), jnp.ones(nq, jnp.int32)]
    )
    idx = jnp.concatenate(
        [jnp.zeros(s_org.shape[0], jnp.int32), jnp.arange(nq, dtype=jnp.int32)]
    )
    org_s, key_s, side_s, idx_s = jax.lax.sort(
        (org, key, side, idx), num_keys=2
    )
    m = org_s.shape[0]
    run_id = _runs(org_s, key_s)
    is_set = (side_s == 0) & (org_s >= 0)
    per_run = jax.ops.segment_sum(
        is_set.astype(jnp.int32), run_id, num_segments=m
    )
    cnt = jnp.take(per_run, run_id)
    tgt = jnp.where((side_s == 1) & (org_s >= 0), idx_s, n_out)
    out = jnp.zeros(n_out + 1, jnp.int32).at[tgt].max(
        jnp.where((side_s == 1) & (org_s >= 0), cnt, 0)
    )
    return out[:n_out]


def _in_set_sorted(
    n_out: int, q_org, q_key, q_mask, s_org, s_key, s_mask
) -> jnp.ndarray:
    """(n_out,) bool: masked query entry has ANY matching masked set
    entry with equal (org, key)."""
    return (
        _set_count_sorted(n_out, q_org, q_key, q_mask, s_org, s_key, s_mask)
        > 0
    )


def _distinct_first_sorted(org, key, mask) -> jnp.ndarray:
    """(N,) bool: True at exactly one representative entry per distinct
    (org, key) among masked entries."""
    n = org.shape[0]
    o = jnp.where(mask, org, -1).astype(jnp.int32)
    k = jnp.where(mask, key, -1).astype(jnp.int32)
    idx = jnp.arange(n, dtype=jnp.int32)
    o_s, k_s, idx_s = jax.lax.sort((o, k, idx), num_keys=3)
    start = jnp.concatenate(
        [jnp.ones((1,), bool), (o_s[1:] != o_s[:-1]) | (k_s[1:] != k_s[:-1])]
    )
    first = start & (o_s >= 0)
    return jnp.zeros(n, bool).at[idx_s].set(first)


def _seg_min_max_keys(seg, mask, hi, lo, num_segments):
    """Per-segment exact (hi, lo)-key minimum and maximum over masked
    entries: ((min_hi, min_lo), (max_hi, max_lo)), int32 each. Empty
    segments read extreme sentinels (callers gate on counts)."""
    # sentinels must not OUTRANK legitimate keys: lo lanes span the
    # full int32 range (encoder.num_key maps the integer 0 to
    # lo = -2**31), so SMALL is exactly INT32_MIN — an excluded entry
    # then ties a legitimate minimum-lo value instead of beating it,
    # which leaves segment_max results correct (same argument for BIG
    # on the min side)
    BIG = jnp.int32(2**31 - 1)
    SMALL = jnp.int32(-(2**31))
    seg_c = jnp.where(mask, seg, num_segments - 1)
    min_hi = jax.ops.segment_min(
        jnp.where(mask, hi, BIG), seg_c, num_segments=num_segments
    )
    max_hi = jax.ops.segment_max(
        jnp.where(mask, hi, SMALL), seg_c, num_segments=num_segments
    )
    at_min = mask & (hi == jnp.take(min_hi, seg_c))
    at_max = mask & (hi == jnp.take(max_hi, seg_c))
    min_lo = jax.ops.segment_min(
        jnp.where(at_min, lo, BIG), seg_c, num_segments=num_segments
    )
    max_lo = jax.ops.segment_max(
        jnp.where(at_max, lo, SMALL), seg_c, num_segments=num_segments
    )
    return (min_hi, min_lo), (max_hi, max_lo)


def _flatten_one_level(d: _DocArrays, sel_v: jnp.ndarray) -> jnp.ndarray:
    """selected()/flattened() (operators.rs:116-144): selected LIST
    values are replaced by their elements (one level); everything else
    keeps its label."""
    psel = _parent_select(d, sel_v)
    child = jnp.where((d.node_parent_kind == LIST) & (psel > 0), psel, 0)
    keep = jnp.where((sel_v > 0) & (d.node_kind != LIST), sel_v, 0)
    return jnp.maximum(child, keep)


def _ordering_outcomes_sorted(d: _DocArrays, c: CClause, lf, rf,
                              lhs_here, rhs_here):
    """(fail_per_i, pass_per_i) for ordering ops against a query RHS
    without the (N, N) pair matrix: the same-kind total order means
    '∃ y: x < y' collapses to 'x < max(same-kind rhs of my origin)'
    (and dually for the other ops / the ¬ok side), so per-(origin,
    kind-class) count/min/max segment aggregates decide every
    element. NULLs all compare equal; cross-kind or non-orderable
    pairs FAIL (path_value.rs:1048-1070)."""
    K = 5  # INT, FLOAT, STRING, NULL, other
    kind = d.node_kind
    kc = jnp.where(
        kind == INT, 0,
        jnp.where(
            kind == FLOAT, 1,
            jnp.where(kind == STRING, 2, jnp.where(kind == NULL, 3, 4)),
        ),
    ).astype(jnp.int32)
    is_str = kind == STRING
    key_hi = jnp.where(is_str, d.str_rank, d.num_hi)
    key_lo = jnp.where(is_str, 0, d.num_lo)

    shared = c.rhs_query_from_root
    # shared-RHS labels are 1 (scalar-mode run); per-origin otherwise
    r_org = rf
    nseg = (d.n + 1) * K
    seg = r_org * K + kc
    cnt = jax.ops.segment_sum(
        jnp.where(rhs_here, 1, 0), jnp.where(rhs_here, seg, 0),
        num_segments=nseg,
    )
    (min_hi, min_lo), (max_hi, max_lo) = _seg_min_max_keys(
        seg, rhs_here, key_hi, key_lo, nseg
    )

    # effective operator: the `not` inversion complements within the
    # same-kind total order (¬(x<y) ⟺ x>=y; null pairs included since
    # lt=gt=False there)
    op = c.op
    if c.op_not:
        op = {
            CmpOperator.Lt: CmpOperator.Ge, CmpOperator.Ge: CmpOperator.Lt,
            CmpOperator.Le: CmpOperator.Gt, CmpOperator.Gt: CmpOperator.Le,
        }[op]

    o_look = jnp.ones(d.n, jnp.int32) if shared else lf
    seg_same = o_look * K + kc
    cnt_same = jnp.take(cnt, seg_same)
    total = jnp.zeros(d.n, jnp.int32)
    for k in range(K):
        total = total + jnp.take(cnt, o_look * K + k)
    mnh = jnp.take(min_hi, seg_same)
    mnl = jnp.take(min_lo, seg_same)
    mxh = jnp.take(max_hi, seg_same)
    mxl = jnp.take(max_lo, seg_same)

    def _lt(ah, al, bh, bl):
        return (ah < bh) | ((ah == bh) & (al < bl))

    x_lt_max = _lt(key_hi, key_lo, mxh, mxl)
    x_le_max = ~_lt(mxh, mxl, key_hi, key_lo)
    x_gt_min = _lt(mnh, mnl, key_hi, key_lo)
    x_ge_min = ~_lt(key_hi, key_lo, mnh, mnl)
    if op == CmpOperator.Lt:
        ok_some, nok_some, null_ok = x_lt_max, x_ge_min, False
    elif op == CmpOperator.Le:
        ok_some, nok_some, null_ok = x_le_max, x_gt_min, True
    elif op == CmpOperator.Gt:
        ok_some, nok_some, null_ok = x_gt_min, x_le_max, False
    else:  # Ge
        ok_some, nok_some, null_ok = x_ge_min, x_lt_max, True

    has_same = cnt_same > 0
    orderable_x = kc <= 2
    is_null_x = kc == 3
    pass_scalar = orderable_x & has_same & ok_some
    pass_null = is_null_x & has_same & null_ok
    pass_per_i = lhs_here & (pass_scalar | pass_null)

    fail_cross = (total - jnp.where(kc <= 3, cnt_same, 0)) > 0
    fail_same = orderable_x & has_same & nok_some
    fail_null = is_null_x & has_same & (not null_ok)
    fail_per_i = lhs_here & (fail_cross | fail_same | fail_null)
    return fail_per_i, pass_per_i


def _eval_query_rhs_ordering(d: _DocArrays, c: CClause, sel, rule_statuses,
                             sel_is_root: bool = False) -> jnp.ndarray:
    """Ordering ops (< <= > >=) against a query RHS: CommonOperator's
    cartesian pair comparison over flattened value sets
    (operators.rs:146-176 + evaluator._common_operation), with
    same-kind-only total order (path_value.rs:1048-1070) — INT/FLOAT
    by the exact (hi, lo) keys, STRING by the host-precomputed rank
    column, NULLs all equal. The `not` inversion flips comparable
    pairs; NotComparable pairs stay FAIL."""
    lhs_sel, lhs_unres = run_steps(
        d, c.steps, sel, rule_statuses, sel_is_root=sel_is_root
    )
    if c.rhs_query_from_root:
        rhs_sel, rhs_unres_s = run_steps(
            d, c.rhs_query_steps, _sel_root(d), rule_statuses, scalar=True
        )
        rhs_unres = jnp.full((d.n + 1,), rhs_unres_s, jnp.int32)
    else:
        rhs_sel, rhs_unres = run_steps(
            d, c.rhs_query_steps, sel, rule_statuses, sel_is_root=sel_is_root
        )
    ones = jnp.ones(d.n, bool)
    n_lhs = _segment_count(d, lhs_sel, ones)
    if c.rhs_query_from_root:
        n_rhs = jnp.full(
            (d.n + 1,), jnp.sum(rhs_sel > 0, dtype=jnp.int32), jnp.int32
        )
    else:
        n_rhs = _segment_count(d, rhs_sel, ones)

    lf = _flatten_one_level(d, lhs_sel)
    rf = _flatten_one_level(d, rhs_sel)
    lhs_here = lf > 0
    rhs_here = rf > 0

    if d.gather_mode:
        # O(N log N): per-(origin, kind-class) rhs count/min/max
        # aggregates replace the (N, N) cartesian comparison
        fail_per_i, pass_per_i = _ordering_outcomes_sorted(
            d, c, lf, rf, lhs_here, rhs_here
        )
        cnt_fail = _segment_count(d, lf, fail_per_i)
        cnt_pass = _segment_count(d, lf, pass_per_i)
        n_lhs_flat = _segment_count(d, lf, jnp.ones(d.n, bool))
        any_fail = (
            (cnt_fail > 0)
            | (lhs_unres > 0)
            | ((rhs_unres > 0) & (n_lhs_flat > 0))
        )
        if c.match_all:
            st = jnp.where(any_fail, FAIL, PASS).astype(jnp.int8)
        else:
            st = jnp.where(cnt_pass > 0, PASS, FAIL).astype(jnp.int8)
        skip = ((n_lhs + lhs_unres) == 0) | ((n_rhs + rhs_unres) == 0)
        return jnp.where(skip, jnp.int8(SKIP), st)

    kind = d.node_kind
    same_kind = kind[:, None] == kind[None, :]
    orderable = (
        (kind == INT) | (kind == FLOAT) | (kind == STRING) | (kind == NULL)
    )
    comp = same_kind & orderable[:, None]
    # lt[i, j]: value i < value j, only meaningful on comparable pairs
    num_lt = (d.num_hi[:, None] < d.num_hi[None, :]) | (
        (d.num_hi[:, None] == d.num_hi[None, :])
        & (d.num_lo[:, None] < d.num_lo[None, :])
    )
    is_str = kind == STRING
    str_lt = d.str_rank[:, None] < d.str_rank[None, :]
    lt = jnp.where(is_str[:, None] & is_str[None, :], str_lt, num_lt)
    is_null = kind == NULL
    lt = jnp.where(is_null[:, None] & is_null[None, :], False, lt)
    gt = lt.T
    if c.op == CmpOperator.Lt:
        ok = lt
    elif c.op == CmpOperator.Le:
        ok = ~gt
    elif c.op == CmpOperator.Gt:
        ok = gt
    else:
        ok = ~lt
    if c.op_not:
        ok = ~ok
    if c.rhs_query_from_root:
        pair = lhs_here[:, None] & rhs_here[None, :]
    else:
        pair = (lf[:, None] == rf[None, :]) & lhs_here[:, None] & rhs_here[None, :]
    success = pair & comp & ok
    fail = pair & ~(comp & ok)
    fail_per_i = jnp.any(fail, axis=1)
    pass_per_i = jnp.any(success, axis=1)
    cnt_fail = _segment_count(d, lf, fail_per_i)
    cnt_pass = _segment_count(d, lf, pass_per_i)
    n_lhs_flat = _segment_count(d, lf, ones)

    any_fail = (
        (cnt_fail > 0)
        | (lhs_unres > 0)
        | ((rhs_unres > 0) & (n_lhs_flat > 0))
    )
    if c.match_all:
        st = jnp.where(any_fail, FAIL, PASS).astype(jnp.int8)
    else:
        st = jnp.where(cnt_pass > 0, PASS, FAIL).astype(jnp.int8)
    skip = ((n_lhs + lhs_unres) == 0) | ((n_rhs + rhs_unres) == 0)
    return jnp.where(skip, jnp.int8(SKIP), st)


def _eval_query_rhs_clause(d: _DocArrays, c: CClause, sel, rule_statuses,
                           sel_is_root: bool = False) -> jnp.ndarray:
    """LHS query vs RHS query, per origin (operators.rs:552-594 Eq
    `query_in` set-difference; :434-451 In containment; the `not`
    inversion reverse-diffs, operators.rs:637-646 via evaluator
    `operator_compare`). Membership tests are canonical struct-id
    equality (= loose_eq, encoder.DocBatch.struct_ids)."""
    lhs_sel, lhs_unres = run_steps(
        d, c.steps, sel, rule_statuses, sel_is_root=sel_is_root
    )
    if c.rhs_query_from_root:
        # root-bound RHS variable: one shared result set for every
        # origin (resolved against the binding scope)
        sel_root = _sel_root(d)
        rhs_sel, rhs_unres_s = run_steps(
            d, c.rhs_query_steps, sel_root, rule_statuses, scalar=True
        )
        rhs_unres = jnp.full((d.n + 1,), rhs_unres_s, jnp.int32)
    else:
        rhs_sel, rhs_unres = run_steps(
            d, c.rhs_query_steps, sel, rule_statuses, sel_is_root=sel_is_root
        )
    ones = jnp.ones(d.n, bool)
    n_lhs = _segment_count(d, lhs_sel, ones)
    if c.rhs_query_from_root:
        n_rhs = jnp.full(
            (d.n + 1,), jnp.sum(rhs_sel > 0, dtype=jnp.int32), jnp.int32
        )
    else:
        n_rhs = _segment_count(d, rhs_sel, ones)
    lhs_total = n_lhs + lhs_unres
    rhs_total = n_rhs + rhs_unres

    if d.gather_mode:
        # O(N log N) sorted-set formulation (big buckets / CPU): no
        # (N, N) matrix is ever built
        q_success = _query_rhs_success_sorted(
            d, c, lhs_sel, rhs_sel, n_lhs, n_rhs, lhs_total, rhs_total
        )
        return _query_rhs_finish(
            d, c, q_success, n_lhs, lhs_unres, rhs_unres,
            lhs_total, rhs_total,
        )

    sid = d.struct_id
    eq = (sid[:, None] == sid[None, :]) & (sid[:, None] >= 0)  # (N,N) loose_eq
    if c.rhs_query_from_root:
        # every (lhs, rhs) pair is in scope — the RHS set is shared
        same_origin = (lhs_sel[:, None] > 0) & (rhs_sel[None, :] > 0)
    else:
        same_origin = (lhs_sel[:, None] == rhs_sel[None, :]) & (lhs_sel[:, None] > 0)

    if c.op == CmpOperator.Eq:
        contained = eq  # loose_eq membership both directions
    else:  # In: contained_in(l, r) — scalar/map in list-r also matches
        is_list = d.node_kind == LIST
        # count children of j loose_eq to i: boolean matmul over nodes
        childmat = (
            (d.node_parent[None, :] == jnp.arange(d.n)[:, None]).T
        ).astype(jnp.float32)  # childmat[c, j] = 1 iff parent(c) == j
        in_list = (eq.astype(jnp.float32) @ childmat) > 0  # (i, j)
        # l LIST in r LIST is mode-dependent (operators.rs:256-321 /
        # evaluator._contained_in): MEMBERSHIP-among-elements when the
        # rhs's FIRST element is itself a list (identity does NOT imply
        # containment there), SUBSET-of-elements otherwise (an empty
        # lhs is a vacuous success); both recurse through loose_eq
        # (= canonical struct-id equality between document values).
        first_is_list = (
            _count_children(d, (d.node_index == 0) & is_list) > 0
        )
        membership_mode = first_is_list & (d.child_count > 0)
        # subset[l, r]: no child of l fails membership among r's
        # children — in_list[c, r] is defined for every node c, so one
        # more boolean matmul regroups it by l's children
        notin = (~in_list).astype(jnp.float32)
        bad = jnp.matmul(
            childmat.T, notin, preferred_element_type=jnp.float32
        )  # (l, r): count of l's children not loose_eq-in r
        subset = bad == 0.0
        ll = jnp.where(membership_mode[None, :], in_list, subset)
        ll_pair = is_list[:, None] & is_list[None, :]
        contained = jnp.where(
            ll_pair,
            ll,
            eq | ((~is_list)[:, None] & is_list[None, :] & in_list),
        )

    # member tests within each origin
    m_lhs_in_rhs = jnp.any(same_origin & (rhs_sel[None, :] > 0) & contained, axis=1)
    lhs_here = lhs_sel > 0
    rhs_here = rhs_sel > 0
    cnt_lhs_not_in = _segment_count(d, lhs_sel, lhs_here & ~m_lhs_in_rhs)

    if c.op == CmpOperator.Eq:
        if c.rhs_query_from_root:
            # one shared RHS set vs per-origin LHS sets: reverse
            # membership is per (origin, rhs-node) — a boolean matmul
            # on the MXU instead of an (N+1, N, N) reduction
            origins = jnp.arange(d.n + 1, dtype=jnp.int32)
            lhs_oh = (lhs_sel[None, :] == origins[:, None]) & lhs_here[None, :]
            eq_f = eq.astype(jnp.float32)
            rhs_in_lhs = (
                jnp.matmul(
                    lhs_oh.astype(jnp.float32), eq_f,
                    preferred_element_type=jnp.float32,
                )
                > 0.0
            )  # (N+1, N)[o, r]: rhs node r loose_eq some lhs of origin o
            cnt_rhs_not_in = jnp.sum(
                rhs_here[None, :] & ~rhs_in_lhs, axis=1, dtype=jnp.int32
            )
        else:
            rl_origin = (rhs_sel[:, None] == lhs_sel[None, :]) & (rhs_sel[:, None] > 0)
            m_rhs_in_lhs = jnp.any(rl_origin & (lhs_sel[None, :] > 0) & eq, axis=1)
            cnt_rhs_not_in = _segment_count(d, rhs_sel, rhs_here & ~m_rhs_in_lhs)
        use_lhs_diff = n_lhs > n_rhs
        diff_cnt = jnp.where(use_lhs_diff, cnt_lhs_not_in, cnt_rhs_not_in)
        q_success = diff_cnt == 0
        if c.op_not and c.rhs_query_from_root:
            # reverse-diff with ONE shared root-resolved RHS set: the
            # diff membership is per (origin, node) — (N+1, N) masks
            # built by boolean matmuls on the MXU (see the non-root arm
            # below for the 4-way side-choice semantics)
            eq_f = eq.astype(jnp.float32)
            diff_l_oh = lhs_oh & (lhs_here & ~m_lhs_in_rhs)[None, :]
            diff_r_oh = rhs_here[None, :] & ~rhs_in_lhs  # (N+1, N)[o, r]
            # in-diff-of-origin-o tests, for ANY node x:
            #   L[o, x] = x loose_eq some lhs-side diff member of o
            #   M[o, x] = x loose_eq some rhs-side diff member of o
            L = (
                jnp.matmul(
                    diff_l_oh.astype(jnp.float32), eq_f,
                    preferred_element_type=jnp.float32,
                )
                > 0.0
            )
            M = (
                jnp.matmul(
                    diff_r_oh.astype(jnp.float32), eq_f,
                    preferred_element_type=jnp.float32,
                )
                > 0.0
            )
            in_diff = jnp.where(use_lhs_diff[:, None], L, M)
            rdiff_a = jnp.sum(lhs_oh & ~in_diff, axis=1, dtype=jnp.int32)
            rdiff_b = jnp.sum(
                rhs_here[None, :] & ~in_diff, axis=1, dtype=jnp.int32
            )
            use_rhs_rdiff = rhs_total >= lhs_total
            rdiff_cnt = jnp.where(use_rhs_rdiff, rdiff_b, rdiff_a)
            q_success = jnp.where(q_success, False, rdiff_cnt == 0)
        elif c.op_not:
            # reverse-diff (operator_compare's inversion arm): the
            # FORWARD diff side is chosen by RESOLVED counts
            # (use_lhs_diff above, :395), but the REVERSE complement
            # side is chosen independently by TOTAL entry counts —
            # `len(rhs) >= len(lhs)` INCLUDING unresolved entries
            # (evaluator.operator_compare:525) — so all four
            # (diff side, rdiff side) combinations occur. Build the
            # per-origin diff membership over BOTH sides, then
            # complement each side against it.
            origins = jnp.arange(d.n + 1, dtype=jnp.int32)
            use_l_at_lhs = jnp.any(
                (lhs_sel[:, None] == origins[None, :]) & use_lhs_diff[None, :],
                axis=1,
            )
            use_l_at_rhs = jnp.any(
                (rhs_sel[:, None] == origins[None, :]) & use_lhs_diff[None, :],
                axis=1,
            )
            diff_l = lhs_here & ~m_lhs_in_rhs & use_l_at_lhs
            diff_r = rhs_here & ~m_rhs_in_lhs & ~use_l_at_rhs
            # in_diff[x on side S] = x loose_eq some diff member of
            # x's origin (diff members carry lhs OR rhs labels)
            def in_diff(side_sel):
                from_l = (lhs_sel[None, :] == side_sel[:, None]) & diff_l[None, :]
                from_r = (rhs_sel[None, :] == side_sel[:, None]) & diff_r[None, :]
                return jnp.any((from_l | from_r) & eq, axis=1)

            rdiff_a = _segment_count(
                d, lhs_sel, lhs_here & ~in_diff(lhs_sel)
            )
            rdiff_b = _segment_count(
                d, rhs_sel, rhs_here & ~in_diff(rhs_sel)
            )
            use_rhs_rdiff = rhs_total >= lhs_total
            rdiff_cnt = jnp.where(use_rhs_rdiff, rdiff_b, rdiff_a)
            q_success = jnp.where(q_success, False, rdiff_cnt == 0)
    else:  # In
        q_success = cnt_lhs_not_in == 0
        if c.op_not:
            diff_lhs = lhs_here & ~m_lhs_in_rhs
            ll_origin = (lhs_sel[:, None] == lhs_sel[None, :]) & (lhs_sel[:, None] > 0)
            in_diff = jnp.any(ll_origin & diff_lhs[None, :] & eq, axis=1)
            rdiff_cnt = _segment_count(d, lhs_sel, lhs_here & ~in_diff)
            q_success = jnp.where(q_success, False, rdiff_cnt == 0)

    return _query_rhs_finish(
        d, c, q_success, n_lhs, lhs_unres, rhs_unres, lhs_total, rhs_total
    )


def _query_rhs_finish(d, c, q_success, n_lhs, lhs_unres, rhs_unres,
                      lhs_total, rhs_total):
    # unresolved entries survive the inversion as FAILs; rhs-unresolved
    # entries exist only when some lhs resolved (evaluator._eq_operation)
    entry_fail = (lhs_unres > 0) | ((rhs_unres > 0) & (n_lhs > 0))
    if c.match_all:
        st = jnp.where(entry_fail | ~q_success, FAIL, PASS).astype(jnp.int8)
    else:
        # `some` needs at least one PASS *entry*: a query_in success
        # records one pass per resolved lhs value
        # (binary_operation's success handler iterates compare[2]), so
        # a vacuous containment with ZERO resolved lhs values emits no
        # passes and FAILs
        st = jnp.where(q_success & (n_lhs > 0), PASS, FAIL).astype(jnp.int8)
    skip = (lhs_total == 0) | (rhs_total == 0)
    return jnp.where(skip, jnp.int8(SKIP), st)


def _query_rhs_success_sorted(d: _DocArrays, c: CClause, lhs_sel, rhs_sel,
                              n_lhs, n_rhs, lhs_total, rhs_total):
    """(N+1,) bool per-origin query_in / containment success — the
    sorted-set counterpart of the dense arm below, reproducing
    operators.rs:552-594 (Eq query_in set-difference), :434-451 (In)
    and the reverse-diff inversion (operators.rs:637-646) through
    per-(origin, struct-id) joins instead of (N, N) matrices.

    The one construction with irreducibly per-PAIR semantics — a list
    LHS value contained in a SUBSET-mode list RHS value
    (operators.rs:256-321 list-vs-list without a list first element) —
    flags the document unsure instead (the oracle reproduces it
    exactly); every other arm is exact here."""
    sid = d.struct_id
    shared = c.rhs_query_from_root
    lhs_here = lhs_sel > 0
    rhs_here = rhs_sel > 0
    valid_l = lhs_here & (sid >= 0)
    valid_r = rhs_here & (sid >= 0)
    zeros = jnp.zeros(d.n, jnp.int32)
    # membership org keys: real origins per side, or one shared key
    l_org = zeros if shared else lhs_sel
    r_org = zeros if shared else rhs_sel

    if c.op == CmpOperator.Eq:
        m_lhs = _in_set_sorted(d.n, l_org, sid, valid_l, r_org, sid, valid_r)
        cnt_lhs_not_in = _segment_count(d, lhs_sel, lhs_here & ~m_lhs)
        if shared:
            # reverse side per origin o: #rhs values loose_eq-present in
            # o's lhs set = sum over DISTINCT (o, sid) lhs entries of
            # the global per-sid rhs count
            w = _set_count_sorted(
                d.n, zeros, sid, valid_l, zeros, sid, valid_r
            )
            f = _distinct_first_sorted(lhs_sel, sid, valid_l)
            cnt_rhs_in = jax.ops.segment_sum(
                jnp.where(f, w, 0), jnp.where(f, lhs_sel, 0),
                num_segments=d.n + 1,
            )
            cnt_rhs_not_in = n_rhs - cnt_rhs_in
        else:
            m_rhs = _in_set_sorted(
                d.n, r_org, sid, valid_r, l_org, sid, valid_l
            )
            cnt_rhs_not_in = _segment_count(d, rhs_sel, rhs_here & ~m_rhs)
        use_lhs_diff = n_lhs > n_rhs
        diff_cnt = jnp.where(use_lhs_diff, cnt_lhs_not_in, cnt_rhs_not_in)
        q_success = diff_cnt == 0
        if c.op_not:
            use_rhs_rdiff = rhs_total >= lhs_total
            if shared:
                # diff side per origin: lhs-side diff members are plain
                # node sets; the rhs-side diff of origin o is
                # {r: sid_r not in lhsset(o)}, whose membership at a
                # node x collapses to sid_x ∉ lhsset(o)
                diff_l = valid_l & ~m_lhs
                # rdiff over the LHS side (per origin o, lhs i of o):
                #   diff=lhs: i ∈ diff_l sids of o?
                #   diff=rhs: no lhs sid can be outside its own lhs set
                #     -> in_diff is False -> every lhs counts
                m_l_in_dl = _in_set_sorted(
                    d.n, lhs_sel, sid, valid_l, lhs_sel, sid, diff_l
                )
                rdiff_a_l = _segment_count(
                    d, lhs_sel, lhs_here & ~m_l_in_dl
                )
                rdiff_a = jnp.where(use_lhs_diff, rdiff_a_l, n_lhs)
                # rdiff over the RHS side (shared rhs values, per o):
                #   diff=lhs: #rhs with sid ∉ diffl-sids(o)
                #   diff=rhs: ¬in_diff ⟺ sid ∈ lhsset(o)
                f_d = _distinct_first_sorted(lhs_sel, sid, diff_l)
                w = _set_count_sorted(
                    d.n, zeros, sid, valid_l, zeros, sid, valid_r
                )
                cnt_rhs_in_dl = jax.ops.segment_sum(
                    jnp.where(f_d, w, 0), jnp.where(f_d, lhs_sel, 0),
                    num_segments=d.n + 1,
                )
                rdiff_b = jnp.where(
                    use_lhs_diff, n_rhs - cnt_rhs_in_dl, cnt_rhs_in
                )
            else:
                # the FORWARD diff side is chosen by RESOLVED counts,
                # the REVERSE side independently by TOTAL counts (see
                # the dense arm's comment); diff members carry lhs OR
                # rhs labels
                use_l_at_lhs = jnp.take(
                    use_lhs_diff, jnp.where(lhs_here, lhs_sel, 0)
                )
                use_l_at_rhs = jnp.take(
                    use_lhs_diff, jnp.where(rhs_here, rhs_sel, 0)
                )
                diff_l = valid_l & ~m_lhs & use_l_at_lhs
                diff_r = valid_r & ~m_rhs & ~use_l_at_rhs
                set_org = jnp.concatenate(
                    [jnp.where(diff_l, lhs_sel, 0),
                     jnp.where(diff_r, rhs_sel, 0)]
                )
                set_sid = jnp.concatenate([sid, sid])
                set_mask = jnp.concatenate([diff_l, diff_r])
                in_diff_l = _in_set_sorted(
                    d.n, lhs_sel, sid, valid_l, set_org, set_sid, set_mask
                )
                in_diff_r = _in_set_sorted(
                    d.n, rhs_sel, sid, valid_r, set_org, set_sid, set_mask
                )
                rdiff_a = _segment_count(d, lhs_sel, lhs_here & ~in_diff_l)
                rdiff_b = _segment_count(d, rhs_sel, rhs_here & ~in_diff_r)
            rdiff_cnt = jnp.where(use_rhs_rdiff, rdiff_b, rdiff_a)
            q_success = jnp.where(q_success, False, rdiff_cnt == 0)
        return q_success

    # In: contained_in per lhs value (operators.rs:256-321). Set
    # sources by lhs shape: any-kind lhs matches rhs values by sid and
    # scalar/map lhs additionally match INSIDE rhs lists; list lhs
    # match list RHS values only in membership mode (first element is
    # itself a list). Subset-mode list-list pairs flag unsure.
    is_list = d.node_kind == LIST
    first_is_list = _count_children(d, (d.node_index == 0) & is_list) > 0
    membership_mode = first_is_list & (d.child_count > 0)
    # children of rhs-selected lists carry the parent's origin key
    pr_org = jnp.take(r_org, jnp.maximum(d.node_parent, 0))
    p_rhs_list = (
        jnp.take((rhs_here & is_list).astype(jnp.int32),
                 jnp.maximum(d.node_parent, 0)) > 0
    ) & (d.node_parent >= 0)
    p_memb = (
        jnp.take((rhs_here & is_list & membership_mode).astype(jnp.int32),
                 jnp.maximum(d.node_parent, 0)) > 0
    ) & (d.node_parent >= 0)
    child_valid = p_rhs_list & (sid >= 0)
    child_memb_valid = p_memb & (sid >= 0)
    # non-list lhs: rhs values (eq) ∪ children of rhs lists
    s_org_nl = jnp.concatenate([r_org, pr_org])
    s_sid_nl = jnp.concatenate([sid, sid])
    s_mask_nl = jnp.concatenate([valid_r, child_valid])
    m_nonlist = _in_set_sorted(
        d.n, l_org, sid, valid_l & ~is_list, s_org_nl, s_sid_nl, s_mask_nl
    )
    # list lhs: non-list rhs values (eq) ∪ children of membership-mode
    # rhs lists
    s_org_l = jnp.concatenate([r_org, pr_org])
    s_sid_l = jnp.concatenate([sid, sid])
    s_mask_l = jnp.concatenate([valid_r & ~is_list, child_memb_valid])
    m_list = _in_set_sorted(
        d.n, l_org, sid, valid_l & is_list, s_org_l, s_sid_l, s_mask_l
    )
    m_lhs = jnp.where(is_list, m_list, m_nonlist)
    # subset-mode pairs (list lhs vs non-membership list rhs) are per
    # PAIR: route the document to the oracle when one can exist
    a = _segment_count(d, lhs_sel, lhs_here & is_list)
    b_mask = rhs_here & is_list & ~membership_mode
    if shared:
        b_any = jnp.sum(b_mask, dtype=jnp.int32) > 0
        subset_possible = jnp.any((a > 0) & b_any)
    else:
        b = _segment_count(d, rhs_sel, b_mask)
        subset_possible = jnp.any((a > 0) & (b > 0))
    d.unsure_acc.append(subset_possible)

    cnt_lhs_not_in = _segment_count(d, lhs_sel, lhs_here & ~m_lhs)
    q_success = cnt_lhs_not_in == 0
    if c.op_not:
        diff_lhs = valid_l & ~m_lhs
        in_diff = _in_set_sorted(
            d.n, lhs_sel, sid, valid_l, lhs_sel, sid, diff_lhs
        )
        rdiff_cnt = _segment_count(d, lhs_sel, lhs_here & ~in_diff)
        q_success = jnp.where(q_success, False, rdiff_cnt == 0)
    return q_success


def eval_clause(d: _DocArrays, c: CClause, sel, rule_statuses=None,
                scalar: bool = False) -> jnp.ndarray:
    if c.eval_from_root and not scalar:
        # root-bound variable head inside a value scope: the result set
        # is origin-independent — evaluate once from the document root
        # and broadcast the status to every origin
        sel_root = _sel_root(d)
        st = eval_clause(d, c, sel_root, rule_statuses, scalar=True)
        return jnp.full((d.n + 1,), st, dtype=jnp.int8)
    if c.rhs_query_steps is not None:
        if c.op in (CmpOperator.Gt, CmpOperator.Ge, CmpOperator.Lt, CmpOperator.Le):
            st = _eval_query_rhs_ordering(
                d, c, sel, rule_statuses, sel_is_root=scalar
            )
        else:
            st = _eval_query_rhs_clause(
                d, c, sel, rule_statuses, sel_is_root=scalar
            )
        return st[1] if scalar else st
    sel_leaf, unres = run_steps(d, c.steps, sel, rule_statuses, scalar=scalar)
    n_res = _agg(d, sel_leaf, jnp.ones(d.n, bool), scalar)
    n_unres = unres
    total = n_res + n_unres

    if c.op.is_unary():
        if c.op == CmpOperator.Empty and c.empty_on_expr:
            # eval.rs:198-298
            is_null = d.node_kind == NULL
            ok_res = jnp.where(c.op_not, ~is_null, is_null)
            if c.negation:
                ok_res = ~ok_res
            pass_res = _agg(d, sel_leaf, ok_res, scalar)
            fail_res = n_res - pass_res
            unres_pass = not c.op_not
            if c.negation:
                unres_pass = not unres_pass
            pass_n = pass_res + (n_unres if unres_pass else 0)
            fail_n = fail_res + (0 if unres_pass else n_unres)
            st = jnp.where(fail_n > 0, FAIL, PASS).astype(jnp.int8)
            empty_result = not c.op_not
            if c.negation:
                empty_result = not empty_result
            empty_status = PASS if empty_result else FAIL
            return jnp.where(total == 0, jnp.int8(empty_status), st)

        # element-wise unary ops (eval.rs:307-405)
        kind = d.node_kind
        if c.op == CmpOperator.Exists:
            base = jnp.ones(d.n, bool)
            unres_base = False
        elif c.op == CmpOperator.Empty:
            str_is_empty = jnp.where(
                kind == STRING, d.bits[d.empty_slot], False
            )
            base = jnp.where(
                (kind == LIST) | (kind == MAP),
                d.child_count == 0,
                str_is_empty,
            )
            unres_base = True
            # elementwise EMPTY on int/float/null RAISES on the oracle
            # (eval.rs:10-30 IncompatibleError): flag the document so
            # the backend reruns it and reproduces the error path
            supported = (
                (kind == STRING) | (kind == LIST) | (kind == MAP)
                | (kind == BOOL)
            )
            d.unsure_acc.append(jnp.any((sel_leaf > 0) & ~supported))
        else:
            target = {
                CmpOperator.IsString: STRING,
                CmpOperator.IsList: LIST,
                CmpOperator.IsMap: MAP,
                CmpOperator.IsInt: INT,
                CmpOperator.IsFloat: FLOAT,
                CmpOperator.IsBool: BOOL,
                CmpOperator.IsNull: NULL,
            }[c.op]
            base = kind == target
            unres_base = False
        outcome = base
        unres_outcome = unres_base
        if c.op_not:
            outcome = ~outcome
            unres_outcome = not unres_outcome
        if c.negation:
            outcome = ~outcome
            unres_outcome = not unres_outcome
        n_pass = _agg(d, sel_leaf, outcome, scalar) + (
            n_unres if unres_outcome else 0
        )
        n_fail = total - n_pass
        if c.match_all:
            st = jnp.where(n_fail > 0, FAIL, PASS).astype(jnp.int8)
        else:
            st = jnp.where(n_pass > 0, PASS, FAIL).astype(jnp.int8)
        return jnp.where(total == 0, jnp.int8(SKIP), st)

    # binary (eval.rs:765-974; operators.rs) — UnResolved LHS entries FAIL
    result = _eval_binary_outcomes(d, c, sel_leaf)
    outcome, active = result
    if isinstance(outcome, tuple):
        outcome_all, outcome_any = outcome
    else:
        outcome_all = outcome_any = outcome
    n_pass_all = _agg(d, sel_leaf, outcome_all, scalar)
    n_pass_any = _agg(d, sel_leaf, outcome_any, scalar)
    n_fail_all = n_res - n_pass_all
    if c.match_all:
        n_fail = n_fail_all + n_unres
        st = jnp.where(n_fail > 0, FAIL, PASS).astype(jnp.int8)
    else:
        st = jnp.where(n_pass_any > 0, PASS, FAIL).astype(jnp.int8)
    return jnp.where(total == 0, jnp.int8(SKIP), st)


def eval_count_clause(d: _DocArrays, c: CCountClause, rule_statuses,
                      scalar: bool) -> jnp.ndarray:
    """`%n <op> rhs` for a count() variable (ir.CCountClause): resolve
    the argument query from the ROOT (the binding basis), count the
    RESOLVED leaves (fn_count skips UnResolved entries,
    functions/collections.rs:6-23), and compare. The status is origin-
    independent — one scalar, broadcast in node mode."""
    if c.static_status is not None:
        st = jnp.int8(c.static_status)
    else:
        sel_leaf, _ = run_steps(
            d, c.steps, _sel_root(d), rule_statuses, scalar=True
        )
        cnt = jnp.sum(sel_leaf > 0, dtype=jnp.int32)
        tag = c.cmp[0]
        if tag == "never":
            ok = jnp.asarray(False)
        elif tag == "int":
            _, v, op, op_not = c.cmp
            v = jnp.int32(v)
            if op == CmpOperator.Eq:
                ok = cnt == v
            elif op == CmpOperator.Gt:
                ok = cnt > v
            elif op == CmpOperator.Ge:
                ok = cnt >= v
            elif op == CmpOperator.Lt:
                ok = cnt < v
            else:
                ok = cnt <= v
            if op_not:
                ok = ~ok
        elif tag == "range":
            _, lo, hi, incl, op_not = c.cmp
            lo_ok = cnt >= lo if incl & LOWER_INCLUSIVE else cnt > lo
            hi_ok = cnt <= hi if incl & UPPER_INCLUSIVE else cnt < hi
            ok = lo_ok & hi_ok
            if op_not:
                ok = ~ok
        else:  # "in" list
            _, ints, op_not = c.cmp
            ok = jnp.asarray(False)
            for v in ints:
                ok = ok | (cnt == jnp.int32(v))
            if op_not:
                ok = ~ok
        st = jnp.where(ok, jnp.int8(PASS), jnp.int8(FAIL))
    if scalar:
        return st
    return jnp.full((d.n + 1,), st, dtype=jnp.int8)


def eval_node(d: _DocArrays, node, sel, rule_statuses, scalar: bool = False) -> jnp.ndarray:
    if isinstance(node, CClause):
        return eval_clause(d, node, sel, rule_statuses, scalar=scalar)
    if isinstance(node, CCountClause):
        return eval_count_clause(d, node, rule_statuses, scalar)
    if isinstance(node, CBlockClause):
        return eval_block_clause(d, node, sel, rule_statuses, scalar=scalar)
    if isinstance(node, CWhenBlock):
        block = eval_conjunctions(d, node.inner, sel, rule_statuses, scalar=scalar)
        if node.conditions is None:
            # ungated grouping (inline-expanded parameterized rule body)
            return block
        cond = eval_conjunctions(d, node.conditions, sel, rule_statuses, scalar=scalar)
        return jnp.where(cond == PASS, block, jnp.int8(SKIP))
    if isinstance(node, CNamedRef):
        # first non-SKIP status among same-named rules, file order
        # (eval_context.rs:1087-1115); SKIP if every one SKIPs
        st = rule_statuses[node.rule_indices[0]]
        d.unsure_acc.append(d.rule_unsure[node.rule_indices[0]])
        for idx in node.rule_indices[1:]:
            st = jnp.where(st == SKIP, rule_statuses[idx], st)
            # an unsure dependency makes the referencing rule unsure too
            d.unsure_acc.append(d.rule_unsure[idx])
        if node.negation:
            out = jnp.where(st == PASS, jnp.int8(FAIL), jnp.int8(PASS))
        else:
            out = jnp.where(st == PASS, jnp.int8(PASS), jnp.int8(FAIL))
        if scalar:
            return out
        return jnp.full((d.n + 1,), out, dtype=jnp.int8)
    raise TypeError(f"unknown node {node!r}")


def eval_block_clause(d: _DocArrays, b: CBlockClause, sel, rule_statuses=None,
                      scalar: bool = False):
    """eval.rs:1303-1426 (+ type blocks, eval.rs:1649-1822)."""
    leaves, unres = run_steps(d, b.query_steps, sel, rule_statuses, scalar=scalar)
    idx = jnp.arange(d.n, dtype=jnp.int32)
    inner_sel = jnp.where(leaves > 0, idx + 1, 0)
    # inner conjunctions evaluate per leaf: always node mode
    inner_status = eval_conjunctions(d, b.inner, inner_sel, rule_statuses)
    leaf_status = inner_status[1:]  # (N,) status per leaf node
    is_leaf = leaves > 0
    # regroup by OUTER origin (labels carried in `leaves`)
    n_pass = _agg(d, leaves, is_leaf & (leaf_status == PASS), scalar)
    n_fail = _agg(d, leaves, is_leaf & (leaf_status == FAIL), scalar)
    n_res = _agg(d, leaves, is_leaf, scalar)
    n_fail = n_fail + unres  # unresolved block values count as fails
    total = n_res + unres
    if b.match_all:
        st = jnp.where(
            n_fail > 0, FAIL, jnp.where(n_pass > 0, PASS, SKIP)
        ).astype(jnp.int8)
    else:
        st = jnp.where(
            n_pass > 0, PASS, jnp.where(n_fail > 0, FAIL, SKIP)
        ).astype(jnp.int8)
    empty_status = FAIL if b.not_empty else SKIP
    return jnp.where(total == 0, jnp.int8(empty_status), st)


def _combine_disjunction(statuses: List[jnp.ndarray]) -> jnp.ndarray:
    """any PASS -> PASS; else any FAIL -> FAIL; else SKIP
    (eval.rs:1989-2034)."""
    any_pass = statuses[0] == PASS
    any_fail = statuses[0] == FAIL
    for s in statuses[1:]:
        any_pass = any_pass | (s == PASS)
        any_fail = any_fail | (s == FAIL)
    return jnp.where(
        any_pass, PASS, jnp.where(any_fail, FAIL, SKIP)
    ).astype(jnp.int8)


def _combine_conjunction(statuses: List[jnp.ndarray]) -> jnp.ndarray:
    """any FAIL -> FAIL; else any PASS -> PASS; else SKIP
    (eval.rs:2057-2064)."""
    any_pass = statuses[0] == PASS
    any_fail = statuses[0] == FAIL
    for s in statuses[1:]:
        any_pass = any_pass | (s == PASS)
        any_fail = any_fail | (s == FAIL)
    return jnp.where(
        any_fail, FAIL, jnp.where(any_pass, PASS, SKIP)
    ).astype(jnp.int8)


def eval_conjunctions(d: _DocArrays, conjunctions, sel, rule_statuses=None,
                      scalar: bool = False):
    conj_statuses = []
    for disj in conjunctions:
        disj_statuses = [
            eval_node(d, n, sel, rule_statuses, scalar=scalar) for n in disj
        ]
        conj_statuses.append(_combine_disjunction(disj_statuses))
    return _combine_conjunction(conj_statuses)


def eval_rule(d: _DocArrays, rule: CRule, rule_statuses) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(status, unsure) of one rule for one document. `unsure` ORs the
    bits clauses in this rule's body appended to d.unsure_acc.

    Rule-level conjunctions evaluate in single-origin scalar mode (the
    selection is the document root): every per-origin (N+1, N) one-hot
    aggregation collapses to an O(N) masked sum; only filter and block
    interiors (genuinely per-node) pay for origin-labeled histograms."""
    mark = len(d.unsure_acc)
    sel_root = _sel_root(d)
    body = eval_conjunctions(
        d, rule.conjunctions, sel_root, rule_statuses, scalar=True
    )
    if rule.conditions is not None:
        cond = eval_conjunctions(
            d, rule.conditions, sel_root, rule_statuses, scalar=True
        )
        status = jnp.where(cond == PASS, body, jnp.int8(SKIP))
    else:
        status = body
    bits = d.unsure_acc[mark:]
    del d.unsure_acc[mark:]
    unsure = jnp.asarray(False)
    for b in bits:
        unsure = unsure | b
    return status, unsure


def build_doc_evaluator(compiled: CompiledRules, with_unsure: bool = False,
                        platform: Optional[str] = None):
    """Returns fn(per-doc arrays dict) -> (num_rules,) int8 statuses,
    or (statuses, unsure (num_rules,) bool) when with_unsure. The
    arrays dict is CompiledRules.device_arrays(batch) sliced per doc.

    The traversal-primitive formulation is picked at TRACE time by
    _use_gather: one-hot masked reductions below GATHER_MIN_NODES on
    accelerators, O(N) gather/segment-sum at and above it (the
    one-hot's N^2 lane count is quadratic in bucket size while the
    walk only ever touches N parent edges) — and gather at EVERY
    bucket on CPU backends (GATHER_ALWAYS_ON_CPU). `platform` is the
    target backend when known (mesh evaluators). Rule files with
    pairwise constructions (query-RHS compares, key interpolation)
    FORCE gather mode above 8,192 nodes regardless of the tuned
    threshold: their one-hot arm builds (N, N) matrices, which only
    the sorted-set gather formulations keep feasible at the extended
    buckets."""
    empty_slot = compiled.str_empty_slot
    force_gather_over = 8192 if compiled.needs_pairwise else None

    def evaluate(arrays: Dict[str, jnp.ndarray], lits: jnp.ndarray):
        n = arrays["node_kind"].shape[-1]
        gather = _use_gather(n, platform) or (
            force_gather_over is not None and n > force_gather_over
        )
        d = _DocArrays(arrays, gather_mode=gather)
        d.lits = lits
        d.empty_slot = empty_slot
        d.rule_unsure = []
        statuses: List[jnp.ndarray] = []
        for rule in compiled.rules:
            st, u = eval_rule(d, rule, statuses)
            statuses.append(st)
            d.rule_unsure.append(u)
        if not statuses:
            out = jnp.zeros((0,), jnp.int8)
            return (out, jnp.zeros((0,), bool)) if with_unsure else out
        out = jnp.stack(statuses)
        if with_unsure:
            return out, jnp.stack(d.rule_unsure)
        return out

    return evaluate


# Status.and_ as a priority order: FAIL dominates, PASS beats SKIP,
# SKIP is the identity — so a segment's folded status is the max
# priority over its rules (qresult.Status.and_ semantics).
_STATUS_PRIO = np.array([1, 2, 0], dtype=np.int8)  # PASS, FAIL, SKIP
_PRIO_STATUS = np.array([2, 0, 1], dtype=np.int8)  # -> SKIP, PASS, FAIL


def segment_doc_status(statuses, seg_ids, n_segments: int):
    """Segment-aware status reduction over a packed rule axis: fold
    (..., R) rule statuses into (..., F) per-segment document statuses,
    where seg_ids maps each packed rule index to its rule FILE
    (ir.PackedRules segments). Reduction is Status.and_ (FAIL dominates,
    PASS beats SKIP, SKIP is the identity), expressed as a segment-max
    over a priority encoding so it stays one fused reduction per
    segment. Accepts jnp arrays (trace-safe, used by packed summary
    paths) or numpy (host-side, used by the backend and bench)."""
    if isinstance(statuses, jnp.ndarray):
        prio = jnp.asarray(_STATUS_PRIO)[statuses]
        moved = jnp.moveaxis(prio, -1, 0)  # (R, ...)
        mx = jax.ops.segment_max(
            moved, jnp.asarray(seg_ids), num_segments=n_segments
        )
        # empty segments come back at the dtype minimum -> clip to SKIP
        mx = jnp.clip(mx, 0, 2)
        return jnp.moveaxis(jnp.asarray(_PRIO_STATUS)[mx], 0, -1)
    statuses = np.asarray(statuses)
    seg_ids = np.asarray(seg_ids)
    prio = _STATUS_PRIO[statuses]
    out = np.zeros(statuses.shape[:-1] + (n_segments,), np.int8)
    np.maximum.at(
        np.moveaxis(out, -1, 0), seg_ids, np.moveaxis(prio, -1, 0)
    )
    return _PRIO_STATUS[out]


def segment_any(flags, seg_ids, n_segments: int):
    """(..., R) bool -> (..., F) bool: does any rule in the segment set
    its flag (e.g. the per-rule unsure bits routed per rule FILE).
    Accepts jnp arrays (trace-safe, used by the device-side rim
    reductions) or numpy (host-side)."""
    if isinstance(flags, jnp.ndarray):
        moved = jnp.moveaxis(flags.astype(jnp.int8), -1, 0)  # (R, ...)
        mx = jax.ops.segment_max(
            moved, jnp.asarray(seg_ids), num_segments=n_segments
        )
        # empty segments come back at the dtype minimum -> False
        return jnp.moveaxis(mx > 0, 0, -1)
    flags = np.asarray(flags)
    seg_ids = np.asarray(seg_ids)
    out = np.zeros(flags.shape[:-1] + (n_segments,), bool)
    np.logical_or.at(
        np.moveaxis(out, -1, 0), seg_ids, np.moveaxis(flags, -1, 0)
    )
    return out


def rim_reduce(statuses, unsure, group_ids, file_ids, last_ids,
               n_groups: int, n_files: int):
    """The post-kernel rim reductions over a (packed) rule axis, in one
    place so the device (jnp, fused into the collect) and the host
    (numpy, per-file fallback paths) produce identical blocks:

      name_statuses (D, G) int8  — per name-group merged status (FAIL
          dominates, PASS beats SKIP, SKIP identity — the same-name
          merge the report layer applies, rule_statuses_from_root);
      name_unsure   (D, G) bool  — any rule in the group unsure;
      doc_status    (D, F) int8  — per-file overall doc status
          (Status.and_ over the file's rules);
      any_fail      (D, F) bool  — any rule in the file FAILed;
      any_unsure    (D, F) bool  — any rule in the file unsure;
      name_last     (D, G) int8  — the group's LAST rule's status (the
          dict-overwrite semantics the sweep tally reproduces).

    `group_ids` maps each rule index to its name group (ir.RimSpec —
    globally numbered across a pack so one reduction serves every
    packed file), `file_ids` to its rule file."""
    name_statuses = segment_doc_status(statuses, group_ids, n_groups)
    doc_status = segment_doc_status(statuses, file_ids, n_files)
    fails = statuses == FAIL
    any_fail = segment_any(fails, file_ids, n_files)
    if isinstance(statuses, jnp.ndarray):
        name_last = jnp.take(statuses, jnp.asarray(last_ids), axis=-1)
    else:
        name_last = np.asarray(statuses)[..., np.asarray(last_ids)]
    if unsure is None:
        if isinstance(statuses, jnp.ndarray):
            name_unsure = jnp.zeros(name_statuses.shape, bool)
            any_unsure = jnp.zeros(any_fail.shape, bool)
        else:
            name_unsure = np.zeros(name_statuses.shape, bool)
            any_unsure = np.zeros(any_fail.shape, bool)
    else:
        name_unsure = segment_any(unsure, group_ids, n_groups)
        any_unsure = segment_any(unsure, file_ids, n_files)
    return (
        name_statuses, name_unsure, doc_status, any_fail, any_unsure,
        name_last,
    )


class BatchEvaluator:
    """Jit-compiled (docs x rules) status evaluator. One instance per
    (compiled rule file); retracing happens only per node/edge bucket.
    When the rule file compares against query RHS, `last_unsure` holds
    the (D, R) bool matrix of results the backend must route to the
    oracle."""

    def __init__(self, compiled: CompiledRules):
        self.compiled = compiled
        self._with_unsure = compiled.needs_unsure
        # lits is batch-constant (in_axes=None): the runtime binding of
        # rule-literal strings to this corpus's interned ids
        self._fn = jax.jit(
            jax.vmap(
                build_doc_evaluator(compiled, with_unsure=self._with_unsure),
                in_axes=(0, None),
            )
        )
        self.last_unsure: Optional[np.ndarray] = None

    def __call__(self, batch: DocBatch) -> np.ndarray:
        """(D, num_rules) int8 statuses: 0 PASS / 1 FAIL / 2 SKIP."""
        arrays = {
            k: jnp.asarray(v)
            for k, v in self.compiled.device_arrays(batch).items()
        }
        out = self._fn(arrays, jnp.asarray(self.compiled.lit_values()))
        if self._with_unsure:
            statuses, unsure = out
            self.last_unsure = np.asarray(unsure)
            return np.asarray(statuses)
        self.last_unsure = None
        return np.asarray(out)


def evaluate_batch(compiled: CompiledRules, batch: DocBatch) -> np.ndarray:
    return BatchEvaluator(compiled)(batch)
