"""The compiled-plan artifact layer: lower once, relocate per chunk.

Sits between rule parsing and dispatch (ROADMAP item 1). Before this
layer the sweep re-ran `compile_rules_file` + `pack_compiled` on every
chunk because compiled IR bakes in chunk-local intern ids; PR 3's
decomposition showed that re-lowering — not ingest or dispatch — was
the dominant per-chunk cost on the registry corpus. The plan layer
removes it in three moves:

1. **Interner-canonical lowering** (`build_plan`): each rule file is
   lowered ONCE against a rule-local canonical `Interner` that starts
   EMPTY — lowering only ever *looks up* document strings (absent
   literals bind to the never-matching id through the runtime
   `lit_values` array), so every bit table starts at length 0 and the
   compiled IR is corpus-independent. The pack plan (membership,
   segment offsets, `RimSpec`) is computed here too, so warm chunks
   skip `pack_compiled` as well.

2. **Per-chunk relocation** (`relocate_batch`): a chunk batch arrives
   in its own interner namespace; relocation interns the chunk's
   strings into the plan interner, remaps the batch's id columns with
   one numpy pass (`encoder.remap_interned_ids` — the symmetric twin
   of the ingest-shard merge), and extends the plan's bit tables over
   just the newly appended strings (`ir.extend_bit_tables`, driven by
   the recorded `bit_specs` predicates). Because `device_arrays`
   gathers bit tables host-side, table growth never reaches the kernel
   trace: zero recompiles, and `trace_signature` — hence the
   `_shared_evaluator_fns` executable cache — is untouched.

3. **Content-addressed disk artifacts** (`get_plan`): the canonical
   plan (still-empty interner + lowered IR + packs) is pickled under
   `GUARD_TPU_PLAN_CACHE_DIR` keyed by a sha256 over (rule-file bytes
   in order, pack config, bucket shape, device kind/count, artifact
   schema version, guard_tpu version). A fresh process with a warm
   cache performs zero lowering passes. Corrupt or mismatched
   artifacts are MISSES, never errors. Jitted executables are not
   serialized here: in-process reuse comes from `_shared_evaluator_fns`
   and cross-process XLA persistence from `GUARD_TPU_JAX_CACHE`
   (backend._setup_compile_cache); where the installed jax lacks a
   stable `jax.export`, the IR-only artifact still skips lowering and
   only re-traces (recorded in the artifact metadata).

Escape hatches: `GUARD_TPU_PLAN_CACHE=0` or `--no-plan-cache` bypasses
the layer entirely (per-chunk lowering, bit-identical output).
Function-variable rule files keep their excluded-from-packing slow
path: they re-encode + re-lower per chunk against the plan interner.

This module imports no jax at module scope (serve sessions stay
jax-free until a tpu-backend request arrives).
"""

from __future__ import annotations

import hashlib
import logging
import os
import pickle
import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from pathlib import Path
from typing import List, Optional, Tuple

import numpy as np

from ..utils.faults import maybe_fail
from ..utils.telemetry import REGISTRY as _TELEMETRY
from ..utils.telemetry import span as _span
from .encoder import Interner, remap_interned_ids
from .ir import (
    CompiledRules,
    PackedRules,
    RimSpec,
    compile_rules_file,
    extend_bit_tables,
    pack_compatible,
)

log = logging.getLogger("guard_tpu.plan")

#: bump when the pickled artifact layout changes — old artifacts then
#: key to different digests and age out as misses.
#: v2: anchor signatures (analysis/signatures.PlanSignatures) ride
#: inside the artifact, digest-versioned with it.
PLAN_SCHEMA_VERSION = 2

#: plan-cache observability, in every --metrics-out snapshot and reset
#: by backend.reset_all_stats(): `hits` counts in-process memo AND disk
#: loads (a warm sweep shows hits > 0 and zero lower_compile seconds),
#: `misses` full builds, `relocations` per-chunk remap+extend passes,
#: `artifacts_saved` / `bytes_loaded` the disk traffic. The three
#: `corrupt_*` counters split load failures by CAUSE so `report` can
#: tell torn writes (`unreadable`) from stale layouts
#: (`version_mismatch`) from real miscompiles (`verify` — a named
#: invariant failed on a structurally readable artifact).
PLAN_COUNTERS = _TELEMETRY.counter_group(
    "plan_cache",
    {
        "hits": 0,
        "misses": 0,
        "relocations": 0,
        "artifacts_saved": 0,
        "bytes_loaded": 0,
        "corrupt_unreadable": 0,
        "corrupt_version_mismatch": 0,
        "corrupt_verify": 0,
    },
)


def plan_stats() -> dict:
    return _TELEMETRY.group_stats("plan_cache")


def reset_plan_stats() -> None:
    _TELEMETRY.reset_group("plan_cache")


def plan_cache_enabled(flag: bool = True) -> bool:
    """The layer's on switch: the caller's --no-plan-cache flag AND the
    `GUARD_TPU_PLAN_CACHE=0` env escape hatch (read at call time so one
    process can compare both paths — the parity tests do)."""
    return bool(flag) and os.environ.get("GUARD_TPU_PLAN_CACHE", "1") != "0"


def plan_cache_dir() -> Path:
    d = os.environ.get("GUARD_TPU_PLAN_CACHE_DIR", "").strip()
    if d:
        return Path(d)
    return Path(os.path.expanduser("~")) / ".cache" / "guard_tpu" / "plans"


def _device_fingerprint() -> Tuple[str, int]:
    """(device kind, device count) for the cache key. Deliberately
    lazy: importable (and keyable, for tests) without jax."""
    try:
        import jax

        return str(jax.default_backend()), int(jax.device_count())
    except Exception:
        return ("unknown", 0)


def _aot_export_supported() -> bool:
    """Whether the installed jax exposes the export/AOT surface. Only
    recorded in artifact metadata today: executables persist through
    GUARD_TPU_JAX_CACHE instead, and IR-only artifacts re-trace."""
    try:
        import jax

        return hasattr(jax, "export")
    except Exception:
        return False


def plan_key(
    rule_files,
    device_kind: Optional[str] = None,
    device_count: Optional[int] = None,
    schema_version: int = PLAN_SCHEMA_VERSION,
    buckets=None,
    pack_max_rules: Optional[int] = None,
) -> str:
    """Content address of a plan: sha256 over everything the canonical
    artifact depends on. The pack plan is a pure function of the rule
    bytes in order plus `PACK_MAX_RULES`, so hashing those covers the
    pack-set; bucket shape and device kind/count key the executables a
    warm process will trace against the plan. File NAMES are excluded —
    the artifact stores none, so byte-identical registries share."""
    from ..ops.backend import PACK_MAX_RULES
    from .encoder import NODE_BUCKETS_EXTENDED

    if device_kind is None or device_count is None:
        dk, dc = _device_fingerprint()
        device_kind = dk if device_kind is None else device_kind
        device_count = dc if device_count is None else device_count
    if buckets is None:
        buckets = NODE_BUCKETS_EXTENDED
    if pack_max_rules is None:
        pack_max_rules = PACK_MAX_RULES
    h = hashlib.sha256()
    h.update(f"schema={schema_version};".encode())
    from .. import __version__

    h.update(f"version={__version__};".encode())
    h.update(f"device={device_kind}x{device_count};".encode())
    h.update(f"buckets={tuple(buckets)};".encode())
    h.update(f"pack_max_rules={pack_max_rules};".encode())
    for rf in rule_files:
        content = rf.content.encode() if isinstance(rf.content, str) else rf.content
        h.update(hashlib.sha256(content).digest())
    return h.hexdigest()


@dataclass
class RulePlan:
    """One registry's canonical compiled program. `interner` starts
    empty and grows monotonically as chunks relocate into it; the
    on-disk artifact is saved BEFORE first use so it stays
    corpus-independent. `compiled[i]` is rule file i's lowered IR, or
    None for function-variable files (the slow path re-encodes and
    re-lowers those per chunk against this same interner). `packs`
    holds the precomputed >= 2-member pack plan: (member file
    positions, PackedRules, RimSpec)."""

    interner: Interner
    compiled: List[Optional[CompiledRules]]
    slow: List[int] = field(default_factory=list)
    packs: List[Tuple[tuple, PackedRules, RimSpec]] = field(
        default_factory=list
    )
    digest: str = ""
    # per-file anchor signatures (analysis/signatures.PlanSignatures):
    # the statically derived key-chain/type-equality anchors relevance
    # routing consumes. None on plans built with extraction disabled —
    # never a correctness dependency.
    signatures: Optional[object] = None

    def all_compiled(self) -> List[CompiledRules]:
        """Every CompiledRules whose bit tables must track the plan
        interner — the per-file programs plus each pack's fused program
        (pack_compiled aliases the underlying arrays, so
        extend_bit_tables' id-memo grows each one exactly once)."""
        parts = [c for c in self.compiled if c is not None]
        parts.extend(p.compiled for _pos, p, _spec in self.packs)
        return parts

    def prepacked_items(self):
        """The dispatch-ready pack list backend.dispatch_packs consumes
        via its `prepacked` parameter: [(pack, PackedRules, RimSpec)]
        with pack = [(file_idx, CompiledRules)]."""
        return [
            ([(fi, self.compiled[fi]) for fi in pos], packed, spec)
            for pos, packed, spec in self.packs
        ]


def build_plan(rule_files) -> RulePlan:
    """Lower + pack the registry once against a fresh empty interner.
    Pure function of (rule bytes, pack config) — everything else in the
    cache key exists to version the executables traced FROM the plan."""
    from ..ops.backend import plan_packs
    from .fnvars import precomputable_fn_vars

    interner = Interner()
    compiled: List[Optional[CompiledRules]] = []
    slow: List[int] = []
    with _span("lower_compile", {"files": len(rule_files), "mode": "plan"}):
        for fi, rf in enumerate(rule_files):
            if precomputable_fn_vars(rf.rules):
                # fn-var files re-encode the batch with per-doc function
                # results before compile — per chunk, on the slow path
                compiled.append(None)
                slow.append(fi)
                continue
            compiled.append(compile_rules_file(rf.rules, interner))
    items = [
        (fi, c)
        for fi, c in enumerate(compiled)
        if c is not None and pack_compatible(c) is None
    ]
    packs = []
    for pack in plan_packs(items):
        if len(pack) < 2:
            continue  # a singleton pack gains nothing over per-file
        with _span("pack_compile", {"files": len(pack), "mode": "plan"}):
            from .ir import pack_compiled

            packed = pack_compiled([c for _fi, c in pack])
            spec = packed.rim_spec()
        packs.append((tuple(fi for fi, _c in pack), packed, spec))
    try:
        from ..analysis.signatures import extract_plan_signatures

        signatures = extract_plan_signatures(rule_files)
    except Exception as e:  # advisory: a plan without anchors still runs
        log.warning("anchor-signature extraction failed (%s); plan "
                    "carries no signatures", e)
        signatures = None
    return RulePlan(
        interner=interner, compiled=compiled, slow=slow, packs=packs,
        signatures=signatures,
    )


def relocate_batch(
    plan: RulePlan, batch, chunk_interner: Interner, verify: bool = True
) -> None:
    """Move one chunk batch into the plan's id namespace, in place:
    intern every chunk string into the plan interner (appending the
    unseen ones), remap the batch's id columns through the resulting
    (chunk id -> plan id) table, then extend the plan's bit tables over
    whatever the interner just gained. After this the batch evaluates
    against the plan's compiled IR bit-identically to IR lowered
    directly against the chunk interner (tests/test_plan_cache.py pins
    the parity). Serialized under PLAN_LOCK: concurrent serve requests
    share one plan object, and interner growth + bit-table extension
    must be atomic with respect to each other.

    With `verify` (and GUARD_TPU_ANALYSIS not 0) the cheap relocation
    invariants run after the extend — a violation here is an
    in-process relocation bug, raised as a hard PlanVerifyError rather
    than letting a stale id gather garbage bit-table rows."""
    with PLAN_LOCK, _span("relocate", {"docs": batch.n_docs}):
        strings = chunk_interner.strings
        if strings:
            remap = np.fromiter(
                (plan.interner.intern(s) for s in strings),
                dtype=np.int32,
                count=len(strings),
            )
            remap_interned_ids(batch, remap)
        extend_bit_tables(plan.all_compiled(), plan.interner)
        PLAN_COUNTERS["relocations"] += 1
        if _verify_enabled(verify):
            from ..analysis.verify import PlanVerifyError, verify_relocation

            violations = verify_relocation(plan, batch)
            if violations:
                raise PlanVerifyError(violations)


def _verify_enabled(flag: bool) -> bool:
    from ..analysis import analysis_enabled

    return analysis_enabled(flag)


# -- in-process memo + on-disk artifacts ------------------------------------

# digest -> RulePlan. Values are the live (grown) plans; sweep chunks,
# serve requests and bench reps in one process share them. Small LRU:
# a plan holds the registry's whole lowered IR.
_PLAN_MEMO: "OrderedDict[str, RulePlan]" = OrderedDict()
_PLAN_MEMO_MAX = 8

#: one lock for the memo/key caches AND per-chunk relocation — the
#: concurrent serving plane (serve/batcher.py) reaches get_plan +
#: relocate_batch from many request threads against shared plan objects
PLAN_LOCK = threading.RLock()

# rule_files identity -> digest, so per-chunk lookups skip re-hashing
# the registry bytes. Values keep the RuleFile objects alive so ids
# cannot be recycled under the cache (same trick as _PACK_CACHE).
_KEY_CACHE: "OrderedDict[tuple, tuple]" = OrderedDict()
_KEY_CACHE_MAX = 8


def clear_plan_memo() -> None:
    """Drop the in-process plan memo and key cache (tests, and
    bench's simulated process restart). Disk artifacts survive."""
    _PLAN_MEMO.clear()
    _KEY_CACHE.clear()


def _digest_for(rule_files) -> str:
    with PLAN_LOCK:
        ident = tuple(id(rf) for rf in rule_files)
        hit = _KEY_CACHE.get(ident)
        if hit is not None:
            _KEY_CACHE.move_to_end(ident)
            return hit[1]
        digest = plan_key(rule_files)
        _KEY_CACHE[ident] = (list(rule_files), digest)
        while len(_KEY_CACHE) > _KEY_CACHE_MAX:
            _KEY_CACHE.popitem(last=False)
        return digest


def plan_digest(rule_files) -> str:
    """Public face of the plan-cache key: the content digest the serve
    coalescing batcher groups in-flight requests by (same digest = same
    lowered program = coalescible into one packed dispatch)."""
    return _digest_for(rule_files)


def _artifact_path(digest: str) -> Path:
    return plan_cache_dir() / f"{digest}.plan"


def save_plan(plan: RulePlan, digest: str) -> bool:
    """Serialize a canonical plan; atomic (tmp + rename) so concurrent
    writers and torn writes can only ever produce a whole artifact or a
    miss. Failures warn and return False — persistence is an
    optimization, never a correctness dependency."""
    with _span("save_plan"):
        try:
            # durability plane's persistence-seam probe: an injected
            # store_write fault exercises this degradation path (a full
            # or unwritable store downgrades to a cache-off warning,
            # never a failed run) exactly like a real ENOSPC would
            maybe_fail("store_write", key=digest)
            payload = {
                "schema": PLAN_SCHEMA_VERSION,
                "version": _guard_version(),
                "digest": digest,
                "aot_export": _aot_export_supported(),
                "plan": plan,
            }
            blob = pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
            path = _artifact_path(digest)
            path.parent.mkdir(parents=True, exist_ok=True)
            tmp = path.with_suffix(f".tmp.{os.getpid()}")
            tmp.write_bytes(blob)
            os.replace(tmp, path)
        except Exception as e:
            log.warning("plan artifact save failed (%s); continuing "
                        "without persistence", e)
            return False
        _save_signature_sidecar(plan, digest, path)
        PLAN_COUNTERS["artifacts_saved"] += 1
        return True


def _save_signature_sidecar(plan: RulePlan, digest: str, path: Path) -> None:
    """The human/router-readable face of the artifact's anchor
    signatures: `<digest>.sigs.json` beside the pickle (routing
    consumers need not unpickle a whole plan to read its anchors).
    Best-effort, like the artifact itself."""
    if getattr(plan, "signatures", None) is None:
        return
    try:
        import json

        from ..analysis.signatures import signatures_payload

        sidecar = path.with_name(f"{digest}.sigs.json")
        tmp = sidecar.with_suffix(f".tmp.{os.getpid()}")
        tmp.write_text(json.dumps(signatures_payload(plan, digest),
                                  indent=1, sort_keys=True))
        os.replace(tmp, sidecar)
    except Exception as e:
        log.warning("signature sidecar save failed (%s)", e)


class _LoadReject(Exception):
    """Internal: a load failure tagged with its cause label —
    `unreadable` (IO / torn pickle), `version-mismatch` (stale
    schema/version/digest/type) or `verify:<invariant>` (a named
    invariant failed on an otherwise readable artifact)."""

    def __init__(self, cause: str, counter: str, detail: str):
        self.cause = cause
        self.counter = counter
        super().__init__(detail)


def load_plan(digest: str, verify: bool = True) -> Optional[RulePlan]:
    """Deserialize a plan artifact, or None on ANY problem — absent
    file, truncated pickle, schema/version/digest mismatch, or (with
    `verify` on) a failed invariant check. A corrupt artifact logs a
    warning NAMING the failure cause, bumps the matching `corrupt_*`
    counter, and counts as a miss; it is rewritten by the save after
    the rebuild."""
    path = _artifact_path(digest)
    with _span("load_plan"):
        try:
            try:
                if not path.exists():
                    return None
                blob = path.read_bytes()
                payload = pickle.loads(blob)
            except Exception as e:
                raise _LoadReject("unreadable", "corrupt_unreadable",
                                  str(e)) from e
            if not isinstance(payload, dict):
                raise _LoadReject("version-mismatch",
                                  "corrupt_version_mismatch",
                                  "artifact payload is not a dict")
            if payload.get("schema") != PLAN_SCHEMA_VERSION:
                raise _LoadReject(
                    "version-mismatch", "corrupt_version_mismatch",
                    f"schema {payload.get('schema')!r} != "
                    f"{PLAN_SCHEMA_VERSION}",
                )
            if payload.get("version") != _guard_version():
                raise _LoadReject("version-mismatch",
                                  "corrupt_version_mismatch",
                                  "guard_tpu version mismatch")
            if payload.get("digest") != digest:
                raise _LoadReject("version-mismatch",
                                  "corrupt_version_mismatch",
                                  "digest mismatch")
            plan = payload.get("plan")
            if not isinstance(plan, RulePlan):
                raise _LoadReject("version-mismatch",
                                  "corrupt_version_mismatch",
                                  "artifact plan is not a RulePlan")
            if _verify_enabled(verify):
                from ..analysis.verify import verify_plan

                violations = verify_plan(plan)
                if violations:
                    raise _LoadReject(
                        f"verify:{violations[0].invariant}",
                        "corrupt_verify",
                        "; ".join(str(v) for v in violations),
                    )
        except _LoadReject as e:
            log.warning(
                "plan artifact %s unusable (cause=%s: %s); treating as "
                "a cache miss", path.name, e.cause, e,
            )
            PLAN_COUNTERS[e.counter] += 1
            return None
        PLAN_COUNTERS["bytes_loaded"] += len(blob)
        return plan


def _guard_version() -> str:
    from .. import __version__

    return __version__


def _memo_store(digest: str, plan: RulePlan) -> None:
    _PLAN_MEMO[digest] = plan
    _PLAN_MEMO.move_to_end(digest)
    while len(_PLAN_MEMO) > _PLAN_MEMO_MAX:
        _PLAN_MEMO.popitem(last=False)


def get_plan(
    rule_files, use_disk: bool = True, verify: bool = True
) -> RulePlan:
    """The layer's one entry point: in-process memo, then the disk
    artifact, then a full build (saved back when `use_disk`). Callers
    gate on `plan_cache_enabled()` BEFORE calling — a disabled plan
    layer means the legacy per-chunk lowering path, untouched.

    `verify` (AND GUARD_TPU_ANALYSIS not 0) runs the plan/IR verifier
    with the asymmetric policy the analysis plane documents: a disk
    artifact failing verification is a logged miss (load_plan), but a
    FRESH build failing is a miscompile in this process and raises
    PlanVerifyError — a hard, named diagnostic."""
    with PLAN_LOCK:
        digest = _digest_for(rule_files)
        plan = _PLAN_MEMO.get(digest)
        if plan is not None:
            _PLAN_MEMO.move_to_end(digest)
            PLAN_COUNTERS["hits"] += 1
            return plan
        if use_disk:
            plan = load_plan(digest, verify=verify)
            if plan is not None:
                plan.digest = digest
                PLAN_COUNTERS["hits"] += 1
                _memo_store(digest, plan)
                return plan
        plan = build_plan(rule_files)
        plan.digest = digest
        if _verify_enabled(verify):
            from ..analysis.verify import PlanVerifyError, verify_plan

            violations = verify_plan(plan)
            if violations:
                raise PlanVerifyError(violations)
        PLAN_COUNTERS["misses"] += 1
        if use_disk:
            # saved BEFORE first relocation: the artifact's interner is
            # still empty, keeping it corpus-independent
            save_plan(plan, digest)
        _memo_store(digest, plan)
        return plan
