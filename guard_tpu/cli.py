"""guard-tpu command-line interface.

Equivalent of the reference's clap-derived CLI
(`/root/reference/guard/src/commands/mod.rs:83-120`, `main.rs:13-44`):
subcommands validate / test / parse-tree / rulegen / completions with the
same flags and exit-code protocol (validate 0/19/5, test 0/7/1).
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from typing import List, Optional

from .commands.completions import Completions
from .commands.lint import Lint
from .commands.parse_tree import ParseTree
from .commands.rulegen import Rulegen
from .commands.test import Test
from .commands.validate import Validate
from .core.errors import GuardError
from .utils.io import Reader, Writer

VERSION = "0.1.0"
PROG = "guard-tpu"


def _add_telemetry_flags(sp: argparse.ArgumentParser) -> None:
    """The telemetry export face (utils/telemetry.py), shared by
    validate / sweep / serve. Either flag enables span tracing for the
    run; with neither, spans stay a single disabled branch."""
    sp.add_argument(
        "--trace-out",
        default=None,
        metavar="FILE",
        help="write a Chrome trace_event JSON profile of this run "
        "(open in Perfetto or chrome://tracing): one lane per pipeline "
        "stage plus per-ingest-worker lanes",
    )
    sp.add_argument(
        "--metrics-out",
        default=None,
        metavar="FILE",
        help="write a schema-versioned JSON metrics snapshot (all "
        "counter groups, histograms and span roll-ups)",
    )


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog=PROG,
        description=(
            "Guard is a general-purpose tool that provides a simple declarative "
            "syntax to define policy-as-code rules and validate JSON/YAML data "
            "against them — with a TPU-native batch evaluation backend."
        ),
    )
    p.add_argument("--version", action="version", version=f"{PROG} {VERSION}")
    sub = p.add_subparsers(dest="command")

    v = sub.add_parser("validate", help="Evaluates rules against data files")
    v.add_argument("--rules", "-r", nargs="*", default=[])
    v.add_argument("--data", "-d", nargs="*", default=[])
    v.add_argument("--input-params", "-i", nargs="*", default=[])
    v.add_argument("--type", "-t", dest="template_type", default=None)
    v.add_argument(
        "--output-format",
        "-o",
        default="single-line-summary",
        choices=["single-line-summary", "json", "yaml", "junit", "sarif"],
    )
    v.add_argument("--show-summary", "-S", default="fail")
    v.add_argument("--alphabetical", "-a", action="store_true")
    v.add_argument("--last-modified", "-m", action="store_true")
    v.add_argument("--verbose", "-v", action="store_true")
    v.add_argument("--print-json", "-p", action="store_true")
    v.add_argument("--payload", "-P", action="store_true")
    v.add_argument("--structured", "-z", action="store_true")
    v.add_argument(
        "--backend",
        default="auto",
        choices=["auto", "cpu", "native", "tpu"],
        help="auto (default) = compiled C++ engine when built, else "
        "pure-Python; native/cpu force one; tpu = JAX batch engine",
    )
    v.add_argument("--statuses-only", action="store_true")
    v.add_argument(
        "--no-pack",
        action="store_true",
        help="tpu backend: disable fused multi-rule-file dispatch "
        "(evaluate each rule file through its own executable)",
    )
    v.add_argument(
        "--no-vector-rim",
        action="store_true",
        help="tpu backend: disable the vectorized results plane "
        "(per-doc scalar status walk instead of mask arithmetic + "
        "bulk report materialization)",
    )
    v.add_argument(
        "--ingest-workers",
        type=int,
        default=None,
        help="tpu backend: worker processes for the parallel host "
        "read/parse/encode plane (default auto; 0 = serial bit-parity "
        "escape hatch; overrides GUARD_TPU_INGEST_WORKERS)",
    )
    v.add_argument(
        "--max-doc-failures",
        type=int,
        default=None,
        help="tpu backend: quarantine documents that fail to "
        "read/parse/encode instead of aborting; exit ERROR only when "
        "more than this many docs were quarantined (0 = quarantine "
        "records but any failing doc still fails the run; omit the "
        "flag for the historical abort-on-first-failure behavior)",
    )
    v.add_argument(
        "--no-plan-cache",
        action="store_true",
        help="tpu backend: disable the compiled-plan artifact layer "
        "(re-lower the rule registry per call instead of reusing the "
        "canonical plan; bit-parity escape hatch — also "
        "GUARD_TPU_PLAN_CACHE=0)",
    )
    v.add_argument(
        "--mesh-shape",
        default=None,
        metavar="RxC",
        help="tpu backend: 2-D (doc shards x pack columns) device mesh "
        "shape, e.g. 2x1 or 2x4; 'auto' (the default when >1 device is "
        "visible) picks 2x1, 'off' is the single-device escape hatch "
        "(overrides GUARD_TPU_MESH)",
    )
    v.add_argument(
        "--no-result-cache",
        action="store_true",
        help="tpu backend: disable the incremental validation plane "
        "(always encode+dispatch every document instead of replaying "
        "unchanged docs from the content-addressed result cache; "
        "bit-parity escape hatch — also GUARD_TPU_RESULT_CACHE=0)",
    )
    v.add_argument(
        "--delta-stats",
        action="store_true",
        help="tpu backend: print a result-cache partition summary "
        "(cached vs dispatched docs) to stderr after the run",
    )
    v.add_argument(
        "--no-verify-plans",
        action="store_true",
        help="tpu backend: skip the analysis plane's plan/IR invariant "
        "verifier after lowering, relocation and artifact load "
        "(advisory escape hatch — also GUARD_TPU_ANALYSIS=0)",
    )
    _add_telemetry_flags(v)

    t = sub.add_parser("test", help="Test rules against expectations")
    t.add_argument("--rules-file", "-r", dest="rules", default=None)
    t.add_argument("--test-data", "-t", dest="test_data", default=None)
    t.add_argument("--dir", "-d", dest="directory", default=None)
    t.add_argument("--alphabetical", "-a", action="store_true")
    t.add_argument("--last-modified", "-m", action="store_true")
    t.add_argument("--verbose", "-v", action="store_true")
    t.add_argument(
        "--output-format",
        "-o",
        default="single-line-summary",
        choices=["single-line-summary", "json", "yaml", "junit"],
    )
    t.add_argument(
        "--backend",
        default="auto",
        choices=["auto", "cpu", "native", "tpu"],
        help="auto (default) = compiled C++ engine when built, else "
        "pure-Python; native/cpu force one; tpu = JAX batch engine",
    )

    s = sub.add_parser(
        "sweep",
        help=(
            "Resumable batch evaluation over a large corpus: chunked TPU "
            "evaluation with a JSONL checkpoint manifest"
        ),
    )
    s.add_argument("--rules", "-r", nargs="*", default=[])
    s.add_argument("--data", "-d", nargs="*", default=[])
    s.add_argument("--manifest", "-M", default="sweep-manifest.jsonl")
    s.add_argument("--chunk-size", "-c", type=int, default=1024)
    s.add_argument("--backend", default="tpu", choices=["cpu", "tpu"])
    s.add_argument(
        "--rule-shards",
        type=int,
        default=1,
        help="split the rule set across this many device groups "
        "(rule-axis parallelism for huge registries)",
    )
    s.add_argument("--last-modified", "-m", action="store_true")
    s.add_argument(
        "--no-pack",
        action="store_true",
        help="tpu backend: disable fused multi-rule-file dispatch "
        "(evaluate each rule file through its own executable)",
    )
    s.add_argument(
        "--no-vector-rim",
        action="store_true",
        help="tpu backend: disable the vectorized results plane "
        "(scalar per-doc chunk tallies)",
    )
    s.add_argument(
        "--ingest-workers",
        type=int,
        default=None,
        help="tpu backend: worker processes for the parallel host "
        "read/parse/encode plane feeding the chunk pipeline (default "
        "auto; 0 = serial bit-parity escape hatch; overrides "
        "GUARD_TPU_INGEST_WORKERS)",
    )
    s.add_argument(
        "--max-doc-failures",
        type=int,
        default=None,
        help="exit ERROR when more than this many documents were "
        "quarantined (failed read/parse/encode). Default: unlimited — "
        "quarantined docs are recorded but never fail the run by "
        "themselves; 0 restores the historical any-doc-error-is-fatal "
        "exit code",
    )
    s.add_argument(
        "--no-plan-cache",
        action="store_true",
        help="tpu backend: disable the compiled-plan artifact layer "
        "(re-lower the rule registry per chunk instead of relocating "
        "into the canonical plan; bit-parity escape hatch — also "
        "GUARD_TPU_PLAN_CACHE=0)",
    )
    s.add_argument(
        "--mesh-shape",
        default=None,
        metavar="RxC",
        help="tpu backend: 2-D (doc shards x pack columns) device mesh "
        "shape, e.g. 2x1 or 2x4; 'auto' (the default when >1 device is "
        "visible) picks 2x1, 'off' is the single-device escape hatch "
        "(overrides GUARD_TPU_MESH)",
    )
    s.add_argument(
        "--no-result-cache",
        action="store_true",
        help="tpu backend: disable the incremental validation plane "
        "(always encode+dispatch every document instead of replaying "
        "unchanged docs from the content-addressed result cache; "
        "bit-parity escape hatch — also GUARD_TPU_RESULT_CACHE=0)",
    )
    s.add_argument(
        "--delta-stats",
        action="store_true",
        help="tpu backend: print a result-cache partition summary "
        "(cached vs dispatched docs) to stderr after the run",
    )
    s.add_argument(
        "--no-verify-plans",
        action="store_true",
        help="tpu backend: skip the analysis plane's plan/IR invariant "
        "verifier after lowering, relocation and artifact load "
        "(advisory escape hatch — also GUARD_TPU_ANALYSIS=0)",
    )
    s.add_argument(
        "--follow",
        action="store_true",
        help="streaming CI mode: validate JSONL documents from stdin "
        "as they arrive (micro-batch dispatch against the precompiled "
        "plan, one result line per input line, summary + sweep exit "
        "code at EOF; GUARD_TPU_FOLLOW_WAIT_MS bounds formation "
        "latency)",
    )
    s.add_argument(
        "--resume",
        action="store_true",
        help="durability plane: replay this run's chunk journal — "
        "completed chunks replay with zero encode and zero device "
        "dispatches, the sweep continues from the first incomplete "
        "chunk, and stdout/stderr/manifest/exit code are byte-"
        "identical to an uninterrupted run (stale journal = logged "
        "cold start; also GUARD_TPU_SWEEP_RESUME=auto)",
    )
    s.add_argument(
        "--no-journal",
        action="store_true",
        help="disable the per-run chunk journal (no checkpointing — "
        "a killed run cannot --resume; bit-parity escape hatch, also "
        "GUARD_TPU_SWEEP_JOURNAL=0)",
    )
    _add_telemetry_flags(s)

    li = sub.add_parser(
        "lint",
        help="Statically analyze Guard rule files: unsatisfiable "
        "conjunctions, type conflicts, dead `when` guards, shadowed "
        "and duplicate rules, unreferenced variables — no data files "
        "needed (exit 0 clean / 19 findings at --fail-on / 5 parse "
        "error)",
    )
    li.add_argument(
        "--rules",
        "-r",
        nargs="*",
        default=[],
        help="rule files or directories to lint (directories are "
        "walked for .guard/.ruleset files)",
    )
    li.add_argument(
        "--structured",
        "-z",
        action="store_true",
        help="emit machine-readable JSON ({findings: [...], summary: "
        "{...}}) on stdout instead of file:line:col text",
    )
    li.add_argument(
        "--fail-on",
        default="error",
        choices=["error", "warning", "info", "never"],
        help="weakest finding severity that makes lint exit 19 "
        "(default error; never = report only, always exit 0 unless a "
        "file fails to parse)",
    )
    li.add_argument("--last-modified", "-m", action="store_true")

    pt = sub.add_parser("parse-tree", help="Prints the parse tree for a rules file")
    pt.add_argument("--rules", "-r", default=None)
    pt.add_argument("--output", "-o", default=None)
    pt.add_argument("--print-json", "-p", action="store_true")
    pt.add_argument("--print-yaml", "-y", action="store_true")

    rg = sub.add_parser("rulegen", help="Autogenerate rules from a CFN template")
    rg.add_argument("--template", "-t", required=True)
    rg.add_argument("--output", "-o", default=None)

    c = sub.add_parser("completions", help="Generate shell completions")
    c.add_argument("--shell", "-s", default="bash", choices=["bash", "zsh", "fish"])

    sv = sub.add_parser(
        "serve",
        help="Persistent validate session: newline-delimited JSON "
        "payload requests on stdin, one JSON response line each "
        "(amortizes startup for embedders, e.g. the npm package)",
    )
    # the transport must be chosen explicitly: --stdio for a piped
    # session, --listen for the threaded TCP/HTTP listener (both at
    # once is fine — one warm process serving pipes and sockets)
    sv.add_argument("--stdio", action="store_true")
    sv.add_argument(
        "--listen",
        default=None,
        metavar="HOST:PORT",
        help="serve the same protocol to TCP/HTTP clients (port 0 = "
        "OS-assigned, announced on stderr); shares the session's "
        "prepared-rules cache, plan memo and coalescing batcher "
        "across connections",
    )
    sv.add_argument(
        "--no-coalesce",
        action="store_true",
        help="disable cross-request batch coalescing (same as "
        "GUARD_TPU_COALESCE=0): every request dispatches alone",
    )
    sv.add_argument(
        "--rules",
        "-r",
        nargs="*",
        default=None,
        metavar="FILE",
        help="rule files preloaded as the session registry for the "
        "POST /webhook face (AdmissionReview objects validate against "
        "these; without it the webhook answers allowed with a "
        "'no rules configured' message)",
    )
    sv.add_argument(
        "--tenant",
        default=None,
        metavar="ID",
        help="connection-default tenant id for the front door's "
        "per-tenant admission quotas (requests may override via their "
        "\"tenant\" field or the X-Guard-Tenant header; also "
        "GUARD_TPU_TENANT_DEFAULT)",
    )
    _add_telemetry_flags(sv)

    rp = sub.add_parser(
        "report",
        help="Render and diff run-ledger records (the operations "
        "plane's cross-run memory; needs GUARD_TPU_LEDGER_DIR or "
        "--ledger)",
    )
    rp.add_argument(
        "--ledger",
        default=None,
        metavar="FILE",
        help="ledger JSONL to read (default: "
        "$GUARD_TPU_LEDGER_DIR/ledger.jsonl)",
    )
    rp.add_argument(
        "--baseline",
        default=None,
        metavar="FILE",
        help="diff the newest record against the newest record of this "
        "committed baseline ledger instead of the previous record",
    )
    rp.add_argument(
        "--efficiency",
        action="store_true",
        help="render the newest record's hardware-efficiency metrics "
        "(padding waste, pack occupancy, transfer bytes)",
    )
    rp.add_argument(
        "--check",
        default=None,
        metavar="METRIC",
        help="min-of-N noise-band regression gate on this headline "
        "metric; exits 19 on a regression",
    )
    rp.add_argument("--tolerance", type=float, default=0.15)
    rp.add_argument(
        "--window",
        type=int,
        default=3,
        help="how many prior records form the noise band (best-of-N "
        "baseline)",
    )

    g = sub.add_parser(
        "gc",
        help="Store hygiene: size-capped LRU eviction over the plan "
        "cache, result cache and sweep journal dir "
        "(GUARD_TPU_CACHE_MAX_BYTES / --max-bytes, mtime-ordered) "
        "plus orphan-tmp reaping; crash-safe and always exit 0",
    )
    g.add_argument(
        "--max-bytes",
        type=int,
        default=None,
        metavar="N",
        help="evict least-recently-used store entries until each "
        "store is under this many bytes (default "
        "GUARD_TPU_CACHE_MAX_BYTES, else 1 GiB; 0 = empty the stores)",
    )
    g.add_argument(
        "--dry-run",
        action="store_true",
        help="report what would be evicted/reaped without deleting "
        "anything",
    )

    return p


def run(argv: Optional[List[str]] = None, writer: Optional[Writer] = None, reader: Optional[Reader] = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    writer = writer or Writer()
    reader = reader or Reader()

    if args.command is None:
        parser.print_help()
        return 0

    # telemetry export face: either flag turns span tracing on for the
    # whole invocation; exports happen in `finally` so a code-5 run
    # still leaves its profile behind for diagnosis
    trace_out = getattr(args, "trace_out", None)
    metrics_out = getattr(args, "metrics_out", None)
    if trace_out or metrics_out:
        from .utils import telemetry

        telemetry.enable()
        telemetry.reset_trace()
    t0 = time.perf_counter()
    rc: Optional[int] = None
    try:
        rc = _dispatch(args, writer, reader)
        return rc
    except BrokenPipeError:
        rc = 141
        raise
    finally:
        if trace_out or metrics_out:
            from .utils import telemetry

            if trace_out:
                telemetry.write_trace(trace_out)
            if metrics_out:
                telemetry.write_metrics(metrics_out)
            telemetry.disable()
        _session_epilogue(args, rc, time.perf_counter() - t0)


def _session_epilogue(args, rc: Optional[int], dt: float) -> None:
    """Operations-plane exit hooks for the engine-driving commands:
    the flight recorder dumps forensics on abnormal exits (code 5,
    unhandled exceptions — rc None here — or latched fault activity),
    and the run ledger appends one session record when
    GUARD_TPU_LEDGER_DIR is set. Both are best-effort: a failing dump
    or append must never change the session's exit code."""
    if args.command not in ("validate", "sweep", "serve"):
        return
    from .utils import telemetry

    try:
        telemetry.flightrec_on_exit(rc)
    except Exception:
        pass
    from .utils import ledger

    if not ledger.ledger_enabled():
        return
    # incremental-plane session shape: what fraction of eligible docs
    # actually hit the device (None when the run never partitioned)
    extra = None
    try:
        gauges = telemetry.REGISTRY.snapshot().get("gauges", {})
        total = gauges.get("result_cache.total_docs")
        if total:
            extra = {
                "delta_docs": gauges.get("result_cache.delta_docs"),
                "total_docs": total,
                "delta_fraction": gauges.get(
                    "result_cache.delta_docs", 0
                ) / total,
            }
            # the registry is process-global: zero the gauges so a
            # later session that never partitions (cpu backend, cache
            # off) cannot inherit this session's delta story
            telemetry.REGISTRY.set_gauge("result_cache.delta_docs", 0)
            telemetry.REGISTRY.set_gauge("result_cache.total_docs", 0)
    except Exception:
        extra = None
    # durability plane: a resumed sweep's record carries which run it
    # resumed and how many chunks replayed (same read-then-clear
    # handoff as the delta gauges); a drained session is recorded
    # distinctly — its exit code is DRAIN_EXIT_CODE (75), never the
    # error ladder's 5, and the extra names it so `report` can surface
    # the drain/resume story without exit-code archaeology
    try:
        from .utils import journal as _journal

        info = _journal.pop_resume_info()
        if info:
            extra = {**(extra or {}), **info}
        if rc == _journal.DRAIN_EXIT_CODE:
            extra = {**(extra or {}), "drained": True}
    except Exception:
        pass
    try:
        ledger.append_record(
            kind=args.command,
            headline={
                "metric": f"{args.command}_session_seconds",
                "value": dt,
                "unit": "seconds",
            },
            config=dict(sorted(vars(args).items())),
            exit_code=rc,
            extra=extra,
        )
    except Exception:
        pass


def _dispatch(args, writer: Writer, reader: Reader) -> int:
    # --mesh-shape wins over the GUARD_TPU_MESH environment: the mesh
    # plane resolves its shape from the env at dispatch time
    # (parallel/mesh2d.resolve_mesh_shape), so the flag just seeds it
    if getattr(args, "mesh_shape", None) is not None:
        os.environ["GUARD_TPU_MESH"] = args.mesh_shape
    try:
        if args.command == "validate":
            cmd = Validate(
                rules=args.rules,
                data=args.data,
                input_params=args.input_params,
                output_format=args.output_format,
                show_summary=args.show_summary.split(","),
                alphabetical=args.alphabetical,
                last_modified=args.last_modified,
                verbose=args.verbose,
                print_json=args.print_json,
                payload=args.payload,
                structured=args.structured,
                backend=args.backend,
                statuses_only=args.statuses_only,
                pack_rules=not args.no_pack,
                vector_rim=not args.no_vector_rim,
                ingest_workers=args.ingest_workers,
                max_doc_failures=args.max_doc_failures,
                plan_cache=not args.no_plan_cache,
                result_cache=not args.no_result_cache,
                delta_stats=args.delta_stats,
                verify_plans=not args.no_verify_plans,
            )
            return cmd.execute(writer, reader)
        if args.command == "test":
            return Test(
                rules=args.rules,
                test_data=args.test_data,
                directory=args.directory,
                alphabetical=args.alphabetical,
                last_modified=args.last_modified,
                verbose=args.verbose,
                output_format=args.output_format,
                backend=args.backend,
            ).execute(writer, reader)
        if args.command == "sweep":
            from .commands.sweep import Sweep

            return Sweep(
                rules=args.rules,
                data=args.data,
                manifest=args.manifest,
                chunk_size=args.chunk_size,
                backend=args.backend,
                rule_shards=args.rule_shards,
                last_modified=args.last_modified,
                pack_rules=not args.no_pack,
                vector_rim=not args.no_vector_rim,
                ingest_workers=args.ingest_workers,
                max_doc_failures=args.max_doc_failures,
                plan_cache=not args.no_plan_cache,
                result_cache=not args.no_result_cache,
                delta_stats=args.delta_stats,
                verify_plans=not args.no_verify_plans,
                follow=args.follow,
                journal=not args.no_journal,
                resume=args.resume,
            ).execute(writer, reader)
        if args.command == "lint":
            return Lint(
                rules=args.rules,
                structured=args.structured,
                fail_on=args.fail_on,
                last_modified=args.last_modified,
            ).execute(writer, reader)
        if args.command == "parse-tree":
            return ParseTree(
                rules=args.rules,
                output=args.output,
                print_json=args.print_json,
                print_yaml=args.print_yaml,
            ).execute(writer, reader)
        if args.command == "rulegen":
            return Rulegen(template=args.template, output=args.output).execute(
                writer, reader
            )
        if args.command == "completions":
            return Completions(shell=args.shell).execute(writer, reader)
        if args.command == "serve":
            if not args.stdio and not args.listen:
                writer.writeln_err(
                    "serve requires a transport: --stdio and/or "
                    "--listen HOST:PORT"
                )
                return 5
            from .commands.serve import Serve

            coalesce = False if args.no_coalesce else None
            return Serve(
                stdio=args.stdio,
                listen=args.listen,
                coalesce=coalesce,
                rules=args.rules,
                default_tenant=args.tenant,
            ).execute(writer, reader)
        if args.command == "gc":
            from .commands.gc import Gc

            return Gc(
                max_bytes=args.max_bytes,
                dry_run=args.dry_run,
            ).execute(writer, reader)
        if args.command == "report":
            from .commands.ops_report import OpsReport

            return OpsReport(
                ledger_file=args.ledger,
                baseline=args.baseline,
                efficiency=args.efficiency,
                check=args.check,
                tolerance=args.tolerance,
                window=args.window,
            ).execute(writer, reader)
    except GuardError as e:
        writer.writeln_err(f"Error: {e}")
        return 5
    except BrokenPipeError:
        # preserved for main()'s quiet-SIGPIPE handling (exit 141)
        raise
    except OSError as e:
        # nonexistent/unreadable paths exit 5 with a clean message, as
        # in the reference ("any of the specified paths do not exist",
        # parse_tree.rs:44)
        writer.writeln_err(f"Error: {e}")
        return 5
    return 0


def main() -> None:
    try:
        code = run()
        sys.stdout.flush()
    except BrokenPipeError:
        # stdout consumer (e.g. `| head`) closed early — exit quietly
        # with the conventional SIGPIPE code
        import os

        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(141)
    sys.exit(code)


if __name__ == "__main__":
    main()
