"""Library API: `run_checks` and the builder surface.

Equivalent of the reference's embedding points:
  * `run_checks` / `validate_and_return_json`
    (`/root/reference/guard/src/lib.rs:11`,
    `guard/src/commands/helper.rs:25-87`) — one-shot validate returning
    a JSON string (or the verbose event tree when verbose=True); the
    surface that FFI, Lambda and fuzzers converge on.
  * `ValidateBuilder` / `TestBuilder` / `ParseTreeBuilder` /
    `RulegenBuilder` (`guard/src/lib.rs:28-495`) — programmatic command
    construction with the same conflict validation as the CLI.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import List, Optional

from .commands.parse_tree import ParseTree
from .commands.report import (
    rule_statuses_from_root,
    serde_record_json,
    simplified_report_from_root,
)
from .commands.rulegen import Rulegen
from .commands.test import Test
from .commands.validate import Validate
from .core.errors import GuardError, ParseError
from .core.evaluator import eval_rules_file
from .core.loader import load_document
from .core.parser import parse_rules_file
from .core.scopes import RootScope
from .utils.io import Reader, Writer


def run_checks(data: str, rules: str, verbose: bool = False,
               data_file_name: str = "", rules_file_name: str = "") -> str:
    """validate_and_return_json (helper.rs:25-87): evaluate one rules
    string against one data string, return a JSON report string."""
    try:
        path_value = load_document(data, data_file_name)
    except ParseError as e:
        raise ParseError(
            f"Unable to process data in file {data_file_name}, Error {e},"
        )
    rules_file = parse_rules_file(rules, rules_file_name)
    if rules_file is None:
        return ""
    scope = RootScope(rules_file, path_value)
    eval_rules_file(rules_file, scope, data_file_name or None)
    root_record = scope.reset_recorder().extract()
    if verbose:
        return json.dumps(
            serde_record_json(root_record), indent=2, ensure_ascii=False
        )
    report = simplified_report_from_root(root_record, data_file_name)
    return json.dumps([report], indent=2, ensure_ascii=False)


class CommandBuilder:
    """lib.rs:28-30."""

    def try_build(self):
        raise NotImplementedError

    def try_build_and_execute(self, payload: Optional[str] = None):
        cmd = self.try_build()
        writer = Writer.buffered()
        reader = Reader.from_string(payload or "")
        code = cmd.execute(writer, reader)
        return code, writer.stripped(), writer.err_to_stripped()


@dataclass
class ValidateBuilder(CommandBuilder):
    """lib.rs:96-347 (incl. the wasm `tryBuildAndExecute` entry)."""

    _rules: List[str] = field(default_factory=list)
    _data: List[str] = field(default_factory=list)
    _input_params: List[str] = field(default_factory=list)
    _output_format: str = "single-line-summary"
    _show_summary: List[str] = field(default_factory=lambda: ["fail"])
    _alphabetical: bool = False
    _last_modified: bool = False
    _verbose: bool = False
    _print_json: bool = False
    _payload: bool = False
    _structured: bool = False
    _backend: str = "cpu"
    _statuses_only: bool = False

    def rules(self, rules: List[str]):
        self._rules = rules
        return self

    def data(self, data: List[str]):
        self._data = data
        return self

    def input_params(self, p: List[str]):
        self._input_params = p
        return self

    def output_format(self, fmt: str):
        self._output_format = fmt
        return self

    def show_summary(self, s: List[str]):
        self._show_summary = s
        return self

    def alphabetical(self, v: bool = True):
        if v and self._last_modified:
            raise GuardError("alphabetical conflicts with last_modified")
        self._alphabetical = v
        return self

    def last_modified(self, v: bool = True):
        if v and self._alphabetical:
            raise GuardError("last_modified conflicts with alphabetical")
        self._last_modified = v
        return self

    def verbose(self, v: bool = True):
        self._verbose = v
        return self

    def print_json(self, v: bool = True):
        self._print_json = v
        return self

    def payload(self, v: bool = True):
        if v and (self._rules or self._data):
            raise GuardError("payload conflicts with rules/data")
        self._payload = v
        return self

    def structured(self, v: bool = True):
        self._structured = v
        return self

    def backend(self, b: str):
        self._backend = b
        return self

    def statuses_only(self, v: bool = True):
        self._statuses_only = v
        return self

    def try_build(self) -> Validate:
        return Validate(
            rules=self._rules,
            data=self._data,
            input_params=self._input_params,
            output_format=self._output_format,
            show_summary=self._show_summary,
            alphabetical=self._alphabetical,
            last_modified=self._last_modified,
            verbose=self._verbose,
            print_json=self._print_json,
            payload=self._payload,
            structured=self._structured,
            backend=self._backend,
            statuses_only=self._statuses_only,
        )


@dataclass
class TestBuilder(CommandBuilder):
    """lib.rs:351-462."""

    _rules_file: Optional[str] = None
    _test_data: Optional[str] = None
    _directory: Optional[str] = None
    _alphabetical: bool = False
    _last_modified: bool = False
    _verbose: bool = False
    _output_format: str = "single-line-summary"

    def rules_file(self, f: str):
        self._rules_file = f
        return self

    def test_data(self, f: str):
        self._test_data = f
        return self

    def directory(self, d: str):
        self._directory = d
        return self

    def alphabetical(self, v: bool = True):
        self._alphabetical = v
        return self

    def last_modified(self, v: bool = True):
        self._last_modified = v
        return self

    def verbose(self, v: bool = True):
        self._verbose = v
        return self

    def output_format(self, fmt: str):
        self._output_format = fmt
        return self

    def try_build(self) -> Test:
        if self._directory and (self._rules_file or self._test_data):
            raise GuardError("directory conflicts with rules_file/test_data")
        return Test(
            rules=self._rules_file,
            test_data=self._test_data,
            directory=self._directory,
            alphabetical=self._alphabetical,
            last_modified=self._last_modified,
            verbose=self._verbose,
            output_format=self._output_format,
        )


@dataclass
class ParseTreeBuilder(CommandBuilder):
    """lib.rs:35-90."""

    _rules: Optional[str] = None
    _output: Optional[str] = None
    _print_json: bool = False
    _print_yaml: bool = False

    def rules(self, r: str):
        self._rules = r
        return self

    def output(self, o: str):
        self._output = o
        return self

    def print_json(self, v: bool = True):
        self._print_json = v
        return self

    def print_yaml(self, v: bool = True):
        self._print_yaml = v
        return self

    def try_build(self) -> ParseTree:
        return ParseTree(
            rules=self._rules,
            output=self._output,
            print_json=self._print_json,
            print_yaml=self._print_yaml,
        )


@dataclass
class RulegenBuilder(CommandBuilder):
    """lib.rs:464-495."""

    _template: Optional[str] = None
    _output: Optional[str] = None

    def template(self, t: str):
        self._template = t
        return self

    def output(self, o: str):
        self._output = o
        return self

    def try_build(self) -> Rulegen:
        if not self._template:
            raise GuardError("template is required")
        return Rulegen(template=self._template, output=self._output)
