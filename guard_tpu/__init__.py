"""guard-tpu: a TPU-native policy-as-code framework.

A from-scratch rebuild of AWS CloudFormation Guard's capabilities
(reference at /root/reference): Guard-DSL parser, location-aware
JSON/YAML document model, a CPU reference evaluator with the full
clause/query/variable/function semantics, the validate/test/parse-tree/
rulegen/completions command surface and console/JSON/YAML/SARIF/JUnit
reporters — plus a JAX/XLA batch-evaluation backend that lowers rules to
a flat predicate IR and evaluates (documents x rules) batches sharded
across a TPU mesh (`validate --backend=tpu`).
"""

import sys as _sys

# Deep documents (terraform plan JSON, BASELINE.md config 4) exceed the
# default interpreter recursion limit in the loader/evaluator; the TPU
# kernels are iterative, but the CPU oracle walks trees recursively.
if _sys.getrecursionlimit() < 20000:
    _sys.setrecursionlimit(20000)

from .api import (
    CommandBuilder,
    ParseTreeBuilder,
    RulegenBuilder,
    TestBuilder,
    ValidateBuilder,
    run_checks,
)
from .core.qresult import Status

__version__ = "0.1.0"

__all__ = [
    "run_checks",
    "CommandBuilder",
    "ValidateBuilder",
    "TestBuilder",
    "ParseTreeBuilder",
    "RulegenBuilder",
    "Status",
    "__version__",
]
