"""The production 2-D (docs x packs) mesh: doc-axis sharding composed
with pack-column sub-meshes, as the DEFAULT sweep / validate dispatch
path whenever more than one device is visible.

Shape semantics — `GUARD_TPU_MESH` / `--mesh-shape`, resolved by
`resolve_mesh_shape`:

  * ``RxC`` — R host-level DOC shards x C pack COLUMNS. The visible
    devices partition into C contiguous groups; each planned pack is
    assigned to one column (greedy rule-count balance, the
    `rules.partition_packs` discipline) and its documents still
    DP-shard over that column's devices via NamedSharding. A column
    spanning m >= 4 devices (m even) gets the hierarchical (dcn, ici)
    layout from `mesh.hierarchical_mesh`; smaller columns stay 1-D.
  * ``auto`` / unset — (2, 1) when >= 2 devices are visible, else off.
    The single column then spans ALL devices, so the column mesh IS
    `mesh.default_mesh()` and every jitted evaluator hits the same
    `_SHARED_FNS` entry the single-shard path compiled — the default
    costs doc-shard concurrency setup, not a second XLA compile.
  * ``off`` / ``0`` / ``1`` / ``1x1`` — the single-device escape
    hatch: the legacy unsharded dispatch path, bit-identical to every
    release before the mesh plane.

Doc shards are CONTIGUOUS row ranges of the encoded batch
(`take_docs`), never an interleave: per-shard results write back
through a plain `lo:hi` offset, and the shard boundary is also the
degradation boundary — a dispatch/collect fault on one (doc-shard,
pack, bucket) walks packed -> per-file -> host-oracle for that shard's
docs only (ops/backend.py), while every other shard's results stand.

`GUARD_TPU_MESH_MIN_DOCS` (default 32) floors the per-shard doc count:
a 48-doc smoke batch under an R=2 shape stays ONE shard, so small
corpora keep the exact legacy dispatch count (and the pack-smoke
dispatch ceiling) while registry-scale chunks fan out.
"""

from __future__ import annotations

import logging
import os
import re
import threading as _threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..ops.encoder import DocBatch
from ..utils.telemetry import REGISTRY as _TELEMETRY
from .mesh import (
    DOC_AXIS,
    EFFICIENCY_COUNTERS,
    Mesh,
    ShardedBatchEvaluator,
    _EFFICIENCY_RESET_HOOKS,
    default_mesh,
    hierarchical_mesh,
)

log = logging.getLogger("guard_tpu.mesh2d")

_SHAPE_RE = re.compile(r"(\d+)\s*x\s*(\d+)")

# Rim-block subsets per consumer (mesh.ShardedBatchEvaluator
# rim_blocks): ONLY these blocks of the 7-tuple rim protocol cross the
# device boundary per collect; the padded status matrix stays on
# device entirely (ship_statuses=False). This is the mesh plane's d2h
# shrink — the report path (validate) reads blocks 0-4 + names, the
# sweep tally reads only any_unsure (4) and name_last (5).
RIM_PROFILE_VALIDATE = (0, 1, 2, 3, 4)
RIM_PROFILE_SWEEP = (4, 5)

RIM_PROFILES = {
    "validate": RIM_PROFILE_VALIDATE,
    "sweep": RIM_PROFILE_SWEEP,
}


def resolve_mesh_shape(n_devices: Optional[int] = None) -> Optional[Tuple[int, int]]:
    """(doc_shards, pack_columns) from GUARD_TPU_MESH, or None for the
    legacy unsharded path. See the module docstring for the grammar."""
    raw = os.environ.get("GUARD_TPU_MESH", "").strip().lower()
    if raw in ("off", "none", "0", "1", "1x1"):
        return None
    if n_devices is None:
        import jax

        n_devices = jax.device_count()
    if raw in ("", "auto"):
        return (2, 1) if n_devices >= 2 else None
    m = _SHAPE_RE.fullmatch(raw)
    if m is None:
        raise ValueError(
            f"GUARD_TPU_MESH={raw!r}: expected RxC (e.g. 2x4), "
            "'auto', or 'off'"
        )
    r, c = int(m.group(1)), int(m.group(2))
    if r < 1 or c < 1:
        raise ValueError(f"GUARD_TPU_MESH={raw!r}: axes must be >= 1")
    if (r, c) == (1, 1):
        return None
    if c > n_devices:
        log.warning(
            "GUARD_TPU_MESH=%s wants %d pack columns but only %d "
            "device(s) are visible; falling back to the unsharded path",
            raw, c, n_devices,
        )
        return None
    return r, c


def mesh_active(n_devices: Optional[int] = None) -> bool:
    return resolve_mesh_shape(n_devices) is not None


def min_shard_docs() -> int:
    try:
        return int(os.environ.get("GUARD_TPU_MESH_MIN_DOCS", "32") or 32)
    except ValueError:
        return 32


def doc_shard_bounds(n_docs: int, r: int) -> List[Tuple[int, int]]:
    """Contiguous (lo, hi) doc ranges for <= r shards, floored so every
    shard carries at least GUARD_TPU_MESH_MIN_DOCS documents (small
    batches collapse to one shard = the exact legacy dispatch count)."""
    floor = max(1, min_shard_docs())
    s = max(1, min(r, n_docs // floor))
    base, rem = divmod(n_docs, s)
    bounds, lo = [], 0
    for i in range(s):
        hi = lo + base + (1 if i < rem else 0)
        bounds.append((lo, hi))
        lo = hi
    return bounds


def take_docs(batch: DocBatch, lo: int, hi: int) -> DocBatch:
    """Contiguous doc-range slice of an encoded batch (numpy views, no
    copies): the unit a doc shard dispatches. Derived per-node columns
    are passed through so __post_init__ skips the edge re-scatter."""
    if lo == 0 and hi == batch.n_docs:
        return batch
    sl = slice(lo, hi)
    return DocBatch(
        node_kind=batch.node_kind[sl],
        node_parent=batch.node_parent[sl],
        scalar_id=batch.scalar_id[sl],
        num_hi=batch.num_hi[sl],
        num_lo=batch.num_lo[sl],
        child_count=batch.child_count[sl],
        edge_parent=batch.edge_parent[sl],
        edge_child=batch.edge_child[sl],
        edge_key_id=batch.edge_key_id[sl],
        edge_index=batch.edge_index[sl],
        edge_valid=batch.edge_valid[sl],
        n_docs=hi - lo,
        n_nodes=batch.n_nodes,
        n_edges=batch.n_edges,
        node_key_id=batch.node_key_id[sl],
        node_index=batch.node_index[sl],
        node_parent_kind=batch.node_parent_kind[sl],
        num_exotic=batch.num_exotic[sl],
        fn_origin=(
            batch.fn_origin[sl] if batch.fn_origin is not None else None
        ),
    )


def column_mesh(shape: Tuple[int, int], column: int,
                devices: Optional[Sequence] = None) -> Mesh:
    """The device mesh for pack column `column` of `shape`: C=1 spans
    every device as the flat default mesh (identical _SHARED_FNS keys
    to the single-shard path); C>1 partitions the devices contiguously,
    laying each column out hierarchically (dcn, ici) when it is big
    and even enough to split into two slices."""
    import jax

    devices = list(devices) if devices is not None else jax.devices()
    _r, c = shape
    if c <= 1:
        return default_mesh(devices)
    groups = np.array_split(np.arange(len(devices)), c)
    col_devices = [devices[i] for i in groups[column]]
    m = len(col_devices)
    if m >= 4 and m % 2 == 0:
        return hierarchical_mesh(col_devices, n_slices=2)
    return Mesh(np.array(col_devices), (DOC_AXIS,))


def assign_columns(loads: Sequence[int], n_columns: int) -> List[int]:
    """Greedy min-load column per item (largest first) — the
    rules.partition_packs balance discipline, but returning a per-item
    column index so pack order is preserved."""
    n_columns = max(1, n_columns)
    col_load = [0] * n_columns
    out = [0] * len(loads)
    for i in sorted(range(len(loads)), key=lambda i: -loads[i]):
        g = col_load.index(min(col_load))
        out[i] = g
        col_load[g] += max(1, loads[i])
    return out


# -- per-doc-shard efficiency attribution ------------------------------
# cumulative per-shard h2d/d2h bytes and doc fill, attributed by
# measuring the EFFICIENCY_COUNTERS deltas around each wrapped
# dispatch/collect and surfaced as `efficiency.shard_{s}.h2d / d2h /
# doc_fill` gauges — the skew view --metrics-out and the flight
# recorder dump for mesh runs. The delta window is NOT held under a
# lock across the (blocking) device call — that would serialize
# concurrent serve-path collects — so simultaneous mesh evaluations
# can misattribute bytes between shards; these are gauges, and the
# sweep path (the mesh's primary consumer) is single-threaded.
_SHARD_LOCK = _threading.Lock()
_SHARD_TOTALS: dict = {}


def _reset_shard_totals() -> None:
    _SHARD_TOTALS.clear()


_EFFICIENCY_RESET_HOOKS.append(_reset_shard_totals)


def _shard_totals(shard: int) -> dict:
    return _SHARD_TOTALS.setdefault(
        int(shard), {"h2d": 0, "d2h": 0, "docs_real": 0, "docs_padded": 0}
    )


def shard_efficiency_snapshot() -> dict:
    with _SHARD_LOCK:
        return {s: dict(t) for s, t in _SHARD_TOTALS.items()}


class MeshSweepEvaluator:
    """One pack's evaluator on the 2-D mesh: a ShardedBatchEvaluator on
    this pack's COLUMN sub-mesh, dispatched once per (doc shard,
    bucket) with per-shard efficiency attribution. `rim_blocks` /
    `ship_statuses` narrow the collect payload to the consumer's rim
    profile (RIM_PROFILES) — the cross-device rim reduction already ran
    behind the dispatch (mesh._rim_device), so only the merged
    per-name-group blocks the profile names leave the mesh."""

    def __init__(self, compiled, rim_spec=None,
                 shape: Optional[Tuple[int, int]] = None, column: int = 0,
                 rim_blocks=None, ship_statuses: bool = True,
                 devices: Optional[Sequence] = None):
        self.shape = shape if shape is not None else resolve_mesh_shape()
        self.column = int(column)
        mesh = (
            column_mesh(self.shape, self.column, devices)
            if self.shape is not None else None
        )
        self._ev = ShardedBatchEvaluator(
            compiled, mesh, rim_spec=rim_spec,
            rim_blocks=rim_blocks, ship_statuses=ship_statuses,
        )
        self.compiled = compiled
        self.rim_spec = rim_spec
        self.mesh = self._ev.mesh

    def dispatch(self, sub: DocBatch, shard: int = 0):
        real0 = EFFICIENCY_COUNTERS["docs_real"]
        pad0 = EFFICIENCY_COUNTERS["docs_padded"]
        h2d0 = EFFICIENCY_COUNTERS["host_to_device_bytes"]
        handle = self._ev.dispatch(sub)
        with _SHARD_LOCK:
            tot = _shard_totals(shard)
            tot["docs_real"] += EFFICIENCY_COUNTERS["docs_real"] - real0
            tot["docs_padded"] += EFFICIENCY_COUNTERS["docs_padded"] - pad0
            tot["h2d"] += (
                EFFICIENCY_COUNTERS["host_to_device_bytes"] - h2d0
            )
            denom = tot["docs_real"] + tot["docs_padded"]
            _TELEMETRY.set_gauge(
                f"efficiency.shard_{shard}.doc_fill",
                tot["docs_real"] / denom if denom else 0.0,
            )
            _TELEMETRY.set_gauge(
                f"efficiency.shard_{shard}.h2d", tot["h2d"]
            )
        return shard, handle

    def collect(self, handle):
        shard, inner = handle
        d2h0 = EFFICIENCY_COUNTERS["device_to_host_bytes"]
        out = self._ev.collect(inner)
        with _SHARD_LOCK:
            tot = _shard_totals(shard)
            tot["d2h"] += (
                EFFICIENCY_COUNTERS["device_to_host_bytes"] - d2h0
            )
            _TELEMETRY.set_gauge(
                f"efficiency.shard_{shard}.d2h", tot["d2h"]
            )
        return out
