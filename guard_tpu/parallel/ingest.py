"""Parallel host ingest plane: multi-worker read/parse/encode.

After PR 1 (fused packed dispatch) and PR 2 (device-side rim
reductions) the (docs x rules) device program is no longer the wall —
the host is, and its read+parse+encode slice ran on ONE Python thread,
interleaved between dispatch and collect (`commands/sweep.py`'s old
single-chunk double buffer). This module turns ingest into stage 1 of
a three-stage pipeline:

  1. **ingest workers** (this module): a spawn-based process pool where
     each worker reads, sniffs, parses and columnarizes one chunk into
     its own `(DocBatch, Interner)` — chunks already carry per-chunk
     interners, so no cross-worker id merge is needed, only picklable
     transport of the numpy columns (`ops.encoder.batch_payload`);
  2. **packed device dispatch** (`ops.backend.dispatch_packs`), fed
     from a bounded prefetch queue (depth >= 2, backpressure via
     `IngestPool` so queued-chunk memory stays bounded);
  3. **rim/report consumption** (`commands/sweep._finish_chunk`):
     collected status blocks materialize while the NEXT chunk is
     already dispatched, with ordered emission so console/structured
     output and exit codes stay byte-identical to the serial path.

Workers never import jax (spawn, not fork: nothing inherits the
initialized runtime). `GUARD_TPU_INGEST_WORKERS=0` (or
`--ingest-workers 0`) is the bit-parity escape hatch back to the old
serial double buffer, the same pattern as `--no-pack` /
`--no-vector-rim`; workers=1 keeps the pipelined control flow but
encodes inline; spawn failure degrades to inline encoding with a
logged warning, never an error.

`validate --backend tpu` reuses the same pool for one-shot batches:
the document list splits into contiguous shards, each worker encodes
its shard with a private interner, and the shards merge through an id
remap (`ops.encoder.remap_interned_ids` + `concat_batches`) — statuses
and reports are invariant under intern-id relabeling, so output stays
byte-identical to the serial encode.
"""

from __future__ import annotations

import logging
import os
import time
from typing import List, Optional, Tuple

log = logging.getLogger("guard_tpu.ingest")

#: bounded prefetch depth: at most this many encoded chunks may exist
#: ahead of the dispatch stage (backpressure bounds peak host memory at
#: depth x chunk columns). Override with GUARD_TPU_INGEST_DEPTH.
DEFAULT_DEPTH = 2

#: auto worker ceiling: ingest rarely scales past a few processes
#: before the dispatch stage is the bottleneck again
DEFAULT_MAX_WORKERS = 4


def pipeline_depth() -> int:
    raw = os.environ.get("GUARD_TPU_INGEST_DEPTH", "").strip()
    try:
        depth = int(raw) if raw else DEFAULT_DEPTH
    except ValueError:
        depth = DEFAULT_DEPTH
    return max(2, depth)


def resolve_ingest_workers(flag: Optional[int] = None) -> int:
    """Worker count for the ingest plane: the CLI flag wins, then
    `GUARD_TPU_INGEST_WORKERS`, then auto (cpu_count - 1, capped at
    DEFAULT_MAX_WORKERS — one core stays with the dispatch/rim
    stages). 0 = the serial bit-parity escape hatch; 1 = pipelined
    control flow with inline encode (no processes)."""
    if flag is None:
        env = os.environ.get("GUARD_TPU_INGEST_WORKERS", "").strip()
        if env:
            try:
                flag = int(env)
            except ValueError:
                flag = None
    if flag is None:
        flag = min((os.cpu_count() or 1) - 1, DEFAULT_MAX_WORKERS)
    return max(0, int(flag))


def _worker_init() -> None:
    # defensive: workers never import jax, but if a transitive import
    # ever does, it must not touch a TPU tunnel
    os.environ["JAX_PLATFORMS"] = "cpu"


def read_paths(paths: List[str]) -> Tuple[list, list, list, int, list]:
    """Read chunk files; unreadable ones are skipped with one error
    each (the sweep's `_read_chunk` contract, message-identical) and
    a structured quarantine record for the failure plane."""
    from ..utils.faults import maybe_fail, quarantine_record

    names, contents, msgs, errors, recs = [], [], [], 0, []
    for p in paths:
        base = os.path.basename(p)
        try:
            maybe_fail("read", key=base)
            with open(p, "r") as f:
                contents.append(f.read())
        except Exception as e:
            msgs.append(f"skipping {p}: {e}")
            errors += 1
            recs.append(quarantine_record(base, "read", e))
            continue
        names.append(base)
    return names, contents, msgs, errors, recs


def _chunk_job(args):
    """Worker body for one sweep chunk: read + sniff + parse +
    columnarize, returning a picklable payload (numpy columns via
    batch_payload, interner strings, error marks/messages and the
    stage timings the bench decomposition rows report)."""
    ci, paths = args
    from ..ops.encoder import batch_payload, encode_chunk_texts
    from ..utils.telemetry import worker_spans

    t0 = time.perf_counter()
    w0 = time.time()
    names, contents, read_msgs, read_errs, read_recs = read_paths(paths)
    t_read = time.perf_counter() - t0
    batch, interner, pv_failed, enc_msgs, enc_errs, enc_recs, _pvs = (
        encode_chunk_texts(names, contents)
    )
    t_enc = time.perf_counter() - t0 - t_read
    return ci, {
        "names": names,
        "contents": contents,
        "payload": batch_payload(batch),
        "strings": interner.strings,
        "pv_failed": pv_failed,
        "messages": read_msgs + enc_msgs,
        "errors": read_errs + enc_errs,
        "quarantined": read_recs + enc_recs,
        "read_seconds": t_read,
        "encode_seconds": t_enc,
        # wall-anchored span records for the parent's trace: dropped
        # there when tracing is off (building them is a few dicts)
        "spans": worker_spans([
            ("read_parse", w0, t_read),
            ("encode", w0 + t_read, t_enc),
        ]),
    }


def _validate_shard_job(args):
    """Worker body for one validate shard: encode a contiguous slice
    of the document list with a private interner. Mirrors the serial
    batch-build decision flow of `ops.backend.tpu_validate`: the native
    C++ JSON encoder when the whole corpus sniffed as JSON (decided in
    the parent so every shard agrees with the serial path), the Python
    loader otherwise — and a Python-loader parse failure reports the
    first failing document instead of encoding (the serial path raises
    there with the same message)."""
    names, contents, use_native = args
    from ..ops.encoder import batch_payload, encode_batch
    from ..utils.telemetry import worker_spans

    t0 = time.perf_counter()
    w0 = time.time()

    def _spans():
        return worker_spans([
            ("encode", w0, time.perf_counter() - t0),
        ])

    if use_native:
        from ..ops.native_encoder import (
            encode_json_batch_native,
            native_available,
        )

        if native_available():
            try:
                batch, interner, err = encode_json_batch_native(contents)
                if err is None:
                    return ("ok", batch_payload(batch),
                            interner.strings, _spans())
            except RuntimeError:
                pass
    from ..core.errors import GuardError
    from ..core.loader import load_document

    pvs = []
    for i, content in enumerate(contents):
        try:
            pvs.append(load_document(content, names[i]))
        except GuardError as e:
            return ("parse_error", i, str(e))
    batch, interner = encode_batch(pvs)
    return ("ok", batch_payload(batch), interner.strings, _spans())


def _spawn_pool(workers: int):
    """Isolated so tests can force a spawn failure."""
    import multiprocessing as mp

    ctx = mp.get_context("spawn")
    return ctx.Pool(processes=workers, initializer=_worker_init)


def _ping_job(x):
    return x


def _spawn_probe_timeout() -> float:
    raw = os.environ.get("GUARD_TPU_INGEST_SPAWN_TIMEOUT", "").strip()
    try:
        return float(raw) if raw else 60.0
    except ValueError:
        return 60.0


class IngestPool:
    """A spawn pool with graceful degradation: construction failure
    sets `.available` False (callers fall back to inline ingest — the
    pipeline must never turn a pool problem into a result problem).

    Construction PROBES the pool with a bounded ping: under an
    embedder whose unguarded __main__ cannot re-execute under spawn,
    workers die during bootstrap and the Pool respawns them forever —
    an unprobed first .get() would hang, not raise. The ping turns
    that failure mode into a clean degradation within
    GUARD_TPU_INGEST_SPAWN_TIMEOUT (default 60s)."""

    def __init__(self, workers: int):
        self.workers = workers
        self.error: Optional[str] = None
        try:
            self._pool = _spawn_pool(workers)
        except Exception as e:  # any bootstrap failure degrades, ever
            self._pool = None
            self.error = str(e)
            return
        try:
            assert self._pool.apply_async(
                _ping_job, (1,)
            ).get(timeout=_spawn_probe_timeout()) == 1
        except Exception as e:
            self.error = f"spawn probe failed: {e!r}"
            try:
                self._pool.terminate()
                self._pool.join()
            except Exception:
                pass
            self._pool = None

    @property
    def available(self) -> bool:
        return self._pool is not None

    def submit(self, fn, args):
        return self._pool.apply_async(fn, (args,))

    def close(self) -> None:
        if self._pool is not None:
            self._pool.terminate()
            self._pool.join()
            self._pool = None


# process-global pool reuse: spawning workers costs ~a second of
# interpreter+import per process, which would otherwise be charged to
# EVERY sweep/validate invocation (serve sessions, bench reps, chunked
# drivers). Pools are stateless (pure-function jobs), so one healthy
# pool per worker count serves the whole process. Spawn FAILURES are
# cached too (_SPAWN_FAILED): the probe ping costs up to
# GUARD_TPU_INGEST_SPAWN_TIMEOUT, so degraded mode pays it at most
# once per process and warns exactly once; `restart_shared_pool`
# clears the mark for deliberate recovery restarts.
_POOL_CACHE: dict = {}
_SPAWN_FAILED: dict = {}


def shared_pool(workers: int) -> Optional[IngestPool]:
    """A cached healthy IngestPool for `workers`, or None when spawn
    fails (caller degrades to inline ingest; the failure is cached so
    repeat invocations skip the spawn probe and its warning). Callers
    must NOT close the returned pool; `close_shared_pools` /
    interpreter exit does (workers are daemonic)."""
    pool = _POOL_CACHE.get(workers)
    if pool is not None and pool.available:
        return pool
    if workers in _SPAWN_FAILED:
        return None
    _POOL_CACHE.pop(workers, None)
    pool = IngestPool(workers)
    if not pool.available:
        _SPAWN_FAILED[workers] = pool.error
        log.warning(
            "ingest worker pool unavailable (%s); "
            "falling back to inline ingest", pool.error,
        )
        return None
    _POOL_CACHE[workers] = pool
    return pool


def restart_shared_pool(workers: int) -> Optional[IngestPool]:
    """Tear down the cached pool for `workers` (crashed worker
    recovery) and spawn a fresh one; a previously cached spawn failure
    is retried, not trusted — a restart is an explicit recovery
    action, unlike the hot-path probe skip."""
    pool = _POOL_CACHE.pop(workers, None)
    if pool is not None:
        pool.close()
    _SPAWN_FAILED.pop(workers, None)
    return shared_pool(workers)


def close_shared_pools() -> None:
    for pool in list(_POOL_CACHE.values()):
        pool.close()
    _POOL_CACHE.clear()
    _SPAWN_FAILED.clear()


def parallel_encode_documents(names: List[str], contents: List[str],
                              workers: int):
    """Validate's one-shot batch encode over an ingest worker pool.

    Returns (DocBatch, Interner) or None when the pool is unavailable
    (caller falls back to the serial encode). A document that fails the
    Python loader raises GuardError with the FIRST failing document's
    message in document order — the serial path's error contract.
    """
    from ..commands.validate import _looks_json
    from ..core.errors import GuardError
    from ..ops.encoder import (
        Interner,
        batch_from_payload,
        concat_batches,
        remap_interned_ids,
    )

    n = len(contents)
    workers = min(workers, n)
    if workers < 2:
        return None
    use_native = all(_looks_json(c) for c in contents)
    pool = shared_pool(workers)
    if pool is None:
        return None
    bounds = [(n * k) // workers for k in range(workers + 1)]
    shards = [
        (names[lo:hi], contents[lo:hi], use_native)
        for lo, hi in zip(bounds, bounds[1:])
        if hi > lo
    ]
    try:
        results = [
            h.get() for h in
            [pool.submit(_validate_shard_job, s) for s in shards]
        ]
    except Exception as e:
        log.warning(
            "ingest workers failed (%s); encoding serially", e
        )
        return None
    for res in results:
        if res[0] == "parse_error":
            # shards are contiguous and in document order, so the
            # earliest shard's first failure is the global first —
            # the serial path's error message, byte for byte
            raise GuardError(res[2])
    from ..utils.telemetry import ingest_worker_spans

    merged = Interner()
    import numpy as np

    parts = []
    for res in results:
        ingest_worker_spans(res[3] if len(res) > 3 else None)
        batch = batch_from_payload(res[1])
        remap = np.array(
            [merged.intern(s) for s in res[2]], dtype=np.int32
        )
        remap_interned_ids(batch, remap)
        parts.append(batch)
    return concat_batches(parts), merged


class ShardPrefetcher:
    """Bounded host-side prefetch of per-doc-shard dispatch inputs for
    the 2-D mesh (`parallel/mesh2d.py`).

    The mesh dispatch loop consumes one `(shard, lo, bucket_groups,
    oversize)` tuple per contiguous doc shard. Producing that tuple is
    pure host work — `mesh2d.take_docs` slicing plus the
    `split_batch_by_size` bucket columnarization — and JAX dispatch is
    asynchronous, so a producer thread can prepare shard s+1 while
    shard s's device programs are still in flight. The queue is bounded
    at `pipeline_depth()` (the PR 3 backpressure discipline: at most
    depth shards' sliced columns exist ahead of dispatch), and the
    `pipeline.shards_prefetched` / `shard_prefetch_stall_seconds`
    counters report how much overlap the thread actually bought.

    Single-shard batches (the MIN_DOCS floor) and the mesh-off path
    never construct this class — callers prepare inline, keeping the
    legacy path thread-free. Unlike chunk encode (the spawn pool),
    shard prep is thread-based: it is numpy slicing over an
    already-encoded batch, where process transport would cost more
    than the slice itself.
    """

    def __init__(self, batch, bounds, buckets, depth: Optional[int] = None):
        import queue
        import threading

        self._q: "queue.Queue" = queue.Queue(
            maxsize=depth if depth else pipeline_depth()
        )
        self._thread = threading.Thread(
            target=self._produce, args=(batch, list(bounds), buckets),
            daemon=True, name="guard-tpu-shard-prefetch",
        )
        self._thread.start()

    def _produce(self, batch, bounds, buckets) -> None:
        from ..ops.encoder import split_batch_by_size
        from . import mesh2d
        from .mesh import PIPELINE_COUNTERS

        try:
            for s, (lo, hi) in enumerate(bounds):
                sub = mesh2d.take_docs(batch, lo, hi)
                groups, oversize = split_batch_by_size(sub, buckets)
                PIPELINE_COUNTERS["shards_prefetched"] += 1
                self._q.put(("ok", (s, lo, groups, oversize)))
        except Exception as e:  # surfaced at the consumer's next get
            self._q.put(("error", e))
        else:
            self._q.put(("done", None))

    def __iter__(self):
        from .mesh import PIPELINE_COUNTERS

        while True:
            t0 = time.perf_counter()
            kind, payload = self._q.get()
            PIPELINE_COUNTERS["shard_prefetch_stall_seconds"] += (
                time.perf_counter() - t0
            )
            if kind == "done":
                return
            if kind == "error":
                raise payload
            yield payload
