"""Rule-axis parallelism: shard a huge compiled rule set across device
groups (SURVEY.md §2.3 — the "model parallel" axis of the (docs x
rules) batch matrix).

Rule programs are compile-time constants baked into each jaxpr, so the
rule axis cannot be a sharded *array* axis the way documents are.
Instead the compiled rule list is partitioned into dependency-closed
groups (named-rule references, `CNamedRef` — eval.rs:1227-1289 — must
stay with their referents), each group compiles into its own SPMD
evaluator over a disjoint sub-mesh of devices, and all groups dispatch
asynchronously before any result is collected — on hardware the groups
run concurrently, each DP-sharding the full document batch over its own
devices. Statuses concatenate on the host.

Use when the rule registry is large enough that one chip's compile/step
time is rule-bound rather than doc-bound; for small rule files the flat
doc-axis evaluator (mesh.ShardedBatchEvaluator) is strictly better.

Registry-scale corpora (many small rule FILES) shard at pack
granularity instead: PackShardedEvaluator concatenates each device
group's files into one packed executable (ops.ir.pack_compiled), so
the per-file dispatch overhead the serial loop pays disappears along
with the per-file executables.
"""

from __future__ import annotations

import copy
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..ops.encoder import DocBatch
from ..ops.ir import (
    CBlockClause,
    CClause,
    CCountClause,
    CNamedRef,
    CompiledRules,
    CWhenBlock,
    StepFilter,
    StepKeyInterpVar,
    compile_rules_file,
)
from ..utils.telemetry import span as _span
from .mesh import Mesh, ShardedBatchEvaluator


def _rule_dependencies(compiled: CompiledRules) -> List[set]:
    """Per-rule sets of referenced rule indices (CNamedRef edges)."""

    deps: List[set] = []

    def walk_steps(steps, acc: set) -> None:
        for s in steps:
            if isinstance(s, StepFilter):
                walk_conjs(s.conjunctions, acc)
            elif isinstance(s, StepKeyInterpVar):
                walk_steps(s.var_steps, acc)

    def walk_node(n, acc: set) -> None:
        if isinstance(n, CNamedRef):
            acc.update(n.rule_indices)
        elif isinstance(n, CClause):
            walk_steps(n.steps + (n.rhs_query_steps or []), acc)
        elif isinstance(n, CCountClause):
            walk_steps(n.steps, acc)
        elif isinstance(n, CBlockClause):
            walk_steps(n.query_steps, acc)
            walk_conjs(n.inner, acc)
        elif isinstance(n, CWhenBlock):
            if n.conditions is not None:
                walk_conjs(n.conditions, acc)
            walk_conjs(n.inner, acc)

    def walk_conjs(conjs, acc: set) -> None:
        for disj in conjs:
            for n in disj:
                walk_node(n, acc)

    for rule in compiled.rules:
        acc: set = set()
        if rule.conditions is not None:
            walk_conjs(rule.conditions, acc)
        walk_conjs(rule.conjunctions, acc)
        deps.append(acc)
    return deps


def partition_rules(compiled: CompiledRules, n_groups: int) -> List[List[int]]:
    """Partition rule indices into <= n_groups dependency-closed groups
    of balanced size (union-find over CNamedRef edges, then greedy
    bin-packing of the components, largest first)."""
    n = len(compiled.rules)
    parent = list(range(n))

    def find(x: int) -> int:
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    def union(a: int, b: int) -> None:
        ra, rb = find(a), find(b)
        if ra != rb:
            parent[ra] = rb

    for i, refs in enumerate(_rule_dependencies(compiled)):
        for j in refs:
            union(i, j)

    components: Dict[int, List[int]] = {}
    for i in range(n):
        components.setdefault(find(i), []).append(i)

    groups: List[List[int]] = [[] for _ in range(max(1, n_groups))]
    for comp in sorted(components.values(), key=len, reverse=True):
        min(groups, key=len).extend(comp)
    return [sorted(g) for g in groups if g]


def _slice_compiled(compiled: CompiledRules, indices: List[int]) -> CompiledRules:
    """A CompiledRules containing only `indices`, with CNamedRef
    rule_index fields remapped into the slice (indices must be
    dependency-closed — guaranteed by partition_rules)."""
    remap = {old: new for new, old in enumerate(indices)}

    def fix_node(n):
        if isinstance(n, CNamedRef):
            return CNamedRef(
                rule_indices=[remap[i] for i in n.rule_indices],
                negation=n.negation,
            )
        if isinstance(n, CClause):
            c = copy.copy(n)
            c.steps = [fix_step(s) for s in n.steps]
            if n.rhs_query_steps is not None:
                c.rhs_query_steps = [fix_step(s) for s in n.rhs_query_steps]
            return c
        if isinstance(n, CCountClause):
            c = copy.copy(n)
            c.steps = [fix_step(s) for s in n.steps]
            return c
        if isinstance(n, CBlockClause):
            b = copy.copy(n)
            b.query_steps = [fix_step(s) for s in n.query_steps]
            b.inner = fix_conjs(n.inner)
            return b
        if isinstance(n, CWhenBlock):
            w = copy.copy(n)
            if n.conditions is not None:
                w.conditions = fix_conjs(n.conditions)
            w.inner = fix_conjs(n.inner)
            return w
        return n

    def fix_step(s):
        if isinstance(s, StepFilter):
            f = copy.copy(s)
            f.conjunctions = fix_conjs(s.conjunctions)
            return f
        if isinstance(s, StepKeyInterpVar):
            v = copy.copy(s)
            v.var_steps = [fix_step(x) for x in s.var_steps]
            return v
        return s

    def fix_conjs(conjs):
        return [[fix_node(n) for n in disj] for disj in conjs]

    rules = []
    for i in indices:
        r = copy.copy(compiled.rules[i])
        if r.conditions is not None:
            r.conditions = fix_conjs(r.conditions)
        r.conjunctions = fix_conjs(r.conjunctions)
        rules.append(r)

    return CompiledRules(
        rules=rules,
        host_rules=[],
        interner=compiled.interner,
        str_empty_bits=compiled.str_empty_bits,
        needs_struct_ids=compiled.needs_struct_ids,
        needs_unsure=compiled.needs_unsure,
        bit_tables=compiled.bit_tables,  # slots stay valid: shared specs
        kidc_tables=compiled.kidc_tables,  # ditto (has-child columns)
        chain_tables=compiled.chain_tables,  # ditto (folded key chains)
        str_empty_slot=compiled.str_empty_slot,
        struct_literals=compiled.struct_literals,
        needs_str_rank=compiled.needs_str_rank,
        needs_pairwise=compiled.needs_pairwise,
        needs_fn_origin=compiled.needs_fn_origin,
        fn_vars=compiled.fn_vars,
        lit_names=compiled.lit_names,  # lit slots stay valid: shared table
    )


class RuleShardedEvaluator:
    """(docs x rules) evaluation over a 2-D (rule-groups x docs)
    device decomposition: devices split into `rule_shards` disjoint
    sub-meshes, each evaluating a dependency-closed slice of the rule
    set DP-sharded over the full document batch. All shards dispatch
    before any collects, so groups run concurrently on hardware."""

    def __init__(
        self,
        compiled: CompiledRules,
        rule_shards: int = 2,
        devices: Optional[Sequence] = None,
    ):
        self.compiled = compiled
        devices = list(devices) if devices is not None else jax.devices()
        rule_shards = max(1, min(rule_shards, len(compiled.rules) or 1, len(devices)))
        self.groups = partition_rules(compiled, rule_shards)
        # disjoint device split covering every device (remainder
        # devices go to the first groups)
        splits = np.array_split(np.arange(len(devices)), len(self.groups))
        self.shards: List[Tuple[ShardedBatchEvaluator, List[int]]] = []
        for idx, dev_idx in zip(self.groups, splits):
            sub_devices = [devices[i] for i in dev_idx]
            sub = _slice_compiled(compiled, idx)
            mesh = Mesh(np.array(sub_devices), ("docs",))
            self.shards.append((ShardedBatchEvaluator(sub, mesh), idx))
        self.last_unsure: Optional[np.ndarray] = None

    def dispatch(self, batch: DocBatch):
        """Dispatch EVERY rule-group shard before any collection (on
        hardware the groups then execute concurrently on their
        disjoint sub-meshes). Carries the `dispatch` fault-injection
        point so the sweep's bucket-isolation ladder is exercisable on
        the rule-sharded path too."""
        from ..utils.faults import maybe_fail

        maybe_fail("dispatch")
        return [(ev, idx, ev.dispatch(batch)) for ev, idx in self.shards]

    def collect(self, pending):
        d0 = pending[0][2][1]
        n_rules = len(self.compiled.rules)
        statuses = np.empty((d0, n_rules), np.int8)
        unsure = np.zeros((d0, n_rules), bool)
        for ev, idx, handle in pending:
            st, un = ev.collect(handle)
            statuses[:, idx] = st
            if un is not None:
                unsure[:, idx] = un
        return statuses, (unsure if self.compiled.needs_unsure else None)

    def __call__(self, batch: DocBatch) -> np.ndarray:
        """(D, num_rules) int8 statuses in the original rule order."""
        statuses, unsure = self.collect(self.dispatch(batch))
        self.last_unsure = unsure
        return statuses


# per-shard pack memo (the plan-layer analogue of backend._PACK_CACHE):
# the shard composition depends on rule_shards and the device census,
# neither of which is part of the on-disk plan artifact's key, so shard
# packs live in-process only — keyed by member CompiledRules identity,
# which the plan layer keeps stable across chunks. Entries carry the
# member list so the id() keys cannot be recycled while cached.
_SHARD_PACK_CACHE: OrderedDict = OrderedDict()
_SHARD_PACK_MAX = 8


def _pack_group(files: List[CompiledRules]):
    """pack_compiled over one shard group, memoized on member identity.
    Cached packs may predate the plan interner's latest relocation, so
    their bit tables are re-extended before reuse (a no-op when the
    interner has not grown)."""
    from ..ops.ir import extend_bit_tables, pack_compiled

    key = tuple(id(f) for f in files)
    hit = _SHARD_PACK_CACHE.get(key)
    if hit is not None:
        _SHARD_PACK_CACHE.move_to_end(key)
        packed = hit[1]
        extend_bit_tables([packed.compiled], packed.compiled.interner)
        return packed
    # per-group pack compile is the sharded path's lowering cost
    # (backend._pack_cached never sees these packs)
    with _span("pack_compile", {"files": len(files)}):
        packed = pack_compiled(files)
    _SHARD_PACK_CACHE[key] = (list(files), packed)
    while len(_SHARD_PACK_CACHE) > _SHARD_PACK_MAX:
        _SHARD_PACK_CACHE.popitem(last=False)
    return packed


def partition_packs(compiled_files, n_groups: int) -> List[List[int]]:
    """Partition rule-FILE indices into <= n_groups groups balanced by
    rule count (greedy largest-first), file order preserved inside each
    group. Unlike partition_rules there is no dependency constraint to
    honor: named-rule references cannot cross rule files."""
    n_groups = max(1, n_groups)
    loads = [0] * n_groups
    groups: List[List[int]] = [[] for _ in range(n_groups)]
    for i in sorted(
        range(len(compiled_files)),
        key=lambda i: -len(compiled_files[i].rules),
    ):
        g = loads.index(min(loads))
        groups[g].append(i)
        loads[g] += max(1, len(compiled_files[i].rules))
    return [sorted(g) for g in groups if g]


class PackShardedEvaluator:
    """Rule-axis parallelism with PACKS as the unit: per-file
    CompiledRules partition into <= rule_shards groups balanced by rule
    count, each group's files concatenate into ONE packed executable
    (ops.ir.pack_compiled) on its own disjoint sub-mesh, and every
    group dispatches before any result is collected. Vs
    RuleShardedEvaluator (which splits the rules of one compiled set),
    the pack is both the compilation unit and the sharding unit: a
    registry of many small rule files costs one executable and one
    dispatch per (group, bucket) instead of one per file — the
    dispatch-bound regime config 5c used to measure. Statuses return
    with files' rules concatenated in input order."""

    def __init__(
        self,
        compiled_files: List[CompiledRules],
        rule_shards: int = 2,
        devices: Optional[Sequence] = None,
        with_rim: bool = False,
    ):
        from ..ops.ir import build_rim_spec

        if not compiled_files:
            raise ValueError("no compiled rule files to shard")
        devices = list(devices) if devices is not None else jax.devices()
        rule_shards = max(
            1, min(rule_shards, len(compiled_files), len(devices))
        )
        self.files = list(compiled_files)
        self.groups = partition_packs(self.files, rule_shards)
        col_base = np.cumsum([0] + [len(c.rules) for c in self.files])
        self.n_rules = int(col_base[-1])
        # vectorized-rim protocol: each shard reduces its pack's
        # statuses on device (mesh.ShardedBatchEvaluator rim_spec) and
        # collect assembles the per-file blocks into GLOBAL arrays in
        # input file order (self.rim_spec indexes them)
        self.rim_spec = (
            build_rim_spec([c.rules for c in self.files]) if with_rim
            else None
        )
        splits = np.array_split(np.arange(len(devices)), len(self.groups))
        self.shards: List[Tuple[ShardedBatchEvaluator, np.ndarray]] = []
        for g, dev_idx in zip(self.groups, splits):
            packed = _pack_group([self.files[i] for i in g])
            cols = np.concatenate(
                [np.arange(col_base[i], col_base[i + 1]) for i in g]
            )
            mesh = Mesh(np.array([devices[i] for i in dev_idx]), ("docs",))
            shard_spec = (
                build_rim_spec([self.files[i].rules for i in g])
                if with_rim else None
            )
            self.shards.append(
                (
                    ShardedBatchEvaluator(
                        packed.compiled, mesh, rim_spec=shard_spec
                    ),
                    cols,
                    list(g),
                )
            )
        self._with_unsure = any(f.needs_unsure for f in self.files)
        self.last_unsure: Optional[np.ndarray] = None

    def dispatch(self, batch: DocBatch):
        """All pack groups dispatch before any collects (with the
        `dispatch` fault-injection point, as on the unsharded path)."""
        from ..utils.faults import maybe_fail

        maybe_fail("dispatch")
        return [
            (ev, cols, g, ev.dispatch(batch)) for ev, cols, g in self.shards
        ]

    def collect(self, pending):
        from ..ops.ir import SKIP

        d0 = pending[0][3][1]
        statuses = np.empty((d0, self.n_rules), np.int8)
        unsure = np.zeros((d0, self.n_rules), bool)
        spec = self.rim_spec
        rim = None
        if spec is not None:
            rim = (
                np.full((d0, spec.n_groups), SKIP, np.int8),
                np.zeros((d0, spec.n_groups), bool),
                np.full((d0, spec.n_files), SKIP, np.int8),
                np.zeros((d0, spec.n_files), bool),
                np.zeros((d0, spec.n_files), bool),
                np.full((d0, spec.n_groups), SKIP, np.int8),
            )
        for ev, cols, g, handle in pending:
            collected = ev.collect(handle)
            st, un = collected[0], collected[1]
            statuses[:, cols] = st
            if un is not None:
                unsure[:, cols] = un
            if spec is not None:
                shard_rim = collected[2]
                sspec = ev.rim_spec
                for k, fi in enumerate(g):
                    gsl, ssl = spec.file_slice(fi), sspec.file_slice(k)
                    for b in (0, 1, 5):  # name-group-axis blocks
                        rim[b][:, gsl] = shard_rim[b][:, ssl]
                    for b in (2, 3, 4):  # file-axis blocks
                        rim[b][:, fi] = shard_rim[b][:, k]
        if spec is None:
            return statuses, (unsure if self._with_unsure else None)
        return statuses, (unsure if self._with_unsure else None), rim

    def __call__(self, batch: DocBatch) -> np.ndarray:
        collected = self.collect(self.dispatch(batch))
        statuses, unsure = collected[0], collected[1]
        self.last_unsure = unsure
        return statuses
