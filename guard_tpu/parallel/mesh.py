"""Device-mesh sharding for batch policy evaluation.

The reference has no parallelism at all — evaluation of N docs x M rule
files is a sequential double loop (`/root/reference/guard/src/commands/
validate.rs:406-434` outer, `:718-756` inner; SURVEY.md §2.3). Here the
document axis is the data-parallel axis:

  * a 1-D `jax.sharding.Mesh` over all devices with axis "docs";
  * every DocBatch array is sharded on its leading doc axis with
    `NamedSharding(P("docs"))`; rule programs are replicated (they are
    compile-time constants baked into the jaxpr);
  * the per-doc evaluator is `vmap`'d and jitted with sharded in/out
    specs, so XLA partitions the whole computation SPMD across the mesh
    — per-chip work is purely local, and only the final pass/fail count
    reduction crosses chips (`jnp.sum` -> psum over ICI/DCN);
  * multi-host: the same code runs under `jax.distributed` since all
    collectives are XLA-inserted — exercised for real by
    tests/test_multihost_distributed.py (2 processes x 4 virtual CPU
    devices, one global (dcn, ici) mesh, gloo collectives, per-process
    oracle parity on the addressable shard).

Rule-axis parallelism (huge registries) composes on top by splitting the
compiled-rule list across a second mesh axis; statuses concatenate.
"""

from __future__ import annotations

import copy
import dataclasses
import threading as _threading
from collections import OrderedDict
from functools import partial
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.encoder import DocBatch
from ..ops.ir import CompiledRules, trace_signature
from ..ops.kernels import build_doc_evaluator
from ..utils.telemetry import REGISTRY as _TELEMETRY

DOC_AXIS = "docs"
DCN_AXIS = "dcn"  # cross-slice / cross-host axis
ICI_AXIS = "ici"  # intra-slice axis


def default_mesh(devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.array(devices), (DOC_AXIS,))


def hierarchical_mesh(devices=None, n_slices: int = 1) -> Mesh:
    """2-D (dcn, ici) mesh for multi-slice / multi-host topologies:
    the document axis shards over BOTH axes (the batch splits first
    across slices over DCN, then across each slice's chips over ICI).
    Policy evaluation has no inter-document communication, so the only
    cross-slice traffic is the final pass/fail count psum — exactly
    the DCN-friendly layout the scaling model prescribes for
    embarrassingly data-parallel work. Run under `jax.distributed` on
    real multi-host topologies; on a single host this still validates
    the sharding layout end to end."""
    devices = list(devices) if devices is not None else jax.devices()
    if len(devices) % n_slices:
        raise ValueError(
            f"{len(devices)} devices do not split into {n_slices} slices"
        )
    arr = np.array(devices).reshape(n_slices, len(devices) // n_slices)
    return Mesh(arr, (DCN_AXIS, ICI_AXIS))


def pad_to_multiple(batch_arrays: Dict[str, np.ndarray], multiple: int) -> Tuple[Dict[str, np.ndarray], int]:
    """Pad the doc axis so it divides the mesh; returns (arrays, orig_d)."""
    d = next(iter(batch_arrays.values())).shape[0]
    target = ((d + multiple - 1) // multiple) * multiple
    if target == d:
        return batch_arrays, d
    out = {}
    for k, v in batch_arrays.items():
        pad = np.zeros((target - d,) + v.shape[1:], dtype=v.dtype)
        if k in ("node_kind", "struct_id", "fn_origin"):
            pad = pad - 1  # padding docs are all-padding nodes
        out[k] = np.concatenate([v, pad], axis=0)
    return out, d


# Shared jitted evaluators, keyed by (trace signature, mesh, knobs):
# the literals-as-inputs design (ir.StepKey / CompiledRules.lit_values)
# makes the kernel trace depend only on rule STRUCTURE, so re-compiling
# the same rule file against a new corpus — the next validate
# invocation in a serve session, the next sweep chunk, the next test
# spec file — reuses the jitted function (and its per-bucket-shape
# executables) instead of paying ~seconds of re-trace + XLA compile.
_SHARED_FNS: "OrderedDict[tuple, tuple]" = OrderedDict()
_SHARED_FNS_MAX = 64

# Dispatch/executable observability (read via ops.backend.dispatch_stats,
# emitted by bench.py and asserted by the CPU bench-smoke): every
# ShardedBatchEvaluator.dispatch counts one device dispatch, and the
# first dispatch of a (jitted evaluator, bucket shape) pair counts one
# compiled executable — jit compiles one XLA executable per input
# shape, and node_kind's (D, N) shape determines the bucket. The packed
# path's whole point is driving both counters down ~n_files-fold.
_COMPILED_SHAPES: set = set()

# Process-wide device-EXECUTION lock (the serving plane made dispatch
# multi-threaded): a sharded execution enqueues one program per mesh
# device, and cross-device collectives inside it wait for every
# participant. Two threads interleaving their per-device enqueues can
# order A,B on one device queue and B,A on another — each collective
# then waits on a participant stuck behind the OTHER execution:
# deadlock (observed on the forced 8-device CPU mesh under concurrent
# serve requests). Holding this lock across the enqueue makes the
# order identical on every queue; COLLECTION (blocking on an already
# enqueued result) stays outside, so the dispatch-then-collect
# pipelining in evaluate_bucketed is preserved.
_EXEC_LOCK = _threading.RLock()

# absorbed into the central telemetry registry (utils/telemetry.py):
# this dict stays the mutation surface (the dispatch sites below
# increment it directly, bit-compatibly), the registry owns
# read/reset/snapshot behind ops.backend.dispatch_stats()
DISPATCH_COUNTERS = _TELEMETRY.counter_group(
    "dispatch",
    {"dispatches": 0, "executables_compiled": 0},
    extra_reset=_COMPILED_SHAPES.clear,
)


def reset_dispatch_counters() -> None:
    _TELEMETRY.reset_group("dispatch")


# Ingest-pipeline observability, next to the dispatch counters above
# and the rim counters (ops.backend.RIM_COUNTERS): stage-level truth
# about the three-stage sweep pipeline (parallel/ingest.py).
#   chunks_prefetched       — chunk payloads produced by ingest WORKERS
#                             (inline encodes don't count);
#   encode_dispatch_overlap — worker payloads dequeued while a previous
#                             chunk's device work was still in flight,
#                             i.e. encodes that genuinely overlapped
#                             dispatch (the CI ingest-smoke pins > 0);
#   max_inflight_chunks     — high-water mark of queued encoded chunks
#                             (bounded by the configured pipeline
#                             depth: backpressure proof);
#   ingest_stall_seconds    — consumer time blocked waiting on the
#                             ingest queue (the pipeline_stall bench
#                             decomposition row);
#   read_parse_seconds /    — cumulative stage-1 timings as measured
#   encode_seconds            inside the workers (or inline);
#   shards_prefetched       — per-doc-shard dispatch inputs prepared
#                             AHEAD of the 2-D mesh dispatch loop by
#                             the bounded shard prefetcher
#                             (ingest.ShardPrefetcher — zero when the
#                             mesh is off or the batch is one shard);
#   shard_prefetch_stall_   — dispatch-loop time blocked waiting on
#   seconds                   the next shard's host prep (small =
#                             shard prep genuinely overlapped the
#                             previous shard's device execution).
PIPELINE_COUNTERS = _TELEMETRY.counter_group("pipeline", {
    "chunks_prefetched": 0,
    "encode_dispatch_overlap": 0,
    "max_inflight_chunks": 0,
    "ingest_stall_seconds": 0.0,
    "read_parse_seconds": 0.0,
    "encode_seconds": 0.0,
    "shards_prefetched": 0,
    "shard_prefetch_stall_seconds": 0.0,
})


def reset_pipeline_counters() -> None:
    _TELEMETRY.reset_group("pipeline")


# Hardware-efficiency observability (the `efficiency` group,
# snapshot schema v2): how much of the padded (docs x nodes) batch the
# device actually chews on, and how many bytes cross the host<->device
# boundary per dispatch/collect — the occupancy/transfer numbers the
# multi-chip mesh and serving tier must tune against.
#   docs_real / docs_padded       — documents dispatched vs padding
#                                   docs added by pad_to_multiple so
#                                   the doc axis divides the mesh;
#   node_slots_real / _padded     — non-padding node slots vs wasted
#                                   slots (doc padding + per-bucket
#                                   node-ceiling padding combined);
#   host_to_device_bytes          — batch arrays + rule literals
#                                   shipped per dispatch;
#   device_to_host_bytes          — status/unsure matrices + rim
#                                   blocks converted back per collect
#                                   (padded shapes: what actually
#                                   crosses, not the trimmed view);
#   device_to_host_bytes_trimmed  — the same transfers after the [:d]
#                                   doc trim (padding docs excluded),
#                                   so mesh bench rows can report both
#                                   and never overstate the rim-only
#                                   transfer savings;
#   pack_rule_slots_used /        — rule slots occupied vs the
#   _capacity                       PACK_MAX_RULES ceiling per planned
#                                   pack (ops.backend increments).
# Per-bucket fill fractions and the live-executable census land as
# `efficiency.*` gauges next to the counters.

# late-bound reset hooks: mesh2d registers its per-doc-shard
# accumulator clear here (a direct import at group-registration time
# would be circular)
_EFFICIENCY_RESET_HOOKS: list = []


def _run_efficiency_reset_hooks() -> None:
    for hook in list(_EFFICIENCY_RESET_HOOKS):
        hook()


EFFICIENCY_COUNTERS = _TELEMETRY.counter_group("efficiency", {
    "docs_real": 0,
    "docs_padded": 0,
    "node_slots_real": 0,
    "node_slots_padded": 0,
    "host_to_device_bytes": 0,
    "device_to_host_bytes": 0,
    "device_to_host_bytes_trimmed": 0,
    "pack_rule_slots_used": 0,
    "pack_rule_slots_capacity": 0,
}, extra_reset=_run_efficiency_reset_hooks)


def reset_efficiency_counters() -> None:
    _TELEMETRY.reset_group("efficiency")


def _mesh_key(mesh: Mesh) -> tuple:
    # platform included: device ids are unique only per backend
    # (CpuDevice 0 and TpuDevice 0 coexist), and an explicit CPU mesh
    # on a TPU host must never hit a cached TPU-sharded executable
    return (
        tuple((d.platform, d.id) for d in mesh.devices.flat),
        tuple(int(x) for x in mesh.devices.shape),
        tuple(mesh.axis_names),
    )


def _scrub_arrays(o, seen: set) -> None:
    """Generic structural walk setting every numpy-array field under
    the IR to None (the trace reads only scalars and slots; the (S,)
    bit tables are bound per batch through device_arrays)."""
    if id(o) in seen:
        return
    seen.add(id(o))
    if dataclasses.is_dataclass(o) and not isinstance(o, type):
        for f in dataclasses.fields(o):
            v = getattr(o, f.name)
            if isinstance(v, np.ndarray):
                setattr(o, f.name, None)
            elif isinstance(v, (list, tuple, dict)) or dataclasses.is_dataclass(v):
                _scrub_arrays(v, seen)
    elif isinstance(o, (list, tuple)):
        for e in o:
            _scrub_arrays(e, seen)
    elif isinstance(o, dict):
        for e in o.values():
            _scrub_arrays(e, seen)


def _slim_for_trace(compiled: CompiledRules) -> CompiledRules:
    """A structure-only CompiledRules for the cached trace closure:
    same rules IR (deep-copied, numpy tables scrubbed), no interner,
    no bit tables, no struct literals — the cache must not pin the
    first corpus's string table for the process lifetime."""
    rules = copy.deepcopy(compiled.rules)
    _scrub_arrays(rules, set())
    return CompiledRules(
        rules=rules,
        host_rules=[],
        interner=None,
        str_empty_bits=None,
        needs_struct_ids=compiled.needs_struct_ids,
        needs_unsure=compiled.needs_unsure,
        str_empty_slot=compiled.str_empty_slot,
        needs_str_rank=compiled.needs_str_rank,
        needs_pairwise=compiled.needs_pairwise,
        needs_fn_origin=compiled.needs_fn_origin,
        lit_names=list(compiled.lit_names),
    )


def _shared_evaluator_fns(compiled: CompiledRules, mesh: Mesh):
    """(jitted batch fn, jitted summary fn) for this rule program
    structure on this mesh — cached across CompiledRules instances."""
    from ..ops import kernels

    with_unsure = compiled.needs_unsure
    key = (
        trace_signature(compiled),
        _mesh_key(mesh),
        with_unsure,
        # formulation knobs are process-mutable (tools/tune_gather.py
        # sweeps GATHER_MIN_NODES): bake them into the cache key
        kernels.GATHER_MIN_NODES,
        kernels.GATHER_ALWAYS_ON_CPU,
        kernels.GATHER_CPU_MIN_NODES,
    )
    hit = _SHARED_FNS.get(key)
    if hit is not None:
        _SHARED_FNS.move_to_end(key)
        return hit

    # the mesh's platform, not the process default, decides the
    # primitive formulation (an explicit CPU mesh on a TPU host
    # must still get the CPU gather override). The closure lives for
    # the cache's lifetime, so it captures a SLIM structural clone —
    # not the first corpus's interner / bit tables / struct literals
    doc_eval = build_doc_evaluator(
        _slim_for_trace(compiled),
        with_unsure=with_unsure,
        platform=mesh.devices.flat[0].platform,
    )
    # every input array is doc-major: one sharding as a pytree
    # prefix covers the whole arrays dict. The doc axis shards
    # over EVERY mesh axis, so the same evaluator runs on a flat
    # 1-D mesh or a hierarchical (dcn, ici) multi-slice mesh. The
    # lits binding is batch-constant: replicated, in_axes=None.
    doc_spec = P(tuple(mesh.axis_names))
    in_spec = NamedSharding(mesh, doc_spec)
    out_spec = NamedSharding(mesh, doc_spec)
    replicated = NamedSharding(mesh, P())
    fn = jax.jit(
        jax.vmap(doc_eval, in_axes=(0, None)),
        in_shardings=(in_spec, replicated),
        out_shardings=(out_spec, out_spec) if with_unsure else out_spec,
    )

    # aggregate summary: per-rule (n_pass, n_fail, n_skip) — the only
    # cross-chip reduction (SURVEY.md §2.3 "communication backend");
    # n_valid masks out docs added by mesh padding
    def summarize(arrays, lits, n_valid):
        out = jax.vmap(doc_eval, in_axes=(0, None))(arrays, lits)
        statuses = out[0] if with_unsure else out
        valid = (jnp.arange(statuses.shape[0]) < n_valid)[:, None]
        counts = jnp.stack(
            [
                jnp.sum((statuses == 0) & valid, axis=0),
                jnp.sum((statuses == 1) & valid, axis=0),
                jnp.sum((statuses == 2) & valid, axis=0),
            ]
        )
        return statuses, counts

    summary_fn = jax.jit(
        summarize,
        in_shardings=(in_spec, replicated, replicated),
        out_shardings=(out_spec, replicated),
    )
    _SHARED_FNS[key] = (fn, summary_fn)
    while len(_SHARED_FNS) > _SHARED_FNS_MAX:
        _SHARED_FNS.popitem(last=False)
    return fn, summary_fn


@partial(jax.jit, static_argnums=(5, 6))
def _rim_device(statuses, unsure, group_ids, file_ids, last_ids,
                n_groups: int, n_files: int):
    """Device-side rim reductions (kernels.rim_reduce) fused behind the
    evaluator dispatch: segment-max folds over the rule axis, purely
    local per doc, so the doc sharding of `statuses` carries through
    and only the reduced (D, G)/(D, F) blocks ever cross to the host.
    group/file index tables are runtime inputs — one executable per
    (bucket shape, n_groups, n_files) serves every pack with that
    shape."""
    from ..ops.kernels import rim_reduce

    return rim_reduce(
        jnp.asarray(statuses),
        None if unsure is None else jnp.asarray(unsure),
        jnp.asarray(group_ids), jnp.asarray(file_ids),
        jnp.asarray(last_ids), n_groups, n_files,
    )


class ShardedBatchEvaluator:
    """DP-sharded (docs x rules) status evaluator over a device mesh.
    When the rule file compares against query RHS, `last_unsure` holds
    the (D, R) bool matrix of results to route to the CPU oracle.

    `rim_spec` (ir.RimSpec) switches dispatch/collect into the
    vectorized-rim protocol: the post-kernel status reductions —
    per-name-group merged statuses, per-doc overall status, any-fail /
    any-unsure bitmaps (kernels.rim_reduce) — run ON DEVICE right
    behind the evaluator dispatch, and `collect` returns them as a
    third element. On accelerators this shrinks the per-collect
    transfer from the (D, R) status matrix to the (D, G)/(D, F) blocks
    the backend's mask arithmetic actually consumes. Without rim_spec
    the two-element protocol is unchanged.

    `rim_blocks` (tuple of rim block indices 0..5) narrows the rim
    protocol further: only the named blocks are converted host-side
    per collect (the rest come back as None placeholders), and
    `ship_statuses=False` skips the padded (D, R) status/unsure
    conversion entirely — the mesh sweep's whole d2h win, since the
    report/tally consumers read ONLY their profile's rim blocks."""

    def __init__(self, compiled: CompiledRules, mesh: Optional[Mesh] = None,
                 rim_spec=None, rim_blocks=None, ship_statuses: bool = True):
        self.compiled = compiled
        self.mesh = mesh if mesh is not None else default_mesh()
        self._with_unsure = compiled.needs_unsure
        self._fn, self._summary_fn = _shared_evaluator_fns(compiled, self.mesh)
        self.rim_spec = rim_spec
        self.rim_blocks = None if rim_blocks is None else tuple(rim_blocks)
        # without a rim there is nothing else to return: statuses ship
        self.ship_statuses = bool(ship_statuses) or rim_spec is None
        self.last_unsure = None

    def _arrays(self, batch: DocBatch):
        return pad_to_multiple(
            self.compiled.device_arrays(batch),
            self.mesh.devices.size,
        )

    def _lits(self) -> np.ndarray:
        return self.compiled.lit_values()

    def dispatch(self, batch: DocBatch):
        """Launch evaluation WITHOUT blocking (JAX dispatch is async):
        returns (device_out, n_valid). Use to overlap host work —
        columnarizing the next bucket / encoding the next chunk — and
        concurrent sub-mesh execution (parallel/rules.py) with device
        execution, collecting deferred."""
        arrays, d = self._arrays(batch)
        DISPATCH_COUNTERS["dispatches"] += 1
        shape_key = (id(self._fn), arrays["node_kind"].shape)
        if shape_key not in _COMPILED_SHAPES:
            _COMPILED_SHAPES.add(shape_key)
            DISPATCH_COUNTERS["executables_compiled"] += 1
        lits = self._lits()
        # hardware-efficiency seam: padded-batch occupancy + the bytes
        # this dispatch ships to the device (batch arrays + literals)
        padded_d, n_nodes = arrays["node_kind"].shape
        real_slots = int((arrays["node_kind"] >= 0).sum())
        EFFICIENCY_COUNTERS["docs_real"] += d
        EFFICIENCY_COUNTERS["docs_padded"] += padded_d - d
        EFFICIENCY_COUNTERS["node_slots_real"] += real_slots
        EFFICIENCY_COUNTERS["node_slots_padded"] += (
            padded_d * n_nodes - real_slots
        )
        EFFICIENCY_COUNTERS["host_to_device_bytes"] += int(
            sum(a.nbytes for a in arrays.values()) + lits.nbytes
        )
        _TELEMETRY.set_gauge(
            f"efficiency.bucket_{n_nodes}.doc_fill",
            d / padded_d if padded_d else 0.0,
        )
        _TELEMETRY.set_gauge(
            f"efficiency.bucket_{n_nodes}.node_fill",
            real_slots / (padded_d * n_nodes) if padded_d * n_nodes
            else 0.0,
        )
        _TELEMETRY.set_gauge(
            "efficiency.live_executables", len(_COMPILED_SHAPES)
        )
        _TELEMETRY.set_gauge(
            "efficiency.shared_evaluators", len(_SHARED_FNS)
        )
        # numpy straight into the jitted call: in_shardings place the
        # arrays on this evaluator's mesh; jnp.asarray would commit them
        # to the default device first (wrong backend on TPU hosts when
        # the mesh is a CPU mesh).
        with _EXEC_LOCK:
            out = self._fn(arrays, lits)
            rim = None
            if self.rim_spec is not None:
                statuses = out[0] if self._with_unsure else out
                unsure = out[1] if self._with_unsure else None
                rim = _rim_device(
                    statuses, unsure,
                    self.rim_spec.group_ids, self.rim_spec.file_ids,
                    self.rim_spec.last_ids,
                    self.rim_spec.n_groups, self.rim_spec.n_files,
                )
        return out, d, rim

    def collect(self, handle):
        """Block on a dispatch handle: (statuses (d, R) int8,
        unsure (d, R) bool or None) — plus the rim blocks as a third
        element (each trimmed to d docs) when this evaluator carries a
        rim_spec."""
        out, d, rim_dev = handle
        # hardware-efficiency seam: the PADDED device arrays are what
        # cross back to the host (the [:d] trim happens host-side);
        # the _trimmed counter records the post-trim view of the same
        # transfers so padding docs never inflate the mesh bench rows
        st = un = None
        if self.ship_statuses:
            if self._with_unsure:
                statuses, unsure = out
                st_full, un_full = np.asarray(statuses), np.asarray(unsure)
                EFFICIENCY_COUNTERS["device_to_host_bytes"] += int(
                    st_full.nbytes + un_full.nbytes
                )
                st, un = st_full[:d], un_full[:d]
                EFFICIENCY_COUNTERS["device_to_host_bytes_trimmed"] += int(
                    st.nbytes + un.nbytes
                )
            else:
                st_full = np.asarray(out)
                EFFICIENCY_COUNTERS["device_to_host_bytes"] += int(
                    st_full.nbytes
                )
                st, un = st_full[:d], None
                EFFICIENCY_COUNTERS["device_to_host_bytes_trimmed"] += int(
                    st.nbytes
                )
        if self.rim_spec is None:
            return st, un
        blocks = []
        for i, b in enumerate(rim_dev):
            if self.rim_blocks is not None and i not in self.rim_blocks:
                blocks.append(None)
                continue
            full = np.asarray(b)
            EFFICIENCY_COUNTERS["device_to_host_bytes"] += int(full.nbytes)
            trimmed = full[:d]
            EFFICIENCY_COUNTERS["device_to_host_bytes_trimmed"] += int(
                trimmed.nbytes
            )
            blocks.append(trimmed)
        return st, un, tuple(blocks)

    def __call__(self, batch: DocBatch) -> np.ndarray:
        collected = self.collect(self.dispatch(batch))
        statuses, unsure = collected[0], collected[1]
        self.last_unsure = unsure
        return statuses

    def evaluate_bucketed(self, batch: DocBatch):
        return evaluate_bucketed(self, len(self.compiled.rules), batch)

    def with_summary(self, batch: DocBatch) -> Tuple[np.ndarray, np.ndarray]:
        arrays, d = self._arrays(batch)
        with _EXEC_LOCK:
            statuses, counts = self._summary_fn(
                arrays, self._lits(), np.int32(d)
            )
        return np.asarray(statuses)[:d], np.asarray(counts)




def evaluate_bucketed(evaluator, n_rules: int, batch: DocBatch):
    """Size-bucketed evaluation of a whole corpus batch through any
    evaluator exposing __call__(sub_batch) -> (d, R) statuses and a
    `last_unsure` attribute (ShardedBatchEvaluator, RuleShardedEvaluator).

    Returns (statuses (D, R) int8, unsure (D, R) bool, host_docs): each
    size-bucket group evaluates at its own padded shape (padding
    everyone to the largest document wastes quadratic work in the
    one-hot buckets); documents beyond the active ceiling are left
    SKIP-filled and returned in `host_docs` for CPU-oracle evaluation.
    EVERY rule file uses the extended buckets (documents up to 64k
    nodes stay on device): pairwise constructions — query-RHS compares
    and variable key interpolation — evaluate through the O(N log N)
    sorted-set formulations in gather mode (kernels._in_set_sorted and
    friends), so no (N, N) matrix exists at the big buckets."""
    from ..ops.encoder import (
        NODE_BUCKETS_EXTENDED,
        split_batch_by_size,
    )
    from ..ops.ir import SKIP

    import logging

    from ..utils.faults import FAULT_COUNTERS, bounded_call, maybe_fail

    log = logging.getLogger("guard_tpu.mesh")
    buckets = NODE_BUCKETS_EXTENDED
    groups, oversize = split_batch_by_size(batch, buckets)
    statuses = np.full((batch.n_docs, n_rules), SKIP, np.int8)
    unsure = np.zeros((batch.n_docs, n_rules), bool)
    host_extra: set = set()

    def _bucket_to_host(stage, exc, idx):
        # one bucket's device failure degrades that bucket to the host
        # oracle; every other bucket's results are untouched
        log.warning(
            "device %s failed for a %d-doc bucket (%s); "
            "falling back to the host oracle", stage, len(idx), exc,
        )
        FAULT_COUNTERS["dispatch_fallbacks"] += 1
        FAULT_COUNTERS["oracle_fallbacks"] += 1
        host_extra.update(int(i) for i in idx)

    if hasattr(evaluator, "dispatch") and hasattr(evaluator, "collect"):
        # pipelined: dispatch EVERY bucket group before collecting any
        # (JAX dispatch is async) — host columnarization of group k+1
        # overlaps device execution of group k instead of serializing
        # behind its collection
        pending = []
        for sub, idx in groups:
            try:
                maybe_fail("dispatch")
                pending.append((idx, evaluator.dispatch(sub)))
            except Exception as e:
                _bucket_to_host("dispatch", e, idx)
        for idx, handle in pending:
            try:
                maybe_fail("collect")
                st, un = bounded_call(evaluator.collect, handle)
            except Exception as e:
                _bucket_to_host("collect", e, idx)
                continue
            statuses[idx] = st
            if un is not None:
                unsure[idx] = un
    else:
        for sub, idx in groups:
            try:
                maybe_fail("dispatch")
                statuses[idx] = bounded_call(evaluator, sub)
            except Exception as e:
                _bucket_to_host("dispatch", e, idx)
                continue
            if evaluator.last_unsure is not None:
                unsure[idx] = evaluator.last_unsure
    return statuses, unsure, {int(i) for i in oversize} | host_extra
