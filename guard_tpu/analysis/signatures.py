"""Anchor-signature extraction: what a rule file can ever match on.

Statically derives, per rule file, the set of ANCHORS a document must
exhibit for any rule in the file to get past its selection queries:

  * type equalities — `Resources.*.Type == 'AWS::X::Y'` shapes, the
    type-block sugar, and `Type IN [...]` filters; the classic
    cfn-guard anchoring idiom;
  * key chains — the leading run of literal map keys on each
    top-level rule query (`Resources`, `Resources.Outputs`, ...): a
    doc with no such key chain can only ever produce retrieval
    misses for that query.

The product (`PlanSignatures`) is persisted inside the plan artifact
(ops/plan.py, digest-versioned via PLAN_SCHEMA_VERSION) and as a
human-readable JSON sidecar next to it, with a pack -> union-signature
inverted index — the routing input `mesh2d.assign_columns` will
consume for rule-relevance partial evaluation (ROADMAP item 2):
"dispatch only packs with >= 1 potentially-matching doc".

Extraction is sound-for-routing, not complete: a rule whose anchors
cannot be derived (variable-headed queries, `this`, interpolation)
is counted in `unanchored_rules` — a file with any unanchored rule
must never be skipped by a router. Signatures never influence
evaluation today; byte parity is structural.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from ..core import values as _v
from ..core.exprs import (
    AccessQuery,
    BlockGuardClause,
    GuardAccessClause,
    QKey,
    Rule,
    RulesFile,
    TypeBlock,
    WhenBlockClause,
    part_is_variable,
    walk_expr_tree,
)
from ..core.values import PV
from . import ANALYSIS_COUNTERS

#: bump when the extracted shape changes — persisted inside the plan
#: artifact AND the JSON sidecar, so stale routers can reject
SIGNATURE_SCHEMA_VERSION = 1


@dataclass
class FileSignature:
    """One rule file's anchors. Empty lists + unanchored_rules == 0
    means the file genuinely anchors on nothing (e.g. pure named-rule
    composition) and a router must treat it as match-anything."""

    type_equalities: List[str] = field(default_factory=list)
    key_chains: List[Tuple[str, ...]] = field(default_factory=list)
    unanchored_rules: int = 0

    def to_json(self) -> dict:
        return {
            "type_equalities": list(self.type_equalities),
            "key_chains": [list(kc) for kc in self.key_chains],
            "unanchored_rules": self.unanchored_rules,
        }

    @staticmethod
    def from_json(doc: dict) -> "FileSignature":
        return FileSignature(
            type_equalities=list(doc.get("type_equalities", [])),
            key_chains=[tuple(kc) for kc in doc.get("key_chains", [])],
            unanchored_rules=int(doc.get("unanchored_rules", 0)),
        )


@dataclass
class PlanSignatures:
    """Per-file signatures in plan file-position order, plus the
    schema stamp. Pickled inside the RulePlan artifact; `pack_union`
    derives the inverted-index row for one pack's member set."""

    schema: int
    files: List[Optional[FileSignature]]

    def pack_union(self, member_positions) -> FileSignature:
        u = FileSignature()
        types: set = set()
        chains: set = set()
        for fi in member_positions:
            sig = self.files[fi] if 0 <= fi < len(self.files) else None
            if sig is None:
                u.unanchored_rules += 1
                continue
            types.update(sig.type_equalities)
            chains.update(sig.key_chains)
            u.unanchored_rules += sig.unanchored_rules
        u.type_equalities = sorted(types)
        u.key_chains = sorted(chains)
        return u


# ---------------------------------------------------------------------------
# extraction
# ---------------------------------------------------------------------------
def _string_values(lv) -> List[str]:
    """STRING literal(s) of a compare RHS: a bare string, or every
    string item of a list literal (`IN [...]`)."""
    if not isinstance(lv, PV):
        return []
    if lv.kind == _v.STRING:
        return [lv.val]
    if lv.kind == _v.LIST:
        return [it.val for it in lv.val if it.kind == _v.STRING]
    return []


def _leading_key_chain(query: List) -> Tuple[str, ...]:
    """The leading run of literal map keys on a root-anchored query —
    empty for variable/`this`-headed queries."""
    out: List[str] = []
    for part in query:
        if isinstance(part, QKey) and not part_is_variable(part):
            out.append(part.name)
        else:
            break
    return tuple(out)


def _type_equalities(obj) -> List[str]:
    """Every `... .Type == 'X'` / `Type IN [...]` anchor reachable in
    `obj` — including filter conjunctions (`Resources[ Type == 'X' ]`)
    and type-block sugar — via the structural AST walk."""
    found: List[str] = []

    def visit(node) -> bool:
        if isinstance(node, TypeBlock):
            found.append(node.type_name)
            return False
        if isinstance(node, GuardAccessClause):
            ac = node.access_clause
            if (
                not node.negation
                and not ac.comparator_inverse
                and ac.comparator.value in ("Eq", "In")
            ):
                parts = ac.query.query
                last_key = parts[-1] if parts else None
                if (
                    isinstance(last_key, QKey)
                    and not part_is_variable(last_key)
                    and last_key.name == "Type"
                ):
                    found.extend(_string_values(ac.compare_with))
        return False

    walk_expr_tree(obj, visit)
    return found


def _rule_anchors(rule: Rule):
    """(type_equalities, key_chains, anchored) for one named rule:
    key chains come from the rule's TOP-LEVEL clause queries only
    (inner block queries are relative, not root-anchored)."""
    types = _type_equalities(rule)
    chains: List[Tuple[str, ...]] = []
    anchored = False
    top: List = []
    for conj in (rule.conditions or []):
        top.extend(conj)
    for conj in rule.block.conjunctions:
        top.extend(conj)
    for clause in top:
        q: Optional[AccessQuery] = None
        if isinstance(clause, GuardAccessClause):
            q = clause.access_clause.query
        elif isinstance(clause, BlockGuardClause):
            q = clause.query
        elif isinstance(clause, TypeBlock):
            kc = _leading_key_chain(clause.query)
            if kc:
                chains.append(kc)
                anchored = True
            continue
        elif isinstance(clause, WhenBlockClause):
            # the when gate's own queries anchor the whole block
            for c2 in (x for conj in clause.conditions for x in conj):
                if isinstance(c2, GuardAccessClause):
                    kc = _leading_key_chain(c2.access_clause.query.query)
                    if kc:
                        chains.append(kc)
                        anchored = True
            continue
        if q is not None:
            kc = _leading_key_chain(q.query)
            if kc:
                chains.append(kc)
                anchored = True
    return types, chains, anchored or bool(types)


def extract_file_signature(rules_file: RulesFile) -> FileSignature:
    """Anchor signature of one parsed rule file."""
    types: set = set()
    chains: set = set()
    unanchored = 0
    rules = list(rules_file.guard_rules)
    rules.extend(pr.rule for pr in rules_file.parameterized_rules)
    for rule in rules:
        t, c, anchored = _rule_anchors(rule)
        types.update(t)
        chains.update(c)
        if not anchored:
            unanchored += 1
    sig = FileSignature(
        type_equalities=sorted(types),
        key_chains=sorted(chains),
        unanchored_rules=unanchored,
    )
    ANALYSIS_COUNTERS["signatures_extracted"] += 1
    return sig


def extract_plan_signatures(rule_files) -> PlanSignatures:
    """Per-file signatures for a registry, in plan file-position
    order. `rule_files` carry parsed ASTs on `.rules` (the
    commands/validate.RuleFile shape build_plan already consumes)."""
    files: List[Optional[FileSignature]] = []
    for rf in rule_files:
        try:
            files.append(extract_file_signature(rf.rules))
        except Exception:
            # extraction is advisory: an unextractable file is an
            # unanchored (never-skippable) one, not an error
            files.append(None)
    return PlanSignatures(schema=SIGNATURE_SCHEMA_VERSION, files=files)


def signatures_payload(plan, digest: str) -> dict:
    """The JSON sidecar body: per-file signatures plus the
    pack -> union-signature inverted index, keyed by the plan digest
    (digest-versioned: a registry edit changes the digest, so stale
    sidecars simply never match a live plan)."""
    sigs: Optional[PlanSignatures] = getattr(plan, "signatures", None)
    files = []
    if sigs is not None:
        files = [
            (None if s is None else s.to_json()) for s in sigs.files
        ]
    packs = []
    if sigs is not None:
        for pos, _packed, _spec in plan.packs:
            u = sigs.pack_union(pos)
            packs.append({"members": list(pos), **u.to_json()})
    return {
        "schema": SIGNATURE_SCHEMA_VERSION,
        "digest": digest,
        "files": files,
        "packs": packs,
    }
