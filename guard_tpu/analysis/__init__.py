"""The static analysis plane: plan/IR verifier, rule linter, anchors.

Three passes that reason about rules and compiled plans WITHOUT
touching a document or a device:

  * ``verify``     — named-invariant checks over ``ops/plan.RulePlan``
                     structures (slot relocation, pack segments, bit
                     tables, anchor chains, rim coverage), hooked into
                     plan build / artifact load / per-chunk relocation;
  * ``lint``       — abstract-domain checks over parsed Guard rules
                     (unsatisfiable conjunctions, type conflicts,
                     shadowed rules, always-SKIP whens, dead lets),
                     surfaced as the ``guard-tpu lint`` subcommand;
  * ``signatures`` — per rule-file anchor key-chains and type
                     equalities, persisted with the plan artifact —
                     the routing input for rule-relevance partial
                     evaluation (ROADMAP item 2).

Every pass is advisory-by-default and pure-host. `GUARD_TPU_ANALYSIS=0`
(or the per-run `--no-verify-plans` flag) disables the verifier hooks
entirely; validation output stays byte-identical either way — the
verifier can only *reject* a plan (hard diagnostic on fresh lowering,
logged miss on artifact load), never change what a healthy plan
computes.
"""

from __future__ import annotations

import os

from ..utils.telemetry import REGISTRY as _TELEMETRY

#: analysis-plane observability, in every --metrics-out snapshot:
#: `invariants_checked` counts individual invariant evaluations across
#: verify_plan/verify_relocation calls, `violations` the failures,
#: `lint_findings` every finding any severity, `signatures_extracted`
#: per-file anchor signatures derived during plan builds.
ANALYSIS_COUNTERS = _TELEMETRY.counter_group(
    "analysis",
    {
        "invariants_checked": 0,
        "violations": 0,
        "lint_findings": 0,
        "signatures_extracted": 0,
    },
)


def analysis_stats() -> dict:
    return _TELEMETRY.group_stats("analysis")


def reset_analysis_stats() -> None:
    _TELEMETRY.reset_group("analysis")


def analysis_enabled(flag: bool = True) -> bool:
    """The verifier's on switch: the caller's --no-verify-plans flag
    AND the `GUARD_TPU_ANALYSIS=0` env escape hatch (read at call time
    so one process can compare both paths — the parity smoke does)."""
    return bool(flag) and os.environ.get("GUARD_TPU_ANALYSIS", "1") != "0"
