"""Plan/IR verifier: named structural invariants over compiled plans.

A `RulePlan` (ops/plan.py) is the product of three slot-rewriting
passes — lowering, packing, relocation — and a pickle round-trip, any
of which can miscompile or corrupt it in ways the dynamic parity
checks only catch after a dispatch has produced wrong bits. This
module checks the invariants those passes promise, as pure-host
structure walks (no jax, no documents):

  segment_offsets_consistent  pack offsets/sizes partition the packed
                              rule list and mirror the member files
  slot_relocation_bijective   every slot reference (lits, bit tables,
                              has-child, chains, structs, named-rule
                              indices) lands inside its table; parallel
                              tables agree on length
  bit_table_width             every (S,) bit table covers exactly the
                              plan interner's current string count
  anchor_chain_domains        folded StepKeyChains keep the >= 2-step,
                              pairwise-disjoint-keys contract and point
                              at the chain_tables spec they were folded
                              from (ir.StepKeyChain docstring)
  rim_name_group_coverage     each pack's RimSpec equals the spec
                              recomputed from its segments (group ids,
                              per-file names, last-rule-wins columns)
  intern_id_domain            a relocated batch's id columns stay
                              inside the plan interner's namespace
  bucket_discipline           the node-bucket ladder is strictly
                              increasing (shape-discipline backstop)

`verify_plan` runs the full structural set (after build_plan and on
every artifact load); `verify_relocation` is the cheap per-chunk
subset (table widths + id domains) run after relocate_batch — sized to
stay inside the <= 2% overhead budget the bench row pins.

Violations are DATA (invariant name + detail), not exceptions: the
plan layer decides policy — a failed verify on artifact load is a
logged miss, a failed verify on fresh lowering raises
`PlanVerifyError` (a hard diagnostic: the bug is in this process's
lowering, not in a stale file).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

import numpy as np

from ..core.errors import GuardError
from ..utils.telemetry import span as _span
from ..ops.ir import (
    CBlockClause,
    CClause,
    CCountClause,
    CNamedRef,
    CWhenBlock,
    StepFilter,
    StepIndex,
    StepKey,
    StepKeyChain,
    StepKeyInterpLit,
    StepKeyInterpVar,
    StepKeysMatch,
    build_rim_spec,
)
from . import ANALYSIS_COUNTERS

#: every invariant name the verifier can emit (docs + mutation tests
#: enumerate against this)
INVARIANTS = (
    "segment_offsets_consistent",
    "slot_relocation_bijective",
    "bit_table_width",
    "anchor_chain_domains",
    "rim_name_group_coverage",
    "intern_id_domain",
    "bucket_discipline",
)


@dataclass
class Violation:
    """One named invariant failure. `where` locates the structure
    (pack index, file position, rule index) in plan coordinates."""

    invariant: str
    detail: str
    where: str = ""

    def __str__(self) -> str:
        loc = f" [{self.where}]" if self.where else ""
        return f"{self.invariant}{loc}: {self.detail}"


class PlanVerifyError(GuardError):
    """A freshly lowered plan failed verification — a miscompile in
    THIS process, surfaced as a hard diagnostic (exit 5) instead of
    wrong device bits later."""

    def __init__(self, violations: List[Violation]):
        self.violations = violations
        super().__init__(
            "plan verification failed: " + "; ".join(str(v) for v in violations)
        )


# ---------------------------------------------------------------------------
# step/node walks (slot references)
# ---------------------------------------------------------------------------
def _walk_steps(steps, visit_step) -> None:
    for s in steps:
        visit_step(s)
        if isinstance(s, StepKeyChain):
            _walk_steps(s.steps, visit_step)
        elif isinstance(s, StepKeyInterpVar):
            _walk_steps(s.var_steps, visit_step)
        elif isinstance(s, StepFilter):
            for disj in s.conjunctions:
                for n in disj:
                    _walk_node(n, visit_step, lambda n: None)


def _walk_node(node, visit_step, visit_node) -> None:
    visit_node(node)
    if isinstance(node, CClause):
        _walk_steps(node.steps, visit_step)
        if node.rhs_query_steps is not None:
            _walk_steps(node.rhs_query_steps, visit_step)
    elif isinstance(node, CCountClause):
        _walk_steps(node.steps, visit_step)
    elif isinstance(node, CBlockClause):
        _walk_steps(node.query_steps, visit_step)
        for disj in node.inner:
            for n in disj:
                _walk_node(n, visit_step, visit_node)
    elif isinstance(node, CWhenBlock):
        for disj in node.conditions or []:
            for n in disj:
                _walk_node(n, visit_step, visit_node)
        for disj in node.inner:
            for n in disj:
                _walk_node(n, visit_step, visit_node)


def _walk_compiled(comp, visit_step, visit_node) -> None:
    for r in comp.rules:
        for disj in r.conditions or []:
            for n in disj:
                _walk_node(n, visit_step, visit_node)
        for disj in r.conjunctions:
            for n in disj:
                _walk_node(n, visit_step, visit_node)


def _rhs_slots(rhs, visit) -> None:
    if rhs is None:
        return
    visit("lit", rhs.str_slot)
    visit("bits", rhs.bits_slot)
    visit("bits", rhs.lt_slot)
    visit("bits", rhs.le_slot)
    visit("struct", rhs.struct_slot)
    for it in rhs.items or []:
        _rhs_slots(it, visit)


# ---------------------------------------------------------------------------
# individual invariants
# ---------------------------------------------------------------------------
def _check_segments(plan) -> List[Violation]:
    out: List[Violation] = []

    def bad(where: str, detail: str) -> None:
        out.append(Violation("segment_offsets_consistent", detail, where))

    n_files = len(plan.compiled)
    for pi, (pos, packed, _spec) in enumerate(plan.packs):
        where = f"pack {pi}"
        if len(packed.offsets) != len(pos) or len(packed.sizes) != len(pos):
            bad(where, f"{len(pos)} members but {len(packed.offsets)} "
                f"offsets / {len(packed.sizes)} sizes")
            continue
        if len(set(pos)) != len(pos):
            bad(where, f"duplicate member positions {pos}")
        expect = 0
        for k, fi in enumerate(pos):
            if not (0 <= fi < n_files) or plan.compiled[fi] is None:
                bad(f"{where} member {k}", f"file position {fi} is not a "
                    "lowered plan file")
                continue
            if packed.offsets[k] != expect:
                bad(f"{where} member {k}", f"offset {packed.offsets[k]} != "
                    f"running total {expect}")
            if packed.sizes[k] != len(plan.compiled[fi].rules):
                bad(f"{where} member {k}", f"size {packed.sizes[k]} != "
                    f"{len(plan.compiled[fi].rules)} rules in file {fi}")
            expect += packed.sizes[k]
        if expect != len(packed.compiled.rules):
            bad(where, f"segments cover {expect} rules but the pack "
                f"holds {len(packed.compiled.rules)}")
    return out


def _check_slots(plan) -> List[Violation]:
    out: List[Violation] = []
    for label, comp in _plan_parts(plan):
        if len(comp.bit_tables) != len(comp.bit_specs):
            out.append(Violation(
                "slot_relocation_bijective",
                f"{len(comp.bit_tables)} bit_tables vs "
                f"{len(comp.bit_specs)} bit_specs (parallel tables "
                "disagree)", label,
            ))
        n_rules = len(comp.rules)
        bounds = {
            "lit": len(comp.lit_names),
            "bits": len(comp.bit_tables),
            "kidc": len(comp.kidc_tables),
            "chain": len(comp.chain_tables),
            "struct": len(comp.struct_literals),
        }

        def visit(kind: str, slot: int) -> None:
            if not (0 <= slot < bounds[kind]):
                out.append(Violation(
                    "slot_relocation_bijective",
                    f"{kind} slot {slot} out of range "
                    f"[0, {bounds[kind]})", label,
                ))

        def visit_step(s) -> None:
            if isinstance(s, StepKey):
                for x in s.lit_slots:
                    visit("lit", x)
                if s.kc_slot >= 0:
                    visit("kidc", s.kc_slot)
            elif isinstance(s, StepKeyChain):
                visit("chain", s.chain_slot)
            elif isinstance(s, StepKeyInterpLit):
                for x in s.lit_slots:
                    visit("lit", x)
                for x in s.kc_slots:
                    visit("kidc", x)
            elif isinstance(s, StepIndex):
                if s.kc_slot >= 0:
                    visit("kidc", s.kc_slot)
            elif isinstance(s, StepKeysMatch):
                _rhs_slots(s.rhs, lambda k, v: v >= 0 and visit(k, v))

        def visit_node(n) -> None:
            if isinstance(n, CClause):
                _rhs_slots(n.rhs, lambda k, v: v >= 0 and visit(k, v))
            elif isinstance(n, CNamedRef):
                for ri in n.rule_indices:
                    if not (0 <= ri < n_rules):
                        out.append(Violation(
                            "slot_relocation_bijective",
                            f"named-rule index {ri} out of range "
                            f"[0, {n_rules})", label,
                        ))

        if comp.str_empty_slot >= len(comp.bit_tables):
            out.append(Violation(
                "slot_relocation_bijective",
                f"str_empty_slot {comp.str_empty_slot} out of range "
                f"[0, {len(comp.bit_tables)})", label,
            ))
        _walk_compiled(comp, visit_step, visit_node)
    return out


def _check_bit_widths(plan) -> List[Violation]:
    out: List[Violation] = []
    n = len(plan.interner.strings)
    for label, comp in _plan_parts(plan):
        for i, (table, _target) in enumerate(comp.bit_tables):
            if len(table) != n:
                out.append(Violation(
                    "bit_table_width",
                    f"bit table {i} covers {len(table)} strings, "
                    f"interner holds {n}", label,
                ))
        if len(comp.str_empty_bits) != n:
            out.append(Violation(
                "bit_table_width",
                f"str_empty_bits covers {len(comp.str_empty_bits)} "
                f"strings, interner holds {n}", label,
            ))
    return out


def _check_chains(plan) -> List[Violation]:
    out: List[Violation] = []
    for label, comp in _plan_parts(plan):
        n_chains = len(comp.chain_tables)

        def visit_step(s) -> None:
            if not isinstance(s, StepKeyChain):
                return
            if len(s.steps) < 2:
                out.append(Violation(
                    "anchor_chain_domains",
                    f"chain of {len(s.steps)} steps (folding requires "
                    ">= 2)", label,
                ))
                return
            seen: set = set()
            for st in s.steps:
                keys = set(st.key_names)
                if seen & keys:
                    out.append(Violation(
                        "anchor_chain_domains",
                        f"chain steps share key(s) {sorted(seen & keys)} "
                        "(anchor positions are no longer unique)", label,
                    ))
                seen |= keys
            if not (0 <= s.chain_slot < n_chains):
                out.append(Violation(
                    "anchor_chain_domains",
                    f"chain_slot {s.chain_slot} out of range "
                    f"[0, {n_chains})", label,
                ))
                return
            spec = tuple(
                (tuple(st.key_names), st.drop_unres) for st in s.steps
            )
            if comp.chain_tables[s.chain_slot] != spec:
                out.append(Violation(
                    "anchor_chain_domains",
                    f"chain_slot {s.chain_slot} binds spec "
                    f"{comp.chain_tables[s.chain_slot]!r}, the folded "
                    f"steps say {spec!r} (anchor columns would be "
                    "computed for the wrong keys)", label,
                ))

        _walk_compiled(comp, visit_step, lambda n: None)
    return out


def _check_rim(plan) -> List[Violation]:
    out: List[Violation] = []
    for pi, (pos, packed, spec) in enumerate(plan.packs):
        where = f"pack {pi}"
        if len(packed.offsets) != len(pos):
            continue  # already reported by segment_offsets_consistent
        if spec.n_files != len(pos):
            out.append(Violation(
                "rim_name_group_coverage",
                f"rim spec covers {spec.n_files} files, pack has "
                f"{len(pos)}", where,
            ))
            continue
        want = build_rim_spec(
            [packed.compiled.rules[packed.segment(i)]
             for i in range(len(pos))]
        )
        for fld in ("group_ids", "file_ids", "last_ids"):
            if not np.array_equal(getattr(spec, fld), getattr(want, fld)):
                out.append(Violation(
                    "rim_name_group_coverage",
                    f"{fld} disagree with the spec recomputed from the "
                    "pack segments", where,
                ))
        if (spec.n_groups != want.n_groups
                or spec.group_offsets != want.group_offsets
                or spec.file_group_names != want.file_group_names):
            out.append(Violation(
                "rim_name_group_coverage",
                "group numbering/name coverage disagrees with the pack "
                "segments", where,
            ))
    return out


def _check_buckets() -> List[Violation]:
    from ..ops.encoder import NODE_BUCKETS_EXTENDED

    b = tuple(NODE_BUCKETS_EXTENDED)
    if all(x > 0 for x in b) and all(b[i] < b[i + 1] for i in range(len(b) - 1)):
        return []
    return [Violation(
        "bucket_discipline",
        f"node-bucket ladder {b} is not strictly increasing positive",
    )]


def _plan_parts(plan):
    """(label, CompiledRules) for every part whose slots/tables the
    invariants cover — per-file programs and each pack's fused one."""
    for fi, c in enumerate(plan.compiled):
        if c is not None:
            yield f"file {fi}", c
    for pi, (_pos, packed, _spec) in enumerate(plan.packs):
        yield f"pack {pi}", packed.compiled


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------
def verify_plan(plan) -> List[Violation]:
    """Full structural verification of a RulePlan; returns every
    violation found (empty list = healthy). Pure host, no jax."""
    with _span("verify_plan", {"files": len(plan.compiled),
                               "packs": len(plan.packs)}):
        out: List[Violation] = []
        out.extend(_check_segments(plan))
        out.extend(_check_slots(plan))
        out.extend(_check_bit_widths(plan))
        out.extend(_check_chains(plan))
        out.extend(_check_rim(plan))
        out.extend(_check_buckets())
        ANALYSIS_COUNTERS["invariants_checked"] += len(INVARIANTS) - 1
        ANALYSIS_COUNTERS["violations"] += len(out)
        return out


def verify_relocation(plan, batch) -> List[Violation]:
    """The cheap per-chunk subset, run after relocate_batch: every bit
    table must cover the (grown) interner, and the relocated batch's
    string-id columns must stay inside the interner's namespace (a
    stale id would gather garbage rows from every bit table). Length
    compares plus two numpy max reductions — sized for the <= 2%
    overhead bar."""
    out: List[Violation] = []
    n = len(plan.interner.strings)
    for label, comp in _plan_parts(plan):
        for i, (table, _target) in enumerate(comp.bit_tables):
            if len(table) != n:
                out.append(Violation(
                    "bit_table_width",
                    f"bit table {i} covers {len(table)} strings after "
                    f"relocation, interner holds {n}", label,
                ))
                break  # one per part is diagnostic enough
    for col in ("scalar_id", "node_key_id"):
        arr = getattr(batch, col, None)
        if arr is None or arr.size == 0:
            continue
        hi = int(np.max(arr))
        if hi >= n:
            out.append(Violation(
                "intern_id_domain",
                f"batch {col} holds intern id {hi}, plan interner ends "
                f"at {n - 1} (stale/unrelocated ids)",
            ))
    ANALYSIS_COUNTERS["invariants_checked"] += 2
    ANALYSIS_COUNTERS["violations"] += len(out)
    return out


def first_violation_name(violations: List[Violation]) -> Optional[str]:
    return violations[0].invariant if violations else None
