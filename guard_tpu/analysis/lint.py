"""Guard rule linter: abstract-domain checks over parsed rules.

Finds rules that are statically broken — they can never pass, never
fire, or silently shadow each other — before any document is read:

  unsat-conjunction      ERROR    AND-ed comparisons on one query path
                                  with an empty intersection (interval
                                  analysis on numerics, equality
                                  conflicts on strings)
  type-conflict          ERROR    two different `IS <type>` assertions
                                  AND-ed on one query path
  always-skip-when       WARNING  a `when` gate (or `rule X when ...`
                                  condition) that is statically
                                  unsatisfiable — the guarded block is
                                  dead and the rule always SKIPs
  unsat-filter           WARNING  a `[ ... ]` filter whose predicate
                                  set is unsatisfiable — it selects
                                  nothing, so the query always misses
  shadowed-rule          WARNING  two rules with one name but different
                                  bodies in one file (the name group
                                  merges them; which status wins is an
                                  evaluation-order accident)
  duplicate-rule         WARNING  two byte-equivalent rules under one
                                  name in one file (evaluated twice)
  cross-file-duplicate   INFO     one rule name defined in several
                                  linted files (named-rule references
                                  resolve per file — easy to misread)
  unreferenced-variable  WARNING  a `let` binding never referenced as
                                  `%name` anywhere in its file

The analysis is deliberately conservative — `some`-quantified, negated
and inverse-comparator clauses never contribute constraints — so a
finding is a real property of the rule text, not a heuristic: the
shipped corpora must lint clean at ERROR severity
(tests/test_lint_corpus.py) and stay clean.

Severity contract (the `guard-tpu lint` exit codes build on it):
ERROR = the rule cannot work as written; WARNING = the rule works but
almost certainly not as intended; INFO = worth a look.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..core import values as _v
from ..core.exprs import (
    BlockGuardClause,
    CmpOperator,
    FileLocation,
    GuardAccessClause,
    LetExpr,
    QFilter,
    QKey,
    Rule,
    RulesFile,
    TypeBlock,
    WhenBlockClause,
    walk_expr_tree,
)
from ..core.values import PV
from ..utils.telemetry import span as _span
from . import ANALYSIS_COUNTERS

ERROR = "ERROR"
WARNING = "WARNING"
INFO = "INFO"
SEVERITIES = (ERROR, WARNING, INFO)
_SEV_RANK = {ERROR: 0, WARNING: 1, INFO: 2}

#: every check code the linter can emit (docs + tests enumerate)
CHECKS = (
    "unsat-conjunction",
    "type-conflict",
    "always-skip-when",
    "unsat-filter",
    "shadowed-rule",
    "duplicate-rule",
    "cross-file-duplicate",
    "unreferenced-variable",
)


@dataclass
class Finding:
    severity: str
    code: str
    message: str
    file: str = ""
    rule: str = ""
    line: int = 0
    column: int = 0

    def render(self) -> str:
        where = f"{self.file}:{self.line}:{self.column}"
        rule = f" (rule {self.rule})" if self.rule else ""
        return f"{where}: {self.severity} [{self.code}]{rule} {self.message}"

    def to_json(self) -> dict:
        return {
            "severity": self.severity,
            "code": self.code,
            "file": self.file,
            "rule": self.rule,
            "line": self.line,
            "column": self.column,
            "message": self.message,
        }


def max_severity(findings: List[Finding]) -> Optional[str]:
    if not findings:
        return None
    return min((f.severity for f in findings), key=_SEV_RANK.get)


# ---------------------------------------------------------------------------
# the abstract numeric/string domain for one (context, query path)
# ---------------------------------------------------------------------------
class _PathDomain:
    """Constraints accumulated for one query path inside one AND
    context: a numeric interval (ints and floats merged — if the
    numeric intersection is empty, no value of either kind satisfies
    the conjunction), string equalities, and `IS <type>` assertions."""

    __slots__ = ("lo", "lo_strict", "hi", "hi_strict", "num_eq",
                 "str_eq", "is_types", "first_loc")

    def __init__(self) -> None:
        self.lo: Optional[float] = None
        self.lo_strict = False
        self.hi: Optional[float] = None
        self.hi_strict = False
        self.num_eq: Optional[float] = None
        self.str_eq: Optional[str] = None
        self.is_types: Dict[str, FileLocation] = {}
        self.first_loc: Optional[FileLocation] = None

    def add_bound(self, op: CmpOperator, val: float) -> Optional[str]:
        """Fold one comparison in; returns an unsat description when
        the interval just became empty."""
        if op is CmpOperator.Eq:
            if self.num_eq is not None and self.num_eq != val:
                return f"== {_fmt(self.num_eq)} conflicts with == {_fmt(val)}"
            self.num_eq = val
        elif op in (CmpOperator.Gt, CmpOperator.Ge):
            strict = op is CmpOperator.Gt
            if self.lo is None or val > self.lo or (
                val == self.lo and strict and not self.lo_strict
            ):
                self.lo, self.lo_strict = val, strict
        elif op in (CmpOperator.Lt, CmpOperator.Le):
            strict = op is CmpOperator.Lt
            if self.hi is None or val < self.hi or (
                val == self.hi and strict and not self.hi_strict
            ):
                self.hi, self.hi_strict = val, strict
        return self._num_unsat()

    def _num_unsat(self) -> Optional[str]:
        lo, hi = self.lo, self.hi
        if lo is not None and hi is not None:
            if lo > hi or (lo == hi and (self.lo_strict or self.hi_strict)):
                return (
                    f"{'>' if self.lo_strict else '>='} {_fmt(lo)} "
                    f"conflicts with "
                    f"{'<' if self.hi_strict else '<='} {_fmt(hi)}"
                )
        if self.num_eq is not None:
            v = self.num_eq
            if lo is not None and (v < lo or (v == lo and self.lo_strict)):
                return (f"== {_fmt(v)} conflicts with "
                        f"{'>' if self.lo_strict else '>='} {_fmt(lo)}")
            if hi is not None and (v > hi or (v == hi and self.hi_strict)):
                return (f"== {_fmt(v)} conflicts with "
                        f"{'<' if self.hi_strict else '<='} {_fmt(hi)}")
        return None

    def add_str_eq(self, val: str) -> Optional[str]:
        if self.str_eq is not None and self.str_eq != val:
            return f"== {self.str_eq!r} conflicts with == {val!r}"
        self.str_eq = val
        return None

    def add_is_type(self, op: CmpOperator, loc: FileLocation) -> Optional[str]:
        self.is_types[op.value] = loc
        if len(self.is_types) > 1:
            return " and ".join(sorted(self.is_types))
        return None


def _fmt(v: float) -> str:
    return str(int(v)) if float(v).is_integer() else str(v)


_IS_TYPES = {
    CmpOperator.IsString,
    CmpOperator.IsList,
    CmpOperator.IsMap,
    CmpOperator.IsBool,
    CmpOperator.IsInt,
    CmpOperator.IsFloat,
    CmpOperator.IsNull,
}


# ---------------------------------------------------------------------------
# AND-context collection
# ---------------------------------------------------------------------------
def _contexts(rule: Rule):
    """Yield every AND context in a rule as (kind, conjunctions) with
    kind 'when' (a gate: unsat = dead block), 'filter' (a selection:
    unsat = empty selection) or 'clauses' (assertions: unsat = the
    rule can never pass). Conjunctions are CNF — outer AND, inner OR —
    so only single-clause disjunctions contribute constraints."""
    if rule.conditions:
        yield ("when", rule.conditions)
    stack: List[Tuple[str, list]] = [("clauses", rule.block.conjunctions)]
    while stack:
        kind, conjs = stack.pop()
        yield (kind, conjs)
        for disj in conjs:
            for clause in disj:
                if isinstance(clause, BlockGuardClause):
                    stack.append(("clauses", clause.block.conjunctions))
                    _push_filters(clause.query.query, stack)
                elif isinstance(clause, WhenBlockClause):
                    stack.append(("when", clause.conditions))
                    stack.append(("clauses", clause.block.conjunctions))
                elif isinstance(clause, TypeBlock):
                    stack.append(("clauses", clause.block.conjunctions))
                    if clause.conditions:
                        stack.append(("when", clause.conditions))
                elif isinstance(clause, GuardAccessClause):
                    _push_filters(clause.access_clause.query.query, stack)


def _push_filters(parts: List, stack: List) -> None:
    for p in parts:
        if isinstance(p, QFilter):
            stack.append(("filter", p.conjunctions))


def _clause_loc(clause) -> FileLocation:
    if isinstance(clause, GuardAccessClause):
        return clause.access_clause.location
    return FileLocation()


def _check_context(
    kind: str, conjs, rule_name: str, file_name: str
) -> List[Finding]:
    """The unsat/type-conflict pass over one AND context."""
    out: List[Finding] = []
    domains: Dict[str, _PathDomain] = {}
    reported: set = set()

    def emit(code: str, sev: str, msg: str, loc: FileLocation) -> None:
        key = (code, rule_name, msg)
        if key in reported:
            return
        reported.add(key)
        out.append(Finding(
            severity=sev, code=code, message=msg, file=file_name,
            rule=rule_name, line=loc.line, column=loc.column,
        ))

    def conflict(detail: str, path: str, loc: FileLocation,
                 type_conflict: bool = False) -> None:
        if type_conflict:
            emit("type-conflict", ERROR,
                 f"`{path}` is asserted to be {detail} on one path — "
                 "the conjunction can never hold", loc)
        elif kind == "when":
            emit("always-skip-when", WARNING,
                 f"when gate is statically unsatisfiable on `{path}`: "
                 f"{detail} — the guarded block is dead (always SKIP)",
                 loc)
        elif kind == "filter":
            emit("unsat-filter", WARNING,
                 f"filter predicate on `{path}` is unsatisfiable: "
                 f"{detail} — the filter selects nothing", loc)
        else:
            emit("unsat-conjunction", ERROR,
                 f"AND-ed comparisons on `{path}` are unsatisfiable: "
                 f"{detail}", loc)

    for disj in conjs:
        if len(disj) != 1 or not isinstance(disj[0], GuardAccessClause):
            continue  # OR'd or non-access clauses add no constraint
        clause = disj[0]
        ac = clause.access_clause
        if clause.negation or ac.comparator_inverse or not ac.query.match_all:
            continue  # negations and `some` never make a conjunction unsat
        path = ac.query.display()
        dom = domains.get(path)
        if dom is None:
            dom = domains[path] = _PathDomain()
            dom.first_loc = ac.location
        op = ac.comparator
        if op in _IS_TYPES:
            detail = dom.add_is_type(op, ac.location)
            if detail:
                conflict(detail, path, ac.location, type_conflict=True)
            continue
        rhs = ac.compare_with
        if not isinstance(rhs, PV):
            continue
        if op is CmpOperator.Eq and rhs.kind == _v.STRING:
            detail = dom.add_str_eq(rhs.val)
            if detail:
                conflict(detail, path, ac.location)
        elif (
            op in (CmpOperator.Eq, CmpOperator.Gt, CmpOperator.Ge,
                   CmpOperator.Lt, CmpOperator.Le)
            and rhs.kind in (_v.INT, _v.FLOAT)
        ):
            detail = dom.add_bound(op, rhs.val)
            if detail:
                conflict(detail, path, ac.location)
    return out


# ---------------------------------------------------------------------------
# duplicate / shadowed rules
# ---------------------------------------------------------------------------
def _canon(obj):
    """Location-insensitive structural fingerprint of an AST subtree
    (PVs canonicalize through their display form — they carry no
    dataclass fields to compare)."""
    if isinstance(obj, FileLocation):
        return "@"
    if isinstance(obj, PV):
        from ..core.values import rust_debug_pv

        return ("pv", rust_debug_pv(obj))
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return (type(obj).__name__,) + tuple(
            _canon(getattr(obj, f.name)) for f in dataclasses.fields(obj)
        )
    if isinstance(obj, (list, tuple)):
        return tuple(_canon(e) for e in obj)
    if isinstance(obj, dict):
        return tuple(sorted((k, _canon(v)) for k, v in obj.items()))
    return obj


def _check_duplicates(rf: RulesFile, file_name: str) -> List[Finding]:
    out: List[Finding] = []
    by_name: Dict[str, List[Rule]] = {}
    for r in rf.guard_rules:
        by_name.setdefault(r.rule_name, []).append(r)
    for name, rules in by_name.items():
        if len(rules) < 2:
            continue
        canons = [_canon(r) for r in rules]
        if all(c == canons[0] for c in canons[1:]):
            out.append(Finding(
                severity=WARNING, code="duplicate-rule", file=file_name,
                rule=name,
                message=f"rule `{name}` is defined {len(rules)} times "
                "with identical bodies — the name group evaluates the "
                "same assertions repeatedly",
            ))
        else:
            out.append(Finding(
                severity=WARNING, code="shadowed-rule", file=file_name,
                rule=name,
                message=f"rule `{name}` is defined {len(rules)} times "
                "with DIFFERENT bodies — same-named rules merge into "
                "one name group, so which status wins is an "
                "evaluation-order accident",
            ))
    return out


# ---------------------------------------------------------------------------
# unreferenced variables
# ---------------------------------------------------------------------------
def _check_variables(rf: RulesFile, file_name: str) -> List[Finding]:
    declared: Dict[str, str] = {}  # var -> owning rule name ("" = file)
    for let in rf.assignments:
        declared.setdefault(let.var, "")

    def collect_lets(rule: Rule) -> None:
        def visit(node) -> bool:
            if isinstance(node, LetExpr):
                declared.setdefault(node.var, rule.rule_name)
            return False

        walk_expr_tree(rule, visit)

    params: set = set()
    for r in rf.guard_rules:
        collect_lets(r)
    for pr in rf.parameterized_rules:
        params.update(pr.parameter_names)
        collect_lets(pr.rule)

    referenced: set = set()

    def visit_ref(node) -> bool:
        if isinstance(node, QKey) and node.name.startswith("%"):
            referenced.add(node.name[1:])
        return False

    walk_expr_tree(rf, visit_ref)

    out: List[Finding] = []
    for var, owner in sorted(declared.items()):
        if var in referenced or var in params:
            continue
        where = f"rule `{owner}`" if owner else "file scope"
        out.append(Finding(
            severity=WARNING, code="unreferenced-variable",
            file=file_name, rule=owner,
            message=f"`let {var}` ({where}) is never referenced as "
            f"`%{var}`",
        ))
    return out


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------
def lint_rules_file(rf: RulesFile, file_name: str) -> List[Finding]:
    """All single-file checks over one parsed rules file."""
    out: List[Finding] = []
    rules = list(rf.guard_rules)
    rules.extend(pr.rule for pr in rf.parameterized_rules)
    for rule in rules:
        for kind, conjs in _contexts(rule):
            out.extend(_check_context(kind, conjs, rule.rule_name,
                                      file_name))
    out.extend(_check_duplicates(rf, file_name))
    out.extend(_check_variables(rf, file_name))
    return out


def lint_files(parsed: List[Tuple[str, RulesFile]]) -> List[Finding]:
    """Lint a set of (file name, parsed file) pairs: per-file checks
    plus the cross-file duplicate-name pass. Findings sort by file,
    then severity."""
    with _span("lint", {"files": len(parsed)}):
        out: List[Finding] = []
        defined: Dict[str, List[str]] = {}
        for name, rf in parsed:
            out.extend(lint_rules_file(rf, name))
            for r in rf.guard_rules:
                files = defined.setdefault(r.rule_name, [])
                if name not in files:
                    files.append(name)
        for rule_name, files in sorted(defined.items()):
            if len(files) > 1:
                out.append(Finding(
                    severity=INFO, code="cross-file-duplicate",
                    file=files[0], rule=rule_name,
                    message=f"rule `{rule_name}` is defined in "
                    f"{len(files)} files ({', '.join(files)}) — "
                    "named-rule references resolve within one file "
                    "only",
                ))
        out.sort(key=lambda f: (f.file, _SEV_RANK[f.severity], f.line,
                                f.code))
        ANALYSIS_COUNTERS["lint_findings"] += len(out)
        return out
