"""Built-in functions for stateful rules.

The 15 functions callable from `let` assignments / clause RHS, with the
name -> arity registry the parser validates against. Mirrors
`/root/reference/guard/src/rules/eval_context.rs:1181-1268` (registry)
and `/root/reference/guard/src/rules/functions/` (semantics):
count (collections.rs:6), json_parse / regex_replace / substring /
to_upper / to_lower / join / url_decode (strings.rs), parse_* converters
(converters.rs), parse_epoch / now (date_time.rs).

Each function receives already-resolved argument lists of QueryResult and
returns a list of Optional[PV]; `None` entries are dropped by the caller
(`resolve_function`, eval_context.rs:2437-2472).
"""

from __future__ import annotations

import re
import datetime
import json
import time
import urllib.parse
from typing import List, Optional

import yaml

from .errors import IncompatibleError, ParseError
from .qresult import QueryResult, RESOLVED, LITERAL, UNRESOLVED
from .values import (
    BOOL,
    CHAR,
    FLOAT,
    INT,
    STRING,
    Path,
    PV,
    compiled_regex,
    from_plain,
)

# name -> expected number of args (eval_context.rs:1200-1218)
FUNCTION_ARITY = {
    "count": 1,
    "join": 2,
    "json_parse": 1,
    "now": 0,
    "parse_boolean": 1,
    "parse_char": 1,
    "parse_epoch": 1,
    "parse_float": 1,
    "parse_int": 1,
    "parse_string": 1,
    "regex_replace": 3,
    "substring": 3,
    "to_lower": 1,
    "to_upper": 1,
    "url_decode": 1,
}


def _resolved_pv(qr: QueryResult) -> Optional[PV]:
    if qr.tag != UNRESOLVED:
        return qr.value
    return None


def _first_resolved(args: List[QueryResult], err: str) -> PV:
    if not args:
        raise ParseError(err)
    v = _resolved_pv(args[0])
    if v is None:
        raise ParseError(err)
    return v


# ---------------------------------------------------------------------------
# collections
# ---------------------------------------------------------------------------
def fn_count(args: List[QueryResult]) -> List[Optional[PV]]:
    """collections.rs:6-23: number of resolved values in the query."""
    n = sum(1 for q in args if q.tag != UNRESOLVED)
    if not args:
        return [PV.int_(Path.root(), 0)]
    first = args[0]
    path = (
        first.value.self_path()
        if first.tag != UNRESOLVED
        else first.unresolved.traversed_to.self_path()
    )
    return [PV.int_(path, n)]


# ---------------------------------------------------------------------------
# strings
# ---------------------------------------------------------------------------
def fn_json_parse(args: List[QueryResult]) -> List[Optional[PV]]:
    """strings.rs json_parse: YAML-parse each string value."""
    out: List[Optional[PV]] = []
    for q in args:
        v = _resolved_pv(q)
        if v is not None and v.kind == STRING:
            try:
                data = yaml.safe_load(v.val)
            except yaml.YAMLError as e:
                raise ParseError(str(e))
            out.append(from_plain(data, v.self_path()))
        else:
            out.append(None)
    return out


def _rust_expand(template: str, match) -> str:
    """Expand $1 / ${name} capture references like fancy-regex's expand."""
    out = []
    i, n = 0, len(template)
    while i < n:
        c = template[i]
        if c == "$" and i + 1 < n:
            nxt = template[i + 1]
            if nxt == "$":
                out.append("$")
                i += 2
                continue
            if nxt == "{":
                end = template.find("}", i + 2)
                if end > 0:
                    name = template[i + 2 : end]
                    out.append(_group_of(match, name))
                    i = end + 1
                    continue
            j = i + 1
            while j < n and (template[j].isalnum() or template[j] == "_"):
                j += 1
            if j > i + 1:
                out.append(_group_of(match, template[i + 1 : j]))
                i = j
                continue
        out.append(c)
        i += 1
    return "".join(out)


def _group_of(match, name: str) -> str:
    try:
        g = match.group(int(name)) if name.isdigit() else match.group(name)
    except (IndexError, KeyError):
        return ""
    return g or ""


def fn_regex_replace(args: List[List[QueryResult]]) -> List[Optional[PV]]:
    """strings.rs regex_replace: extract with capture groups, re-expand."""
    base, extract_q, replace_q = args
    extract = _first_resolved(
        extract_q, "regex_replace function requires the second argument to be a string"
    )
    replace = _first_resolved(
        replace_q, "regex_replace function requires the third argument to be a string"
    )
    if extract.kind != STRING or replace.kind != STRING:
        raise ParseError("regex_replace function requires string arguments")
    try:
        rx = compiled_regex(extract.val)
    except re.error as e:
        # the reference surfaces an invalid runtime pattern as a clean
        # evaluation error (strings.rs Regex::try_from(...)?), never a
        # crash — string arguments are not parse-time validated the
        # way regex literals are
        raise ParseError(
            f"regex_replace: invalid regular expression "
            f"{extract.val!r}: {e}"
        )
    out: List[Optional[PV]] = []
    for q in base:
        v = _resolved_pv(q)
        if v is not None and v.kind == STRING:
            pieces = [_rust_expand(replace.val, m) for m in rx.finditer(v.val)]
            out.append(PV.string(v.self_path(), "".join(pieces)))
        else:
            out.append(None)
    return out


def fn_substring(args: List[List[QueryResult]]) -> List[Optional[PV]]:
    """strings.rs substring: [from, to) slice; out-of-bounds -> skipped."""
    base, from_q, to_q = args

    def as_index(qlist, which):
        v = _first_resolved(
            qlist, f"substring function requires the {which} argument to be a number"
        )
        if v.kind not in (INT, FLOAT):
            raise ParseError(
                f"substring function requires the {which} argument to be a number"
            )
        return int(v.val)

    start = as_index(from_q, "second")
    end = as_index(to_q, "third")
    out: List[Optional[PV]] = []
    for q in base:
        v = _resolved_pv(q)
        if (
            v is not None
            and v.kind == STRING
            and v.val
            and start < end
            and start <= len(v.val)
            and end <= len(v.val)
        ):
            out.append(PV.string(v.self_path(), v.val[start:end]))
        else:
            out.append(None)
    return out


def _map_strings(args: List[QueryResult], f) -> List[Optional[PV]]:
    out: List[Optional[PV]] = []
    for q in args:
        v = _resolved_pv(q)
        if v is not None and v.kind == STRING:
            out.append(PV.string(v.self_path(), f(v.val)))
        else:
            out.append(None)
    return out


def fn_to_upper(args: List[QueryResult]) -> List[Optional[PV]]:
    return _map_strings(args, str.upper)


def fn_to_lower(args: List[QueryResult]) -> List[Optional[PV]]:
    return _map_strings(args, str.lower)


def fn_url_decode(args: List[QueryResult]) -> List[Optional[PV]]:
    return _map_strings(args, urllib.parse.unquote)


def fn_join(args: List[List[QueryResult]]) -> List[Optional[PV]]:
    """strings.rs join: string values joined by a char/string delimiter."""
    collection, delim_q = args
    delim_pv = _first_resolved(
        delim_q, "join function requires the second argument to be either a char or string"
    )
    if delim_pv.kind not in (STRING, CHAR):
        raise ParseError(
            "join function requires the second argument to be either a char or string"
        )
    parts = []
    for q in collection:
        if q.tag == UNRESOLVED:
            raise IncompatibleError(
                f"Joining unresolved values is not allowed "
                f"{q.unresolved.traversed_to!r}, unsatisfied part {q.unresolved.remaining_query}"
            )
        v = q.value
        if v.kind != STRING:
            raise IncompatibleError(f"Joining non string values {v!r}")
        parts.append(v.val)
    path = (
        collection[0].value.self_path() if collection else Path.root()
    )
    return [PV.string(path, delim_pv.val.join(parts))]


# ---------------------------------------------------------------------------
# converters (converters.rs) — unsupported element types are skipped
# ---------------------------------------------------------------------------
def fn_parse_int(args: List[QueryResult]) -> List[Optional[PV]]:
    out: List[Optional[PV]] = []
    for q in args:
        v = _resolved_pv(q)
        if v is None:
            out.append(None)
        elif v.kind == INT:
            out.append(v)
        elif v.kind == FLOAT:
            out.append(PV.int_(v.self_path(), int(v.val)))
        elif v.kind in (STRING, CHAR):
            try:
                out.append(PV.int_(v.self_path(), int(v.val.strip())))
            except ValueError:
                raise IncompatibleError(f"Cannot parse int from {v.val!r}")
        else:
            out.append(None)
    return out


def fn_parse_float(args: List[QueryResult]) -> List[Optional[PV]]:
    out: List[Optional[PV]] = []
    for q in args:
        v = _resolved_pv(q)
        if v is None:
            out.append(None)
        elif v.kind == FLOAT:
            out.append(v)
        elif v.kind == INT:
            out.append(PV.float_(v.self_path(), float(v.val)))
        elif v.kind in (STRING, CHAR):
            try:
                out.append(PV.float_(v.self_path(), float(v.val.strip())))
            except ValueError:
                raise IncompatibleError(f"Cannot parse float from {v.val!r}")
        else:
            out.append(None)
    return out


def fn_parse_boolean(args: List[QueryResult]) -> List[Optional[PV]]:
    out: List[Optional[PV]] = []
    for q in args:
        v = _resolved_pv(q)
        if v is None:
            out.append(None)
        elif v.kind == BOOL:
            out.append(v)
        elif v.kind == STRING:
            low = v.val.lower()
            if low == "true":
                out.append(PV.boolean(v.self_path(), True))
            elif low == "false":
                out.append(PV.boolean(v.self_path(), False))
            else:
                raise IncompatibleError(f"Cannot parse boolean from {v.val!r}")
        else:
            out.append(None)
    return out


def fn_parse_string(args: List[QueryResult]) -> List[Optional[PV]]:
    out: List[Optional[PV]] = []
    for q in args:
        v = _resolved_pv(q)
        if v is None:
            out.append(None)
        elif v.kind == STRING:
            out.append(v)
        elif v.kind == BOOL:
            out.append(PV.string(v.self_path(), "true" if v.val else "false"))
        elif v.kind in (INT, CHAR):
            out.append(PV.string(v.self_path(), str(v.val)))
        elif v.kind == FLOAT:
            out.append(PV.string(v.self_path(), _format_float(v.val)))
        else:
            out.append(None)
    return out


def _format_float(f: float) -> str:
    """Rust Display for f64: integral floats print without '.0'? No —
    Rust prints 1.5 as '1.5' and 1.0 as '1'. Match Rust's fmt."""
    if f == int(f) and abs(f) < 1e16:
        return str(int(f))
    return repr(f)


def fn_parse_char(args: List[QueryResult]) -> List[Optional[PV]]:
    out: List[Optional[PV]] = []
    for q in args:
        v = _resolved_pv(q)
        if v is None:
            out.append(None)
        elif v.kind == CHAR:
            out.append(v)
        elif v.kind == INT:
            if 0 <= v.val <= 9:
                out.append(PV.char(v.self_path(), str(v.val)))
            else:
                raise IncompatibleError(f"Cannot parse char from int {v.val}")
        elif v.kind == STRING:
            if len(v.val) == 1:
                out.append(PV.char(v.self_path(), v.val))
            else:
                raise IncompatibleError(f"Cannot parse char from string {v.val!r}")
        else:
            out.append(None)
    return out


# ---------------------------------------------------------------------------
# date/time (date_time.rs)
# ---------------------------------------------------------------------------
def fn_parse_epoch(args: List[QueryResult]) -> List[Optional[PV]]:
    """RFC3339 timestamp string -> unix epoch seconds."""
    out: List[Optional[PV]] = []
    for q in args:
        v = _resolved_pv(q)
        if v is not None and v.kind == STRING:
            try:
                s = v.val.replace("Z", "+00:00")
                dt = datetime.datetime.fromisoformat(s)
                if dt.tzinfo is None:
                    dt = dt.replace(tzinfo=datetime.timezone.utc)
                out.append(PV.int_(v.self_path(), int(dt.timestamp())))
            except ValueError:
                raise IncompatibleError(f"Cannot parse epoch from {v.val!r}")
        else:
            out.append(None)
    return out


def fn_now(args: List[QueryResult]) -> List[Optional[PV]]:
    return [PV.int_(Path.root(), int(time.time()))]


# dispatch table; entries marked multi=True receive the full args list
_SINGLE_ARG = {
    "count": fn_count,
    "json_parse": fn_json_parse,
    "to_upper": fn_to_upper,
    "to_lower": fn_to_lower,
    "url_decode": fn_url_decode,
    "parse_int": fn_parse_int,
    "parse_float": fn_parse_float,
    "parse_boolean": fn_parse_boolean,
    "parse_string": fn_parse_string,
    "parse_char": fn_parse_char,
    "parse_epoch": fn_parse_epoch,
}

_MULTI_ARG = {
    "join": fn_join,
    "regex_replace": fn_regex_replace,
    "substring": fn_substring,
}


def call_function(name: str, args: List[List[QueryResult]]) -> List[Optional[PV]]:
    """FunctionName::call dispatch (eval_context.rs:1290-1310)."""
    if name == "now":
        return fn_now([])
    if name in _SINGLE_ARG:
        return _SINGLE_ARG[name](args[0])
    if name in _MULTI_ARG:
        return _MULTI_ARG[name](args)
    raise ParseError(f"No function with the name '{name}' exists.")
