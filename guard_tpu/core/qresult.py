"""Query results and the tri-state evaluation status.

Mirrors `/root/reference/guard/src/rules/mod.rs`:
`Status` (mod.rs:88-133), `QueryResult::{Literal,Resolved,UnResolved}`
(mod.rs:172-177) and `UnResolved{traversed_to, remaining_query, reason}`
(mod.rs:166-170). UnResolved values never abort evaluation — they FAIL
(or SKIP) the owning clause with a retained reason.
"""

from __future__ import annotations

from enum import Enum
from typing import Optional

from .values import PV


class Status(str, Enum):
    PASS = "PASS"
    FAIL = "FAIL"
    SKIP = "SKIP"

    def and_(self, other: "Status") -> "Status":
        """mod.rs:122-133."""
        if self == Status.FAIL:
            return Status.FAIL
        if self == Status.PASS:
            return Status.FAIL if other == Status.FAIL else Status.PASS
        return other


LITERAL = 0
RESOLVED = 1
UNRESOLVED = 2


class UnResolved:
    """mod.rs:166-170."""

    __slots__ = ("traversed_to", "remaining_query", "reason")

    def __init__(self, traversed_to: PV, remaining_query: str, reason: Optional[str]):
        self.traversed_to = traversed_to
        self.remaining_query = remaining_query
        self.reason = reason

    def __repr__(self):
        return (
            f"UnResolved(at={self.traversed_to.self_path().s!r}, "
            f"remaining={self.remaining_query!r})"
        )


class QueryResult:
    """Tagged union: Literal | Resolved (both carry a PV) | UnResolved."""

    __slots__ = ("tag", "value", "unresolved")

    def __init__(self, tag: int, value: Optional[PV] = None, unresolved: Optional[UnResolved] = None):
        self.tag = tag
        self.value = value
        self.unresolved = unresolved

    @staticmethod
    def literal(value: PV) -> "QueryResult":
        return QueryResult(LITERAL, value=value)

    @staticmethod
    def resolved(value: PV) -> "QueryResult":
        return QueryResult(RESOLVED, value=value)

    @staticmethod
    def unresolved_(ur: UnResolved) -> "QueryResult":
        return QueryResult(UNRESOLVED, unresolved=ur)

    def is_unresolved(self) -> bool:
        return self.tag == UNRESOLVED

    def resolved_value(self) -> Optional[PV]:
        """mod.rs:180-185 (resolved())."""
        return self.value if self.tag == RESOLVED else None

    def any_value(self) -> Optional[PV]:
        return self.value if self.tag != UNRESOLVED else None

    def __repr__(self):
        if self.tag == UNRESOLVED:
            return f"QR({self.unresolved!r})"
        return f"QR({'lit' if self.tag == LITERAL else 'res'}:{self.value!r})"
