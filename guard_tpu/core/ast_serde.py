"""Wire serialization of the Guard AST and documents for the native
C++ oracle (native/oracle.cpp).

The native statuses oracle is a from-scratch C++ port of the evaluation
core (evaluator.py / scopes.py / functions.py / values.py — themselves
ports of the reference's `eval.rs` / `eval_context.rs`). Python remains
the single owner of both grammars: the DSL parser and the YAML/JSON
loaders run here, and this module flattens their outputs — the
`RulesFile` AST and located `PV` document trees — into a compact JSON
the C++ side deserializes 1:1. That keeps the native engine free of any
parser beyond one small JSON reader, and guarantees both engines
evaluate the exact same trees.

Everything is JSON-serializable losslessly except integers outside
i64 — documents containing them raise `Unserializable`, and callers
fall back to the Python oracle (the same contract as the device
encoder's `num_exotic` flag).
"""

from __future__ import annotations

import json
import math
from typing import List, Optional

from .exprs import (
    AccessQuery,
    Block,
    BlockGuardClause,
    FileLocation,
    FunctionExpr,
    GuardAccessClause,
    GuardNamedRuleClause,
    LetExpr,
    ParameterizedNamedRuleClause,
    QAllIndices,
    QAllValues,
    QFilter,
    QIndex,
    QKey,
    QMapKeyFilter,
    QThis,
    Rule,
    RulesFile,
    TypeBlock,
    WhenBlockClause,
)
from .values import (
    BOOL,
    CHAR,
    FLOAT,
    INT,
    LIST,
    MAP,
    NULL,
    RANGE_CHAR,
    RANGE_FLOAT,
    RANGE_INT,
    REGEX,
    STRING,
    PV,
)

I64_MIN = -(2**63)
I64_MAX = 2**63 - 1


class Unserializable(Exception):
    """The value cannot be represented losslessly on the wire."""


def _loc(loc: FileLocation) -> dict:
    return {"line": loc.line, "col": loc.column, "file": loc.file_name}


def _num(v):
    if isinstance(v, int) and not (I64_MIN <= v <= I64_MAX):
        raise Unserializable(f"integer {v} outside i64")
    if isinstance(v, float) and (math.isnan(v) or math.isinf(v)):
        # JSON has no NaN/Inf; documents never contain them (loaders
        # produce finite floats), ranges neither (parser rejects)
        raise Unserializable(f"non-finite float {v}")
    return v


def pv_to_wire(pv: PV) -> dict:
    """Serialize a PV (with its path + location) to the wire dict."""
    k = pv.kind
    out: dict = {"k": k}
    p = pv.path
    if p.s or p.loc.line or p.loc.col:
        out["p"] = [p.s, p.loc.line, p.loc.col]
    if k == NULL:
        pass
    elif k in (STRING, REGEX, CHAR):
        out["s"] = pv.val
    elif k == BOOL:
        out["b"] = bool(pv.val)
    elif k == INT:
        out["i"] = _num(pv.val)
    elif k == FLOAT:
        out["f"] = _num(float(pv.val))
    elif k == LIST:
        out["items"] = [pv_to_wire(e) for e in pv.val]
    elif k == MAP:
        mv = pv.val
        out["entries"] = [
            [pv_to_wire(key_node), pv_to_wire(mv.values[key_node.val])]
            for key_node in mv.keys
        ]
    elif k in (RANGE_INT, RANGE_FLOAT, RANGE_CHAR):
        r = pv.val
        lo = r.lower if k == RANGE_CHAR else _num(r.lower)
        hi = r.upper if k == RANGE_CHAR else _num(r.upper)
        out["lo"] = lo
        out["hi"] = hi
        out["inc"] = r.inclusive
    else:
        raise Unserializable(f"unknown PV kind {k}")
    return out


def _let_value(lv) -> dict:
    if isinstance(lv, PV):
        return {"l": "pv", "pv": pv_to_wire(lv)}
    if isinstance(lv, AccessQuery):
        return {"l": "q", "q": _query(lv)}
    if isinstance(lv, FunctionExpr):
        return {
            "l": "fn",
            "name": lv.name,
            "params": [_let_value(p) for p in lv.parameters],
            "loc": _loc(lv.location),
        }
    raise Unserializable(f"unknown let value {lv!r}")


def _part(part) -> dict:
    if isinstance(part, QThis):
        return {"p": "this"}
    if isinstance(part, QKey):
        return {"p": "key", "name": part.name}
    if isinstance(part, QAllValues):
        return {"p": "all_values", "name": part.name}
    if isinstance(part, QAllIndices):
        return {"p": "all_indices", "name": part.name}
    if isinstance(part, QIndex):
        return {"p": "index", "i": part.index}
    if isinstance(part, QFilter):
        return {"p": "filter", "name": part.name, "conj": _conj(part.conjunctions)}
    if isinstance(part, QMapKeyFilter):
        c = part.clause
        return {
            "p": "keys",
            "name": part.name,
            "cmp": c.comparator.value,
            "inv": c.comparator_inverse,
            "cw": _let_value(c.compare_with),
        }
    raise Unserializable(f"unknown query part {part!r}")


def _query(q: AccessQuery) -> dict:
    return {"parts": [_part(p) for p in q.query], "match_all": q.match_all}


def _assignments(assignments: List[LetExpr]) -> list:
    return [{"var": a.var, "value": _let_value(a.value)} for a in assignments]


def _clause(c) -> dict:
    if isinstance(c, GuardAccessClause):
        ac = c.access_clause
        return {
            "t": "access",
            "query": _query(ac.query),
            "cmp": ac.comparator.value,
            "inv": ac.comparator_inverse,
            "neg": c.negation,
            "cw": None if ac.compare_with is None else _let_value(ac.compare_with),
            "msg": ac.custom_message,
            "loc": _loc(ac.location),
        }
    if isinstance(c, GuardNamedRuleClause):
        return {
            "t": "named",
            "rule": c.dependent_rule,
            "neg": c.negation,
            "msg": c.custom_message,
            "loc": _loc(c.location),
        }
    if isinstance(c, BlockGuardClause):
        return {
            "t": "block",
            "query": _query(c.query),
            "assignments": _assignments(c.block.assignments),
            "conj": _conj(c.block.conjunctions),
            "not_empty": c.not_empty,
            "loc": _loc(c.location),
        }
    if isinstance(c, WhenBlockClause):
        return {
            "t": "when",
            "conditions": _conj(c.conditions),
            "assignments": _assignments(c.block.assignments),
            "conj": _conj(c.block.conjunctions),
        }
    if isinstance(c, ParameterizedNamedRuleClause):
        return {
            "t": "call",
            "params": [_let_value(p) for p in c.parameters],
            "named": _clause(c.named_rule),
        }
    if isinstance(c, TypeBlock):
        return {
            "t": "type_block",
            "type_name": c.type_name,
            "query": [_part(p) for p in c.query],
            "conditions": None if c.conditions is None else _conj(c.conditions),
            "assignments": _assignments(c.block.assignments),
            "conj": _conj(c.block.conjunctions),
        }
    raise Unserializable(f"unknown clause {type(c).__name__}")


def _conj(conjunctions) -> list:
    return [[_clause(c) for c in disj] for disj in conjunctions]


def _rule(rule: Rule) -> dict:
    return {
        "name": rule.rule_name,
        "conditions": None if rule.conditions is None else _conj(rule.conditions),
        "assignments": _assignments(rule.block.assignments),
        "conj": _conj(rule.block.conjunctions),
    }


def rules_file_to_wire(rf: RulesFile) -> dict:
    return {
        "assignments": _assignments(rf.assignments),
        "rules": [_rule(r) for r in rf.guard_rules],
        "param_rules": [
            {"params": pr.parameter_names, "rule": _rule(pr.rule)}
            for pr in rf.parameterized_rules
        ],
    }


def rules_file_to_json(rf: RulesFile) -> str:
    return json.dumps(rules_file_to_wire(rf), ensure_ascii=False)


def doc_to_json(doc: PV) -> str:
    """Records-mode document wire: full paths + source locations (the
    record tree embeds them in reasons and report locations)."""
    return json.dumps(pv_to_wire(doc), ensure_ascii=False)


def pv_from_wire(d: dict) -> PV:
    """Inverse of pv_to_wire — rebuilds PVs emitted by the native
    engine's record tree."""
    from .values import Location, MapValue, Path, Range

    p = d.get("p")
    path = Path(p[0], Location(p[1], p[2])) if p else Path.root()
    k = d["k"]
    if k == NULL:
        return PV(path, k, None)
    if k in (STRING, REGEX, CHAR):
        return PV(path, k, d["s"])
    if k == BOOL:
        return PV(path, k, d["b"])
    if k == INT:
        return PV(path, k, d["i"])
    if k == FLOAT:
        return PV(path, k, float(d["f"]))
    if k == LIST:
        return PV(path, k, [pv_from_wire(e) for e in d["items"]])
    if k == MAP:
        mv = MapValue()
        for key_d, val_d in d["entries"]:
            key_pv = pv_from_wire(key_d)
            mv.keys.append(key_pv)
            mv.values[key_pv.val] = pv_from_wire(val_d)
        return PV(path, k, mv)
    if k in (RANGE_INT, RANGE_FLOAT, RANGE_CHAR):
        return PV(path, k, Range(d["lo"], d["hi"], d["inc"]))
    raise Unserializable(f"unknown wire kind {k}")


def records_from_wire(text: str):
    """Rebuild the EventRecord tree emitted by the native engine's
    records mode (native/oracle.cpp rec_json) so commands/report.py
    consumes it exactly as it consumes the Python evaluator's tree."""
    from .exprs import CmpOperator
    from .qresult import QueryResult, Status, UnResolved
    from .records import (
        BlockCheck,
        ClauseCheck,
        ComparisonClauseCheck,
        EventRecord,
        InComparisonCheck,
        MissingValueCheck,
        NamedStatus,
        RecordType,
        TypeBlockCheck,
        UnaryValueCheck,
        ValueCheck,
    )

    STATUS = {0: Status.PASS, 1: Status.FAIL, 2: Status.SKIP}

    def qr(d):
        t = d["t"]
        if t == "ur":
            return QueryResult.unresolved_(
                UnResolved(pv_from_wire(d["to"]), d["rem"], d["reason"])
            )
        pv = pv_from_wire(d["pv"])
        return QueryResult.literal(pv) if t == "lit" else QueryResult.resolved(pv)

    def cmp_of(p):
        return (CmpOperator(p["cmp"][0]), p["cmp"][1])

    def clause_check(p):
        cc = p["cc"]
        if cc == ClauseCheck.SUCCESS:
            return ClauseCheck.success()
        if cc == ClauseCheck.NO_VALUE_FOR_EMPTY:
            return ClauseCheck.no_value_for_empty(p["custom"])
        if cc == ClauseCheck.COMPARISON:
            return ClauseCheck.comparison(
                ComparisonClauseCheck(
                    comparison=cmp_of(p),
                    from_=qr(p["from"]),
                    to=None if p["to"] is None else qr(p["to"]),
                    status=STATUS[p["status"]],
                    message=p["msg"],
                    custom_message=p["custom"],
                )
            )
        if cc == ClauseCheck.IN_COMPARISON:
            return ClauseCheck.in_comparison(
                InComparisonCheck(
                    comparison=cmp_of(p),
                    from_=qr(p["from"]),
                    to=[qr(e) for e in p["to_list"]],
                    status=STATUS[p["status"]],
                    message=p["msg"],
                    custom_message=p["custom"],
                )
            )
        if cc == ClauseCheck.UNARY:
            return ClauseCheck.unary(
                UnaryValueCheck(
                    value=ValueCheck(
                        from_=qr(p["from"]),
                        status=STATUS[p["status"]],
                        message=p["msg"],
                        custom_message=p["custom"],
                    ),
                    comparison=cmp_of(p),
                )
            )
        if cc == ClauseCheck.DEPENDENT_RULE:
            return ClauseCheck.dependent_rule(
                MissingValueCheck(
                    rule=p["rule"],
                    status=STATUS[p["status"]],
                    message=p["msg"],
                    custom_message=p["custom"],
                )
            )
        if cc == ClauseCheck.MISSING_BLOCK_VALUE:
            return ClauseCheck.missing_block_value(
                ValueCheck(
                    from_=qr(p["from"]),
                    status=STATUS[p["status"]],
                    message=p["msg"],
                    custom_message=p["custom"],
                )
            )
        raise Unserializable(f"unknown clause check {cc}")

    def record(d) -> EventRecord:
        ev = EventRecord(context=d["c"])
        k = d["k"]
        if k is not None:
            p = d.get("p", {})
            if k in (RecordType.FILE_CHECK, RecordType.RULE_CHECK):
                payload = NamedStatus(
                    name=p["name"], status=STATUS[p["status"]], message=p["msg"]
                )
            elif k in (
                RecordType.RULE_CONDITION,
                RecordType.TYPE_CONDITION,
                RecordType.TYPE_BLOCK,
                RecordType.FILTER,
                RecordType.WHEN_CONDITION,
            ):
                payload = STATUS[p["status"]]
            elif k == RecordType.TYPE_CHECK:
                payload = TypeBlockCheck(
                    type_name=p["type_name"],
                    block=BlockCheck(
                        at_least_one_matches=p["alo"],
                        status=STATUS[p["status"]],
                        message=p["msg"],
                    ),
                )
            elif k in (
                RecordType.WHEN_CHECK,
                RecordType.DISJUNCTION,
                RecordType.BLOCK_GUARD_CHECK,
                RecordType.GUARD_CLAUSE_BLOCK_CHECK,
            ):
                payload = BlockCheck(
                    at_least_one_matches=p["alo"],
                    status=STATUS[p["status"]],
                    message=p["msg"],
                )
            elif k == RecordType.CLAUSE_VALUE_CHECK:
                payload = clause_check(p)
            else:
                raise Unserializable(f"unknown record kind {k}")
            ev.container = RecordType(k, payload)
        ev.children = [record(ch) for ch in d["ch"]]
        return ev

    return record(json.loads(text))


def _pv_to_compact(pv: PV, locs: bool):
    k = pv.kind
    if k == NULL:
        head = (0,)
    elif k in (STRING, REGEX, CHAR):
        head = (k, pv.val)
    elif k == BOOL:
        head = (3, bool(pv.val))
    elif k == INT:
        head = (4, _num(pv.val))
    elif k == FLOAT:
        head = (5, _num(float(pv.val)))
    elif k == LIST:
        head = (7, [_pv_to_compact(e, locs) for e in pv.val])
    elif k == MAP:
        mv = pv.val
        if locs:
            head = (
                8,
                [
                    [
                        kn.val,
                        kn.path.loc.line,
                        kn.path.loc.col,
                        _pv_to_compact(mv.values[kn.val], locs),
                    ]
                    for kn in mv.keys
                ],
            )
        else:
            head = (
                8,
                [[kn.val, _pv_to_compact(mv.values[kn.val], locs)] for kn in mv.keys],
            )
    else:
        raise Unserializable(f"kind {k} cannot appear in a document")
    if locs:
        loc = pv.path.loc
        return head + (loc.line, loc.col)
    return head


def doc_to_compact(doc: PV, locs: bool = False) -> str:
    """Document wire: positional [kind, payload] arrays, about 3x
    leaner than the rich wire and parsed by a dedicated direct scanner
    in C++. Statuses mode omits paths/locations entirely (C++ derives
    paths); records mode (`locs=True`) appends per-node and per-key
    line/col trailers so report locations match the loader's."""
    return json.dumps(_pv_to_compact(doc, locs), ensure_ascii=False)
