"""Wire serialization of the Guard AST and documents for the native
C++ oracle (native/oracle.cpp).

The native statuses oracle is a from-scratch C++ port of the evaluation
core (evaluator.py / scopes.py / functions.py / values.py — themselves
ports of the reference's `eval.rs` / `eval_context.rs`). Python remains
the single owner of both grammars: the DSL parser and the YAML/JSON
loaders run here, and this module flattens their outputs — the
`RulesFile` AST and located `PV` document trees — into a compact JSON
the C++ side deserializes 1:1. That keeps the native engine free of any
parser beyond one small JSON reader, and guarantees both engines
evaluate the exact same trees.

Everything is JSON-serializable losslessly except integers outside
i64 — documents containing them raise `Unserializable`, and callers
fall back to the Python oracle (the same contract as the device
encoder's `num_exotic` flag).
"""

from __future__ import annotations

import json
import math
from typing import List, Optional

from .exprs import (
    AccessQuery,
    Block,
    BlockGuardClause,
    FileLocation,
    FunctionExpr,
    GuardAccessClause,
    GuardNamedRuleClause,
    LetExpr,
    ParameterizedNamedRuleClause,
    QAllIndices,
    QAllValues,
    QFilter,
    QIndex,
    QKey,
    QMapKeyFilter,
    QThis,
    Rule,
    RulesFile,
    TypeBlock,
    WhenBlockClause,
)
from .values import (
    BOOL,
    CHAR,
    FLOAT,
    INT,
    LIST,
    MAP,
    NULL,
    RANGE_CHAR,
    RANGE_FLOAT,
    RANGE_INT,
    REGEX,
    STRING,
    PV,
)

I64_MIN = -(2**63)
I64_MAX = 2**63 - 1


class Unserializable(Exception):
    """The value cannot be represented losslessly on the wire."""


def _loc(loc: FileLocation) -> dict:
    return {"line": loc.line, "col": loc.column, "file": loc.file_name}


def _num(v):
    if isinstance(v, int) and not (I64_MIN <= v <= I64_MAX):
        raise Unserializable(f"integer {v} outside i64")
    if isinstance(v, float) and (math.isnan(v) or math.isinf(v)):
        # JSON has no NaN/Inf; documents never contain them (loaders
        # produce finite floats), ranges neither (parser rejects)
        raise Unserializable(f"non-finite float {v}")
    return v


def pv_to_wire(pv: PV) -> dict:
    """Serialize a PV (with its path + location) to the wire dict."""
    k = pv.kind
    out: dict = {"k": k}
    p = pv.path
    if p.s or p.loc.line or p.loc.col:
        out["p"] = [p.s, p.loc.line, p.loc.col]
    if k == NULL:
        pass
    elif k in (STRING, REGEX, CHAR):
        out["s"] = pv.val
    elif k == BOOL:
        out["b"] = bool(pv.val)
    elif k == INT:
        out["i"] = _num(pv.val)
    elif k == FLOAT:
        out["f"] = _num(float(pv.val))
    elif k == LIST:
        out["items"] = [pv_to_wire(e) for e in pv.val]
    elif k == MAP:
        mv = pv.val
        out["entries"] = [
            [pv_to_wire(key_node), pv_to_wire(mv.values[key_node.val])]
            for key_node in mv.keys
        ]
    elif k in (RANGE_INT, RANGE_FLOAT, RANGE_CHAR):
        r = pv.val
        lo = r.lower if k == RANGE_CHAR else _num(r.lower)
        hi = r.upper if k == RANGE_CHAR else _num(r.upper)
        out["lo"] = lo
        out["hi"] = hi
        out["inc"] = r.inclusive
    else:
        raise Unserializable(f"unknown PV kind {k}")
    return out


def _let_value(lv) -> dict:
    if isinstance(lv, PV):
        return {"l": "pv", "pv": pv_to_wire(lv)}
    if isinstance(lv, AccessQuery):
        return {"l": "q", "q": _query(lv)}
    if isinstance(lv, FunctionExpr):
        return {
            "l": "fn",
            "name": lv.name,
            "params": [_let_value(p) for p in lv.parameters],
            "loc": _loc(lv.location),
        }
    raise Unserializable(f"unknown let value {lv!r}")


def _part(part) -> dict:
    if isinstance(part, QThis):
        return {"p": "this"}
    if isinstance(part, QKey):
        return {"p": "key", "name": part.name}
    if isinstance(part, QAllValues):
        return {"p": "all_values", "name": part.name}
    if isinstance(part, QAllIndices):
        return {"p": "all_indices", "name": part.name}
    if isinstance(part, QIndex):
        return {"p": "index", "i": part.index}
    if isinstance(part, QFilter):
        return {"p": "filter", "name": part.name, "conj": _conj(part.conjunctions)}
    if isinstance(part, QMapKeyFilter):
        c = part.clause
        return {
            "p": "keys",
            "name": part.name,
            "cmp": c.comparator.value,
            "inv": c.comparator_inverse,
            "cw": _let_value(c.compare_with),
        }
    raise Unserializable(f"unknown query part {part!r}")


def _query(q: AccessQuery) -> dict:
    return {"parts": [_part(p) for p in q.query], "match_all": q.match_all}


def _assignments(assignments: List[LetExpr]) -> list:
    return [{"var": a.var, "value": _let_value(a.value)} for a in assignments]


def _clause(c) -> dict:
    if isinstance(c, GuardAccessClause):
        ac = c.access_clause
        return {
            "t": "access",
            "query": _query(ac.query),
            "cmp": ac.comparator.value,
            "inv": ac.comparator_inverse,
            "neg": c.negation,
            "cw": None if ac.compare_with is None else _let_value(ac.compare_with),
            "msg": ac.custom_message,
            "loc": _loc(ac.location),
        }
    if isinstance(c, GuardNamedRuleClause):
        return {
            "t": "named",
            "rule": c.dependent_rule,
            "neg": c.negation,
            "msg": c.custom_message,
            "loc": _loc(c.location),
        }
    if isinstance(c, BlockGuardClause):
        return {
            "t": "block",
            "query": _query(c.query),
            "assignments": _assignments(c.block.assignments),
            "conj": _conj(c.block.conjunctions),
            "not_empty": c.not_empty,
            "loc": _loc(c.location),
        }
    if isinstance(c, WhenBlockClause):
        return {
            "t": "when",
            "conditions": _conj(c.conditions),
            "assignments": _assignments(c.block.assignments),
            "conj": _conj(c.block.conjunctions),
        }
    if isinstance(c, ParameterizedNamedRuleClause):
        return {
            "t": "call",
            "params": [_let_value(p) for p in c.parameters],
            "named": _clause(c.named_rule),
        }
    if isinstance(c, TypeBlock):
        return {
            "t": "type_block",
            "type_name": c.type_name,
            "query": [_part(p) for p in c.query],
            "conditions": None if c.conditions is None else _conj(c.conditions),
            "assignments": _assignments(c.block.assignments),
            "conj": _conj(c.block.conjunctions),
        }
    raise Unserializable(f"unknown clause {type(c).__name__}")


def _conj(conjunctions) -> list:
    return [[_clause(c) for c in disj] for disj in conjunctions]


def _rule(rule: Rule) -> dict:
    return {
        "name": rule.rule_name,
        "conditions": None if rule.conditions is None else _conj(rule.conditions),
        "assignments": _assignments(rule.block.assignments),
        "conj": _conj(rule.block.conjunctions),
    }


def rules_file_to_wire(rf: RulesFile) -> dict:
    return {
        "assignments": _assignments(rf.assignments),
        "rules": [_rule(r) for r in rf.guard_rules],
        "param_rules": [
            {"params": pr.parameter_names, "rule": _rule(pr.rule)}
            for pr in rf.parameterized_rules
        ],
    }


def rules_file_to_json(rf: RulesFile) -> str:
    return json.dumps(rules_file_to_wire(rf), ensure_ascii=False)


def _pv_to_compact(pv: PV):
    k = pv.kind
    if k == NULL:
        return (0,)
    if k in (STRING, REGEX, CHAR):
        return (k, pv.val)
    if k == BOOL:
        return (3, bool(pv.val))
    if k == INT:
        return (4, _num(pv.val))
    if k == FLOAT:
        return (5, _num(float(pv.val)))
    if k == LIST:
        return (7, [_pv_to_compact(e) for e in pv.val])
    if k == MAP:
        mv = pv.val
        return (8, [[kn.val, _pv_to_compact(mv.values[kn.val])] for kn in mv.keys])
    raise Unserializable(f"kind {k} cannot appear in a document")


def doc_to_compact(doc: PV) -> str:
    """Status-mode document wire: positional [kind, payload] arrays, no
    paths/locations (statuses never read them) — about 3x leaner than
    the rich wire and parsed by a dedicated direct scanner in C++."""
    return json.dumps(_pv_to_compact(doc), ensure_ascii=False)
