"""Traversal index: path-string -> node lookup for reporters.

Equivalent of `/root/reference/guard/src/rules/path_value/traversal.rs:
12-45`: builds an index from a document tree so reporters can map
`"/Resources/x/..."` path strings back to nodes (and their source
locations); supports relative `N#` / `N/...` paths.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .values import LIST, MAP, PV


class Node:
    __slots__ = ("parent", "value")

    def __init__(self, parent: Optional[str], value: PV):
        self.parent = parent
        self.value = value


class Traversal:
    def __init__(self, root: PV):
        self.nodes: Dict[str, Node] = {}
        self._root_path = root.self_path().s
        self._build(root, None)

    def _build(self, pv: PV, parent: Optional[str]) -> None:
        path = pv.self_path().s
        self.nodes[path] = Node(parent, pv)
        if pv.kind == MAP:
            for key, value in pv.val.values.items():
                self._build(value, path)
        elif pv.kind == LIST:
            for item in pv.val:
                self._build(item, path)

    def root(self) -> Optional[Node]:
        return self.nodes.get(self._root_path)

    def at(self, path: str, node: Optional[Node] = None):
        """Resolve an absolute path, or a relative path of the form
        `N#` (climb N levels) or `N/suffix` (climb then descend)
        (traversal.rs:47-100). Returns the Node or None (abort)."""
        if path in self.nodes:
            return self.nodes[path]
        # relative: <digits>'#' or <digits>'/rest'
        i = 0
        while i < len(path) and path[i].isdigit():
            i += 1
        if i == 0 or node is None:
            return None
        levels = int(path[:i])
        current: Optional[Node] = node
        for _ in range(levels):
            if current is None or current.parent is None:
                return None
            current = self.nodes.get(current.parent)
        if current is None:
            return None
        rest = path[i:]
        if rest == "#" or rest == "":
            return current
        if rest.startswith("/"):
            target = current.value.self_path().s + rest
            return self.nodes.get(target)
        return None
