"""Path-aware value model: every node knows its JSON-pointer-ish path and
source location.

This is the working representation of both documents and DSL literal
values, equivalent to the reference's `PathAwareValue`
(`/root/reference/guard/src/rules/path_value.rs:172-185`) and `Value`
(`/root/reference/guard/src/rules/values.rs:82-95`), redesigned as a
single tagged node class (cheap dispatch, and trivially flattenable into
the columnar arrays the TPU backend consumes — see guard_tpu/ops/encoder.py).

Comparison semantics mirror `path_value.rs:1047-1196`:
  * ordering is only defined between same-kind scalars (int/int,
    float/float, string/string, char/char, null/null) — int vs float is
    deliberately NOT coerced, matching `compare_values`
    (path_value.rs:1048-1070);
  * equality additionally understands string<->regex matching, ranges and
    deep list/map equality (`compare_eq`, path_value.rs:1071-1146).
"""

from __future__ import annotations

import re
from functools import lru_cache
from typing import Dict, List, Optional, Tuple

from .errors import IncompatibleError, MultipleValuesError, NotComparableError

# ---------------------------------------------------------------------------
# Kinds (stable small ints: these double as the node-type column in the
# TPU columnar encoding, guard_tpu/ops/encoder.py)
# ---------------------------------------------------------------------------
NULL = 0
STRING = 1
REGEX = 2
BOOL = 3
INT = 4
FLOAT = 5
CHAR = 6
LIST = 7
MAP = 8
RANGE_INT = 9
RANGE_FLOAT = 10
RANGE_CHAR = 11

_KIND_NAMES = {
    NULL: "null",
    STRING: "String",
    REGEX: "Regex",
    BOOL: "bool",
    INT: "int",
    FLOAT: "float",
    CHAR: "char",
    LIST: "array",
    MAP: "map",
    RANGE_INT: "range(int, int)",
    RANGE_FLOAT: "range(float, float)",
    RANGE_CHAR: "range(char, char)",
}

LOWER_INCLUSIVE = 0x01  # values.rs:239
UPPER_INCLUSIVE = 0x02  # values.rs:240


class Location:
    """Line/col of a node in its source file (path_value.rs:30-40)."""

    __slots__ = ("line", "col")

    def __init__(self, line: int = 0, col: int = 0):
        self.line = line
        self.col = col

    def __repr__(self):
        return f"L:{self.line},C:{self.col}"

    def __eq__(self, other):
        return (
            isinstance(other, Location)
            and self.line == other.line
            and self.col == other.col
        )

    def __hash__(self):
        return hash((self.line, self.col))


_ROOT_LOC = Location(0, 0)


class Path:
    """Slash-separated pointer from the document root (path_value.rs:48-49)."""

    __slots__ = ("s", "loc")

    def __init__(self, s: str = "", loc: Optional[Location] = None):
        self.s = s
        self.loc = loc if loc is not None else _ROOT_LOC

    @staticmethod
    def root() -> "Path":
        return Path("", _ROOT_LOC)

    def disp(self) -> str:
        """Path Display (path_value.rs:62-66): "{path}[L:{l},C:{c}]" —
        the form the reference embeds in unresolved reasons/messages."""
        return f"{self.s}[L:{self.loc.line},C:{self.loc.col}]"

    def extend(self, part: str, loc: Optional[Location] = None) -> "Path":
        return Path(self.s + "/" + part, loc if loc is not None else self.loc)

    def relative(self) -> str:
        """Last path component (path_value.rs:73-78)."""
        pos = self.s.rfind("/")
        return self.s[pos + 1 :] if pos >= 0 else self.s

    def __repr__(self):
        return f"Path({self.s!r})"

    def __eq__(self, other):
        return isinstance(other, Path) and self.s == other.s

    def __hash__(self):
        return hash(self.s)


class Range:
    """Numeric/char range literal, e.g. r[10, 20) (values.rs:232-240)."""

    __slots__ = ("lower", "upper", "inclusive")

    def __init__(self, lower, upper, inclusive: int):
        self.lower = lower
        self.upper = upper
        self.inclusive = inclusive

    def contains(self, v) -> bool:
        """values.rs:266-278 (is_within)."""
        lo_ok = (
            self.lower <= v if (self.inclusive & LOWER_INCLUSIVE) else self.lower < v
        )
        hi_ok = (
            self.upper >= v if (self.inclusive & UPPER_INCLUSIVE) else self.upper > v
        )
        return lo_ok and hi_ok

    def __repr__(self):
        o = "[" if self.inclusive & LOWER_INCLUSIVE else "("
        c = "]" if self.inclusive & UPPER_INCLUSIVE else ")"
        return f"r{o}{self.lower},{self.upper}{c}"

    def __eq__(self, other):
        return (
            isinstance(other, Range)
            and self.lower == other.lower
            and self.upper == other.upper
            and self.inclusive == other.inclusive
        )


class MapValue:
    """Ordered map that keeps the *key nodes* as well as the values so
    `keys ==` filters and key-capture projections can see key source
    locations (path_value.rs:139-142)."""

    __slots__ = ("keys", "values")

    def __init__(self, keys: Optional[List["PV"]] = None, values: Optional[Dict[str, "PV"]] = None):
        self.keys: List[PV] = keys if keys is not None else []
        self.values: Dict[str, PV] = values if values is not None else {}

    def is_empty(self) -> bool:
        return not self.values

    def __eq__(self, other):
        # MapValue PartialEq compares only values (path_value.rs:157-161)
        if not isinstance(other, MapValue):
            return NotImplemented
        if len(self.values) != len(other.values):
            return False
        for k, v in self.values.items():
            if k not in other.values or not loose_eq(v, other.values[k]):
                return False
        return True


class PV:
    """A path-aware value node (path_value.rs:172-185).

    `kind` is one of the module-level kind constants; `val` holds:
      NULL -> None; STRING/REGEX/CHAR -> str; BOOL -> bool; INT -> int;
      FLOAT -> float; LIST -> list[PV]; MAP -> MapValue;
      RANGE_* -> Range.
    """

    __slots__ = ("path", "kind", "val")

    def __init__(self, path: Path, kind: int, val):
        self.path = path
        self.kind = kind
        self.val = val

    # -- constructors -------------------------------------------------
    @staticmethod
    def null(path: Path) -> "PV":
        return PV(path, NULL, None)

    @staticmethod
    def string(path: Path, s: str) -> "PV":
        return PV(path, STRING, s)

    @staticmethod
    def regex(path: Path, s: str) -> "PV":
        return PV(path, REGEX, s)

    @staticmethod
    def boolean(path: Path, b: bool) -> "PV":
        return PV(path, BOOL, b)

    @staticmethod
    def int_(path: Path, i: int) -> "PV":
        return PV(path, INT, i)

    @staticmethod
    def float_(path: Path, f: float) -> "PV":
        return PV(path, FLOAT, f)

    @staticmethod
    def char(path: Path, c: str) -> "PV":
        return PV(path, CHAR, c)

    @staticmethod
    def list_(path: Path, items: List["PV"]) -> "PV":
        return PV(path, LIST, items)

    @staticmethod
    def map_(path: Path, mv: MapValue) -> "PV":
        return PV(path, MAP, mv)

    # -- shape predicates (path_value.rs:921-963) ---------------------
    def is_list(self) -> bool:
        return self.kind == LIST

    def is_map(self) -> bool:
        return self.kind == MAP

    def is_null(self) -> bool:
        return self.kind == NULL

    def is_scalar(self) -> bool:
        return self.kind != LIST and self.kind != MAP

    def type_info(self) -> str:
        return _KIND_NAMES[self.kind]

    def self_path(self) -> Path:
        return self.path

    # -- merge for --input-params docs (path_value.rs:889-919) --------
    def merge(self, other: "PV") -> "PV":
        if self.kind == LIST and other.kind == LIST:
            self.val.extend(other.val)
            return self
        if self.kind == MAP and other.kind == MAP:
            mv: MapValue = self.val
            omv: MapValue = other.val
            for key, value in omv.values.items():
                if key in mv.values:
                    raise MultipleValuesError(f"Key {key}, already exists in map")
                mv.values[key] = value
                mv.keys.append(PV.string(other.path.extend(key), key))
            return self
        raise IncompatibleError(
            f"Types are not compatible for merges {self.type_info()}, {other.type_info()}"
        )

    # -- python protocol ----------------------------------------------
    def __eq__(self, other):
        if not isinstance(other, PV):
            return NotImplemented
        return loose_eq(self, other)

    def __hash__(self):
        # structural hash ignoring path (values.rs:97-153)
        k = self.kind
        if k in (STRING, REGEX, CHAR):
            return hash(self.val)
        if k == NULL:
            return hash("NULL")
        if k in (INT, BOOL):
            return hash(self.val)
        if k == FLOAT:
            return hash(int(self.val))
        if k == LIST:
            return hash(tuple(hash(e) for e in self.val))
        if k == MAP:
            return hash(tuple((kk, hash(vv)) for kk, vv in self.val.values.items()))
        r: Range = self.val
        return hash((r.lower, r.upper, r.inclusive))

    def __repr__(self):
        return f"PV({_KIND_NAMES[self.kind]}@{self.path.s!r}={self.val!r})"

    # -- plain-python projection (for reporters / JSON output) --------
    def to_plain(self):
        k = self.kind
        if k == NULL:
            return None
        if k == LIST:
            return [e.to_plain() for e in self.val]
        if k == MAP:
            return {kk: vv.to_plain() for kk, vv in self.val.values.items()}
        if k == REGEX:
            return f"/{self.val}/"
        if k in (RANGE_INT, RANGE_FLOAT, RANGE_CHAR):
            return repr(self.val)
        return self.val


# ---------------------------------------------------------------------------
# Regex compilation cache. The reference uses fancy-regex (lookaround +
# backreference support); Python `re` covers the same feature class.
# ---------------------------------------------------------------------------
_GLOBAL_FLAGS_RE = re.compile(r"\(\?([aiLmsux]+)\)")


@lru_cache(maxsize=4096)
def compiled_regex(pattern: str):
    try:
        try:
            return re.compile(pattern)
        except re.error:
            # Rust regex crates allow inline global flags anywhere in
            # the pattern (e.g. `^(?i)name$`); Python requires them at
            # the start. Hoist them to the front and retry.
            flags = "".join(
                sorted(set("".join(_GLOBAL_FLAGS_RE.findall(pattern))))
            )
            if not flags:
                raise
            stripped = _GLOBAL_FLAGS_RE.sub("", pattern)
            return re.compile(f"(?{flags})" + stripped)
    except OverflowError as e:
        # CPython raises OverflowError (not re.error) for repetition
        # counts beyond its limit (x{9999999999}); normalize so every
        # caller's re.error handling applies — the reference rejects
        # such patterns at parse time (parser.rs:273-277)
        raise re.error(f"invalid regex {pattern!r}: {e}")


def regex_matches(pattern: str, s: str) -> bool:
    """Unanchored match, like fancy_regex::Regex::is_match."""
    return compiled_regex(pattern).search(s) is not None


# ---------------------------------------------------------------------------
# Comparisons (path_value.rs:1047-1196)
# ---------------------------------------------------------------------------
_ORDERED_KINDS = {NULL, INT, STRING, FLOAT, CHAR}


def compare_values(first: PV, other: PV) -> int:
    """Total order only between same-kind scalars (path_value.rs:1048-1070)."""
    if first.kind == other.kind and first.kind in _ORDERED_KINDS:
        if first.kind == NULL:
            return 0
        a, b = first.val, other.val
        if a < b:
            return -1
        if a > b:
            return 1
        return 0
    raise NotComparableError(
        f"PathAwareValues are not comparable {first.type_info()}, {other.type_info()}"
    )


def compare_eq(first: PV, second: PV) -> bool:
    """Equality incl. regex matching / ranges (path_value.rs:1071-1146)."""
    fk, sk = first.kind, second.kind
    if fk == STRING and sk == REGEX:
        return regex_matches(second.val, first.val)
    if fk == REGEX and sk == STRING:
        return regex_matches(first.val, second.val)
    if fk == STRING and sk == STRING:
        return first.val == second.val
    if fk == MAP and sk == MAP:
        m1: MapValue = first.val
        m2: MapValue = second.val
        if len(m1.values) != len(m2.values):
            return False
        for key, value in m1.values.items():
            v2 = m2.values.get(key)
            if v2 is None or not compare_eq(value, v2):
                return False
        return True
    if fk == LIST and sk == LIST:
        if len(first.val) != len(second.val):
            return False
        return all(compare_eq(a, b) for a, b in zip(first.val, second.val))
    if fk == BOOL and sk == BOOL:
        return first.val == second.val
    if fk == REGEX and sk == REGEX:
        return first.val == second.val
    if fk == INT and sk == RANGE_INT:
        return second.val.contains(first.val)
    if fk == FLOAT and sk == RANGE_FLOAT:
        return second.val.contains(first.val)
    if fk == CHAR and sk == RANGE_CHAR:
        return second.val.contains(first.val)
    return compare_values(first, second) == 0


def loose_eq(first: PV, second: PV) -> bool:
    """PartialEq semantics: like compare_eq but never raises
    (path_value.rs:245-291); used by IN-containment checks."""
    fk, sk = first.kind, second.kind
    if fk == MAP and sk == MAP:
        return first.val == second.val  # MapValue.__eq__ (loose)
    if fk == LIST and sk == LIST:
        if len(first.val) != len(second.val):
            return False
        return all(loose_eq(a, b) for a, b in zip(first.val, second.val))
    if (fk == STRING and sk == REGEX) or (fk == REGEX and sk == STRING):
        pattern = second.val if sk == REGEX else first.val
        s = first.val if fk == STRING else second.val
        try:
            return regex_matches(pattern, s)
        except re.error:
            return False
    try:
        return compare_eq(first, second)
    except NotComparableError:
        return False


def _ord_cmp(op):
    def cmp(first: PV, other: PV) -> bool:
        return op(compare_values(first, other))

    return cmp


compare_lt = _ord_cmp(lambda o: o < 0)
compare_le = _ord_cmp(lambda o: o <= 0)
compare_gt = _ord_cmp(lambda o: o > 0)
compare_ge = _ord_cmp(lambda o: o >= 0)


# ---------------------------------------------------------------------------
# Conversion from plain python data (JSON payloads, test specs) — the
# equivalent of TryFrom<serde_json::Value> (path_value.rs:313-357).
# ---------------------------------------------------------------------------
def from_plain(value, path: Optional[Path] = None) -> PV:
    path = path if path is not None else Path.root()
    if value is None:
        return PV.null(path)
    if value is True or value is False:
        return PV.boolean(path, value)
    if isinstance(value, int):
        return PV.int_(path, value)
    if isinstance(value, float):
        return PV.float_(path, value)
    if isinstance(value, str):
        return PV.string(path, value)
    if isinstance(value, list):
        return PV.list_(
            path, [from_plain(v, path.extend(str(i))) for i, v in enumerate(value)]
        )
    if isinstance(value, dict):
        mv = MapValue()
        for k, v in value.items():
            ks = str(k)
            kp = path.extend(ks)
            mv.keys.append(PV.string(kp, ks))
            mv.values[ks] = from_plain(v, kp)
        return PV.map_(path, mv)
    raise IncompatibleError(f"Cannot convert {type(value)} to a path-aware value")


def _rust_num(v) -> str:
    """Rust {} Display for numbers: integral floats print bare."""
    import math

    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, int):
        return str(v)
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "inf" if v > 0 else "-inf"
    if float(v) == int(v) and abs(v) < 1e16:
        return str(int(v))
    return repr(float(v))


def plain_value_display(v) -> str:
    """ValueOnlyDisplay over a plain-python projection (reports store
    to_plain() values); same rendering rules as value_only_display."""
    if v is None:
        return '"NULL"'
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, str):
        return f'"{v}"'
    if isinstance(v, (int, float)):
        return _rust_num(v)
    if isinstance(v, list):
        return "[" + ",".join(plain_value_display(e) for e in v) + "]"
    if isinstance(v, dict):
        return "{" + ",".join(
            f'"{k}":{plain_value_display(val)}' for k, val in v.items()
        ) + "}"
    return str(v)


def value_only_display(pv: "PV") -> str:
    """ValueOnlyDisplay (display.rs:42-99): the reference's value
    rendering used in clause-display contexts and console reporters —
    double-quoted strings, "/re/" regexes, "NULL", compact containers."""
    k = pv.kind
    if k == NULL:
        return '"NULL"'
    if k == STRING:
        return f'"{pv.val}"'
    if k == REGEX:
        return f'"/{pv.val}/"'
    if k == CHAR:
        return f"'{pv.val}'"
    if k == BOOL:
        return "true" if pv.val else "false"
    if k in (INT, FLOAT):
        return _rust_num(pv.val)
    if k == LIST:
        return "[" + ",".join(value_only_display(e) for e in pv.val) + "]"
    if k == MAP:
        return "{" + ",".join(
            f'"{kk}":{value_only_display(vv)}' for kk, vv in pv.val.values.items()
        ) + "}"
    r = pv.val  # ranges (display.rs write_range); char bounds print bare
    lo = "[" if r.inclusive & LOWER_INCLUSIVE else "("
    hi = "]" if r.inclusive & UPPER_INCLUSIVE else ")"

    def bound(b):
        return b if isinstance(b, str) else _rust_num(b)

    return f"{lo}{bound(r.lower)},{bound(r.upper)}{hi}"


def rust_debug_pv(pv: "PV") -> str:
    """Rust derive(Debug) rendering of a PathAwareValue, embedded in one
    unresolved reason (eval_context.rs:580-581 uses {:?} of the value)."""
    p = pv.path
    path = f'Path("{p.s}", Location {{ line: {p.loc.line}, col: {p.loc.col} }})'
    k = pv.kind
    if k == STRING:
        return f'String(({path}, "{pv.val}"))'
    if k == REGEX:
        return f'Regex(({path}, "{pv.val}"))'
    if k == CHAR:
        return f"Char(({path}, '{pv.val}'))"
    if k == BOOL:
        return f"Bool(({path}, {'true' if pv.val else 'false'}))"
    if k == INT:
        return f"Int(({path}, {pv.val}))"
    if k == FLOAT:
        fv = float(pv.val)
        if fv != fv or fv in (float("inf"), float("-inf")):
            # Rust {:?} renders non-finite f64 as NaN / inf / -inf
            s = "NaN" if fv != fv else ("inf" if fv > 0 else "-inf")
            return f"Float(({path}, {s}))"
        if fv == int(fv):
            return f"Float(({path}, {_rust_num(pv.val)}.0))"
        return f"Float(({path}, {pv.val}))"
    if k == NULL:
        return f"Null({path})"
    if k == LIST:
        inner = ", ".join(rust_debug_pv(e) for e in pv.val)
        return f"List(({path}, [{inner}]))"
    if k == MAP:
        entries = ", ".join(
            f'"{kk}": {rust_debug_pv(vv)}' for kk, vv in pv.val.values.items()
        )
        return f"Map(({path}, MapValue {{ values: {{{entries}}} }}))"
    return repr(pv)
