"""Guard DSL parser.

Hand-written recursive-descent parser that mirrors, production for
production, the nom combinator grammar of the reference
(`/root/reference/guard/src/rules/parser.rs`): scalar/range/regex/list/map
literals (parser.rs:167-425), access queries with filters and projections
(parser.rs:718-951), clauses with CNF or-joins (parser.rs:1180-1412),
blocks / named rules / parameterized rules / type blocks
(parser.rs:1510-1790) and the top-level rules-file assembly with the
synthesized `default` rule (parser.rs:1840-1932).

Backtracking model: `Backtrack` is nom's recoverable `Err::Error` (alt
tries the next branch); `Fatal` is `Err::Failure` (a `cut` — no
backtracking, surfaces as a ParseError to the caller).
"""

from __future__ import annotations

import bisect
import re
from typing import Callable, List, Optional, Tuple

from .errors import ParseError
from .exprs import (
    AccessClause,
    AccessQuery,
    Block,
    BlockGuardClause,
    CmpOperator,
    Conjunctions,
    FileLocation,
    FunctionExpr,
    GuardAccessClause,
    GuardNamedRuleClause,
    LetExpr,
    MapKeyFilterClause,
    ParameterizedNamedRuleClause,
    ParameterizedRule,
    QAllIndices,
    QAllValues,
    QFilter,
    QIndex,
    QKey,
    QMapKeyFilter,
    QThis,
    Rule,
    RulesFile,
    TypeBlock,
    WhenBlockClause,
    part_is_variable,
)
from .functions import FUNCTION_ARITY
from .values import (
    LOWER_INCLUSIVE,
    RANGE_CHAR,
    RANGE_FLOAT,
    RANGE_INT,
    UPPER_INCLUSIVE,
    MapValue,
    Path,
    PV,
    Range,
    compiled_regex,
)

DEFAULT_RULE_NAME = "default"  # parser.rs:33


class Backtrack(Exception):
    """Recoverable parse error (nom Err::Error)."""

    def __init__(self, pos: int, context: str = ""):
        self.pos = pos
        self.context = context
        super().__init__(context)


class Fatal(Exception):
    """Unrecoverable parse error (nom Err::Failure / cut)."""

    def __init__(self, pos: int, context: str = ""):
        self.pos = pos
        self.context = context
        super().__init__(context)


_VAR_NAME_RE = re.compile(r"[A-Za-z][A-Za-z0-9_]*")
_KEY_CHARS_RE = re.compile(r"[A-Za-z0-9_-]+")
_INT_RE = re.compile(r"[0-9]+")
# the GATE mirrors parser.rs:230-243: fraction, or exponent WITH a
# sign; on success the reference re-parses with nom's `double`, whose
# grammar also takes an UNSIGNED exponent — so `1.5e3` is a float but
# `2e3` is not (gate fails: no fraction, no signed exponent)
_FLOAT_BODY_RE = re.compile(r"[0-9]+(\.[0-9]+)?([eE][+-][0-9]+)?")
_FLOAT_DOUBLE_RE = re.compile(r"[0-9]+(\.[0-9]+)?([eE][+-]?[0-9]+)?")


class Parser:
    def __init__(self, text: str, file_name: str = ""):
        self.text = text
        self.n = len(text)
        self.pos = 0
        self.file_name = file_name
        # line-start offsets for location computation
        self._line_starts = [0]
        for m in re.finditer("\n", text):
            self._line_starts.append(m.end())

    # ------------------------------------------------------------------
    # primitives
    # ------------------------------------------------------------------
    def loc(self, pos: Optional[int] = None) -> FileLocation:
        p = self.pos if pos is None else pos
        line_idx = bisect.bisect_right(self._line_starts, p) - 1
        return FileLocation(
            line=line_idx + 1,
            column=p - self._line_starts[line_idx] + 1,
            file_name=self.file_name,
        )

    def eof(self) -> bool:
        return self.pos >= self.n

    def peek(self) -> str:
        return self.text[self.pos] if self.pos < self.n else ""

    def tag(self, s: str) -> None:
        if self.text.startswith(s, self.pos):
            self.pos += len(s)
            return
        raise Backtrack(self.pos, f"expected {s!r}")

    def char(self, c: str) -> None:
        if self.pos < self.n and self.text[self.pos] == c:
            self.pos += 1
            return
        raise Backtrack(self.pos, f"expected {c!r}")

    def try_tag(self, s: str) -> bool:
        if self.text.startswith(s, self.pos):
            self.pos += len(s)
            return True
        return False

    def regex(self, rx) -> str:
        m = rx.match(self.text, self.pos)
        if not m:
            raise Backtrack(self.pos)
        self.pos = m.end()
        return m.group(0)

    # ws / comments ----------------------------------------------------
    def skip_ws(self) -> None:
        """zero_or_more_ws_or_comment (parser.rs:139-141)."""
        t, n = self.text, self.n
        p = self.pos
        while p < n:
            c = t[p]
            if c in " \t\r\n":
                p += 1
            elif c == "#":
                nl = t.find("\n", p)
                p = n if nl < 0 else nl + 1
            else:
                break
        self.pos = p

    def skip_ws1(self) -> None:
        """one_or_more_ws_or_comment (parser.rs:131-133)."""
        start = self.pos
        self.skip_ws()
        if self.pos == start:
            raise Backtrack(self.pos, "expected whitespace or comment")

    def skip_space0(self) -> None:
        while self.pos < self.n and self.text[self.pos] in " \t":
            self.pos += 1

    def space1(self) -> None:
        if self.pos < self.n and self.text[self.pos] in " \t":
            self.skip_space0()
            return
        raise Backtrack(self.pos, "expected space")

    def alt(self, *parsers):
        """nom alt: try in order, backtracking on Backtrack only."""
        start = self.pos
        last = None
        for p in parsers:
            try:
                return p()
            except Backtrack as e:
                self.pos = start
                last = e
        raise last if last is not None else Backtrack(start)

    def opt(self, parser):
        start = self.pos
        try:
            return parser()
        except Backtrack:
            self.pos = start
            return None

    def cut(self, parser, context: str = ""):
        try:
            return parser()
        except Backtrack as e:
            raise Fatal(e.pos, context or e.context)

    # ------------------------------------------------------------------
    # value literals (parser.rs:167-425)
    # ------------------------------------------------------------------
    def var_name(self) -> str:
        """parser.rs:545-551."""
        return self.regex(_VAR_NAME_RE)

    def var_name_access(self) -> str:
        self.char("%")
        return self.var_name()

    def parse_string(self) -> str:
        """Single or double quoted with backslash-escape of the quote
        (parser.rs:177-208)."""
        q = self.peek()
        if q not in ("'", '"'):
            raise Backtrack(self.pos, "expected string")
        self.pos += 1
        out = []
        t = self.text
        while True:
            end = t.find(q, self.pos)
            if end < 0:
                raise Fatal(self.pos, "unterminated string")
            frag = t[self.pos : end]
            if frag.endswith("\\"):
                out.append(frag[:-1])
                out.append(q)
                self.pos = end + 1
                continue
            out.append(frag)
            self.pos = end + 1
            return "".join(out)

    def parse_int_scalar(self) -> int:
        """parser.rs:167-175 (note: negative branch is tried second)."""
        if self.try_tag("-"):
            return -int(self.regex(_INT_RE))
        return int(self.regex(_INT_RE))

    def parse_float_scalar(self) -> float:
        """parser.rs:230-243 — the gate requires a fraction or a
        SIGNED exponent, then nom `double` consumes the maximal float
        (incl. an unsigned exponent after a fraction: `1.5e3`)."""
        m = _FLOAT_BODY_RE.match(self.text, self.pos)
        if not m or (m.group(1) is None and m.group(2) is None):
            raise Backtrack(self.pos, "not a float")
        m2 = _FLOAT_DOUBLE_RE.match(self.text, self.pos)
        self.pos = m2.end()
        return float(m2.group(0))

    def parse_regex_literal(self) -> str:
        """parser.rs:245-286 — /.../ with \\/ escapes; validated."""
        self.char("/")
        out = []
        t = self.text
        while True:
            end = t.find("/", self.pos)
            if end < 0:
                raise Backtrack(self.pos, "unterminated regex")
            frag = t[self.pos : end]
            if frag.endswith("\\"):
                out.append(frag[:-1])
                out.append("/")
                self.pos = end + 1
                continue
            out.append(frag)
            self.pos = end + 1
            pattern = "".join(out)
            try:
                compiled_regex(pattern)
            except re.error as e:
                raise Backtrack(self.pos, f"Could not parse regular expression: {e}")
            return pattern

    def parse_scalar_value(self) -> PV:
        """parser.rs:345-357 — order matters: string, float, int, bool, regex."""
        start = self.pos
        p = Path.root()
        try:
            return PV.string(p, self.parse_string())
        except Backtrack:
            self.pos = start
        try:
            return PV.float_(p, self.parse_float_scalar())
        except Backtrack:
            self.pos = start
        try:
            return PV.int_(p, self.parse_int_scalar())
        except Backtrack:
            self.pos = start
        for lit, val in (("true", True), ("True", True), ("false", False), ("False", False)):
            if self.try_tag(lit):
                return PV.boolean(p, val)
        try:
            return PV.regex(p, self.parse_regex_literal())
        except Backtrack:
            self.pos = start
        raise Backtrack(self.pos, "expected scalar value")

    def parse_range(self) -> PV:
        """parser.rs:292-340: r[lo, hi) etc."""
        p = Path.root()
        self.char("r")
        open_c = self.peek()
        if open_c not in "([":
            raise Backtrack(self.pos, "expected ( or [")
        self.pos += 1

        def range_endpoint():
            self.skip_space0()
            v = self.alt(
                lambda: ("f", self.parse_float_scalar()),
                lambda: ("i", self.parse_int_scalar()),
                lambda: ("c", self._any_char()),
            )
            self.skip_space0()
            return v

        (k1, lo) = range_endpoint()
        self.char(",")
        (k2, hi) = range_endpoint()
        close_c = self.peek()
        if close_c not in ")]":
            raise Backtrack(self.pos, "expected ) or ]")
        self.pos += 1
        inclusive = (LOWER_INCLUSIVE if open_c == "[" else 0) | (
            UPPER_INCLUSIVE if close_c == "]" else 0
        )
        if k1 == "i" and k2 == "i":
            return PV(p, RANGE_INT, Range(lo, hi, inclusive))
        if k1 == "f" and k2 == "f":
            return PV(p, RANGE_FLOAT, Range(lo, hi, inclusive))
        if k1 == "c" and k2 == "c":
            return PV(p, RANGE_CHAR, Range(lo, hi, inclusive))
        raise Fatal(self.pos, "Could not parse range")

    def _any_char(self) -> str:
        if self.eof():
            raise Backtrack(self.pos)
        c = self.text[self.pos]
        self.pos += 1
        return c

    def parse_list_literal(self) -> PV:
        """parser.rs:363-372."""
        self.skip_ws()
        self.char("[")
        items: List[PV] = []
        while True:
            start = self.pos
            try:
                items.append(self.parse_value())
            except Backtrack:
                self.pos = start
                break
            save = self.pos
            self.skip_ws()
            if not self.try_tag(","):
                self.pos = save
                break
        self.skip_ws()
        self.char("]")
        return PV.list_(Path.root(), items)

    def _key_part(self) -> str:
        """parser.rs:374-388."""
        start = self.pos
        m = _KEY_CHARS_RE.match(self.text, self.pos)
        if m:
            self.pos = m.end()
            return m.group(0)
        self.pos = start
        return self.parse_string()

    def parse_map_literal(self) -> PV:
        """parser.rs:390-408."""
        self.char("{")
        mv = MapValue()
        first = True
        while True:
            save = self.pos
            try:
                self.skip_ws()
                key = self._key_part()
                self.skip_ws()
                self.char(":")
                val = self.parse_value()
            except Backtrack:
                self.pos = save
                break
            if key not in mv.values:
                mv.keys.append(PV.string(Path.root(), key))
            mv.values[key] = val
            first = False
            save = self.pos
            self.skip_ws()
            if not self.try_tag(","):
                self.pos = save
                break
        self.skip_ws()
        self.char("}")
        return PV.map_(Path.root(), mv)

    def parse_value(self) -> PV:
        """parser.rs:414-425 (order: null, scalar, range, list, map)."""
        self.skip_ws()
        start = self.pos
        for lit in ("null", "NULL"):
            if self.try_tag(lit):
                return PV.null(Path.root())
        for fn in (self.parse_scalar_value, self.parse_range, self.parse_list_literal, self.parse_map_literal):
            try:
                return fn()
            except Backtrack:
                self.pos = start
        raise Backtrack(self.pos, "expected value")

    # ------------------------------------------------------------------
    # comparison operators (parser.rs:578-694)
    # ------------------------------------------------------------------
    def _not_kw(self) -> bool:
        """parser.rs:582-593: 'not'/'NOT' + space, or '!'. Returns True so
        `opt(_not_kw) is not None` detects presence."""
        start = self.pos
        for kw in ("not", "NOT"):
            if self.try_tag(kw):
                try:
                    self.space1()
                    return True
                except Backtrack:
                    self.pos = start
        self.char("!")
        return True

    _IS_TYPE_OPS = [
        ("IS_STRING", "is_string", CmpOperator.IsString),
        ("IS_LIST", "is_list", CmpOperator.IsList),
        ("IS_STRUCT", "is_struct", CmpOperator.IsMap),
        ("IS_BOOL", "is_bool", CmpOperator.IsBool),
        ("IS_INT", "is_int", CmpOperator.IsInt),
        ("IS_NULL", "is_null", CmpOperator.IsNull),
        ("IS_FLOAT", "is_float", CmpOperator.IsFloat),
    ]

    def value_cmp(self) -> Tuple[CmpOperator, bool]:
        """parser.rs:663-694."""
        # '<<' is the custom-message delimiter, not Lt (parser.rs:669-676)
        if self.text.startswith("<<", self.pos):
            raise Backtrack(self.pos, "custom message tag detected")
        if self.try_tag("=="):
            return (CmpOperator.Eq, False)
        if self.try_tag("!="):
            return (CmpOperator.Eq, True)
        if self.try_tag(">="):
            return (CmpOperator.Ge, False)
        if self.try_tag("<="):
            return (CmpOperator.Le, False)
        if self.try_tag(">"):
            return (CmpOperator.Gt, False)
        if self.try_tag("<"):
            return (CmpOperator.Lt, False)
        # other_operations: opt(not) (in|exists|empty|is_*)
        start = self.pos
        negated = self.opt(self._not_kw) is not None
        for tags, op in (
            (("in", "IN"), CmpOperator.In),
            (("EXISTS", "exists"), CmpOperator.Exists),
            (("EMPTY", "empty"), CmpOperator.Empty),
        ):
            for t in tags:
                if self.try_tag(t):
                    return (op, negated)
        for upper, lower, op in self._IS_TYPE_OPS:
            if self.try_tag(upper) or self.try_tag(lower):
                return (op, negated)
        self.pos = start
        raise Backtrack(self.pos, "expected comparison operator")

    def custom_message(self) -> str:
        """parser.rs:696-712: << ... >>."""
        self.tag("<<")
        end = self.text.find(">>", self.pos)
        if end < 0:
            raise Fatal(self.pos, "Unable to find a closing >> tag for message")
        msg = self.text[self.pos : end]
        self.pos = end + 2
        return msg

    # ------------------------------------------------------------------
    # access queries (parser.rs:718-951)
    # ------------------------------------------------------------------
    def _property_name(self) -> str:
        """parser.rs:879-887: bare name or quoted string."""
        try:
            return self.var_name()
        except Backtrack:
            return self.parse_string()

    def _dotted_property(self):
        """parser.rs:732-751."""
        self.skip_ws()
        self.char(".")
        start = self.pos
        # int index
        try:
            return QIndex(self.parse_int_scalar())
        except Backtrack:
            self.pos = start
        try:
            return QKey(self._property_name())
        except Backtrack:
            self.pos = start
        try:
            return QKey("%" + self.var_name_access())
        except Backtrack:
            self.pos = start
        self.char("*")
        return QAllValues(None)

    def _variable_capture(self) -> str:
        """parser.rs:718-722: `name |` inside [ ]."""
        self.skip_ws()
        name = self.var_name()
        self.skip_space0()
        self.char("|")
        return name

    def _bracket_part(self):
        """predicate_or_index (parser.rs:847-855)."""
        start = self.pos
        # all_indices: [*] or [name] (parser.rs:761-772)
        try:
            self.skip_ws()
            self.char("[")
            try:
                save = self.pos
                self.skip_ws()
                self.char("*")
                part = QAllIndices(None)
            except Backtrack:
                self.pos = save
                part = QAllIndices(self.var_name())
            self.skip_ws()
            self.char("]")
            return part
        except Backtrack:
            self.pos = start
        # array_index: [int] (parser.rs:774-785)
        try:
            self.skip_ws()
            self.char("[")
            idx = self.parse_int_scalar()
            self.cut(lambda: (self.skip_ws(), self.char("]")))
            return QIndex(idx)
        except Backtrack:
            self.pos = start
        # map_key_lookup: ['key'] or [ name ] (parser.rs:787-808)
        try:
            self.skip_ws()
            self.char("[")
            try:
                save = self.pos
                s = self.parse_string()
                part = QKey(s)
            except Backtrack:
                self.pos = save
                self.skip_ws()
                name = self.var_name()
                self.skip_ws()
                part = QAllValues(name)
            self.skip_ws()
            self.char("]")
            return part
        except Backtrack:
            self.pos = start
        # map_keys_match: [ keys == ... ] (parser.rs:810-845)
        try:
            return self._map_keys_match()
        except Backtrack:
            self.pos = start
        # predicate_filter_clauses: [ cnf ] (parser.rs:724-730)
        self.skip_ws()
        self.char("[")
        var = self.opt(self._variable_capture)
        filters = self._cnf_clauses(self.clause)
        self.cut(lambda: (self.skip_ws(), self.char("]")), "expected ]")
        return QFilter(var, filters)

    def _map_keys_match(self):
        self.skip_ws()
        self.char("[")
        var = self.opt(self._variable_capture)
        self.skip_ws()
        if not (self.try_tag("KEYS") or self.try_tag("keys")):
            raise Backtrack(self.pos, "expected keys")

        def cmp_parser():
            self.skip_ws()
            if self.try_tag("=="):
                return (CmpOperator.Eq, False)
            if self.try_tag("!="):
                return (CmpOperator.Eq, True)
            start = self.pos
            try:
                self._not_kw()
                if self.try_tag("in") or self.try_tag("IN"):
                    return (CmpOperator.In, True)
                raise Backtrack(self.pos)
            except Backtrack:
                self.pos = start
            if self.try_tag("in") or self.try_tag("IN"):
                return (CmpOperator.In, False)
            raise Backtrack(self.pos, "expected keys comparator")

        cmp = self.cut(cmp_parser, "expected comparator after keys")

        def with_parser():
            self.skip_ws()
            try:
                return self.parse_value()
            except Backtrack:
                pass
            self.skip_ws()
            return self.access()

        with_val = self.cut(with_parser, "expected RHS for keys filter")
        self.skip_ws()
        self.char("]")
        op, inv = cmp
        return QMapKeyFilter(var, MapKeyFilterClause(op, inv, with_val))

    def _some_keyword(self) -> bool:
        self.skip_ws()
        if self.try_tag("SOME") or self.try_tag("some"):
            self.skip_ws1()
            return True
        raise Backtrack(self.pos)

    def access(self) -> AccessQuery:
        """parser.rs:913-951."""
        some = self.opt(self._some_keyword)
        self.skip_ws()
        # first part: this | %var | property
        start = self.pos
        first = None
        for kw in ("this", "THIS"):
            if self.try_tag(kw):
                first = QThis()
                break
        if first is None:
            try:
                first = QKey("%" + self.var_name_access())
            except Backtrack:
                self.pos = start
                first = QKey(self._property_name())
        rest_start = self.pos
        parts: List = []
        while True:
            save = self.pos
            try:
                parts.append(self.alt(self._dotted_property, self._bracket_part))
            except Backtrack:
                self.pos = save
                break
        if parts:
            parts.insert(0, first)
            # variable first part gets an implicit [*] (parser.rs:926-944)
            if part_is_variable(first):
                if not (len(parts) > 1 and isinstance(parts[1], QAllIndices)):
                    parts.insert(1, QAllIndices(None))
        else:
            self.pos = rest_start
            parts = [first]
        return AccessQuery(query=parts, match_all=some is None)

    # ------------------------------------------------------------------
    # function expressions (parser.rs:1074-1134)
    # ------------------------------------------------------------------
    def _call_expr(self) -> Tuple[str, List]:
        name = self.var_name()
        self.char("(")
        params: List = []
        while True:
            save = self.pos
            try:
                self.skip_ws()
                params.append(self.let_value())
                self.skip_ws()
            except Backtrack:
                self.pos = save
                break
            if not self.try_tag(","):
                break
        self.char(")")
        return name, params

    def function_expr(self) -> FunctionExpr:
        location = self.loc()
        name, params = self._call_expr()
        if name not in FUNCTION_ARITY:
            raise Backtrack(self.pos, f"No function with the name '{name}' exists.")
        if len(params) != FUNCTION_ARITY[name]:
            raise Backtrack(
                self.pos,
                f"function: {name} requires: {FUNCTION_ARITY[name]} parameters to "
                f"be passed, but received: {len(params)}",
            )
        return FunctionExpr(name=name, parameters=params, location=location)

    def let_value(self):
        """parser.rs:1112-1123 (order: value, function, access)."""
        self.skip_ws()
        start = self.pos
        try:
            return self.parse_value()
        except Backtrack:
            self.pos = start
        try:
            return self.function_expr()
        except Backtrack:
            self.pos = start
        return self.access()

    # ------------------------------------------------------------------
    # clauses (parser.rs:954-1198)
    # ------------------------------------------------------------------
    def _access_clause(self, mk) -> object:
        """clause_with_map (parser.rs:954-1038)."""
        self.skip_ws()
        location = self.loc()
        negation = self.opt(self._not_kw) is not None
        query = self.access()
        self.skip_ws()
        cmp = self.value_cmp()
        op, inverse = cmp
        if op.is_unary():
            save = self.pos
            self.skip_ws()
            msg = self.opt(self.custom_message)
            if msg is None:
                self.pos = save
            return mk(
                GuardAccessClause(
                    access_clause=AccessClause(
                        query=query,
                        comparator=op,
                        comparator_inverse=inverse,
                        compare_with=None,
                        custom_message=msg,
                        location=location,
                    ),
                    negation=negation,
                )
            )

        def rhs():
            start = self.pos
            try:
                v = self.parse_value()
            except Backtrack:
                self.pos = start
                try:
                    self.skip_ws()
                    v = self.function_expr()
                except Backtrack:
                    self.pos = start
                    self.skip_ws()
                    v = self.access()
            save = self.pos
            self.skip_ws()
            msg = self.opt(self.custom_message)
            if msg is None:
                self.pos = save
            return v, msg

        compare_with, msg = self.cut(
            rhs,
            'expecting either a property access "engine.core" or value like '
            '"string" or ["this", "that"]',
        )
        return mk(
            GuardAccessClause(
                access_clause=AccessClause(
                    query=query,
                    comparator=op,
                    comparator_inverse=inverse,
                    compare_with=compare_with,
                    custom_message=msg,
                    location=location,
                ),
                negation=negation,
            )
        )

    def block_clause(self) -> BlockGuardClause:
        """parser.rs:1047-1072: `query [!empty] { ... }`."""
        location = self.loc()
        query = self.access()
        save = self.pos
        not_empty = False
        try:
            self.skip_ws()
            self._not_kw()
            if not (self.try_tag("EMPTY") or self.try_tag("empty")):
                raise Backtrack(self.pos)
            not_empty = True
        except Backtrack:
            self.pos = save
        assignments, conjunctions = self._block(self.clause)
        return BlockGuardClause(
            query=query,
            block=Block(assignments=assignments, conjunctions=conjunctions),
            location=location,
            not_empty=not_empty,
        )

    def parameterized_rule_call_clause(self) -> ParameterizedNamedRuleClause:
        """parser.rs:1136-1160."""
        location = self.loc()
        negation = self.opt(self._not_kw) is not None
        name, params = self._call_expr()
        save = self.pos
        self.skip_ws()
        msg = self.opt(self.custom_message)
        if msg is None:
            self.pos = save
        return ParameterizedNamedRuleClause(
            parameters=params,
            named_rule=GuardNamedRuleClause(
                dependent_rule=name,
                negation=negation,
                custom_message=msg,
                location=location,
            ),
        )

    def clause(self):
        """parser.rs:1180-1198 (order: when-block, block, param-call, access)."""
        start = self.pos
        try:
            return self._when_block(self._single_clauses, self.clause, WhenBlockClause)
        except (Backtrack, Fatal) as e:
            if isinstance(e, Fatal):
                raise
            self.pos = start
        try:
            return self.block_clause()
        except Backtrack:
            self.pos = start
        try:
            return self.parameterized_rule_call_clause()
        except Backtrack:
            self.pos = start
        return self._access_clause(lambda c: c)

    def _single_clause(self):
        return self._access_clause(lambda c: c)

    def rule_clause(self) -> GuardNamedRuleClause:
        """Named-rule reference clause (parser.rs:1228-1278)."""
        self.skip_ws()
        location = self.loc()
        negation = self.opt(self._not_kw) is not None
        name = self.var_name()
        # peek: end, newline, comment, '{' or or-join (parser.rs:1242-1251)
        save = self.pos
        ok = False
        if self.pos >= self.n:
            ok = True
        else:
            self.skip_space0()
            c = self.peek()
            if c == "\n" or self.text.startswith("\r\n", self.pos) or c == "#" or c == "{":
                ok = True
            else:
                self.pos = save
                try:
                    self._or_join_peek()
                    ok = True
                except Backtrack:
                    pass
        self.pos = save
        if ok:
            return GuardNamedRuleClause(
                dependent_rule=name, negation=negation, custom_message=None, location=location
            )
        # else must be a custom message (parser.rs:1265-1277)
        self.skip_space0()
        msg = self.cut(self.custom_message, "expected custom message after rule name")
        return GuardNamedRuleClause(
            dependent_rule=name, negation=negation, custom_message=msg, location=location
        )

    def _or_join_peek(self):
        start = self.pos
        self.skip_ws()
        self._or_term()
        self.skip_ws1()
        self.pos = start

    def _or_term(self):
        for t in ("or", "OR", "|OR|"):
            if self.try_tag(t):
                return
        raise Backtrack(self.pos, "expected or")

    def _or_join(self):
        """parser.rs:1941-1947."""
        self.skip_ws()
        self._or_term()
        self.skip_ws1()

    # CNF machinery (parser.rs:1284-1347) ------------------------------
    def _disjunction(self, item_parser) -> List:
        items = [item_parser_first(self, item_parser)]
        while True:
            save = self.pos
            try:
                self._or_join()
                self.skip_ws()
                items.append(item_parser())
            except Backtrack:
                self.pos = save
                break
        return items

    def _cnf_clauses(self, item_parser) -> Conjunctions:
        conjunctions: Conjunctions = []
        while True:
            save = self.pos
            try:
                disj = self._disjunction(item_parser)
            except Backtrack:
                self.pos = save
                if not conjunctions:
                    raise Fatal(
                        self.pos,
                        f"There were no clauses present "
                        f"{self.file_name}#{self.loc().line}@{self.loc().column}",
                    )
                return conjunctions
            conjunctions.append(disj)

    def _single_clauses(self) -> Conjunctions:
        """single_clauses (parser.rs:1349-1384): when-condition clauses."""

        def item():
            start = self.pos
            try:
                return self._single_clause()
            except Backtrack:
                self.pos = start
            try:
                return self.parameterized_rule_call_clause()
            except Backtrack:
                self.pos = start
            return self.rule_clause()

        conjunctions: Conjunctions = []
        while True:
            save = self.pos
            try:
                disj = self._disjunction(item)
            except Backtrack:
                self.pos = save
                return conjunctions
            conjunctions.append(disj)

    # assignments (parser.rs:1414-1474) --------------------------------
    def assignment(self) -> LetExpr:
        self.tag("let")
        self.skip_ws1()
        var = self.var_name()
        self.cut(
            lambda: (
                self.skip_ws(),
                self.tag(":=") if self.text.startswith(":=", self.pos) else self.tag("="),
            ),
            "expected = or := after let variable",
        )
        start = self.pos
        try:
            value = self.parse_value()
            return LetExpr(var=var, value=value)
        except Backtrack:
            self.pos = start
        try:
            self.skip_ws()
            fn = self.function_expr()
            return LetExpr(var=var, value=fn)
        except (Backtrack, Fatal):
            self.pos = start
        self.skip_ws()
        acc = self.cut(self.access, "expected value, function call or query after =")
        return LetExpr(var=var, value=acc)

    # when-conditions + blocks (parser.rs:1479-1554) -------------------
    def _when_conditions(self, condition_parser) -> Conjunctions:
        self.skip_ws()
        if not (self.try_tag("when") or self.try_tag("WHEN")):
            raise Backtrack(self.pos, "expected when")
        self.cut(self.skip_ws1, "expected space after when")
        return condition_parser()

    def _block(self, clause_parser) -> Tuple[List[LetExpr], Conjunctions]:
        """block() (parser.rs:1510-1554)."""
        self.skip_ws()
        self.char("{")
        assignments: List[LetExpr] = []
        conjunctions: Conjunctions = []
        found = False
        while True:
            save = self.pos
            try:
                self.skip_ws()
                assignments.append(self.assignment())
                found = True
                continue
            except Backtrack:
                self.pos = save
            try:
                disj = self._disjunction(clause_parser)
                conjunctions.append(disj)
                found = True
                continue
            except Backtrack:
                self.pos = save
                break
        if not found:
            raise Backtrack(self.pos, "empty block")
        self.cut(lambda: (self.skip_ws(), self.char("}")), "expected } to close block")
        return assignments, conjunctions

    def _when_block(self, conditions_parser, block_parser, mapper):
        """when_block() (parser.rs:1661-1682)."""
        self.skip_ws()
        conds = self._when_conditions(conditions_parser)
        assignments, conjunctions = self._block(block_parser)
        return mapper(conds, Block(assignments=assignments, conjunctions=conjunctions))

    # type blocks (parser.rs:1556-1658) --------------------------------
    def type_name(self) -> str:
        start = self.pos
        try:
            a = self.var_name()
            self.tag("::")
            b = self.var_name()
            self.tag("::")
            c = self.var_name()
            self.try_tag("::MODULE")
            return f"{a}::{b}::{c}"
        except Backtrack:
            self.pos = start
        a = self.var_name()
        self.tag("::")
        b = self.var_name()
        return f"{a}::{b}"

    def type_block(self) -> TypeBlock:
        location = self.loc()
        name = self.type_name()
        self.cut(self.skip_ws1, "expected space after type name")
        conds = self.opt(lambda: self._when_conditions(self._single_clauses))
        if conds is not None:
            assignments, clauses = self.cut(
                lambda: self._block(self.clause), "expected block after type when conditions"
            )
        else:
            save = self.pos
            try:
                assignments, clauses = self._block(self.clause)
            except Backtrack:
                self.pos = save
                self.skip_ws()
                single = self.cut(self.clause, "expected clause after type name")
                assignments, clauses = [], [[single]]
        # synthesized query Resources.*[ Type == '<name>' ] (parser.rs:1631-1655)
        query = [
            QKey("Resources"),
            QAllValues(None),
            QFilter(
                None,
                [
                    [
                        GuardAccessClause(
                            access_clause=AccessClause(
                                query=AccessQuery(query=[QKey("Type")], match_all=True),
                                comparator=CmpOperator.Eq,
                                comparator_inverse=False,
                                compare_with=PV.string(Path.root(), name),
                                custom_message=None,
                                location=location,
                            ),
                            negation=False,
                        )
                    ]
                ],
            ),
        ]
        return TypeBlock(
            type_name=name,
            conditions=conds,
            block=Block(assignments=assignments, conjunctions=clauses),
            query=query,
        )

    # rule blocks (parser.rs:1684-1790) --------------------------------
    def _rule_block_clause(self):
        start = self.pos
        try:
            self.skip_ws()
            return self.type_block()
        except Backtrack:
            self.pos = start
        try:
            self.skip_ws()
            conds = self._when_conditions(self._single_clauses)
            assignments, conjunctions = self._block(self._clause_or_rule_clause)
            return WhenBlockClause(
                conditions=conds,
                block=Block(assignments=assignments, conjunctions=conjunctions),
            )
        except Backtrack:
            self.pos = start
        self.skip_ws()
        return self._clause_or_rule_clause()

    def _clause_or_rule_clause(self):
        start = self.pos
        try:
            return self.clause()
        except Backtrack:
            self.pos = start
        return self.rule_clause()

    def rule_block(self) -> Rule:
        self.skip_ws()
        self.tag("rule")
        self.skip_ws1()
        name = self.cut(self.var_name, "expected rule name")
        conds = self.opt(lambda: self._when_conditions(self._single_clauses))
        assignments, conjunctions = self.cut(
            lambda: self._block(self._rule_block_clause), "expected rule block"
        )
        return Rule(
            rule_name=name,
            conditions=conds,
            block=Block(assignments=assignments, conjunctions=conjunctions),
        )

    def parameterized_rule_block(self) -> ParameterizedRule:
        self.skip_ws()
        self.tag("rule")
        self.skip_ws1()
        name = self.cut(self.var_name, "expected rule name")
        self.char("(")
        params: List[str] = []
        while True:
            self.skip_ws()
            params.append(self.cut(self.var_name, "expected parameter name"))
            self.skip_ws()
            if not self.try_tag(","):
                break
        self.cut(lambda: self.char(")"), "expected ) after parameters")
        # dedupe preserving order (IndexSet)
        seen = set()
        unique = []
        for p in params:
            if p not in seen:
                seen.add(p)
                unique.append(p)
        assignments, conjunctions = self.cut(
            lambda: self._block(self._rule_block_clause), "expected rule block"
        )
        return ParameterizedRule(
            parameter_names=unique,
            rule=Rule(
                rule_name=name,
                conditions=None,
                block=Block(assignments=assignments, conjunctions=conjunctions),
            ),
        )


def item_parser_first(p: Parser, item_parser):
    p.skip_ws()
    return item_parser()


# ---------------------------------------------------------------------------
# top-level rules file (parser.rs:1840-1932)
# ---------------------------------------------------------------------------
def parse_rules_file(content: str, file_name: str = "") -> Optional[RulesFile]:
    p = Parser(content, file_name)
    p.skip_ws()
    if p.eof():
        return None

    assignments: List[LetExpr] = []
    named_rules: List[Rule] = []
    parameterized_rules: List[ParameterizedRule] = []
    default_rule_clauses: List[List] = []

    try:
        while True:
            p.skip_ws()
            if p.eof():
                break
            start = p.pos
            # order mirrors parser.rs:1852-1868
            try:
                assignments.append(p.assignment())
                continue
            except Backtrack:
                p.pos = start
            try:
                parameterized_rules.append(p.parameterized_rule_block())
                continue
            except Backtrack:
                p.pos = start
            try:
                named_rules.append(p.rule_block())
                continue
            except Backtrack:
                p.pos = start
            try:
                disj = p._disjunction(p.type_block)
                default_rule_clauses.append(list(disj))
                continue
            except Backtrack:
                p.pos = start
            try:
                wb = p._when_block(
                    p._single_clauses, p._clause_or_rule_clause, WhenBlockClause
                )
                default_rule_clauses.append([wb])
                continue
            except Backtrack:
                p.pos = start
            disj = p._disjunction(p.clause)
            default_rule_clauses.append(disj)
    except Backtrack as e:
        loc = p.loc(e.pos)
        raise ParseError(
            f"Error parsing file {file_name} at line {loc.line} at column "
            f"{loc.column}, when handling {e.context}, fragment "
            f"{content[e.pos:e.pos + 40]!r}"
        )
    except Fatal as e:
        loc = p.loc(e.pos)
        raise ParseError(
            f"Error parsing file {file_name} at line {loc.line} at column "
            f"{loc.column}, when handling {e.context}, fragment "
            f"{content[e.pos:e.pos + 40]!r}"
        )

    if default_rule_clauses:
        default_rule_name = (
            DEFAULT_RULE_NAME
            if not file_name.strip()
            else f"{file_name}/{DEFAULT_RULE_NAME}"
        )
        named_rules.insert(
            0,
            Rule(
                rule_name=default_rule_name,
                conditions=None,
                block=Block(assignments=[], conjunctions=default_rule_clauses),
            ),
        )

    return RulesFile(
        assignments=assignments,
        guard_rules=named_rules,
        parameterized_rules=parameterized_rules,
    )


def get_rule_name(rule_file_name: str, rule_name: str) -> str:
    """parser.rs:1828-1835."""
    prefix = f"{rule_file_name}/"
    return rule_name[len(prefix) :] if rule_name.startswith(prefix) else rule_name
