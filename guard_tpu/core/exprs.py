"""Guard DSL abstract syntax tree.

Python equivalent of `/root/reference/guard/src/rules/exprs.rs`:
`RulesFile`/`Rule`/`ParameterizedRule` (exprs.rs:277-284, 264-274),
`GuardClause` variants (exprs.rs:225-231), `QueryPart` (exprs.rs:65-73),
CNF encoding `Conjunctions<T> = list[list[T]]` (exprs.rs:174-175).

The AST is also the input of the TPU lowering pass
(guard_tpu/ops/ir.py), so every node is a plain, cheap dataclass.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum
from typing import List, Optional, Union

from .values import PV

# ---------------------------------------------------------------------------
# Comparison operators (values.rs:22-39)
# ---------------------------------------------------------------------------
class CmpOperator(str, Enum):
    Eq = "Eq"
    In = "In"
    Gt = "Gt"
    Lt = "Lt"
    Le = "Le"
    Ge = "Ge"
    Exists = "Exists"
    Empty = "Empty"
    IsString = "IsString"
    IsList = "IsList"
    IsMap = "IsMap"
    IsBool = "IsBool"
    IsInt = "IsInt"
    IsFloat = "IsFloat"
    IsNull = "IsNull"

    def is_unary(self) -> bool:
        # values.rs:42-55
        return self in _UNARY

    def display(self) -> str:
        return _CMP_DISPLAY[self]


_UNARY = {
    CmpOperator.Exists,
    CmpOperator.Empty,
    CmpOperator.IsString,
    CmpOperator.IsBool,
    CmpOperator.IsList,
    CmpOperator.IsInt,
    CmpOperator.IsMap,
    CmpOperator.IsFloat,
    CmpOperator.IsNull,
}

_CMP_DISPLAY = {
    CmpOperator.Eq: "EQUALS",
    CmpOperator.In: "IN",
    CmpOperator.Gt: "GREATER THAN",
    CmpOperator.Lt: "LESS THAN",
    CmpOperator.Ge: "GREATER THAN EQUALS",
    CmpOperator.Le: "LESS THAN EQUALS",
    CmpOperator.Exists: "EXISTS",
    CmpOperator.Empty: "EMPTY",
    CmpOperator.IsString: "IS STRING",
    CmpOperator.IsBool: "IS BOOL",
    CmpOperator.IsInt: "IS INT",
    CmpOperator.IsList: "IS LIST",
    CmpOperator.IsMap: "IS MAP",
    CmpOperator.IsNull: "IS NULL",
    CmpOperator.IsFloat: "IS FLOAT",
}


@dataclass
class FileLocation:
    """exprs.rs:12-18."""

    line: int = 0
    column: int = 0
    file_name: str = ""

    def __str__(self):
        return f"Location[file:{self.file_name}, line:{self.line}, column:{self.column}]"


# ---------------------------------------------------------------------------
# Query parts (exprs.rs:65-73)
# ---------------------------------------------------------------------------
@dataclass
class QThis:
    """`this` keyword."""

    def display(self) -> str:
        return "_"


@dataclass
class QKey:
    name: str

    def display(self) -> str:
        return self.name


@dataclass
class QAllValues:
    """`.*` — all values of a map (capture name optional)."""

    name: Optional[str] = None

    def display(self) -> str:
        return "*"


@dataclass
class QAllIndices:
    """`[*]` — all elements of a list (capture name optional)."""

    name: Optional[str] = None

    def display(self) -> str:
        return "[*]"


@dataclass
class QIndex:
    index: int

    def display(self) -> str:
        return str(self.index)


@dataclass
class QFilter:
    """`[ <cnf clauses> ]` predicate filter."""

    name: Optional[str]
    conjunctions: "Conjunctions"  # Conjunctions[GuardClause]

    def display(self) -> str:
        return f"{self.name or ''} (filter-clauses)"


@dataclass
class QMapKeyFilter:
    """`[ keys == ... ]` map-key filter."""

    name: Optional[str]
    clause: "MapKeyFilterClause"

    def display(self) -> str:
        return f"{self.name or ''} (map-key-filter-clauses)"


QueryPart = Union[QThis, QKey, QAllValues, QAllIndices, QIndex, QFilter, QMapKeyFilter]


def part_is_variable(part) -> bool:
    """exprs.rs:76-83."""
    return isinstance(part, QKey) and part.name.startswith("%")


def part_variable(part) -> Optional[str]:
    """exprs.rs:84-94."""
    if isinstance(part, QKey) and part.name.startswith("%"):
        return part.name[1:]
    return None


def display_query(parts: List[QueryPart]) -> str:
    """SliceDisplay (exprs.rs:286-303)."""
    out = ".".join(p.display() for p in parts)
    return out.replace(".[", "[")


@dataclass
class AccessQuery:
    """exprs.rs:139-142 — `some` sets match_all=False."""

    query: List[QueryPart]
    match_all: bool = True

    def display(self) -> str:
        return display_query(self.query)


# ---------------------------------------------------------------------------
# Let values & function calls (exprs.rs:31-35, 218-222)
# ---------------------------------------------------------------------------
@dataclass
class FunctionExpr:
    name: str  # validated against FUNCTIONS registry at parse time
    parameters: List["LetValue"]
    location: FileLocation = field(default_factory=FileLocation)

    def display(self) -> str:
        return f"{self.name}({', '.join(display_let_value(p) for p in self.parameters)})"


# LetValue is one of: PV (literal), AccessQuery, FunctionExpr
LetValue = Union[PV, AccessQuery, FunctionExpr]


def display_let_value(lv: LetValue) -> str:
    if isinstance(lv, AccessQuery):
        return lv.display()
    if isinstance(lv, FunctionExpr):
        return lv.display()
    from .values import value_only_display

    return value_only_display(lv)


@dataclass
class LetExpr:
    """`let var = value|query|fn()` (exprs.rs:43-47)."""

    var: str
    value: LetValue


# ---------------------------------------------------------------------------
# Clauses (exprs.rs:146-231)
# ---------------------------------------------------------------------------
@dataclass
class AccessClause:
    """exprs.rs:146-153."""

    query: AccessQuery
    comparator: CmpOperator
    comparator_inverse: bool  # the `!`/`not` fused into the operator (e.g. !=)
    compare_with: Optional[LetValue] = None
    custom_message: Optional[str] = None
    location: FileLocation = field(default_factory=FileLocation)


@dataclass
class GuardAccessClause:
    """exprs.rs:177-181."""

    access_clause: AccessClause
    negation: bool = False

    def display(self) -> str:
        # exprs.rs:332-359: GuardAccessClause renders "{not|} {clause}"
        # (leading space when not negated) and AccessClause renders
        # "{query} {display_comparator}{rhs}" where display_comparator
        # carries a trailing space — hence the double space before the
        # RHS and the trailing spaces on unary clauses. Reports pin
        # these strings byte-for-byte.
        ac = self.access_clause
        lead = "not" if self.negation else ""
        cmp_not = "not " if ac.comparator_inverse else ""
        rhs = display_let_value(ac.compare_with) if ac.compare_with is not None else ""
        return f"{lead} {ac.query.display()} {cmp_not}{ac.comparator.display()}  {rhs}"


@dataclass
class MapKeyFilterClause:
    """exprs.rs:183-187."""

    comparator: CmpOperator
    comparator_inverse: bool
    compare_with: LetValue


@dataclass
class GuardNamedRuleClause:
    """Reference to another named rule (exprs.rs:189-195)."""

    dependent_rule: str
    negation: bool = False
    custom_message: Optional[str] = None
    location: FileLocation = field(default_factory=FileLocation)

    def display(self) -> str:
        return f"{'not ' if self.negation else ''}{self.dependent_rule}"


@dataclass
class Block:
    """exprs.rs:242-246."""

    assignments: List[LetExpr]
    conjunctions: "Conjunctions"


@dataclass
class BlockGuardClause:
    """`query { clauses }` (exprs.rs:197-203)."""

    query: AccessQuery
    block: Block
    location: FileLocation = field(default_factory=FileLocation)
    not_empty: bool = False


@dataclass
class ParameterizedNamedRuleClause:
    """`rule_name(arg1, arg2)` call (exprs.rs:211-215)."""

    parameters: List[LetValue]
    named_rule: GuardNamedRuleClause


@dataclass
class WhenBlockClause:
    """`when <conds> { ... }` inside a rule/block (exprs.rs:230)."""

    conditions: "Conjunctions"  # Conjunctions[GuardClause-like when clauses]
    block: Block


# GuardClause = GuardAccessClause | GuardNamedRuleClause
#             | ParameterizedNamedRuleClause | BlockGuardClause | WhenBlockClause
GuardClause = Union[
    GuardAccessClause,
    GuardNamedRuleClause,
    ParameterizedNamedRuleClause,
    BlockGuardClause,
    WhenBlockClause,
]

# Conjunctions<T> = Vec<Vec<T>> — CNF: AND over the outer list, OR inner
Conjunctions = List[List[GuardClause]]


@dataclass
class TypeBlock:
    """`AWS::X::Y { ... }` — sugar for Resources.*[ Type == 'AWS::X::Y' ]
    (exprs.rs:249-254, query construction parser.rs:1622-1656)."""

    type_name: str
    block: Block
    query: List[QueryPart]
    conditions: Optional[Conjunctions] = None


# RuleClause = GuardClause | WhenBlockClause | TypeBlock (exprs.rs:257-261)
RuleClause = Union[GuardClause, TypeBlock]


@dataclass
class Rule:
    """Named rule block (exprs.rs:264-268)."""

    rule_name: str
    conditions: Optional[Conjunctions]
    block: Block


@dataclass
class ParameterizedRule:
    """exprs.rs:271-274."""

    parameter_names: List[str]
    rule: Rule


@dataclass
class RulesFile:
    """exprs.rs:277-284."""

    assignments: List[LetExpr]
    guard_rules: List[Rule]
    parameterized_rules: List[ParameterizedRule]


def walk_expr_tree(obj, visit) -> None:
    """Generic structural walk over the parsed AST: calls
    `visit(node)` on every object reached through dataclass fields,
    lists, tuples and dict values; `visit` returning True stops
    descent below that node. PVs never contain AST nodes, so the walk
    stops there; an id-based seen set makes shared subobjects safe.
    Being structural (not channel-enumerated), new syntax cannot be
    silently missed by consumers like ir._referenced_variable_names
    and fnvars._excluded_fn_vars."""
    import dataclasses as _dc

    from .values import PV

    seen = set()

    def walk(o) -> None:
        if isinstance(o, (str, bytes, int, float, bool)) or o is None:
            return
        if id(o) in seen:
            return
        seen.add(id(o))
        if isinstance(o, PV):
            return
        if visit(o):
            return
        if _dc.is_dataclass(o) and not isinstance(o, type):
            for f in _dc.fields(o):
                walk(getattr(o, f.name))
        elif isinstance(o, (list, tuple)):
            for e in o:
                walk(e)
        elif isinstance(o, dict):
            for e in o.values():
                walk(e)

    walk(obj)
