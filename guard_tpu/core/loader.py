"""Location-aware YAML/JSON document loader.

Event-driven loader over PyYAML's parser events (the exact analogue of the
reference driving libyaml events, `/root/reference/guard/src/rules/libyaml/
loader.rs:31-60` + `parser.rs:44-61`), producing path-aware `PV` trees:

  * per-node line/col from 0-based parser marks (libyaml/util.rs:56-61);
  * scalar typing from the raw scalar string, NOT the YAML 1.1 resolver:
    plain scalars try i64 -> f64 -> bool(true/yes/on/y|false/no/off/n) ->
    null(~|null, case-insensitive) -> string (loader.rs:83-99);
  * CloudFormation intrinsic short-forms (`!Ref`, `!GetAtt`, ...) are
    rewritten to their long forms `{"Fn::X": value}`
    (loader.rs:197-225, rules/mod.rs:30-86);
  * YAML aliases are rejected (loader.rs:52-56);
  * JSON is loaded through the same path (JSON is a YAML subset), so JSON
    documents get source locations too.
"""

from __future__ import annotations

import json
import re
from typing import Iterator, Optional, Tuple

import yaml

from .errors import ParseError
from .values import Location, MapValue, Path, PV, from_plain

# rules/mod.rs:30-54
SHORT_FORM_TO_LONG = {
    "Ref": "Ref",
    "GetAtt": "Fn::GetAtt",
    "Base64": "Fn::Base64",
    "Sub": "Fn::Sub",
    "GetAZs": "Fn::GetAZs",
    "ImportValue": "Fn::ImportValue",
    "Condition": "Condition",
    "RefAll": "Fn::RefAll",
    "Select": "Fn::Select",
    "Split": "Fn::Split",
    "Join": "Fn::Join",
    "FindInMap": "Fn::FindInMap",
    "And": "Fn::And",
    "Equals": "Fn::Equals",
    "Contains": "Fn::Contains",
    "EachMemberIn": "Fn::EachMemberIn",
    "EachMemberEquals": "Fn::EachMemberEquals",
    "ValueOf": "Fn::ValueOf",
    "If": "Fn::If",
    "Not": "Fn::Not",
    "Or": "Fn::Or",
}

# rules/mod.rs:55-66
SINGLE_VALUE_FUNC_REF = {
    "Ref", "Base64", "Sub", "GetAZs", "ImportValue", "GetAtt", "Condition", "RefAll",
}

# rules/mod.rs:67-85
SEQUENCE_VALUE_FUNC_REF = {
    "GetAtt", "Sub", "Select", "Split", "Join", "FindInMap", "And", "Equals",
    "Contains", "EachMemberIn", "EachMemberEquals", "ValueOf", "If", "Not", "Or",
}

_TYPE_REF_PREFIX = "tag:yaml.org,2002:"

_INT_RE = re.compile(r"^[+-]?[0-9]+$")
# Rust f64::from_str grammar (no underscores, optional exp, inf/nan)
_FLOAT_RE = re.compile(
    r"^[+-]?((inf(inity)?)|(nan)|((([0-9]+)|([0-9]+\.[0-9]*)|(\.[0-9]+))([eE][+-]?[0-9]+)?))$",
    re.IGNORECASE,
)

_TRUE_SET = {"true", "yes", "on", "y"}  # loader.rs:103-105
_FALSE_SET = {"false", "no", "off", "n"}  # loader.rs:107-109


def _typed_scalar(raw: str, path: Path) -> PV:
    """Plain-scalar typing, mirroring loader.rs:86-98."""
    if _INT_RE.match(raw):
        try:
            return PV.int_(path, int(raw))
        except ValueError:
            pass
    if _FLOAT_RE.match(raw):
        try:
            return PV.float_(path, float(raw))
        except ValueError:
            pass
    if raw in _TRUE_SET:
        return PV.boolean(path, True)
    if raw in _FALSE_SET:
        return PV.boolean(path, False)
    if raw.lower() in ("~", "null"):
        return PV.null(path)
    return PV.string(path, raw)


def _loc(event) -> Location:
    m = event.start_mark
    return Location(m.line, m.column)


class _EventLoader:
    """Recursive-descent build of a PV tree from PyYAML parser events."""

    def __init__(self, events: Iterator, file_name: str):
        self.events = events
        self.file_name = file_name

    def _next(self):
        try:
            return next(self.events)
        except StopIteration:
            raise ParseError(f"Unexpected end of YAML stream in {self.file_name}")
        except yaml.YAMLError as e:
            raise ParseError(f"Error parsing file {self.file_name}: {e}")

    def load(self) -> PV:
        doc: Optional[PV] = None
        while True:
            ev = self._next()
            if isinstance(ev, (yaml.StreamStartEvent, yaml.DocumentStartEvent)):
                continue
            if isinstance(ev, (yaml.DocumentEndEvent, yaml.StreamEndEvent)):
                if doc is None:
                    raise ParseError(f"Empty YAML document in {self.file_name}")
                return doc
            doc = self._node(ev, Path.root())

    def _node(self, ev, path: Path) -> PV:
        if isinstance(ev, yaml.AliasEvent):
            # loader.rs:52-56
            raise ParseError("Guard does not currently support aliases")

        if isinstance(ev, yaml.ScalarEvent):
            return self._scalar(ev, path)

        if isinstance(ev, yaml.SequenceStartEvent):
            loc = _loc(ev)
            tag = ev.tag
            items = []
            idx = 0
            while True:
                child = self._next()
                if isinstance(child, yaml.SequenceEndEvent):
                    break
                items.append(self._node(child, path.extend(str(idx), None)))
                idx += 1
            seq = PV.list_(Path(path.s, loc), items)
            # CFN short-form over a sequence, e.g. `!GetAtt [a, b]`
            # (loader.rs:148-163 + handle_sequence_value_func_ref)
            if tag and tag.startswith("!") and not tag.startswith("!!"):
                suffix = tag[1:]
                if suffix in SEQUENCE_VALUE_FUNC_REF:
                    return self._wrap_fn(suffix, seq, loc, path)
            return seq

        if isinstance(ev, yaml.MappingStartEvent):
            loc = _loc(ev)
            mv = MapValue()
            while True:
                key_ev = self._next()
                if isinstance(key_ev, yaml.MappingEndEvent):
                    break
                if not isinstance(key_ev, yaml.ScalarEvent):
                    raise ParseError(
                        f"Non-scalar mapping key at line {_loc(key_ev).line} in {self.file_name}"
                    )
                key = key_ev.value
                key_path = path.extend(key, _loc(key_ev))
                val_ev = self._next()
                value = self._node(val_ev, key_path)
                # last-write-wins on duplicate keys (IndexMap::insert)
                if key not in mv.values:
                    mv.keys.append(PV.string(key_path, key))
                mv.values[key] = value
            return PV.map_(Path(path.s, loc), mv)

        raise ParseError(f"Unexpected YAML event {ev!r} in {self.file_name}")

    def _scalar(self, ev, path: Path) -> PV:
        loc = _loc(ev)
        p = Path(path.s, loc)
        raw = ev.value
        tag = ev.tag
        if tag is not None:
            if tag.startswith(_TYPE_REF_PREFIX):
                return self._type_ref(raw, p, tag)
            if tag.startswith("!") and not tag.startswith("!!"):
                suffix = tag[1:]
                # loader.rs:197-210: short-form scalar like `!Ref foo`
                if suffix in SINGLE_VALUE_FUNC_REF:
                    return self._wrap_fn(suffix, PV.string(p, raw), loc, path)
                return PV.string(p, raw)
            return PV.string(p, raw)
        if ev.style is not None and ev.style != "":
            # quoted / literal / folded scalars stay strings (loader.rs:83-84)
            return PV.string(p, raw)
        return _typed_scalar(raw, p)

    def _type_ref(self, raw: str, p: Path, tag: str) -> PV:
        """Explicit `!!type` tags (loader.rs:227-244)."""
        if tag == _TYPE_REF_PREFIX + "bool":
            if raw in ("true", "false"):
                return PV.boolean(p, raw == "true")
            return PV.string(p, raw)
        if tag == _TYPE_REF_PREFIX + "int":
            if _INT_RE.match(raw):
                return PV.int_(p, int(raw))
            raise ParseError(f"Bad !!int value {raw!r}")
        if tag == _TYPE_REF_PREFIX + "float":
            if _FLOAT_RE.match(raw):
                return PV.float_(p, float(raw))
            raise ParseError(f"Bad !!float value {raw!r}")
        if tag == _TYPE_REF_PREFIX + "null":
            return PV.null(p)
        return PV.string(p, raw)

    def _wrap_fn(self, suffix: str, value: PV, loc: Location, path: Path) -> PV:
        long_name = SHORT_FORM_TO_LONG[suffix]
        key_path = path.extend(long_name, loc)
        value.path = Path(key_path.s, value.path.loc)
        mv = MapValue(
            keys=[PV.string(key_path, long_name)], values={long_name: value}
        )
        return PV.map_(Path(path.s, loc), mv)


def load_document(content: str, file_name: str = "") -> PV:
    """Parse a YAML or JSON document into a path-aware tree.

    Equivalent of `values::read_from` -> `Loader::load` ->
    `PathAwareValue::try_from(MarkedValue)`
    (values.rs:444, loader.rs:31, path_value.rs:407-414).
    """
    try:
        events = yaml.parse(content, Loader=getattr(yaml, "CSafeLoader", yaml.SafeLoader))
        return _EventLoader(iter(events), file_name).load()
    except ParseError:
        raise
    except yaml.YAMLError as yaml_err:
        # JSON documents that YAML 1.1 rejects (rare: tabs, special keys)
        try:
            data = json.loads(content)
        except (json.JSONDecodeError, ValueError):
            raise ParseError(f"Error parsing file {file_name}: {yaml_err}")
        return from_plain(data)


class _IntrinsicsSafeLoader(yaml.SafeLoader):
    """SafeLoader that rewrites CFN short-form tags to long forms when
    loading plain python data (test specs, rulegen templates) — the
    analogue of serde_yaml's Tagged handling + `handle_tagged_value`
    (values.rs:324-336)."""


def _intrinsic_multi_constructor(loader, tag_suffix, node):
    name = tag_suffix
    if isinstance(node, yaml.ScalarNode):
        value = loader.construct_scalar(node)
        if name in SINGLE_VALUE_FUNC_REF:
            return {SHORT_FORM_TO_LONG[name]: value}
        return value
    if isinstance(node, yaml.SequenceNode):
        value = loader.construct_sequence(node, deep=True)
        if name in SEQUENCE_VALUE_FUNC_REF:
            return {SHORT_FORM_TO_LONG[name]: value}
        return value
    return loader.construct_mapping(node, deep=True)


yaml.add_multi_constructor("!", _intrinsic_multi_constructor, Loader=_IntrinsicsSafeLoader)


def yaml_load_with_intrinsics(content: str):
    """yaml.safe_load that tolerates CFN short-form intrinsic tags."""
    return yaml.load(content, Loader=_IntrinsicsSafeLoader)


def load_payload(content: str) -> Tuple[list, list]:
    """Parse a stdin payload `{"rules": [...], "data": [...]}`
    (validate.rs:507-513)."""
    try:
        payload = json.loads(content)
    except json.JSONDecodeError as e:
        raise ParseError(f"Error parsing payload: {e}")
    if not isinstance(payload, dict) or "rules" not in payload or "data" not in payload:
        raise ParseError("Payload must be a JSON object with 'rules' and 'data' lists")
    return list(payload["rules"]), list(payload["data"])
