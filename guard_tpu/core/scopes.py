"""Scopes, variable resolution and the query tree-walk (the hot loop).

Python equivalent of `/root/reference/guard/src/rules/eval_context.rs`:
`RootScope`/`BlockScope`/`ValueScope` (eval_context.rs:47-87),
`extract_variables` (eval_context.rs:95-117), the recursive
`query_retrieval_with_converter` (eval_context.rs:337-924),
`RecordTracker` (eval_context.rs:999-1059), and `resolve_function`
(eval_context.rs:2437-2472).

Filters inside queries recursively evaluate guard clauses, so this module
and `evaluator.py` are mutually recursive; the evaluator is imported
lazily where needed.
"""

from __future__ import annotations

import re
from typing import Callable, Dict, List, Optional

from .errors import IncompatibleError, InternalError, MissingValueError, NotComparableError
from .exprs import (
    AccessQuery,
    Block,
    FunctionExpr,
    LetExpr,
    ParameterizedRule,
    QAllIndices,
    QAllValues,
    QFilter,
    QIndex,
    QKey,
    QMapKeyFilter,
    QThis,
    Rule,
    RulesFile,
    display_query,
    part_is_variable,
    part_variable,
)
from .functions import call_function
from .qresult import LITERAL, RESOLVED, UNRESOLVED, QueryResult, Status, UnResolved
from .records import EventRecord, RecordType
from .values import LIST, MAP, STRING, PV, rust_debug_pv

# ---------------------------------------------------------------------------
# Key-case converters (eval_context.rs:315-326, via the cruet crate):
# when a map key is missing, the walk retries the key converted to
# camel / Class / kebab-case / PascalCase / snake_case / Title Case /
# Train-Case before reporting UnResolved.
# ---------------------------------------------------------------------------
_WORD_RE = re.compile(r"[A-Za-z0-9]+")


def _words(s: str) -> List[str]:
    out: List[str] = []
    for token in _WORD_RE.findall(s):
        # split camel humps: XMLHttpRequest -> XML, Http, Request
        for m in re.finditer(r"[A-Z]+(?![a-z])|[A-Z][a-z0-9]*|[a-z0-9]+", token):
            out.append(m.group(0))
    return out


def to_camel_case(s: str) -> str:
    w = [x.lower() for x in _words(s)]
    return w[0] + "".join(x.capitalize() for x in w[1:]) if w else s


def to_pascal_case(s: str) -> str:
    return "".join(x.capitalize() for x in _words(s))


def to_kebab_case(s: str) -> str:
    return "-".join(x.lower() for x in _words(s))


def to_snake_case(s: str) -> str:
    return "_".join(x.lower() for x in _words(s))


def to_title_case(s: str) -> str:
    return " ".join(x.capitalize() for x in _words(s))


def to_train_case(s: str) -> str:
    return "-".join(x.capitalize() for x in _words(s))


# order matches CONVERTERS (eval_context.rs:317-325): camel, class,
# kebab, pascal, snake, title, train
CONVERTERS: List[Callable[[str], str]] = [
    to_camel_case,
    to_pascal_case,  # cruet class-case == PascalCase for keys
    to_kebab_case,
    to_pascal_case,
    to_snake_case,
    to_title_case,
    to_train_case,
]


# ---------------------------------------------------------------------------
# Record tracker (eval_context.rs:999-1059)
# ---------------------------------------------------------------------------
class RecordTracker:
    def __init__(self):
        self.events: List[EventRecord] = []
        self.final_event: Optional[EventRecord] = None

    def start_record(self, context: str) -> None:
        self.events.append(EventRecord(context=context))

    def end_record(self, context: str, record: RecordType) -> None:
        if not self.events:
            raise InternalError(
                f"Event Record end with context {context} did not have a corresponding start"
            )
        event = self.events.pop()
        if event.context != context:
            raise InternalError(
                f"Event Record context start and end does not match {context}"
            )
        event.container = record
        if self.events:
            self.events[-1].children.append(event)
        else:
            self.final_event = event

    def extract(self) -> EventRecord:
        ev = self.final_event
        self.final_event = None
        return ev


# ---------------------------------------------------------------------------
# Scope machinery
# ---------------------------------------------------------------------------
def extract_variables(assignments: List[LetExpr]):
    """eval_context.rs:95-117 — split let-exprs into literals / queries /
    function expressions."""
    literals: Dict[str, PV] = {}
    queries: Dict[str, AccessQuery] = {}
    functions: Dict[str, FunctionExpr] = {}
    for each in assignments:
        v = each.value
        if isinstance(v, PV):
            literals[each.var] = v
        elif isinstance(v, AccessQuery):
            queries[each.var] = v
        else:
            functions[each.var] = v
    return literals, queries, functions


class _ScopeData:
    __slots__ = ("root", "literals", "variable_queries", "function_expressions", "resolved_variables")

    def __init__(self, root: PV, literals, queries, functions):
        self.root = root
        self.literals = literals
        self.variable_queries = queries
        self.function_expressions = functions
        self.resolved_variables: Dict[str, List[QueryResult]] = {}


class RootScope:
    """File-level scope + rule registry + status cache + recorder
    (eval_context.rs:47-53, 1062-1177)."""

    def __init__(self, rules_file: RulesFile, root: PV):
        literals, queries, functions = extract_variables(rules_file.assignments)
        self.scope = _ScopeData(root, literals, queries, functions)
        self.rules: Dict[str, List[Rule]] = {}
        for rule in rules_file.guard_rules:
            self.rules.setdefault(rule.rule_name, []).append(rule)
        self.parameterized_rules: Dict[str, ParameterizedRule] = {
            pr.rule.rule_name: pr for pr in rules_file.parameterized_rules
        }
        self.rules_status: Dict[str, Status] = {}
        self.recorder = RecordTracker()

    # RecordTracer
    def start_record(self, context: str) -> None:
        self.recorder.start_record(context)

    def end_record(self, context: str, record: RecordType) -> None:
        self.recorder.end_record(context, record)

    def reset_recorder(self) -> RecordTracker:
        old = self.recorder
        self.recorder = RecordTracker()
        return old

    # EvalContext
    def query(self, query: List) -> List[QueryResult]:
        return query_retrieval(0, query, self.root(), self)

    def root(self) -> PV:
        return self.scope.root

    def find_parameterized_rule(self, rule_name: str) -> ParameterizedRule:
        pr = self.parameterized_rules.get(rule_name)
        if pr is None:
            raise MissingValueError(
                f"Parameterized Rule with name {rule_name} was not found, "
                f"candidate {list(self.parameterized_rules)}"
            )
        return pr

    def rule_status(self, rule_name: str) -> Status:
        """eval_context.rs:1087-1115 — first non-SKIP status among
        same-named rules, cached."""
        if rule_name in self.rules_status:
            return self.rules_status[rule_name]
        rules = self.rules.get(rule_name)
        if rules is None:
            raise MissingValueError(
                f"Rule {rule_name} by that name does not exist, Rule Names = {list(self.rules)}"
            )
        from .evaluator import eval_rule  # lazy: mutual recursion

        status = Status.SKIP
        for each_rule in rules:
            s = eval_rule(each_rule, self)
            if s != Status.SKIP:
                status = s
                break
        self.rules_status[rule_name] = status
        return status

    def resolve_variable(self, variable_name: str) -> List[QueryResult]:
        """eval_context.rs:1117-1163 — single-shot caching; `some`-marked
        query assignments silently drop UnResolved entries."""
        return _resolve_variable_in(self, self.scope, variable_name)

    def add_variable_capture_key(self, variable_name: str, key: PV) -> None:
        self.scope.resolved_variables.setdefault(variable_name, []).append(
            QueryResult.resolved(key)
        )


def _resolve_variable_in(ctx, scope: _ScopeData, variable_name: str):
    if variable_name in scope.literals:
        return [QueryResult.literal(scope.literals[variable_name])]
    if variable_name in scope.resolved_variables:
        return list(scope.resolved_variables[variable_name])
    if variable_name in scope.function_expressions:
        fexpr = scope.function_expressions[variable_name]
        result = resolve_function(fexpr.name, fexpr.parameters, ctx)
        scope.resolved_variables[variable_name] = result
        return list(result)
    query = scope.variable_queries.get(variable_name)
    if query is None:
        raise MissingValueError(
            f"Could not resolve variable by name {variable_name} across scopes"
        )
    result = query_retrieval(0, query.query, ctx.root(), ctx)
    if not query.match_all:
        result = [q for q in result if q.tag == RESOLVED]
    scope.resolved_variables[variable_name] = result
    return list(result)


class BlockScope:
    """eval_context.rs:79-82, 1525-...: block-local lets over a parent."""

    def __init__(self, block: Block, root: PV, parent):
        literals, queries, functions = extract_variables(block.assignments)
        self.scope = _ScopeData(root, literals, queries, functions)
        self.parent = parent

    def start_record(self, context: str) -> None:
        self.parent.start_record(context)

    def end_record(self, context: str, record: RecordType) -> None:
        self.parent.end_record(context, record)

    def query(self, query: List) -> List[QueryResult]:
        return query_retrieval(0, query, self.root(), self)

    def root(self) -> PV:
        return self.scope.root

    def find_parameterized_rule(self, rule_name: str) -> ParameterizedRule:
        return self.parent.find_parameterized_rule(rule_name)

    def rule_status(self, rule_name: str) -> Status:
        return self.parent.rule_status(rule_name)

    def resolve_variable(self, variable_name: str) -> List[QueryResult]:
        if (
            variable_name in self.scope.literals
            or variable_name in self.scope.resolved_variables
            or variable_name in self.scope.function_expressions
            or variable_name in self.scope.variable_queries
        ):
            return _resolve_variable_in(self, self.scope, variable_name)
        return self.parent.resolve_variable(variable_name)

    def add_variable_capture_key(self, variable_name: str, key: PV) -> None:
        self.scope.resolved_variables.setdefault(variable_name, []).append(
            QueryResult.resolved(key)
        )


class ValueScope:
    """eval_context.rs:84-87: re-roots queries at a selected value."""

    __slots__ = ("root_value", "parent")

    def __init__(self, root: PV, parent):
        self.root_value = root
        self.parent = parent

    def start_record(self, context: str) -> None:
        self.parent.start_record(context)

    def end_record(self, context: str, record: RecordType) -> None:
        self.parent.end_record(context, record)

    def query(self, query: List) -> List[QueryResult]:
        # eval_context.rs:1483-1485: resolves against parent context
        return query_retrieval(0, query, self.root(), self.parent)

    def root(self) -> PV:
        return self.root_value

    def find_parameterized_rule(self, rule_name: str) -> ParameterizedRule:
        return self.parent.find_parameterized_rule(rule_name)

    def rule_status(self, rule_name: str) -> Status:
        return self.parent.rule_status(rule_name)

    def resolve_variable(self, variable_name: str) -> List[QueryResult]:
        return self.parent.resolve_variable(variable_name)

    def add_variable_capture_key(self, variable_name: str, key: PV) -> None:
        self.parent.add_variable_capture_key(variable_name, key)


# ---------------------------------------------------------------------------
# Function resolution (eval_context.rs:2437-2472)
# ---------------------------------------------------------------------------
def resolve_function(name: str, parameters: List, resolver) -> List[QueryResult]:
    args: List[List[QueryResult]] = []
    for param in parameters:
        if isinstance(param, PV):
            args.append([QueryResult.literal(param)])
        elif isinstance(param, AccessQuery):
            args.append(resolver.query(param.query))
        elif isinstance(param, FunctionExpr):
            args.append(resolve_function(param.name, param.parameters, resolver))
        else:
            raise InternalError(f"Unexpected function parameter {param!r}")
    results = call_function(name, args)
    return [QueryResult.resolved(v) for v in results if v is not None]


# ---------------------------------------------------------------------------
# Query retrieval — the recursive tree-walk (eval_context.rs:337-924)
# ---------------------------------------------------------------------------
def _unresolved(current: PV, reason: str, query_rest: List) -> QueryResult:
    return QueryResult.unresolved_(
        UnResolved(
            traversed_to=current,
            remaining_query=display_query(query_rest),
            reason=reason,
        )
    )


def query_retrieval(
    query_index: int, query: List, current: PV, resolver
) -> List[QueryResult]:
    return query_retrieval_with_converter(query_index, query, current, resolver, None)


def query_retrieval_with_converter(
    query_index: int,
    query: List,
    current: PV,
    resolver,
    converter: Optional[Callable[[str], str]],
) -> List[QueryResult]:
    if query_index >= len(query):
        return [QueryResult.resolved(current)]

    part = query[query_index]

    # %variable head (eval_context.rs:348-385)
    if query_index == 0 and part_is_variable(part):
        retrieved = resolver.resolve_variable(part_variable(part))
        resolved: List[QueryResult] = []
        for each in retrieved:
            if each.tag == UNRESOLVED:
                resolved.append(each)
                continue
            value = each.value
            index = query_index + 1
            if index < len(query) and isinstance(query[index], QAllIndices):
                index = query_index + 2
            if index < len(query):
                scope = ValueScope(value, resolver)
                resolved.extend(
                    query_retrieval_with_converter(index, query, value, scope, converter)
                )
            else:
                resolved.append(each)
        return resolved

    if isinstance(part, QThis):
        return query_retrieval_with_converter(
            query_index + 1, query, current, resolver, converter
        )

    if isinstance(part, QKey):
        return _retrieve_key(part, query_index, query, current, resolver, converter)

    if isinstance(part, QIndex):
        if current.kind == LIST:
            qr = _retrieve_index(current, part.index, current.val, query)
            if qr.tag == RESOLVED:
                return query_retrieval_with_converter(
                    query_index + 1, query, qr.value, resolver, converter
                )
            return [qr]
        return [
            _unresolved(
                current,
                f"Attempting to retrieve from index {part.index} but type is not an "
                f"array at path {current.self_path().disp()}, type {current.type_info()}",
                query[query_index:],
            )
        ]

    if isinstance(part, QAllIndices):
        return _retrieve_all_indices(part, query_index, query, current, resolver, converter)

    if isinstance(part, QAllValues):
        return _retrieve_all_values(part, query_index, query, current, resolver, converter)

    if isinstance(part, QFilter):
        return _retrieve_filter(part, query_index, query, current, resolver, converter)

    if isinstance(part, QMapKeyFilter):
        return _retrieve_map_key_filter(part, query_index, query, current, resolver, converter)

    raise InternalError(f"Unknown query part {part!r}")


def _retrieve_index(parent: PV, index: int, elements: List[PV], query: List) -> QueryResult:
    """eval_context.rs:119-140."""
    check = index if index >= 0 else -index
    if check < len(elements):
        return QueryResult.resolved(elements[check])
    return _unresolved(
        parent,
        f"Array Index out of bounds for path = {parent.self_path().disp()} on index = "
        f"{index} inside Array, remaining query = {display_query(query)}",
        query,
    )


def _accumulate(
    parent: PV, query_index: int, query: List, elements: List[PV], resolver, converter
) -> List[QueryResult]:
    """[*] over a list (eval_context.rs:142-177); empty -> UnResolved."""
    if not elements:
        return [
            _unresolved(
                parent,
                f"No more entries for value at path = {parent.self_path().disp()} on type = "
                f"{parent.type_info()} ",
                query[query_index:],
            )
        ]
    accumulated: List[QueryResult] = []
    for each in elements:
        accumulated.extend(
            query_retrieval_with_converter(query_index + 1, query, each, resolver, converter)
        )
    return accumulated


def _accumulate_map(
    parent: PV, mv, query_index: int, query: List, resolver, converter, func
) -> List[QueryResult]:
    """`.*` over a map (eval_context.rs:179-232); empty -> UnResolved.
    Each value is visited under a ValueScope rooted at that value."""
    if mv.is_empty():
        return [
            _unresolved(
                parent,
                f"No more entries for value at path = {parent.self_path().disp()} on type = "
                f"{parent.type_info()} ",
                query[query_index:],
            )
        ]
    resolved: List[QueryResult] = []
    for key_node in mv.keys:
        value = mv.values[key_node.val]
        val_resolver = ValueScope(value, resolver)
        resolved.extend(
            func(query_index + 1, query, key_node, value, val_resolver, converter)
        )
    return resolved


def _retrieve_key(part: QKey, query_index, query, current: PV, resolver, converter):
    key = part.name
    # integer-looking key -> array index (eval_context.rs:392-417)
    try:
        idx = int(key)
        is_int_key = bool(re.fullmatch(r"[+-]?[0-9]+", key))
    except ValueError:
        is_int_key = False
    if is_int_key:
        if current.kind == LIST:
            qr = _retrieve_index(current, idx, current.val, query)
            if qr.tag == RESOLVED:
                return query_retrieval_with_converter(
                    query_index + 1, query, qr.value, resolver, converter
                )
            return [qr]
        return [
            _unresolved(
                current,
                f"Attempting to retrieve from index {idx} but type is not an array "
                f"at path {current.self_path().disp()}",
                query,
            )
        ]

    if current.kind != MAP:
        return [
            _unresolved(
                current,
                f"Attempting to retrieve from key {key} but type is not an struct "
                f"type at path {current.self_path().disp()}, Type = "
                f"{current.type_info()}, Value = {rust_debug_pv(current)}",
                query[query_index:],
            )
        ]

    mv = current.val
    if part_is_variable(part):
        # variable interpolation as a key (eval_context.rs:421-526)
        var = part_variable(part)
        keys = resolver.resolve_variable(var)
        if len(query) > query_index + 1:
            nxt = query[query_index + 1]
            if isinstance(nxt, QIndex):
                check = nxt.index if nxt.index >= 0 else -nxt.index
                if check < len(keys):
                    keys = [keys[check]]
                else:
                    return [
                        _unresolved(
                            current,
                            f"Index {check} on the set of values returned for "
                            f"variable {var} on the join, is out of bounds. "
                            f"Length {len(keys)}",
                            query[query_index:],
                        )
                    ]
            elif not isinstance(nxt, (QAllIndices, QKey)):
                raise IncompatibleError(
                    f"This type of query variable interpolation is not supported "
                    f"{display_query(query)}"
                )
        acc: List[QueryResult] = []
        for each_key in keys:
            if each_key.tag == UNRESOLVED:
                ur = each_key.unresolved
                acc.append(
                    _unresolved(
                        current,
                        f"Keys returned for variable {var} could not completely "
                        f"resolve. Path traversed until {ur.traversed_to.self_path().disp()}"
                        f"{ur.reason or ''}",
                        query[query_index:],
                    )
                )
                continue
            kv = each_key.value
            if kv.kind == STRING:
                nxt_val = mv.values.get(kv.val)
                if nxt_val is not None:
                    acc.extend(
                        query_retrieval_with_converter(
                            query_index + 1, query, nxt_val, resolver, converter
                        )
                    )
                else:
                    acc.append(
                        _unresolved(
                            current,
                            f"Could not locate key = {kv.val} inside struct at path = "
                            f"{current.self_path().disp()}",
                            query[query_index:],
                        )
                    )
            elif kv.kind == LIST:
                for inner in kv.val:
                    if inner.kind == STRING:
                        nxt_val = mv.values.get(inner.val)
                        if nxt_val is not None:
                            acc.extend(
                                query_retrieval_with_converter(
                                    query_index + 1, query, nxt_val, resolver, converter
                                )
                            )
                        else:
                            acc.append(
                                _unresolved(
                                    current,
                                    f"Could not locate key = {inner.val} inside struct "
                                    f"at path = {inner.self_path().disp()}",
                                    query[query_index:],
                                )
                            )
                    else:
                        raise NotComparableError(
                            f"Variable projections inside Query {display_query(query)}, "
                            f"is returning a non-string value for key "
                            f"{inner.type_info()}"
                        )
            else:
                raise NotComparableError(
                    f"Variable projections inside Query {display_query(query)}, is "
                    f"returning a non-string value for key {kv.type_info()}"
                )
        return acc

    # plain key (eval_context.rs:527-576)
    val = mv.values.get(key)
    if val is not None:
        return query_retrieval_with_converter(
            query_index + 1, query, val, resolver, converter
        )
    if converter is not None:
        converted = converter(key)
        val = mv.values.get(converted)
        if val is not None:
            return query_retrieval_with_converter(
                query_index + 1, query, val, resolver, converter
            )
    else:
        for each_converter in CONVERTERS:
            candidate = mv.values.get(each_converter(key))
            if candidate is not None:
                return query_retrieval_with_converter(
                    query_index + 1, query, candidate, resolver, each_converter
                )
    return [
        _unresolved(
            current,
            f"Could not find key {key} inside struct at path {current.self_path().disp()}",
            query[query_index:],
        )
    ]


def _retrieve_all_indices(part: QAllIndices, query_index, query, current: PV, resolver, converter):
    """eval_context.rs:609-665."""
    if current.kind == LIST:
        return _accumulate(current, query_index, query, current.val, resolver, converter)
    if current.kind == MAP:
        if part.name is None:
            return query_retrieval_with_converter(
                query_index + 1, query, current, resolver, converter
            )

        def visit(index, q, key, value, ctx, conv):
            ctx.add_variable_capture_key(part.name, key)
            return query_retrieval_with_converter(index, q, value, ctx, conv)

        return _accumulate_map(current, current.val, query_index, query, resolver, converter, visit)
    # single value accepted where a list is expected (eval_context.rs:652-664)
    return query_retrieval_with_converter(
        query_index + 1, query, current, resolver, converter
    )


def _retrieve_all_values(part: QAllValues, query_index, query, current: PV, resolver, converter):
    """eval_context.rs:667-721."""
    if current.kind == LIST:
        return _accumulate(current, query_index, query, current.val, resolver, converter)
    if current.kind == MAP:
        report = part.name is not None

        def visit(index, q, key, value, ctx, conv):
            if report:
                ctx.add_variable_capture_key(part.name, key)
            return query_retrieval_with_converter(index, q, value, ctx, conv)

        return _accumulate_map(current, current.val, query_index, query, resolver, converter, visit)
    return query_retrieval_with_converter(
        query_index + 1, query, current, resolver, converter
    )


def _retrieve_filter(part: QFilter, query_index, query, current: PV, resolver, converter):
    """eval_context.rs:723-828 — filters run the clause CNF over each
    candidate; PASS selects, FAIL/SKIP drops (no UnResolved)."""
    from .evaluator import eval_conjunction_clauses, eval_guard_clause  # lazy

    conjunctions = part.conjunctions
    if current.kind == MAP:
        prev = query[query_index - 1] if query_index > 0 else None
        if isinstance(prev, (QAllValues, QAllIndices)):
            return _filter_check_delegate(
                conjunctions, part.name, query_index + 1, query, current, current,
                resolver, converter,
            )
        if isinstance(prev, QKey) or prev is None:
            mv = current.val
            if mv.is_empty():
                return []
            return _accumulate_map(
                current, mv, query_index, query, resolver, converter,
                lambda index, q, key, value, ctx, conv: _filter_check_delegate(
                    conjunctions, part.name, index, q, key, value, ctx, conv
                ),
            )
        raise InternalError(f"Filter after unexpected query part {prev!r}")

    if current.kind == LIST:
        selected: List[QueryResult] = []
        for each in current.val:
            context = f"Filter/List#{len(conjunctions)}"
            resolver.start_record(context)
            val_resolver = ValueScope(each, resolver)
            try:
                status = eval_conjunction_clauses(
                    conjunctions, val_resolver, eval_guard_clause
                )
            except Exception:
                resolver.end_record(context, RecordType(RecordType.FILTER, Status.FAIL))
                raise
            resolver.end_record(context, RecordType(RecordType.FILTER, status))
            if status == Status.PASS:
                selected.extend(
                    query_retrieval_with_converter(
                        query_index + 1, query, each, resolver, converter
                    )
                )
        return selected

    prev = query[query_index - 1] if query_index > 0 else None
    if isinstance(prev, QAllIndices):
        val_resolver = ValueScope(current, resolver)
        status = eval_conjunction_clauses(conjunctions, val_resolver, eval_guard_clause)
        if status == Status.PASS:
            return query_retrieval_with_converter(
                query_index + 1, query, current, resolver, converter
            )
        return []
    return [
        _unresolved(
            current,
            f"Filter on value type that was not a struct or array "
            f"{current.type_info()} {current.self_path().disp()}",
            query[query_index:],
        )
    ]


def _filter_check_delegate(
    conjunctions, name, index, query, key, value, eval_context, converter
):
    """check_and_delegate (eval_context.rs:268-313)."""
    from .evaluator import eval_conjunction_clauses, eval_guard_clause  # lazy

    context = f"Filter/Map#{len(conjunctions)}"
    eval_context.start_record(context)
    try:
        status = eval_conjunction_clauses(conjunctions, eval_context, eval_guard_clause)
    except Exception:
        eval_context.end_record(context, RecordType(RecordType.FILTER, Status.FAIL))
        raise
    eval_context.end_record(context, RecordType(RecordType.FILTER, status))
    if name is not None and status == Status.PASS:
        eval_context.add_variable_capture_key(name, key)
    if status == Status.PASS:
        return query_retrieval_with_converter(index, query, value, eval_context, converter)
    return []


def _retrieve_map_key_filter(
    part: QMapKeyFilter, query_index, query, current: PV, resolver, converter
):
    """`[ keys == ... ]` (eval_context.rs:830-922)."""
    from .evaluator import real_binary_operation  # lazy

    if current.kind != MAP:
        return [
            _unresolved(
                current,
                f"Map Filter for keys was not a struct {current.type_info()} "
                f"{current.self_path().disp()}",
                query[query_index:],
            )
        ]
    mv = current.val
    clause = part.clause
    cw = clause.compare_with
    if isinstance(cw, AccessQuery):
        rhs = query_retrieval_with_converter(0, cw.query, current, resolver, converter)
    elif isinstance(cw, PV):
        rhs = [QueryResult.literal(cw)]
    elif isinstance(cw, FunctionExpr):
        rhs = resolve_function(cw.name, cw.parameters, resolver)
    else:
        raise InternalError(f"Unexpected map key filter RHS {cw!r}")

    lhs = [QueryResult.resolved(k) for k in mv.keys]
    results = real_binary_operation(
        lhs, rhs, (clause.comparator, clause.comparator_inverse), "", None, resolver
    )
    selected: List[QueryResult] = []
    for qr, status in results:
        if qr.tag == RESOLVED and status == Status.PASS:
            if qr.value.kind == STRING:
                selected.append(QueryResult.resolved(mv.values[qr.value.val]))
        elif qr.tag == UNRESOLVED:
            selected.append(qr)
    extended: List[QueryResult] = []
    for each in selected:
        if each.tag == UNRESOLVED:
            extended.append(each)
        else:
            extended.extend(
                query_retrieval_with_converter(
                    query_index + 1, query, each.value, resolver, converter
                )
            )
    return extended
