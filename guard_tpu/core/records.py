"""Structured evaluation event records.

The evaluator emits a typed event tree — the engine's tracing system and
the contract all reporters consume. Mirrors the `RecordType` hierarchy of
`/root/reference/guard/src/rules/mod.rs:279-355` and the `EventRecord`
tree built by `RecordTracker` (eval_context.rs:999-1059, 41-45).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from .exprs import CmpOperator
from .qresult import QueryResult, Status


@dataclass
class NamedStatus:
    """mod.rs:262-266."""

    name: str
    status: Status
    message: Optional[str] = None


@dataclass
class BlockCheck:
    """mod.rs:255-259."""

    at_least_one_matches: bool
    status: Status
    message: Optional[str] = None


@dataclass
class TypeBlockCheck:
    """mod.rs:249-252."""

    type_name: str
    block: BlockCheck


@dataclass
class ValueCheck:
    """mod.rs:216-221."""

    from_: QueryResult
    status: Status
    message: Optional[str] = None
    custom_message: Optional[str] = None


@dataclass
class UnaryValueCheck:
    """mod.rs:224-227."""

    value: ValueCheck
    comparison: Tuple[CmpOperator, bool]


@dataclass
class ComparisonClauseCheck:
    """mod.rs:196-203."""

    comparison: Tuple[CmpOperator, bool]
    from_: QueryResult
    to: Optional[QueryResult]
    status: Status
    message: Optional[str] = None
    custom_message: Optional[str] = None


@dataclass
class InComparisonCheck:
    """mod.rs:206-213."""

    comparison: Tuple[CmpOperator, bool]
    from_: QueryResult
    to: List[QueryResult]
    status: Status
    message: Optional[str] = None
    custom_message: Optional[str] = None


@dataclass
class MissingValueCheck:
    """mod.rs:230-235."""

    rule: str
    status: Status
    message: Optional[str] = None
    custom_message: Optional[str] = None


# ClauseCheck variants (mod.rs:238-246) — each record carries `kind`
class ClauseCheck:
    SUCCESS = "Success"
    COMPARISON = "Comparison"
    IN_COMPARISON = "InComparison"
    UNARY = "Unary"
    NO_VALUE_FOR_EMPTY = "NoValueForEmptyCheck"
    DEPENDENT_RULE = "DependentRule"
    MISSING_BLOCK_VALUE = "MissingBlockValue"

    def __init__(self, kind: str, payload=None):
        self.kind = kind
        self.payload = payload

    @staticmethod
    def success() -> "ClauseCheck":
        return ClauseCheck(ClauseCheck.SUCCESS)

    @staticmethod
    def comparison(c: ComparisonClauseCheck) -> "ClauseCheck":
        return ClauseCheck(ClauseCheck.COMPARISON, c)

    @staticmethod
    def in_comparison(c: InComparisonCheck) -> "ClauseCheck":
        return ClauseCheck(ClauseCheck.IN_COMPARISON, c)

    @staticmethod
    def unary(c: UnaryValueCheck) -> "ClauseCheck":
        return ClauseCheck(ClauseCheck.UNARY, c)

    @staticmethod
    def no_value_for_empty(custom_message: Optional[str]) -> "ClauseCheck":
        return ClauseCheck(ClauseCheck.NO_VALUE_FOR_EMPTY, custom_message)

    @staticmethod
    def dependent_rule(c: MissingValueCheck) -> "ClauseCheck":
        return ClauseCheck(ClauseCheck.DEPENDENT_RULE, c)

    @staticmethod
    def missing_block_value(c: ValueCheck) -> "ClauseCheck":
        return ClauseCheck(ClauseCheck.MISSING_BLOCK_VALUE, c)

    def status(self) -> Status:
        if self.kind == ClauseCheck.SUCCESS:
            return Status.PASS
        if self.kind == ClauseCheck.NO_VALUE_FOR_EMPTY:
            return Status.FAIL
        if self.kind == ClauseCheck.UNARY:
            return self.payload.value.status
        return self.payload.status

    def custom_message(self) -> Optional[str]:
        if self.kind == ClauseCheck.SUCCESS:
            return None
        if self.kind == ClauseCheck.NO_VALUE_FOR_EMPTY:
            return self.payload
        if self.kind == ClauseCheck.UNARY:
            return self.payload.value.custom_message
        return self.payload.custom_message


class RecordType:
    """Tagged container mirroring mod.rs:279-355."""

    FILE_CHECK = "FileCheck"
    RULE_CHECK = "RuleCheck"
    RULE_CONDITION = "RuleCondition"
    TYPE_CHECK = "TypeCheck"
    TYPE_CONDITION = "TypeCondition"
    TYPE_BLOCK = "TypeBlock"
    FILTER = "Filter"
    WHEN_CHECK = "WhenCheck"
    WHEN_CONDITION = "WhenCondition"
    DISJUNCTION = "Disjunction"
    BLOCK_GUARD_CHECK = "BlockGuardCheck"
    GUARD_CLAUSE_BLOCK_CHECK = "GuardClauseBlockCheck"
    CLAUSE_VALUE_CHECK = "ClauseValueCheck"

    __slots__ = ("kind", "payload")

    def __init__(self, kind: str, payload):
        self.kind = kind
        self.payload = payload

    def status(self) -> Optional[Status]:
        k = self.kind
        if k in (RecordType.FILE_CHECK, RecordType.RULE_CHECK):
            return self.payload.status
        if k in (
            RecordType.RULE_CONDITION,
            RecordType.TYPE_CONDITION,
            RecordType.TYPE_BLOCK,
            RecordType.FILTER,
            RecordType.WHEN_CONDITION,
        ):
            return self.payload
        if k == RecordType.TYPE_CHECK:
            return self.payload.block.status
        if k in (
            RecordType.WHEN_CHECK,
            RecordType.DISJUNCTION,
            RecordType.BLOCK_GUARD_CHECK,
            RecordType.GUARD_CLAUSE_BLOCK_CHECK,
        ):
            return self.payload.status
        if k == RecordType.CLAUSE_VALUE_CHECK:
            return self.payload.status()
        return None


@dataclass
class EventRecord:
    """eval_context.rs:41-45."""

    context: str
    container: Optional[RecordType] = None
    children: List["EventRecord"] = field(default_factory=list)
