"""Error types for the guard-tpu engine.

Mirrors the error taxonomy of the reference implementation
(`/root/reference/guard/src/rules/errors.rs:11-54`) with the subset that
carries evaluation semantics: parse errors, retrieval errors and
non-comparability (the latter two drive UnResolved / FAIL outcomes in the
evaluator rather than aborting it).
"""

from __future__ import annotations


class GuardError(Exception):
    """Base class for all engine errors (errors.rs:11)."""


class ParseError(GuardError):
    """Rule-file or data-file parse failure (errors.rs ParseError)."""


class RetrievalError(GuardError):
    """A query traversal failed hard (errors.rs RetrievalError)."""


class IncompatibleRetrievalError(GuardError):
    """Traversal hit a node of the wrong shape (errors.rs:~)."""


class NotComparableError(GuardError):
    """Two values cannot be ordered/compared (errors.rs NotComparable).

    The evaluator catches this and turns it into a FAIL with a reason,
    mirroring `eval/operators.rs:195-206`.
    """


class MissingValueError(GuardError):
    """A named rule / variable / parameterized rule was not found."""


class MultipleValuesError(GuardError):
    """Input-parameter merge found a duplicate key (path_value.rs:897)."""


class IncompatibleError(GuardError):
    """Catch-all semantic incompatibility (errors.rs IncompatibleError)."""


class InternalError(GuardError):
    """Invariant violation inside the engine."""
