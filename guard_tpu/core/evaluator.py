"""The evaluation engine: CNF clause evaluation with PASS/FAIL/SKIP.

Python equivalent of `/root/reference/guard/src/rules/eval.rs` and
`/root/reference/guard/src/rules/eval/operators.rs`:

  * unary operations incl. the `empty`-on-query special case
    (eval.rs:174-405);
  * binary LHS x RHS comparison with literal/query flattening, QueryIn /
    ListIn semantics and the `not` inversion pass (operators.rs:100-787);
  * clause -> block -> rule -> file evaluation with `some`/`match_all`,
    when-condition SKIP gating, named-rule references and parameterized
    rule calls (eval.rs:1078-2065).

UnResolved query results FAIL the owning clause (with a retained reason)
rather than aborting evaluation — the semantics the TPU backend encodes
as a tri-state status lattice.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

from .errors import GuardError, IncompatibleError, NotComparableError
from .exprs import (
    AccessQuery,
    Block,
    BlockGuardClause,
    CmpOperator,
    FunctionExpr,
    GuardAccessClause,
    GuardNamedRuleClause,
    ParameterizedNamedRuleClause,
    Rule,
    RulesFile,
    TypeBlock,
    WhenBlockClause,
    display_query,
    part_is_variable,
)
from .qresult import LITERAL, RESOLVED, UNRESOLVED, QueryResult, Status, UnResolved
from .records import (
    BlockCheck,
    ClauseCheck,
    ComparisonClauseCheck,
    EventRecord,
    InComparisonCheck,
    MissingValueCheck,
    NamedStatus,
    RecordType,
    TypeBlockCheck,
    UnaryValueCheck,
    ValueCheck,
)
from .scopes import BlockScope, ValueScope, resolve_function
from .values import (
    BOOL,
    LIST,
    MAP,
    STRING,
    PV,
    compare_eq,
    compare_ge,
    compare_gt,
    compare_le,
    compare_lt,
    loose_eq,
)

# ---------------------------------------------------------------------------
# Unary operations (eval.rs:10-92)
# ---------------------------------------------------------------------------
def _exists_op(qr: QueryResult) -> bool:
    return qr.tag != UNRESOLVED


def _element_empty_op(qr: QueryResult) -> bool:
    if qr.tag == UNRESOLVED:
        return True  # !EXISTS == EMPTY (eval.rs:33-36)
    v = qr.value
    if v.kind == LIST:
        return len(v.val) == 0
    if v.kind == MAP:
        return v.val.is_empty()
    if v.kind == STRING:
        return len(v.val) == 0
    if v.kind == BOOL:
        return False  # bool -> to_string never empty (eval.rs:23)
    raise IncompatibleError(
        f"Attempting EMPTY operation on type {v.type_info()} that does not "
        f"support it at {v.self_path().s}"
    )


def _is_kind_op(kind: int):
    def op(qr: QueryResult) -> bool:
        return qr.tag != UNRESOLVED and qr.value.kind == kind

    return op


from .values import CHAR, FLOAT, INT, NULL  # noqa: E402

_UNARY_OPS = {
    CmpOperator.Exists: _exists_op,
    CmpOperator.Empty: _element_empty_op,
    CmpOperator.IsString: _is_kind_op(STRING),
    CmpOperator.IsList: _is_kind_op(LIST),
    CmpOperator.IsMap: _is_kind_op(MAP),
    CmpOperator.IsInt: _is_kind_op(INT),
    CmpOperator.IsFloat: _is_kind_op(FLOAT),
    CmpOperator.IsBool: _is_kind_op(BOOL),
    CmpOperator.IsNull: _is_kind_op(NULL),
}

# sentinel for the EmptyQueryResult evaluation outcome (eval.rs:168-171)
class EmptyQueryResult:
    __slots__ = ("status",)

    def __init__(self, status: Status):
        self.status = status


def unary_operation(
    lhs_query: List,
    cmp: Tuple[CmpOperator, bool],
    inverse: bool,
    context: str,
    custom_message: Optional[str],
    eval_context,
):
    """eval.rs:174-405."""
    lhs = eval_context.query(lhs_query)
    op, op_not = cmp

    last = lhs_query[-1]
    from .exprs import QFilter, QMapKeyFilter  # local to avoid cycle clutter

    empty_on_expr = isinstance(last, (QFilter, QMapKeyFilter)) or (
        part_is_variable(last) and len(lhs_query) == 1
    )

    if empty_on_expr and op == CmpOperator.Empty:
        # eval.rs:198-298 — EMPTY over a projection/variable: resolved
        # entries are non-empty (unless null), unresolved ones are empty
        if lhs:
            results = []
            for each in lhs:
                eval_context.start_record(context)
                if each.tag != UNRESOLVED:
                    ok = (not each.value.is_null()) if op_not else each.value.is_null()
                    qr = QueryResult.resolved(each.value)
                    status = Status.PASS if ok else Status.FAIL
                else:
                    qr = each
                    status = Status.FAIL if op_not else Status.PASS
                if inverse:
                    status = Status.PASS if status == Status.FAIL else Status.FAIL
                if status == Status.PASS:
                    eval_context.end_record(
                        context,
                        RecordType(RecordType.CLAUSE_VALUE_CHECK, ClauseCheck.success()),
                    )
                else:
                    eval_context.end_record(
                        context,
                        RecordType(
                            RecordType.CLAUSE_VALUE_CHECK,
                            ClauseCheck.unary(
                                UnaryValueCheck(
                                    value=ValueCheck(
                                        from_=qr,
                                        status=Status.FAIL,
                                        custom_message=custom_message,
                                    ),
                                    comparison=cmp,
                                )
                            ),
                        ),
                    )
                results.append((qr, status))
            return results
        result = not op_not
        if inverse:
            result = not result
        eval_context.start_record(context)
        if result:
            eval_context.end_record(
                context, RecordType(RecordType.CLAUSE_VALUE_CHECK, ClauseCheck.success())
            )
            return EmptyQueryResult(Status.PASS)
        eval_context.end_record(
            context,
            RecordType(
                RecordType.CLAUSE_VALUE_CHECK,
                ClauseCheck.no_value_for_empty(custom_message),
            ),
        )
        return EmptyQueryResult(Status.FAIL)

    if not lhs:
        # only happens when the query has filters (eval.rs:300-305)
        return EmptyQueryResult(Status.SKIP)

    base_op = _UNARY_OPS[op]

    def operation(qr: QueryResult) -> bool:
        r = base_op(qr)
        if op_not:
            r = not r
        if inverse:
            r = not r
        return r

    results = []
    for each in lhs:
        eval_context.start_record(context)
        try:
            ok = operation(each)
        except GuardError as e:
            eval_context.end_record(
                context,
                RecordType(
                    RecordType.CLAUSE_VALUE_CHECK,
                    ClauseCheck.unary(
                        UnaryValueCheck(
                            value=ValueCheck(
                                from_=each,
                                status=Status.FAIL,
                                message=str(e),
                                custom_message=custom_message,
                            ),
                            comparison=cmp,
                        )
                    ),
                ),
            )
            raise
        if ok:
            eval_context.end_record(
                context, RecordType(RecordType.CLAUSE_VALUE_CHECK, ClauseCheck.success())
            )
            results.append((each, Status.PASS))
        else:
            eval_context.end_record(
                context,
                RecordType(
                    RecordType.CLAUSE_VALUE_CHECK,
                    ClauseCheck.unary(
                        UnaryValueCheck(
                            value=ValueCheck(
                                from_=each,
                                status=Status.FAIL,
                                custom_message=custom_message,
                            ),
                            comparison=cmp,
                        )
                    ),
                ),
            )
            results.append((each, Status.FAIL))
    return results


# ---------------------------------------------------------------------------
# operators.rs — ValueEvalResult as tagged tuples:
#   ("lhs_unresolved", UnResolved)
#   ("rhs_unresolved", UnResolved, lhs_pv)
#   ("not_comparable", reason, lhs_pv, rhs_pv)
#   ("success"|"fail", compare) where compare is:
#       ("value", lhs, rhs) | ("value_in", lhs, rhs)
#       | ("list_in", diff, lhs, rhs) | ("query_in", diff, lhs_list, rhs_list)
# ---------------------------------------------------------------------------
def _selected(query_results, on_unresolved, flatten_lists=False):
    """selected()/flattened() (operators.rs:116-144)."""
    out: List[PV] = []
    for each in query_results:
        if each.tag == UNRESOLVED:
            on_unresolved(each.unresolved)
        elif flatten_lists and each.value.kind == LIST:
            out.extend(each.value.val)
        else:
            out.append(each.value)
    return out


def _match_value(lhs: PV, rhs: PV, comparator) -> tuple:
    """operators.rs:178-207."""
    try:
        ok = comparator(lhs, rhs)
    except NotComparableError as e:
        return ("not_comparable", str(e), lhs, rhs)
    return ("success", ("value", lhs, rhs)) if ok else ("fail", ("value", lhs, rhs))


def _is_literal(query_results) -> Optional[PV]:
    """operators.rs:209-216."""
    if len(query_results) == 1 and query_results[0].tag == LITERAL:
        return query_results[0].value
    return None


def _string_in(lhs: PV, rhs: PV) -> tuple:
    """operators.rs:218-230 — substring containment."""
    if lhs.kind == STRING and rhs.kind == STRING:
        ok = lhs.val in rhs.val
        return ("success", ("value", lhs, rhs)) if ok else ("fail", ("value", lhs, rhs))
    return (
        "not_comparable",
        f"Type not comparable, {lhs.type_info()}, {rhs.type_info()}",
        lhs,
        rhs,
    )


def _contained_in(lhs: PV, rhs: PV) -> tuple:
    """operators.rs:256-321."""
    if lhs.kind == LIST:
        if rhs.kind == LIST:
            rhsl = rhs.val
            if rhsl and rhsl[0].kind == LIST:
                # list-of-lists membership
                if any(loose_eq(lhs, e) for e in rhsl):
                    return ("success", ("list_in", [], lhs, rhs))
                return ("fail", ("list_in", [lhs], lhs, rhs))
            diff = [e for e in lhs.val if not any(loose_eq(e, r) for r in rhsl)]
            tag = "success" if not diff else "fail"
            return (tag, ("list_in", diff, lhs, rhs))
        return (
            "not_comparable",
            f"Can not compare type {lhs.type_info()}, {rhs.type_info()}",
            lhs,
            rhs,
        )
    if rhs.kind == LIST:
        if any(loose_eq(lhs, e) for e in rhs.val):
            return ("success", ("value_in", lhs, rhs))
        return ("fail", ("value_in", lhs, rhs))
    return _match_value(lhs, rhs, compare_eq)


def _eq_operation(lhs_results, rhs_results) -> List[tuple]:
    """EqOperation (operators.rs:453-598)."""
    results: List[tuple] = []
    l_lit = _is_literal(lhs_results)
    r_lit = _is_literal(rhs_results)

    if l_lit is not None and r_lit is not None:
        results.append(_match_value(l_lit, r_lit, compare_eq))
        return results

    if l_lit is not None:
        rhs = _selected(
            rhs_results,
            lambda ur: results.append(("rhs_unresolved", ur, l_lit)),
        )
        if l_lit.kind == LIST:
            for each in rhs:
                results.append(_match_value(l_lit, each, compare_eq))
        else:
            for each_r in rhs:
                if each_r.kind == LIST:
                    for inner in each_r.val:
                        results.append(_match_value(l_lit, inner, compare_eq))
                else:
                    results.append(_match_value(l_lit, each_r, compare_eq))
        return results

    if r_lit is not None:
        lhs_flat = _selected(
            lhs_results, lambda ur: results.append(("lhs_unresolved", ur))
        )
        if r_lit.kind == LIST:
            for each in lhs_flat:
                if each.is_scalar() and len(r_lit.val) == 1:
                    results.append(_match_value(each, r_lit.val[0], compare_eq))
                else:
                    results.append(_match_value(each, r_lit, compare_eq))
        else:
            for each in lhs_flat:
                if each.kind == LIST:
                    for inner in each.val:
                        results.append(_match_value(inner, r_lit, compare_eq))
                else:
                    results.append(_match_value(each, r_lit, compare_eq))
        return results

    # query vs query: set-difference semantics (operators.rs:552-594)
    lhs_sel = _selected(lhs_results, lambda ur: results.append(("lhs_unresolved", ur)))
    rhs_sel = _selected(
        rhs_results,
        lambda ur: results.extend(
            ("rhs_unresolved", ur, l) for l in lhs_sel
        ),
    )
    if len(lhs_sel) > len(rhs_sel):
        diff = [e for e in lhs_sel if not any(loose_eq(e, r) for r in rhs_sel)]
    else:
        diff = [e for e in rhs_sel if not any(loose_eq(e, l) for l in lhs_sel)]
    tag = "success" if not diff else "fail"
    results.append((tag, ("query_in", diff, lhs_sel, rhs_sel)))
    return results


def _in_operation(lhs_results, rhs_results) -> List[tuple]:
    """InOperation (operators.rs:323-451)."""
    results: List[tuple] = []
    l_lit = _is_literal(lhs_results)
    r_lit = _is_literal(rhs_results)

    if l_lit is not None and r_lit is not None:
        first = _string_in(l_lit, r_lit)
        if first[0] == "success":
            results.append(first)
        else:
            results.append(_contained_in(l_lit, r_lit))
        return results

    if l_lit is not None:
        rhs = _selected(
            rhs_results, lambda ur: results.append(("rhs_unresolved", ur, l_lit))
        )
        if any(e.kind == LIST for e in rhs):
            for r in rhs:
                results.append(_contained_in(l_lit, r))
        elif l_lit.kind == LIST:
            diff = [e for e in l_lit.val if not any(loose_eq(e, r) for r in rhs)]
            tag = "success" if not diff else "fail"
            results.append((tag, ("query_in", diff, [l_lit], rhs)))
        else:
            for r in rhs:
                results.append(_contained_in(l_lit, r))
        return results

    if r_lit is not None:
        lhs_sel = _selected(
            lhs_results, lambda ur: results.append(("lhs_unresolved", ur))
        )
        for l in lhs_sel:
            if r_lit.kind == STRING:
                if l.kind == LIST:
                    for inner in l.val:
                        results.append(_string_in(inner, r_lit))
                else:
                    results.append(_string_in(l, r_lit))
            else:
                results.append(_contained_in(l, r_lit))
        return results

    lhs_sel = _selected(lhs_results, lambda ur: results.append(("lhs_unresolved", ur)))
    rhs_sel = _selected(
        rhs_results,
        lambda ur: results.extend(("rhs_unresolved", ur, l) for l in lhs_sel),
    )
    diff = []
    for l in lhs_sel:
        if not any(_contained_in(l, r)[0] == "success" for r in rhs_sel):
            diff.append(l)
    tag = "success" if not diff else "fail"
    results.append((tag, ("query_in", diff, lhs_sel, rhs_sel)))
    return results


def _common_operation(lhs_results, rhs_results, comparator) -> List[tuple]:
    """CommonOperator for < <= > >= (operators.rs:146-176): flattens
    list values on both sides, full cartesian comparison."""
    results: List[tuple] = []
    lhs_flat = _selected(
        lhs_results, lambda ur: results.append(("lhs_unresolved", ur)),
        flatten_lists=True,
    )
    rhs_flat = _selected(
        rhs_results,
        lambda ur: results.extend(("rhs_unresolved", ur, l) for l in lhs_flat),
        flatten_lists=True,
    )
    for l in lhs_flat:
        for r in rhs_flat:
            results.append(_match_value(l, r, comparator))
    return results


_COMMON_CMP = {
    CmpOperator.Lt: compare_lt,
    CmpOperator.Gt: compare_gt,
    CmpOperator.Le: compare_le,
    CmpOperator.Ge: compare_ge,
}


def _reverse_diff(diff: List[PV], other: List[PV]) -> List[PV]:
    """operators.rs:637-646."""
    return [e for e in other if not any(loose_eq(e, d) for d in diff)]


def operator_compare(cmp: Tuple[CmpOperator, bool], lhs, rhs):
    """(CmpOperator, bool)::compare (operators.rs:600-787).

    Returns None for Skip, else a list of ValueEvalResult tuples with the
    `not` inversion applied.
    """
    op, negated = cmp
    if not lhs or not rhs:
        return None  # EvalResult::Skip (operators.rs:606-608)

    if op == CmpOperator.Eq:
        results = _eq_operation(lhs, rhs)
    elif op == CmpOperator.In:
        results = _in_operation(lhs, rhs)
    elif op in _COMMON_CMP:
        results = _common_operation(lhs, rhs, _COMMON_CMP[op])
    else:
        raise IncompatibleError(f"Operation {op} NOT PERMITTED")

    if not negated:
        return results

    inverted: List[tuple] = []
    for e in results:
        tag = e[0]
        if tag == "fail":
            compare = e[1]
            ckind = compare[0]
            if ckind == "query_in":
                _, diff, lhs_list, rhs_list = compare
                if len(rhs) >= len(lhs) and op == CmpOperator.Eq:
                    rdiff = _reverse_diff(diff, rhs_list)
                else:
                    rdiff = _reverse_diff(diff, lhs_list)
                new_tag = "success" if not rdiff else "fail"
                inverted.append((new_tag, ("query_in", rdiff, lhs_list, rhs_list)))
            elif ckind == "list_in":
                _, diff, l, r = compare
                rdiff = [e2 for e2 in l.val if not any(loose_eq(e2, d) for d in diff)]
                new_tag = "success" if not rdiff else "fail"
                inverted.append((new_tag, ("list_in", rdiff, l, r)))
            else:
                inverted.append(("success", compare))
        elif tag == "success":
            compare = e[1]
            ckind = compare[0]
            if ckind == "query_in":
                _, diff, lhs_list, rhs_list = compare
                inverted.append(("fail", ("query_in", list(lhs_list), lhs_list, rhs_list)))
            elif ckind == "list_in":
                _, diff, l, r = compare
                inverted.append(("fail", ("list_in", list(l.val), l, r)))
            else:
                inverted.append(("fail", compare))
        else:
            inverted.append(e)
    return inverted


# ---------------------------------------------------------------------------
# binary operation record emission (eval.rs:765-974)
# ---------------------------------------------------------------------------
def binary_operation(
    lhs_query: List,
    rhs: List[QueryResult],
    cmp: Tuple[CmpOperator, bool],
    context: str,
    custom_message: Optional[str],
    eval_context,
):
    lhs = eval_context.query(lhs_query)
    results = operator_compare(cmp, lhs, rhs)
    if results is None:
        return EmptyQueryResult(Status.SKIP)

    statuses: List[Tuple[QueryResult, Status]] = []

    def record_fail(check: ClauseCheck, qr: QueryResult):
        eval_context.start_record(context)
        eval_context.end_record(context, RecordType(RecordType.CLAUSE_VALUE_CHECK, check))
        statuses.append((qr, Status.FAIL))

    def record_pass(qr: QueryResult):
        eval_context.start_record(context)
        eval_context.end_record(
            context, RecordType(RecordType.CLAUSE_VALUE_CHECK, ClauseCheck.success())
        )
        statuses.append((qr, Status.PASS))

    for each in results:
        tag = each[0]
        if tag == "lhs_unresolved":
            ur = each[1]
            record_fail(
                ClauseCheck.comparison(
                    ComparisonClauseCheck(
                        status=Status.FAIL,
                        custom_message=custom_message,
                        comparison=cmp,
                        from_=QueryResult.unresolved_(ur),
                        to=None,
                    )
                ),
                QueryResult.unresolved_(ur),
            )
        elif tag == "rhs_unresolved":
            ur, lhs_pv = each[1], each[2]
            record_fail(
                ClauseCheck.comparison(
                    ComparisonClauseCheck(
                        status=Status.FAIL,
                        custom_message=custom_message,
                        comparison=cmp,
                        from_=QueryResult.resolved(lhs_pv),
                        to=QueryResult.unresolved_(ur),
                    )
                ),
                QueryResult.resolved(lhs_pv),
            )
        elif tag == "not_comparable":
            reason, lhs_pv, rhs_pv = each[1], each[2], each[3]
            record_fail(
                ClauseCheck.comparison(
                    ComparisonClauseCheck(
                        status=Status.FAIL,
                        message=reason,
                        custom_message=custom_message,
                        comparison=cmp,
                        from_=QueryResult.resolved(lhs_pv),
                        to=QueryResult.resolved(rhs_pv),
                    )
                ),
                QueryResult.resolved(lhs_pv),
            )
        elif tag == "success":
            compare = each[1]
            ckind = compare[0]
            if ckind == "query_in":
                for l in compare[2]:
                    record_pass(QueryResult.resolved(l))
            else:
                record_pass(QueryResult.resolved(compare[1] if ckind != "list_in" else compare[2]))
        elif tag == "fail":
            compare = each[1]
            ckind = compare[0]
            if ckind == "value":
                _, l, r = compare
                record_fail(
                    ClauseCheck.comparison(
                        ComparisonClauseCheck(
                            status=Status.FAIL,
                            custom_message=custom_message,
                            comparison=cmp,
                            from_=QueryResult.resolved(l),
                            to=QueryResult.resolved(r),
                        )
                    ),
                    QueryResult.resolved(l),
                )
            elif ckind == "value_in":
                _, l, r = compare
                record_fail(
                    ClauseCheck.in_comparison(
                        InComparisonCheck(
                            status=Status.FAIL,
                            custom_message=custom_message,
                            comparison=cmp,
                            from_=QueryResult.resolved(l),
                            to=[QueryResult.resolved(r)],
                        )
                    ),
                    QueryResult.resolved(l),
                )
            elif ckind == "list_in":
                _, diff, l, r = compare
                record_fail(
                    ClauseCheck.in_comparison(
                        InComparisonCheck(
                            status=Status.FAIL,
                            custom_message=custom_message,
                            comparison=cmp,
                            from_=QueryResult.resolved(l),
                            to=[QueryResult.resolved(r)],
                        )
                    ),
                    QueryResult.resolved(l),
                )
            else:  # query_in
                _, diff, lhs_list, rhs_list = compare
                rhs_qrs = [QueryResult.resolved(r) for r in rhs_list]
                for l in diff:
                    record_fail(
                        ClauseCheck.in_comparison(
                            InComparisonCheck(
                                status=Status.FAIL,
                                custom_message=custom_message,
                                comparison=cmp,
                                from_=QueryResult.resolved(l),
                                to=list(rhs_qrs),
                            )
                        ),
                        QueryResult.resolved(l),
                    )
    return statuses


# ---------------------------------------------------------------------------
# real_binary_operation (eval.rs:976-1075) — per-LHS-element comparison
# used by map-key filters
# ---------------------------------------------------------------------------
def _in_cmp(not_in: bool):
    """eval.rs:560-583."""

    def cmp(lhs: PV, rhs: PV) -> bool:
        if lhs.kind == STRING and rhs.kind == STRING:
            result = lhs.val in rhs.val
            return (not result) if not_in else result
        if rhs.kind == LIST:
            found = any(compare_eq(lhs, e) for e in rhs.val)
            return (not found) if not_in else found
        result = compare_eq(lhs, rhs)
        return (not result) if not_in else result

    return cmp


def _not_compare(base, invert: bool):
    def cmp(l: PV, r: PV) -> bool:
        v = base(l, r)
        return (not v) if invert else v

    return cmp


def _each_lhs_compare(cmp_fn, lhs: PV, rhs: List[QueryResult]) -> List[tuple]:
    """eval.rs:434-558."""
    statuses: List[tuple] = []
    for each_rhs in rhs:
        if each_rhs.tag == UNRESOLVED:
            statuses.append(("rhs_unresolved", each_rhs, lhs))
            continue
        rv = each_rhs.value
        try:
            outcome = cmp_fn(lhs, rv)
            statuses.append(
                ("comparable", outcome, lhs, rv)
            )
        except NotComparableError as reason:
            if lhs.kind == LIST:
                handled = True
                for inner in lhs.val:
                    try:
                        outcome = cmp_fn(inner, rv)
                        statuses.append(("comparable", outcome, inner, rv))
                    except NotComparableError as inner_reason:
                        statuses.append(("not_comparable", str(inner_reason), inner, rv))
                continue
            if lhs.is_scalar() and each_rhs.tag == LITERAL and rv.kind == LIST and len(rv.val) == 1:
                inner_rhs = rv.val[0]
                try:
                    outcome = cmp_fn(lhs, inner_rhs)
                    statuses.append(("comparable", outcome, lhs, inner_rhs))
                except NotComparableError as inner_reason:
                    statuses.append(("not_comparable", str(inner_reason), lhs, inner_rhs))
                continue
            statuses.append(("not_comparable", str(reason), lhs, rv))
    return statuses


def real_binary_operation(
    lhs: List[QueryResult],
    rhs: List[QueryResult],
    cmp: Tuple[CmpOperator, bool],
    context: str,
    custom_message: Optional[str],
    eval_context,
) -> List[Tuple[QueryResult, Status]]:
    statuses: List[Tuple[QueryResult, Status]] = []
    op, negated = cmp
    if op == CmpOperator.Eq and len(rhs) > 1:
        op = CmpOperator.In  # eval.rs:986-990

    for each in lhs:
        if each.tag == UNRESOLVED:
            eval_context.start_record(context)
            eval_context.end_record(
                context,
                RecordType(
                    RecordType.CLAUSE_VALUE_CHECK,
                    ClauseCheck.comparison(
                        ComparisonClauseCheck(
                            status=Status.FAIL,
                            custom_message=custom_message,
                            comparison=(op, negated),
                            from_=each,
                            to=None,
                        )
                    ),
                ),
            )
            statuses.append((each, Status.FAIL))
            continue

        l = each.value
        if op == CmpOperator.Eq:
            r = _each_lhs_compare(_not_compare(compare_eq, negated), l, rhs)
        elif op == CmpOperator.Ge:
            r = _each_lhs_compare(_not_compare(compare_ge, negated), l, rhs)
        elif op == CmpOperator.Gt:
            r = _each_lhs_compare(_not_compare(compare_gt, negated), l, rhs)
        elif op == CmpOperator.Lt:
            r = _each_lhs_compare(_not_compare(compare_lt, negated), l, rhs)
        elif op == CmpOperator.Le:
            r = _each_lhs_compare(_not_compare(compare_le, negated), l, rhs)
        elif op == CmpOperator.In:
            r = _each_lhs_compare(_in_cmp(negated), l, rhs)
        else:
            raise IncompatibleError(f"Operation {op} NOT PERMITTED")

        if op == CmpOperator.In:
            statuses.extend(
                _report_at_least_one(r, (op, negated), context, custom_message, eval_context)
            )
        else:
            statuses.extend(
                _report_all_values(r, (op, negated), context, custom_message, eval_context)
            )
    return statuses


def _report_all_values(comparisons, cmp, context, custom_message, eval_context):
    """eval.rs:653-671 + report_value (eval.rs:585-651)."""
    out: List[Tuple[QueryResult, Status]] = []
    for each in comparisons:
        tag = each[0]
        if tag == "comparable":
            _, outcome, l, r = each
            lhs_qr = QueryResult.resolved(l)
            rhs_qr = QueryResult.resolved(r)
        elif tag == "not_comparable":
            _, reason, l, r = each
            outcome = False
            lhs_qr = QueryResult.resolved(l)
            rhs_qr = QueryResult.resolved(r)
        else:  # rhs_unresolved
            _, rhs_q, l = each
            outcome = False
            lhs_qr = QueryResult.resolved(l)
            rhs_qr = rhs_q
        eval_context.start_record(context)
        if outcome:
            eval_context.end_record(
                context, RecordType(RecordType.CLAUSE_VALUE_CHECK, ClauseCheck.success())
            )
            out.append((lhs_qr, Status.PASS))
        else:
            eval_context.end_record(
                context,
                RecordType(
                    RecordType.CLAUSE_VALUE_CHECK,
                    ClauseCheck.comparison(
                        ComparisonClauseCheck(
                            from_=lhs_qr,
                            comparison=cmp,
                            to=rhs_qr,
                            custom_message=custom_message,
                            status=Status.FAIL,
                        )
                    ),
                ),
            )
            out.append((lhs_qr, Status.FAIL))
    return out


def _report_at_least_one(comparisons, cmp, context, custom_message, eval_context):
    """eval.rs:673-753 — group by LHS; PASS if any rhs matched."""
    out: List[Tuple[QueryResult, Status]] = []
    by_lhs: List[Tuple[PV, List[tuple]]] = []

    def entry_for(l: PV) -> List[tuple]:
        for existing, bucket in by_lhs:
            if existing is l:
                return bucket
        bucket: List[tuple] = []
        by_lhs.append((l, bucket))
        return bucket

    for each in comparisons:
        tag = each[0]
        if tag == "comparable":
            entry_for(each[2]).append((each, QueryResult.resolved(each[3])))
        elif tag == "not_comparable":
            entry_for(each[2]).append((each, QueryResult.resolved(each[3])))
        else:  # rhs_unresolved
            entry_for(each[2]).append((each, each[1]))

    for l, bucket in by_lhs:
        found = any(
            e[0] == "comparable" and e[1] for (e, _rhs) in bucket
        )
        eval_context.start_record(context)
        if found:
            eval_context.end_record(
                context, RecordType(RecordType.CLAUSE_VALUE_CHECK, ClauseCheck.success())
            )
            out.append((QueryResult.resolved(l), Status.PASS))
        else:
            to_collected = [rhs for (_e, rhs) in bucket]
            eval_context.end_record(
                context,
                RecordType(
                    RecordType.CLAUSE_VALUE_CHECK,
                    ClauseCheck.in_comparison(
                        InComparisonCheck(
                            from_=QueryResult.resolved(l),
                            to=to_collected,
                            custom_message=custom_message,
                            status=Status.FAIL,
                            comparison=cmp,
                        )
                    ),
                ),
            )
            out.append((QueryResult.resolved(l), Status.FAIL))
    return out


# ---------------------------------------------------------------------------
# Clause evaluation (eval.rs:1078-1225)
# ---------------------------------------------------------------------------
def eval_guard_access_clause(gac: GuardAccessClause, resolver) -> Status:
    all_match = gac.access_clause.query.match_all
    display = gac.display()
    blk_context = f"GuardAccessClause#block{display}"
    resolver.start_record(blk_context)

    cmp = (gac.access_clause.comparator, gac.access_clause.comparator_inverse)
    try:
        if gac.access_clause.comparator.is_unary():
            statuses = unary_operation(
                gac.access_clause.query.query,
                cmp,
                gac.negation,
                display,
                gac.access_clause.custom_message,
                resolver,
            )
        else:
            cw = gac.access_clause.compare_with
            if cw is None:
                resolver.end_record(
                    blk_context,
                    RecordType(
                        RecordType.GUARD_CLAUSE_BLOCK_CHECK,
                        BlockCheck(
                            status=Status.FAIL,
                            at_least_one_matches=not all_match,
                            message="Error not RHS for binary clause when handling clause, bailing",
                        ),
                    ),
                )
                raise NotComparableError(
                    f"GuardAccessClause {blk_context}, did not have a RHS for compare operation"
                )
            if isinstance(cw, PV):
                rhs = [QueryResult.literal(cw)]
            elif isinstance(cw, AccessQuery):
                rhs = resolver.query(cw.query)
            elif isinstance(cw, FunctionExpr):
                rhs = resolve_function(cw.name, cw.parameters, resolver)
            else:
                raise IncompatibleError(f"Unexpected RHS {cw!r}")
            statuses = binary_operation(
                gac.access_clause.query.query,
                rhs,
                cmp,
                display,
                gac.access_clause.custom_message,
                resolver,
            )
    except GuardError as e:
        resolver.end_record(
            blk_context,
            RecordType(
                RecordType.GUARD_CLAUSE_BLOCK_CHECK,
                BlockCheck(
                    status=Status.FAIL,
                    at_least_one_matches=not all_match,
                    message=f"Error {e} when handling clause, bailing",
                ),
            ),
        )
        raise

    if isinstance(statuses, EmptyQueryResult):
        status = statuses.status
        resolver.end_record(
            blk_context,
            RecordType(
                RecordType.GUARD_CLAUSE_BLOCK_CHECK,
                BlockCheck(status=status, at_least_one_matches=all_match, message=None),
            ),
        )
        return status

    fails = sum(1 for (_v, s) in statuses if s == Status.FAIL)
    passes = sum(1 for (_v, s) in statuses if s == Status.PASS)
    if all_match:
        outcome = Status.FAIL if fails > 0 else Status.PASS
    else:
        outcome = Status.PASS if passes > 0 else Status.FAIL
    resolver.end_record(
        blk_context,
        RecordType(
            RecordType.GUARD_CLAUSE_BLOCK_CHECK,
            BlockCheck(status=outcome, at_least_one_matches=not all_match, message=None),
        ),
    )
    return outcome


def eval_guard_named_clause(gnc: GuardNamedRuleClause, resolver) -> Status:
    """eval.rs:1227-1289."""
    context = gnc.display()
    resolver.start_record(context)
    try:
        status = resolver.rule_status(gnc.dependent_rule)
    except GuardError as e:
        resolver.end_record(
            context,
            RecordType(
                RecordType.CLAUSE_VALUE_CHECK,
                ClauseCheck.dependent_rule(
                    MissingValueCheck(
                        rule=gnc.dependent_rule,
                        status=Status.FAIL,
                        message=f"{context} failed due to error {e}",
                        custom_message=gnc.custom_message,
                    )
                ),
            ),
        )
        raise
    if status == Status.PASS:
        outcome = Status.FAIL if gnc.negation else Status.PASS
    else:
        outcome = Status.PASS if gnc.negation else Status.FAIL
    if outcome == Status.PASS:
        resolver.end_record(
            context, RecordType(RecordType.CLAUSE_VALUE_CHECK, ClauseCheck.success())
        )
    else:
        resolver.end_record(
            context,
            RecordType(
                RecordType.CLAUSE_VALUE_CHECK,
                ClauseCheck.dependent_rule(
                    MissingValueCheck(
                        rule=gnc.dependent_rule,
                        status=Status.FAIL,
                        custom_message=gnc.custom_message,
                    )
                ),
            ),
        )
    return outcome


def eval_general_block_clause(
    block: Block,
    resolver,
    eval_fn,
    context: str = "cfn_guard::rules::exprs::GuardClause#disjunction",
) -> Status:
    """eval.rs:1291-1301."""
    scope = BlockScope(block, resolver.root(), resolver)
    return eval_conjunction_clauses(block.conjunctions, scope, eval_fn, context)


def eval_guard_block_clause(block_clause: BlockGuardClause, resolver) -> Status:
    """eval.rs:1303-1426."""
    context = f"BlockGuardClause#{block_clause.location}"
    match_all = block_clause.query.match_all
    resolver.start_record(context)
    try:
        block_values = resolver.query(block_clause.query.query)
    except GuardError:
        resolver.end_record(
            context,
            RecordType(
                RecordType.BLOCK_GUARD_CHECK,
                BlockCheck(status=Status.FAIL, at_least_one_matches=not match_all),
            ),
        )
        raise
    if not block_values:
        status = Status.FAIL if block_clause.not_empty else Status.SKIP
        resolver.end_record(
            context,
            RecordType(
                RecordType.BLOCK_GUARD_CHECK,
                BlockCheck(status=status, at_least_one_matches=not match_all),
            ),
        )
        return status

    fails = passes = 0
    for each in block_values:
        if each.tag == UNRESOLVED:
            fails += 1
            ur = each.unresolved
            guard_cxt = f"GuardBlockAccessClause#{block_clause.location}"
            resolver.start_record(guard_cxt)
            resolver.end_record(
                guard_cxt,
                RecordType(
                    RecordType.CLAUSE_VALUE_CHECK,
                    ClauseCheck.missing_block_value(
                        ValueCheck(
                            from_=each,
                            status=Status.FAIL,
                            message=(
                                f"Query {display_query(block_clause.query.query)} did not "
                                f"resolve to correct value, reason {ur.reason or ''}"
                            ),
                        )
                    ),
                ),
            )
            continue
        val_resolver = ValueScope(each.value, resolver)
        try:
            status = eval_general_block_clause(
                block_clause.block, val_resolver, eval_guard_clause
            )
        except GuardError as e:
            resolver.end_record(
                context,
                RecordType(
                    RecordType.BLOCK_GUARD_CHECK,
                    BlockCheck(
                        status=Status.FAIL,
                        at_least_one_matches=not match_all,
                        message=f"Error {e} when handling block clause, bailing",
                    ),
                ),
            )
            raise
        if status == Status.PASS:
            passes += 1
        elif status == Status.FAIL:
            fails += 1

    if match_all:
        status = (
            Status.FAIL if fails > 0 else Status.PASS if passes > 0 else Status.SKIP
        )
    else:
        status = (
            Status.PASS if passes > 0 else Status.FAIL if fails > 0 else Status.SKIP
        )
    resolver.end_record(
        context,
        RecordType(
            RecordType.BLOCK_GUARD_CHECK,
            BlockCheck(status=status, at_least_one_matches=not match_all),
        ),
    )
    return status


def eval_when_condition_block(context: str, conditions, block: Block, resolver) -> Status:
    """eval.rs:1428-1502."""
    resolver.start_record(context)
    when_context = f"{context}/When"
    resolver.start_record(when_context)
    try:
        status = eval_conjunction_clauses(
            conditions, resolver, eval_when_clause, context="cfn_guard::rules::exprs::WhenGuardClause#disjunction"
        )
    except GuardError as e:
        resolver.end_record(when_context, RecordType(RecordType.WHEN_CONDITION, Status.FAIL))
        resolver.end_record(
            context,
            RecordType(
                RecordType.WHEN_CHECK,
                BlockCheck(
                    status=Status.FAIL,
                    at_least_one_matches=False,
                    message=f"Error {e} during type condition evaluation, bailing",
                ),
            ),
        )
        raise
    if status != Status.PASS:
        resolver.end_record(when_context, RecordType(RecordType.WHEN_CONDITION, status))
        resolver.end_record(
            context,
            RecordType(
                RecordType.WHEN_CHECK,
                BlockCheck(status=Status.SKIP, at_least_one_matches=False),
            ),
        )
        return Status.SKIP
    resolver.end_record(when_context, RecordType(RecordType.WHEN_CONDITION, Status.PASS))

    try:
        status = eval_general_block_clause(block, resolver, eval_guard_clause)
    except GuardError as e:
        resolver.end_record(
            context,
            RecordType(
                RecordType.WHEN_CHECK,
                BlockCheck(
                    status=Status.FAIL,
                    at_least_one_matches=False,
                    message=f"Error {e} during type condition evaluation, bailing",
                ),
            ),
        )
        raise
    resolver.end_record(
        context,
        RecordType(RecordType.WHEN_CHECK, BlockCheck(status=status, at_least_one_matches=False)),
    )
    return status


class _ResolvedParameterContext:
    """eval.rs:1504-1572 — overlays resolved call parameters over the
    parent scope and rewrites the called rule's RuleCheck message."""

    def __init__(self, call_rule: ParameterizedNamedRuleClause, resolved_parameters, parent):
        self.call_rule = call_rule
        self.resolved_parameters = resolved_parameters
        self.parent = parent

    def query(self, query):
        return self.parent.query(query)

    def find_parameterized_rule(self, rule_name):
        return self.parent.find_parameterized_rule(rule_name)

    def root(self):
        return self.parent.root()

    def rule_status(self, rule_name):
        return self.parent.rule_status(rule_name)

    def resolve_variable(self, variable_name):
        if variable_name in self.resolved_parameters:
            return list(self.resolved_parameters[variable_name])
        return self.parent.resolve_variable(variable_name)

    def add_variable_capture_key(self, variable_name, key):
        self.parent.add_variable_capture_key(variable_name, key)

    def start_record(self, context):
        self.parent.start_record(context)

    def end_record(self, context, record: RecordType):
        if (
            record.kind == RecordType.RULE_CHECK
            and record.payload.name == self.call_rule.named_rule.dependent_rule
        ):
            record = RecordType(
                RecordType.RULE_CHECK,
                NamedStatus(
                    name=record.payload.name,
                    status=record.payload.status,
                    message=self.call_rule.named_rule.custom_message,
                ),
            )
        self.parent.end_record(context, record)


def eval_parameterized_rule_call(call_rule: ParameterizedNamedRuleClause, resolver) -> Status:
    """eval.rs:1574-1618."""
    param_rule = resolver.find_parameterized_rule(call_rule.named_rule.dependent_rule)
    if len(param_rule.parameter_names) != len(call_rule.parameters):
        raise IncompatibleError(
            f"Arity mismatch for called parameter rule "
            f"{call_rule.named_rule.dependent_rule}, expected "
            f"{len(param_rule.parameter_names)}, got {len(call_rule.parameters)}"
        )
    resolved = {}
    for idx, each in enumerate(call_rule.parameters):
        name = param_rule.parameter_names[idx]
        if isinstance(each, PV):
            resolved[name] = [QueryResult.resolved(each)]
        elif isinstance(each, AccessQuery):
            resolved[name] = resolver.query(each.query)
        elif isinstance(each, FunctionExpr):
            resolved[name] = resolve_function(each.name, each.parameters, resolver)
        else:
            raise IncompatibleError(f"Unexpected parameter {each!r}")
    ctx = _ResolvedParameterContext(call_rule, resolved, resolver)
    return eval_rule(param_rule.rule, ctx)


def eval_guard_clause(gc, resolver) -> Status:
    """eval.rs:1620-1636."""
    if isinstance(gc, GuardAccessClause):
        return eval_guard_access_clause(gc, resolver)
    if isinstance(gc, GuardNamedRuleClause):
        return eval_guard_named_clause(gc, resolver)
    if isinstance(gc, BlockGuardClause):
        return eval_guard_block_clause(gc, resolver)
    if isinstance(gc, WhenBlockClause):
        return eval_when_condition_block(
            "GuardConditionClause", gc.conditions, gc.block, resolver
        )
    if isinstance(gc, ParameterizedNamedRuleClause):
        return eval_parameterized_rule_call(gc, resolver)
    raise IncompatibleError(f"Unknown guard clause {gc!r}")


def eval_when_clause(wc, resolver) -> Status:
    """eval.rs:1638-1647."""
    if isinstance(wc, GuardAccessClause):
        return eval_guard_access_clause(wc, resolver)
    if isinstance(wc, GuardNamedRuleClause):
        return eval_guard_named_clause(wc, resolver)
    if isinstance(wc, ParameterizedNamedRuleClause):
        return eval_parameterized_rule_call(wc, resolver)
    raise IncompatibleError(f"Unknown when clause {wc!r}")


def eval_type_block_clause(type_block: TypeBlock, resolver) -> Status:
    """eval.rs:1649-1822."""
    context = f"TypeBlock#{type_block.type_name}"
    resolver.start_record(context)
    block = type_block.block
    if type_block.conditions is not None:
        when_context = f"TypeBlock#{type_block.type_name}/When"
        resolver.start_record(when_context)
        try:
            status = eval_conjunction_clauses(
                type_block.conditions,
                resolver,
                eval_when_clause,
                context="cfn_guard::rules::exprs::WhenGuardClause#disjunction",
            )
        except GuardError as e:
            resolver.end_record(
                when_context, RecordType(RecordType.TYPE_CONDITION, Status.FAIL)
            )
            resolver.end_record(
                context,
                RecordType(
                    RecordType.TYPE_CHECK,
                    TypeBlockCheck(
                        type_name=type_block.type_name,
                        block=BlockCheck(
                            status=Status.FAIL,
                            at_least_one_matches=False,
                            message=f"Error {e} during type condition evaluation, bailing",
                        ),
                    ),
                ),
            )
            raise
        if status != Status.PASS:
            resolver.end_record(when_context, RecordType(RecordType.TYPE_CONDITION, status))
            resolver.end_record(
                context,
                RecordType(
                    RecordType.TYPE_CHECK,
                    TypeBlockCheck(
                        type_name=type_block.type_name,
                        block=BlockCheck(status=Status.SKIP, at_least_one_matches=False),
                    ),
                ),
            )
            return Status.SKIP
        resolver.end_record(when_context, RecordType(RecordType.TYPE_CONDITION, Status.PASS))

    try:
        values = resolver.query(type_block.query)
    except GuardError:
        resolver.end_record(
            context,
            RecordType(
                RecordType.TYPE_CHECK,
                TypeBlockCheck(
                    type_name=type_block.type_name,
                    block=BlockCheck(status=Status.FAIL, at_least_one_matches=False),
                ),
            ),
        )
        raise
    if not values:
        resolver.end_record(
            context,
            RecordType(
                RecordType.TYPE_CHECK,
                TypeBlockCheck(
                    type_name=type_block.type_name,
                    block=BlockCheck(status=Status.SKIP, at_least_one_matches=False),
                ),
            ),
        )
        return Status.SKIP

    fails = passes = 0
    for idx, each in enumerate(values):
        if each.tag == UNRESOLVED:
            resolver.end_record(
                context,
                RecordType(
                    RecordType.TYPE_CHECK,
                    TypeBlockCheck(
                        type_name=type_block.type_name,
                        block=BlockCheck(
                            status=Status.FAIL,
                            at_least_one_matches=False,
                            message=each.unresolved.reason,
                        ),
                    ),
                ),
            )
            from .errors import MissingValueError

            raise MissingValueError(
                f"Unable to resolve type block query: {type_block.type_name}"
            )
        block_context = f"{context}/{idx}"
        resolver.start_record(block_context)
        val_resolver = ValueScope(each.value, resolver)
        try:
            status = eval_general_block_clause(block, val_resolver, eval_guard_clause)
        except GuardError as e:
            resolver.end_record(block_context, RecordType(RecordType.TYPE_BLOCK, Status.FAIL))
            resolver.end_record(
                context,
                RecordType(
                    RecordType.TYPE_CHECK,
                    TypeBlockCheck(
                        type_name=type_block.type_name,
                        block=BlockCheck(
                            status=Status.FAIL,
                            at_least_one_matches=False,
                            message=f"Error {e} during type block evaluation, bailing",
                        ),
                    ),
                ),
            )
            raise
        resolver.end_record(block_context, RecordType(RecordType.TYPE_BLOCK, status))
        if status == Status.PASS:
            passes += 1
        elif status == Status.FAIL:
            fails += 1

    status = Status.FAIL if fails > 0 else Status.PASS if passes > 0 else Status.SKIP
    resolver.end_record(
        context,
        RecordType(
            RecordType.TYPE_CHECK,
            TypeBlockCheck(
                type_name=type_block.type_name,
                block=BlockCheck(status=status, at_least_one_matches=False),
            ),
        ),
    )
    return status


def eval_rule_clause(rule_clause, resolver) -> Status:
    """eval.rs:1824-1835."""
    if isinstance(rule_clause, TypeBlock):
        return eval_type_block_clause(rule_clause, resolver)
    if isinstance(rule_clause, WhenBlockClause):
        return eval_when_condition_block(
            "RuleClause", rule_clause.conditions, rule_clause.block, resolver
        )
    return eval_guard_clause(rule_clause, resolver)


def eval_rule(rule: Rule, resolver) -> Status:
    """eval.rs:1837-1906."""
    context = rule.rule_name
    resolver.start_record(context)
    if rule.conditions is not None:
        when_context = f"Rule#{context}/When"
        resolver.start_record(when_context)
        try:
            status = eval_conjunction_clauses(
                rule.conditions,
                resolver,
                eval_when_clause,
                context="cfn_guard::rules::exprs::WhenGuardClause#disjunction",
            )
        except GuardError:
            resolver.end_record(when_context, RecordType(RecordType.RULE_CONDITION, Status.FAIL))
            resolver.end_record(
                context,
                RecordType(
                    RecordType.RULE_CHECK,
                    NamedStatus(name=rule.rule_name, status=Status.FAIL),
                ),
            )
            raise
        if status != Status.PASS:
            resolver.end_record(when_context, RecordType(RecordType.RULE_CONDITION, status))
            resolver.end_record(
                context,
                RecordType(
                    RecordType.RULE_CHECK,
                    NamedStatus(name=rule.rule_name, status=Status.SKIP),
                ),
            )
            return Status.SKIP
        resolver.end_record(when_context, RecordType(RecordType.RULE_CONDITION, Status.PASS))

    try:
        status = eval_general_block_clause(
            rule.block,
            resolver,
            eval_rule_clause,
            context="cfn_guard::rules::exprs::RuleClause#disjunction",
        )
    except GuardError:
        resolver.end_record(
            context,
            RecordType(
                RecordType.RULE_CHECK, NamedStatus(name=rule.rule_name, status=Status.FAIL)
            ),
        )
        raise
    resolver.end_record(
        context,
        RecordType(RecordType.RULE_CHECK, NamedStatus(name=rule.rule_name, status=status)),
    )
    return status


def eval_rules_file(
    rules_file: RulesFile, resolver, data_file_name: Optional[str] = None
) -> Status:
    """eval.rs:1915-1968."""
    context = f"File(rules={len(rules_file.guard_rules)})"
    resolver.start_record(context)
    fails = passes = 0
    for each_rule in rules_file.guard_rules:
        try:
            status = eval_rule(each_rule, resolver)
        except GuardError:
            resolver.end_record(
                context,
                RecordType(
                    RecordType.RULE_CHECK,
                    NamedStatus(name=each_rule.rule_name, status=Status.FAIL),
                ),
            )
            raise
        if status == Status.PASS:
            passes += 1
        elif status == Status.FAIL:
            fails += 1
    overall = Status.FAIL if fails > 0 else Status.PASS if passes > 0 else Status.SKIP
    resolver.end_record(
        context,
        RecordType(
            RecordType.FILE_CHECK,
            NamedStatus(name=data_file_name or "", status=overall),
        ),
    )
    return overall


def eval_conjunction_clauses(
    conjunctions,
    resolver,
    eval_fn,
    context: str = "cfn_guard::rules::exprs::GuardClause#disjunction",
) -> Status:
    """eval.rs:1971-2065 — AND over conjunctions, OR within each;
    SKIPs don't count either way. The context embeds the reference's
    generic type name (eval.rs:1982 uses std::any::type_name::<T>()),
    which reporters pin byte-for-byte."""
    num_passes = num_fails = 0
    for conjunction in conjunctions:
        num_of_disjunction_fails = 0
        multiple_ors = len(conjunction) > 1
        if multiple_ors:
            resolver.start_record(context)
        passed = False
        for disjunction in conjunction:
            try:
                status = eval_fn(disjunction, resolver)
            except GuardError as e:
                if multiple_ors:
                    resolver.end_record(
                        context,
                        RecordType(
                            RecordType.DISJUNCTION,
                            BlockCheck(
                                status=Status.FAIL,
                                at_least_one_matches=True,
                                message=f"Disjunction failed due to error {e}, bailing",
                            ),
                        ),
                    )
                raise
            if status == Status.PASS:
                num_passes += 1
                if multiple_ors:
                    resolver.end_record(
                        context,
                        RecordType(
                            RecordType.DISJUNCTION,
                            BlockCheck(status=Status.PASS, at_least_one_matches=True),
                        ),
                    )
                passed = True
                break
            if status == Status.FAIL:
                num_of_disjunction_fails += 1
        if passed:
            continue
        if num_of_disjunction_fails > 0:
            num_fails += 1
        if multiple_ors:
            resolver.end_record(
                context,
                RecordType(
                    RecordType.DISJUNCTION,
                    BlockCheck(
                        status=Status.FAIL if num_of_disjunction_fails > 0 else Status.SKIP,
                        at_least_one_matches=True,
                    ),
                ),
            )
    if num_fails > 0:
        return Status.FAIL
    if num_passes > 0:
        return Status.PASS
    return Status.SKIP
