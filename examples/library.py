"""Minimal embedding example for the guard-tpu library API.

Equivalent of the reference's library example
(/root/reference/guard-examples/library/src/main.rs:22-45): build a
Validate command programmatically, feed a payload through an injected
reader, and capture structured output — no files, no CLI.

Run: python examples/library.py
"""

import json

import guard_tpu
from guard_tpu.api import ValidateBuilder
from guard_tpu.utils.io import Reader, Writer

RULES = """
rule s3_bucket_server_side_encryption {
    Resources.*[ Type == 'AWS::S3::Bucket' ] {
        Properties.BucketEncryption exists
    }
}
"""

TEMPLATE = json.dumps(
    {
        "Resources": {
            "logs": {
                "Type": "AWS::S3::Bucket",
                "Properties": {"BucketEncryption": {"ServerSideEncryptionConfiguration": []}},
            },
            "scratch": {"Type": "AWS::S3::Bucket", "Properties": {}},
        }
    }
)


def one_shot() -> None:
    """run_checks: single (data, rules) pair -> JSON report string."""
    report = guard_tpu.run_checks(TEMPLATE, RULES)
    print("run_checks ->")
    print(json.dumps(json.loads(report), indent=2)[:400], "...")


def builder_payload() -> None:
    """ValidateBuilder payload mode (the wasm/npm entry in the
    reference, lib.rs:318-347): rules+data from one JSON payload."""
    payload = json.dumps({"rules": [RULES], "data": [TEMPLATE]})
    cmd = (
        ValidateBuilder()
        .payload(True)
        .structured(True)
        .output_format("json")
        .show_summary(["none"])
        .try_build()
    )
    writer = Writer.buffered()
    code = cmd.execute(writer, Reader.from_string(payload))
    print(f"builder payload exit code: {code}")
    print(writer.stripped()[:400], "...")


if __name__ == "__main__":
    one_shot()
    builder_payload()
