"""Bench-artifact hygiene (VERDICT r5 Weak #3): the committed bench
artifact must contain every metric row the CURRENT bench driver emits,
and tools/check_bench_schema.py must flag artifacts that don't."""

import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tools"))

import bench  # noqa: E402
import check_bench_schema  # noqa: E402


def _newest_artifact():
    candidates = sorted(REPO.glob("bench_all_*.json"),
                        key=check_bench_schema.artifact_order)
    assert candidates, "no committed bench_all_*.json artifact"
    return candidates[-1]


def test_committed_artifact_matches_current_driver():
    problems = check_bench_schema.check(_newest_artifact())
    assert problems == [], "\n".join(problems)


def test_checker_flags_missing_metric(tmp_path):
    src = _newest_artifact().read_text().splitlines()
    victim = bench.expected_metrics()[0]
    doctored = tmp_path / "bench_all_doctored.json"
    doctored.write_text(
        "\n".join(ln for ln in src if f'"{victim}"' not in ln) + "\n"
    )
    problems = check_bench_schema.check(doctored)
    assert any(victim in p for p in problems)


def test_expected_metrics_cover_fail_heavy_batch_rows():
    metrics = bench.expected_metrics()
    for tag in ("50pct", "allfail"):
        for nd in bench.FAIL_HEAVY_BATCH_SIZES:
            assert (
                f"config6_fail_{tag}_docs{nd}_full_docs_per_sec" in metrics
            )
    assert "config5b_packed_templates_per_sec" in metrics


def test_expected_metrics_cover_ingest_rows():
    """PR 3: the ingest-plane decomposition rows (workers=1 vs 2, for
    the registry and fail-heavy corpora) are part of the driver
    contract and gated by the schema checker."""
    metrics = bench.expected_metrics()
    for m in (
        "config5b_ingest_workers1_templates_per_sec",
        "config5b_ingest_workers2_templates_per_sec",
        "config6_ingest_workers1_docs_per_sec",
        "config6_ingest_workers2_docs_per_sec",
    ):
        assert m in metrics


def test_checker_requires_ingest_decomposition_keys(tmp_path):
    """An ingest row missing its decomposition extras fails the gate."""
    row = {
        "metric": "config5b_ingest_workers2_templates_per_sec",
        "value": 1.0,
        "unit": "templates/sec",
        "vs_baseline": 1.0,
        "workers": 2,
        # read_parse/encode/pipeline_stall keys intentionally missing
    }
    src = _newest_artifact().read_text().splitlines()
    doctored = tmp_path / "bench_all_doctored_ingest.json"
    doctored.write_text(
        "\n".join(
            ln for ln in src
            if '"config5b_ingest_workers2_templates_per_sec"' not in ln
        )
        + "\n"
        + __import__("json").dumps(row)
        + "\n"
    )
    problems = check_bench_schema.check(doctored)
    assert any("read_parse_seconds_per_run" in p for p in problems)
    assert any("pipeline_stall_seconds_per_run" in p for p in problems)


def test_expected_metrics_cover_quarantine_rows():
    """PR 5: the failure-plane overhead rows (clean quarantine cost vs
    fail-fast, degraded-run throughput) are part of the driver
    contract and gated by the schema checker."""
    metrics = bench.expected_metrics()
    assert "config5b_quarantine_clean_templates_per_sec" in metrics
    assert "config5b_quarantine_degraded_templates_per_sec" in metrics


def test_checker_requires_quarantine_keys(tmp_path):
    """A degraded-run row missing its recovery counters fails the
    gate."""
    row = {
        "metric": "config5b_quarantine_degraded_templates_per_sec",
        "value": 1.0,
        "unit": "templates/sec",
        "vs_baseline": 1.0,
        "poisoned_docs": 8,
        # quarantined_docs / retries / dispatch_fallbacks missing
    }
    src = _newest_artifact().read_text().splitlines()
    doctored = tmp_path / "bench_all_doctored_quarantine.json"
    doctored.write_text(
        "\n".join(
            ln for ln in src
            if '"config5b_quarantine_degraded_templates_per_sec"'
            not in ln
        )
        + "\n"
        + __import__("json").dumps(row)
        + "\n"
    )
    problems = check_bench_schema.check(doctored)
    assert any("quarantined_docs" in p for p in problems)
    assert any("dispatch_fallbacks" in p for p in problems)


def test_expected_metrics_cover_telemetry_rows():
    """PR 6: the telemetry on/off overhead row pair is part of the
    driver contract and gated by the schema checker."""
    metrics = bench.expected_metrics()
    assert "config5b_telemetry_off_templates_per_sec" in metrics
    assert "config5b_telemetry_on_templates_per_sec" in metrics


def test_checker_requires_telemetry_overhead_keys(tmp_path):
    """A telemetry-on row that doesn't quantify its overhead against
    the disabled branch fails the gate."""
    row = {
        "metric": "config5b_telemetry_on_templates_per_sec",
        "value": 1.0,
        "unit": "templates/sec",
        "vs_baseline": 1.0,
        "telemetry": "enabled",
        # overhead_vs_off / spans_recorded_per_run missing
    }
    src = _newest_artifact().read_text().splitlines()
    doctored = tmp_path / "bench_all_doctored_telemetry.json"
    doctored.write_text(
        "\n".join(
            ln for ln in src
            if '"config5b_telemetry_on_templates_per_sec"' not in ln
        )
        + "\n"
        + __import__("json").dumps(row)
        + "\n"
    )
    problems = check_bench_schema.check(doctored)
    assert any("overhead_vs_off" in p for p in problems)
    assert any("spans_recorded_per_run" in p for p in problems)


def test_expected_metrics_cover_plan_cache_rows():
    """PR 7: the plan-artifact-layer regime rows (cold re-lower, warm
    in-process memo, restart from the persisted artifact) are part of
    the driver contract and gated by the schema checker."""
    metrics = bench.expected_metrics()
    assert "config5b_plan_cold_templates_per_sec" in metrics
    assert "config5b_plan_warm_templates_per_sec" in metrics
    assert "config5b_plan_restart_templates_per_sec" in metrics


def test_checker_requires_plan_cache_keys(tmp_path):
    """A plan-regime row missing its lowering decomposition or the
    plan_cache counters fails the gate."""
    row = {
        "metric": "config5b_plan_warm_templates_per_sec",
        "value": 1.0,
        "unit": "templates/sec",
        "vs_baseline": 2.0,
        "plan_hits": 4,
        # lower/pack/relocate seconds + misses/bytes_loaded missing
    }
    src = _newest_artifact().read_text().splitlines()
    doctored = tmp_path / "bench_all_doctored_plan.json"
    doctored.write_text(
        "\n".join(
            ln for ln in src
            if '"config5b_plan_warm_templates_per_sec"' not in ln
        )
        + "\n"
        + __import__("json").dumps(row)
        + "\n"
    )
    problems = check_bench_schema.check(doctored)
    assert any("lower_compile_seconds_per_run" in p for p in problems)
    assert any("plan_bytes_loaded" in p for p in problems)


def test_expected_metrics_cover_delta_rows():
    """PR 11: the incremental-plane regime rows (cache-off cold,
    0%-changed warm, 1%-changed) are part of the driver contract and
    gated by the schema checker, arriving with the round-14
    artifact."""
    metrics = bench.expected_metrics()
    for m in (
        "config5b_delta_cold_templates_per_sec",
        "config5b_delta_warm_templates_per_sec",
        "config5b_delta_1pct_templates_per_sec",
    ):
        assert m in metrics
        assert check_bench_schema.metric_since(m) == 14


def test_checker_requires_delta_keys(tmp_path):
    """A delta-regime row missing the result_cache counters or the
    per-run dispatch count fails the gate."""
    row = {
        "metric": "config5b_delta_warm_templates_per_sec",
        "value": 1.0,
        "unit": "templates/sec",
        "vs_baseline": 5.0,
        "result_hits": 1024,
        # dispatches_per_run + misses/stores/bytes keys missing
    }
    src = _newest_artifact().read_text().splitlines()
    doctored = tmp_path / "bench_all_doctored_delta.json"
    doctored.write_text(
        "\n".join(
            ln for ln in src
            if '"config5b_delta_warm_templates_per_sec"' not in ln
        )
        + "\n"
        + __import__("json").dumps(row)
        + "\n"
    )
    problems = check_bench_schema.check(doctored)
    assert any("dispatches_per_run" in p for p in problems)
    assert any("result_bytes_loaded" in p for p in problems)


def test_expected_metrics_cover_verify_rows():
    """PR 14: the plan/IR verifier on/off overhead row pair is part of
    the driver contract and gated by the schema checker, arriving with
    the round-15 artifact."""
    metrics = bench.expected_metrics()
    for m in (
        "config5b_verify_off_templates_per_sec",
        "config5b_verify_on_templates_per_sec",
    ):
        assert m in metrics
        assert check_bench_schema.metric_since(m) == 15


def test_checker_requires_verify_overhead_keys(tmp_path):
    """A verifier-on row that doesn't quantify its overhead against
    the unverified branch fails the gate."""
    row = {
        "metric": "config5b_verify_on_templates_per_sec",
        "value": 1.0,
        "unit": "templates/sec",
        "vs_baseline": 1.0,
        "plan_verifier": "enabled",
        # overhead_vs_off / invariants_checked_per_run missing
    }
    src = _newest_artifact().read_text().splitlines()
    doctored = tmp_path / "bench_all_doctored_verify.json"
    doctored.write_text(
        "\n".join(
            ln for ln in src
            if '"config5b_verify_on_templates_per_sec"' not in ln
        )
        + "\n"
        + __import__("json").dumps(row)
        + "\n"
    )
    problems = check_bench_schema.check(doctored)
    assert any("overhead_vs_off" in p for p in problems)
    assert any("invariants_checked_per_run" in p for p in problems)


def test_expected_metrics_cover_journal_rows():
    """PR 20: the durability plane's checkpoint-overhead pair and the
    half-journaled resume row are part of the driver contract, gated
    by the schema checker and arriving with the round-17 artifact."""
    metrics = bench.expected_metrics()
    for m in (
        "config5b_journal_off_templates_per_sec",
        "config5b_journal_on_templates_per_sec",
        "config5b_resume_50pct_templates_per_sec",
    ):
        assert m in metrics
        assert check_bench_schema.metric_since(m) == 17


def test_checker_requires_journal_keys(tmp_path):
    """A journal-on row that doesn't quantify its checkpoint overhead,
    or a resume row without its replayed/dispatched evidence, fails
    the gate."""
    import json

    rows = [
        {
            "metric": "config5b_journal_on_templates_per_sec",
            "value": 1.0,
            "unit": "templates/sec",
            "vs_baseline": 1.0,
            "journal": "on",
            # overhead_vs_off / chunks_journaled_per_run missing
        },
        {
            "metric": "config5b_resume_50pct_templates_per_sec",
            "value": 1.0,
            "unit": "templates/sec",
            "vs_baseline": 1.0,
            # chunks_replayed / chunks_total / dispatches_per_run
            # missing
        },
    ]
    src = _newest_artifact().read_text().splitlines()
    doctored = tmp_path / "bench_all_doctored_journal.json"
    doctored.write_text(
        "\n".join(
            ln for ln in src
            if '"config5b_journal_on_templates_per_sec"' not in ln
            and '"config5b_resume_50pct_templates_per_sec"' not in ln
        )
        + "\n"
        + "\n".join(json.dumps(r) for r in rows)
        + "\n"
    )
    problems = check_bench_schema.check(doctored)
    for needle in ("overhead_vs_off", "chunks_journaled_per_run",
                   "chunks_replayed", "chunks_total",
                   "dispatches_per_run"):
        assert any(needle in p for p in problems), needle


def test_registry_stage_seconds_reconcile_with_wall_time(tmp_path):
    """The registry-derived stage decomposition bench.py reports must
    account for the run it claims to decompose: summing the top-level
    pipeline stage totals over a serial (workers=0) sweep lands within
    tolerance of the end-to-end wall time — no stage double-counted
    past the wall, and the instrumented stages cover the bulk of it."""
    import json
    import time

    from guard_tpu.cli import run
    from guard_tpu.parallel import ingest
    from guard_tpu.utils import telemetry
    from guard_tpu.utils.io import Reader, Writer

    rules = tmp_path / "rules.guard"
    rules.write_text(
        "let b = Resources.*[ Type == 'AWS::S3::Bucket' ]\n"
        "rule sse when %b !empty { %b.Properties.Enc == true }\n"
    )
    data = tmp_path / "data"
    data.mkdir()
    for i in range(24):
        doc = {
            "Resources": {
                "b": {
                    "Type": "AWS::S3::Bucket",
                    "Properties": {"Enc": True},
                }
            }
        }
        (data / f"t{i:02d}.json").write_text(json.dumps(doc))

    def sweep(tag):
        w = Writer.buffered()
        rc = run(
            ["sweep", "-r", str(rules), "-d", str(data),
             "-M", str(tmp_path / f"{tag}.jsonl"), "-c", "8",
             "--backend", "tpu", "--ingest-workers", "0"],
            writer=w, reader=Reader(),
        )
        assert rc == 0

    ingest.close_shared_pools()
    sweep("warm")  # absorb first-touch compile outside the measurement
    telemetry.enable()
    telemetry.reset_trace()
    try:
        from guard_tpu.ops.backend import reset_all_stats

        reset_all_stats()
        t0 = time.perf_counter()
        sweep("measured")
        wall = time.perf_counter() - t0
        stage = telemetry.REGISTRY.stage_seconds()
    finally:
        telemetry.disable()
        telemetry.reset_trace()
    # top-level (non-nested) stage names only: pack_compile nests
    # inside dispatch, worker stages don't occur at workers=0
    top = (
        "rule_parse", "read_parse", "encode", "lower_compile",
        "dispatch", "collect", "rim_reduce", "report", "oracle",
    )
    total = sum(stage.get(name, 0.0) for name in top)
    assert stage.get("dispatch", 0.0) > 0.0
    assert stage.get("report", 0.0) > 0.0
    # stages never sum past the wall (5% slack for timer granularity),
    # and the instrumented pipeline accounts for most of the run
    assert total <= wall * 1.05, (total, wall, stage)
    assert total >= wall * 0.35, (total, wall, stage)


def test_expected_metrics_cover_front_door_rows():
    """PR 16: the serving front door's overload row pair (shed off/on
    p99 under a stalled coalesce window) and the quota-isolation quiet
    p50 are part of the driver contract, arriving with the round-16
    artifact."""
    metrics = bench.expected_metrics()
    for m in (
        "serve_overload_shed_off_p99_ms",
        "serve_overload_shed_on_p99_ms",
        "serve_quota_isolation_quiet_p50_ms",
    ):
        assert m in metrics
        assert check_bench_schema.metric_since(m) == 16


def test_checker_requires_front_door_keys(tmp_path):
    """A shed-on row that doesn't carry its breaker/shed evidence, or
    a quota row without its isolation context, fails the gate."""
    import json

    rows = [
        {
            "metric": "serve_overload_shed_on_p99_ms",
            "value": 1.0,
            "unit": "ms",
            "vs_baseline": 1.0,
            "dispatches_per_request": 1.0,
            "stall_window_ms": 250,
            "concurrency": 4,
            # slo_ms / breaker_trips / shed_solo missing
        },
        {
            "metric": "serve_quota_isolation_quiet_p50_ms",
            "value": 1.0,
            "unit": "ms",
            "vs_baseline": 1.0,
            # p50_alone_ms / hot_rejected / quota_rejections /
            # envelope_parity / tenant_max_inflight missing
        },
    ]
    src = _newest_artifact().read_text().splitlines()
    doctored = tmp_path / "bench_all_doctored_frontdoor.json"
    doctored.write_text(
        "\n".join(
            ln for ln in src
            if '"serve_overload_shed_on_p99_ms"' not in ln
            and '"serve_quota_isolation_quiet_p50_ms"' not in ln
        )
        + "\n"
        + "\n".join(json.dumps(r) for r in rows)
        + "\n"
    )
    problems = check_bench_schema.check(doctored)
    for needle in ("slo_ms", "breaker_trips", "shed_solo",
                   "envelope_parity", "quota_rejections"):
        assert any(needle in p for p in problems), needle
