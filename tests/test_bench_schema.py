"""Bench-artifact hygiene (VERDICT r5 Weak #3): the committed bench
artifact must contain every metric row the CURRENT bench driver emits,
and tools/check_bench_schema.py must flag artifacts that don't."""

import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tools"))

import bench  # noqa: E402
import check_bench_schema  # noqa: E402


def _newest_artifact():
    candidates = sorted(REPO.glob("bench_all_*.json"))
    assert candidates, "no committed bench_all_*.json artifact"
    return candidates[-1]


def test_committed_artifact_matches_current_driver():
    problems = check_bench_schema.check(_newest_artifact())
    assert problems == [], "\n".join(problems)


def test_checker_flags_missing_metric(tmp_path):
    src = _newest_artifact().read_text().splitlines()
    victim = bench.expected_metrics()[0]
    doctored = tmp_path / "bench_all_doctored.json"
    doctored.write_text(
        "\n".join(ln for ln in src if f'"{victim}"' not in ln) + "\n"
    )
    problems = check_bench_schema.check(doctored)
    assert any(victim in p for p in problems)


def test_expected_metrics_cover_fail_heavy_batch_rows():
    metrics = bench.expected_metrics()
    for tag in ("50pct", "allfail"):
        for nd in bench.FAIL_HEAVY_BATCH_SIZES:
            assert (
                f"config6_fail_{tag}_docs{nd}_full_docs_per_sec" in metrics
            )
    assert "config5b_packed_templates_per_sec" in metrics


def test_expected_metrics_cover_ingest_rows():
    """PR 3: the ingest-plane decomposition rows (workers=1 vs 2, for
    the registry and fail-heavy corpora) are part of the driver
    contract and gated by the schema checker."""
    metrics = bench.expected_metrics()
    for m in (
        "config5b_ingest_workers1_templates_per_sec",
        "config5b_ingest_workers2_templates_per_sec",
        "config6_ingest_workers1_docs_per_sec",
        "config6_ingest_workers2_docs_per_sec",
    ):
        assert m in metrics


def test_checker_requires_ingest_decomposition_keys(tmp_path):
    """An ingest row missing its decomposition extras fails the gate."""
    row = {
        "metric": "config5b_ingest_workers2_templates_per_sec",
        "value": 1.0,
        "unit": "templates/sec",
        "vs_baseline": 1.0,
        "workers": 2,
        # read_parse/encode/pipeline_stall keys intentionally missing
    }
    src = _newest_artifact().read_text().splitlines()
    doctored = tmp_path / "bench_all_doctored_ingest.json"
    doctored.write_text(
        "\n".join(
            ln for ln in src
            if '"config5b_ingest_workers2_templates_per_sec"' not in ln
        )
        + "\n"
        + __import__("json").dumps(row)
        + "\n"
    )
    problems = check_bench_schema.check(doctored)
    assert any("read_parse_seconds_per_run" in p for p in problems)
    assert any("pipeline_stall_seconds_per_run" in p for p in problems)


def test_expected_metrics_cover_quarantine_rows():
    """PR 5: the failure-plane overhead rows (clean quarantine cost vs
    fail-fast, degraded-run throughput) are part of the driver
    contract and gated by the schema checker."""
    metrics = bench.expected_metrics()
    assert "config5b_quarantine_clean_templates_per_sec" in metrics
    assert "config5b_quarantine_degraded_templates_per_sec" in metrics


def test_checker_requires_quarantine_keys(tmp_path):
    """A degraded-run row missing its recovery counters fails the
    gate."""
    row = {
        "metric": "config5b_quarantine_degraded_templates_per_sec",
        "value": 1.0,
        "unit": "templates/sec",
        "vs_baseline": 1.0,
        "poisoned_docs": 8,
        # quarantined_docs / retries / dispatch_fallbacks missing
    }
    src = _newest_artifact().read_text().splitlines()
    doctored = tmp_path / "bench_all_doctored_quarantine.json"
    doctored.write_text(
        "\n".join(
            ln for ln in src
            if '"config5b_quarantine_degraded_templates_per_sec"'
            not in ln
        )
        + "\n"
        + __import__("json").dumps(row)
        + "\n"
    )
    problems = check_bench_schema.check(doctored)
    assert any("quarantined_docs" in p for p in problems)
    assert any("dispatch_fallbacks" in p for p in problems)
