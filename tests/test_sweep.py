"""`sweep` command: chunked evaluation with checkpoint/resume manifest
(the batch-resumability subsystem, SURVEY.md §5)."""

import json

import pytest

from guard_tpu.cli import run
from guard_tpu.utils.io import Reader, Writer

RULES = """\
rule sized {
    Resources.*.Size <= 100
}
"""


def _mk_corpus(tmp_path, n=5, bad=(2,)):
    rules = tmp_path / "rules.guard"
    rules.write_text(RULES)
    data = tmp_path / "data"
    data.mkdir()
    for i in range(n):
        size = 500 if i in bad else 50
        (data / f"doc{i:02}.json").write_text(
            json.dumps({"Resources": {"r": {"Size": size}}})
        )
    return rules, data


def _run_sweep(tmp_path, rules, data, backend="tpu", chunk=2):
    w = Writer.buffered()
    code = run(
        [
            "sweep",
            "-r", str(rules),
            "-d", str(data),
            "-M", str(tmp_path / "manifest.jsonl"),
            "-c", str(chunk),
            "--backend", backend,
        ],
        writer=w,
        reader=Reader.from_string(""),
    )
    out = w.stripped()
    summary = json.loads(out.splitlines()[-1]) if out.strip() else None
    return code, summary


@pytest.mark.parametrize("backend", ["cpu", "tpu"])
def test_sweep_counts_and_exit_code(tmp_path, backend):
    rules, data = _mk_corpus(tmp_path)
    code, summary = _run_sweep(tmp_path, rules, data, backend=backend)
    assert code == 19
    assert summary["documents"] == 5
    assert summary["counts"] == {"pass": 4, "fail": 1, "skip": 0}
    assert summary["failed"] == [{"data": "doc02.json", "rules": ["sized"]}]
    assert summary["evaluated"] == 3  # ceil(5 / 2) chunks
    assert summary["resumed"] == 0


def test_sweep_resume_skips_completed_chunks(tmp_path):
    rules, data = _mk_corpus(tmp_path)
    code, s1 = _run_sweep(tmp_path, rules, data, backend="cpu")
    assert s1["evaluated"] == 3
    # second run: everything checkpointed, nothing re-evaluated
    code, s2 = _run_sweep(tmp_path, rules, data, backend="cpu")
    assert code == 19
    assert s2["evaluated"] == 0
    assert s2["resumed"] == 3
    assert s2["counts"] == s1["counts"]


def test_sweep_interrupted_manifest_resumes_tail(tmp_path):
    rules, data = _mk_corpus(tmp_path)
    _run_sweep(tmp_path, rules, data, backend="cpu")
    manifest = tmp_path / "manifest.jsonl"
    lines = manifest.read_text().splitlines()
    # simulate a crash after the first two chunks (plus a torn write)
    manifest.write_text("\n".join(lines[:2]) + '\n{"chunk": 2, "tor')
    code, s = _run_sweep(tmp_path, rules, data, backend="cpu")
    assert s["evaluated"] == 1
    assert s["resumed"] == 2
    assert s["counts"] == {"pass": 4, "fail": 1, "skip": 0}


def test_sweep_reruns_changed_chunk(tmp_path):
    rules, data = _mk_corpus(tmp_path)
    _, s1 = _run_sweep(tmp_path, rules, data, backend="cpu")
    # fix the failing doc: its chunk signature changes -> re-evaluated
    bad = data / "doc02.json"
    bad.write_text(json.dumps({"Resources": {"r": {"Size": 10}}}))
    import os

    os.utime(bad, (0, 0))  # force a different mtime signature
    code, s2 = _run_sweep(tmp_path, rules, data, backend="cpu")
    assert code == 0
    assert s2["evaluated"] == 1
    assert s2["resumed"] == 2
    assert s2["counts"] == {"pass": 5, "fail": 0, "skip": 0}


def test_sweep_error_paths(tmp_path):
    rules, data = _mk_corpus(tmp_path, n=2, bad=())
    w = Writer.buffered()
    code = run(
        ["sweep", "-d", str(data)],
        writer=w,
        reader=Reader.from_string(""),
    )
    assert code == 5  # no rules


def test_sweep_rule_shards_matches_flat(tmp_path):
    """--rule-shards N produces the same manifest counts as flat."""
    import json

    from guard_tpu.cli import run

    rules = tmp_path / "r.guard"
    rules.write_text(
        "let b = Resources.*[ Type == 'AWS::S3::Bucket' ]\n"
        "rule sse when %b !empty { %b.Properties.Enc == true }\n"
        "rule named when %b !empty {\n"
        "    %b.Properties.Name == /^[a-z]+$/\n"
        "}\n"
    )
    data = tmp_path / "data"
    data.mkdir()
    for i in range(9):
        doc = {"Resources": {"b": {"Type": "AWS::S3::Bucket", "Properties": {
            "Enc": i % 2 == 0, "Name": "logs" if i % 3 else "BAD"}}}}
        (data / f"t{i}.json").write_text(json.dumps(doc))

    def counts(args, manifest):
        run(["sweep", "-r", str(rules), "-d", str(data),
             "-M", str(tmp_path / manifest), "-c", "4"] + args)
        recs = [json.loads(l) for l in
                (tmp_path / manifest).read_text().splitlines()]
        total = {"pass": 0, "fail": 0, "skip": 0}
        for r in recs:
            for k in total:
                total[k] += r["counts"][k]
        return total

    flat = counts([], "flat.jsonl")
    sharded = counts(["--rule-shards", "2"], "sharded.jsonl")
    assert flat == sharded and sum(flat.values()) == 9


def test_sweep_function_rules_tpu_matches_cpu(tmp_path):
    """Function lets go through the per-rule-file precompute+re-encode
    path inside the sweep (ops/fnvars.py); both backends must agree."""
    rules = tmp_path / "fn.guard"
    rules.write_text(
        """let upper = to_upper(Resources.*.Name)
let n = count(Resources.*)

rule named_prod when %n >= 1 { some %upper == /PROD/ }
"""
    )
    data = tmp_path / "data"
    data.mkdir()
    for i, name in enumerate(["prod-a", "dev-b", "prod-c"]):
        (data / f"d{i}.json").write_text(
            json.dumps({"Resources": {"r": {"Name": name}}})
        )
    results = {}
    for backend in ("cpu", "tpu"):
        mdir = tmp_path / backend
        mdir.mkdir()
        w = Writer.buffered()
        code = run(
            [
                "sweep", "-r", str(rules), "-d", str(data),
                "-M", str(mdir / "m.jsonl"), "-c", "2",
                "--backend", backend,
            ],
            writer=w,
            reader=Reader.from_string(""),
        )
        summary = json.loads(w.stripped().splitlines()[-1])
        results[backend] = (code, summary["counts"], summary["failed"])
    assert results["cpu"] == results["tpu"]
    assert results["cpu"][1] == {"pass": 2, "fail": 1, "skip": 0}


def test_sweep_invalid_json_doc_quarantines_and_counts_error(tmp_path):
    """One truncated JSON doc must not stall the chunk: it is
    quarantined with one error while the remaining documents still
    evaluate (on the native encoder when available). By default doc
    failures degrade the run (exit stays green); `--max-doc-failures 0`
    restores the historical fail-fast exit."""
    rules = tmp_path / "r.guard"
    rules.write_text("rule ok { Resources exists }\n")
    data = tmp_path / "data"
    data.mkdir()
    for i in range(5):
        (data / f"t{i}.json").write_text('{"Resources": {"a": 1}}')
    (data / "bad.json").write_text('{"Resources": {')  # truncated
    w = Writer.buffered()
    rc = run(
        ["sweep", "-r", str(rules), "-d", str(data),
         "-M", str(tmp_path / "m.jsonl"), "-c", "16"],
        writer=w, reader=Reader(),
    )
    summary = json.loads(w.out.getvalue().strip().splitlines()[-1])
    assert summary["errors"] == 1
    assert summary["counts"]["pass"] == 5
    assert summary["counts"]["fail"] == 0
    # the failure plane: doc skips surface as quarantine records, not
    # a hard-error exit
    assert [q["file"] for q in summary["quarantined"]] == ["bad.json"]
    assert summary["quarantined"][0]["stage"] == "parse"
    assert rc == 0

    w = Writer.buffered()
    rc = run(
        ["sweep", "-r", str(rules), "-d", str(data),
         "-M", str(tmp_path / "m0.jsonl"), "-c", "16",
         "--max-doc-failures", "0"],
        writer=w, reader=Reader(),
    )
    assert rc == 5  # fail-fast semantics restored on request
