"""The native statuses oracle as the backend prefilter (VERDICT r3
item 2 integration): host-fallback rules and passing documents must be
settled by the C++ engine with ZERO Python-oracle reruns, and failing
documents must reach the Python oracle only when rich reports are
actually wanted."""

import json

import pytest

import guard_tpu.ops.backend as backend_mod
from guard_tpu.cli import run
from guard_tpu.ops.native_oracle import build_native, native_available
from guard_tpu.utils.io import Reader, Writer

# one lowerable rule + one host-only rule (per-origin inline call keeps
# `upper` on the CPU oracle — ir.HOST_ONLY_CONSTRUCTS)
RULES = """\
rule sse when Resources exists {
    Resources.*.Properties.Enc == true
}
rule upper when Resources exists {
    Resources.* { Name == to_lower(Name) }
}
"""


@pytest.fixture(scope="module", autouse=True)
def _built():
    assert build_native(), "native oracle failed to build"
    assert native_available()


def _mk_corpus(tmp_path, n, fail_every):
    rules = tmp_path / "r.guard"
    rules.write_text(RULES)
    data = tmp_path / "data"
    data.mkdir()
    n_fail = 0
    for i in range(n):
        fail = fail_every and (i % fail_every == 0)
        n_fail += bool(fail)
        (data / f"t{i:03d}.json").write_text(json.dumps({
            "Resources": {
                "b": {
                    "Name": "ok",
                    "Properties": {"Enc": not fail},
                }
            }
        }))
    return rules, data, n_fail


def _run_counting(monkeypatch, args):
    calls = {"n": 0}
    real = backend_mod.eval_rules_file

    def counting(*a, **kw):
        calls["n"] += 1
        return real(*a, **kw)

    monkeypatch.setattr(backend_mod, "eval_rules_file", counting)
    w = Writer.buffered()
    rc = run(args, writer=w, reader=Reader())
    return rc, calls["n"], w.out.getvalue()


def test_host_rules_all_pass_needs_zero_python(tmp_path, monkeypatch):
    rules, data, _ = _mk_corpus(tmp_path, 8, fail_every=0)
    rc, n_python, out = _run_counting(monkeypatch, [
        "validate", "-r", str(rules), "-d", str(data), "--backend", "tpu",
    ])
    assert rc == 0, out
    # the host-only rule used to force a Python rerun for EVERY doc;
    # the native engine settles all of them
    assert n_python == 0


def test_host_rule_failure_detected_natively(tmp_path, monkeypatch):
    # the FAILING rule is the host-only one: its status must come from
    # the native engine (device kernels never see it), zero Python
    rules = tmp_path / "r.guard"
    rules.write_text(RULES)
    data = tmp_path / "data"
    data.mkdir()
    (data / "t.json").write_text(json.dumps({
        "Resources": {"b": {"Name": "UPPER", "Properties": {"Enc": True}}}
    }))
    rc, n_python, out = _run_counting(monkeypatch, [
        "validate", "-r", str(rules), "-d", str(data), "--backend", "tpu",
        "--statuses-only",
    ])
    assert rc == 19, out
    assert n_python == 0


def test_statuses_only_needs_zero_python_even_failing(tmp_path, monkeypatch):
    rules, data, n_fail = _mk_corpus(tmp_path, 8, fail_every=2)
    assert n_fail > 0
    rc, n_python, out = _run_counting(monkeypatch, [
        "validate", "-r", str(rules), "-d", str(data), "--backend", "tpu",
        "--statuses-only",
    ])
    assert rc == 19, out
    assert n_python == 0


def test_failing_docs_need_zero_python_via_records(tmp_path, monkeypatch):
    rules, data, n_fail = _mk_corpus(tmp_path, 8, fail_every=2)
    assert n_fail > 0
    rc, n_python, out = _run_counting(monkeypatch, [
        "validate", "-r", str(rules), "-d", str(data), "--backend", "tpu",
    ])
    assert rc == 19, out
    # rich reports for failing docs come from the native records
    # engine — the Python oracle is not invoked at all
    assert n_python == 0
    # and the report content is real: the failing rule is named
    assert "sse" in out


def test_yaml_flow_docs_not_misrouted(tmp_path, monkeypatch):
    # flow-style YAML sniffs as JSON ('{' first byte) but is NOT JSON;
    # the backend must fall back to the loaded-tree wire, not error
    # (round-4 review finding)
    rules = tmp_path / "r.guard"
    rules.write_text(RULES)
    data = tmp_path / "data"
    data.mkdir()
    (data / "t.yaml").write_text(
        "{Resources: {b: {Name: ok, Properties: {Enc: false}}}}"
    )
    args = ["validate", "-r", str(rules), "-d", str(data), "--backend", "tpu"]
    w1 = Writer.buffered()
    rc1 = run(args, writer=w1, reader=Reader())
    assert rc1 == 19, w1.err.getvalue()

    from guard_tpu.ops.native_oracle import NativeUnsupported
    import guard_tpu.ops.native_oracle as no_mod

    def refuse(rf):
        raise NativeUnsupported("disabled for differential")

    monkeypatch.setattr(no_mod, "NativeOracle", refuse)
    w2 = Writer.buffered()
    rc2 = run(args, writer=w2, reader=Reader())
    assert rc1 == rc2
    assert w1.out.getvalue() == w2.out.getvalue()


def test_output_identical_with_and_without_native(tmp_path, monkeypatch):
    rules, data, _ = _mk_corpus(tmp_path, 6, fail_every=3)
    args = ["validate", "-r", str(rules), "-d", str(data), "--backend", "tpu"]

    w1 = Writer.buffered()
    rc1 = run(args, writer=w1, reader=Reader())

    # disable the native path: statuses must come out identical
    from guard_tpu.ops.native_oracle import NativeUnsupported

    def refuse(rf):
        raise NativeUnsupported("disabled for differential")

    import guard_tpu.ops.native_oracle as no_mod

    monkeypatch.setattr(no_mod, "NativeOracle", refuse)
    w2 = Writer.buffered()
    rc2 = run(args, writer=w2, reader=Reader())
    assert rc1 == rc2
    assert w1.out.getvalue() == w2.out.getvalue()
