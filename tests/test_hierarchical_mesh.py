"""Multi-slice (DCN x ICI) sharding: the hierarchical 2-D mesh must
produce byte-identical statuses and summary counts to the flat 1-D
mesh — the doc axis shards over both axes and the only cross-slice
communication is the final count reduction (SURVEY.md §2.3)."""

import jax
import numpy as np
import pytest

from guard_tpu.core.parser import parse_rules_file
from guard_tpu.core.values import from_plain
from guard_tpu.ops.encoder import encode_batch
from guard_tpu.ops.ir import compile_rules_file
from guard_tpu.parallel.mesh import (
    ShardedBatchEvaluator,
    default_mesh,
    hierarchical_mesh,
)

RULES = """
let buckets = Resources.*[ Type == 'Bucket' ]

rule named when %buckets !empty { %buckets.Name exists }
rule sized when %buckets !empty { %buckets.Size IN r[1, 100] }
"""


def _batch(n=24):
    docs = []
    for i in range(n):
        docs.append(
            from_plain(
                {
                    "Resources": {
                        "b": {
                            "Type": "Bucket" if i % 3 else "Other",
                            "Name": f"b{i}" if i % 2 else None,
                            "Size": (i % 120) + 1,
                        }
                    }
                }
            )
        )
    return encode_batch(docs)


@pytest.mark.parametrize("n_slices", [2, 4])
def test_hierarchical_matches_flat(n_slices):
    devices = jax.devices()
    if len(devices) < 8:
        pytest.skip("needs the 8-device test mesh")
    batch, interner = _batch()
    rf = parse_rules_file(RULES, "mesh.guard")
    compiled = compile_rules_file(rf, interner)
    assert not compiled.host_rules

    flat = ShardedBatchEvaluator(compiled, mesh=default_mesh(devices[:8]))
    hier = ShardedBatchEvaluator(
        compiled, mesh=hierarchical_mesh(devices[:8], n_slices=n_slices)
    )
    st_flat, counts_flat = flat.with_summary(batch)
    st_hier, counts_hier = hier.with_summary(batch)
    np.testing.assert_array_equal(st_flat, st_hier)
    np.testing.assert_array_equal(counts_flat, counts_hier)

    # the plain evaluator path shards identically
    np.testing.assert_array_equal(flat(batch), hier(batch))


def test_hierarchical_mesh_shape_validation():
    devices = jax.devices()
    if len(devices) < 8:
        pytest.skip("needs the 8-device test mesh")
    with pytest.raises(ValueError):
        hierarchical_mesh(devices[:8], n_slices=3)
    m = hierarchical_mesh(devices[:8], n_slices=2)
    assert m.devices.shape == (2, 4)
    assert m.axis_names == ("dcn", "ici")
