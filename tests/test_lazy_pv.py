"""Lazy document trees in plain validate (VERDICT r3 item 4): on the
tpu backend, JSON corpora evaluate natively from raw content and the
Python tree builds only for documents something actually walks."""

import json

import pytest

import guard_tpu.commands.validate as vmod
from guard_tpu.cli import run
from guard_tpu.commands.reporters.aware import _top_level_json_keys
from guard_tpu.utils.io import Reader, Writer

RULES = "rule named { Resources.*.Name exists }\n"


def _mk(tmp_path, n, fail_every=0):
    (tmp_path / "r.guard").write_text(RULES)
    data = tmp_path / "data"
    data.mkdir()
    for i in range(n):
        body = {"Resources": {"a": {}}} if fail_every and i % fail_every == 0 \
            else {"Resources": {"a": {"Name": f"n{i}"}}}
        (data / f"t{i}.json").write_text(json.dumps(body))
    return tmp_path / "r.guard", data


def _run(args):
    w = Writer.buffered()
    rc = run(args, writer=w, reader=Reader())
    return rc, w.out.getvalue(), w.err.getvalue()


def test_passing_json_corpus_builds_zero_trees(tmp_path, monkeypatch):
    rules, data = _mk(tmp_path, 6)
    loads = {"n": 0}
    real = vmod.load_document

    def counting(content, name=""):
        loads["n"] += 1
        return real(content, name)

    monkeypatch.setattr(vmod, "load_document", counting)
    rc, out, err = _run([
        "validate", "-r", str(rules), "-d", str(data), "--backend", "tpu",
    ])
    assert rc == 0, err
    # all-passing JSON corpus: native encode + device statuses + the
    # raw-JSON shape probe — no Python tree ever builds
    assert loads["n"] == 0


def test_failing_docs_materialize_only_themselves(tmp_path, monkeypatch):
    rules, data = _mk(tmp_path, 6, fail_every=3)
    loads = {"n": 0}
    real = vmod.load_document

    def counting(content, name=""):
        loads["n"] += 1
        return real(content, name)

    monkeypatch.setattr(vmod, "load_document", counting)
    rc, out, err = _run([
        "validate", "-r", str(rules), "-d", str(data), "--backend", "tpu",
    ])
    assert rc == 19, err
    # failing docs (2 of 6) need trees for the aware failure report;
    # passing docs stay raw
    assert 0 < loads["n"] <= 2


def test_lazy_output_identical_to_cpu_backend(tmp_path):
    # the cpu backend is fully eager and takes the pre-change reporter
    # path (real PVs, no probe) — the strongest identity baseline
    rules, data = _mk(tmp_path, 8, fail_every=2)
    base = ["validate", "-r", str(rules), "-d", str(data)]
    lazy_tpu = _run(base + ["--backend", "tpu"])
    eager_cpu = _run(base)
    assert lazy_tpu[0] == eager_cpu[0]
    assert lazy_tpu[1] == eager_cpu[1]


def test_escaped_key_spelling_matches_cpu(tmp_path):
    # \u0052esources == "Resources": the probe must decline (build the
    # tree) rather than misclassify the document shape
    (tmp_path / "r.guard").write_text(RULES)
    data = tmp_path / "data"
    data.mkdir()
    (data / "t.json").write_text(
        '{"\\u0052esources": {"a": {"Name": "x"}}}'
    )
    base = ["validate", "-r", str(tmp_path / "r.guard"), "-d", str(data),
            "--show-summary", "pass"]
    tpu = _run(base + ["--backend", "tpu"])
    cpu = _run(base)
    assert tpu[0] == cpu[0]
    assert tpu[1] == cpu[1]


def test_broken_doc_keeps_error_contract(tmp_path):
    rules, data = _mk(tmp_path, 2)
    (data / "bad.json").write_text("{this is not json: [")
    rc, out, err = _run([
        "validate", "-r", str(rules), "-d", str(data), "--backend", "tpu",
    ])
    assert rc == 5
    assert err.strip()


def test_top_level_json_keys_scanner():
    f = _top_level_json_keys
    assert f('{"Resources": {"a": 1}, "Outputs": []}') == {"Resources", "Outputs"}
    assert f('  {"a": [1, {"Resources": 2}], "b": "x{y}"}') == {"a", "b"}
    assert f('{"a": "s\\"t", "b": 1}') == {"a", "b"}
    assert f("[1, 2]") == set()
    assert f('{"dup": 1, "dup": 2}') == {"dup"}
    assert f("Resources:\n  a: 1\n") is None  # YAML
    assert f("") is None
    assert f('{"unterminated": ') is None
    # nested resource_changes must NOT count as top-level
    assert "resource_changes" not in f(
        '{"plan": {"resource_changes": []}, "x": 1}'
    )
