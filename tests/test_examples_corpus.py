"""This repo's own example-rule corpus (examples/rules/): every domain
runs through the `test` command (expectation suites must pass), and
every lowerable rule also runs differentially kernel-vs-oracle on the
test inputs — the corpus doubles as a TPU parity suite."""

import pathlib

import pytest
import yaml

from guard_tpu.cli import run
from guard_tpu.core.parser import parse_rules_file
from guard_tpu.core.scopes import RootScope
from guard_tpu.core.values import from_plain
from guard_tpu.ops.encoder import encode_batch
from guard_tpu.ops.ir import compile_rules_file
from guard_tpu.ops.kernels import BatchEvaluator

ROOT = pathlib.Path(__file__).resolve().parent.parent / "examples" / "rules"
DOMAINS = sorted(p.name for p in ROOT.iterdir() if p.is_dir())

STATUS = {0: "PASS", 1: "FAIL", 2: "SKIP"}


@pytest.mark.parametrize("domain", DOMAINS)
def test_domain_expectations(domain):
    code = run(["test", "-d", str(ROOT / domain)])
    assert code == 0, f"expectation suite failed for {domain}"


def _domain_cases(domain):
    for guard in sorted((ROOT / domain).glob("*.guard")):
        rf = parse_rules_file(guard.read_text(), guard.name)
        for spec in sorted((ROOT / domain / "tests").glob("*.yaml")):
            for case in yaml.safe_load(spec.read_text()):
                yield rf, case


@pytest.mark.parametrize("domain", DOMAINS)
def test_domain_tpu_parity(domain):
    from guard_tpu.ops.fnvars import precompute_fn_values

    checked = 0
    for rf, case in _domain_cases(domain):
        doc = from_plain(case.get("input") or {})
        # mirror the backend: function slots precompute per document
        # BEFORE encode (ops/backend.py) — without this, fn-dependent
        # rules see no result subtrees and decide wrongly
        fn_vars, fn_vals, fn_err = precompute_fn_values(rf, [doc])
        if fn_err:
            continue  # routed to the oracle by the backend
        batch, interner = encode_batch(
            [doc], fn_values=fn_vals, fn_var_order=fn_vars
        )
        compiled = compile_rules_file(rf, interner)
        if not compiled.rules:
            continue
        ev = BatchEvaluator(compiled)
        statuses = ev(batch)
        unsure = ev.last_unsure
        scope = RootScope(rf, doc)
        for ri, crule in enumerate(compiled.rules):
            if unsure is not None and bool(unsure[0, ri]):
                continue
            cpu = scope.rule_status(crule.name).value
            tpu = STATUS[int(statuses[0, ri])]
            assert cpu == tpu, (
                f"{domain}/{crule.name} on {case['name']}: cpu={cpu} tpu={tpu}"
            )
            checked += 1
    assert checked > 0, f"no lowerable rules exercised in {domain}"
