"""Differential suite: the native C++ statuses oracle vs the Python
oracle (VERDICT r3 item 2).

The native engine (native/oracle.cpp) promises: for every document it
accepts, its per-rule statuses equal the Python oracle's bit-for-bit;
anything it cannot guarantee raises NativeUnsupported and falls back.
This suite drives that promise across the full vendored corpus (249
rule files x their expectation-suite inputs), the example rule domains,
and targeted semantic edge shapes ported from the evaluator test
batches. It must run without JAX (pure CPU work).
"""

import pathlib

import pytest
import yaml

from guard_tpu.core.evaluator import eval_rules_file
from guard_tpu.core.errors import GuardError
from guard_tpu.core.parser import parse_rules_file
from guard_tpu.core.scopes import RootScope
from guard_tpu.core.values import from_plain
from guard_tpu.commands.report import rule_statuses_from_root
from guard_tpu.ops.native_oracle import (
    NativeEvalError,
    NativeOracle,
    NativeUnsupported,
    build_native,
    native_available,
)

REPO = pathlib.Path(__file__).resolve().parent.parent
CORPUS = REPO / "corpus" / "rules"
EXAMPLES = REPO / "examples" / "rules"

ST = {0: "PASS", 1: "FAIL", 2: "SKIP"}


@pytest.fixture(scope="module", autouse=True)
def _built():
    assert build_native(), "native oracle failed to build"
    assert native_available()


def _python_statuses(rf, doc):
    scope = RootScope(rf, doc)
    eval_rules_file(rf, scope, None)
    root = scope.reset_recorder().extract()
    return {n: s.value for n, s in rule_statuses_from_root(root).items()}


def _native_statuses(native, rf, doc):
    """Returns {rule: status} with the same same-name merge the report
    layer applies (non-SKIP beats SKIP, FAIL dominates)."""
    raw = native.eval_doc(doc)
    merged = {}
    for rule, s in zip(rf.guard_rules, raw):
        st = ST[s]
        prev = merged.get(rule.rule_name)
        if prev is None or (prev == "SKIP" and st != "SKIP"):
            merged[rule.rule_name] = st
        elif st == "FAIL":
            merged[rule.rule_name] = "FAIL"
    return merged


def _differential(rules_text, docs_plain, name="diff.guard"):
    """Both engines must agree (or the native one must decline/error
    exactly when Python errors)."""
    rf = parse_rules_file(rules_text, name)
    native = NativeOracle(rf)
    checked = declined = 0
    try:
        for i, dp in enumerate(docs_plain):
            doc = from_plain(dp)
            try:
                nat = _native_statuses(native, rf, doc)
            except NativeUnsupported:
                declined += 1
                continue
            except NativeEvalError:
                # python must error too
                with pytest.raises(GuardError):
                    _python_statuses(rf, doc)
                checked += 1
                continue
            py = _python_statuses(rf, doc)
            assert nat == py, f"{name} doc {i}: native={nat} python={py}"
            checked += 1
    finally:
        native.close()
    return checked, declined


# ---------------------------------------------------------------------------
# corpus-wide differential (the registry-gate analogue)
# ---------------------------------------------------------------------------
def test_corpus_native_oracle_differential():
    guard_files = sorted(CORPUS.glob("*.guard"))
    assert len(guard_files) >= 200
    total_checked = total_declined = 0
    for g in guard_files:
        spec = yaml.safe_load((CORPUS / "tests" / f"{g.stem}_tests.yaml").read_text())
        docs_plain = [case.get("input") or {} for case in spec]
        checked, declined = _differential(g.read_text(), docs_plain, g.name)
        total_checked += checked
        total_declined += declined
    # the corpus must overwhelmingly run native (declines are the
    # exception, not the norm); 725 (file, doc) pairs as of round 4
    assert total_checked > 700, (total_checked, total_declined)
    assert total_declined < total_checked / 20, (total_checked, total_declined)


def test_examples_native_oracle_differential():
    pairs = 0
    for g in sorted(EXAMPLES.rglob("*.guard")):
        tests_dir = g.parent / "tests"
        if not tests_dir.is_dir():
            continue
        for spec_file in sorted(tests_dir.glob(f"{g.stem}*_tests.yaml")):
            spec = yaml.safe_load(spec_file.read_text())
            docs_plain = [case.get("input") or {} for case in spec]
            checked, _ = _differential(g.read_text(), docs_plain, g.name)
            pairs += checked
    assert pairs > 20, pairs


# ---------------------------------------------------------------------------
# targeted semantic shapes (the evaluator-port edge cases)
# ---------------------------------------------------------------------------
DOCS = [
    {"Resources": {"a": {"Type": "A", "N": 5, "Tags": [{"K": "x"}, {"K": "y"}]}}},
    {"Resources": {"a": {"Type": "B", "N": 5.0, "Tags": []}}},
    {"Resources": {}},
    {"Resources": {"a": {"Type": "A"}, "b": {"Type": "A", "N": 7}}},
    {},
]


def test_numeric_no_coercion():
    # 1 == 1.0 is NotComparable -> FAIL on both engines
    _differential(
        "rule r when Resources.a exists { Resources.a.N == 5 }", DOCS
    )


def test_unresolved_lattice_and_negation():
    _differential(
        """
rule r1 when Resources exists { Resources.a.Missing exists }
rule r2 when Resources exists { Resources.a.Missing !exists }
rule r3 when Resources exists { Resources.a.Missing empty }
rule r4 when Resources exists { not Resources.a.Missing empty }
rule r5 when Resources exists { Resources.a.N != 6 }
""",
        DOCS,
    )


def test_some_vs_match_all():
    _differential(
        """
rule all_tags when Resources.a.Tags !empty { Resources.a.Tags[*].K == 'x' }
rule some_tags when Resources.a.Tags !empty { some Resources.a.Tags[*].K == 'x' }
""",
        DOCS,
    )


def test_filters_and_variables():
    _differential(
        """
let typed = Resources.*[ Type == 'A' ]

rule has_a when %typed !empty { %typed.N exists }
rule in_list when Resources exists { Resources.*.Type IN ['A', 'B'] }
rule keyed when Resources exists { Resources[ keys == /^a/ ].Type == 'A' }
""",
        DOCS,
    )


def test_blocks_when_named_and_ranges():
    _differential(
        """
rule base when Resources exists {
    Resources.* {
        Type exists
        when N exists { N IN r[0, 10) }
    }
}

rule downstream when Resources exists {
    base
}
rule neg_downstream when Resources exists {
    not base
}
""",
        DOCS,
    )


def test_parameterized_rules():
    _differential(
        """
rule check(expected) {
    Resources.*.Type == %expected
}

rule call_a when Resources exists { check('A') }
""",
        DOCS,
    )


def test_query_to_query_and_string_ops():
    _differential(
        """
rule qq when Resources exists { Resources.a.Type == Resources.b.Type }
rule substr when Resources.a.Type exists { Resources.a.Type IN 'ABC' }
""",
        DOCS,
    )


def test_builtin_functions_differential():
    docs = [
        {"Resources": {"x": {"Name": "hello", "Count": "42", "Flag": "true",
                             "Json": '{"a": [1, 2]}', "Ts": "2023-01-15T10:30:00Z",
                             "Url": "a%20b", "F": "3.25"}}},
        {"Resources": {"x": {"Name": "WORLD", "Count": "7", "Flag": "false",
                             "Json": '[true, null]', "Ts": "2020-06-01",
                             "Url": "plain", "F": "10"}}},
    ]
    _differential(
        """
let names = Resources.*.Name
let upper = to_upper(%names)
let lower = to_lower(%names)
let n = parse_int(Resources.*.Count)
let f = parse_float(Resources.*.F)
let b = parse_boolean(Resources.*.Flag)
let j = json_parse(Resources.*.Json)
let epoch = parse_epoch(Resources.*.Ts)
let dec = url_decode(Resources.*.Url)
let joined = join(%names, ",")
let cnt = count(Resources.*.Name)
let sub = substring(%names, 0, 3)
let rep = regex_replace(%names, "l+", "L")

rule r1 when Resources exists { %upper exists }
rule r2 when Resources exists { %lower exists }
rule r3 when Resources exists { %n >= 7 }
rule r4 when Resources exists { %f > 3 }
rule r5 when Resources exists { %b exists }
rule r6 when Resources exists { %j !empty }
rule r7 when Resources exists { %epoch > 1577836800 }
rule r8 when Resources exists { %dec exists }
rule r9 when Resources exists { %joined exists }
rule r10 when Resources exists { %cnt == 1 }
rule r11 when Resources exists { %sub exists }
rule r12 when Resources exists { %rep exists }
""",
        docs,
    )


def test_eval_error_parity():
    # join over unresolved values raises on both engines
    rf = parse_rules_file(
        """
let joined = join(Resources.*.Missing, ",")
rule r when Resources exists { %joined exists }
""",
        "err.guard",
    )
    native = NativeOracle(rf)
    doc = from_plain({"Resources": {"a": {"Type": "A"}}})
    with pytest.raises(NativeEvalError):
        native.eval_doc(doc)
    with pytest.raises(GuardError):
        _python_statuses(rf, doc)
    native.close()


# ---------------------------------------------------------------------------
# records mode: the full evaluation record tree must be byte-identical
# (serde encoding) to the Python evaluator's, so reports built from it
# are bit-exact
# ---------------------------------------------------------------------------
def _records_differential(rules_text, docs_plain, name="rec.guard"):
    import json as _json

    from guard_tpu.commands.report import (
        serde_record_json,
        simplified_report_from_root,
    )

    rf = parse_rules_file(rules_text, name)
    native = NativeOracle(rf)
    checked = declined = 0
    try:
        for i, dp in enumerate(docs_plain):
            doc = from_plain(dp)
            try:
                nat_root = native.eval_records(doc, f"d{i}.json")
            except NativeUnsupported:
                declined += 1
                continue
            except NativeEvalError:
                with pytest.raises(GuardError):
                    _python_statuses(rf, doc)
                checked += 1
                continue
            scope = RootScope(rf, doc)
            eval_rules_file(rf, scope, f"d{i}.json")
            py_root = scope.reset_recorder().extract()
            nat_j = _json.dumps(serde_record_json(nat_root), sort_keys=True)
            py_j = _json.dumps(serde_record_json(py_root), sort_keys=True)
            assert nat_j == py_j, f"{name} doc {i}: record trees differ"
            assert simplified_report_from_root(
                nat_root, f"d{i}.json"
            ) == simplified_report_from_root(py_root, f"d{i}.json")
            checked += 1
    finally:
        native.close()
    return checked, declined


def test_corpus_records_differential():
    guard_files = sorted(CORPUS.glob("*.guard"))
    total_checked = total_declined = 0
    for g in guard_files:
        spec = yaml.safe_load((CORPUS / "tests" / f"{g.stem}_tests.yaml").read_text())
        docs_plain = [case.get("input") or {} for case in spec]
        checked, declined = _records_differential(g.read_text(), docs_plain, g.name)
        total_checked += checked
        total_declined += declined
    assert total_checked > 700, (total_checked, total_declined)
    assert total_declined < total_checked / 20, (total_checked, total_declined)


def test_examples_records_differential():
    pairs = 0
    for g in sorted(EXAMPLES.rglob("*.guard")):
        tests_dir = g.parent / "tests"
        if not tests_dir.is_dir():
            continue
        for spec_file in sorted(tests_dir.glob(f"{g.stem}*_tests.yaml")):
            spec = yaml.safe_load(spec_file.read_text())
            docs_plain = [case.get("input") or {} for case in spec]
            checked, _ = _records_differential(g.read_text(), docs_plain, g.name)
            pairs += checked
    assert pairs > 20, pairs


def test_semantic_shapes_records_differential():
    # the same edge shapes the statuses differential drives, now at
    # record-tree fidelity (custom messages + unresolved reasons incl.)
    _records_differential(
        """
rule r1 when Resources exists { Resources.a.Missing exists <<must exist>> }
rule r2 when Resources exists { not Resources.a.Missing empty }
rule r3 when Resources exists { Resources.a.N != 6 }
rule r4 when Resources exists { Resources.a.Tags[*].K == 'x' or Resources.a.N >= 5 }
rule blocky when Resources exists {
    Resources.* {
        Type exists
        when N exists { N IN r[0, 10) }
    }
}
rule downstream when Resources exists {
    blocky
}
rule typed when Resources exists {
    Resources.*[ Type == 'A' ].N == 5
}
""",
        DOCS,
    )
    _records_differential(
        """
rule check(expected) {
    Resources.*.Type == %expected <<wrong type>>
}
rule call_a when Resources exists { check('A') }
rule keyed when Resources exists { Resources[ keys == /^a/ ].Type == 'A' }
rule qq when Resources exists { Resources.a.Type == Resources.b.Type }
""",
        DOCS,
    )


# ---------------------------------------------------------------------------
# report mode: the natively-assembled simplified report (the path the
# backend and bench actually use) must byte-equal the Python one
# ---------------------------------------------------------------------------
def _report_differential(rules_text, docs_plain, name="rep.guard"):
    import json as _json

    from guard_tpu.commands.report import (
        rule_statuses_from_root,
        simplified_report_from_root,
    )
    from guard_tpu.core.loader import load_document

    rf = parse_rules_file(rules_text, name)
    native = NativeOracle(rf)
    checked = declined = 0
    try:
        for i, dp in enumerate(docs_plain):
            raw = _json.dumps(dp)
            doc = load_document(raw, f"d{i}.json")  # real loader marks
            for source in ("raw", "pv"):
                try:
                    if source == "raw":
                        nat = native.eval_report_raw(raw, f"d{i}.json")
                    else:
                        nat = native.eval_report(doc, f"d{i}.json")
                except NativeUnsupported:
                    declined += 1
                    continue
                except NativeEvalError:
                    with pytest.raises(GuardError):
                        _python_statuses(rf, doc)
                    checked += 1
                    continue
                rep, statuses, overall = nat
                scope = RootScope(rf, doc)
                st = eval_rules_file(rf, scope, f"d{i}.json")
                root = scope.reset_recorder().extract()
                assert rep == simplified_report_from_root(root, f"d{i}.json"), (
                    f"{name} doc {i} [{source}]: report differs"
                )
                assert statuses == rule_statuses_from_root(root)
                assert overall == st
                checked += 1
    finally:
        native.close()
    return checked, declined


def test_corpus_report_differential():
    guard_files = sorted(CORPUS.glob("*.guard"))
    total_checked = total_declined = 0
    for g in guard_files:
        spec = yaml.safe_load((CORPUS / "tests" / f"{g.stem}_tests.yaml").read_text())
        docs_plain = [case.get("input") or {} for case in spec]
        checked, declined = _report_differential(g.read_text(), docs_plain, g.name)
        total_checked += checked
        total_declined += declined
    assert total_checked > 1400, (total_checked, total_declined)  # raw + pv legs
    assert total_declined < total_checked / 20, (total_checked, total_declined)


def test_semantic_shapes_report_differential():
    _report_differential(
        """
rule r1 when Resources exists { Resources.a.Missing exists <<must exist>> }
rule r2 when Resources exists { not Resources.a.Missing empty }
rule r3 when Resources exists { Resources.a.N != 6 }
rule r4 when Resources exists { Resources.a.Tags[*].K == 'x' or Resources.a.N >= 5 }
rule in_list when Resources exists { Resources.*.Type IN ['A', 'B'] }
rule blocky when Resources exists {
    Resources.* {
        Type exists
        when N exists { N IN r[0, 10) }
    }
}
rule downstream when Resources exists {
    blocky
}
""",
        DOCS,
    )


def test_report_float_rendering_differential():
    # the review-found %g divergence class: integral and exponent-range
    # floats embedded in report messages
    docs = [
        {"N": v}
        for v in [10.0, 20.0, 100000.0, 1e15, 1e16, 1e17, 0.0001, 1.5e-5,
                   2.5, -10.0, 123456789012345680.0, 0.1]
    ]
    _report_differential("rule r { N == 5 }", docs, "floats.guard")


# ---------------------------------------------------------------------------
# the decline path: uncertain constructs fall back, never guess
# ---------------------------------------------------------------------------
def test_unsupported_regex_declines():
    # POSIX class syntax: python treats `[[:alpha:]]` as a literal
    # char class, pcre2/ecmascript as a posix class -> must decline
    rf = parse_rules_file(
        "rule r when Resources exists { Resources.a.Type == /[[:alpha:]]+/ }",
        "posix.guard",
    )
    native = NativeOracle(rf)
    with pytest.raises(NativeUnsupported):
        native.eval_doc(from_plain({"Resources": {"a": {"Type": "xy"}}}))
    native.close()


def test_lookbehind_declines():
    # python `re` demands fixed-width lookbehind bodies and errors on
    # variable-width ones; pcre2 is laxer, so lookbehind stays declined
    rf = parse_rules_file(
        "rule r when V exists { V == /(?<=x)y/ }", "look.guard"
    )
    native = NativeOracle(rf)
    with pytest.raises(NativeUnsupported):
        native.eval_doc(from_plain({"V": "xy"}))
    native.close()


def test_review_findings_regressions():
    """Round-4 code-review findings: epoch grammar/calendar, huge-float
    parse_int, json_parse control chars, closed-handle guard."""
    # Feb 30 is calendar-invalid: BOTH engines error
    rf = parse_rules_file(
        """
let e = parse_epoch(Resources.*.Ts)
rule r when Resources exists { %e > 0 }
""",
        "epoch.guard",
    )
    native = NativeOracle(rf)
    bad = from_plain({"Resources": {"a": {"Ts": "2023-02-30T00:00:00Z"}}})
    with pytest.raises(NativeEvalError):
        native.eval_doc(bad)
    with pytest.raises(GuardError):
        _python_statuses(rf, bad)
    # hour-only time: python evaluates; the native grammar declines
    hour_only = from_plain({"Resources": {"a": {"Ts": "2023-01-15T10"}}})
    with pytest.raises(NativeUnsupported):
        native.eval_doc(hour_only)
    _python_statuses(rf, hour_only)  # must not raise
    # leap-year Feb 29 agrees
    _differential(
        """
let e = parse_epoch(Resources.*.Ts)
rule r when Resources exists { %e > 0 }
""",
        [{"Resources": {"a": {"Ts": "2024-02-29T12:00:00Z"}}}],
    )
    native.close()

    # parse_int on a float outside i64: python is exact -> decline
    rf2 = parse_rules_file(
        """
let n = parse_int(Resources.*.Big)
rule r when Resources exists { %n > 0 }
""",
        "big.guard",
    )
    native2 = NativeOracle(rf2)
    with pytest.raises(NativeUnsupported):
        native2.eval_doc(from_plain({"Resources": {"a": {"Big": 1e30}}}))

    # closed handle raises instead of passing NULL into C
    native2.close()
    with pytest.raises(NativeUnsupported):
        native2.eval_doc(from_plain({"Resources": {}}))

    # json_parse with a raw control char in the string declines
    # (pyyaml line-folds; keeping the newline would silently diverge)
    rf3 = parse_rules_file(
        """
let j = json_parse(Resources.*.Payload)
rule r when Resources exists { %j exists }
""",
        "ctrl.guard",
    )
    native3 = NativeOracle(rf3)
    with pytest.raises(NativeUnsupported):
        native3.eval_doc(
            from_plain({"Resources": {"a": {"Payload": '{"a": "x\ny"}'}}})
        )
    native3.close()


def test_non_ascii_case_conversion_declines():
    rf = parse_rules_file(
        """
let u = to_upper(Resources.*.Name)
rule r when Resources exists { %u exists }
""",
        "uni.guard",
    )
    native = NativeOracle(rf)
    with pytest.raises(NativeUnsupported):
        native.eval_doc(from_plain({"Resources": {"a": {"Name": "über"}}}))
    # ascii docs still evaluate
    assert native.eval_doc(from_plain({"Resources": {"a": {"Name": "ok"}}}))
    native.close()


def test_supported_regex_agree():
    docs = [
        {"V": v}
        for v in ["abc", "ABC", "a-b", "x.y", "10.0.0.1", "arn:aws:iam::123",
                   "", "multi\nline", "end$"]
    ]
    _differential(
        r"""
rule anchored when V exists { V == /^a/ }
rule cls when V exists { V == /[a-z]+[-.][a-z]+/ }
rule alt when V exists { V == /(abc|xyz)/ }
rule ipish when V exists { V == /^10\.(\d+)\.\d+\.\d+$/ }
rule icase when V exists { V == /(?i)abc/ }
rule rep when V exists { V == /a{1,2}b/ }
""",
        docs,
    )


def test_raw_json_path_typing_differential():
    """eval_raw_json (the C++ raw scanner) must type scalars exactly
    like the location-aware loader: quoted strings stay strings,
    undotted numbers are ints, dotted/exponent numbers floats."""
    import json

    from guard_tpu.core.loader import load_document

    rules = """
rule is_int when V exists { V is_int }
rule is_float when V exists { V is_float }
rule is_str when V exists { V is_string }
rule is_bool when V exists { V is_bool }
rule is_null when Marker exists { V is_null }
rule big when V exists { V >= 5 }
rule eq5 when V exists { V == 5 }
rule eq5f when V exists { V == 5.0 }
"""
    rf = parse_rules_file(rules, "typing.guard")
    native = NativeOracle(rf)
    docs = [
        {"V": 5},
        {"V": 5.0},
        {"V": "5"},
        {"V": 5.5},
        {"V": -0},
        {"V": 1e3},
        {"V": 123456789012345678},
        {"V": True},
        {"V": None, "Marker": 1},
        {"V": [1, 2.5, "x", {"a": 1}]},
        {"V": {"nested": {"deep": [True, None]}}},
    ]
    checked = 0
    for dp in docs:
        raw = json.dumps(dp)
        doc = load_document(raw, "d.json")
        try:
            nat = native.eval_raw_json(raw)
        except NativeUnsupported:
            continue
        merged = {}
        for rule, s in zip(rf.guard_rules, nat):
            merged[rule.rule_name] = ST[s]
        py = _python_statuses(rf, doc)
        assert merged == py, f"{raw}: native={merged} python={py}"
        checked += 1
    assert checked >= len(docs) - 1

    # raw negative-zero tokens (json.dumps would fold int -0 to 0)
    for raw in ('{"V": -0}', '{"V": -0.0}'):
        doc = load_document(raw, "nz.json")
        assert native.eval_raw_json(raw) == native.eval_doc(doc), raw

    # duplicate keys: loader keeps first position, last value
    raw = '{"V": 1, "V": 5}'
    assert native.eval_raw_json(raw) == native.eval_doc(
        load_document(raw, "dup.json")
    )

    # ints outside i64 decline on the raw path too
    with pytest.raises(NativeUnsupported):
        native.eval_raw_json('{"V": 99999999999999999999999999}')
    native.close()


def test_case_converter_key_fallback():
    # key-case converters (camel/pascal/kebab/...) in the walk
    docs = [
        {"Resources": {"a": {"instanceType": "t2"}}},
        {"Resources": {"a": {"instance_type": "t2"}}},
        {"Resources": {"a": {"instance-type": "t2"}}},
        {"Resources": {"a": {"InstanceType": "t2"}}},
        {"Resources": {"a": {"INSTANCE_TYPE": "t2"}}},
    ]
    _differential(
        "rule r when Resources exists { Resources.a.InstanceType == 't2' }",
        docs,
    )
    _differential(
        "rule r when Resources exists { Resources.a.instance_type == 't2' }",
        docs,
    )


def test_native_oracle_thread_safety_two_thread_hammer():
    """The per-thread handle pool (PR 3): ONE shared NativeOracle
    hammered from two threads must produce exactly the serial results
    — the former one-handle design shared an unsynchronized regex
    cache/pcre2 match data across threads (a documented footgun, now
    fixed for the pipelined consumer stage)."""
    import threading

    rf = parse_rules_file(
        "rule named { Resources.*.Name == /^prod-[a-z0-9-]+$/ }\n"
        "rule sized { Resources.*.Size <= 100 }\n",
        "mt.guard",
    )
    docs = [
        from_plain(
            {
                "Resources": {
                    "r": {
                        "Name": f"prod-app-{i}" if i % 3 else f"DEV_{i}",
                        "Size": (i * 7) % 160,
                    }
                }
            }
        )
        for i in range(40)
    ]
    native = NativeOracle(rf)
    try:
        expected = [native.eval_doc(d) for d in docs]
        results = {0: [], 1: []}
        errors = []

        def hammer(slot):
            try:
                for _ in range(5):
                    out = [native.eval_doc(d) for d in docs]
                    results[slot].append(out)
            except Exception as e:  # pragma: no cover - the regression
                errors.append(e)

        threads = [
            threading.Thread(target=hammer, args=(s,)) for s in (0, 1)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors
        for slot in (0, 1):
            assert len(results[slot]) == 5
            for out in results[slot]:
                assert out == expected
    finally:
        native.close()
    # closed oracles refuse cleanly from any thread
    with pytest.raises(NativeUnsupported):
        native.eval_doc(docs[0])
