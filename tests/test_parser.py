"""Parser grammar coverage, pinned against parser.rs productions."""

import pathlib

import pytest

from guard_tpu.core.errors import ParseError
from guard_tpu.core.exprs import (
    BlockGuardClause,
    CmpOperator,
    GuardAccessClause,
    GuardNamedRuleClause,
    ParameterizedNamedRuleClause,
    QAllIndices,
    QAllValues,
    QFilter,
    QIndex,
    QKey,
    QMapKeyFilter,
    TypeBlock,
    WhenBlockClause,
)
from guard_tpu.core.parser import Parser, parse_rules_file
from guard_tpu.core.values import RANGE_INT, REGEX


def parse_clause(text):
    return Parser(text, "t").clause()


def test_basic_binary_clause():
    c = parse_clause("Properties.BucketName != /(?i)encrypt/")
    assert isinstance(c, GuardAccessClause)
    assert c.access_clause.comparator == CmpOperator.Eq
    assert c.access_clause.comparator_inverse is True
    assert c.access_clause.compare_with.kind == REGEX


def test_unary_with_custom_message():
    c = parse_clause("Resources !empty <<no resources>>")
    assert c.access_clause.comparator == CmpOperator.Empty
    assert c.access_clause.comparator_inverse is True
    assert c.access_clause.custom_message == "no resources"


def test_some_keyword_sets_match_all_false():
    c = parse_clause("some Tags[*].Key == /PROD$/")
    assert c.access_clause.query.match_all is False


def test_variable_gets_implicit_all_indices():
    c = parse_clause("%resources.Properties exists")
    q = c.access_clause.query.query
    assert isinstance(q[0], QKey) and q[0].name == "%resources"
    assert isinstance(q[1], QAllIndices)
    assert isinstance(q[2], QKey) and q[2].name == "Properties"


def test_filter_query():
    c = parse_clause("Resources.*[ Type == 'AWS::S3::Bucket' ] exists")
    q = c.access_clause.query.query
    assert isinstance(q[1], QAllValues)
    assert isinstance(q[2], QFilter)


def test_map_keys_match():
    c = parse_clause("Condition.*[ keys == /aws:[sS]ourceVpc/ ] !empty")
    q = c.access_clause.query.query
    assert isinstance(q[2], QMapKeyFilter)


def test_range_literal():
    c = parse_clause("Properties.Size IN r[50,200]")
    assert c.access_clause.compare_with.kind == RANGE_INT


def test_bracket_variants():
    p = Parser("a[*].b[0].c['key-name'].d[ x ]", "t")
    q = p.access().query
    kinds = [type(part).__name__ for part in q]
    assert kinds == [
        "QKey", "QAllIndices", "QKey", "QIndex", "QKey", "QKey", "QKey",
        "QAllValues",
    ]
    assert q[4].name == "c"
    assert q[5].name == "key-name"
    assert q[7].name == "x"  # [ x ] -> AllValues capture


def test_block_clause_not_empty():
    c = parse_clause("Properties.Tags !empty {\n  Key exists\n}")
    assert isinstance(c, BlockGuardClause)
    assert c.not_empty is True


def test_when_block_clause():
    c = parse_clause("when a == 1 {\n  b == 2\n}")
    assert isinstance(c, WhenBlockClause)


def test_parameterized_call():
    c = parse_clause("check_sse(%buckets, 'aws:kms')")
    assert isinstance(c, ParameterizedNamedRuleClause)
    assert c.named_rule.dependent_rule == "check_sse"
    assert len(c.parameters) == 2


def test_cnf_or_joins():
    rf = parse_rules_file(
        "rule r {\n  a == 1 OR\n  b == 2\n  c == 3\n}\n", ""
    )
    conj = rf.guard_rules[0].block.conjunctions
    assert len(conj) == 2
    assert len(conj[0]) == 2  # a OR b
    assert len(conj[1]) == 1  # c


def test_type_block_desugars_to_resources_query():
    rf = parse_rules_file("AWS::S3::Bucket {\n  Properties exists\n}\n", "")
    tb = rf.guard_rules[0].block.conjunctions[0][0]
    assert isinstance(tb, TypeBlock)
    assert tb.type_name == "AWS::S3::Bucket"
    assert isinstance(tb.query[2], QFilter)


def test_default_rule_name_with_file():
    rf = parse_rules_file("a == 1\n", "my.guard")
    assert rf.guard_rules[0].rule_name == "my.guard/default"
    rf2 = parse_rules_file("a == 1\n", "")
    assert rf2.guard_rules[0].rule_name == "default"


def test_empty_file_returns_none():
    assert parse_rules_file("", "x") is None
    assert parse_rules_file("# comments only\n", "x") is None


def test_named_rule_reference():
    rf = parse_rules_file(
        "rule a {\n  x == 1\n}\nrule b {\n  a\n}\n", ""
    )
    ref = rf.guard_rules[1].block.conjunctions[0][0]
    assert isinstance(ref, GuardNamedRuleClause)
    assert ref.dependent_rule == "a"


def test_assignment_forms():
    rf = parse_rules_file(
        "let a = 10\nlet b := Resources.*\nlet c = count(%b)\n"
        "rule r { %a == 10 }\n",
        "",
    )
    assert len(rf.assignments) == 3


def test_invalid_rule_rejected():
    with pytest.raises(ParseError):
        parse_rules_file('"">/\\\n', "bad")


@pytest.mark.parametrize(
    "path",
    sorted(
        p
        for p in pathlib.Path("/root/reference/guard-examples").rglob("*.guard")
    ),
    ids=lambda p: p.name,
)
def test_reference_examples_parse(path):
    assert parse_rules_file(path.read_text(), path.name) is not None


def test_float_exponent_grammar_matches_reference():
    """parser.rs:230-243: the float gate needs a fraction or a SIGNED
    exponent, but nom `double` then consumes unsigned exponents too —
    `1.5e3` parses as 1500.0 while `2e3` is not a float (and `e3`
    residue makes the clause unparseable)."""
    from guard_tpu.core.errors import GuardError

    rf = parse_rules_file("rule r { x == 1.5e3 }", "f.guard")
    cw = rf.guard_rules[0].block.conjunctions[0][0].access_clause.compare_with
    assert cw.val == 1500.0
    rf = parse_rules_file("rule r { x == 2e+3 }", "f.guard")
    cw = rf.guard_rules[0].block.conjunctions[0][0].access_clause.compare_with
    assert cw.val == 2000.0
    rf = parse_rules_file("rule r { x == 1.5E-2 }", "f.guard")
    cw = rf.guard_rules[0].block.conjunctions[0][0].access_clause.compare_with
    assert cw.val == 0.015
    with pytest.raises(GuardError):
        parse_rules_file("rule r { x == 2e3 }", "f.guard")
