"""Test configuration: force an 8-device virtual CPU mesh so sharding
tests exercise real multi-device paths without TPU hardware."""

import os

# HARD set (not setdefault): the ambient environment on TPU driver
# hosts exports JAX_PLATFORMS=axon, and test SUBPROCESSES (CLI parity
# tests) inherit os.environ — with a wedged TPU tunnel they would hang
# at device discovery. Tests that exercise ambient-platform handling
# (test_multichip_dryrun) build their own env explicitly.
os.environ["JAX_PLATFORMS"] = "cpu"

# keep the TPU-like formulation split under test: without this, the
# CPU-only suite would trace ONLY the gather kernels (the CPU override
# forces gather at every bucket) and the one-hot branches production
# TPU uses below GATHER_MIN_NODES would lose nearly all coverage.
# test_gather_kernels still compares both formulations explicitly.
os.environ["GUARD_TPU_GATHER_ON_CPU"] = "0"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The plan artifact layer persists content-addressed plans under
# ~/.cache/guard_tpu/plans by default. The suite must neither read a
# previous checkout's artifacts nor leave its own behind, so it runs
# against a throwaway cache dir (an explicit operator setting wins;
# individual tests override with monkeypatch).
import tempfile

os.environ.setdefault(
    "GUARD_TPU_PLAN_CACHE_DIR", tempfile.mkdtemp(prefix="guard_plans_")
)

# The incremental plane's result cache is keyed by CONTENT (not path),
# so two tests evaluating the same small fixture docs would cross-hit
# and silently turn full-dispatch assertions (dispatch counters, rim
# counters, fault ladders) into replays. Default it off for the suite;
# the dedicated result-cache tests opt in with monkeypatch + a private
# cache dir. The throwaway dir below covers any test that re-enables
# the flag without overriding the directory.
os.environ.setdefault("GUARD_TPU_RESULT_CACHE", "0")
os.environ.setdefault(
    "GUARD_TPU_RESULT_CACHE_DIR", tempfile.mkdtemp(prefix="guard_results_")
)

# The durability plane's sweep journal persists per-run chunk records
# under ~/.cache/guard_tpu/journal by default, keyed by (rules, docs,
# config) content — two suite runs over the same fixtures would replay
# each other's journals and turn dispatch-count assertions into
# no-dispatch replays. Point the suite at a throwaway dir; durability
# tests override per-test with monkeypatch.
os.environ.setdefault(
    "GUARD_TPU_JOURNAL_DIR", tempfile.mkdtemp(prefix="guard_journal_")
)

# The flight recorder is armed by default in production (abnormal exits
# dump forensics into the working directory). The suite exercises
# hundreds of deliberate exit-5 paths — without this default-off, every
# one would litter flightrec-*.json files in the checkout. Dedicated
# operations-plane tests arm it explicitly (monkeypatch + refresh).
os.environ.setdefault("GUARD_TPU_FLIGHT_RECORDER", "0")

# Force the CPU platform programmatically as well: with a wedged axon
# TPU tunnel, plugin discovery can hang even under JAX_PLATFORMS=cpu.
import jax

jax.config.update("jax_platforms", "cpu")

import pathlib
import sys

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

REFERENCE = pathlib.Path("/root/reference")


def reference_available() -> bool:
    return REFERENCE.exists()
