"""Fused multi-rule-file dispatch (ops/ir.pack_compiled + the backend
pack planner): the packed path must be BIT-IDENTICAL to the per-file
path — statuses, unsure bits, reports and exit codes — while issuing
an order of magnitude fewer device dispatches. The parity spans
examples/rules/, a sampled slice of the registry corpus, and mixes
that include host-fallback and function-variable rule files (which the
planner must route back to the per-file path, not silently drop)."""

import glob
import io
import json
import pathlib

import numpy as np
import pytest

import bench
from guard_tpu.cli import run
from guard_tpu.core.parser import parse_rules_file
from guard_tpu.core.values import from_plain
from guard_tpu.ops.backend import (
    _evaluate_packs,
    dispatch_stats,
    plan_packs,
    reset_dispatch_stats,
)
from guard_tpu.ops.encoder import encode_batch
from guard_tpu.ops.ir import (
    PackIncompatible,
    compile_rules_file,
    pack_compatible,
    pack_compiled,
)
from guard_tpu.ops.kernels import segment_any, segment_doc_status
from guard_tpu.parallel.mesh import ShardedBatchEvaluator
from guard_tpu.utils.io import Writer

REPO = pathlib.Path(__file__).resolve().parent.parent
CORPUS = REPO / "corpus" / "rules"


def _corpus_slice(n_files, n_docs=32):
    """(docs, [RulesFile]) over the first n_files corpus rule files and
    the union of their own test inputs."""
    import yaml

    paths = sorted(CORPUS.glob("*.guard"))[:n_files]
    docs_plain = []
    for p in paths:
        spec = CORPUS / "tests" / f"{p.stem}_tests.yaml"
        if spec.exists():
            for case in yaml.safe_load(spec.read_text()) or []:
                if isinstance(case, dict) and "input" in case:
                    docs_plain.append(case["input"])
    docs = [from_plain(d) for d in docs_plain][:n_docs]
    rfs = [parse_rules_file(p.read_text(), p.name) for p in paths]
    return docs, rfs


def _example_rules():
    out = []
    for p in sorted(REPO.glob("examples/rules/*/*.guard")):
        out.append(parse_rules_file(p.read_text(), p.name))
    return out


def _perfile_vs_packed(docs, rfs):
    """Evaluate every packable file through both paths; assert
    bit-identity of statuses AND unsure bits."""
    batch, interner = encode_batch(docs)
    compiled_files = [compile_rules_file(rf, interner) for rf in rfs]
    items = [
        (fi, c)
        for fi, c in enumerate(compiled_files)
        if pack_compatible(c) is None
    ]
    packed_results = _evaluate_packs(items, batch)
    assert set(packed_results) == {fi for fi, _ in items}
    for fi, c in items:
        ev = ShardedBatchEvaluator(c)
        st, un, hd = ev.evaluate_bucketed(batch)
        pst, pun, phd = packed_results[fi][:3]
        assert np.array_equal(pst, st), f"statuses diverge for file {fi}"
        assert np.array_equal(pun, un), f"unsure diverges for file {fi}"
        assert phd == hd
    return compiled_files, items


def test_packed_parity_corpus_slice():
    docs, rfs = _corpus_slice(24)
    assert docs, "corpus test inputs missing"
    _perfile_vs_packed(docs, rfs)


def test_packed_parity_examples():
    rng = np.random.default_rng(2)
    docs = [from_plain(bench.make_template(rng, i)) for i in range(12)]
    docs += [from_plain(bench.make_config_item(rng, i)) for i in range(6)]
    rfs = _example_rules()
    assert len(rfs) >= 5
    compiled_files, items = _perfile_vs_packed(docs, rfs)
    # the examples mix packable and unpackable (fn-var / host-only)
    # files; the planner must not have dropped any packable one
    assert len(items) >= 2


def test_packed_parity_mixed_host_fallback():
    """A pack whose neighbors include a host-fallback-only file and a
    function-variable file: both must route to the per-file path while
    the rest pack, and the end result must be identical."""
    rng = np.random.default_rng(7)
    docs = [from_plain(bench.make_template(rng, i)) for i in range(8)]
    host_only = parse_rules_file(
        "rule host_now { Resources.created == now() }", "host.guard"
    )
    fn_file = parse_rules_file(
        "let upper = to_upper(Resources.*.Type)\n"
        "rule named when Resources exists { %upper !empty }",
        "fn.guard",
    )
    packable = [
        parse_rules_file(bench.RULES, "a.guard"),
        parse_rules_file(bench.ENCRYPTION_RULES, "b.guard"),
    ]
    batch, interner = encode_batch(docs)
    compiled = [
        compile_rules_file(rf, interner)
        for rf in [packable[0], host_only, fn_file, packable[1]]
    ]
    assert compiled[1].host_rules, "now() should refuse lowering"
    reasons = [pack_compatible(c) for c in compiled]
    assert reasons[0] is None and reasons[3] is None
    assert reasons[2] is not None, "fn-var file must be pack-excluded"
    items = [
        (fi, c) for fi, c in enumerate(compiled) if pack_compatible(c) is None
    ]
    packed_results = _evaluate_packs(items, batch)
    for fi, c in items:
        if fi not in packed_results:
            continue
        st, un, _hd = ShardedBatchEvaluator(c).evaluate_bucketed(batch)
        assert np.array_equal(packed_results[fi][0], st)
        assert np.array_equal(packed_results[fi][1], un)


def test_pack_incompatible_raises():
    rng = np.random.default_rng(9)
    docs = [from_plain(bench.make_template(rng, i)) for i in range(4)]
    batch, interner = encode_batch(docs)
    fn_file = parse_rules_file(
        "let upper = to_upper(Resources.*.Type)\n"
        "rule named when Resources exists { %upper !empty }",
        "fn.guard",
    )
    ok = compile_rules_file(parse_rules_file(bench.RULES, "a.guard"), interner)
    bad = compile_rules_file(fn_file, interner)
    with pytest.raises(PackIncompatible):
        pack_compiled([ok, bad])


def test_plan_packs_respects_rule_ceiling():
    class _C:
        def __init__(self, n):
            self.rules = [None] * n

    items = [(i, _C(3)) for i in range(10)]
    packs = plan_packs(items, max_rules=9)
    assert [len(p) for p in packs] == [3, 3, 3, 1]
    # file order preserved within and across packs
    assert [fi for p in packs for fi, _ in p] == list(range(10))


def test_packed_dispatch_counters_under_ceiling():
    """The acceptance counter: over a 24-file corpus slice the packed
    path must issue >= 10x fewer dispatches than the per-file path and
    stay under the pinned smoke ceiling."""
    docs, rfs = _corpus_slice(24)
    batch, interner = encode_batch(docs)
    compiled_files = [compile_rules_file(rf, interner) for rf in rfs]
    items = [
        (fi, c)
        for fi, c in enumerate(compiled_files)
        if pack_compatible(c) is None
    ]
    assert len(items) >= 20
    reset_dispatch_stats()
    _evaluate_packs(items, batch)
    packed = dispatch_stats()
    reset_dispatch_stats()
    for _, c in items:
        ShardedBatchEvaluator(c).evaluate_bucketed(batch)
    perfile = dispatch_stats()
    assert packed["dispatches"] * 10 <= perfile["dispatches"]
    assert packed["dispatches"] <= 8  # the CI pack-smoke ceiling


def test_segment_doc_status_reduction():
    PASS, FAIL, SKIP = 0, 1, 2
    st = np.array(
        [[PASS, SKIP, FAIL, PASS], [SKIP, SKIP, PASS, SKIP]], np.int8
    )
    seg = np.array([0, 0, 1, 1])
    out = segment_doc_status(st, seg, 2)
    assert out.tolist() == [[PASS, FAIL], [SKIP, PASS]]
    any_fail = segment_any(st == FAIL, seg, 2)
    assert any_fail.tolist() == [[False, True], [False, False]]
    import jax.numpy as jnp

    outj = segment_doc_status(jnp.asarray(st), seg, 2)
    assert np.array_equal(np.asarray(outj), out)


def test_validate_cli_packed_vs_unpacked_end_to_end(tmp_path):
    """Exit codes + console output byte-identical with packing on and
    off, over a doc mix with real failures."""
    rng = np.random.default_rng(5)
    for i in range(10):
        (tmp_path / f"t{i}.json").write_text(
            json.dumps(bench.make_template(rng, i))
        )
    rules = sorted(glob.glob(str(CORPUS / "*.guard")))[:8]

    def run_cli(extra):
        out, err = io.StringIO(), io.StringIO()
        rc = run(
            ["validate", "--backend", "tpu", "-r", *rules,
             "-d", str(tmp_path)] + extra,
            writer=Writer(out=out, err=err),
        )
        return rc, out.getvalue(), err.getvalue()

    rc1, o1, e1 = run_cli([])
    rc2, o2, e2 = run_cli(["--no-pack"])
    assert (rc1, o1, e1) == (rc2, o2, e2)


def test_sweep_cli_packed_vs_unpacked(tmp_path):
    rng = np.random.default_rng(6)
    data = tmp_path / "data"
    data.mkdir()
    for i in range(12):
        (data / f"t{i}.json").write_text(
            json.dumps(bench.make_template(rng, i))
        )
    rules = sorted(glob.glob(str(CORPUS / "*.guard")))[:6]

    def run_sweep(extra, tag):
        out, err = io.StringIO(), io.StringIO()
        rc = run(
            ["sweep", "-r", *rules, "-d", str(data),
             "-M", str(tmp_path / f"m_{tag}.jsonl"), "-c", "5"] + extra,
            writer=Writer(out=out, err=err),
        )
        return rc, out.getvalue()

    rc1, o1 = run_sweep([], "packed")
    rc2, o2 = run_sweep(["--no-pack"], "unpacked")
    s1, s2 = json.loads(o1), json.loads(o2)
    assert rc1 == rc2
    for k in ("counts", "failed", "errors", "documents"):
        assert s1[k] == s2[k], k
