"""`--backend native` (VERDICT r4 item 3): the compiled C++ engine
(native/oracle.cpp) as a first-class CPU evaluation backend for
validate/test — byte-identical output to the pure-Python evaluator,
declining constructs fall back per (rule-file, document) pair, and the
CLI default (`auto`) resolves to it when the library is built.

Reference bar: compiled-engine evaluation everywhere
(/root/reference/guard/src/rules/eval.rs:1915)."""

import json

import pytest

import guard_tpu.commands.validate as vmod
from guard_tpu.cli import run
from guard_tpu.commands.validate import Validate, resolve_backend
from guard_tpu.ops.native_oracle import native_available
from guard_tpu.utils.io import Reader, Writer

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native oracle not built"
)

RULES = """\
rule s3_sse {
    Resources.*[ Type == "AWS::S3::Bucket" ] {
        Properties.BucketEncryption exists
        <<Bucket must be encrypted>>
    }
}
rule named when s3_sse {
    Resources.*.Name exists
}
"""


def _run(args):
    w = Writer.buffered()
    rc = run(args, writer=w, reader=Reader())
    return rc, w.out.getvalue(), w.err.getvalue()


def _mk(tmp_path, docs):
    (tmp_path / "r.guard").write_text(RULES)
    data = tmp_path / "data"
    data.mkdir()
    for name, body in docs.items():
        (data / name).write_text(
            body if isinstance(body, str) else json.dumps(body)
        )
    return str(tmp_path / "r.guard"), str(data)


PASS_DOC = {
    "Resources": {
        "b": {
            "Type": "AWS::S3::Bucket",
            "Properties": {"BucketEncryption": {"k": "v"}},
            "Name": "x",
        }
    }
}
FAIL_DOC = {"Resources": {"b": {"Type": "AWS::S3::Bucket", "Properties": {}}}}


def test_auto_resolves_to_native():
    assert resolve_backend("auto") == "native"
    assert resolve_backend("cpu") == "cpu"
    assert resolve_backend("tpu") == "tpu"


@pytest.mark.parametrize(
    "extra",
    [
        [],
        ["--verbose"],
        ["--print-json"],
        ["--show-summary", "all"],
        ["-o", "yaml"],
        ["--structured", "-o", "sarif", "--show-summary", "none"],
        ["--structured", "-o", "json", "--show-summary", "none"],
        ["--structured", "-o", "junit", "--show-summary", "none"],
    ],
)
def test_validate_byte_parity_vs_cpu(tmp_path, extra):
    rules, data = _mk(
        tmp_path, {"a_fail.json": FAIL_DOC, "b_pass.json": PASS_DOC}
    )
    base = ["validate", "-r", rules, "-d", data] + extra
    nat = _run(base + ["--backend", "native"])
    cpu = _run(base + ["--backend", "cpu"])
    assert nat == cpu
    assert nat[0] == 19


def test_default_backend_is_auto_and_matches_cpu(tmp_path):
    rules, data = _mk(tmp_path, {"t.json": FAIL_DOC})
    default = _run(["validate", "-r", rules, "-d", data])
    cpu = _run(["validate", "-r", rules, "-d", data, "--backend", "cpu"])
    assert default == cpu


def test_yaml_documents_take_tree_path(tmp_path):
    # YAML docs can't go raw-JSON into the engine: the PV wire path
    # must produce the same bytes
    rules, data = _mk(
        tmp_path,
        {"t.yaml": "Resources:\n  b:\n    Type: AWS::S3::Bucket\n    Properties: {}\n"},
    )
    nat = _run(["validate", "-r", rules, "-d", data, "--backend", "native"])
    cpu = _run(["validate", "-r", rules, "-d", data, "--backend", "cpu"])
    assert nat == cpu
    assert nat[0] == 19


def test_passing_json_corpus_builds_zero_trees(tmp_path, monkeypatch):
    rules, data = _mk(
        tmp_path, {f"t{i}.json": PASS_DOC for i in range(5)}
    )
    loads = {"n": 0}
    real = vmod.load_document

    def counting(content, name=""):
        loads["n"] += 1
        return real(content, name)

    monkeypatch.setattr(vmod, "load_document", counting)
    rc, out, err = _run(
        ["validate", "-r", rules, "-d", data, "--backend", "native"]
    )
    assert rc == 0, err
    # the compiled engine evaluates raw JSON; the aware reporter's
    # shape probe answers from a key scan — no Python tree builds
    assert loads["n"] == 0


def test_broken_json_doc_keeps_error_contract(tmp_path):
    # unparseable doc sorted AFTER a good one: the error must still
    # surface before ANY evaluation output (eager-loader contract; the
    # lazy docs are pre-validated up front)
    rules, data = _mk(
        tmp_path, {"a_ok.json": PASS_DOC, "zbad.json": "{this is not json: ["}
    )
    nat = _run(["validate", "-r", rules, "-d", data, "--backend", "native"])
    cpu = _run(["validate", "-r", rules, "-d", data, "--backend", "cpu"])
    assert nat == cpu
    assert nat[0] == 5
    assert nat[1] == ""  # no partial evaluation output


def test_flow_yaml_sniffing_as_json_keeps_tree_path(tmp_path):
    # valid YAML flow mapping that json.loads rejects: loses raw
    # eligibility but must still evaluate (from its tree), not error
    rules, data = _mk(tmp_path, {"t.json": '{"Resources": {"b": {"Type": "AWS::S3::Bucket", "Properties": { }, }}}'})
    nat = _run(["validate", "-r", rules, "-d", data, "--backend", "native"])
    cpu = _run(["validate", "-r", rules, "-d", data, "--backend", "cpu"])
    assert nat == cpu


def test_eval_time_parse_error_keeps_pair_isolation(tmp_path):
    # json_parse raising ParseError at EVALUATION time is an evaluation
    # error (per-pair isolation, exit 5 after the loop) — not a fatal
    # document-load error (code-review finding r5)
    (tmp_path / "r.guard").write_text(
        "rule r { let parsed = json_parse(bad) %parsed exists }\n"
    )
    data = tmp_path / "data"
    data.mkdir()
    (data / "a.json").write_text(json.dumps({"bad": "{not json"}))
    (data / "b.json").write_text(json.dumps({"bad": '{"k": 1}'}))
    base = ["validate", "-r", str(tmp_path / "r.guard"), "-d", str(data),
            "--show-summary", "all"]
    nat = _run(base + ["--backend", "native"])
    cpu = _run(base + ["--backend", "cpu"])
    assert nat == cpu
    assert nat[0] == 5
    # the second document still evaluated (isolation, not abort)
    assert "b.json" in nat[1]


def test_decline_falls_back_to_python(tmp_path):
    # non-ASCII literal: outside the engine's certain-parity subset
    # (conservative classifier) — the pair must fall back to Python
    # and still match the cpu backend byte-for-byte
    (tmp_path / "r.guard").write_text(
        'rule uni { Resources.*.Tag == "héllo" }\n'
    )
    data = tmp_path / "data"
    data.mkdir()
    (data / "t.json").write_text(
        json.dumps({"Resources": {"a": {"Tag": "héllo"}}})
    )
    base = ["validate", "-r", str(tmp_path / "r.guard"), "-d", str(data),
            "--show-summary", "pass"]
    nat = _run(base + ["--backend", "native"])
    cpu = _run(base + ["--backend", "cpu"])
    assert nat == cpu


def test_test_command_byte_parity(tmp_path):
    (tmp_path / "r.guard").write_text(RULES)
    tests_dir = tmp_path / "tests"
    tests_dir.mkdir()
    (tests_dir / "r_tests.yaml").write_text(
        json.dumps(
            [
                {
                    "name": "fails",
                    "input": FAIL_DOC,
                    "expectations": {"rules": {"s3_sse": "FAIL", "named": "SKIP"}},
                },
                {
                    "name": "passes",
                    "input": PASS_DOC,
                    "expectations": {"rules": {"s3_sse": "PASS", "named": "PASS"}},
                },
            ]
        )
    )
    for fmt in ("single-line-summary", "json"):
        base = ["test", "--dir", str(tmp_path), "-o", fmt]
        nat = _run(base + ["--backend", "native"])
        cpu = _run(base + ["--backend", "cpu"])
        assert nat == cpu
        assert nat[0] == 0


def test_builder_api_backend_native(tmp_path):
    from guard_tpu.api import ValidateBuilder

    rules, data = _mk(tmp_path, {"t.json": FAIL_DOC})
    results = {}
    for be in ("native", "cpu"):
        code, out, err = (
            ValidateBuilder().rules([rules]).data([data]).backend(be)
            .try_build_and_execute()
        )
        results[be] = (code, out, err)
    assert results["native"] == results["cpu"]
    assert results["native"][0] == 19
