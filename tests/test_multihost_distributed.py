"""Multi-host evidence (VERDICT r4 item 8): the SAME evaluator code
runs under `jax.distributed` across OS processes — 2 processes x 4
virtual CPU devices form one global 8-device (dcn, ici) mesh, the
document batch shards across processes on the dcn axis, evaluation is
SPMD, and the only cross-process traffic is the terminal summary
reduction (gloo collectives on CPU; ICI/DCN collectives on real TPU
topologies). Each process asserts bit-parity against the CPU oracle
for its addressable shard."""

import json
import os
import subprocess
import sys
import tempfile
import textwrap

import pytest

_WORKER = textwrap.dedent(
    '''
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    os.environ.setdefault("GUARD_TPU_GATHER_ON_CPU", "0")
    import jax
    jax.config.update("jax_platforms", "cpu")
    pid = int(sys.argv[1]); port = sys.argv[2]
    jax.distributed.initialize(
        f"127.0.0.1:{port}", num_processes=2, process_id=pid
    )
    assert len(jax.devices()) == 8 and len(jax.local_devices()) == 4

    sys.path.insert(0, os.getcwd())  # repo root (test sets cwd)
    import numpy as np
    from guard_tpu.core.parser import parse_rules_file
    from guard_tpu.core.scopes import RootScope
    from guard_tpu.core.evaluator import eval_rules_file
    from guard_tpu.core.qresult import Status
    from guard_tpu.core.values import from_plain
    from guard_tpu.ops.encoder import encode_batch
    from guard_tpu.ops.ir import compile_rules_file
    from guard_tpu.parallel import mesh as mesh_mod

    RULES = """
    rule enc { Resources.*[ Type == 'B' ] { Properties.E exists } }
    rule named when enc { Resources.*.Name exists }
    """
    # identical corpus on every process (deterministic encode)
    docs_plain = [
        {"Resources": {f"r{i}": {
            "Type": "B",
            "Properties": ({"E": 1} if i % 3 else {}),
            **({"Name": f"n{i}"} if i % 2 else {}),
        }}}
        for i in range(16)
    ]
    docs = [from_plain(d) for d in docs_plain]
    rf = parse_rules_file(RULES, "m.guard")
    batch, interner = encode_batch(docs)
    compiled = compile_rules_file(rf, interner)
    assert not compiled.host_rules

    mesh = mesh_mod.hierarchical_mesh(n_slices=2)  # (dcn=2, ici=4)
    assert mesh.axis_names == ("dcn", "ici")
    fn, _summary = mesh_mod._shared_evaluator_fns(compiled, mesh)

    from jax.sharding import NamedSharding, PartitionSpec as P
    arrays, d_valid = mesh_mod.pad_to_multiple(
        compiled.device_arrays(batch), mesh.devices.size
    )
    doc_sharding = NamedSharding(mesh, P(("dcn", "ici")))
    D = next(iter(arrays.values())).shape[0]
    half = D // 2
    lo, hi = (0, half) if pid == 0 else (half, D)
    global_arrays = {
        k: jax.make_array_from_process_local_data(
            doc_sharding, np.ascontiguousarray(v[lo:hi]), v.shape
        )
        for k, v in arrays.items()
    }
    out = fn(global_arrays, compiled.lit_values())
    statuses = out[0] if compiled.needs_unsure else out

    # every process checks ITS addressable rows against the oracle
    to_int = {Status.PASS: 0, Status.FAIL: 1, Status.SKIP: 2}
    checked = 0
    for shard in statuses.addressable_shards:
        start = shard.index[0].start or 0
        rows = np.asarray(shard.data)
        for j in range(rows.shape[0]):
            di = start + j
            if di >= d_valid:
                continue
            scope = RootScope(rf, docs[di])
            eval_rules_file(rf, scope, None)
            root = scope.reset_recorder().extract()
            expect = [
                to_int[c.container.payload.status] for c in root.children
            ]
            got = [int(v) for v in rows[j]]
            assert got == expect, (di, got, expect)
            checked += 1
    assert checked >= 4  # each process owns half the real docs
    print(f"OK pid={pid} checked={checked}", flush=True)
    '''
)


def test_two_process_dcn_mesh_parity(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    # ephemeral port: a fixed one collides with concurrent runs or a
    # leftover worker from a timed-out previous run
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = str(s.getsockname()[1])
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(i), port],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("distributed worker timed out")
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-3000:]}"
        assert f"OK pid={i}" in out
