"""Template-aware console reporter tests: the CfnAware / TfAware /
generic chain (`/root/reference/guard/src/commands/validate.rs:703-716`,
`reporters/validate/cfn.rs`, `tf.rs`)."""

import json
import textwrap

from guard_tpu.cli import run
from guard_tpu.utils.io import Reader, Writer


def run_cli(args, stdin=""):
    w = Writer.buffered()
    code = run(args, writer=w, reader=Reader.from_string(stdin))
    return code, w.stripped(), w.err_to_stripped()


CFN_TEMPLATE = textwrap.dedent(
    """\
    Resources:
      logs:
        Type: AWS::S3::Bucket
        Metadata:
          aws:cdk:path: stack/logs/Resource
        Properties:
          AccessControl: PublicRead
      data:
        Type: AWS::S3::Bucket
        Properties:
          AccessControl: Private
    """
)

CFN_RULE = "rule no_public { Resources.*.Properties.AccessControl != 'PublicRead' }"


def test_cfn_aware_resource_aggregation(tmp_path):
    t = tmp_path / "t.yaml"
    t.write_text(CFN_TEMPLATE)
    r = tmp_path / "r.guard"
    r.write_text(CFN_RULE)
    code, out, _ = run_cli(["validate", "-r", str(r), "-d", str(t)])
    assert code == 19
    assert "Number of non-compliant resources 1" in out
    assert "Resource = logs {" in out
    assert "Type      = AWS::S3::Bucket" in out
    assert "CDK-Path  = stack/logs/Resource" in out
    assert "Rule = " in out and "no_public" in out
    assert "ComparisonError {" in out
    assert "PropertyPath" in out and "/Resources/logs/Properties/AccessControl" in out
    assert "Operator" in out and "NOT EQUAL" in out
    # source excerpt around the failing line
    assert "Code:" in out
    assert "AccessControl: PublicRead" in out
    # compliant resource is not reported
    assert "Resource = data {" not in out


def test_cfn_aware_missing_property(tmp_path):
    t = tmp_path / "t.yaml"
    t.write_text(
        "Resources:\n  b:\n    Type: AWS::S3::Bucket\n    Properties: {}\n"
    )
    r = tmp_path / "r.guard"
    r.write_text("rule enc { Resources.*.Properties.BucketEncryption exists }")
    code, out, _ = run_cli(["validate", "-r", str(r), "-d", str(t)])
    assert code == 19
    assert "Resource = b {" in out
    assert "RequiredPropertyError {" in out
    assert "MissingProperty" in out and "BucketEncryption" in out


def test_cfn_aware_silent_on_pass(tmp_path):
    t = tmp_path / "t.yaml"
    t.write_text(CFN_TEMPLATE)
    r = tmp_path / "r.guard"
    r.write_text("rule types { Resources.*.Type == 'AWS::S3::Bucket' }")
    code, out, _ = run_cli(["validate", "-r", str(r), "-d", str(t)])
    assert code == 0
    assert out == ""


TF_PLAN = {
    "resource_changes": [
        {
            "address": "aws_s3_bucket.my_bucket",
            "change": {"after": {"acl": "public-read", "bucket": "b1"}},
        },
        {
            "address": "aws_s3_bucket.other",
            "change": {"after": {"acl": "private", "bucket": "b2"}},
        },
    ]
}


def test_tf_aware_resource_aggregation(tmp_path):
    t = tmp_path / "plan.json"
    t.write_text(json.dumps(TF_PLAN))
    r = tmp_path / "r.guard"
    r.write_text("rule acl { resource_changes[*].change.after.acl == 'private' }")
    code, out, _ = run_cli(["validate", "-r", str(r), "-d", str(t)])
    assert code == 19
    assert "Number of non-compliant resources 1" in out
    assert "Resource = my_bucket {" in out
    assert "Type      = aws_s3_bucket" in out
    # property path is rewritten below change/after and dotted (tf.rs:215-231)
    assert "PropertyPath" in out and "= acl" in out
    assert "Resource = other {" not in out


def test_generic_fallback_for_other_docs(tmp_path):
    t = tmp_path / "d.json"
    t.write_text(json.dumps({"config": {"mode": "off"}}))
    r = tmp_path / "r.guard"
    r.write_text("rule on { config.mode == 'on' }")
    code, out, _ = run_cli(["validate", "-r", str(r), "-d", str(t)])
    assert code == 19
    assert "Evaluation of rules" in out
    assert "Property [/config/mode]" in out
    assert "Resource =" not in out
