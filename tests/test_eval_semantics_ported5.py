"""Fifth batch of reference-pinned semantics, re-expressed at rule
level (`/root/reference/guard/src/rules/eval_tests.rs` —
query_empty_and_non_empty:294, each_lhs_value_not_comparable:359,
each_lhs_value_eq_compare:443, binary_comparisons_gt_ge:671 /
lt_le:781 essences). The reference drives internal APIs
(unary_operation / each_lhs_compare); the observable contract — the
statuses those comparisons produce — is asserted here on BOTH
engines."""

import pytest

from guard_tpu.core.parser import parse_rules_file
from guard_tpu.core.scopes import RootScope
from guard_tpu.core.evaluator import eval_rules_file
from guard_tpu.core.loader import load_document
from guard_tpu.ops.encoder import encode_batch
from guard_tpu.ops.fnvars import precompute_fn_values
from guard_tpu.ops.ir import compile_rules_file
from guard_tpu.ops.kernels import BatchEvaluator

STATUS = {0: "PASS", 1: "FAIL", 2: "SKIP"}

DOC = """
Parameters:
  allowed_images: [ami-123456789012, ami-01234567890]
Resources:
  s3:
    Type: AWS::S3::Bucket
  ec2:
    Type: AWS::EC2::Instance
    Properties:
      ImageId: ami-123456789012
"""


def _both(rules_text, yaml_doc=DOC):
    from guard_tpu.commands.report import rule_statuses_from_root

    rf = parse_rules_file(rules_text, "ported5.guard")
    doc = load_document(yaml_doc, "doc.yaml")
    scope = RootScope(rf, doc)
    eval_rules_file(rf, scope, None)
    root = scope.reset_recorder().extract()
    oracle = {n: s.value for n, s in rule_statuses_from_root(root).items()}

    fn_vars, fn_vals, fn_err = precompute_fn_values(rf, [doc])
    assert not fn_err
    batch, interner = encode_batch([doc], fn_values=fn_vals, fn_var_order=fn_vars)
    compiled = compile_rules_file(rf, interner)
    evaluator = BatchEvaluator(compiled)
    statuses = evaluator(batch)
    unsure = evaluator.last_unsure
    for ri, crule in enumerate(compiled.rules):
        if unsure is not None and bool(unsure[0, ri]):
            continue
        assert STATUS[int(statuses[0, ri])] == oracle[crule.name], crule.name
    return oracle


def test_query_empty_and_non_empty():
    # eval_tests.rs:294 — `not empty` on a filter query tests whether
    # anything was selected
    oracle = _both(
        """
rule has_bucket { Resources.*[ Type == /Bucket/ ] !empty }
rule has_broker { Resources.*[ Type == /Broker/ ] !empty }
"""
    )
    assert oracle == {"has_bucket": "PASS", "has_broker": "FAIL"}


def test_each_lhs_value_vs_list_value():
    # eval_tests.rs:359 — a string LHS against a resolved LIST value:
    # Eq is NotComparable (FAIL), `in` membership PASSes, `not in`
    # FAILs
    oracle = _both(
        """
rule eq_list { Resources.ec2.Properties.ImageId == Parameters.allowed_images }
rule in_list { Resources.ec2.Properties.ImageId in Parameters.allowed_images }
rule not_in_list { Resources.ec2.Properties.ImageId not in Parameters.allowed_images }
"""
    )
    assert oracle == {
        "eq_list": "FAIL",
        "in_list": "PASS",
        "not_in_list": "FAIL",
    }


def test_each_lhs_value_eq_compare_flattened():
    # eval_tests.rs:443 exercises each_lhs_compare pairwise; at RULE
    # level Eq against a query is SET-difference (operators.rs:552-594
    # query_in): {ami-123} vs {ami-123, ami-012} leaves ami-012 in the
    # diff, so both forms FAIL — `some` has no pass entries to find.
    # Containment is what `in` expresses (test above).
    oracle = _both(
        """
rule all_match { Resources.ec2.Properties.ImageId == Parameters.allowed_images[*] }
rule some_match { some Resources.ec2.Properties.ImageId == Parameters.allowed_images[*] }
"""
    )
    assert oracle == {"all_match": "FAIL", "some_match": "FAIL"}


NUM_DOC = """
values:
  int: 10
  ints: [20, 10]
  float: 1.0
  string: "Hi"
"""


@pytest.mark.parametrize(
    "clause,expected",
    [
        # binary_comparisons_gt_ge essence (eval_tests.rs:671)
        ("values.int > 5", "PASS"),
        ("values.int >= 10", "PASS"),
        ("values.int > 10", "FAIL"),
        ("values.ints[*] >= 10", "PASS"),
        ("values.ints[*] > 10", "FAIL"),
        ("some values.ints[*] > 10", "PASS"),
        # binary_comparisons_lt_le essence (eval_tests.rs:781)
        ("values.int < 20", "PASS"),
        ("values.int <= 10", "PASS"),
        ("values.int < 10", "FAIL"),
        ("values.float <= 1.0", "PASS"),
        ("values.string < 'Ji'", "PASS"),
        ("values.string > 'Di'", "PASS"),
        ("values.string < 'Di'", "FAIL"),
        # cross-kind ordering is NotComparable -> FAIL
        ("values.int > 'Hi'", "FAIL"),
        ("values.string > 5", "FAIL"),
        ("values.int > 1.0", "FAIL"),
    ],
)
def test_binary_comparisons(clause, expected):
    oracle = _both(f"rule r {{ {clause} }}", NUM_DOC)
    assert oracle == {"r": expected}, clause
