"""Loader semantics: locations, scalar typing, CFN intrinsic short forms."""

import pytest

from guard_tpu.core.errors import ParseError
from guard_tpu.core.loader import load_document
from guard_tpu.core.values import BOOL, FLOAT, INT, MAP, NULL, STRING


def test_scalar_typing_plain():
    doc = load_document(
        "a: 10\nb: 1.5\nc: yes\nd: Null\ne: hello\nf: '10'\ng: True\n"
    )
    v = doc.val.values
    assert v["a"].kind == INT and v["a"].val == 10
    assert v["b"].kind == FLOAT
    assert v["c"].kind == BOOL and v["c"].val is True
    assert v["d"].kind == NULL
    assert v["e"].kind == STRING
    # quoted scalars stay strings (loader.rs:83-84)
    assert v["f"].kind == STRING and v["f"].val == "10"
    # 'True' (capital T, plain) is NOT a bool in the reference loader
    assert v["g"].kind == STRING


def test_locations_are_zero_based_marks():
    doc = load_document("Resources:\n  Bucket:\n    Type: T\n")
    bucket = doc.val.values["Resources"].val.values["Bucket"]
    t = bucket.val.values["Type"]
    assert t.self_path().s == "/Resources/Bucket/Type"
    assert t.self_path().loc.line == 2  # 0-based third line
    assert t.self_path().loc.col == 10


def test_cfn_short_form_scalar():
    doc = load_document("Value: !Ref MyParam\n")
    ref = doc.val.values["Value"]
    assert ref.kind == MAP
    assert ref.val.values["Ref"].val == "MyParam"


def test_cfn_short_form_getatt_sequence():
    doc = load_document("Value: !GetAtt [iamRole, Arn]\n")
    ga = doc.val.values["Value"]
    assert ga.kind == MAP
    inner = ga.val.values["Fn::GetAtt"]
    assert [e.val for e in inner.val] == ["iamRole", "Arn"]


def test_aliases_rejected():
    with pytest.raises(ParseError):
        load_document("a: &x 1\nb: *x\n")


def test_json_through_yaml_path():
    doc = load_document('{"Resources": {"b": {"Type": "T", "n": 3}}}')
    b = doc.val.values["Resources"].val.values["b"]
    assert b.val.values["n"].kind == INT
