"""Smoke tier for the coverage-guided fuzzer (tools/fuzz.py): a short
in-CI run per target must execute cleanly with zero crashes and show
the coverage feedback actually growing the corpus. The 420 s/target
soak runs in the nightly workflow (.github/workflows/nightly.yml),
mirroring the reference's libFuzzer gate (pr.yml:109-127)."""

import pathlib
import re
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent


@pytest.mark.parametrize("target", ["dsl", "yaml"])
def test_fuzz_smoke(target, tmp_path):
    proc = subprocess.run(
        [
            sys.executable, str(REPO / "tools" / "fuzz.py"),
            "--target", target, "--time", "8",
            "--crash-dir", str(tmp_path / "crashes"),
        ],
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    m = re.search(
        r"executions=(\d+) corpus=(\d+) coverage=(\d+) crashes=(\d+)",
        proc.stdout,
    )
    assert m, proc.stdout
    executions, corpus, coverage, crashes = map(int, m.groups())
    assert crashes == 0
    assert executions > 1000, "fuzzer throughput collapsed"
    assert coverage > 300, "coverage feedback not wired"
    assert not (tmp_path / "crashes").exists()


def test_nonfinite_float_report_regression():
    """Reproducer for the OverflowError the fuzzer found: non-finite
    floats inside failure reports (rust_debug_pv) must format like
    Rust's {:?} instead of crashing."""
    from guard_tpu.api import run_checks

    # plain scalars type like Rust's f64 FromStr (loader.rs:86-98):
    # "inf"/"-inf"/"1e999" are floats; ".inf" stays a string — and the
    # rust-debug renderer (the crash site) must format them like {:?}
    from guard_tpu.core.loader import load_document
    from guard_tpu.core.values import rust_debug_pv

    doc = load_document("a: 1e999\nb: -inf\nc: nan\n", "f.yaml")
    rendered = rust_debug_pv(doc)
    assert "Float((" in rendered
    assert "inf" in rendered and "-inf" in rendered and "NaN" in rendered

    out = run_checks("a: 1e999\nb: -inf\nc: nan\n", "a exists\nb == 5.0\nc exists")
    assert '"status": "FAIL"' in out  # evaluated, no crash
