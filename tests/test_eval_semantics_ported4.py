"""Fourth batch of evaluation-semantics cases re-expressed from the
reference's pinned suite (`/root/reference/guard/src/rules/
eval_tests.rs` — variable_projections:1205, query_cross_joins:1339,
cross_rule_clause_when_checks:1454, block_evaluation:1119,
block_evaluation_fail:1158). Each case runs on BOTH the CPU oracle and
the device kernels: statuses must match the reference's pinned
expectations and each other."""

import pytest

from guard_tpu.core.parser import parse_rules_file
from guard_tpu.core.scopes import RootScope
from guard_tpu.core.evaluator import eval_rules_file
from guard_tpu.core.loader import load_document
from guard_tpu.ops.encoder import encode_batch
from guard_tpu.ops.fnvars import precompute_fn_values
from guard_tpu.ops.ir import compile_rules_file
from guard_tpu.ops.kernels import BatchEvaluator

STATUS = {0: "PASS", 1: "FAIL", 2: "SKIP"}


def _both_engines(rules_text, yaml_doc):
    """{rule: status} from the oracle, asserted equal to the kernels
    for every lowered rule."""
    from guard_tpu.commands.report import rule_statuses_from_root

    rf = parse_rules_file(rules_text, "ported4.guard")
    doc = load_document(yaml_doc, "doc.yaml")
    scope = RootScope(rf, doc)
    overall = eval_rules_file(rf, scope, None)
    root = scope.reset_recorder().extract()
    oracle = {n: s.value for n, s in rule_statuses_from_root(root).items()}

    fn_vars, fn_vals, fn_err = precompute_fn_values(rf, [doc])
    assert not fn_err
    batch, interner = encode_batch([doc], fn_values=fn_vals, fn_var_order=fn_vars)
    compiled = compile_rules_file(rf, interner)
    evaluator = BatchEvaluator(compiled)
    statuses = evaluator(batch)
    unsure = evaluator.last_unsure
    for ri, crule in enumerate(compiled.rules):
        if unsure is not None and bool(unsure[0, ri]):
            continue
        assert STATUS[int(statuses[0, ri])] == oracle[crule.name], crule.name
    return overall.value, oracle


PROJECTION_DOC_PASS = """
Resources:
  s3_bucket:
    Type: AWS::S3::Bucket
  s3_bucket_policy:
    Type: AWS::S3::BucketPolicy
    Properties:
      Bucket:
        Ref: s3_bucket
  s3_bucket_policy_2:
    Type: AWS::S3::BucketPolicy
    Properties:
      Bucket: aws:arn
"""

PROJECTION_RULES = """
let policies = Resources[ Type == /BucketPolicy$/ ]
rule policies_check when %policies not empty {
  %policies.Properties.Bucket exists
  %policies.Properties.Bucket not empty
  some %policies.Properties.Bucket.Ref not empty
}
"""


def test_variable_projections():
    # eval_tests.rs:1205 — `some` saves the clause: one Ref resolves
    overall, _ = _both_engines(PROJECTION_RULES, PROJECTION_DOC_PASS)
    assert overall == "PASS"


def test_variable_projections_failures():
    # eval_tests.rs:1245 — Bucket: "" fails `not empty`
    doc = PROJECTION_DOC_PASS.replace("Bucket: aws:arn", 'Bucket: ""')
    overall, _ = _both_engines(PROJECTION_RULES, doc)
    assert overall == "FAIL"


CROSS_JOIN_DOC = """
Resources:
  s3_bucket:
    Type: AWS::S3::Bucket
  s3_bucket_policy:
    Type: AWS::S3::BucketPolicy
    Properties:
      Bucket:
        Ref: s3_bucket
"""

CROSS_JOIN_DOC_2 = CROSS_JOIN_DOC + """  s3_bucket_policy_2:
    Type: AWS::S3::BucketPolicy
    Properties:
      Bucket: aws:arn...
"""


@pytest.mark.parametrize(
    "rules,doc,expected",
    [
        # eval_tests.rs:1339 query_cross_joins, all five sub-cases
        (
            """rule s3_cross_query_join {
   let policies = Resources[ Type == /BucketPolicy$/ ].Properties.Bucket.Ref
   Resources.%policies {
     Type == 'AWS::S3::Bucket'
   }
}""",
            CROSS_JOIN_DOC,
            "PASS",
        ),
        (
            """rule s3_cross_query_join {
   let policies = Resources[ Type == /NotBucketPolicy$/ ].Properties.Bucket.Ref
   Resources.%policies {
     Type == 'AWS::S3::Bucket'
   }
}""",
            CROSS_JOIN_DOC,
            "SKIP",
        ),
        # no `some` on the assignment: the unresolved Ref FAILs
        (
            """rule s3_cross_query_join {
   let policies = Resources[ Type == /BucketPolicy$/ ].Properties.Bucket.Ref
   Resources.%policies {
     Type == 'AWS::S3::Bucket'
   }
}""",
            CROSS_JOIN_DOC_2,
            "FAIL",
        ),
        # `some` on the assignment drops the unresolved entry
        (
            """rule s3_cross_query_join {
   let policies = some Resources[ Type == /BucketPolicy$/ ].Properties.Bucket.Ref
   Resources.%policies {
     Type == 'AWS::S3::Bucket'
   }
}""",
            CROSS_JOIN_DOC_2,
            "PASS",
        ),
        # `some` at the block level yields the same result
        (
            """rule s3_cross_query_join {
   let policies = Resources[ Type == /BucketPolicy$/ ].Properties.Bucket.Ref
   some Resources.%policies {
     Type == 'AWS::S3::Bucket'
   }
}""",
            CROSS_JOIN_DOC_2,
            "PASS",
        ),
    ],
)
def test_query_cross_joins(rules, doc, expected):
    overall, _ = _both_engines(rules, doc)
    assert overall == expected


CROSS_RULE_RULES = """
rule skipped when skip !exists {
    Resources.*.Properties.Tags !empty
}

rule dependent_on_skipped when skipped {
    Resources.*.Properties exists
}

rule dependent_on_dependent when dependent_on_skipped {
    Resources.*.Properties exists
}

rule dependent_on_not_skipped when !skipped {
    Resources.*.Properties exists
}
"""

CROSS_RULE_DOC_SKIP = """
skip: true
Resources:
  first:
    Type: 'WhackWhat'
    Properties:
      Tags:
        - hi: "there"
        - right: "way"
"""


def test_cross_rule_clause_when_checks_skipped():
    # eval_tests.rs:1454 — `skip` present: gate rule SKIPs, dependents
    # SKIP, the negated dependent PASSes
    overall, statuses = _both_engines(CROSS_RULE_RULES, CROSS_RULE_DOC_SKIP)
    assert overall == "PASS"
    assert statuses == {
        "skipped": "SKIP",
        "dependent_on_skipped": "SKIP",
        "dependent_on_dependent": "SKIP",
        "dependent_on_not_skipped": "PASS",
    }


def test_cross_rule_clause_when_checks_not_skipped():
    doc = CROSS_RULE_DOC_SKIP.replace("skip: true\n", "")
    overall, statuses = _both_engines(CROSS_RULE_RULES, doc)
    assert overall == "PASS"
    assert statuses == {
        "skipped": "PASS",
        "dependent_on_skipped": "PASS",
        "dependent_on_dependent": "PASS",
        "dependent_on_not_skipped": "SKIP",
    }


BLOCK_EVAL_DOC = """
Resources:
  apiGw:
    Type: 'AWS::ApiGateway::RestApi'
    Properties:
      EndpointConfiguration: ["PRIVATE"]
      Policy:
        Statement:
          - Action: Allow
            Resource: ['*', "aws:"]
            Condition:
                'aws:IsSecure': true
                'aws:sourceVpc': ['vpc-1234']
          - Action: Allow
            Resource: ['*', "aws:"]
"""

BLOCK_EVAL_RULES = """
rule api_private {
    Resources.*[ Type == 'AWS::ApiGateway::RestApi' ].Properties {
        EndpointConfiguration == ["PRIVATE"]
        some Policy.Statement[*] {
            Action == 'Allow'
            Condition[ keys == 'aws:IsSecure' ] !empty
        }
    }
}
"""


def test_block_evaluation():
    # eval_tests.rs:1119
    overall, _ = _both_engines(BLOCK_EVAL_RULES, BLOCK_EVAL_DOC)
    assert overall == "PASS"


def test_block_evaluation_fail():
    # eval_tests.rs:1158 — a second RestApi with no IsSecure condition
    doc = BLOCK_EVAL_DOC + """  apiGw2:
    Type: 'AWS::ApiGateway::RestApi'
    Properties:
      EndpointConfiguration: ["PRIVATE"]
      Policy:
        Statement:
          - Action: Allow
            Resource: ['*', "aws:"]
"""
    overall, _ = _both_engines(BLOCK_EVAL_RULES, doc)
    assert overall == "FAIL"


def test_block_guard_custom_message_principal():
    # eval_tests.rs:925 block_guard_pass — wildcard principal FAILs
    doc = """
Resources:
  iam:
    Type: AWS::IAM::Role
    Properties:
      PolicyDocument:
        Statement:
          - Principal: '*'
            Effect: Allow
            Resource: ['s3*']
          - Principal: [aws-123, aws-345]
            Effect: Allow
            Resource: '*'
  ecs:
    Type: AWS::ECS::Task
    Properties:
      Role:
        Ref: iam
"""
    rules = """
rule no_wildcard {
    Resources[ Type == /Role/ ].Properties.PolicyDocument {
      Statement[*] {
         Principal != '*' <<No wildcard allowed for Principals>>
      }
    }
}
"""
    overall, _ = _both_engines(rules, doc)
    assert overall == "FAIL"
