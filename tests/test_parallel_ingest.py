"""Bit-parity suite for the parallel host ingest plane (PR 3).

`sweep` and `validate --backend tpu` output under the three-stage
pipeline (ingest workers -> packed dispatch -> rim/report consumption,
parallel/ingest.py) must be byte-identical to the serial path
(`--ingest-workers 0`, the old single-chunk double buffer) for every
worker count, over the mixed corpus of the vector-rim suite —
fail-heavy docs, unsure-flagged docs, host-fallback rules, fn-var
files — on both the packed and per-file dispatch paths, across
console, structured JSON, YAML and JUnit output. Plus: graceful
serial fallback when worker spawn fails, bounded prefetch (the queue
high-water mark never exceeds the configured depth), and
deterministic file ordering."""

import json

import pytest

from guard_tpu.cli import run
from guard_tpu.ops.backend import pipeline_stats, reset_pipeline_stats
from guard_tpu.utils.io import Reader, Writer

RULES_MAIN = (
    "let b = Resources.*[ Type == 'AWS::S3::Bucket' ]\n"
    "rule sse when %b !empty { %b.Properties.Enc == true }\n"
    "rule named { Resources.* { Type exists } }\n"
)
RULES_HOST = "let t = now()\nrule fresh { Resources exists }\n"
RULES_UNSURE = (
    "let names = Selection.targets\n"
    "rule sel { Resources.%names exists }\n"
)
RULES_FN = (
    "let up = to_upper(Meta.name)\n"
    "rule upper when Meta.name exists { %up == 'WIDGET' }\n"
)


def _mk_corpus(tmp_path, n_docs=10):
    rdir = tmp_path / "rules"
    rdir.mkdir(exist_ok=True)
    (rdir / "main.guard").write_text(RULES_MAIN)
    (rdir / "host.guard").write_text(RULES_HOST)
    (rdir / "unsure.guard").write_text(RULES_UNSURE)
    (rdir / "fnvar.guard").write_text(RULES_FN)
    data = tmp_path / "data"
    data.mkdir(exist_ok=True)
    for i in range(n_docs):
        doc = {
            "Resources": {
                "b": {
                    "Type": "AWS::S3::Bucket",
                    "Properties": {"Enc": (i % 3) != 0},
                }
            },
            "Meta": {"name": "widget" if i % 2 else "gadget"},
            "Selection": {"targets": [3] if i % 4 == 0 else ["b"]},
        }
        (data / f"t{i:03d}.json").write_text(json.dumps(doc))
    return rdir, data


def _rule_args(rdir):
    return ["-r", *(str(rf) for rf in sorted(rdir.glob("*.guard")))]


def _sweep(tmp_path, rdir, data, workers, tag, chunk=3):
    w = Writer.buffered()
    rc = run(
        ["sweep", *_rule_args(rdir), "-d", str(data),
         "-M", str(tmp_path / f"m-{tag}.jsonl"), "-c", str(chunk),
         "--ingest-workers", str(workers)],
        writer=w,
        reader=Reader(),
    )
    summary = json.loads(w.out.getvalue().strip().splitlines()[-1])
    summary.pop("manifest")
    return rc, summary, w.err.getvalue()


def _validate(rdir, data, workers, extra=()):
    w = Writer.buffered()
    rc = run(
        ["validate", *_rule_args(rdir), "-d", str(data),
         "--backend", "tpu", "--ingest-workers", str(workers), *extra],
        writer=w,
        reader=Reader(),
    )
    return rc, w.out.getvalue(), w.err.getvalue()


def test_sweep_parity_across_worker_counts(tmp_path):
    """workers 0/1/2/4 over the mixed corpus: identical counts, failed
    lists (deterministic file ordering), error tallies, exit codes and
    stderr bytes — and the pipelined runs genuinely prefetch."""
    rdir, data = _mk_corpus(tmp_path)
    base = _sweep(tmp_path, rdir, data, 0, "w0")
    for w in (1, 2, 4):
        reset_pipeline_stats()
        got = _sweep(tmp_path, rdir, data, w, f"w{w}")
        assert got == base, f"workers={w} diverged"
        stats = pipeline_stats()
        if w >= 2:
            assert stats["chunks_prefetched"] > 0
            assert stats["encode_dispatch_overlap"] > 0
    assert base[0] != 0  # the corpus contains genuine failures
    # file ordering inside the failed list is deterministic
    names = [f["data"] for f in base[1]["failed"]]
    assert names == sorted(names)


@pytest.mark.parametrize("pack", ["1", "0"], ids=["packed", "perfile"])
def test_sweep_parity_pack_modes(tmp_path, monkeypatch, pack):
    """Parity holds on both dispatch paths: packed executables and
    per-file dispatch."""
    rdir, data = _mk_corpus(tmp_path)
    monkeypatch.setenv("GUARD_TPU_PACK", pack)
    base = _sweep(tmp_path, rdir, data, 0, f"p{pack}-w0")
    got = _sweep(tmp_path, rdir, data, 2, f"p{pack}-w2")
    assert got == base


def test_sweep_bounded_prefetch_queue(tmp_path, monkeypatch):
    """Backpressure: the queued-chunk high-water mark never exceeds
    the configured pipeline depth."""
    rdir, data = _mk_corpus(tmp_path, n_docs=12)
    monkeypatch.setenv("GUARD_TPU_INGEST_DEPTH", "2")
    reset_pipeline_stats()
    _sweep(tmp_path, rdir, data, 2, "depth", chunk=2)  # 6 chunks
    stats = pipeline_stats()
    assert 0 < stats["max_inflight_chunks"] <= 2


VALIDATE_MODES = [
    [],
    ["-o", "yaml"],
    ["--structured", "-o", "json", "--show-summary", "none"],
    ["--structured", "-o", "junit", "--show-summary", "none"],
]


@pytest.mark.parametrize(
    "mode", VALIDATE_MODES, ids=lambda m: "_".join(m) or "console"
)
def test_validate_parity_workers(tmp_path, mode):
    """validate's sharded parallel encode (contiguous shards, private
    interners, id-remap merge): console/YAML/structured/JUnit bytes and
    exit codes identical to the serial encode."""
    rdir, data = _mk_corpus(tmp_path)
    base = _validate(rdir, data, 0, mode)
    got = _validate(rdir, data, 2, mode)
    assert got == base
    assert base[0] != 0


def test_validate_parity_more_worker_counts(tmp_path):
    rdir, data = _mk_corpus(tmp_path)
    base = _validate(rdir, data, 0)
    for w in (1, 4):
        assert _validate(rdir, data, w) == base


def test_spawn_failure_falls_back_serially(tmp_path, monkeypatch):
    """A failing worker spawn degrades to inline ingest with identical
    output — never an error."""
    import guard_tpu.parallel.ingest as ingest

    rdir, data = _mk_corpus(tmp_path)
    base_sweep = _sweep(tmp_path, rdir, data, 0, "sf-w0")
    base_val = _validate(rdir, data, 0)

    def boom(workers):
        raise OSError("spawn blocked for test")

    ingest.close_shared_pools()  # a cached healthy pool would bypass
    monkeypatch.setattr(ingest, "_spawn_pool", boom)
    try:
        assert _sweep(tmp_path, rdir, data, 4, "sf-w4") == base_sweep
        assert _validate(rdir, data, 4) == base_val
    finally:
        # clear the cached spawn failure so later tests spawn again
        ingest.close_shared_pools()


def test_sweep_parity_unreadable_and_invalid_docs(tmp_path):
    """Worker-read chunks reproduce the serial error accounting: an
    invalid JSON doc is skipped with one error (native-encoder retry)
    on every worker count."""
    rdir, data = _mk_corpus(tmp_path, n_docs=7)
    (data / "zbad.json").write_text('{"Resources": {')  # truncated
    base = _sweep(tmp_path, rdir, data, 0, "err-w0")
    assert base[1]["errors"] >= 1
    for w in (1, 2):
        assert _sweep(tmp_path, rdir, data, w, f"err-w{w}") == base


def test_resolve_ingest_workers_env_and_flag(monkeypatch):
    from guard_tpu.parallel.ingest import resolve_ingest_workers

    monkeypatch.delenv("GUARD_TPU_INGEST_WORKERS", raising=False)
    assert resolve_ingest_workers(3) == 3
    assert resolve_ingest_workers(0) == 0
    monkeypatch.setenv("GUARD_TPU_INGEST_WORKERS", "2")
    assert resolve_ingest_workers(None) == 2
    assert resolve_ingest_workers(5) == 5  # explicit flag wins
    monkeypatch.setenv("GUARD_TPU_INGEST_WORKERS", "0")
    assert resolve_ingest_workers(None) == 0
    monkeypatch.delenv("GUARD_TPU_INGEST_WORKERS", raising=False)
    import os

    auto = resolve_ingest_workers(None)
    assert 0 <= auto <= min((os.cpu_count() or 1) - 1, 4) or auto == 0


def test_batch_payload_roundtrip():
    """The picklable wire form reconstructs an equivalent DocBatch
    without re-deriving columns."""
    import numpy as np

    from guard_tpu.core.values import from_plain
    from guard_tpu.ops.encoder import (
        batch_from_payload,
        batch_payload,
        encode_batch,
    )

    docs = [from_plain({"a": [1, 2, {"b": "x"}]}), from_plain({"c": None})]
    batch, _ = encode_batch(docs)
    clone = batch_from_payload(batch_payload(batch))
    for attr in (
        "node_kind", "node_parent", "scalar_id", "num_hi", "num_lo",
        "child_count", "edge_parent", "edge_child", "edge_key_id",
        "edge_index", "edge_valid", "node_key_id", "node_index",
        "node_parent_kind", "num_exotic",
    ):
        np.testing.assert_array_equal(
            getattr(batch, attr), getattr(clone, attr), err_msg=attr
        )
    assert (clone.n_docs, clone.n_nodes, clone.n_edges) == (
        batch.n_docs, batch.n_nodes, batch.n_edges
    )


def test_shard_merge_matches_serial_encode():
    """Encoding two shards with private interners and merging through
    remap+concat yields the same statuses as one serial encode."""
    import numpy as np

    from guard_tpu.core.parser import parse_rules_file
    from guard_tpu.core.values import from_plain
    from guard_tpu.ops.encoder import (
        Interner,
        concat_batches,
        encode_batch,
        remap_interned_ids,
    )
    from guard_tpu.ops.ir import compile_rules_file
    from guard_tpu.parallel.mesh import ShardedBatchEvaluator

    docs = [
        from_plain({"Resources": {"r": {"Type": t, "Size": s}}})
        for t, s in [("A", 1), ("B", 200), ("A", 50), ("C", 7)]
    ]
    rf = parse_rules_file(
        "rule small { Resources.*.Size <= 100 }\n"
        "rule typed { Resources.*.Type IN ['A', 'B'] }\n",
        "m.guard",
    )
    # serial
    batch_s, interner_s = encode_batch(docs)
    ev_s = ShardedBatchEvaluator(compile_rules_file(rf, interner_s))
    st_s, _, _ = ev_s.evaluate_bucketed(batch_s)
    # sharded: 2 + 2 with private interners, merged
    merged = Interner()
    parts = []
    for shard in (docs[:2], docs[2:]):
        b, it = encode_batch(shard)
        remap = np.array(
            [merged.intern(s) for s in it.strings], dtype=np.int32
        )
        remap_interned_ids(b, remap)
        parts.append(b)
    batch_m = concat_batches(parts)
    ev_m = ShardedBatchEvaluator(compile_rules_file(rf, merged))
    st_m, _, _ = ev_m.evaluate_bucketed(batch_m)
    np.testing.assert_array_equal(st_s, st_m)
