"""Rule-axis parallelism: dependency-closed partitioning + 2-D
(rule-groups x docs) evaluation parity on the virtual CPU mesh."""

import numpy as np

from guard_tpu.core.parser import parse_rules_file
from guard_tpu.core.values import from_plain
from guard_tpu.ops.encoder import encode_batch
from guard_tpu.ops.ir import compile_rules_file
from guard_tpu.ops.kernels import BatchEvaluator
from guard_tpu.parallel.rules import RuleShardedEvaluator, partition_rules

RULES = """
let buckets = Resources.*[ Type == 'AWS::S3::Bucket' ]

rule base when %buckets !empty { %buckets.Properties.Enc == true }
rule derived when %buckets !empty {
    base
}
rule named when %buckets !empty {
    %buckets.Properties.Name == /^[a-z-]+$/
}
rule sized when %buckets !empty { %buckets.Properties.Size IN r[1,100] }
rule tagged when %buckets !empty { %buckets.Properties.Tag exists }
rule negates when %buckets !empty {
    not base
}
"""


def _docs(n=12):
    out = []
    for i in range(n):
        out.append(
            from_plain(
                {
                    "Resources": {
                        "b": {
                            "Type": "AWS::S3::Bucket",
                            "Properties": {
                                "Enc": i % 2 == 0,
                                "Name": "logs" if i % 3 else "BAD!",
                                "Size": (i * 17) % 150,
                                **({"Tag": "x"} if i % 4 else {}),
                            },
                        }
                    }
                }
            )
        )
    return out


def _compiled():
    rf = parse_rules_file(RULES, "t.guard")
    batch, interner = encode_batch(_docs())
    return compile_rules_file(rf, interner), batch


def test_partition_keeps_named_dependencies_together():
    compiled, _ = _compiled()
    names = [r.name for r in compiled.rules]
    for n_groups in (2, 3, 4):
        groups = partition_rules(compiled, n_groups)
        # every rule exactly once
        flat = sorted(i for g in groups for i in g)
        assert flat == list(range(len(compiled.rules)))
        # base, derived, negates reference each other -> same group
        dep_named = {"base", "derived", "negates"}
        containing = [
            gi for gi, g in enumerate(groups)
            if dep_named & {names[i] for i in g}
        ]
        assert len(set(containing)) == 1


def test_rule_sharded_matches_flat_evaluator():
    compiled, batch = _compiled()
    flat = BatchEvaluator(compiled)(batch)
    for shards in (2, 3):
        ev = RuleShardedEvaluator(compiled, rule_shards=shards)
        sharded = ev(batch)
        np.testing.assert_array_equal(flat, sharded)


def test_rule_sharded_single_group_degenerate():
    compiled, batch = _compiled()
    ev = RuleShardedEvaluator(compiled, rule_shards=1)
    np.testing.assert_array_equal(BatchEvaluator(compiled)(batch), ev(batch))
