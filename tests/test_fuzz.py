"""Fuzz-style robustness tests for the two parsers.

The reference fuzzes the DSL and YAML parsers through `run_checks` with
libFuzzer (guard/fuzz/fuzz_targets/, 420s/target in CI). Here: seeded
random mutations of valid inputs plus raw garbage — the engine must
either succeed or raise ParseError/GuardError, never crash with an
unrelated exception.
"""

import random
import string

import pytest

from guard_tpu.api import run_checks
from guard_tpu.core.errors import GuardError
from guard_tpu.core.loader import load_document
from guard_tpu.core.parser import parse_rules_file

SEED_RULES = [
    "Resources !empty",
    "let x = Resources.*[ Type == 'T' ]\nrule r when %x !empty {\n  %x.P exists\n}\n",
    "AWS::S3::Bucket {\n  Properties.Name == /x/ or Properties.Name !exists\n}\n",
    "a.b[*].c IN r[0,10]\nsome d.*.e != 'v' <<msg>>\n",
    "rule p(a, b) {\n  %a == %b\n}\nrule q {\n  p(x, y)\n}\n",
]

SEED_DOCS = [
    "{}",
    '{"Resources": {"a": {"Type": "T", "P": [1, 2]}}}',
    "Resources:\n  a:\n    Type: T\n",
]

CHARS = string.printable


def _mutate(rng, s: str) -> str:
    s = list(s)
    for _ in range(rng.randint(1, 6)):
        op = rng.randint(0, 2)
        pos = rng.randrange(0, max(1, len(s)))
        if op == 0 and s:
            s[pos % len(s)] = rng.choice(CHARS)
        elif op == 1:
            s.insert(pos, rng.choice(CHARS))
        elif op == 2 and s:
            del s[pos % len(s)]
    return "".join(s)


def test_dsl_parser_fuzz():
    rng = random.Random(1234)
    for i in range(400):
        base = rng.choice(SEED_RULES)
        mutated = _mutate(rng, base)
        try:
            parse_rules_file(mutated, "fuzz.guard")
        except GuardError:
            pass  # expected failure mode
        except RecursionError:
            pytest.fail(f"recursion blowup on: {mutated!r}")


def test_yaml_loader_fuzz():
    rng = random.Random(99)
    for i in range(400):
        base = rng.choice(SEED_DOCS)
        mutated = _mutate(rng, base)
        try:
            load_document(mutated, "fuzz.yaml")
        except GuardError:
            pass


def test_run_checks_fuzz():
    rng = random.Random(7)
    for i in range(150):
        rules = _mutate(rng, rng.choice(SEED_RULES))
        data = _mutate(rng, rng.choice(SEED_DOCS))
        try:
            run_checks(data, rules)
        except GuardError:
            pass


def test_deep_document_no_stack_overflow():
    # terraform-style deep trees (BASELINE.md config 4)
    depth = 2000
    doc = "{" * 0 + '{"a":' * depth + "1" + "}" * depth
    pv = load_document(doc)
    out = run_checks(doc, "a exists")
    assert out
